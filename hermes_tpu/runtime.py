"""Run driver: step loop, membership service hooks, history recording.

This is the rebuild of the reference's L0/L4/L7 host side (SURVEY.md §1):
``main()``+worker-loop becomes a host loop over compiled steps; the
membership service (epoch + live bitmap + lease bookkeeping, SURVEY.md §5.3)
lives here on the host, exactly where Hermes puts it (an external service,
not the data plane); stats are read off the device Meta counters.

Backends:
  * ``batched``  — R replicas on one device, fused jit step (test/bench mode,
                   the reference's single-process multi-replica pattern,
                   BASELINE.json:7)
  * ``sharded``  — one replica per mesh device, fused jit step with ICI
                   collectives (transport=tpu_ici, BASELINE.json:5)
  * ``sim``      — host-mediated exchanges through a SimTransport (or any
                   HostTransport): deterministic adversarial scheduling
"""

from __future__ import annotations

import collections
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from hermes_tpu.checker.history import HistoryRecorder
from hermes_tpu.checker.fast import ArrayRecorder, check_arrays
from hermes_tpu.checker import linearizability as lin
from hermes_tpu.config import HermesConfig
from hermes_tpu.core import state as st, step as step_lib
from hermes_tpu.core import types as t
from hermes_tpu.workload import ycsb


# Process-wide compiled-step cache.  build_fast_* returns a fresh jit
# wrapper per call, so every FastRuntime used to recompile the round
# program (~seconds) even when an identical-shape store had already
# compiled it in this process.  The traced program is a pure function of
# the config (and mesh, for sharded) — EXCEPT the wal_* fields, which
# live entirely on the host plane (round-22: the log taps the harvest
# AFTER the step runs), so two stores differing only in wal dir/mode
# share one executable.  Keys fall back to no caching when a config or
# mesh is unhashable rather than ever guessing.
_STEP_CACHE: dict = {}


def _cached_step(cfg: HermesConfig, backend: str, mesh, build):
    import dataclasses
    try:
        key = (backend,
               dataclasses.replace(cfg, wal_dir=None, wal_sync="commit",
                                   wal_segment_bytes=1 << 20,
                                   wal_dirty_window=256),
               cfg.donate_state, mesh)
        hash(key)
    except TypeError:
        return build()
    fn = _STEP_CACHE.get(key)
    if fn is None:
        fn = _STEP_CACHE[key] = build()
    return fn


class _ObsHooks:
    """Shared observability surface of both run drivers (hermes_tpu.obs):
    ``attach_obs`` installs the run's Observability context; fault-injection
    and membership transitions emit point events on its timeline, drains and
    rebases emit spans.  Interval metrics stay the caller's job (cli.py /
    scripts poll ``counters()``/``stats.summarize`` at their own cadence).
    Everything is a no-op while no obs context is attached."""

    obs = None
    # fleet group label (round-13, hermes_tpu/fleet): set by the fleet
    # facade at construction; when set, every trace event this runtime
    # emits carries it, so one shared obs sink stays attributable
    # per group (scripts/obs_report.py aggregates fleet-wide)
    group = None
    # round-22 WAL tap defaults (attach_wal installs; only the fast
    # drivers' harvest path feeds it)
    wal = None
    _wal_heap = None
    wal_last_lsn = 0

    def attach_obs(self, obs):
        self.obs = obs
        if self.wal is not None:
            # round-22: late obs attach still feeds the WAL's fsync-
            # latency + dirty-window series (the KVS builds the log
            # before any obs context exists)
            self.wal.obs = obs
        return obs

    def attach_wal(self, wal, heap=None):
        """Install the round-22 write-ahead log tap: every committed
        write harvest_comp surfaces is appended to ``wal`` (with its
        extent bytes read from ``heap`` in heap mode)."""
        self.wal = wal
        self._wal_heap = heap
        if wal.obs is None and self.obs is not None:
            wal.obs = self.obs
        return wal

    def _trace(self, name: str, **fields) -> None:
        if self.obs is not None:
            if self.group is not None and "group" not in fields:
                fields["group"] = self.group
            self.obs.tracer.event(name, step=self.step_idx, **fields)

    def healthy_replicas(self) -> list:
        """Replicas that are live AND unfrozen — the set that can serve and
        ack right now.  One definition for every consumer (chaos runner
        legality floors, KVS degraded mode + retry routing, grow/restart
        donor selection) so 'healthy' cannot drift between subsystems."""
        live = int(self.live[0])
        return [r for r in range(self.cfg.n_replicas)
                if (live >> r) & 1 and not self.frozen[r]]


class _ElasticResize:
    """Live group resize (round-10, hermes_tpu/elastic): administrative
    grow/shrink of the replica set under traffic, shared by both run
    drivers.  ``shrink`` composes the existing fence+remove (a removed
    replica self-fences and quorums re-evaluate against the shrunken
    mask); ``grow`` composes the existing join-with-state-transfer
    (value sync from a live donor, coordinator/replay re-validation of
    the donor's in-flight keys).  Both flush the serving pipeline first
    so every completion of the old quorum era lands before the epoch
    bumps, and both land on the obs timeline — distinct from detector-
    driven removals, which trace as suspect→remove."""

    def shrink(self, replica: int) -> None:
        """Resize OUT: fence + remove ``replica`` from every quorum.  The
        membership service (if attached) logs the removal as
        administrative (``note_shrink``) so a timeline reader can tell a
        planned shrink from a detector ejection."""
        if not (int(self.live[0]) >> replica) & 1:
            raise ValueError(f"replica {replica} is not live")
        if hasattr(self, "flush_pipeline"):
            self.flush_pipeline()
        self.remove(replica)
        if self.membership is not None:
            self.membership.note_shrink(self, replica)
        self._trace("shrink", replica=replica, live_mask=int(self.live[0]))

    def grow(self, replica: int, from_replica: Optional[int] = None) -> None:
        """Resize IN: value-sync ``replica`` from a live unfrozen donor
        (default: the lowest) via the join state-transfer path and
        re-admit it into quorums."""
        if (int(self.live[0]) >> replica) & 1 and not self.frozen[replica]:
            raise ValueError(f"replica {replica} is already live")
        if from_replica is None:
            cands = [d for d in self.healthy_replicas() if d != replica]
            if not cands:
                raise RuntimeError("grow needs a live unfrozen donor; "
                                   "none left")
            from_replica = cands[0]
        if hasattr(self, "flush_pipeline"):
            self.flush_pipeline()
        self.join(replica, from_replica)
        self._trace("grow", replica=replica, donor=from_replica,
                    live_mask=int(self.live[0]))


def _sum_meta_counters(m) -> dict:
    """Shared ``counters()`` body of both runtimes (round-8 satellite):
    the Meta tree is fetched ONCE by the caller; this just sums the
    already-host-resident columns."""
    return dict(
        n_read=m.n_read.sum(),
        n_write=m.n_write.sum(),
        n_rmw=m.n_rmw.sum(),
        n_abort=m.n_abort.sum(),
        lat_sum=m.lat_sum.sum(),
        lat_cnt=m.lat_cnt.sum(),
        lat_hist=m.lat_hist.sum(axis=0),
    )


class Runtime(_ObsHooks, _ElasticResize):
    def __init__(
        self,
        cfg: HermesConfig,
        backend: str = "batched",
        mesh=None,
        transport=None,
        record: bool = False,
        stream: Optional[st.OpStream] = None,
    ):
        self.cfg = cfg
        self.backend = backend
        r = cfg.n_replicas

        rs0 = st.init_replica_state(cfg)
        self.rs = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), rs0)
        raw = stream if stream is not None else ycsb.make_streams(cfg)
        self.stream = jax.tree.map(jnp.asarray, raw)

        self.step_idx = 0
        self.epoch = np.zeros((r,), np.int32)
        self.live = np.full((r,), cfg.full_mask, np.int32)
        self.frozen = np.zeros((r,), bool)
        # cached device copies of the membership rows (round-8 satellite):
        # re-uploaded only when freeze/thaw/set_live/remove/join dirty them
        self._ctl_dev = None
        self._ctl_dirty = True

        self.recorder = HistoryRecorder(cfg) if record else None
        self.membership = None  # optional MembershipService (attach_membership)

        if backend == "batched":
            self._fused = step_lib.build_step_batched(cfg)
        elif backend == "sharded":
            if mesh is None:
                raise ValueError("sharded backend needs a mesh")
            self._fused = step_lib.build_step_sharded(cfg, mesh)
            self.rs, self.stream = step_lib.place_sharded(cfg, mesh, self.rs, self.stream)
        elif backend == "sim":
            from hermes_tpu.transport.sim import SimTransport

            self._fused = None
            self.transport = transport if transport is not None else SimTransport(r)
            ph = step_lib.vmapped_phases(cfg)
            self._ph = {k: jax.jit(v) for k, v in ph.items()}
        else:
            raise ValueError(f"unknown backend {backend!r}")

    # -- control -----------------------------------------------------------

    def _ctl(self) -> step_lib.StepCtl:
        """Per-round control.  The membership rows (epoch/live/frozen) are
        uploaded once and cached on device until a membership/fault hook
        dirties them (the ``ctl_upload`` trace event counts the uploads);
        only the step scalar rides along per round (the phases engine keeps
        it host-derived — FastRuntime holds it device-resident)."""
        if self._ctl_dirty:
            self._ctl_dev = step_lib.StepCtl(
                step=jnp.int32(0),
                epoch=jnp.asarray(self.epoch),
                live_mask=jnp.asarray(self.live),
                frozen=jnp.asarray(self.frozen),
            )
            self._ctl_dirty = False
            self._trace("ctl_upload", epoch=int(self.epoch[0]),
                        live_mask=int(self.live[0]))
        return self._ctl_dev._replace(step=jnp.int32(self.step_idx))

    def freeze(self, replica: int) -> None:
        """Failure injection: replica stops processing and emitting
        (config 4, BASELINE.json:10)."""
        self.frozen[replica] = True
        self._ctl_dirty = True
        self._trace("freeze", replica=replica)

    def thaw(self, replica: int) -> None:
        self.frozen[replica] = False
        self._ctl_dirty = True
        self._trace("thaw", replica=replica)

    def set_live(self, mask: int) -> None:
        """Membership change: new live bitmap, epoch bump everywhere (stale
        epoch messages are dropped on receipt)."""
        self.live[:] = mask
        self.epoch += 1
        self._ctl_dirty = True

    def remove(self, replica: int) -> None:
        """Remove from membership AND fence: a removed replica must stop
        serving reads immediately (its keys can go stale the moment the
        quorum shrinks past it) — the lease self-fencing rule (SURVEY.md
        §5.3).  Freezing is how a fenced replica is modeled; join() unfences
        after state transfer."""
        self.frozen[replica] = True
        self.set_live(int(self.live[0]) & ~(1 << replica))
        self._trace("remove", replica=replica, live_mask=int(self.live[0]))

    def join(self, replica: int, from_replica: int) -> None:
        """Reconfiguration join (config 5, BASELINE.json:11): state transfer
        from a live replica, then admit.  Keys the donor holds in
        WRITE/TRANS/REPLAY (its own pending coordination) enter the joiner as
        INVALID — the joiner has no session/replay slot for them; the live
        coordinator's VAL (or the replay scan) validates them."""
        tbl = self.rs.table
        donor_state = tbl.state[from_replica]
        j_state = jnp.where(
            (donor_state == t.WRITE) | (donor_state == t.TRANS) | (donor_state == t.REPLAY),
            t.INVALID,
            donor_state,
        )
        new_tbl = st.KeyTable(
            state=tbl.state.at[replica].set(j_state),
            ver=tbl.ver.at[replica].set(tbl.ver[from_replica]),
            fc=tbl.fc.at[replica].set(tbl.fc[from_replica]),
            val=tbl.val.at[replica].set(tbl.val[from_replica]),
            inv_step=tbl.inv_step.at[replica].set(jnp.int32(self.step_idx)),
        )
        self.rs = self.rs._replace(table=new_tbl)
        self.frozen[replica] = False
        self.set_live(int(self.live[0]) | (1 << replica))
        self._trace("join", replica=replica, from_replica=from_replica,
                    live_mask=int(self.live[0]))
        if self.membership is not None:
            self.membership.note_join(self, replica)

    # -- stepping ----------------------------------------------------------

    def attach_membership(self, service) -> None:
        """Enable automatic lease-based failure detection: the service polls
        heartbeat clocks after every step (membership.MembershipService)."""
        self.membership = service

    def step_once(self) -> None:
        ctl = self._ctl()
        obs = self.obs
        trace = obs is not None and obs.trace_steps
        if trace:
            td = obs.tracer.span_begin("step_dispatch", step=self.step_idx)
        if self._fused is not None:
            self.rs, comp = self._fused(self.rs, self.stream, ctl)
        else:
            self.rs, comp = self._host_step(ctl)
        if trace:
            obs.tracer.span_end("step_dispatch", td)
        if self.recorder is not None:
            if trace:
                tr = obs.tracer.span_begin("readback", step=self.step_idx)
            comp_np = jax.device_get(comp)
            if trace:
                obs.tracer.span_end("readback", tr)
            self.recorder.record_step(comp_np)
        self.step_idx += 1
        if self.membership is not None:
            self.membership.poll(self)

    def _host_step(self, ctl: step_lib.StepCtl):
        """One step through step._step_core with host-mediated exchanges
        (sim/tcp transports) — the same body the fused backends run."""
        cfg = self.cfg
        pctl = step_lib._per_replica_ctl(cfg, ctl)
        step = self.step_idx

        def ex(fn):
            return lambda blk: _to_jnp(fn(jax.device_get(blk), step))

        return step_lib._step_core(
            cfg,
            self._ph,
            ex(self.transport.exchange_inv),
            ex(self.transport.exchange_ack),
            ex(self.transport.exchange_val),
            self.rs,
            self.stream,
            pctl,
        )

    def run(self, n_steps: int) -> None:
        for _ in range(n_steps):
            self.step_once()

    def drain(self, max_steps: int = 10_000) -> bool:
        """Step until every session finished its stream and the network is
        empty; returns False if max_steps elapsed first."""
        if self.obs is not None:
            with self.obs.tracer.span("drain", step=self.step_idx):
                return self._drain(max_steps)
        return self._drain(max_steps)

    def _drain(self, max_steps: int) -> bool:
        # one device-side reduction per poll (round-8 satellite) instead of
        # fetching the whole (R, S) status array: sessions not yet DONE on
        # live, unfrozen replicas — the membership rows ride the cached ctl
        from hermes_tpu.core import faststep as fst

        for _ in range(max_steps):
            ctl = self._ctl()
            undone = int(jax.device_get(fst.pending_sessions(
                self.rs.sess.status, ctl.live_mask, ctl.frozen)))
            net = getattr(self, "transport", None)
            net_empty = net.pending() == 0 if net is not None else True
            if undone == 0 and net_empty:
                return True
            self.step_once()
        return False

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        return _sum_meta_counters(jax.device_get(self.rs.meta))

    def history_ops(self):
        assert self.recorder is not None, "construct Runtime(record=True)"
        return self.recorder.finalize(jax.device_get(self.rs.sess))

    def check(self, max_keys: Optional[int] = None) -> lin.Verdict:
        """Finalize the history and run the linearizability gate
        (BASELINE.json:2)."""
        ops = self.history_ops()
        if max_keys is not None:
            ops = lin.sample_keys(ops, max_keys=max_keys)
        v = lin.check_history(ops, aborted_uids=self.recorder.aborted_uids)
        self._trace("checker_verdict", ok=v.ok, keys_checked=v.keys_checked)
        if not v.ok and self.obs is not None:
            # checker red: the linearizability witness failed — dump the
            # black box while the run's last records are still in the ring
            self.obs.flight_dump("checker_red",
                                 extra=dict(keys_checked=v.keys_checked))
        return v


def _to_jnp(block):
    return jax.tree.map(jnp.asarray, block)


class FastRuntime(_ObsHooks, _ElasticResize):
    """Run driver for the TPU-optimized round (core/faststep.py): same
    membership / failure-injection / history-recording surface as Runtime,
    over the packed-column FastState.  Backends: ``batched`` (R replicas on
    one device) and ``sharded`` (one replica per mesh device — the
    transport=tpu_ici layout, BASELINE.json:5)."""

    def __init__(self, cfg: HermesConfig, backend: str = "batched", mesh=None,
                 record=False, stream: Optional[st.OpStream] = None):
        from hermes_tpu.core import faststep as fst

        self.cfg = cfg
        self.backend = backend
        r = cfg.n_replicas
        # sharded: every shard owns its own value table (n_local allocates
        # per-replica vals); batched shares one (see faststep.FastTable)
        self.fs = fst.init_fast_state(cfg, n_local=r if backend == "sharded" else None)
        if cfg.device_stream:
            if stream is not None:
                raise ValueError(
                    "device_stream generates ops on device; a caller-supplied "
                    "op stream would be silently ignored")
            raw = ycsb.stub_stream(cfg)
        else:
            raw = stream if stream is not None else ycsb.make_streams(cfg)
        self.stream = fst.prep_stream(raw)

        # device-resident round counter (round-8): FastCtl.step is bumped
        # ON DEVICE between rounds (faststep.bump_step), so the steady
        # state uploads no control scalars at all; the host mirror
        # (step_idx) exists for tracing/recording only.  Assigning
        # step_idx (snapshot restore) re-seeds the device scalar.
        self._step_dev = jnp.int32(0)
        self.step_idx = 0
        self.epoch = np.zeros((r,), np.int32)
        self.live = np.full((r,), cfg.full_mask, np.int32)
        self.frozen = np.zeros((r,), bool)
        # cached device-side FastCtl rows (round-8): rebuilt+re-uploaded
        # only when a membership/fault/quiesce hook dirties them — zero
        # steady-state per-round H2D control transfers
        self._ctl_dev = None
        self._ctl_dirty = True
        # async completion-harvest ring (round-8): device-side Completions
        # handles of dispatched-but-unharvested rounds, drained FIFO so
        # completions surface strictly in round order.  Depth 1 (default)
        # is the synchronous pre-round-8 behavior.
        self._ring: collections.deque = collections.deque()
        self._devwait_s = 0.0
        # a client layer that defers its own completion handling (kvs.KVS)
        # installs a flush hook here so rebase/drain boundaries can force
        # every in-flight completion out before re-anchoring versions
        self.comp_flush = None
        # async failure detection (round-9): per-round device-side COPIES of
        # Meta.suspect_age ride this FIFO next to the completion ring, and
        # the last harvested (round, ages) feeds the membership service —
        # detection input rides the completion harvest, never a
        # dispatch-path device_get.  Copies, not views: the donated state
        # tree a round's ages live in dies at the NEXT dispatch, and a
        # fetch must only ever touch a round the harvest already proved
        # complete (fetching the freshest in-flight round's handle would
        # stall the host on the executing round and re-serialize the
        # pipeline — the regression this subsystem exists to avoid).
        self._age_ring: collections.deque = collections.deque()
        self.harvested_ages = None
        # version-rebase state (round-4, rebase_versions): host quiesce
        # flag (traced into FastCtl — flipping it never recompiles),
        # cumulative per-key version deltas for recorder continuity, and
        # the lazily-built rebase program
        self.quiesce = False
        self.rebases = 0
        # watermark value that TRIGGERED each auto-rebase (the true
        # pre-rebase peak — counter polls otherwise only ever see the
        # post-rebase value at the poll where a rebase fired)
        self.prerebase_peaks: list = []
        self._ver_base = None  # np.int64 (K,), allocated on first rebase
        self._rebase_fn = None
        self._in_rebase = False
        self._next_rebase_at = 0
        # completion consumer for rebase's internal quiesce drain: a client
        # layer that resolves futures off step_once's Completions (kvs.KVS)
        # installs its own step here so drained completions are never
        # dropped on the floor
        self.comp_sink = None
        # round-17 value heap: the client layer hooks the rebase boundary
        # (the one moment the store is quiesced, drained, and flushed) so
        # heap compaction rides EVERY version rebase — dead extents are
        # reclaimed exactly when dead versions are (kvs.KVS.heap_gc)
        self.rebase_hook = None
        # completion fetch per round (device->host).  At bench shape the
        # Completions tuple is tens of MB — a telemetry-only driver (e.g.
        # scripts/rebase_soak.py) sets this False to poll counters alone;
        # recording/client runs need it True (the default)
        self.fetch_completions = True
        # round-22 durability tier: an attached GroupCommitWal taps the
        # harvest stream — every committed write a harvested round
        # carries is appended (with its heap extent bytes) right after
        # the recorder sees it, so the log and the recorded history agree
        # record-for-record.  wal_last_lsn is the LSN of the newest
        # appended batch; kvs.KVS gates client resolution on it under
        # wal_sync='commit'.
        self.wal = None
        self._wal_heap = None
        self.wal_last_lsn = 0
        # record: False | True (Python Op recorder) | "array" (columnar
        # recorder + native witness checker, checker/fast.py — bench scale)
        if record == "array":
            self.recorder = ArrayRecorder(cfg)
        else:
            self.recorder = HistoryRecorder(cfg) if record else None
        self.membership = None

        # donated state (round-8): XLA aliases the state tree in place
        # instead of copying ~tens of MB per dispatch.  A superseded
        # reference to self.fs raises loudly on use (the red test in
        # tests/test_pipeline.py); cfg.donate_state=False restores the
        # copying program (the bench A/B baseline).
        if backend == "batched":
            self._step = _cached_step(
                cfg, "batched", None,
                lambda: fst.build_fast_batched(cfg, donate=cfg.donate_state))
        elif backend == "sharded":
            if mesh is None:
                raise ValueError("sharded backend needs a mesh")
            self._step = _cached_step(
                cfg, "sharded", mesh,
                lambda: fst.build_fast_sharded(cfg, mesh, rounds=1,
                                               donate=cfg.donate_state))
            self.fs, self.stream = fst.place_fast_sharded(cfg, mesh, self.fs, self.stream)
            self.mesh = mesh
        else:
            raise ValueError(f"unknown backend {backend!r}")
        self._fst = fst

    # -- device-resident control (round-8) ---------------------------------

    @property
    def step_idx(self) -> int:
        return self._step_idx

    @step_idx.setter
    def step_idx(self, v: int) -> None:
        # external assignment (snapshot restore) — re-seed the device
        # counter; the hot-path increment bypasses this (dispatch_round)
        self._step_idx = int(v)
        self._step_dev = jnp.int32(self._step_idx)

    @property
    def quiesce(self) -> bool:
        return self._quiesce

    @quiesce.setter
    def quiesce(self, v: bool) -> None:
        v = bool(v)
        if v != getattr(self, "_quiesce", None):
            self._ctl_dirty = True
        self._quiesce = v

    def _ctl(self):
        """Per-round FastCtl: every row lives ON DEVICE and is re-uploaded
        only when membership/fault/quiesce hooks dirty it (the
        ``ctl_upload`` trace event counts uploads — the steady-state round
        has none); the step scalar rides the device-side increment."""
        if self._ctl_dirty:
            fst = self._fst
            r = self.cfg.n_replicas
            ctl = fst.FastCtl(
                step=jnp.int32(0),  # per-round step rides _step_dev
                my_cid=jnp.arange(r, dtype=jnp.int32),
                epoch=jnp.asarray(self.epoch),
                live_mask=jnp.asarray(self.live),
                frozen=jnp.asarray(self.frozen),
                quiesce=jnp.bool_(self.quiesce),
            )
            if self.backend == "sharded" and jax.process_count() == 1:
                # pre-place the per-replica rows in their mesh sharding so
                # the dispatch doesn't re-spread them every round
                from jax.sharding import NamedSharding, PartitionSpec as P

                sh = NamedSharding(self.mesh, P("replica"))
                ctl = ctl._replace(
                    epoch=jax.device_put(ctl.epoch, sh),
                    live_mask=jax.device_put(ctl.live_mask, sh),
                    frozen=jax.device_put(ctl.frozen, sh),
                )
            self._ctl_dev = ctl
            self._ctl_dirty = False
            self._trace("ctl_upload", epoch=int(self.epoch[0]),
                        live_mask=int(self.live[0]))
        return self._ctl_dev._replace(step=self._step_dev)

    # -- membership / failure injection (same surface as Runtime) ----------

    def freeze(self, replica: int) -> None:
        self.frozen[replica] = True
        self._ctl_dirty = True
        self._trace("freeze", replica=replica)

    def thaw(self, replica: int) -> None:
        self.frozen[replica] = False
        self._ctl_dirty = True
        self._trace("thaw", replica=replica)

    def set_live(self, mask: int) -> None:
        self.live[:] = mask
        self.epoch += 1
        self._ctl_dirty = True

    def remove(self, replica: int) -> None:
        self.frozen[replica] = True
        self.set_live(int(self.live[0]) & ~(1 << replica))
        self._trace("remove", replica=replica, live_mask=int(self.live[0]))

    def join(self, replica: int, from_replica: int) -> None:
        """Reconfiguration join (config 5, BASELINE.json:11): copy a live
        donor's table; the donor's own pending-coordination keys enter the
        joiner as Invalid (validated by the live coordinator's VAL/replay)."""
        fst = self._fst
        tbl = self.fs.table
        K = self.cfg.n_keys
        if tbl.vpts.shape[0] != K:
            # sharded: each shard owns its table — transfer the donor's
            # rows, folding its in-flight coordination states to Invalid (the
            # live coordinator's VAL or the replay scan re-validates them)
            dst, dsrc = replica * K, from_replica * K
            d_rows = fst._bank_to_i32(
                jax.lax.dynamic_slice_in_dim(tbl.bank, dsrc, K))
            d_state = fst.sst_state(d_rows[:, fst.BANK_SST])
            j_state = jnp.where(
                (d_state == t.WRITE) | (d_state == t.TRANS) | (d_state == t.REPLAY),
                t.INVALID, d_state,
            )
            j_rows = d_rows.at[:, fst.BANK_SST].set(
                fst.pack_sst(jnp.int32(self.step_idx), j_state)
            )
            # (No issue-ledger transfer exists: a faststep write always
            # broadcasts — and so invalidates its key — in its own round,
            # so the joiner's in-flight writes are visible in the table
            # itself; see faststep._coordinate's revert rule.)
            self.fs = self.fs._replace(table=tbl._replace(
                vpts=jax.lax.dynamic_update_slice_in_dim(
                    tbl.vpts, jax.lax.dynamic_slice_in_dim(tbl.vpts, dsrc, K),
                    dst, 0),
                bank=jax.lax.dynamic_update_slice_in_dim(
                    tbl.bank, fst._i32_to_bank(j_rows), dst, 0),
            ))
        # batched: the authoritative table is shared — it already IS the
        # joiner's state, so no transfer is needed.
        self.frozen[replica] = False
        self.set_live(int(self.live[0]) | (1 << replica))
        self._trace("join", replica=replica, from_replica=from_replica,
                    live_mask=int(self.live[0]))
        if self.membership is not None:
            self.membership.note_join(self, replica)

    def attach_membership(self, service) -> None:
        self.membership = service

    # -- stepping ----------------------------------------------------------

    def dispatch_round(self):
        """Dispatch one protocol round WITHOUT syncing; returns the
        device-side Completions handles (None on multi-host runs — the
        global completion arrays span non-addressable devices).  The
        pipelined layers build on this: step_once's harvest ring and the
        KVS client layer both keep the handles in flight while the device
        runs the next round."""
        obs = self.obs
        trace = obs is not None and obs.trace_steps
        if trace:
            td = obs.tracer.span_begin("step_dispatch", step=self.step_idx)
        self.fs, comp = self._step(self.fs, self.stream, self._ctl())
        self._step_dev = self._fst.bump_step(self._step_dev)
        if trace:
            obs.tracer.span_end("step_dispatch", td)
        self._step_idx += 1
        if jax.process_count() > 1:
            assert self.recorder is None, "history recording is single-host only"
            return None
        if self.membership is not None:
            if self.fetch_completions or self.recorder is not None:
                # async detection (round-9): enqueue a device-side COPY of
                # this round's suspect_age columns (a few KB; the copy op
                # dispatches async and survives the donation of the state
                # tree at the next dispatch).  harvest_comp fetches the
                # copy belonging to the round it harvests — a round the
                # completion fetch already proved complete, so the age
                # readback never blocks on an executing round.
                self._age_ring.append(
                    (self.step_idx - 1, jnp.copy(self.fs.meta.suspect_age)))
            else:
                # telemetry-only runs (fetch_completions=False, no
                # recorder) never harvest, so the detector falls back to
                # the synchronous poll — the one remaining configuration
                # where an attached service syncs the dispatch
                self.membership.poll(self)
        return comp

    def harvest_comp(self, comp, round_idx: Optional[int] = None):
        """Fetch one dispatched round's completions, re-anchor rebased
        versions, and feed the recorder.  Callers must harvest in round
        order (the ring and kvs.KVS both drain FIFO) — the recorder's
        history is ordered by record time."""
        obs = self.obs
        trace = obs is not None and obs.trace_steps
        if trace:
            tr = obs.tracer.span_begin("readback", step=self.step_idx,
                                       round=round_idx)
        t0 = time.perf_counter() if obs is not None else 0.0
        comp_np = jax.device_get(comp)
        if obs is not None:
            dt = time.perf_counter() - t0
            self._devwait_s += dt
            obs.registry.counter("device_wait_s").inc(dt)
        if trace:
            obs.tracer.span_end("readback", tr)
        if self._age_ring and (round_idx is None
                               or self._age_ring[0][0] <= round_idx):
            # detector input (round-9): fetch the freshest suspect-age
            # copy belonging to a round at or before the one just
            # harvested — its device work completed with that round, so
            # this readback adds no stall — and run the suspicion machine
            age_round, age_h = self._age_ring.popleft()
            while self._age_ring and (round_idx is None
                                      or self._age_ring[0][0] <= round_idx):
                age_round, age_h = self._age_ring.popleft()
            self.harvested_ages = (age_round,
                                   np.asarray(jax.device_get(age_h)))
            if self.membership is not None:
                self.membership.poll(self)
        if self._ver_base is not None:
            # re-anchor post-rebase versions into the global (monotone)
            # version space the recorder/checker needs (see rebase_versions)
            multi = isinstance(comp_np, tuple) and not isinstance(comp_np, st.Completions)
            fix = lambda c: c._replace(
                ver=np.asarray(c.ver).astype(np.int64)
                + self._ver_base[np.asarray(c.key)])
            comp_np = (tuple(fix(c) for c in comp_np) if multi
                       else fix(comp_np))
        if self.recorder is not None:
            # read_unroll > 1 yields one Completions per sub-step, in
            # program order; record each
            multi = isinstance(comp_np, tuple) and not isinstance(comp_np, st.Completions)
            subs = comp_np if multi else (comp_np,)
            for c in subs:
                self.recorder.record_step(c)
        if self.wal is not None:
            # round-22: append AFTER the ver-base re-anchor above, so the
            # log carries globally-monotone versions (replay subtracts the
            # target runtime's own ver_base back out)
            multi = isinstance(comp_np, tuple) and not isinstance(comp_np, st.Completions)
            subs = comp_np if multi else (comp_np,)
            for c in subs:
                lsn = self.wal.append_comp(c, heap=self._wal_heap,
                                           round_idx=round_idx)
                if lsn is not None:
                    self.wal_last_lsn = lsn
            self.wal.kick()
        return comp_np

    def _harvest_one(self):
        idx, comp = self._ring.popleft()
        return self.harvest_comp(comp, round_idx=idx)

    def flush_pipeline(self) -> int:
        """Harvest every in-flight completion in round order (the ring plus
        any client layer's deferred round via ``comp_flush``); returns the
        number of ring rounds drained.  Rebase/drain/check boundaries call
        this so no completion is re-anchored with the wrong version era or
        missing from the recorded history."""
        n = len(self._ring)
        while self._ring:
            self._harvest_one()
        if self.comp_flush is not None:
            self.comp_flush()
        return n

    def step_once(self):
        """One protocol round.  At ``cfg.pipeline_depth == 1`` (default)
        this is synchronous: the round's host-side Completions are fetched
        and returned (also fed to the recorder when recording).  At depth
        >= 2 the round is dispatched and the OLDEST in-flight round is
        harvested instead once the ring is full (returns None while it
        fills) — the completion readback overlaps with the device
        executing newer rounds, and completions still surface strictly in
        round order.  ``fetch_completions=False`` (telemetry-only) runs
        never sync at all.  Multi-host runs (jax.distributed,
        hermes_tpu/launch.py) skip the completion fetch — use counters()
        (which allgathers) for observability there."""
        obs = self.obs
        t0 = time.perf_counter() if obs is not None else 0.0
        self._devwait_s = 0.0
        comp = self.dispatch_round()
        out = None
        if comp is not None and (self.fetch_completions
                                 or self.recorder is not None):
            self._ring.append((self.step_idx - 1, comp))
            if len(self._ring) >= self.cfg.pipeline_depth:
                out = self._harvest_one()
        if obs is not None:
            reg = obs.registry
            reg.counter("host_work_s").inc(
                time.perf_counter() - t0 - self._devwait_s)
            reg.gauge("pipeline_depth").set(len(self._ring))
            # windowed history (round-18, obs/series.py): ring occupancy
            # per round, keyed by the deterministic round index — the
            # occupancy-over-time view a controller steers on
            reg.series("pipeline_depth_series").append(
                self.step_idx, len(self._ring))
        return out

    def run(self, n_steps: int) -> None:
        for _ in range(n_steps):
            self.step_once()

    # -- version rebase (round-4; round-3 verdict item 4) ------------------

    def _inflight_count(self) -> int:
        s = jnp.sum((self.fs.sess.status == t.S_INFL).astype(jnp.int32))
        rp = jnp.sum(self.fs.replay.active.astype(jnp.int32))
        return int(jax.device_get(s + rp))

    def rebase_versions(self, quiesce: bool = True,
                        max_quiesce_rounds: int = 256) -> int:
        """Restore packed-ts headroom by resetting quiesced keys to version
        1 (faststep.build_rebase).  With ``quiesce`` (default), new intake
        and issues pause (FastCtl.quiesce — traced, no recompile) while
        in-flight writes/replays drain, so in a healthy run EVERY written
        key becomes eligible; frozen/dead replicas can pin their keys busy,
        in which case the pass is best-effort (busy keys keep their
        versions — sound, just less headroom recovered).

        Recorded histories stay checkable across the rebase: the per-key
        version delta accumulates in ``_ver_base`` and is added back to
        every later completion, so the checker's (ver, fc) witness order
        is globally monotone even though on-device versions restart.

        Returns the number of keys rebased."""
        if self.obs is not None:
            with self.obs.tracer.span("rebase_versions", step=self.step_idx):
                return self._rebase_versions(quiesce, max_quiesce_rounds)
        return self._rebase_versions(quiesce, max_quiesce_rounds)

    def _rebase_versions(self, quiesce: bool, max_quiesce_rounds: int) -> int:
        fst = self._fst
        if jax.process_count() > 1:
            raise NotImplementedError("rebase_versions is single-host only")
        if quiesce:
            prev = self.quiesce  # host may already be quiescing — restore
            self.quiesce = True
            step = self.comp_sink or self.step_once
            try:
                for _ in range(max_quiesce_rounds):
                    if self._inflight_count() == 0:
                        break
                    step()
            finally:
                self.quiesce = prev
        # every in-flight completion must land BEFORE the delta accumulates:
        # ring/client-deferred rounds were dispatched in the pre-rebase
        # version era and must be re-anchored with the pre-rebase _ver_base
        self.flush_pipeline()
        if self._rebase_fn is None:
            self._rebase_fn = fst.build_rebase(
                self.cfg, backend=self.backend,
                mesh=getattr(self, "mesh", None))
        self.fs, delta = self._rebase_fn(self.fs)
        delta = np.asarray(jax.device_get(delta)).astype(np.int64)
        n = int(np.count_nonzero(delta))
        if n:
            if self._ver_base is None:
                self._ver_base = np.zeros(self.cfg.n_keys, np.int64)
            self._ver_base += delta
            self.rebases += 1
        if self.rebase_hook is not None:
            # value-heap GC (round-17): the store is quiesced, drained,
            # and pipeline-flushed right here — the client layer compacts
            # dead extents while the invariant holds
            self.rebase_hook()
        return n

    def drain(self, max_steps: int = 10_000) -> bool:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "drain() polls per-step session status and is single-host "
                "only; multi-host runs should use run(n_steps)")
        if self.obs is not None:
            with self.obs.tracer.span("drain", step=self.step_idx):
                return self._drain(max_steps)
        return self._drain(max_steps)

    def _drain(self, max_steps: int) -> bool:
        # one device-side scalar per poll (round-8 satellite; was a full
        # (R, S) status fetch per iteration), with the membership rows
        # riding the cached device ctl
        fst = self._fst
        ok = False
        for _ in range(max_steps):
            ctl = self._ctl()
            undone = int(jax.device_get(fst.pending_sessions(
                self.fs.sess.status, ctl.live_mask, ctl.frozen)))
            if undone == 0:
                ok = True
                break
            self.step_once()
        # in-flight ring rounds carry completions the recorder still needs
        self.flush_pipeline()
        return ok

    # -- observability -----------------------------------------------------

    def counters(self) -> dict:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # meta leaves are (R, ...) sharded over the global 'replica'
            # axis; tiled=True reassembles the global value on every host
            # (non-fully-addressable arrays reject the stacking default)
            m = multihost_utils.process_allgather(self.fs.meta, tiled=True)
        else:
            m = jax.device_get(self.fs.meta)
        max_ver = self._check_version_headroom(m)
        out = _sum_meta_counters(m)
        out["max_ver"] = max_ver
        if self.obs is not None:
            # round-18 observed-state feeds, keyed by the poll's round
            # index: version watermark (headroom trend — the hottest key's
            # churn) and cumulative commit count (windowed rate = per-round
            # commit throughput); plus one Meta summary into the flight
            # recorder's last-N ring (device-truth context for a dump)
            reg = self.obs.registry
            reg.series("max_ver_series").append(self.step_idx, max_ver)
            reg.series("commits_series").append(
                self.step_idx, int(out["n_write"]) + int(out["n_rmw"]))
            self.obs.flight.note_meta(dict(
                step=self.step_idx,
                **{k: (v.tolist() if isinstance(v, np.ndarray) else int(v))
                   for k, v in out.items()}))
        return out

    def _check_version_headroom(self, m) -> int:
        """Packed-ts overflow guard (HermesConfig.max_key_versions): the
        engine tracks the max issued packed ts (Meta.max_pts); past the
        documented limit the int32 Lamport compare would corrupt silently.
        With ``cfg.auto_rebase`` (default), crossing the soft watermark
        (``cfg.rebase_fraction`` of the budget) at a counter poll triggers
        a quiesce+rebase (rebase_versions) that restores headroom instead
        of marching toward the cliff; the loud RuntimeError remains as the
        backstop for keys that cannot be rebased (e.g. pinned busy by a
        frozen coordinator).  Returns the high-water version."""
        from hermes_tpu.core import faststep as fst

        max_ver = int(np.asarray(m.max_pts).max()) >> fst.PTS_FC_BITS
        soft = int(self.cfg.rebase_fraction * self.cfg.max_key_versions)
        if (self.cfg.auto_rebase and not self._in_rebase
                and max_ver >= max(soft, self._next_rebase_at)
                and jax.process_count() == 1):
            self._in_rebase = True
            self.prerebase_peaks.append(max_ver)
            try:
                self.rebase_versions()
            finally:
                self._in_rebase = False
            max_ver = int(np.asarray(
                jax.device_get(self.fs.meta.max_pts)).max()) >> fst.PTS_FC_BITS
            # back off when a key can't be reclaimed (e.g. pinned busy by a
            # frozen coordinator): don't re-pay the quiesce drain on every
            # poll — only once the watermark has grown meaningfully again
            self._next_rebase_at = max_ver + max(
                1, self.cfg.max_key_versions // 64)
        if max_ver >= self.cfg.max_key_versions:
            raise RuntimeError(
                f"packed-timestamp overflow: a key reached version "
                f"{max_ver} >= max_key_versions={self.cfg.max_key_versions};"
                f" faststep's int32 packed ts cannot represent further "
                f"versions of this key — auto-rebase could not reclaim it "
                f"(busy/unquiesceable key); use the phases engine (Runtime) "
                f"for runs that rotate single keys this long"
            )
        return max_ver

    def _sess_view(self):
        fst = self._fst
        sess = jax.device_get(self.fs.sess)
        # sess.val holds int8 value BYTES; recorders read uid WORDS 0-1
        val32 = np.asarray(jax.device_get(fst._bank_to_i32(jnp.asarray(sess.val))))
        ver = np.asarray(fst.pts_ver(jnp.asarray(sess.pts))).astype(np.int64)
        if self._ver_base is not None:
            # pending in-flight ops carry current-era versions; re-anchor
            # them like step_once does for completions
            ver = ver + self._ver_base[np.asarray(sess.key)]
        return type("SessView", (), dict(
            status=sess.status, op=sess.op, key=sess.key, val=val32,
            ver=ver,
            fc=np.asarray(fst.pts_fc(jnp.asarray(sess.pts))),
            invoke_step=sess.invoke_step,
        ))

    def history_ops(self):
        assert self.recorder is not None, "construct FastRuntime(record=True)"
        self.flush_pipeline()
        rec = self.recorder.finalize(self._sess_view())
        return rec.to_ops() if isinstance(rec, ArrayRecorder) else rec

    def check(self, max_keys: Optional[int] = None) -> lin.Verdict:
        assert self.recorder is not None, "construct FastRuntime(record=True)"
        self.flush_pipeline()
        if isinstance(self.recorder, ArrayRecorder):
            self.recorder.finalize(self._sess_view())
            v = check_arrays(self.recorder, max_keys=max_keys)
        else:
            ops = self.history_ops()
            if max_keys is not None:
                ops = lin.sample_keys(ops, max_keys=max_keys)
            v = lin.check_history(ops, aborted_uids=self.recorder.aborted_uids)
        self._trace("checker_verdict", ok=v.ok, keys_checked=v.keys_checked)
        if not v.ok and self.obs is not None:
            # checker red: the linearizability witness failed — dump the
            # black box while the run's last records are still in the ring
            self.obs.flight_dump("checker_red",
                                 extra=dict(keys_checked=v.keys_checked))
        return v
