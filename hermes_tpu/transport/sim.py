"""Deterministic adversarial simulation transport (SURVEY.md §4.2).

The reference validates multi-node behavior in a single process
(BASELINE.json:7); upstream relied on asserts + operational validation.  The
rebuild goes further: this transport gives a *schedule-controlled* network —
per-(kind, src, dst, step) delay / drop / duplication — so protocol races
(delayed INVs, lost VALs, reordered ACK/VAL, replica stalls) are explored
deterministically and every run is gated by the linearizability checker.

Semantics: each directed edge carries one FIFO channel per message kind.  A
send enqueues zero or more copies (drop = zero, dup = two) with delivery
steps; every block due by the current step is delivered, merged in FIFO
order (later valid lanes overlay earlier ones — lane l always carries the
same session/slot's current pending record, so the overlay is the natural
"latest packet wins" of a real network).  Same-step delivery reproduces the
lockstep schedule exactly.

The protocol tolerates all of this by design: pending updates re-broadcast
their INV every step (idempotent same-ts), ACKs accumulate in the bitmap,
lost VALs are recovered by the replay scan (SURVEY.md §3.4).
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, Sequence, Tuple

import numpy as np

# schedule(kind, src, dst, step) -> list of delivery steps for this send.
# [step] = lockstep; [] = drop; [step+3] = delay; [step, step+2] = duplicate.
Schedule = Callable[[str, int, int, int], Sequence[int]]


def lockstep_schedule(kind: str, src: int, dst: int, step: int) -> Sequence[int]:
    return [step]


class SimTransport:
    """Host-side adversarial network between vmapped protocol phases.

    ``registry`` (optional ``hermes_tpu.obs.MetricsRegistry``) makes the
    adversarial schedule itself observable: per-kind send / dropped /
    duplicated / delayed counters plus the in-flight queue gauge, so a chaos
    soak's metrics record says HOW hostile the network actually was, not
    just how the protocol fared under it."""

    def __init__(self, n_replicas: int, schedule: Schedule = lockstep_schedule,
                 registry=None):
        self.r = n_replicas
        self.schedule = schedule
        self.registry = registry
        # (kind, src, dst) -> deque of (deliver_step, block-dict of numpy arrays)
        self.chan: Dict[Tuple[str, int, int], collections.deque] = collections.defaultdict(
            collections.deque
        )

    # -- helpers -----------------------------------------------------------

    def _send(self, kind: str, src: int, dst: int, step: int, block: dict) -> None:
        whens = list(self.schedule(kind, src, dst, step))
        reg = self.registry
        if reg is not None:
            reg.counter(f"net_{kind}_sends").inc()
            if not whens:
                reg.counter(f"net_{kind}_dropped").inc()
            elif len(whens) > 1:
                reg.counter(f"net_{kind}_duplicated").inc(len(whens) - 1)
            late = sum(1 for w in whens if w > step)
            if late:
                reg.counter(f"net_{kind}_delayed").inc(late)
        for when in whens:
            assert when >= step, "cannot deliver into the past"
            self.chan[(kind, src, dst)].append((when, block))

    def _recv(self, kind: str, src: int, dst: int, step: int):
        """Pop and merge every block due by ``step`` (FIFO; later valid lanes
        overlay earlier)."""
        q = self.chan[(kind, src, dst)]
        merged = None
        delivered = 0
        while q and q[0][0] <= step:
            blk = q.popleft()[1]
            delivered += 1
            if merged is None:
                merged = dict(blk)
                continue
            v = blk["valid"]
            for f, arr in blk.items():
                if f == "alive":
                    merged[f] = merged[f] | arr
                elif f == "valid":
                    continue
                elif arr.ndim > v.ndim:  # value words (L, V)
                    merged[f] = np.where(v[..., None], arr, merged[f])
                else:
                    merged[f] = np.where(v, arr, merged[f])
            merged["valid"] = merged["valid"] | v
        if delivered and self.registry is not None:
            self.registry.counter(f"net_{kind}_delivered").inc(delivered)
        return merged

    def _exchange_bcast(self, kind: str, out, step: int):
        """INV/VAL: outbound (R_src, L, ...) broadcast to every dst; inbound
        (R_dst, R_src, L, ...)."""
        fields = {f: np.asarray(v) for f, v in out._asdict().items()}
        r = self.r
        for src in range(r):
            block = {f: v[src] for f, v in fields.items()}
            for dst in range(r):
                self._send(kind, src, dst, step, block)
        inb = {
            f: np.zeros((r,) + v.shape, v.dtype) for f, v in fields.items()
        }
        for dst in range(r):
            for src in range(r):
                got = self._recv(kind, src, dst, step)
                if got is None:
                    continue
                for f in inb:
                    inb[f][dst, src] = got[f]
        return out._replace(**inb)

    def exchange_inv(self, out_inv, step: int):
        return self._exchange_bcast("inv", out_inv, step)

    def exchange_val(self, out_val, step: int):
        return self._exchange_bcast("val", out_val, step)

    def exchange_ack(self, out_ack, step: int):
        """ACK: outbound (R_src, R_dst, L, ...): row p of source q answers
        the INVs q received from p.  Inbound (R_dst, R_src, L, ...)."""
        fields = {f: np.asarray(v) for f, v in out_ack._asdict().items()}
        r = self.r
        for src in range(r):
            for dst in range(r):
                block = {f: v[src, dst] for f, v in fields.items()}
                self._send("ack", src, dst, step, block)
        inb = {
            f: np.zeros((r, r) + v.shape[2:], v.dtype) for f, v in fields.items()
        }
        for dst in range(r):
            for src in range(r):
                got = self._recv("ack", src, dst, step)
                if got is None:
                    continue
                for f in inb:
                    inb[f][dst, src] = got[f]
        return out_ack._replace(**inb)

    def pending(self) -> int:
        n = sum(len(q) for q in self.chan.values())
        if self.registry is not None:
            self.registry.gauge("net_pending_blocks").set(n)
        return n
