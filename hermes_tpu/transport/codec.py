"""Fixed-size wire codec for message blocks (tcp transport).

Blocks are NamedTuples of fixed-shape arrays (state.Invs/Acks/Vals), so a
block serializes to a fixed byte length: fields concatenated in definition
order, raveled, raw little-endian bytes (bool = 1 byte, int32 = 4).  Both
ends derive the layout from the same config, the way the reference's
fixed-format wire structs do (SURVEY.md §1 L1).

Round-11 adds the FRAME layer: every block that crosses a real (or
adversarial) wire rides a checksummed frame —

    [magic u16 | algo u8 | pad u8 | length u32 | crc u32] + payload

so corruption anywhere in the payload is *detected* on receipt and the
frame is downgraded to a drop (the protocol already tolerates drops:
idempotent re-INV, ack accumulation, replay scan) instead of a scrambled
key/ts/value entering the round.  ``frame_unpack`` raises ``FrameCorrupt``;
transports catch it, count it, and deliver nothing.

Checksum algorithm: CRC32C (Castagnoli) when the hardware-accelerated
``crc32c`` module is importable, else zlib's IEEE CRC32 — same 32-bit
detection strength, both C-speed; the ``algo`` header byte records which
one produced the sum so a receiver never verifies with the wrong
polynomial (a mismatch is itself a corruption verdict).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

try:  # pragma: no cover - depends on container image
    from crc32c import crc32c as _crc32c

    _ALGO = 1  # CRC32C (Castagnoli)
except ImportError:
    _crc32c = None
    _ALGO = 0  # IEEE CRC32 (zlib)

FRAME_MAGIC = 0x48F7  # 'H' | frame marker
FRAME_HEADER = struct.Struct("<HBBII")  # magic, algo, pad, length, crc
FRAME_OVERHEAD = FRAME_HEADER.size


class FrameCorrupt(ValueError):
    """A framed payload failed its integrity check (bad magic, length
    mismatch, or checksum mismatch): the frame must be treated as DROPPED,
    never applied."""


def wire_crc(payload: bytes, algo: int = _ALGO) -> int:
    """Frame checksum over ``payload`` with the given header algo byte.
    Raises ``FrameCorrupt`` for an algo this end cannot compute — a
    receiver must never fall back to the WRONG polynomial (every frame
    from a better-equipped sender would silently fail verification, and
    the only symptom would be a climbing corrupt_dropped counter)."""
    if algo == 1:
        if _crc32c is None:
            raise FrameCorrupt(
                "frame uses crc32c but no crc32c module is available on "
                "this end — install it or have the sender use the crc32 "
                "fallback (algo=0)")
        return _crc32c(payload) & 0xFFFFFFFF
    if algo == 0:
        return zlib.crc32(payload) & 0xFFFFFFFF
    raise FrameCorrupt(f"unknown frame checksum algo {algo}")


def block_nbytes(template) -> int:
    return sum(np.asarray(f).nbytes for f in template)


def frame_nbytes(template) -> int:
    """On-the-wire size of a framed block (header + payload)."""
    return FRAME_OVERHEAD + block_nbytes(template)


def pack(block) -> np.ndarray:
    """Serialize a block to a 1-D uint8 array."""
    parts = [np.ascontiguousarray(np.asarray(f)).view(np.uint8).ravel() for f in block]
    return np.concatenate(parts) if parts else np.zeros(0, np.uint8)


def unpack(template, buf: np.ndarray):
    """Deserialize ``buf`` (uint8, block_nbytes(template) long) into a block
    shaped like ``template``."""
    out = []
    off = 0
    for f in template:
        f = np.asarray(f)
        n = f.nbytes
        out.append(buf[off : off + n].view(f.dtype).reshape(f.shape))
        off += n
    assert off == buf.nbytes, "wire size mismatch"
    if hasattr(template, "_fields"):  # NamedTuple blocks
        return type(template)(*out)
    return tuple(out)  # bare field tuples (the interposer's frame path)


def frame_pack(payload: np.ndarray) -> np.ndarray:
    """Wrap a serialized block (``pack`` output) in a checksummed frame."""
    payload = np.ascontiguousarray(payload, dtype=np.uint8)
    pb = payload.tobytes()
    hdr = FRAME_HEADER.pack(FRAME_MAGIC, _ALGO, 0, len(pb), wire_crc(pb))
    return np.concatenate([np.frombuffer(hdr, np.uint8), payload])


def frame_unpack(buf: np.ndarray) -> np.ndarray:
    """Verify and strip a frame header; returns the payload bytes.  Raises
    ``FrameCorrupt`` on any integrity failure — the caller must treat the
    frame as dropped (and count it), NEVER apply its contents."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    if buf.nbytes < FRAME_OVERHEAD:
        raise FrameCorrupt(f"frame truncated: {buf.nbytes} < header "
                           f"{FRAME_OVERHEAD} bytes")
    magic, algo, _pad, length, crc = FRAME_HEADER.unpack(
        buf[:FRAME_OVERHEAD].tobytes())
    if magic != FRAME_MAGIC:
        raise FrameCorrupt(f"bad frame magic 0x{magic:04x}")
    payload = buf[FRAME_OVERHEAD:]
    if length != payload.nbytes:
        raise FrameCorrupt(
            f"frame length mismatch: header says {length}, "
            f"got {payload.nbytes}")
    got = wire_crc(payload.tobytes(), algo)
    if got != crc:
        raise FrameCorrupt(
            f"frame checksum mismatch: header 0x{crc:08x} != payload "
            f"0x{got:08x} (algo={'crc32c' if algo == 1 else 'crc32'})")
    return payload


def stack(blocks):
    """Stack per-source blocks into an inbound block with leading R axis."""
    first = blocks[0]
    return type(first)(*[np.stack([np.asarray(b[i]) for b in blocks]) for i in range(len(first))])


# --------------------------------------------------------------------------
# The host byte<->word codec (round-17: ONE implementation).
#
# The fast engines store values as int8 BYTE rows on device and int32 words
# at every host boundary (faststep._bank_to_i32 defines the byte order:
# little-endian word composition).  The host-side mirror of that codec used
# to live as private helpers in snapshot.py; the value heap (variable-
# length extents, ragged byte lengths) and the serving wire need it too, so
# it lives here now — snapshot.py aliases these.  Discipline: every
# conversion is a pure byte REINTERPRET (numpy views over contiguous
# buffers), never an astype — an astype of int8 bytes through a signed
# intermediate shears/sign-extends the tail bytes exactly the way the
# analyzer's dtype pass bans on device (tests/test_heap.py property-tests
# the adversarial lengths 0 / 1 / word-1 / word / word+1 / max with
# high-bit bytes in every position).
# --------------------------------------------------------------------------


def rows_to_words(rows8: np.ndarray) -> np.ndarray:
    """int8 byte rows (..., 4*W) -> int32 words (..., W): host mirror of
    faststep._bank_to_i32 (little-endian byte composition)."""
    u = rows8.view(np.uint8).astype(np.uint32)
    w = (u[..., 0::4] | (u[..., 1::4] << 8)
         | (u[..., 2::4] << 16) | (u[..., 3::4] << 24))
    return np.ascontiguousarray(w).view(np.int32)


def words_to_rows(rows32: np.ndarray) -> np.ndarray:
    """Inverse of ``rows_to_words`` (host mirror of faststep._i32_to_bank)."""
    u = np.ascontiguousarray(rows32).view(np.uint32)
    parts = np.stack([((u >> (8 * k)) & 0xFF) for k in range(4)],
                     axis=-1).astype(np.uint8)
    b = parts.reshape(rows32.shape[:-1] + (4 * rows32.shape[-1],))
    return b.view(np.int8)


def bytes_to_words(data, n_words=None) -> np.ndarray:
    """Ragged bytes -> zero-padded little-endian int32 words.  ``n_words``
    fixes the output width (the config-width discipline: both ends derive
    it from the same config); default is the tightest fit.  Byte-exact
    round trip with ``words_to_bytes`` for EVERY length including 0 and
    non-word-multiples — the tail bytes ride a zero-padded buffer view,
    never a sign-extending arithmetic conversion."""
    raw = bytes(data)
    need = (len(raw) + 3) // 4
    if n_words is None:
        n_words = need
    elif need > n_words:
        raise ValueError(f"{len(raw)} bytes exceed {n_words} int32 words")
    buf = np.zeros(4 * n_words, np.uint8)
    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    return buf.view(np.dtype("<i4")).copy()


def words_to_bytes(words, length=None) -> bytes:
    """int32 words -> the first ``length`` bytes (little-endian); default
    the full word span.  Inverse of ``bytes_to_words``."""
    w = np.ascontiguousarray(np.asarray(words, np.int32).ravel())
    raw = w.astype(np.dtype("<i4"), copy=False).tobytes()
    if length is None:
        return raw
    if length > len(raw):
        raise ValueError(f"length {length} exceeds the {len(raw)}-byte span")
    return raw[:length]


# --------------------------------------------------------------------------
# Columnar record primitives (round-19).
#
# The columnar wire codec (serving/wire.py batch functions) decodes a whole
# drained socket buffer in one numpy pass.  Fixed-stride record streams are
# a single reshape; heap-mode streams have variable strides, so the codec
# needs two primitives: gather K fixed-size headers at arbitrary byte
# offsets into a (K, H) matrix, and move ragged payload extents between a
# record stream and one contiguous blob.  Both are pure fancy-index passes
# over uint8 views — no per-row Python, the rows_to_words discipline
# applied to record streams.
# --------------------------------------------------------------------------


def _ragged_index(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat byte indices addressing ``lens[i]`` consecutive bytes from each
    ``starts[i]`` — the one index pattern behind ragged gather/scatter.
    Length-0 rows contribute nothing (np.repeat drops them)."""
    starts = np.asarray(starts, np.int64)
    lens = np.asarray(lens, np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    # position-within-row = global arange minus each row's exclusive cumsum
    excl = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return (np.repeat(starts, lens)
            + (np.arange(total, dtype=np.int64) - np.repeat(excl, lens)))


def gather_records(buf8: np.ndarray, offs: np.ndarray, nbytes: int) -> np.ndarray:
    """(K, nbytes) uint8 matrix of the fixed-size record heads at byte
    offsets ``offs`` in ``buf8`` — the variable-stride decode primitive
    (a fixed-stride stream is just ``buf8.reshape(k, stride)``)."""
    offs = np.asarray(offs, np.int64)
    if offs.size == 0:
        return np.zeros((0, nbytes), np.uint8)
    return buf8[offs[:, None] + np.arange(nbytes, dtype=np.int64)]


def scatter_records(out8: np.ndarray, offs: np.ndarray,
                    mat: np.ndarray) -> None:
    """Inverse of ``gather_records``: write each row of ``mat`` at its
    record's byte offset in ``out8`` (in place)."""
    offs = np.asarray(offs, np.int64)
    if offs.size == 0:
        return
    out8[offs[:, None] + np.arange(mat.shape[1], dtype=np.int64)] = mat


def ragged_gather(buf8: np.ndarray, starts: np.ndarray,
                  lens: np.ndarray) -> np.ndarray:
    """Concatenate the ragged extents ``buf8[starts[i]:starts[i]+lens[i]]``
    into one contiguous uint8 blob (one fancy-index pass)."""
    return buf8[_ragged_index(starts, lens)]


def ragged_scatter(out8: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                   blob8: np.ndarray) -> None:
    """Inverse of ``ragged_gather``: scatter a contiguous blob back out to
    ragged extents at ``starts`` (in place)."""
    idx = _ragged_index(starts, lens)
    out8[idx] = blob8[:idx.size]
