"""Fixed-size wire codec for message blocks (tcp transport).

Blocks are NamedTuples of fixed-shape arrays (state.Invs/Acks/Vals), so a
block serializes to a fixed byte length: fields concatenated in definition
order, raveled, raw little-endian bytes (bool = 1 byte, int32 = 4).  Both
ends derive the layout from the same config, the way the reference's
fixed-format wire structs do (SURVEY.md §1 L1).

Round-11 adds the FRAME layer: every block that crosses a real (or
adversarial) wire rides a checksummed frame —

    [magic u16 | algo u8 | pad u8 | length u32 | crc u32] + payload

so corruption anywhere in the payload is *detected* on receipt and the
frame is downgraded to a drop (the protocol already tolerates drops:
idempotent re-INV, ack accumulation, replay scan) instead of a scrambled
key/ts/value entering the round.  ``frame_unpack`` raises ``FrameCorrupt``;
transports catch it, count it, and deliver nothing.

Checksum algorithm: CRC32C (Castagnoli) when the hardware-accelerated
``crc32c`` module is importable, else zlib's IEEE CRC32 — same 32-bit
detection strength, both C-speed; the ``algo`` header byte records which
one produced the sum so a receiver never verifies with the wrong
polynomial (a mismatch is itself a corruption verdict).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

try:  # pragma: no cover - depends on container image
    from crc32c import crc32c as _crc32c

    _ALGO = 1  # CRC32C (Castagnoli)
except ImportError:
    _crc32c = None
    _ALGO = 0  # IEEE CRC32 (zlib)

FRAME_MAGIC = 0x48F7  # 'H' | frame marker
FRAME_HEADER = struct.Struct("<HBBII")  # magic, algo, pad, length, crc
FRAME_OVERHEAD = FRAME_HEADER.size


class FrameCorrupt(ValueError):
    """A framed payload failed its integrity check (bad magic, length
    mismatch, or checksum mismatch): the frame must be treated as DROPPED,
    never applied."""


def wire_crc(payload: bytes, algo: int = _ALGO) -> int:
    """Frame checksum over ``payload`` with the given header algo byte.
    Raises ``FrameCorrupt`` for an algo this end cannot compute — a
    receiver must never fall back to the WRONG polynomial (every frame
    from a better-equipped sender would silently fail verification, and
    the only symptom would be a climbing corrupt_dropped counter)."""
    if algo == 1:
        if _crc32c is None:
            raise FrameCorrupt(
                "frame uses crc32c but no crc32c module is available on "
                "this end — install it or have the sender use the crc32 "
                "fallback (algo=0)")
        return _crc32c(payload) & 0xFFFFFFFF
    if algo == 0:
        return zlib.crc32(payload) & 0xFFFFFFFF
    raise FrameCorrupt(f"unknown frame checksum algo {algo}")


def block_nbytes(template) -> int:
    return sum(np.asarray(f).nbytes for f in template)


def frame_nbytes(template) -> int:
    """On-the-wire size of a framed block (header + payload)."""
    return FRAME_OVERHEAD + block_nbytes(template)


def pack(block) -> np.ndarray:
    """Serialize a block to a 1-D uint8 array."""
    parts = [np.ascontiguousarray(np.asarray(f)).view(np.uint8).ravel() for f in block]
    return np.concatenate(parts) if parts else np.zeros(0, np.uint8)


def unpack(template, buf: np.ndarray):
    """Deserialize ``buf`` (uint8, block_nbytes(template) long) into a block
    shaped like ``template``."""
    out = []
    off = 0
    for f in template:
        f = np.asarray(f)
        n = f.nbytes
        out.append(buf[off : off + n].view(f.dtype).reshape(f.shape))
        off += n
    assert off == buf.nbytes, "wire size mismatch"
    if hasattr(template, "_fields"):  # NamedTuple blocks
        return type(template)(*out)
    return tuple(out)  # bare field tuples (the interposer's frame path)


def frame_pack(payload: np.ndarray) -> np.ndarray:
    """Wrap a serialized block (``pack`` output) in a checksummed frame."""
    payload = np.ascontiguousarray(payload, dtype=np.uint8)
    pb = payload.tobytes()
    hdr = FRAME_HEADER.pack(FRAME_MAGIC, _ALGO, 0, len(pb), wire_crc(pb))
    return np.concatenate([np.frombuffer(hdr, np.uint8), payload])


def frame_unpack(buf: np.ndarray) -> np.ndarray:
    """Verify and strip a frame header; returns the payload bytes.  Raises
    ``FrameCorrupt`` on any integrity failure — the caller must treat the
    frame as dropped (and count it), NEVER apply its contents."""
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    if buf.nbytes < FRAME_OVERHEAD:
        raise FrameCorrupt(f"frame truncated: {buf.nbytes} < header "
                           f"{FRAME_OVERHEAD} bytes")
    magic, algo, _pad, length, crc = FRAME_HEADER.unpack(
        buf[:FRAME_OVERHEAD].tobytes())
    if magic != FRAME_MAGIC:
        raise FrameCorrupt(f"bad frame magic 0x{magic:04x}")
    payload = buf[FRAME_OVERHEAD:]
    if length != payload.nbytes:
        raise FrameCorrupt(
            f"frame length mismatch: header says {length}, "
            f"got {payload.nbytes}")
    got = wire_crc(payload.tobytes(), algo)
    if got != crc:
        raise FrameCorrupt(
            f"frame checksum mismatch: header 0x{crc:08x} != payload "
            f"0x{got:08x} (algo={'crc32c' if algo == 1 else 'crc32'})")
    return payload


def stack(blocks):
    """Stack per-source blocks into an inbound block with leading R axis."""
    first = blocks[0]
    return type(first)(*[np.stack([np.asarray(b[i]) for b in blocks]) for i in range(len(first))])
