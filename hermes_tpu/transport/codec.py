"""Fixed-size wire codec for message blocks (tcp transport).

Blocks are NamedTuples of fixed-shape arrays (state.Invs/Acks/Vals), so a
block serializes to a fixed byte length: fields concatenated in definition
order, raveled, raw little-endian bytes (bool = 1 byte, int32 = 4).  Both
ends derive the layout from the same config, the way the reference's
fixed-format wire structs do (SURVEY.md §1 L1)."""

from __future__ import annotations

import numpy as np


def block_nbytes(template) -> int:
    return sum(np.asarray(f).nbytes for f in template)


def pack(block) -> np.ndarray:
    """Serialize a block to a 1-D uint8 array."""
    parts = [np.ascontiguousarray(np.asarray(f)).view(np.uint8).ravel() for f in block]
    return np.concatenate(parts) if parts else np.zeros(0, np.uint8)


def unpack(template, buf: np.ndarray):
    """Deserialize ``buf`` (uint8, block_nbytes(template) long) into a block
    shaped like ``template``."""
    out = []
    off = 0
    for f in template:
        f = np.asarray(f)
        n = f.nbytes
        out.append(buf[off : off + n].view(f.dtype).reshape(f.shape))
        off += n
    assert off == buf.nbytes, "wire size mismatch"
    return type(template)(*out)


def stack(blocks):
    """Stack per-source blocks into an inbound block with leading R axis."""
    first = blocks[0]
    return type(first)(*[np.stack([np.asarray(b[i]) for b in blocks]) for i in range(len(first))])
