"""RDMA transport stub (SURVEY.md §2 "Net-transport: rdma").

The reference's primary transport is RDMA (ibverbs UD sends with inlining,
doorbell batching, credit flow control, and a memcached-style bootstrap for
QP exchange).  This environment has no RDMA NIC, so per the survey the
plugin *interface* ships with an explicit stub: the constructor documents
exactly what a real implementation must provide, and fails loudly rather
than silently degrading to something slower.

A real backend would implement the same surface as transport.tcp.TcpMesh —
``exchange(out_slices: (R, B) uint8) -> (R, B) uint8`` with per-edge FIFO
delivery — on ibverbs: one UD QP per process, INV/ACK/VAL records inlined
into sends (IBV_SEND_INLINE for <= ~188B), doorbell-batched posts per step,
and a credit counter per peer for flow control.
"""

from __future__ import annotations


class RdmaMesh:
    """Interface-compatible stand-in for an ibverbs transport."""

    def __init__(self, my_rank: int, n_ranks: int, hosts: str | None = None, **kw):
        raise NotImplementedError(
            "transport=rdma requires an RDMA NIC and an ibverbs build; this "
            "environment has neither.  Use transport=tcp (same wire contract "
            "over sockets) or transport=tpu_ici (ICI collectives).  See this "
            "module's docstring for the implementation contract."
        )
