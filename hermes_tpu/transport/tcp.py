"""TCP transport binding (SURVEY.md §2 "Net-transport: tcp").

ctypes binding of the C++ full-mesh exchanger (native/tcp_transport.cpp).
One process = one Hermes replica; ``TcpMesh.exchange`` moves one fixed-size
block per peer per call with per-edge FIFO + reliability (TCP), i.e. the
lockstep schedule of the sim transport realized over real sockets.  Used by
hermes_tpu.distributed for multi-process runs; proves the transport plugin
seam is real native code, not a Python stand-in.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO = _NATIVE_DIR / "libhermes_tcp.so"
_SRC = _NATIVE_DIR / "tcp_transport.cpp"


def _ensure_built(force: bool = False) -> pathlib.Path:
    if not force and _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    # Atomic build: compile to a unique temp path, rename into place — many
    # replica processes may race here on a fresh checkout, and a rank must
    # never dlopen a half-written .so.
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC), "-pthread"],
        check=True,
        cwd=str(_NATIVE_DIR),
    )
    os.replace(tmp, _SO)
    return _SO


class TcpMesh:
    """Full-mesh, step-synchronous block exchange between replica processes.

    ``registry`` (optional ``hermes_tpu.obs.MetricsRegistry``) counts
    exchanges and wire bytes per rank — the distributed driver's transport
    feed into the obs metrics snapshot."""

    def __init__(self, my_rank: int, n_ranks: int, hosts: str | None = None,
                 base_port: int = 29500, registry=None):
        from hermes_tpu.core.compat import load_native

        self.registry = registry
        lib = load_native(_ensure_built)
        lib.ht_create.restype = ctypes.c_void_p
        lib.ht_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.ht_exchange.restype = ctypes.c_int
        lib.ht_exchange.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ht_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self.my_rank = my_rank
        self.n_ranks = n_ranks
        hosts = hosts or ",".join(["127.0.0.1"] * n_ranks)
        self._h = lib.ht_create(my_rank, n_ranks, hosts.encode(), base_port)
        if not self._h:
            raise RuntimeError(
                f"tcp mesh setup failed (rank {my_rank}/{n_ranks}, base_port {base_port})"
            )

    def exchange(self, out_slices: np.ndarray) -> np.ndarray:
        """out_slices: (R, B) uint8, slice r to rank r.  Returns (R, B) with
        slice r received from rank r (self slice copied through)."""
        out = np.ascontiguousarray(out_slices, dtype=np.uint8)
        assert out.shape[0] == self.n_ranks
        inb = np.empty_like(out)
        rc = self._lib.ht_exchange(
            self._h,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_uint64(out.shape[1]),
            inb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if rc != 0:
            raise RuntimeError("tcp exchange failed (peer closed?)")
        if self.registry is not None:
            self.registry.counter("net_tcp_exchanges").inc()
            # every exchange moves one block per non-self peer, both ways
            self.registry.counter("net_tcp_bytes_sent").inc(
                int(out.shape[1]) * (self.n_ranks - 1))
            self.registry.counter("net_tcp_bytes_recv").inc(
                int(out.shape[1]) * (self.n_ranks - 1))
        return inb

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ht_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass
