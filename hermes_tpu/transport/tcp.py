"""TCP transport binding (SURVEY.md §2 "Net-transport: tcp").

ctypes binding of the C++ full-mesh exchanger (native/tcp_transport.cpp).
One process = one Hermes replica; ``TcpMesh.exchange`` moves one fixed-size
block per peer per call with per-edge FIFO + reliability (TCP), i.e. the
lockstep schedule of the sim transport realized over real sockets.  Used by
hermes_tpu.distributed for multi-process runs; proves the transport plugin
seam is real native code, not a Python stand-in.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading

import numpy as np

from hermes_tpu.concurrency import make_lock

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO = _NATIVE_DIR / "libhermes_tcp.so"
_SRC = _NATIVE_DIR / "tcp_transport.cpp"


def _ensure_built(force: bool = False) -> pathlib.Path:
    if not force and _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    # Atomic build: compile to a unique temp path, rename into place — many
    # replica processes may race here on a fresh checkout, and a rank must
    # never dlopen a half-written .so.
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC), "-pthread"],
        check=True,
        cwd=str(_NATIVE_DIR),
    )
    os.replace(tmp, _SO)
    return _SO


def serving_listener(host: str, port: int, reuseport: bool = False,
                     backlog: int = 128):
    """Bound+listening TCP socket for the serving RPC servers
    (round-19).  ``reuseport=True`` sets SO_REUSEPORT before bind so N
    worker processes can shard accepts on ONE port — the kernel
    load-balances incoming connections across the listeners.  Raises
    loudly where the platform has no SO_REUSEPORT rather than silently
    falling back to a single-listener bind (the second worker would
    EADDRINUSE anyway, later and more confusingly)."""
    import socket as _socket

    sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        if reuseport:
            if not hasattr(_socket, "SO_REUSEPORT"):
                raise RuntimeError(
                    "accept sharding needs SO_REUSEPORT, which this "
                    "platform's socket module does not expose")
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
        sock.bind((host, port))
        sock.listen(backlog)
    except BaseException:
        sock.close()
        raise
    return sock


class FramedSocket:
    """Checksummed-frame message boundary over one stream socket
    (round-14, the serving RPC path).  Every message crosses as a
    round-11 CRC frame (``codec.frame_pack``): the fixed header carries
    the payload length (the stream framing) AND the checksum (end-to-end
    integrity) — a corrupt frame raises ``codec.FrameCorrupt`` at the
    receiver, which must treat it as dropped, never decode it.

    Blocking, one-message-at-a-time; the serving server gives each
    connection its own reader thread (serving/rpc.py).  ``send`` is
    internally serialized, so two threads sharing one FramedSocket can
    never splice frames mid-stream.

    ``expect_lens`` (optional: a set of plausible payload lengths, OR a
    predicate ``len -> bool`` for variable-size protocols like the
    round-16 K_MGET/K_SCAN frames) is consulted ONLY when a frame fails
    its CRC: a failing frame whose length field is not a plausible
    message size most likely had the LENGTH itself corrupted — skipping
    it would silently misalign the stream cursor — so the stream tears
    down loudly instead.  Frames with a valid CRC pass through at any
    length (the server must still see wrong-width-but-intact requests
    to refuse them decodably)."""

    def __init__(self, sock, expect_lens=None):
        from hermes_tpu.transport import codec

        self._codec = codec
        self.sock = sock
        self.corrupt_dropped = 0
        if expect_lens is None or callable(expect_lens):
            self._plausible = expect_lens
        else:
            lens = frozenset(expect_lens)
            self._plausible = lens.__contains__
        # make_lock: instrumented under HERMES_LOCKLINT=1 (sanitizer
        # soaks), plain threading.Lock otherwise
        self._send_lock = make_lock("FramedSocket._send_lock")

    def send(self, payload: bytes) -> None:
        frame = self._codec.frame_pack(np.frombuffer(
            bytes(payload), np.uint8))
        # sendall UNDER the lock is deliberate (a BlockingAudit in
        # concurrency.REGISTRY): the lock exists precisely to keep
        # whole frames atomic on the stream, and SO_SNDTIMEO bounds
        # the stall a non-reading peer can impose
        with self._send_lock:
            self.sock.sendall(frame.tobytes())

    def _read_exact(self, n: int):
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None  # peer closed
            buf += chunk
        return bytes(buf)

    def recv(self):
        """One framed payload as bytes, None on orderly EOF.  A frame
        that fails its CRC is counted and skipped (the serving analogue
        of TcpHostTransport's corrupt -> zero-block downgrade); a
        header too mangled to carry a believable length — or, with
        ``expect_lens``, a CRC failure on an implausible length —
        tears the stream down (raises), since the message boundary
        itself is suspect."""
        codec = self._codec
        while True:
            hdr = self._read_exact(codec.FRAME_OVERHEAD)
            if hdr is None:
                return None
            magic, _algo, _pad, length, _crc = codec.FRAME_HEADER.unpack(hdr)
            if magic != codec.FRAME_MAGIC or length > (1 << 26):
                raise codec.FrameCorrupt(
                    f"unrecoverable stream framing (magic 0x{magic:04x}, "
                    f"len {length}): message boundary lost")
            body = self._read_exact(length)
            if body is None:
                return None
            try:
                payload = codec.frame_unpack(np.frombuffer(
                    hdr + body, np.uint8))
            except codec.FrameCorrupt:
                if (self._plausible is not None
                        and not self._plausible(length)):
                    # the CRC failed AND the length field names no
                    # plausible message: the corruption likely hit the
                    # length itself, so the bytes just consumed straddle
                    # a real frame boundary — "skip and continue" would
                    # silently desynchronize the stream
                    raise codec.FrameCorrupt(
                        f"CRC failure on implausible frame length "
                        f"{length}: length field suspect, stream "
                        f"alignment lost") from None
                self.corrupt_dropped += 1
                continue
            return payload.tobytes()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class TcpMesh:
    """Full-mesh, step-synchronous block exchange between replica processes.

    ``registry`` (optional ``hermes_tpu.obs.MetricsRegistry``) counts
    exchanges and wire bytes per rank — the distributed driver's transport
    feed into the obs metrics snapshot."""

    def __init__(self, my_rank: int, n_ranks: int, hosts: str | None = None,
                 base_port: int = 29500, registry=None):
        from hermes_tpu.core.compat import load_native

        self.registry = registry
        lib = load_native(_ensure_built)
        lib.ht_create.restype = ctypes.c_void_p
        lib.ht_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        lib.ht_exchange.restype = ctypes.c_int
        lib.ht_exchange.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ht_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self.my_rank = my_rank
        self.n_ranks = n_ranks
        hosts = hosts or ",".join(["127.0.0.1"] * n_ranks)
        self._h = lib.ht_create(my_rank, n_ranks, hosts.encode(), base_port)
        if not self._h:
            raise RuntimeError(
                f"tcp mesh setup failed (rank {my_rank}/{n_ranks}, base_port {base_port})"
            )

    def exchange(self, out_slices: np.ndarray) -> np.ndarray:
        """out_slices: (R, B) uint8, slice r to rank r.  Returns (R, B) with
        slice r received from rank r (self slice copied through)."""
        out = np.ascontiguousarray(out_slices, dtype=np.uint8)
        assert out.shape[0] == self.n_ranks
        inb = np.empty_like(out)
        rc = self._lib.ht_exchange(
            self._h,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_uint64(out.shape[1]),
            inb.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if rc != 0:
            raise RuntimeError("tcp exchange failed (peer closed?)")
        if self.registry is not None:
            self.registry.counter("net_tcp_exchanges").inc()
            # every exchange moves one block per non-self peer, both ways
            self.registry.counter("net_tcp_bytes_sent").inc(
                int(out.shape[1]) * (self.n_ranks - 1))
            self.registry.counter("net_tcp_bytes_recv").inc(
                int(out.shape[1]) * (self.n_ranks - 1))
        return inb

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.ht_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort
        try:
            self.close()
        except Exception:
            pass


class TcpHostTransport:
    """``transport.base.HostTransport`` over the C++ TcpMesh, one process =
    one replica (round-11; extracted from hermes_tpu.distributed so the
    socket path is a first-class transport the chaos interposer can wrap).

    Single-rank layout: outbound blocks are THIS rank's (no leading R_src
    axis); inbound blocks carry a leading ``(R_src, ...)`` axis with the
    destination implicit (``local_rank`` mode of
    chaos.net.FaultingTransport).

    Every block crosses the wire as a checksummed FRAME
    (codec.frame_pack): TCP already guarantees link integrity, but the
    frame CRC is END-TO-END — a corrupted or mis-framed payload (buggy
    peer, adversarial interposer, torn buffer) is detected on receipt and
    downgraded to a DROP (zero block, counted in ``corrupt_dropped``)
    instead of a scrambled key/ts/value entering the protocol, which
    tolerates drops by design (re-INV, ack accumulation, replay scan)."""

    def __init__(self, cfg, my_rank: int, n_ranks: int,
                 hosts: str | None = None, base_port: int = 29500,
                 registry=None, mesh=None):
        import jax

        from hermes_tpu.core import state as st
        from hermes_tpu.transport import codec

        self._codec = codec
        self.cfg = cfg
        self.my_rank = my_rank
        self.n_ranks = n_ranks
        # ``mesh``: injectable exchanger (tests stub the socket layer to
        # exercise the frame path without a live peer set)
        self.mesh = mesh if mesh is not None else TcpMesh(
            my_rank, n_ranks, hosts=hosts, base_port=base_port,
            registry=registry)
        self._inv_t = jax.tree.map(np.asarray, st.empty_invs(cfg))
        self._ack_row_t = jax.tree.map(
            lambda x: np.asarray(x)[0], st.empty_acks(cfg, lead=(n_ranks,)))
        self._val_t = jax.tree.map(np.asarray, st.empty_vals(cfg))
        self.corrupt_dropped = 0

    def _exchange_framed(self, template, rows):
        """Frame per-peer payload rows, move them through the mesh, verify
        + unpack each inbound frame (corrupt -> zero block + counter)."""
        codec = self._codec
        framed = np.stack([codec.frame_pack(r) for r in rows])
        inb = self.mesh.exchange(framed)
        blocks = []
        for r in range(self.n_ranks):
            try:
                payload = codec.frame_unpack(inb[r])
                blocks.append(codec.unpack(template, payload))
            except codec.FrameCorrupt:
                self.corrupt_dropped += 1
                if self.mesh.registry is not None:
                    self.mesh.registry.counter("net_tcp_corrupt_dropped").inc()
                blocks.append(type(template)(
                    *[np.zeros_like(np.asarray(f)) for f in template]))
        return codec.stack(blocks)

    def _bcast(self, template, block):
        """INV/VAL: the same serialized block goes to every peer."""
        import jax

        payload = self._codec.pack(jax.device_get(block))
        return self._exchange_framed(
            template, [payload] * self.n_ranks)

    def exchange_inv(self, out_inv, step: int):
        return self._bcast(self._inv_t, out_inv)

    def exchange_val(self, out_val, step: int):
        return self._bcast(self._val_t, out_val)

    def exchange_ack(self, out_ack, step: int):
        """ACK: row p of my (R, L) block routes to rank p."""
        import jax

        blk = jax.device_get(out_ack)
        rows = [self._codec.pack(jax.tree.map(lambda x: np.asarray(x)[p], blk))
                for p in range(self.n_ranks)]
        return self._exchange_framed(self._ack_row_t, rows)

    def pending(self) -> int:
        return 0  # TCP delivers within the exchange: nothing in flight after

    def close(self) -> None:
        self.mesh.close()
