"""Host-mediated transport interface.

The in-jit backends (batched / tpu_ici) fuse the exchange into the compiled
step (core/step.py).  Host-mediated backends implement this interface
instead: the runtime calls ``exchange_{inv,ack,val}`` between phase
invocations, passing outbound blocks with a leading source-replica axis and
receiving inbound blocks with leading (dst, src) axes.

Blocks are numpy pytrees (state.Invs / Acks / Vals):

  * INV/VAL outbound: per-src ``(R, L, ...)`` is NOT the shape — outbound is
    ``(R_src, L, ...)`` one lane-block per source (broadcast semantics: the
    same block goes to every destination).
  * ACK outbound: ``(R_src, R_dst, L, ...)`` — acks are point-to-point,
    row p of src q answers the INVs q received from p and is routed back to p.
  * Inbound (all kinds): ``(R_dst, R_src, L, ...)``.
"""

from __future__ import annotations

from typing import Protocol

import numpy as np


class HostTransport(Protocol):
    def exchange_inv(self, out_inv, step: int): ...

    def exchange_ack(self, out_ack, step: int): ...

    def exchange_val(self, out_val, step: int): ...


class LockstepHostTransport:
    """Zero-delay host exchange — semantically identical to the in-jit
    batched backend; the degenerate case of the sim transport."""

    def exchange_inv(self, out_inv, step: int):
        r = np.asarray(out_inv.valid).shape[0]
        return out_inv._replace(
            **{
                f: np.broadcast_to(np.asarray(v)[None], (r,) + np.asarray(v).shape)
                for f, v in out_inv._asdict().items()
            }
        )

    def exchange_ack(self, out_ack, step: int):
        return out_ack._replace(
            **{f: np.swapaxes(np.asarray(v), 0, 1) for f, v in out_ack._asdict().items()}
        )

    def exchange_val(self, out_val, step: int):
        r = np.asarray(out_val.valid).shape[0]
        return out_val._replace(
            **{
                f: np.broadcast_to(np.asarray(v)[None], (r,) + np.asarray(v).shape)
                for f, v in out_val._asdict().items()
            }
        )
