"""Net-transport plugin layer (SURVEY.md §1 L1, §5.8).

The reference moves INV/ACK/VAL batches through a transport plugin interface
with `rdma` and `tcp` backends; BASELINE.json:5 adds `tpu_ici` as the target.
The rebuild's seam is the *exchange* of fixed-shape message blocks once per
phase boundary:

  * ``tpu_ici``  — collectives inside one jit step (core/step.py sharded)
  * ``batched``  — array ops inside one jit step, R replicas on one device
  * ``sim``      — host-mediated, deterministic + adversarial (this package)
  * ``tcp``      — host-mediated over real sockets via the C++ core (M5)
  * ``rdma``     — interface stub (no NIC in scope; SURVEY.md §2)
"""

from hermes_tpu.transport.base import HostTransport, LockstepHostTransport

__all__ = ["HostTransport", "LockstepHostTransport"]
