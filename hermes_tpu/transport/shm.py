"""Zero-copy SPSC columnar rings over POSIX shared memory (round-21).

The serving data plane's process boundary: N front-end worker processes
feed ONE device-owning store process (serving/ipc.py), and every byte
crosses that boundary through these rings — preallocated columnar slots
(numpy views over one ``multiprocessing.shared_memory`` block), a
seq-counter handshake per slot batch, and LOUD backpressure on a full
ring.  Nothing is pickled, nothing is copied through a pipe: the
producer writes request columns straight into mapped memory and the
consumer reads the same cache lines.

Ring layout (one shared block; every array 64-byte aligned)::

    begin[nslots]  u64   producer: stamped BEFORE the slot fill
    end[nslots]    u64   producer: stamped AFTER count + columns
    count[nslots]  i64   rows valid in the slot this generation
    ack[nslots]    u64   consumer: stamped after the slot is drained
    <field 0>[nslots, slot_rows(, width)]   caller-declared columns
    <field 1> ...

Seq-counter protocol — slot ``i`` at monotone position ``pos`` carries
generation ``g = pos // nslots + 1`` (generations start at 1 so the
all-zero fresh mapping reads as "generation 0 fully consumed"):

  * producer claim: legal iff ``ack[i] == g - 1`` (the consumer has
    drained the previous lap).  Claiming stamps ``begin[i] = g``.
  * producer commit: fill columns, write ``count[i]``, THEN stamp
    ``end[i] = g`` — the publish.  A reader that sees ``end[i] == g``
    is guaranteed a fully-written slot.
  * consumer poll: ready iff ``end[i] == g``.  Polling advances the
    read cursor but defers the ack, so a consumer may gather views of
    several ready slots (one merged ``np.concatenate`` out of shm)
    before releasing any of them.
  * consumer ack: ``ack[i] = g`` — the slot is reusable.
  * torn slot: ``begin[i] == g`` but ``end[i] != g``.  Mid-write for a
    live producer; a dead producer's tombstone (the crash-semantics
    signal serving/ipc.py's owner consumes).

Memory-model note: correctness of the handshake rides CPython + the
platform's store ordering.  Each counter is ONE aligned 8-byte numpy
store (a single mov), CPython executes the fill and the ``end`` stamp
as distinct bytecodes, and x86-TSO keeps stores in program order, so a
consumer that observes ``end[i] == g`` observes the slot's columns and
count.  On weakly-ordered ISAs the guarantee degrades gracefully: a
stale read can only mis-report "not ready yet" (a retry), never surface
a half-written slot as ready, because nothing is ever read without the
``end`` generation matching first and a spuriously EARLY ``end`` would
require the store to be reordered before its own claim — which the
per-slot ``ack`` gate makes harmless (the producer never reclaims an
unacked slot).

Backpressure contract (the house rule: never drop, never silently
block past a deadline): ``try_claim`` is non-blocking; ``claim_wait``
spins with a micro-sleep and raises ``ShmBackpressure`` LOUDLY when the
deadline passes — the caller turns that into a wire-visible refusal
(S_RETRY_AFTER / R_QUEUE_FULL) or a teardown, never a silent stall.

Python 3.10 quirk: attaching ``SharedMemory`` by name registers the
segment with this process's ``resource_tracker``, which would unlink a
still-live segment (and warn) when the ATTACHING process exits first.
Only the creator owns the segment's lifetime here, so attachers
unregister themselves (the ``track=False`` of 3.13+, done by hand).
"""

from __future__ import annotations

import dataclasses
import secrets
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, List, Optional, Tuple

import numpy as np

_ALIGN = 64  # cache-line align every array: no false sharing between
#              control words and columns

#: (name, numpy dtype string, width) — width 0 declares a 1-D
#: ``(slot_rows,)`` column, width w > 0 a 2-D ``(slot_rows, w)`` matrix.
FieldSpec = Tuple[str, str, int]


class ShmBackpressure(RuntimeError):
    """A ring stayed full past the caller's deadline.  Loud by design:
    the producer must surface this as a wire refusal or a teardown —
    never swallow it (the never-drop / never-silently-block rule)."""


@dataclasses.dataclass(frozen=True)
class RingSpec:
    """The picklable identity of a ring: everything a child process
    needs to ``SpscColumnRing.attach`` the same mapping by name."""

    name: str                        # SharedMemory segment name
    nslots: int
    slot_rows: int
    fields: Tuple[FieldSpec, ...]


def _aligned(off: int) -> int:
    return (off + _ALIGN - 1) & ~(_ALIGN - 1)


def _layout(spec: RingSpec):
    """Byte offsets of every array in the block: (total_size,
    {ctrl_name: off}, {field_name: (off, shape, dtype)})."""
    off = 0
    ctrl: Dict[str, int] = {}
    for cname in ("begin", "end", "count", "ack"):
        off = _aligned(off)
        ctrl[cname] = off
        off += 8 * spec.nslots
    cols: Dict[str, Tuple[int, tuple, np.dtype]] = {}
    for fname, dts, width in spec.fields:
        dt = np.dtype(dts)
        shape = ((spec.nslots, spec.slot_rows) if width == 0
                 else (spec.nslots, spec.slot_rows, width))
        nbytes = dt.itemsize * int(np.prod(shape[1:])) * spec.nslots
        off = _aligned(off)
        cols[fname] = (off, shape, dt)
        off += nbytes
    return _aligned(off), ctrl, cols


class SlotView:
    """A claimed/ready slot: ``cols[name]`` are LIVE numpy views into
    shared memory for slot ``idx`` (valid until the producer's commit
    or the consumer's ack of this slot), ``count`` the valid row count
    (consumer side; the producer declares it at commit)."""

    __slots__ = ("idx", "gen", "count", "cols")

    def __init__(self, idx: int, gen: int, count: int,
                 cols: Dict[str, np.ndarray]):
        self.idx = idx
        self.gen = gen
        self.count = count
        self.cols = cols


class SpscColumnRing:
    """One single-producer / single-consumer columnar ring (the module
    docstring's protocol).  Exactly one process may produce and exactly
    one may consume; within a process, callers serialize their own
    access (serving/ipc.py's worker holds its ``_ring_lock`` across the
    claim/fill/commit of the request ring — the reader threads are
    collectively ONE producer)."""

    def __init__(self, spec: RingSpec, shm: shared_memory.SharedMemory,
                 is_creator: bool):
        self.spec = spec
        self._shm = shm
        self._is_creator = is_creator
        self._closed = False
        total, ctrl, cols = _layout(spec)
        buf = shm.buf
        self._begin = np.frombuffer(buf, np.uint64, spec.nslots,
                                    ctrl["begin"])
        self._end = np.frombuffer(buf, np.uint64, spec.nslots,
                                  ctrl["end"])
        self._count = np.frombuffer(buf, np.int64, spec.nslots,
                                    ctrl["count"])
        self._ack = np.frombuffer(buf, np.uint64, spec.nslots,
                                  ctrl["ack"])
        self._cols: Dict[str, np.ndarray] = {}
        for fname, (off, shape, dt) in cols.items():
            n = int(np.prod(shape))
            self._cols[fname] = np.frombuffer(
                buf, dt, n, off).reshape(shape)
        # local (per-process) cursors: monotone positions, never shared
        self.produced = 0          # committed slots
        self.consumed = 0          # acked slots
        self._write_pos = 0        # next slot to claim
        self._read_pos = 0         # next slot to poll
        self._claimed = False      # claim outstanding (producer side)
        self._pending_ack: List[Tuple[int, int]] = []  # (idx, gen) FIFO

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def create(cls, nslots: int, slot_rows: int,
               fields: Tuple[FieldSpec, ...],
               name_hint: str = "hermes") -> "SpscColumnRing":
        if nslots < 2 or slot_rows < 1:
            raise ValueError("ring needs nslots >= 2 and slot_rows >= 1")
        spec = RingSpec(name="", nslots=int(nslots),
                        slot_rows=int(slot_rows),
                        fields=tuple((str(n), str(d), int(w))
                                     for n, d, w in fields))
        total, _, _ = _layout(spec)
        shm = shared_memory.SharedMemory(
            create=True, size=total,
            name=f"{name_hint}_{secrets.token_hex(6)}")
        spec = dataclasses.replace(spec, name=shm.name)
        shm.buf[:total] = b"\x00" * total  # generation 0 = fully consumed
        return cls(spec, shm, is_creator=True)

    @classmethod
    def attach(cls, spec: RingSpec) -> "SpscColumnRing":
        # Python 3.10 has no ``track=False``: plain attach would register
        # the segment with resource_tracker a second time, and since
        # spawn children SHARE the parent's tracker process, a later
        # unregister-on-close from either side corrupts the other's
        # bookkeeping (KeyError noise, or worse: the tracker unlinking a
        # live segment).  Only the creator owns lifetime here, so the
        # attach suppresses registration outright.  Attach is only
        # called from single-threaded startup paths (child boot, test
        # setup), so the brief monkeypatch cannot race another register.
        orig = resource_tracker.register
        try:
            resource_tracker.register = lambda *a, **k: None
            shm = shared_memory.SharedMemory(name=spec.name)
        finally:
            resource_tracker.register = orig
        return cls(spec, shm, is_creator=False)

    def close(self) -> None:
        """Unmap; the creator also unlinks the segment."""
        if self._closed:
            return
        self._closed = True
        # drop every view BEFORE closing the mapping (exported pointers
        # keep the mmap alive and SharedMemory.close raises); callers
        # may still hold SlotViews, so tolerate a pinned mapping — the
        # OS reclaims it at process exit and the unlink below still
        # removes the name
        self._begin = self._end = self._count = self._ack = None
        self._cols = {}
        try:
            self._shm.close()
        except BufferError:
            # a live SlotView pins the mmap; disarm SharedMemory.__del__
            # so interpreter exit doesn't re-raise the same error as
            # "Exception ignored" noise — the OS unmaps at process exit
            self._shm._mmap = None  # noqa: SLF001
        if self._is_creator:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass

    # -- producer side -------------------------------------------------------

    def _pos(self, pos: int) -> Tuple[int, int]:
        return pos % self.spec.nslots, pos // self.spec.nslots + 1

    def try_claim(self) -> Optional[SlotView]:
        """Claim the next slot (stamps ``begin``), or None while the
        consumer still owns it (ring full)."""
        if self._claimed:
            raise RuntimeError("claim already outstanding: commit first")
        i, g = self._pos(self._write_pos)
        if int(self._ack[i]) != g - 1:
            return None
        self._begin[i] = g
        self._claimed = True
        return SlotView(i, g, 0,
                        {n: a[i] for n, a in self._cols.items()})

    def claim_wait(self, timeout_s: float,
                   poll_s: float = 50e-6) -> SlotView:
        """``try_claim`` with a spin-wait bound: raises
        ``ShmBackpressure`` loudly once ``timeout_s`` passes."""
        deadline = time.monotonic() + timeout_s
        while True:
            slot = self.try_claim()
            if slot is not None:
                return slot
            if time.monotonic() >= deadline:
                raise ShmBackpressure(
                    f"ring {self.spec.name} full for {timeout_s:.3f}s "
                    f"({self.spec.nslots} slots x {self.spec.slot_rows} "
                    "rows): consumer stalled or dead — refusing loudly "
                    "instead of blocking past the deadline")
            time.sleep(poll_s)

    def commit(self, count: int) -> None:
        """Publish the claimed slot: ``count`` valid rows, then the
        ``end`` stamp (the ordering the protocol rides)."""
        if not self._claimed:
            raise RuntimeError("commit without a claim")
        if not (0 <= count <= self.spec.slot_rows):
            raise ValueError(f"count {count} outside [0, "
                             f"{self.spec.slot_rows}]")
        i, g = self._pos(self._write_pos)
        self._count[i] = count
        self._end[i] = g      # publish AFTER count + columns
        self._claimed = False
        self._write_pos += 1
        self.produced += 1

    def free_slots(self) -> int:
        """Producer-side occupancy gauge: claimable slots right now."""
        free = 0
        for d in range(self.spec.nslots):
            i, g = self._pos(self._write_pos + d)
            if int(self._ack[i]) != g - 1:
                break
            free += 1
        return free

    # -- consumer side -------------------------------------------------------

    def poll(self) -> Optional[SlotView]:
        """Next ready slot (advances the read cursor, defers the ack),
        or None when the cursor slot is unpublished.  Views stay valid
        until this slot's ``ack``."""
        i, g = self._pos(self._read_pos)
        if int(self._end[i]) != g:
            return None
        self._read_pos += 1
        self._pending_ack.append((i, g))
        return SlotView(i, g, int(self._count[i]),
                        {n: a[i] for n, a in self._cols.items()})

    def ack(self, n: Optional[int] = None) -> int:
        """Release the oldest ``n`` polled slots back to the producer
        (default: all).  Returns the number released."""
        k = len(self._pending_ack) if n is None \
            else min(n, len(self._pending_ack))
        for _ in range(k):
            i, g = self._pending_ack.pop(0)
            self._ack[i] = g
            self.consumed += 1
        return k

    def ready(self) -> int:
        """Consumer-side depth gauge: published slots beyond the read
        cursor (not counting polled-but-unacked ones)."""
        depth = 0
        for d in range(self.spec.nslots):
            i, g = self._pos(self._read_pos + d)
            if int(self._end[i]) != g:
                break
            depth += 1
        return depth

    def torn(self) -> bool:
        """True when the cursor slot was claimed but never published —
        mid-write for a live producer, a tombstone for a dead one (the
        caller brings the liveness verdict)."""
        i, g = self._pos(self._read_pos)
        return int(self._begin[i]) == g and int(self._end[i]) != g

    def pending_ack(self) -> int:
        return len(self._pending_ack)
