"""HBM value heap (round-17): MICA-style variable-length values behind
one packed ref word per key.  See heap/core.py for the design notes."""

from hermes_tpu.heap.core import (  # noqa: F401
    GRANULE,
    HeapFull,
    MIN_BATCH,
    ValueHeap,
    analyze_gather,
    append_census,
    build_append,
    build_extent_gather,
    cap_bytes,
    gather_census,
    pack_ref,
    ref_gran,
    ref_len,
)
