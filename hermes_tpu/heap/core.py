"""Round-17: the HBM value heap (ROADMAP item 3 — MICA-style values).

PAPER.md frames Hermes as membership-based invalidation replication over
a MICA-style KVS, whose values are variable-length byte payloads in a
log-structured store.  Through round 16 this rebuild stored values as
fixed config-width words, so every "memcached-shaped" claim (tens of
bytes-KB payloads, GB/s served) was untestable.  This module is that
missing storage layer:

  * ``ValueHeap`` — a per-store append log: extents of up to
    ``config.max_value_bytes`` bytes land at a granule-aligned bump
    cursor; each extent is named by ONE packed int32 ref word
    ``(granule << 12) | byte_length`` (the declared ``layouts.HEAP_REF``
    word — ref 0 is the null sentinel, granule 0 reserved).  The host
    mirror is authoritative for writes (the client layer appends BEFORE
    the INV issues — the out-of-band bulk value transfer of an
    RDMA/MICA deployment); the device log is the SAME bytes, synced
    with one dense ``dynamic_update_slice`` of the dirty tail, and
    serves the batched device-resident read path.

  * ``build_extent_gather`` — ONE dynamic gather answers a whole batch
    of refs from the device log: unpack (shift/mask the declared
    fields), clamp every byte index into the log (untrusted refs can
    never gather out of bounds — the round-3 wire-clamp rule), mask the
    tail past each extent's length.  Budgeted under OP_BUDGET.json's
    ``heap_path`` section (sparse_total 1); the append program is dense
    (``heap_append``: sparse_total 0).  The ROUND census does not move
    at all: the protocol carries only the ref word in an existing
    payload slot.

  * ``compact`` — GC: dead extents (overwritten values, lost writes)
    are reclaimed by copying the LIVE extents (every ref reachable from
    table rows, staged streams, queued client ops) to the front of a
    fresh log and remapping the ref words in place.  The client layer
    (kvs.KVS.heap_gc) drives it at version-rebase boundaries and on
    allocation pressure, under the same quiesce the rebase uses, with a
    ``heap_gc`` span and ``heap_util`` gauge on the obs timeline.

Consistency: an extent is immutable once appended (a new value = a new
extent + a new ref word through the normal INV/ACK/VAL round), so the
ref word inherits the row's linearizability — readers observe (uid, ref)
atomically from the committed row, and the bytes behind a ref never
change until a compaction, which only runs with the store quiesced and
every completion resolved.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import layouts

GRANULE = layouts.HEAP_GRANULE
_LEN = layouts.HEAP_REF.field("len")
_GRAN = layouts.HEAP_REF.field("gran")


class HeapFull(RuntimeError):
    """The append log is out of granules even after compaction: the LIVE
    value bytes exceed ``config.heap_bytes``.  Loud by design — a full
    store must refuse writes, never silently drop payload bytes."""


def pack_ref(gran: int, length: int) -> int:
    """Pack an extent ref word from the declared fields."""
    return (int(gran) << _GRAN.shift) | int(length)


def ref_len(ref) -> int:
    """Extent byte length of a packed ref (field ``len``)."""
    return ref & _LEN.mask


def ref_gran(ref) -> int:
    """Granule index of a packed ref (field ``gran``)."""
    return (ref >> _GRAN.shift) & (_GRAN.cap - 1)


def cap_bytes(cfg: HermesConfig) -> int:
    """Word-aligned per-extent gather width (the compiled row extent)."""
    return 4 * ((cfg.max_value_bytes + 3) // 4)


# --------------------------------------------------------------------------
# Device programs (compiled per shape, cached — the readpath discipline)
# --------------------------------------------------------------------------

#: Smallest compiled ref-batch bucket (matches readpath.MIN_BATCH's role).
MIN_BATCH = 256


def _batch_bucket(n: int) -> int:
    b = MIN_BATCH
    while b < n:
        b <<= 1
    return b


@functools.lru_cache(maxsize=None)
def build_extent_gather(heap_bytes: int, cap: int, batch: int):
    """Compile the batched extent gather: ``fn(log, refs) -> (rows, lens)``
    answering ``batch`` packed refs with ONE dynamic gather of ``cap``
    bytes each from the ``(heap_bytes,)`` int8 log.  Refs are UNTRUSTED:
    the granule and length unpack through the declared field masks and
    every byte index clamps into the log (promised-in-bounds — the
    analyzer's scatter/gather pass proves it from the seeded ref bound,
    scripts/check_heap.py), and bytes past each extent's length are
    masked to zero so an over-wide gather can never leak a neighbor's
    bytes."""
    import jax
    import jax.numpy as jnp

    def gather(log, refs):
        refs = refs.astype(jnp.int32)
        lens = jnp.clip(refs & jnp.int32(_LEN.mask), 0, cap)
        gran = (refs >> _GRAN.shift) & jnp.int32(_GRAN.cap - 1)
        start = gran * jnp.int32(GRANULE)
        off = jnp.arange(cap, dtype=jnp.int32)
        idx = jnp.minimum(start[:, None] + off[None, :],
                          jnp.int32(heap_bytes - 1))
        rows = log[idx]  # the ONE sparse op (heap_path budget)
        rows = jnp.where(off[None, :] < lens[:, None], rows, jnp.int8(0))
        return rows, lens

    return jax.jit(gather)


@functools.lru_cache(maxsize=None)
def build_append(heap_bytes: int, chunk: int):
    """Compile the log append: ``fn(log, chunk_bytes, start) -> log`` —
    one dense ``dynamic_update_slice`` of a ``chunk``-byte tail (the
    ``heap_append`` budget: ZERO sparse ops).  The log buffer is donated:
    appends bump a cursor, they never copy the heap."""
    import jax
    import jax.numpy as jnp

    def append(log, data, start):
        return jax.lax.dynamic_update_slice(log, data, (start,))

    return jax.jit(append, donate_argnums=(0,))


def gather_census(cfg: HermesConfig, batch: int = 1024) -> dict:
    """StableHLO op census of ONE extent-gather dispatch (the
    measurement half of OP_BUDGET.json's ``heap_path`` section)."""
    import jax
    import jax.numpy as jnp

    from hermes_tpu.obs.profile import census_text

    fn = build_extent_gather(cfg.heap_bytes, cap_bytes(cfg), batch)
    txt = fn.lower(jax.ShapeDtypeStruct((cfg.heap_bytes,), jnp.int8),
                   jax.ShapeDtypeStruct((batch,), jnp.int32)).as_text()
    return census_text(txt)


def append_census(cfg: HermesConfig, chunk: int = 4096) -> dict:
    """Census of one log-append dispatch (``heap_append``: dense only)."""
    import jax
    import jax.numpy as jnp

    from hermes_tpu.obs.profile import census_text

    fn = build_append(cfg.heap_bytes, chunk)
    txt = fn.lower(jax.ShapeDtypeStruct((cfg.heap_bytes,), jnp.int8),
                   jax.ShapeDtypeStruct((chunk,), jnp.int8),
                   jnp.int32(0)).as_text()
    return census_text(txt)


def analyze_gather(cfg: HermesConfig, batch: int = 1024) -> list:
    """Run the static invariant analyzer over the extent-gather program
    with the config-seeded ref bound (analysis/seeds.seed_heap_gather):
    the bitpack pass proves the field unpacks respect the declared
    layout and the gather indices are promised-in-bounds.  Returns the
    findings list (empty = clean)."""
    import jax
    import jax.numpy as jnp

    from hermes_tpu.analysis import seeds as seeds_lib
    from hermes_tpu.analysis.interp import Ctx, eval_jaxpr
    from hermes_tpu.analysis.passes import default_passes

    fn = build_extent_gather(cfg.heap_bytes, cap_bytes(cfg), batch)
    closed = jax.make_jaxpr(fn)(
        jax.ShapeDtypeStruct((cfg.heap_bytes,), jnp.int8),
        jax.ShapeDtypeStruct((batch,), jnp.int32))
    passes = default_passes()
    ctx = Ctx(cfg=cfg, mesh_axes={}, passes=passes, donated=frozenset())
    eval_jaxpr(closed.jaxpr, list(seeds_lib.seed_heap_gather(cfg, batch)),
               ctx, consts=list(closed.consts))
    findings = []
    for p in passes:
        p.finalize(ctx)
        for f in p.results():
            f.engine = "heap/gather"
            findings.append(f)
    return findings


# --------------------------------------------------------------------------
# The heap
# --------------------------------------------------------------------------


class ValueHeap:
    """One store's value log: host mirror (authoritative, append-ordered)
    + lazily-synced device log.  NOT thread-safe — it lives under the
    KVS's single-threaded step loop like every other host structure."""

    def __init__(self, cfg: HermesConfig):
        if not cfg.use_heap:
            raise ValueError("ValueHeap needs cfg.max_value_bytes > 0")
        self.cfg = cfg
        self.capacity = cfg.heap_bytes
        self.granules = cfg.heap_granules
        self.cap = cap_bytes(cfg)
        self._mirror = np.zeros(cfg.heap_bytes, np.uint8)
        self._cursor = 1       # granules; granule 0 = the null-ref sentinel
        self._synced = 1       # granules already uploaded to the device log
        self._dev = None       # lazy device-resident log
        self.appends = 0
        self.append_bytes = 0
        self.gc_runs = 0
        self.gc_reclaimed_bytes = 0
        self.live_bytes = 0    # as of the last compaction (gauge input)
        self.gather_dispatches = 0

    # -- allocation ----------------------------------------------------------

    def used_bytes(self) -> int:
        return self._cursor * GRANULE

    def free_bytes(self) -> int:
        return (self.granules - self._cursor) * GRANULE

    def _granules_for(self, nbytes: int) -> int:
        return max(1, (nbytes + GRANULE - 1) // GRANULE)

    def append(self, data) -> int:
        """Land one extent at the bump cursor; returns its packed ref
        word.  Raises ``HeapFull`` when the log is out of granules (the
        caller compacts and retries — kvs.KVS drives that) and
        ``ValueError`` on an over-long payload (a config contract, not a
        capacity condition)."""
        raw = bytes(data)
        if len(raw) > self.cfg.max_value_bytes:
            raise ValueError(
                f"value is {len(raw)} bytes > max_value_bytes="
                f"{self.cfg.max_value_bytes}")
        need = self._granules_for(len(raw))
        if self._cursor + need > self.granules:
            raise HeapFull(
                f"value heap out of space: {len(raw)}-byte extent needs "
                f"{need} granule(s), {self.granules - self._cursor} free "
                f"of {self.granules} (heap_bytes={self.capacity})")
        ref = pack_ref(self._cursor, len(raw))
        start = self._cursor * GRANULE
        self._mirror[start:start + len(raw)] = np.frombuffer(raw, np.uint8)
        self._cursor += need
        self.appends += 1
        self.append_bytes += len(raw)
        return ref

    # -- reads ---------------------------------------------------------------

    def _check_ref(self, ref: int) -> Tuple[int, int]:
        gran, ln = ref_gran(ref), ref_len(ref)
        if not (1 <= gran < self._cursor) or gran * GRANULE + ln > \
                self._cursor * GRANULE:
            raise ValueError(
                f"dangling heap ref 0x{ref:08x} (gran={gran}, len={ln}, "
                f"cursor={self._cursor}): the extent is not inside the "
                "allocated log — row corruption or a missed GC remap")
        return gran, ln

    def read(self, ref: int) -> bytes:
        """The extent bytes behind one packed ref (host mirror)."""
        gran, ln = self._check_ref(int(ref))
        start = gran * GRANULE
        return self._mirror[start:start + ln].tobytes()

    def read_many(self, refs) -> List[Optional[bytes]]:
        """Mirror reads for a ref vector; ``None`` for null refs (the
        never-written row)."""
        return [None if int(r) == 0 else self.read(int(r)) for r in refs]

    # -- the device log ------------------------------------------------------

    def device_log(self):
        """The HBM-resident log, dirty tail synced with ONE dense
        ``dynamic_update_slice`` (no per-extent uploads: appends since
        the last sync are contiguous by construction)."""
        import jax.numpy as jnp

        if self._dev is None:
            self._dev = jnp.asarray(self._mirror.view(np.int8))
            self._synced = self._cursor
            return self._dev
        if self._synced < self._cursor:
            lo, hi = self._synced * GRANULE, self._cursor * GRANULE
            chunk = min(_batch_bucket(hi - lo), self.capacity)
            start = max(0, min(lo, self.capacity - chunk))
            fn = build_append(self.capacity, chunk)
            self._dev = fn(
                self._dev,
                jnp.asarray(self._mirror[start:start + chunk].view(np.int8)),
                jnp.int32(start))
            self._synced = self._cursor
        return self._dev

    def device_gather(self, refs) -> Tuple[np.ndarray, np.ndarray]:
        """Batched extent fetch through the DEVICE log (the GB/s path the
        bench measures and the gate cross-checks against the mirror):
        returns ``(rows (n, cap) uint8 zero-masked past each length,
        lens (n,))``."""
        import jax

        refs = np.asarray(refs, np.int32)
        n = refs.shape[0]
        b = _batch_bucket(n)
        padded = np.zeros(b, np.int32)
        padded[:n] = refs
        fn = build_extent_gather(self.capacity, self.cap, b)
        rows, lens = jax.device_get(fn(self.device_log(), padded))
        self.gather_dispatches += 1
        return (np.asarray(rows)[:n].view(np.uint8),
                np.asarray(lens)[:n])

    # -- compaction (GC) -----------------------------------------------------

    def compact(self, roots) -> Tuple[np.ndarray, np.ndarray]:
        """Copy the live extents (the unique non-null refs of ``roots``)
        to the front of a fresh log in allocation order and reset the
        bump cursor behind them.  Returns ``(old_refs, new_refs)`` sorted
        by ``old_refs`` — feed any ref array through ``remap`` to follow
        the move.  The device log is invalidated (re-synced lazily).
        The caller owns quiescence: every live ref must be IN ``roots``
        (kvs.KVS.heap_gc collects table rows + staged streams + queued
        client ops under the rebase quiesce)."""
        roots = np.asarray(roots, np.int64).ravel()
        old = np.unique(roots[roots != 0]).astype(np.int64)
        grans = (old >> _GRAN.shift) & (_GRAN.cap - 1)
        lens = old & _LEN.mask
        order = np.argsort(grans, kind="stable")
        new_mirror = np.zeros(self.capacity, np.uint8)
        new_refs = np.zeros(old.shape[0], np.int64)
        cursor = 1
        for j in order:
            g, ln = int(grans[j]), int(lens[j])
            if not (1 <= g < self._cursor):
                raise ValueError(
                    f"GC root 0x{int(old[j]):08x} is dangling (gran={g}, "
                    f"cursor={self._cursor})")
            need = self._granules_for(ln)
            src = g * GRANULE
            dst = cursor * GRANULE
            new_mirror[dst:dst + ln] = self._mirror[src:src + ln]
            new_refs[j] = pack_ref(cursor, ln)
            cursor += need
        reclaimed = (self._cursor - cursor) * GRANULE
        self._mirror = new_mirror
        self._cursor = cursor
        self._dev = None
        self._synced = 1
        self.gc_runs += 1
        self.gc_reclaimed_bytes += max(0, reclaimed)
        self.live_bytes = int(lens.sum())
        return old, new_refs

    @staticmethod
    def remap(refs, old: np.ndarray, new: np.ndarray) -> np.ndarray:
        """Apply a compaction's (old, new) ref mapping to an int array;
        null refs stay null, unknown refs raise (they were not rooted —
        a GC soundness bug, never silently preserved)."""
        refs = np.asarray(refs)
        out = refs.astype(np.int64).copy()
        nz = out != 0
        if nz.any():
            idx = np.searchsorted(old, out[nz])
            bad = (idx >= old.shape[0])
            safe = np.where(bad, 0, idx)
            bad |= old[safe] != out[nz]
            if bad.any():
                raise ValueError(
                    f"{int(bad.sum())} ref(s) missing from the GC root set "
                    "(first: 0x%08x)" % int(out[nz][bad][0]))
            out[nz] = new[idx]
        return out.astype(refs.dtype, copy=False)

    # -- accounting ----------------------------------------------------------

    def stats(self) -> dict:
        used = self.used_bytes()
        return dict(
            capacity_bytes=self.capacity,
            used_bytes=used,
            free_bytes=self.free_bytes(),
            appends=self.appends,
            append_bytes=self.append_bytes,
            gc_runs=self.gc_runs,
            gc_reclaimed_bytes=self.gc_reclaimed_bytes,
            live_bytes=self.live_bytes,
            # post-GC utilization: live bytes over the allocated prefix
            # (1.0 = perfectly compacted modulo granule rounding); the
            # heap_util GAUGE on the obs timeline is live/capacity —
            # how full the log is, the operator's headroom number
            util=(self.live_bytes / used) if self.live_bytes else None,
        )
