"""The host concurrency model, declared as data (round-20).

The device side of the round gets its invariants proved by the jaxpr
analyzer against the declarative field tables in ``core/layouts.py``.
This module is the HOST half's equivalent table: per class, which
attributes are shared mutable state, which lock attribute guards each of
them, and which are deliberately lock-free with a written justification
(the ``audited(tag)`` escape hatch — same visibility contract as
``layouts.audited``: a suppression is an info finding, never silence).

Consumers:

  * ``hermes_tpu/analysis/hostlint.py`` — the static AST pass proves the
    package against this registry (guarded access outside ``with
    <lock>:``, blocking calls under a lock, nested-``with`` lock-order
    cycles, undeclared locks, unowned daemon threads).
  * ``hermes_tpu/analysis/lockgraph.py`` — the dynamic sanitizer; its
    ``ObsLock`` instances are minted through :func:`make_lock` below.
  * ``scripts/check_hostlint.py`` — the eleventh serial CI gate.

Design rules the table encodes (ARCHITECTURE.md "Round-20"):

  * A lock guards ATTRIBUTES, not code paths: every read or write of a
    guarded attribute outside ``__init__`` must happen inside ``with
    self.<lock>:`` of the declaring class.
  * ``audited(tag, *attrs)`` declares deliberately lock-free fields; the
    wildcard ``"*"`` covers every otherwise-undeclared mutable attribute
    of the class (single-threaded-by-contract classes like the KVS).
  * ``BlockingAudit`` declares the one sanctioned blocking-call-under-
    lock site class (``FramedSocket.send``'s sendall — the lock exists
    to serialize whole-frame writes, and SO_SNDTIMEO bounds the stall).
"""

from __future__ import annotations

import os
import threading
from typing import NamedTuple, Optional, Tuple

#: env switch: "1" swaps every lock minted via make_lock for the
#: instrumented analysis/lockgraph.ObsLock, so serving/chaos soaks
#: double as dynamic lock-order sanitizer runs
LOCKLINT_ENV = "HERMES_LOCKLINT"


def locklint_enabled() -> bool:
    return os.environ.get(LOCKLINT_ENV, "") not in ("", "0")


def make_lock(name: str):
    """The serving tier's lock factory: a plain ``threading.Lock`` in
    production, the instrumented ``lockgraph.ObsLock`` under
    ``HERMES_LOCKLINT=1``.  ``name`` must be ``"Class.attr"`` — the
    identity the dynamic held-before graph and the hold-time series key
    on (instances share the name; per-instance graphs would never see a
    cross-object ordering).  The lockgraph import is deferred so the
    production path stays free of the analysis package."""
    if locklint_enabled():
        from hermes_tpu.analysis.lockgraph import ObsLock

        return ObsLock(name)
    return threading.Lock()


class Guard(NamedTuple):
    """One lock attribute and the attributes it guards."""

    lock: str            # lock attribute name on the class, e.g. "_map_lock"
    attrs: Tuple[str, ...]


class Audited(NamedTuple):
    """Deliberately lock-free attributes + the justification tag."""

    attrs: Tuple[str, ...]   # attribute names, or ("*",) for the wildcard
    tag: str


class BlockingAudit(NamedTuple):
    """One sanctioned blocking call under one lock (downgraded to info)."""

    lock: str            # lock attribute whose critical section may block
    call: str            # blocking callee name, e.g. "sendall"
    tag: str


class ClassGuards(NamedTuple):
    """The concurrency declaration of one class."""

    cls: str                       # bare class name
    module: str                    # dotted module the class lives in
    locks: Tuple[str, ...] = ()    # every lock attribute the class owns
    guards: Tuple[Guard, ...] = ()
    audited: Tuple[Audited, ...] = ()
    blocking: Tuple[BlockingAudit, ...] = ()
    thread_owner: Optional[str] = None  # attr close() joins threads from
    notes: str = ""


def audited(tag: str, *attrs: str) -> Audited:
    """Declarative twin of ``layouts.audited``: same tag contract (non-
    empty, no square brackets — the tag rides finding records)."""
    if not tag or any(c in tag for c in "[]"):
        raise ValueError("audit tag must be a non-empty string without "
                         "square brackets")
    if not attrs:
        raise ValueError("audited() needs at least one attribute name")
    return Audited(attrs=tuple(attrs), tag=tag)


#: the whole-package table.  Order: serving tier, transport, obs, store,
#: then the sanitizer's own machinery (dogfooded like everything else).
REGISTRY: Tuple[ClassGuards, ...] = (
    ClassGuards(
        cls="TcpRpcServer", module="hermes_tpu.serving.rpc",
        locks=("_lock", "_map_lock"),
        guards=(Guard("_map_lock", ("_next_iid", "_conn_of", "_conns",
                                    "_threads", "undecodable")),),
        audited=(
            audited("single-writer-publish: set once by the dying pump "
                    "thread; every other thread only polls it", "pump_error"),
            audited("threading.Event is internally synchronized", "_stop"),
        ),
        thread_owner="_threads",
        notes="_lock guards the shared Frontend (submit/pump critical "
              "section), which keeps no lock of its own — see the "
              "Frontend entry's wildcard audit.",
    ),
    ClassGuards(
        cls="ColumnarTcpServer", module="hermes_tpu.serving.rpc",
        locks=("_lock", "_map_lock"),
        guards=(Guard("_map_lock", ("_next_cid", "_sock_of", "_conns",
                                    "_threads", "undecodable")),),
        audited=(
            audited("single-writer-publish: set once by the dying pump "
                    "thread; every other thread only polls it", "pump_error"),
            audited("threading.Event is internally synchronized", "_stop"),
        ),
        thread_owner="_threads",
        notes="same lock split as TcpRpcServer: _lock is the frontend "
              "critical section, _map_lock the connection bookkeeping.",
    ),
    ClassGuards(
        cls="LoopbackServer", module="hermes_tpu.serving.rpc",
        audited=(audited("single-threaded in-process server: no socket, "
                         "no thread, driven by one soak loop", "*"),),
    ),
    ClassGuards(
        cls="ColumnarLoopback", module="hermes_tpu.serving.rpc",
        audited=(audited("single-threaded in-process server: no socket, "
                         "no thread, driven by one soak loop", "*"),),
    ),
    ClassGuards(
        cls="RpcClient", module="hermes_tpu.serving.rpc",
        audited=(audited("single-threaded blocking client by contract "
                         "(one owner thread per client instance)", "*"),),
    ),
    ClassGuards(
        cls="ColumnarClient", module="hermes_tpu.serving.rpc",
        audited=(audited("single-threaded blocking client by contract "
                         "(one owner thread per client instance)", "*"),),
    ),
    ClassGuards(
        cls="Frontend", module="hermes_tpu.serving.server",
        audited=(audited("server-serialized: every access happens under "
                         "the owning RPC server's _lock (TcpRpcServer."
                         "_reader_body/_pump_loop) or inside a single-"
                         "threaded loopback driver", "*"),),
    ),
    ClassGuards(
        cls="ColumnarFrontend", module="hermes_tpu.serving.server",
        audited=(audited("server-serialized: every access happens under "
                         "the owning RPC server's _lock or inside a "
                         "single-threaded loopback driver", "*"),),
    ),
    ClassGuards(
        cls="CompletionRing", module="hermes_tpu.serving.server",
        audited=(audited("frontend-serialized: owned by ColumnarFrontend "
                         "and touched only under its owner's "
                         "serialization", "*"),),
    ),
    ClassGuards(
        cls="RespMetaRing", module="hermes_tpu.serving.server",
        audited=(audited("frontend-serialized: owned by a Frontend/"
                         "ColumnarFrontend and touched only under its "
                         "owner's serialization", "*"),),
    ),
    ClassGuards(
        cls="ShmWorker", module="hermes_tpu.serving.ipc",
        locks=("_ring_lock", "_map_lock"),
        guards=(
            Guard("_ring_lock", ("rows_in",)),
            Guard("_map_lock", ("_next_cid", "_sock_of", "_conns",
                                "_threads", "undecodable",
                                "backpressured")),
        ),
        audited=(
            audited("single-thread: only the response-drain thread "
                    "touches the rsp ring consumer cursor and this "
                    "counter", "rows_out"),
            audited("threading.Event is internally synchronized", "_stop"),
            audited("spsc-by-contract: the request ring's cursor-"
                    "mutating producer calls all run under _ring_lock; "
                    "the spec reads outside it are frozen-dataclass "
                    "immutable", "req_ring", "rsp_ring"),
        ),
        thread_owner="_threads",
        notes="_ring_lock makes the reader threads collectively ONE "
              "producer on the request ring (the SPSC contract); "
              "_map_lock is the ColumnarTcpServer-style connection "
              "bookkeeping split.",
    ),
    ClassGuards(
        cls="StoreOwner", module="hermes_tpu.serving.ipc",
        audited=(audited("single-threaded by contract: the owner pump "
                         "thread (OneStoreServer) or the soak driver is "
                         "the only entrant; ring consumer/producer "
                         "cursors and counters never see a second "
                         "thread", "*"),),
    ),
    ClassGuards(
        cls="OneStoreServer", module="hermes_tpu.serving.ipc",
        audited=(
            audited("single-writer-publish: set once by the dying pump "
                    "thread; every other thread only polls it",
                    "pump_error"),
            audited("threading.Event is internally synchronized", "_stop"),
            audited("sequential handoff: the pump thread is the sole "
                    "mutator while running; close() joins it before "
                    "touching owner/ring/process state, and the boot "
                    "path runs before the thread starts", "*"),
        ),
        thread_owner="_pump_t",
        notes="worker shutdown rides SIGTERM, not a shared mp.Event: "
              "mp.Event.set() handshakes with sleepers and deadlocks "
              "against a SIGKILLed waiter (the crash path the kill "
              "soak gates).",
    ),
    ClassGuards(
        cls="SpscColumnRing", module="hermes_tpu.transport.shm",
        audited=(audited("spsc-by-contract: exactly one producer and "
                         "one consumer process/thread (callers "
                         "serialize their own side — ShmWorker._ring_"
                         "lock); the cross-process handshake is the "
                         "begin/end/ack generation protocol, not a "
                         "lock", "*"),),
    ),
    ClassGuards(
        cls="FramedSocket", module="hermes_tpu.transport.tcp",
        locks=("_send_lock",),
        audited=(audited("single-reader: recv runs on exactly one thread "
                         "per socket (the server's per-connection reader "
                         "or the blocking client's owner thread)",
                         "corrupt_dropped"),),
        blocking=(BlockingAudit(
            "_send_lock", "sendall",
            "frame-atomicity: the send lock exists precisely to "
            "serialize whole-frame writes from concurrent senders; "
            "SO_SNDTIMEO bounds the stall on the serving path"),),
        notes="_send_lock guards the socket's WRITE STREAM, not an "
              "attribute: two threads sharing one FramedSocket must "
              "never splice frames mid-stream.",
    ),
    ClassGuards(
        cls="MetricsRegistry", module="hermes_tpu.obs.metrics",
        locks=("_lock",),
        guards=(Guard("_lock", ("_metrics",)),),
        notes="the registry map is fed from pump + reader threads; "
              "individual metric objects stay lock-free (GIL-atomic int "
              "adds — a rare lost increment is acceptable for metrics; "
              "exact counts come from the device Meta sums).  _lock is "
              "a PLAIN threading.Lock, never make_lock: the registry is "
              "the sink the lock sanitizer feeds its hold-time series "
              "into, and instrumenting the sink's own lock would "
              "recurse.",
    ),
    ClassGuards(
        cls="FlightRecorder", module="hermes_tpu.obs.flightrec",
        audited=(audited("gil-atomic: bounded deque appends from "
                         "whichever thread writes obs records; dump() "
                         "snapshots via list() copies", "*"),),
    ),
    ClassGuards(
        cls="KVS", module="hermes_tpu.kvs",
        audited=(audited("externally serialized: the KVS step loop "
                         "(queues, inflight maps, batch tables) is "
                         "single-threaded; the serving tier serializes "
                         "every entry point under the owning server's "
                         "_lock", "*"),),
    ),
    ClassGuards(
        cls="ValueHeap", module="hermes_tpu.heap.core",
        audited=(audited("store-serialized: lives under the KVS's "
                         "single-threaded step loop (class docstring: "
                         "NOT thread-safe)", "*"),),
    ),
    ClassGuards(
        cls="GroupCommitWal", module="hermes_tpu.wal.log",
        locks=("_lock",),
        guards=(Guard("_lock", ("_buf", "_next_lsn", "_durable_lsn",
                                "_dirty", "_flush_evt")),),
        audited=(
            audited("threading.Event is internally synchronized",
                    "_stop", "_wake"),
            audited("flusher-thread-private: the open segment file and "
                    "its rotation bookkeeping are touched only by the "
                    "flusher (close() joins it before the final seal)",
                    "_f", "_seg_path", "_seg_bytes", "_seg_max_step",
                    "_sealed_steps", "_seg_seq"),
            audited("single-writer-publish: set once by the dying "
                    "flusher thread; every other thread only polls it",
                    "_error"),
            audited("gil-atomic counters: stats-only, exact durability "
                    "accounting rides _durable_lsn under _lock",
                    "records", "rounds", "remaps", "fsyncs", "wal_bytes",
                    "retired_segments"),
        ),
        thread_owner="_flusher_t",
        notes="the group-commit split: producers only append to _buf "
              "and bump _next_lsn under _lock; the flusher drains the "
              "batch under _lock but encodes/writes/fsyncs with the "
              "lock RELEASED (the whole point — fsync off the hot "
              "path), then re-acquires to publish _durable_lsn and "
              "swap the generation Event.  sync() waits on the Event "
              "outside the lock.",
    ),
    ClassGuards(
        cls="LockGraph", module="hermes_tpu.analysis.lockgraph",
        locks=("_graph_lock",),
        guards=(Guard("_graph_lock", ("_edges", "_stats", "_registry")),),
        audited=(audited("threading.local is per-thread by construction",
                         "_held"),),
        notes="the sanitizer's own bookkeeping, held only for dict "
              "updates; the one static edge out of it (the series feed "
              "into MetricsRegistry._lock) is one-directional, and the "
              "registry lock stays uninstrumented, so the pair cannot "
              "deadlock.",
    ),
    ClassGuards(
        cls="ObsLock", module="hermes_tpu.analysis.lockgraph",
        locks=("_lk",),
        notes="the instrumented drop-in lock itself; all bookkeeping "
              "lives in its LockGraph (per-thread via threading.local, "
              "shared via _graph_lock).",
    ),
)


def validate(registry: Tuple[ClassGuards, ...] = REGISTRY) -> None:
    """Import-time schema check (the layouts.py pattern): one entry per
    (module, class); an attribute is guarded XOR audited; guards name
    declared locks; tags are well-formed."""
    seen = set()
    for e in registry:
        if not e.cls or not e.module:
            raise ValueError("registry entry needs cls and module names")
        key = (e.module, e.cls)
        if key in seen:
            raise ValueError(f"duplicate registry entry for {key}")
        seen.add(key)
        declared: dict = {}
        for g in e.guards:
            if g.lock not in e.locks:
                raise ValueError(
                    f"{e.cls}: guard names lock {g.lock!r} not in the "
                    f"entry's declared locks {e.locks}")
            for a in g.attrs:
                if a in declared:
                    raise ValueError(
                        f"{e.cls}.{a}: declared twice ({declared[a]} and "
                        f"guard {g.lock})")
                declared[a] = f"guard {g.lock}"
        for au in e.audited:
            if not au.tag or any(c in au.tag for c in "[]"):
                raise ValueError(f"{e.cls}: malformed audit tag {au.tag!r}")
            for a in au.attrs:
                if a in declared:
                    raise ValueError(
                        f"{e.cls}.{a}: declared twice ({declared[a]} and "
                        f"audited)")
                declared[a] = "audited"
        for b in e.blocking:
            if b.lock not in e.locks:
                raise ValueError(
                    f"{e.cls}: blocking audit names lock {b.lock!r} not "
                    f"in the entry's declared locks {e.locks}")
            if not b.tag or any(c in b.tag for c in "[]"):
                raise ValueError(f"{e.cls}: malformed blocking-audit tag "
                                 f"{b.tag!r}")


def by_class(registry: Tuple[ClassGuards, ...] = REGISTRY) -> dict:
    """{(module, cls): entry} — the static pass's lookup table."""
    return {(e.module, e.cls): e for e in registry}


validate()
