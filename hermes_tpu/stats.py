"""Stats / telemetry (SURVEY.md §1 L7, §5.5).

The reference aggregates cache-line-padded per-thread counters in a stats
thread that prints ops/s and latency percentiles.  Here the counters are the
device-side Meta columns (summed per step at zero cost); the host reads them
off-device at reporting interval and derives throughput and the commit-latency
distribution (in protocol steps, convertible to wall time via the measured
step duration).  ``JsonlLogger`` writes one JSON object per interval, the
rebuild's machine-readable metrics log."""

from __future__ import annotations

import json
import time
from typing import IO, Optional

import jax
import numpy as np


def percentile_from_hist(hist: np.ndarray, q: float) -> int:
    """q in [0,1]; histogram bins are latency-in-steps (last bin = clip)."""
    cum = hist.cumsum()
    if cum[-1] == 0:
        return -1
    return int((cum >= q * cum[-1]).argmax())


def summarize(meta, wall_s: Optional[float] = None, steps: Optional[int] = None) -> dict:
    m = jax.device_get(meta)
    hist = np.asarray(m.lat_hist)
    if hist.ndim > 1:
        hist = hist.sum(axis=0)
    commits = int(np.asarray(m.n_write).sum() + np.asarray(m.n_rmw).sum())
    out = dict(
        n_read=int(np.asarray(m.n_read).sum()),
        n_write=int(np.asarray(m.n_write).sum()),
        n_rmw=int(np.asarray(m.n_rmw).sum()),
        n_abort=int(np.asarray(m.n_abort).sum()),
        commits=commits,
        p50_commit_steps=percentile_from_hist(hist, 0.5),
        p99_commit_steps=percentile_from_hist(hist, 0.99),
        mean_commit_steps=(
            float(np.asarray(m.lat_sum).sum()) / max(1, int(np.asarray(m.lat_cnt).sum()))
        ),
    )
    if wall_s:
        out["wall_s"] = round(wall_s, 4)
        out["writes_per_sec"] = round(commits / wall_s, 1)
        out["ops_per_sec"] = round((commits + out["n_read"]) / wall_s, 1)
    if steps:
        out["steps"] = steps
        if wall_s:
            out["step_us"] = round(wall_s / steps * 1e6, 1)
    return out


class JsonlLogger:
    """Interval metrics to a JSONL stream (one object per report)."""

    def __init__(self, fp: IO[str]):
        self.fp = fp
        self.t0 = time.perf_counter()

    def log(self, record: dict) -> None:
        record = dict(record, t=round(time.perf_counter() - self.t0, 4))
        self.fp.write(json.dumps(record) + "\n")
        self.fp.flush()
