"""Stats / telemetry (SURVEY.md §1 L7, §5.5).

The reference aggregates cache-line-padded per-thread counters in a stats
thread that prints ops/s and latency percentiles.  Here the counters are the
device-side Meta columns (summed per step at zero cost); the host reads them
off-device at reporting interval and derives throughput and the commit-latency
distribution (in protocol steps, convertible to wall time via the measured
step duration).

This module is the thin summarize layer over those columns; the registry /
exporter / tracing machinery lives in ``hermes_tpu.obs`` (``JsonlLogger``
below is the back-compat shim over ``obs.metrics.JsonlExporter``).
"""

from __future__ import annotations

from typing import IO, Optional

import numpy as np

from hermes_tpu.obs.metrics import JsonlExporter, percentile_from_counts


def percentile_from_hist(hist: np.ndarray, q: float) -> Optional[int]:
    """q in [0,1]; histogram bins are latency-in-steps (last bin = clip).
    Returns None on an empty histogram — never a numeric sentinel that
    silently poisons downstream JSON (``p50_commit_steps: -1``)."""
    return percentile_from_counts(hist, q)


def percentile_nearest_rank(sorted_vals, q: float):
    """Nearest-rank percentile (the ceil(q*n)-th order statistic) of an
    already-sorted sequence: with 100 samples p99 is the 99th value, not
    the max — one outlier no longer defines the reported tail.  Returns
    None on an empty sequence."""
    import math

    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(q * len(sorted_vals)) - 1))]


def summarize(meta, wall_s: Optional[float] = None, steps: Optional[int] = None,
              hists: bool = False) -> dict:
    """One metrics record from a Meta pytree (batched (R, ...) or
    per-replica).  Percentile fields are omitted when their histogram is
    empty; phase-metric fields (obs pillar 1) are included whenever any
    replica recorded them (faststep under cfg.phase_metrics — the phases
    engine leaves them 0).  ``hists=True`` attaches the raw histogram
    arrays, which scripts/obs_report.py renders."""
    # jax is imported lazily: this module sits on the serving import path
    # (soak -> stats) and the shm IPC worker processes (serving/ipc.py)
    # must come up without paying the jax import — only ``summarize``,
    # which handles device pytrees, needs it
    import jax

    m = jax.device_get(meta)

    def tot(field):
        return int(np.asarray(getattr(m, field)).sum())

    def hist_of(field):
        h = np.asarray(getattr(m, field))
        return h.sum(axis=0) if h.ndim > 1 else h

    hist = hist_of("lat_hist")
    commits = tot("n_write") + tot("n_rmw")
    out = dict(
        n_read=tot("n_read"),
        n_write=tot("n_write"),
        n_rmw=tot("n_rmw"),
        n_abort=tot("n_abort"),
        commits=commits,
        mean_commit_steps=(
            float(np.asarray(m.lat_sum).sum()) / max(1, tot("lat_cnt"))
        ),
    )
    for q, tag in ((0.5, "p50"), (0.99, "p99")):
        p = percentile_from_hist(hist, q)
        if p is not None:
            out[f"{tag}_commit_steps"] = p
    qhist = hist_of("qwait_hist") if hasattr(m, "qwait_hist") else None
    if hasattr(m, "n_inv") and tot("n_inv"):
        out.update(
            n_inv=tot("n_inv"),
            n_rebcast=tot("n_rebcast"),
            n_nack=tot("n_nack"),
            n_retry=tot("n_retry"),
            replay_peak=int(np.asarray(m.replay_peak).max()),
        )
        for q, tag in ((0.5, "p50"), (0.99, "p99")):
            p = percentile_from_hist(qhist, q)
            if p is not None:
                out[f"{tag}_qwait_steps"] = p
    if wall_s:
        out["wall_s"] = round(wall_s, 4)
        out["writes_per_sec"] = round(commits / wall_s, 1)
        out["ops_per_sec"] = round((commits + out["n_read"]) / wall_s, 1)
    if steps:
        out["steps"] = steps
        if wall_s:
            out["step_us"] = round(wall_s / steps * 1e6, 1)
    if hists:
        out["lat_hist"] = hist.astype(int).tolist()
        if qhist is not None:
            out["qwait_hist"] = qhist.astype(int).tolist()
    return out


class JsonlLogger:
    """Back-compat interval logger: one JSON object per report, now routed
    through the obs exporter (every record gains the shared ``t``/``kind``
    schema the obs timeline tools consume)."""

    def __init__(self, fp: IO[str]):
        self._exp = JsonlExporter(fp)

    def log(self, record: dict) -> None:
        self._exp.write(dict(record), kind="metrics")
