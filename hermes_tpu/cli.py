"""CLI (SURVEY.md §1 L7): run a replicated-KVS workload from the command line.

The reference configures via compile-time macros + run-script flags; the
rebuild exposes the same knobs as flags over the frozen config dataclass.

    python -m hermes_tpu --replicas 8 --keys $((1<<20)) --sessions 1024 \
        --steps 200 --backend batched --workload a --check

Backends: batched (one device), sharded (one replica per device), sim
(host-mediated deterministic).  ``--check`` records the op history and runs
the linearizability gate at the end (sampled via --check-keys).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="hermes_tpu", description=__doc__)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--value-words", type=int, default=2)
    ap.add_argument("--sessions", type=int, default=256)
    ap.add_argument("--replay-slots", type=int, default=64)
    ap.add_argument("--ops-per-session", type=int, default=256)
    ap.add_argument("--steps", type=int, default=0, help="0 = run until drained")
    ap.add_argument(
        "--backend",
        choices=["batched", "sharded", "sim", "fast", "fast-sharded"],
        default="fast",
        help="fast/fast-sharded = TPU-optimized round (core/faststep.py); "
        "batched/sharded = reference phases; sim = host-mediated adversarial",
    )
    ap.add_argument("--lane-budget", type=int, default=None,
                    help="faststep outbound-lane compaction budget")
    ap.add_argument("--wrap-stream", action="store_true",
                    help="cycle op streams forever (bench mode; use --steps)")
    ap.add_argument("--acceptance", default=None,
                    choices=["1", "2", "2r", "3", "3c", "4", "5", "all",
                             "all+variants"],
                    help="run BASELINE acceptance config N (1-5, or the 2r/3c"
                    " variants); 'all' = the judged configs 1-5 (the baseline"
                    " gate's exit code covers exactly those), 'all+variants'"
                    " additionally runs the 2r/3c variants; "
                    "ignores most other flags")
    ap.add_argument("--scale", type=float, default=0.01,
                    help="acceptance size scale (1.0 = full 1M-key shape)")
    ap.add_argument("--profile", type=str, default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the run into DIR")
    ap.add_argument(
        "--workload", choices=["a", "b", "c", "f"], default="a",
        help="YCSB mix: a=50/50, b=95/5, c=read-only, f=50/50 with RMW updates",
    )
    ap.add_argument("--arb-mode", choices=["race", "sort"], default="race",
                    help="same-key issue arbitration strategy (faststep)")
    ap.add_argument("--mega-round", action="store_true",
                    help="round-15 Pallas mega-round (core/megaround.py): "
                         "fuse the arbiter/apply/quorum chain's sparse ops "
                         "into kernels — bit-identical state, batched "
                         "census 12 -> 4; needs --arb-mode sort; falls "
                         "back LOUDLY to the fused-sort program when "
                         "Pallas/analysis refuse")
    ap.add_argument("--chain-writes", type=int, default=0,
                    help="intra-round same-key write chain length (faststep "
                         "hot-key throughput; needs --arb-mode sort)")
    ap.add_argument("--rmw-retries", type=int, default=0,
                    help="RMW nack retry-in-place budget (faststep; 0 = "
                         "reference abort-on-nack behavior)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="in-flight dispatch ring depth (round-8 pipelined "
                         "serving: depth >= 2 overlaps the completion "
                         "readback with the next device round; 1 = "
                         "synchronous).  Fast backends only; with "
                         "--acceptance, runs the scenarios pipelined")
    ap.add_argument("--no-donate", action="store_true",
                    help="compile the round WITHOUT state-tree donation "
                         "(the copying A/B baseline, cfg.donate_state; "
                         "fast backends only)")
    ap.add_argument("--no-auto-rebase", action="store_true",
                    help="disable the automatic version rebase at counter "
                         "polls (restores the loud packed-ts overflow error "
                         "as the only budget behavior)")
    ap.add_argument("--distribution", choices=["uniform", "zipfian"], default="uniform")
    ap.add_argument("--zipf-theta", type=float, default=0.99)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--check", action="store_true", help="record history + linearizability gate")
    ap.add_argument("--check-keys", type=int, default=512, help="sampled keys for the gate")
    ap.add_argument("--report-every", type=int, default=0, help="steps between stat lines")
    ap.add_argument("--metrics-jsonl", type=str, default=None,
                    help="legacy interval-metrics JSONL (see --metrics-out)")
    ap.add_argument("--metrics-out", type=str, default=None, metavar="RUN_JSONL",
                    help="obs run log: interval metrics + trace events + "
                    "summary on one monotonic clock (hermes_tpu.obs); render "
                    "with scripts/obs_report.py")
    ap.add_argument("--trace-steps", action="store_true",
                    help="with --metrics-out: per-step dispatch/readback "
                    "spans (verbose; faults/drains/intervals are always "
                    "traced)")
    ap.add_argument("--trace-sample", type=int, default=0, metavar="N",
                    help="per-op tracing (cfg.trace_sample): mint a trace "
                    "id for ~1 in N submitted ops with a seeded "
                    "deterministic sampler; spans land in the --metrics-out "
                    "run log (obs/tracing.py); 0 disables")
    ap.add_argument("--freeze", action="append", default=[],
                    metavar="R:FROM:TO",
                    help="failure injection: freeze replica R at step FROM, "
                    "thaw at step TO (repeatable; emits obs fault events)")
    ap.add_argument("--op-timeout", type=int, default=0, metavar="ROUNDS",
                    help="stuck-op watchdog budget (cfg.op_timeout_rounds): "
                    "a client op pending past this many rounds surfaces a "
                    "stuck_op diagnostic; 0 disables")
    ap.add_argument("--op-retries", type=int, default=0, metavar="N",
                    help="bounded client retry (round-11, "
                    "cfg.op_retry_limit): ops wedged on a fenced replica "
                    "are salvaged and re-routed up to N times (needs "
                    "--op-timeout); 0 disables")
    ap.add_argument("--degraded-floor", type=int, default=0, metavar="N",
                    help="quorum-loss degraded mode (round-11, cfg."
                    "min_healthy_for_writes): with fewer than N healthy "
                    "replicas new writes are shed loudly (kind='rejected') "
                    "instead of wedging; 0 disables")
    ap.add_argument("--detect", type=int, default=None, metavar="CONFIRM",
                    help="attach the lease failure detector "
                    "(membership.MembershipService) with the given confirm "
                    "window in rounds (0 = remove at first suspicion); on "
                    "the fast backends detection rides the completion "
                    "harvest — zero dispatch-path device_gets")
    ap.add_argument("--chaos", type=int, default=None, metavar="SEED",
                    help="drive a seeded chaos schedule (hermes_tpu.chaos: "
                    "freeze/thaw/join/crash-restart/hb-skew) against the "
                    "run; needs --steps and a fast backend; heals + drains "
                    "at the end; events ride the obs timeline")
    ap.add_argument("--chaos-schedule", type=str, default=None,
                    metavar="FILE",
                    help="declarative chaos schedule file ('@STEP KIND "
                    "[replica] [k=v...]' lines, chaos.Schedule.parse) "
                    "instead of a seeded one; needs --steps and a fast "
                    "backend")
    ap.add_argument("--fleet-groups", type=int, default=0,
                    help="run a key-sharded FLEET (round-13, hermes_tpu."
                    "fleet): N independent groups of --replicas each "
                    "behind the routed client facade, a seeded mix "
                    "spanning every group driven through it; --check "
                    "gates every group's history plus the fleet "
                    "invariants (verify_fleet); --steps bounds the "
                    "drive.  Fast batched backend; needs --value-words "
                    ">= 3 (the client KVS carries write uids)")
    ap.add_argument("--fleet-ops", type=int, default=512,
                    help="ops in the fleet quickstart mix (--fleet-groups)")
    ap.add_argument("--drill", default=None,
                    choices=["rolling", "resize", "migrate"],
                    help="run an elastic drill (round-10, hermes_tpu."
                    "elastic): 'rolling' crash-restarts every replica in "
                    "sequence under load, 'resize' shrinks+grows every "
                    "replica live through the KVS, 'migrate' moves a key "
                    "range between two groups under client traffic; "
                    "--check gates each with the linearizability checker, "
                    "and the measured worst-window throughput dip is "
                    "reported (dip_pct).  Fast backends only; resize/"
                    "migrate need --value-words >= 3")
    ap.add_argument("--serve", type=int, default=None, metavar="N",
                    help="serving-front-end quickstart (round-14, hermes_"
                    "tpu/serving): drive N open-loop Poisson ops through "
                    "the byte-honest loopback RPC server over the KVS "
                    "(admission control, deadlines, backpressure, shed "
                    "ladder) and print one JSON summary line; --check "
                    "additionally gates the linearizability checker AND "
                    "the serving invariants (response conservation, "
                    "admission accounting exactness).  Needs "
                    "--value-words >= 3; fast batched backend")
    ap.add_argument("--reads", type=int, default=None, metavar="N",
                    help="local-read fast-path quickstart (round-16, "
                    "core/readpath.py): drive N ops — reads through the "
                    "batched device-resident multi_get, writes through "
                    "submit_batch, interleaved — and print one JSON "
                    "summary line; --check additionally gates the "
                    "linearizability checker AND the stale-read check "
                    "(checker/linearizability.stale_read).  Needs "
                    "--value-words >= 3; fast batched backend.  "
                    "--read-frac sets the read share, --distribution/"
                    "--zipf-theta shape the keys (plus 'latest' via "
                    "--read-latest)")
    ap.add_argument("--value-bytes", type=int, default=None, metavar="N",
                    help="value-heap quickstart (round-17, hermes_tpu/"
                    "heap): drive variable-length byte values up to N "
                    "bytes — memcached-shaped sizes (ycsb.value_sizes) "
                    "through submit_batch puts and batched multi_get "
                    "reads, with a compaction at the end — and print one "
                    "JSON summary line (writes/s, value GB/s, heap "
                    "stats); --check additionally gates the "
                    "linearizability checker, the stale-read check, AND "
                    "the post-compaction heap-utilization bound.  Needs "
                    "--value-words >= 3; fast batched backend.  "
                    "--values-ops sizes the drive")
    ap.add_argument("--values-ops", type=int, default=4096, metavar="N",
                    help="op count for the --value-bytes drive "
                    "(default 4096)")
    ap.add_argument("--read-frac", type=float, default=0.95,
                    help="read fraction of the --reads mix (default "
                    "0.95, the YCSB-B shape)")
    ap.add_argument("--read-latest", action="store_true",
                    help="--reads: draw read keys latest-distribution "
                    "(YCSB-D) instead of --distribution")
    ap.add_argument("--serve-rate", type=float, default=8000.0,
                    help="open-loop arrival rate (ops per virtual second) "
                    "for --serve")
    ap.add_argument("--serve-deadline-us", type=int, default=50_000,
                    metavar="US",
                    help="client deadline for --serve ops (virtual "
                    "microseconds; 0 = none)")
    ap.add_argument("--bench-latency", action="store_true",
                    help="measure the serving latency operating point "
                    "end-to-end from a real client socket (round-14: "
                    "small dispatches at pipeline_depth>=2, donated "
                    "state, framed RPC over localhost TCP) and print one "
                    "JSON line with p50/p99 vs the 28 ms dispatch-loop "
                    "figure")
    ap.add_argument("--locklint", action="store_true",
                    help="run the serving drive under the dynamic "
                    "lock-order sanitizer (HERMES_LOCKLINT=1: every "
                    "serving-tier lock becomes an instrumented ObsLock, "
                    "analysis/lockgraph.py) and append the held-before "
                    "graph report — per-lock acquires/contention/"
                    "hold-p99, edge count, any potential-deadlock "
                    "cycles — to the JSON summary line")
    ap.add_argument("--wal-dir", type=str, default=None, metavar="DIR",
                    help="enable the round-22 durability tier: append "
                    "every committed write to a CRC-framed write-ahead "
                    "extent+commit log under DIR (created if missing); "
                    "recover a killed store with "
                    "chaos.recovery.recover_store")
    ap.add_argument("--wal-sync", choices=["commit", "round", "off"],
                    default="commit",
                    help="WAL durability mode (with --wal-dir): 'commit' "
                    "resolves a write to the client only after its group-"
                    "commit fsync (the zero-loss contract); 'round' and "
                    "'off' resolve immediately and LABEL completions "
                    "'<mode>:not-fsynced-at-resolve'")
    ap.add_argument("--profile-out", type=str, default=None,
                    metavar="PROFILE_JSONL",
                    help="write the run config's round op census + cost-model"
                    " pricing as obs profile records (fast backends only; "
                    "abstract lowering — adds no device work to the run)")
    ap.add_argument("--analyze", type=str, default=None,
                    metavar="FINDINGS_JSONL",
                    help="run the static jaxpr invariant analyzer "
                    "(hermes_tpu.analysis) on the run config's round program "
                    "and write the findings as obs analysis records (fast "
                    "backends only; abstract tracing — no device work)")
    return ap


MIXES = {
    "a": dict(read_frac=0.5, rmw_frac=0.0),
    "b": dict(read_frac=0.95, rmw_frac=0.0),
    "c": dict(read_frac=1.0, rmw_frac=0.0),
    "f": dict(read_frac=0.5, rmw_frac=1.0),
}


def _run_fleet(args, cfg) -> int:
    """Fleet quickstart (round-13, hermes_tpu/fleet): N key-sharded
    groups behind the routed facade, a seeded get/put mix spanning every
    group's range, per-group + fleet counters as one JSON line; --check
    runs every group's linearizability gate plus verify_fleet."""
    import json

    from hermes_tpu.config import FleetConfig
    from hermes_tpu.fleet import Fleet

    fcfg = FleetConfig(groups=args.fleet_groups, base=cfg)
    fleet = Fleet(fcfg, record="array" if args.check else False)
    rng = np.random.default_rng(args.seed)
    n = args.fleet_ops
    keys = rng.integers(0, fcfg.total_keys, size=n).astype(np.int64)
    kinds = np.where(rng.random(n) < cfg.workload.read_frac,
                     Fleet.GET, Fleet.PUT).astype(np.int32)
    values = rng.integers(0, 1 << 20,
                          size=(n, cfg.value_words - 2)).astype(np.int32)
    t0 = time.perf_counter()
    fb = fleet.submit_batch(kinds, keys, values)
    drained = fleet.run_batch(fb, max_steps=args.steps or 50_000)
    wall = time.perf_counter() - t0
    summary = dict(fleet_groups=args.fleet_groups, ops=n,
                   done=fb.done_count(), drained=bool(drained),
                   wall_s=round(wall, 3),
                   ranges=fleet.router.owned_ranges(),
                   counters=fleet.counters())
    ok = drained
    if args.check:
        verdicts = fleet.check()
        summary["checked_ok"] = verdicts["ok"]
        summary["group_verdicts"] = verdicts["groups"]
        ok = ok and verdicts["ok"]
    summary["ok"] = bool(ok)
    print(json.dumps(summary, default=str))
    return 0 if ok else 1


def _run_serve(args, cfg) -> int:
    """Serving quickstart (round-14, hermes_tpu/serving): N open-loop
    Poisson ops through the loopback RPC path over the KVS — admission,
    deadlines, backpressure, shedding — as one JSON summary line.
    --check gates the checker plus the serving invariants."""
    import json

    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving import ServingConfig, run_open_loop
    from hermes_tpu.workload.openloop import MixSpec

    kvs = KVS(cfg, record="array" if args.check else False)
    obs = None
    if args.metrics_out or args.trace_sample:
        # the traced-serving quickstart (round-18): spans + series ride
        # the run log; the report renders the per-op critical path
        from hermes_tpu.obs import Observability

        obs = kvs.rt.attach_obs(Observability(path=args.metrics_out,
                                              trace_steps=args.trace_steps))
    scfg = ServingConfig(trace_sample=args.trace_sample,
                         trace_seed=args.seed)
    spec = MixSpec(name=cfg.workload.distribution,
                   distribution=cfg.workload.distribution,
                   zipf_theta=cfg.workload.zipf_theta,
                   read_frac=cfg.workload.read_frac)
    res = run_open_loop(
        kvs, scfg, spec,
        rate_per_s=args.serve_rate, n=args.serve, seed=args.seed,
        deadline_us=args.serve_deadline_us)
    if obs is not None:
        obs.series_snapshot()
        obs.close()
    summary = {k: v for k, v in res.items() if not k.startswith("_")}
    # the serving invariants (response conservation, per-tenant admission
    # accounting exactness) are asserted by verify_serving INSIDE
    # run_open_loop — reaching here means they held
    ok = True
    if args.check:
        v = kvs.rt.check(max_keys=args.check_keys)
        summary["checked_ok"] = bool(v.ok)
        ok = ok and v.ok
    if args.locklint:
        ok = _append_locklint(summary) and ok
    summary["ok"] = bool(ok)
    print(json.dumps(summary, default=str))
    return 0 if ok else 1


def _append_locklint(summary: dict) -> bool:
    """Attach the dynamic lock sanitizer's held-before graph report to a
    quickstart summary; a cycle (potential deadlock) fails the run."""
    from hermes_tpu.analysis import lockgraph

    rep = lockgraph.global_graph().report()
    summary["locklint"] = rep
    return not rep["cycles"]


#: --value-bytes --check: post-compaction utilization floor (live bytes /
#: allocated log prefix) — granule rounding is the only honest slack
VALUES_UTIL_FLOOR = 0.75


def _run_values(args, cfg) -> int:
    """Value-heap quickstart (round-17): N variable-length puts
    (memcached-shaped sizes) + batched reads + one compaction, one JSON
    line; --check gates the linearizability checker, the stale-read
    check, and the post-compaction heap-utilization bound."""
    import dataclasses
    import json

    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.checker.fast import default_record
    from hermes_tpu.core import layouts
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.ycsb import value_payload, value_sizes

    cfg = dataclasses.replace(cfg, max_value_bytes=args.value_bytes,
                              heap_bytes=min(layouts.MAX_HEAP_BYTES, 1 << 22))
    kvs = KVS(cfg, record=default_record(args.check))
    n = args.values_ops
    rng = np.random.default_rng(args.seed)
    lens = value_sizes(dict(n=n, max_bytes=args.value_bytes), args.seed)
    chunk = min(2048, cfg.n_keys)
    latest = {}
    written = 0
    t0 = time.perf_counter()
    for lo in range(0, n, chunk):
        m = min(chunk, n - lo)
        # unique keys per batch: same-key writes inside one batch commit
        # in arbiter order, so byte-exactness needs one write per key
        kk = rng.permutation(cfg.n_keys)[:m].astype(np.int64)
        pays = [value_payload(args.seed, lo + j, int(lens[lo + j]))
                for j in range(m)]
        bf = kvs.submit_batch(np.full(m, KVS.PUT, np.int32), kk, pays)
        if not kvs.run_batch(bf, max_steps=args.steps or 50_000):
            print(json.dumps({"ok": False,
                              "error": "value puts did not drain"}))
            return 1
        for k, p in zip(kk, pays):
            latest[int(k)] = p
        written += int(sum(len(p) for p in pays))
    put_wall = time.perf_counter() - t0
    skeys = np.asarray(sorted(latest), np.int64)
    t0 = time.perf_counter()
    res = kvs.multi_get(skeys)
    if not res.all_done():
        print(json.dumps({"ok": False, "error": "reads did not drain"}))
        return 1
    get_wall = time.perf_counter() - t0
    exact = all(res.data[j] == latest[int(k)]
                for j, k in enumerate(skeys))
    stats = kvs.heap_gc(reason="quickstart")
    util = (stats["live_bytes"] / stats["used_bytes"]) if stats else None
    gb = 1 << 30
    summary = dict(ops=n, value_bytes_cap=args.value_bytes,
                   bytes_written=written,
                   wall_s=round(put_wall + get_wall, 3),
                   writes_per_sec=round(n / put_wall, 1),
                   put_gb_per_sec=round(written / put_wall / gb, 4),
                   byte_exact=bool(exact),
                   heap=kvs.heap.stats(),
                   post_gc_util=round(util, 4) if util else None)
    ok = exact
    if args.check:
        v = kvs.rt.check(max_keys=args.check_keys)
        stale = lin.stale_read(kvs.rt.history_ops())
        summary["checked_ok"] = bool(v.ok)
        summary["stale_read"] = [repr(e) for e in stale[:4]]
        summary["util_floor"] = VALUES_UTIL_FLOOR
        ok = (ok and bool(v.ok) and not stale
              and util is not None and util >= VALUES_UTIL_FLOOR)
    summary["ok"] = bool(ok)
    print(json.dumps(summary, default=str))
    return 0 if ok else 1


def _run_reads(args, cfg) -> int:
    """Local-read quickstart (round-16): N ops at --read-frac through
    the batched device-resident read path (reads) and submit_batch
    (writes), one JSON line; --check gates the linearizability checker
    plus the structural stale-read check."""
    import json

    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.checker.fast import default_record
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.openloop import MixSpec, make_mix

    kvs = KVS(cfg, record=default_record(args.check))
    dist = "latest" if args.read_latest else cfg.workload.distribution
    spec = MixSpec(name=dist, distribution=dist,
                   zipf_theta=cfg.workload.zipf_theta,
                   read_frac=args.read_frac)
    n = args.reads
    mix = make_mix(spec, cfg.n_keys, n, args.seed,
                   value_words=cfg.value_words - 2)
    chunk = 4096
    t0 = time.perf_counter()
    reads = writes = local = 0
    for lo in range(0, n, chunk):
        kk = mix["key"][lo: lo + chunk]
        wr = mix["kind"][lo: lo + chunk] != 0
        if wr.any():
            bf = kvs.submit_batch(
                np.full(int(wr.sum()), KVS.PUT, np.int32), kk[wr],
                mix["value"][lo: lo + chunk][wr])
            if not kvs.run_batch(bf, max_steps=args.steps or 50_000):
                print(json.dumps({"ok": False,
                                  "error": "write share did not drain"}))
                return 1
            writes += int(wr.sum())
        rd = ~wr
        if rd.any():
            res = kvs.multi_get(kk[rd])
            if not res.all_done():
                print(json.dumps({"ok": False,
                                  "error": "read share did not drain"}))
                return 1
            reads += int(rd.sum())
            local += res.local_served
    wall = time.perf_counter() - t0
    summary = dict(ops=n, reads=reads, writes=writes,
                   read_frac=args.read_frac, distribution=dist,
                   wall_s=round(wall, 3),
                   reads_per_sec=round(reads / wall, 1) if reads else 0.0,
                   **kvs.read_stats())
    ok = True
    if args.check:
        v = kvs.rt.check(max_keys=args.check_keys)
        stale = lin.stale_read(kvs.rt.history_ops())
        summary["checked_ok"] = bool(v.ok)
        summary["stale_read"] = [repr(e) for e in stale[:4]]
        ok = bool(v.ok) and not stale
    summary["ok"] = bool(ok)
    print(json.dumps(summary, default=str))
    return 0 if ok else 1


def _run_bench_latency(args, cfg) -> int:
    """One-cell serving latency quickstart: the latency operating point
    measured end-to-end from a real client socket."""
    import json

    from hermes_tpu.serving.bench import (DISPATCH_LOOP_P50_MS, host_cfg,
                                          improves_dispatch_loop,
                                          run_socket_cell)
    from hermes_tpu.serving.server import ServingConfig
    from hermes_tpu.workload.openloop import MixSpec

    scfg = ServingConfig(tenant_rate_per_s=1e6, tenant_burst=1e5,
                         tenant_quota=64, queue_cap=256)
    # probe capacity closed-loop first and open-loop at 0.2x it (the
    # run_serve_bench discipline): a fixed rate above this box's service
    # rate would measure queueing delay, not service latency
    probe = run_socket_cell(host_cfg("latency"), scfg, MixSpec(),
                            n=32, mode="closed", window=8, seed=args.seed)
    cell = run_socket_cell(host_cfg("latency"), scfg, MixSpec(),
                           n=64, mode="open",
                           rate_per_s=max(10.0, 0.2 * probe["ops_per_sec"]),
                           seed=args.seed)
    cell["capacity_probe_ops_per_sec"] = probe["ops_per_sec"]
    cell["dispatch_loop_p50_ms"] = DISPATCH_LOOP_P50_MS
    cell["improves_dispatch_loop"] = improves_dispatch_loop(cell["p50_us"])
    # a cell that lost its server or part of its answers is NOT a pass,
    # however good the answered-prefix percentiles look
    cell["ok"] = bool(cell["improves_dispatch_loop"]) and cell["error"] is None
    if args.locklint:
        cell["ok"] = _append_locklint(cell) and cell["ok"]
    print(json.dumps(cell, default=str))
    return 0 if cell["ok"] else 1


def _run_drill(args, cfg, mesh) -> int:
    """Elastic drills (round-10, hermes_tpu/elastic): rolling restart /
    rolling resize / key-range migration, checker-gated with --check,
    worst-window dip reported.  Prints one JSON summary line."""
    import json

    from hermes_tpu import elastic
    from hermes_tpu.checker.fast import default_record
    from hermes_tpu.kvs import KVS
    from hermes_tpu.runtime import FastRuntime

    backend = "batched" if args.backend == "fast" else "sharded"
    rec = default_record(args.check)
    summary: dict = {"drill": args.drill, "backend": backend}

    if args.drill == "rolling":
        rt = FastRuntime(cfg, backend=backend, mesh=mesh, record=rec)
        if args.detect is not None:
            from hermes_tpu.membership import MembershipService

            rt.attach_membership(
                MembershipService(cfg, confirm_steps=args.detect))
        res = elastic.run_rolling_restart(
            rt, steps=args.steps or None, check=args.check)
        ok = (res["restarts"] == cfg.n_replicas and res.get("drained", True)
              and res.get("checked_ok", not args.check))
        summary.update(restarts=res["restarts"], drained=res.get("drained"),
                       lost_ops=res["lost_ops"], dip=res["dip"],
                       checked_ok=res.get("checked_ok"))
    elif args.drill == "resize":
        kvs = KVS(cfg, backend=backend, mesh=mesh, record=rec)
        # size the standing load to outlast the whole drill (~R cycles of
        # 2*hold_steps rounds plus per-cycle drains, up to R*S completions
        # per round) — a load that dries up mid-drill reads as a 100% dip
        # (load exhaustion, not service degradation)
        rounds_est = cfg.n_replicas * (2 * 8 + 6) + 24
        n_ops = rounds_est * cfg.n_replicas * cfg.n_sessions
        bf = elastic.submit_drill_mix(kvs, n_ops, seed=args.seed)
        res = elastic.rolling_resize(kvs, check=args.check)
        kvs.run_batch(bf)
        ok = (res["resizes"] == cfg.n_replicas and bf.all_done()
              and res.get("checked_ok", not args.check))
        summary.update(resizes=res["resizes"], dip=res["dip"],
                       rejected_ops=res["rejected_ops"],
                       load_done=bf.done_count(),
                       checked_ok=res.get("checked_ok"))
    else:  # migrate
        res = elastic.migration_drill(cfg, backend=backend, mesh=mesh,
                                      record=rec, seed=args.seed,
                                      check=args.check)
        ok = (res.get("src_checked_ok", not args.check)
              and res.get("dst_checked_ok", not args.check))
        summary.update({k: v for k, v in res.items() if k != "dest_slots"})

    summary["ok"] = bool(ok)
    print(json.dumps(summary, default=str))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = build_parser()
    args = ap.parse_args(argv)
    if args.locklint:
        # must land before any serving/transport object mints its locks
        # (concurrency.make_lock reads the switch at mint time)
        import os

        os.environ["HERMES_LOCKLINT"] = "1"
    if args.chain_writes and args.arb_mode != "sort":
        ap.error("--chain-writes needs --arb-mode sort")
    if args.mega_round and args.arb_mode != "sort":
        ap.error("--mega-round needs --arb-mode sort (the mega route "
                 "kernel consumes the fused sort's verdicts)")
    if ((args.arb_mode != "race" or args.chain_writes or args.mega_round
         or args.no_auto_rebase or args.rmw_retries)
            and args.backend not in ("fast", "fast-sharded")):
        ap.error("--arb-mode/--chain-writes/--mega-round/--no-auto-rebase/"
                 "--rmw-retries only affect the fast backends "
                 "(core/faststep.py / runtime.FastRuntime); use --backend "
                 "fast or fast-sharded")
    if args.pipeline_depth < 1:
        ap.error("--pipeline-depth must be >= 1")
    if ((args.pipeline_depth > 1 or args.no_donate)
            and args.backend not in ("fast", "fast-sharded")):
        ap.error("--pipeline-depth/--no-donate only affect the fast "
                 "backends (runtime.FastRuntime's harvest ring / donated "
                 "state); use --backend fast or fast-sharded")
    if args.profile_out and args.backend not in ("fast", "fast-sharded"):
        ap.error("--profile-out censuses the fast round (core/faststep.py); "
                 "use --backend fast or fast-sharded")
    if args.profile_out and args.acceptance:
        ap.error("--profile-out does not apply to acceptance runs (they "
                 "build their own configs); census a run config instead")
    if args.analyze and args.backend not in ("fast", "fast-sharded"):
        ap.error("--analyze traces the fast round (core/faststep.py); "
                 "use --backend fast or fast-sharded")
    if args.analyze and args.acceptance:
        ap.error("--analyze does not apply to acceptance runs (they build "
                 "their own configs); analyze a run config instead")
    if args.chaos is not None and args.chaos_schedule:
        ap.error("--chaos and --chaos-schedule are mutually exclusive")
    if args.drill:
        if args.backend not in ("fast", "fast-sharded"):
            ap.error("--drill drives the fast runtimes (hermes_tpu."
                     "elastic); use --backend fast or fast-sharded")
        if args.chaos is not None or args.chaos_schedule or args.freeze:
            ap.error("--drill and --chaos/--freeze are mutually exclusive "
                     "(drills build their own schedules)")
        if args.acceptance:
            ap.error("--drill and --acceptance are mutually exclusive")
        if args.drill in ("resize", "migrate") and args.value_words < 3:
            ap.error(f"--drill {args.drill} drives the client KVS: needs "
                     "--value-words >= 3 (words 0-1 carry the write uid)")
    if args.fleet_groups:
        if args.fleet_groups < 1:
            ap.error("--fleet-groups must be >= 1")
        if args.backend != "fast":
            ap.error("--fleet-groups drives the fast batched backend "
                     "through the KVS facade (hermes_tpu.fleet); sharded "
                     "fleets are launched via hermes_tpu.launch "
                     "--fleet-groups")
        if args.value_words < 3:
            ap.error("--fleet-groups needs --value-words >= 3 (words 0-1 "
                     "carry the write uid)")
        if (args.acceptance or args.drill or args.chaos is not None
                or args.chaos_schedule or args.freeze):
            ap.error("--fleet-groups is its own drive; drop --acceptance/"
                     "--drill/--chaos/--freeze")
    if args.serve is not None:
        if args.serve < 1:
            ap.error("--serve wants a positive op count")
        if args.bench_latency:
            ap.error("--serve and --bench-latency are separate drives; "
                     "pick one")
        if args.backend != "fast":
            ap.error("--serve drives the fast batched backend through the "
                     "KVS facade (hermes_tpu/serving)")
        if args.value_words < 3:
            ap.error("--serve needs --value-words >= 3 (words 0-1 carry "
                     "the write uid)")
        if (args.acceptance or args.drill or args.fleet_groups
                or args.chaos is not None or args.chaos_schedule
                or args.freeze):
            ap.error("--serve is its own drive; drop --acceptance/--drill/"
                     "--fleet-groups/--chaos/--freeze")
    if args.bench_latency and (args.acceptance or args.drill
                               or args.fleet_groups
                               or args.chaos is not None
                               or args.chaos_schedule or args.freeze):
        ap.error("--bench-latency is its own drive; drop --acceptance/"
                 "--drill/--fleet-groups/--chaos/--freeze")
    if args.reads is not None:
        if args.reads < 1:
            ap.error("--reads wants a positive op count")
        if not (0.0 <= args.read_frac <= 1.0):
            ap.error("--read-frac must be in [0, 1]")
        if args.backend != "fast":
            ap.error("--reads drives the fast batched backend through the "
                     "KVS facade (core/readpath.py)")
        if args.value_words < 3:
            ap.error("--reads needs --value-words >= 3 (words 0-1 carry "
                     "the write uid)")
        if (args.acceptance or args.drill or args.fleet_groups
                or args.serve is not None or args.bench_latency
                or args.chaos is not None or args.chaos_schedule
                or args.freeze):
            ap.error("--reads is its own drive; drop --acceptance/--drill/"
                     "--fleet-groups/--serve/--chaos/--freeze")
    if args.value_bytes is not None:
        if args.value_bytes < 1:
            ap.error("--value-bytes wants a positive byte cap")
        if args.values_ops < 1:
            ap.error("--values-ops wants a positive op count")
        if args.backend != "fast":
            ap.error("--value-bytes drives the fast batched backend "
                     "through the KVS facade (hermes_tpu/heap)")
        if args.value_words < 3:
            ap.error("--value-bytes needs --value-words >= 3 (words 0-1 "
                     "carry the write uid, word 2 the packed heap ref)")
        if (args.acceptance or args.drill or args.fleet_groups
                or args.serve is not None or args.bench_latency
                or args.reads is not None or args.chaos is not None
                or args.chaos_schedule or args.freeze):
            ap.error("--value-bytes is its own drive; drop --acceptance/"
                     "--drill/--fleet-groups/--serve/--reads/--chaos/"
                     "--freeze")
    chaos_on = args.chaos is not None or args.chaos_schedule
    if chaos_on:
        if args.backend not in ("fast", "fast-sharded"):
            ap.error("--chaos/--chaos-schedule drive the fast runtimes "
                     "(hermes_tpu.chaos); use --backend fast or "
                     "fast-sharded")
        if args.steps <= 0:
            ap.error("--chaos needs a bounded run (--steps > 0)")
        if args.freeze:
            ap.error("--chaos and --freeze are mutually exclusive (put "
                     "freeze windows in the schedule instead)")

    from hermes_tpu import stats as stats_lib
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.runtime import FastRuntime, Runtime

    if args.acceptance:
        from hermes_tpu import acceptance

        # 'all' is the JUDGED set 1-5 (round-5 advice #3: the baseline
        # gate's aggregate exit code must not fail on a non-judged variant)
        which = ([1, 2, 3, 4, 5] if args.acceptance == "all"
                 else [1, 2, "2r", 3, "3c", 4, 5]
                 if args.acceptance == "all+variants"
                 else [args.acceptance if args.acceptance in ("2r", "3c")
                       else int(args.acceptance)])
        rc = 0
        for n in which:
            counters, verdict = acceptance.run_config(
                n, scale=args.scale, pipeline_depth=args.pipeline_depth,
                log=lambda s: print(s, file=sys.stderr)
            )
            ok = counters["drained"] and (verdict is None or verdict.ok)
            print(f"config {n}: {'PASS' if ok else 'FAIL'} {counters}")
            rc |= 0 if ok else 1
        return rc

    cfg = HermesConfig(
        n_replicas=args.replicas,
        n_keys=args.keys,
        value_words=args.value_words,
        n_sessions=args.sessions,
        replay_slots=args.replay_slots,
        ops_per_session=args.ops_per_session,
        lane_budget_cfg=args.lane_budget,
        wrap_stream=args.wrap_stream,
        arb_mode=args.arb_mode,
        chain_writes=args.chain_writes,
        mega_round=args.mega_round,
        rmw_retries=args.rmw_retries,
        auto_rebase=not args.no_auto_rebase,
        pipeline_depth=args.pipeline_depth,
        donate_state=not args.no_donate,
        op_timeout_rounds=args.op_timeout,
        op_retry_limit=args.op_retries,
        min_healthy_for_writes=args.degraded_floor,
        trace_sample=args.trace_sample,
        wal_dir=args.wal_dir,
        wal_sync=args.wal_sync,
        workload=WorkloadConfig(
            distribution=args.distribution,
            zipf_theta=args.zipf_theta,
            seed=args.seed,
            **MIXES[args.workload],
        ),
    )

    # validate --freeze before the runtime, profiler trace, or any output
    # file exists: an argument error must not truncate a previous run's
    # metrics log or leave an unstopped profiler trace behind
    windows: dict = {}  # replica -> [(lo, hi)]
    for spec in args.freeze:
        try:
            r, lo, hi = (int(x) for x in spec.split(":"))
        except ValueError:
            ap.error(f"--freeze wants R:FROM:TO, got {spec!r}")
        if not 0 <= r < args.replicas:
            ap.error(f"--freeze replica {r} out of range (0..{args.replicas - 1})")
        if not 0 <= lo < hi:
            ap.error(f"--freeze window {lo}:{hi} must satisfy 0 <= FROM < TO")
        windows.setdefault(r, []).append((lo, hi))
    faults = []  # (step, replica, action) sorted by step, thaw before freeze
    for r, wins in windows.items():
        wins.sort()
        for (_, hi_a), (lo_b, _) in zip(wins, wins[1:]):
            if lo_b < hi_a:
                ap.error(f"--freeze windows for replica {r} overlap "
                         f"(..:{hi_a} vs {lo_b}:..)")
        for lo, hi in wins:
            faults += [(lo, r, "freeze"), (hi, r, "thaw")]
    # thaw before freeze at the same step so back-to-back windows
    # (R:10:20 + R:20:30) keep the replica continuously frozen
    faults.sort(key=lambda f: (f[0], f[2] != "thaw", f[1]))
    if faults:
        if args.steps <= 0:
            ap.error("--freeze needs a bounded run (--steps > 0)")
        if faults[-1][0] >= args.steps:
            ap.error(f"--freeze window ends at step {faults[-1][0]} but the "
                     f"run stops after --steps {args.steps}; the thaw would "
                     "never fire (want TO < --steps)")

    mesh = None
    if args.backend in ("sharded", "fast-sharded"):
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()[: cfg.n_replicas]
        if len(devs) < cfg.n_replicas:
            print(f"need {cfg.n_replicas} devices, have {len(devs)}", file=sys.stderr)
            return 2
        mesh = Mesh(np.array(devs), ("replica",))

    if args.fleet_groups:
        return _run_fleet(args, cfg)

    if args.serve is not None:
        return _run_serve(args, cfg)

    if args.reads is not None:
        return _run_reads(args, cfg)

    if args.value_bytes is not None:
        return _run_values(args, cfg)

    if args.bench_latency:
        return _run_bench_latency(args, cfg)

    if args.drill:
        return _run_drill(args, cfg, mesh)

    if args.backend in ("fast", "fast-sharded"):
        backend = "batched" if args.backend == "fast" else "sharded"
        # fast backends use the columnar recorder + native witness checker
        rt = FastRuntime(cfg, backend=backend, mesh=mesh,
                         record="array" if args.check else False)
        if cfg.use_wal:
            # round-22: the raw workload drive taps the WAL straight off
            # the harvest path (no KVS client layer, so no commit-gated
            # futures here — the serving paths get those; this drive
            # logs every committed write and group-commits in the
            # background, with a final sync before exit)
            from hermes_tpu.wal import GroupCommitWal

            rt.attach_wal(GroupCommitWal(cfg))
    else:
        if cfg.use_wal:
            ap.error("--wal-dir rides the fast engines' harvest path; "
                     f"the {args.backend!r} backend has no WAL tap "
                     "(use --backend fast or fast-sharded)")
        rt = Runtime(cfg, backend=args.backend, mesh=mesh, record=args.check)

    if args.profile:
        import jax

        jax.profiler.start_trace(args.profile)
    logger = None
    if args.metrics_jsonl:
        logger = stats_lib.JsonlLogger(open(args.metrics_jsonl, "w"))
    obs = None
    if args.metrics_out:
        from hermes_tpu.obs import Observability

        obs = rt.attach_obs(Observability(path=args.metrics_out,
                                          trace_steps=args.trace_steps))

    if args.detect is not None:
        from hermes_tpu.membership import MembershipService

        rt.attach_membership(MembershipService(cfg, confirm_steps=args.detect))

    meta_of = lambda: rt.fs.meta if hasattr(rt, "fs") else rt.rs.meta
    t0 = time.perf_counter()
    chaos_result = None
    try:
        if chaos_on:
            from hermes_tpu import chaos as chaos_lib

            if args.chaos_schedule:
                with open(args.chaos_schedule) as f:
                    sched = chaos_lib.Schedule.parse(f.read())
            else:
                sched = chaos_lib.Schedule.random(cfg, args.chaos, args.steps)

            def on_step(s):
                if args.report_every and (s + 1) % args.report_every == 0:
                    rec = stats_lib.summarize(
                        meta_of(), time.perf_counter() - t0, s + 1)
                    print(rec, file=sys.stderr)
                    if logger:
                        logger.log(rec)
                    if obs:
                        obs.interval(rec)

            runner = chaos_lib.ChaosRunner(rt, sched, on_step=on_step)
            chaos_result = runner.run(args.steps)
            print(f"chaos: {len(runner.log)} event(s) applied, "
                  f"lost_ops={chaos_result['lost_ops']}, "
                  f"drained={chaos_result['drained']}", file=sys.stderr)
        elif args.steps > 0:
            for s in range(args.steps):
                while faults and faults[0][0] <= s:
                    _, r, action = faults.pop(0)
                    getattr(rt, action)(r)
                rt.step_once()
                if args.report_every and (s + 1) % args.report_every == 0:
                    rec = stats_lib.summarize(meta_of(), time.perf_counter() - t0, s + 1)
                    print(rec, file=sys.stderr)
                    if logger:
                        logger.log(rec)
                    if obs:
                        obs.interval(rec)
        else:
            ok = rt.drain()
            if not ok:
                print("WARNING: did not drain", file=sys.stderr)
    finally:
        if args.profile:
            import jax

            jax.profiler.stop_trace()
        if getattr(rt, "wal", None) is not None:
            # round-22: force the final group commit out and stop the
            # flusher — the drive's last rounds must be on disk before
            # the summary line claims them committed
            rt.wal.sync()
            rec = rt.wal.stats()
            print(f"wal: {rec['records']} record(s), {rec['fsyncs']} "
                  f"fsync(s), {rec['bytes']} byte(s), "
                  f"{rec['segments']} segment(s), sync={rec['sync']}",
                  file=sys.stderr)
            rt.wal.close()
    wall = time.perf_counter() - t0

    # one Meta readback: the run-log summary carries the raw histograms
    # (obs_report.py renders them); the stdout/legacy lines stay scalar-only
    rec = stats_lib.summarize(meta_of(), wall, rt.step_idx,
                              hists=obs is not None)
    if obs:
        obs.summary(rec)
        # registry totals (round-8 overlap counters host_work_s /
        # device_wait_s + the pipeline_depth gauge, transport counters, …)
        obs.registry_snapshot()
        rec = {k: v for k, v in rec.items()
               if k not in ("lat_hist", "qwait_hist")}
    print(rec)
    if logger:
        logger.log(rec)

    if args.profile_out:
        from hermes_tpu.obs import profile as prof_mod

        eng = "batched" if args.backend == "fast" else "sharded"
        prof_mod.export_profile(args.profile_out, [prof_mod.round_record(
            prof_mod.op_census(cfg, eng, mesh), backend=eng)])

    if args.analyze:
        from hermes_tpu import analysis as ana

        eng = "batched" if args.backend == "fast" else "sharded"
        reports = [ana.analyze_program(ana.trace_program(cfg, eng,
                                                         mesh=mesh))]
        ana.export_findings(args.analyze, reports)
        n_gating = sum(1 for r in reports for f in r["findings"]
                       if f.severity in ana.GATING)
        print(f"analysis: {n_gating} gating finding(s) -> {args.analyze}")

    try:
        if args.check:
            v = rt.check(max_keys=args.check_keys)
            print(f"linearizability: {'PASS' if v.ok else 'FAIL'} ({v.keys_checked} keys)")
            if not v.ok:
                for f in v.failures[:5]:
                    print("  ", f.reason[:200])
                return 1
        return 0
    finally:
        if obs:
            obs.close()


if __name__ == "__main__":
    sys.exit(main())
