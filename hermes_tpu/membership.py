"""Host-side membership service (SURVEY.md §1 L4, §5.3).

Hermes delegates membership to an external lease-based service: replicas
hold leases; a replica that stops heartbeating is suspected, removed from
the live set with an epoch bump, and pending writes re-evaluate their ack
quorum against the shrunken mask (unblocking them); a removed replica must
not serve reads (it self-fences — in this rebuild a frozen/fenced replica
makes no transitions at all, core/state.Ctl).

The rebuild keeps the service on the host, exactly where the reference
keeps it (outside the data plane).  Detection input is in-band: every INV
block carries an ``alive`` heartbeat bit; each replica records
``meta.last_seen[peer]`` (core/phases.apply_inv) and the service reads
those clocks off the device every ``poll_interval`` steps.

Suspicion rule: replica r is suspected when NO live peer has heard from it
for more than ``lease_steps`` steps.  Using the max over live observers
keeps one partitioned observer from ejecting a healthy replica.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import numpy as np

from hermes_tpu.config import HermesConfig


@dataclasses.dataclass
class MembershipEvent:
    step: int
    kind: str  # 'remove' | 'join'
    replica: int
    live_mask: int


class MembershipService:
    """Polls heartbeat clocks and drives remove (and scripted join) through
    a Runtime.  Attach with ``Runtime.attach_membership`` or call ``poll``
    manually between steps."""

    def __init__(self, cfg: HermesConfig, poll_interval: int = 1):
        self.cfg = cfg
        self.poll_interval = poll_interval
        self.events: List[MembershipEvent] = []

    def poll(self, rt) -> Optional[MembershipEvent]:
        if rt.step_idx % self.poll_interval != 0:
            return None
        live = int(rt.live[0])
        state = getattr(rt, "fs", None) or rt.rs  # FastRuntime | Runtime
        last_seen = np.asarray(jax.device_get(state.meta.last_seen))  # (R_obs, R_src)
        evt = None
        for r in range(self.cfg.n_replicas):
            if not (live >> r) & 1:
                continue
            observers = [
                i
                for i in range(self.cfg.n_replicas)
                if i != r and (live >> i) & 1 and not rt.frozen[i]
            ]
            if not observers:
                continue
            freshest = max(int(last_seen[i, r]) for i in observers)
            if rt.step_idx - freshest > self.cfg.lease_steps:
                # suspect precedes remove on the obs timeline: the remove
                # event records the membership outcome, this one records the
                # detector's evidence (how stale the freshest observation was)
                trace = getattr(rt, "_trace", None)
                if trace is not None:
                    trace("suspect", replica=r,
                          stale_steps=rt.step_idx - freshest)
                rt.remove(r)
                live = int(rt.live[0])
                evt = MembershipEvent(rt.step_idx, "remove", r, live)
                self.events.append(evt)
        return evt

    def note_join(self, rt, replica: int) -> None:
        self.events.append(
            MembershipEvent(rt.step_idx, "join", replica, int(rt.live[0]))
        )
