"""Host-side membership service (SURVEY.md §1 L4, §5.3).

Hermes delegates membership to an external lease-based service: replicas
hold leases; a replica that stops heartbeating is suspected, removed from
the live set with an epoch bump, and pending writes re-evaluate their ack
quorum against the shrunken mask (unblocking them); a removed replica must
not serve reads (it self-fences — in this rebuild a frozen/fenced replica
makes no transitions at all, core/state.Ctl).

The rebuild keeps the service on the host, exactly where the reference
keeps it (outside the data plane).  Detection input is in-band: every INV
block carries an ``alive`` heartbeat bit; each replica records
``meta.last_seen[peer]`` (core/phases.apply_inv / faststep._apply_inv) and
the fast round additionally folds the staleness reduction into the round
itself (``Meta.suspect_age`` — per-peer heartbeat age, round-9).

Suspicion is a STATE MACHINE with hysteresis (round-9, Chandra–Toueg-style
unreliable detector): replica r enters ``suspect`` when NO live unfrozen
peer has heard from it for more than ``lease_steps`` rounds (max over live
observers, so one partitioned observer cannot eject a healthy replica);
it must STAY stale for ``confirm_steps`` further rounds before the
``remove`` fires; a fresh heartbeat inside the confirm window cancels the
suspicion (``suspect_clear`` on the obs timeline — spontaneous recovery).
``confirm_steps=0`` (default) removes at first suspicion, the pre-round-9
behavior.  ``skew[r]`` biases the observed age of replica r (heartbeat
clock-skew injection — chaos.schedule drives it to exercise the
hysteresis without real faults).

Detector input transport — the pipelining caveat: ``poll`` consumes the
runtime's HARVESTED age columns (``rt.harvested_ages``, fed by
``FastRuntime.harvest_comp`` off the completion readback that is already
overlapped with device execution) whenever they are fresh, so on the fast
runtimes an attached service costs the dispatch path NOTHING — zero
synchronous ``device_get`` (the ``membership_fetch`` trace event counts
the fallback fetches; a pipelined run must show none).  Ages observed this
way are up to ``pipeline_depth - 1`` rounds stale — detection latency
grows by at most the ring depth, never the dispatch.  On the phases
``Runtime`` (sim/tcp engines) there is no harvest ring: every poll is a
synchronous ``(R, R)`` ``last_seen`` fetch, so raise ``poll_interval``
there if the fetch shows up in profiles.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import numpy as np

from hermes_tpu.config import HermesConfig


@dataclasses.dataclass
class MembershipEvent:
    step: int
    # 'remove' (detector-driven) | 'join' | 'shrink' (administrative
    # removal, round-10 elastic resize); suspect/suspect_clear are
    # timeline-only
    kind: str
    replica: int
    live_mask: int
    # fleet group this event belongs to (round-13, hermes_tpu/fleet):
    # -1 = single-group deployment.  Membership state is GROUP-SCOPED —
    # one service instance per group, over that group's replicas only —
    # and the label keeps a merged fleet membership log attributable.
    group: int = -1


class MembershipService:
    """Polls heartbeat ages and drives the suspect → confirm → remove
    machine (and scripted join bookkeeping) through a Runtime.  Attach with
    ``Runtime.attach_membership`` or call ``poll`` manually between steps."""

    def __init__(self, cfg: HermesConfig, poll_interval: int = 1,
                 confirm_steps: int = 0, group: int = -1):
        if confirm_steps < 0:
            raise ValueError("confirm_steps must be >= 0")
        self.cfg = cfg
        self.poll_interval = poll_interval
        self.confirm_steps = confirm_steps
        # fleet group this service watches (round-13): a label only —
        # the service itself is group-scoped by construction (it polls
        # ONE runtime's heartbeat ages and drives ONE live mask)
        self.group = group
        self.events: List[MembershipEvent] = []
        # replica -> step the current suspicion began (cleared on recovery)
        self.suspects: Dict[int, int] = {}
        # replica -> step it (re)joined: ages observed at-or-shortly-after
        # a join were computed from pre-join rounds (the harvest lags the
        # dispatch by the ring depth) where the replica was legitimately
        # dead — a full lease window of POST-join observation must elapse
        # before those ages can ground a new suspicion, or every rejoin
        # would be instantly re-ejected (with confirm_steps=0) or burn a
        # spurious suspect/clear pair (with a window)
        self._joined_at: Dict[int, int] = {}
        # injected heartbeat clock-skew, added to every observed age of the
        # replica (chaos.schedule's hb_skew events)
        self.skew = np.zeros(cfg.n_replicas, np.int64)
        # partition oracle (round-11, chaos/net.py): directed heartbeat
        # edges severed by an adversarial partition.  The FAST engines have
        # no wire to cut — their round is fused on one device/mesh — so a
        # ``partition`` schedule verb models exactly the detector-visible
        # consequence: observer ``dst`` stops hearing replica ``src`` from
        # the sever step on, and the observed age is floored at
        # ``step - since`` for that edge.  The sim/tcp engines never need
        # this (FaultingTransport starves last_seen organically); directed
        # edges make partitions asymmetric, and the min-over-observers rule
        # below already guarantees ONE severed observer cannot eject a
        # replica the rest of the cluster hears fine.
        self._severed: Dict[tuple, int] = {}  # (src, dst) -> since step

    # -- partition oracle (round-11) ----------------------------------------

    def sever(self, src: int, dst: int, at_step: int) -> None:
        """Cut the directed heartbeat edge src -> dst (dst = -1: src's
        heartbeats reach NO observer — full outbound isolation)."""
        dsts = range(self.cfg.n_replicas) if dst < 0 else (dst,)
        for d in dsts:
            if d != src:
                self._severed.setdefault((src, d), at_step)

    def restore(self, src: int = -1, dst: int = -1) -> int:
        """Re-connect matching severed edges (-1 = wildcard); returns the
        number restored."""
        victims = [e for e in self._severed
                   if (src < 0 or e[0] == src) and (dst < 0 or e[1] == dst)]
        for e in victims:
            del self._severed[e]
        return len(victims)

    def heal_partitions(self) -> int:
        n = len(self._severed)
        self._severed.clear()
        return n

    def severed_edges(self) -> list:
        """Active severed (src, dst) edges — diagnostics surface."""
        return sorted(self._severed)

    # -- detector input ------------------------------------------------------

    def _ages(self, rt):
        """(at_step, (R_obs, R_src) age matrix).  Prefers the runtime's
        harvested device-side ``suspect_age`` columns (no fetch); falls back
        to a synchronous ``last_seen`` fetch — counted on the obs timeline
        as ``membership_fetch`` so pipelined runs can regression-test that
        the dispatch path stays fetch-free."""
        cached = getattr(rt, "harvested_ages", None)
        if cached is not None:
            at_step, ages = cached
            # fresh = observed within one poll interval + the ring depth of
            # the current step (older than that means harvesting stopped —
            # e.g. fetch_completions was flipped off — so fetch)
            depth = getattr(rt.cfg, "pipeline_depth", 1)
            if rt.step_idx - at_step <= self.poll_interval + depth:
                return at_step, ages
        state = getattr(rt, "fs", None) or rt.rs  # FastRuntime | Runtime
        trace = getattr(rt, "_trace", None)
        if trace is not None:
            trace("membership_fetch")
        last_seen = np.asarray(jax.device_get(state.meta.last_seen))
        return rt.step_idx, np.maximum(rt.step_idx - last_seen, 0)

    # -- the suspicion state machine ----------------------------------------

    def poll(self, rt) -> Optional[MembershipEvent]:
        if rt.step_idx % self.poll_interval != 0:
            return None
        at_step, ages = self._ages(rt)
        return self._drive(rt, at_step, ages)

    def _drive(self, rt, step: int, ages) -> Optional[MembershipEvent]:
        live = int(rt.live[0])
        trace = getattr(rt, "_trace", None)
        evt = None
        for r in range(self.cfg.n_replicas):
            if not (live >> r) & 1:
                self.suspects.pop(r, None)
                continue
            observers = [
                i
                for i in range(self.cfg.n_replicas)
                if i != r and (live >> i) & 1 and not rt.frozen[i]
            ]
            if not observers:
                continue
            ja = self._joined_at.get(r)
            if ja is not None and step - ja <= self.cfg.lease_steps:
                # join grace: these ages predate (or barely postdate) the
                # rejoin — no post-join lease window has been observed yet
                continue
            # freshest observation of r = max last_seen over observers
            # = MIN age over observers; a severed edge r -> i floors
            # observer i's view at the partition age (round-11 oracle)
            def _age(i: int) -> int:
                a = int(ages[i, r])
                since = self._severed.get((r, i))
                if since is not None:
                    a = max(a, step - since)
                return a

            age = int(min(_age(i) for i in observers))
            age += int(self.skew[r])
            if age <= self.cfg.lease_steps:
                if self.suspects.pop(r, None) is not None:
                    # spontaneous recovery inside the confirm window: the
                    # suspicion cancels instead of ejecting a healthy
                    # replica.  Timeline-only (self.events stays the
                    # remove/join membership log callers consume).
                    if trace is not None:
                        trace("suspect_clear", replica=r, stale_steps=age)
                continue
            since = self.suspects.get(r)
            if since is None:
                self.suspects[r] = since = step
                # suspect precedes remove on the obs timeline: the remove
                # event records the membership outcome, this one records the
                # detector's evidence (how stale the freshest observation was)
                if trace is not None:
                    trace("suspect", replica=r, stale_steps=age)
            if step - since >= self.confirm_steps:
                del self.suspects[r]
                rt.remove(r)
                live = int(rt.live[0])
                evt = MembershipEvent(rt.step_idx, "remove", r, live,
                                      group=self.group)
                self.events.append(evt)
        return evt

    def note_join(self, rt, replica: int) -> None:
        self.suspects.pop(replica, None)
        self._joined_at[replica] = rt.step_idx
        self.events.append(
            MembershipEvent(rt.step_idx, "join", replica, int(rt.live[0]),
                            group=self.group)
        )

    def note_shrink(self, rt, replica: int) -> None:
        """Administrative removal (round-10 elastic resize: the runtime's
        ``shrink`` fenced + removed the replica deliberately).  Clears any
        live suspicion and logs the event as ``shrink`` so the membership
        log attributes the removal to the operator, not the detector."""
        self.suspects.pop(replica, None)
        self._joined_at.pop(replica, None)
        self.events.append(
            MembershipEvent(rt.step_idx, "shrink", replica, int(rt.live[0]),
                            group=self.group)
        )
