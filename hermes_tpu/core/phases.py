"""The Hermes protocol phases as pure, per-replica JAX functions.

The reference's hot loop (SURVEY.md §3.1, function names per BASELINE.json:5)
is per-op:

    coordinator: broadcast_inv() -> poll_acks() -> broadcast_val()
    follower:    apply_inv() / apply_val()

Here the same state machine runs bulk-synchronously: each phase is
data-parallel over every session / message lane / key at once, and the
network rounds between phases are collectives supplied by the transport
backend.  One protocol step is:

    coordinate  -> [INV broadcast]  -> apply_inv  -> [ACK all_to_all]
                -> collect_acks     -> [VAL broadcast] -> apply_val

so an uncontended write commits in a single step (commit latency = one
INV/ACK round trip, the protocol's headline property, SURVEY.md §3.1).

Every function here takes per-replica state WITHOUT a leading replica axis;
replica batching is done outside with vmap (single-device simulation) or
shard_map (one chip = one replica over the ICI mesh, BASELINE.json:5).

Design notes (SURVEY.md §7 "hard parts"):
  * Variable-length message batches live in fixed lanes: lane l < S is
    session l's pending update, lanes S..S+RS are replay slots; ``valid``
    masks dead lanes.  A pending update re-broadcasts its INV every step
    until committed — same-ts INVs are idempotent, which makes message loss,
    duplication, and replica stalls all collapse into the same code path.
  * Contended keys (Zipfian, BASELINE.json:9): the per-key winner among all
    INVs of a step is the lexicographic-max timestamp, found with a two-pass
    scatter-max (ver, then fc among max-ver), not last-write-wins.
  * RMW aborts (BASELINE.json:8): a pending RMW aborts iff a conflicting
    higher-ts update supersedes it.  Plain writes carry a higher tie-break
    flag than RMWs (types.FLAG_*), so concurrent plain writes always beat
    concurrent RMWs from the same base version and an aborted RMW's value can
    never become readable anywhere.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t
from hermes_tpu.core.timestamps import make_fc, ts_eq, ts_gt

I32_MIN = jnp.iinfo(jnp.int32).min


def _set(arr, idx, val, mask):
    """Masked scatter-set: rows where ``mask`` is False are dropped (sentinel
    out-of-bounds index + mode='drop')."""
    sent = arr.shape[0]
    return arr.at[jnp.where(mask, idx, sent)].set(val, mode="drop")


def _write_value(cfg: HermesConfig, my_cid, sess_idx, op_idx):
    """Unique write values, derived on device: words 0/1 are the unique id
    (lo = op_idx*S + session, hi = replica), remaining words a cheap mix so
    value payloads are non-trivial.  Uniqueness is what makes the
    linearizability check tractable (SURVEY.md §4); this formula stays unique
    under ``wrap_stream`` too, where op_idx grows past ops_per_session."""
    lo = op_idx * cfg.n_sessions + sess_idx
    hi = jnp.broadcast_to(my_cid, lo.shape)
    words = [lo, hi]
    for j in range(2, cfg.value_words):
        words.append(lo * jnp.int32(-1640531527) + jnp.int32(j))  # 2654435761 mod 2^32
    return jnp.stack(words, axis=-1).astype(jnp.int32)


class CoordinateOut(NamedTuple):
    table: st.KeyTable
    sess: st.Sessions
    replay: st.ReplaySlots
    out_inv: st.Invs
    comp: st.Completions


def coordinate(
    cfg: HermesConfig,
    ctl: st.Ctl,
    table: st.KeyTable,
    sess: st.Sessions,
    replay: st.ReplaySlots,
    stream: st.OpStream,
) -> CoordinateOut:
    """Session op intake + local reads + update issue + replay scan.

    Covers the reference's worker-loop front half (SURVEY.md §3.1 L5/L6->L3):
    idle sessions load their next op; reads complete locally iff the key is
    Valid (Hermes's local-read property, §3.2); updates issue iff the key is
    Valid — the issuing replica applies the new value locally, moves the key
    to Write state, and opens an INV lane.  Also runs the replay scan
    (§3.4): keys Invalid for more than ``replay_age`` steps are snapshotted
    into replay slots and re-driven with their original timestamp.
    """
    S, K, G = cfg.n_sessions, cfg.n_keys, cfg.ops_per_session
    RS = cfg.replay_slots
    idx = jnp.arange(S, dtype=jnp.int32)

    # --- 1) op intake -----------------------------------------------------
    if cfg.wrap_stream:
        can_load = (sess.status == t.S_IDLE) & ~ctl.frozen
        g = sess.op_idx % G
    else:
        can_load = (sess.status == t.S_IDLE) & (sess.op_idx < G) & ~ctl.frozen
        g = jnp.clip(sess.op_idx, 0, G - 1)
    new_op = stream.op[idx, g]
    new_key = stream.key[idx, g]
    new_val = _write_value(cfg, ctl.my_cid, idx, sess.op_idx)
    if stream.uval is not None:
        # client-supplied payload (hermes_tpu/kvs.py): words 2.. carry the
        # user value; words 0-1 keep the derived unique write id.
        new_val = jnp.concatenate([new_val[:, :2], stream.uval[idx, g]], axis=-1)

    is_nop = can_load & (new_op == t.OP_NOP)
    status = jnp.where(
        can_load,
        jnp.where(
            new_op == t.OP_READ,
            t.S_READ,
            jnp.where(new_op == t.OP_NOP, t.S_IDLE, t.S_ISSUE),
        ),
        sess.status,
    )
    if not cfg.wrap_stream:
        status = jnp.where((status == t.S_IDLE) & (sess.op_idx >= G), t.S_DONE, status)
    sess = sess._replace(
        status=status,
        op=jnp.where(can_load, new_op, sess.op),
        key=jnp.where(can_load, new_key, sess.key),
        val=jnp.where(can_load[:, None], new_val, sess.val),
        invoke_step=jnp.where(can_load, ctl.step, sess.invoke_step),
        op_idx=jnp.where(is_nop, sess.op_idx + 1, sess.op_idx),
    )

    # --- 2) local reads ---------------------------------------------------
    kstate = table.state[sess.key]
    read_done = (sess.status == t.S_READ) & (kstate == t.VALID) & ~ctl.frozen
    rd_val = table.val[sess.key]
    sess = sess._replace(
        status=jnp.where(read_done, t.S_IDLE, sess.status),
        op_idx=jnp.where(read_done, sess.op_idx + 1, sess.op_idx),
        rd_val=jnp.where(read_done[:, None], rd_val, sess.rd_val),
    )

    # --- 3) update issue (put / rmw), with local same-key arbitration -----
    kstate = table.state[sess.key]  # re-read: reads don't change it, but keep exact
    want = (sess.status == t.S_ISSUE) & (kstate == t.VALID) & ~ctl.frozen
    arb = _minscatter(K, sess.key, idx, want)
    win = want & (arb[sess.key] == idx)

    new_ver = table.ver[sess.key] + 1
    flag = jnp.where(sess.op == t.OP_WRITE, t.FLAG_WRITE, t.FLAG_RMW)
    new_fc = jnp.broadcast_to(make_fc(flag, ctl.my_cid), (S,)).astype(jnp.int32)
    old_val = table.val[sess.key]  # RMW read-part observes the pre-issue value

    table = table._replace(
        state=_set(table.state, sess.key, jnp.full((S,), t.WRITE, jnp.int32), win),
        ver=_set(table.ver, sess.key, new_ver, win),
        fc=_set(table.fc, sess.key, new_fc, win),
        val=_set(table.val, sess.key, sess.val, win),
        inv_step=_set(table.inv_step, sess.key, jnp.broadcast_to(ctl.step, (S,)), win),
    )
    sess = sess._replace(
        status=jnp.where(win, t.S_INFL, sess.status),
        ver=jnp.where(win, new_ver, sess.ver),
        fc=jnp.where(win, new_fc, sess.fc),
        acks=jnp.where(win, 0, sess.acks),
        superseded=jnp.where(win, False, sess.superseded),
        rd_val=jnp.where((win & (sess.op == t.OP_RMW))[:, None], old_val, sess.rd_val),
    )

    # --- 4) replay scan (SURVEY.md §3.4) ----------------------------------
    stuck = ((table.state == t.INVALID) | (table.state == t.TRANS)) & (
        ctl.step - table.inv_step > cfg.replay_age
    )
    cand = jnp.nonzero(stuck, size=RS, fill_value=K)[0].astype(jnp.int32)
    fslot = jnp.nonzero(~replay.active, size=RS, fill_value=RS)[0].astype(jnp.int32)
    assign = (cand < K) & (fslot < RS) & ~ctl.frozen
    replay = replay._replace(
        active=_set(replay.active, fslot, jnp.ones((RS,), jnp.bool_), assign),
        key=_set(replay.key, fslot, cand, assign),
        ver=_set(replay.ver, fslot, table.ver[jnp.clip(cand, 0, K - 1)], assign),
        fc=_set(replay.fc, fslot, table.fc[jnp.clip(cand, 0, K - 1)], assign),
        val=_set(replay.val, fslot, table.val[jnp.clip(cand, 0, K - 1)], assign),
        acks=_set(replay.acks, fslot, jnp.zeros((RS,), jnp.int32), assign),
    )
    table = table._replace(
        state=_set(table.state, cand, jnp.full((RS,), t.REPLAY, jnp.int32), assign)
    )

    # --- 5) outbound INV lanes (sessions ++ replay slots) -----------------
    infl = sess.status == t.S_INFL
    out_inv = st.Invs(
        valid=jnp.concatenate([infl, replay.active]) & ~ctl.frozen,
        key=jnp.concatenate([sess.key, replay.key]),
        ver=jnp.concatenate([sess.ver, replay.ver]),
        fc=jnp.concatenate([sess.fc, replay.fc]),
        epoch=jnp.broadcast_to(ctl.epoch, (cfg.n_lanes,)).astype(jnp.int32),
        val=jnp.concatenate([sess.val, replay.val], axis=0),
        alive=~ctl.frozen,
    )

    # --- completions (reads + nops) ---------------------------------------
    code = jnp.where(read_done, t.C_READ, jnp.where(is_nop, t.C_NOP, t.C_NONE))
    comp = st.Completions(
        code=code.astype(jnp.int32),
        key=sess.key,
        wval=sess.val,
        rval=sess.rd_val,
        ver=sess.ver,
        fc=sess.fc,
        invoke_step=sess.invoke_step,
        commit_step=jnp.broadcast_to(ctl.step, (S,)).astype(jnp.int32),
    )
    return CoordinateOut(table, sess, replay, out_inv, comp)


def _minscatter(size, idx, val, mask):
    return jnp.full((size,), jnp.iinfo(jnp.int32).max, jnp.int32).at[
        jnp.where(mask, idx, size)
    ].min(val, mode="drop")


class ApplyInvOut(NamedTuple):
    table: st.KeyTable
    sess: st.Sessions
    meta: st.Meta
    out_ack: st.Acks
    comp: st.Completions


def apply_inv(
    cfg: HermesConfig,
    ctl: st.Ctl,
    table: st.KeyTable,
    sess: st.Sessions,
    meta: st.Meta,
    in_inv: st.Invs,
) -> ApplyInvOut:
    """The follower-side ``apply_inv()`` handler (BASELINE.json:5) over a full
    (R, L) inbound INV block: if ts_in > ts_local apply value+ts and move the
    key to Invalid (Trans if a local write was pending), and ALWAYS ack —
    same-ts duplicates (rebroadcast, replay) are acked without effect, the
    idempotence the recovery path relies on (SURVEY.md §3.4).

    Also detects supersession of local pending updates: a pending RMW whose
    key timestamp moved is aborted here (YCSB-F conflict rule,
    BASELINE.json:8); a pending plain write just marks ``superseded`` and
    keeps gathering acks (the Trans path).
    """
    K, S = cfg.n_keys, cfg.n_sessions
    R, L = in_inv.valid.shape

    # PRE-apply commit detection (round-9; surfaced by the chaos net-drop
    # schedules): a pending update whose key is ALREADY VALID at its own ts
    # was finished by a replayer (VALID at ts => a full live quorum acked
    # it — any replica can complete a write whose coordinator looks dead,
    # SURVEY.md §3.4) while this coordinator's acks were lost.  It must
    # complete as COMMITTED — and must NOT be aborted below when a newer
    # INV in this very block supersedes the key (committed-then-superseded
    # is a normal history; superseded-before-commit is the abort case).
    # Evaluated against the PRE-apply table: the VAL that validated the key
    # landed at the end of an earlier step, strictly before any superseding
    # INV processed here.  Residual limit, as in the real protocol: if that
    # VAL itself was lost, a late nack is indistinguishable from a genuine
    # pre-commit conflict — the membership remove/rejoin (crash semantics)
    # owns that case.
    pre_infl = sess.status == t.S_INFL
    pre_committed = (
        pre_infl
        & (table.state[sess.key] == t.VALID)
        & ts_eq(sess.ver, sess.fc, table.ver[sess.key], table.fc[sess.key])
        & ~ctl.frozen
    )

    ok = in_inv.valid & (in_inv.epoch == ctl.epoch) & ~ctl.frozen
    key = in_inv.key.reshape(-1)
    ver = in_inv.ver.reshape(-1)
    fc = in_inv.fc.reshape(-1)
    val = in_inv.val.reshape(R * L, cfg.value_words)
    okf = ok.reshape(-1)

    # Two-pass lexicographic max over this step's INVs per key (contended-key
    # conflict resolution, SURVEY.md §7 hard part 4).
    bver = _maxscatter(K, key, ver, okf)
    vmax = okf & (ver == bver[jnp.clip(key, 0, K - 1)])
    bfc = _maxscatter(K, key, fc, vmax)
    winner = vmax & (fc == bfc[jnp.clip(key, 0, K - 1)])

    beats = winner & ts_gt(ver, fc, table.ver[jnp.clip(key, 0, K - 1)], table.fc[jnp.clip(key, 0, K - 1)])
    had_pending = (table.state == t.WRITE) | (table.state == t.TRANS)
    new_state = jnp.where(had_pending[jnp.clip(key, 0, K - 1)], t.TRANS, t.INVALID).astype(jnp.int32)

    table = table._replace(
        state=_set(table.state, key, new_state, beats),
        ver=_set(table.ver, key, ver, beats),
        fc=_set(table.fc, key, fc, beats),
        val=_set(table.val, key, val, beats),
        inv_step=_set(table.inv_step, key, jnp.broadcast_to(ctl.step, key.shape), beats),
    )

    # --- supersession of local pending updates ----------------------------
    infl = sess.status == t.S_INFL
    moved = infl & ~ts_eq(sess.ver, sess.fc, table.ver[sess.key], table.fc[sess.key]) & ~ctl.frozen
    abort = moved & (sess.op == t.OP_RMW) & ~pre_committed
    is_rmw = sess.op == t.OP_RMW
    done = abort | pre_committed
    sess = sess._replace(
        superseded=sess.superseded | (moved & (sess.op == t.OP_WRITE) & ~pre_committed),
        status=jnp.where(done, t.S_IDLE, sess.status),
        op_idx=jnp.where(done, sess.op_idx + 1, sess.op_idx),
    )
    lat = jnp.where(pre_committed, ctl.step - sess.invoke_step, 0)
    nbin = st.LAT_BINS
    meta = meta._replace(
        n_abort=meta.n_abort + jnp.sum(abort, dtype=jnp.int32),
        n_write=meta.n_write + jnp.sum(pre_committed & ~is_rmw, dtype=jnp.int32),
        n_rmw=meta.n_rmw + jnp.sum(pre_committed & is_rmw, dtype=jnp.int32),
        lat_sum=meta.lat_sum + jnp.sum(lat, dtype=jnp.int32),
        lat_cnt=meta.lat_cnt + jnp.sum(pre_committed, dtype=jnp.int32),
        lat_hist=meta.lat_hist.at[
            jnp.where(pre_committed, jnp.clip(lat, 0, nbin - 1), nbin)
        ].add(1, mode="drop"),
    )

    comp = st.Completions(
        code=jnp.where(
            abort, t.C_RMW_ABORT,
            jnp.where(pre_committed,
                      jnp.where(is_rmw, t.C_RMW, t.C_WRITE), t.C_NONE),
        ).astype(jnp.int32),
        key=sess.key,
        wval=sess.val,
        rval=sess.rd_val,
        ver=sess.ver,
        fc=sess.fc,
        invoke_step=sess.invoke_step,
        commit_step=jnp.broadcast_to(ctl.step, (S,)).astype(jnp.int32),
    )

    # --- ACK every valid INV (echo its ts back to its sender's lane) ------
    # The conflict flag: ok iff the INV's ts is the key's max after this
    # step's applies (losers/stale INVs get ok=False).  RMW coordinators
    # abort on a False ack (collect_acks); plain writes ignore it.
    ack_ok = ts_eq(
        ver, fc, table.ver[jnp.clip(key, 0, K - 1)], table.fc[jnp.clip(key, 0, K - 1)]
    ).reshape(R, L)
    out_ack = st.Acks(
        valid=ok & ~ctl.frozen,
        key=in_inv.key,
        ver=in_inv.ver,
        fc=in_inv.fc,
        ok=ack_ok,
        epoch=jnp.broadcast_to(ctl.epoch, (R, L)).astype(jnp.int32),
    )

    # --- heartbeats (host membership service input, SURVEY.md §5.3) -------
    meta = meta._replace(
        last_seen=jnp.where(in_inv.alive & ~ctl.frozen, ctl.step, meta.last_seen)
    )
    return ApplyInvOut(table, sess, meta, out_ack, comp)


def _maxscatter(size, idx, val, mask):
    return jnp.full((size,), I32_MIN, jnp.int32).at[
        jnp.where(mask, idx, size)
    ].max(val, mode="drop")


class CollectAcksOut(NamedTuple):
    table: st.KeyTable
    sess: st.Sessions
    replay: st.ReplaySlots
    meta: st.Meta
    out_val: st.Vals
    comp: st.Completions


def collect_acks(
    cfg: HermesConfig,
    ctl: st.Ctl,
    table: st.KeyTable,
    sess: st.Sessions,
    replay: st.ReplaySlots,
    meta: st.Meta,
    in_ack: st.Acks,
) -> CollectAcksOut:
    """The coordinator-side ``poll_acks()`` + commit + ``broadcast_val()``
    (BASELINE.json:5).  Inbound acks are lane-aligned: in_ack[q, l] is
    replica q's ack of MY lane l's INV.  A pending update commits when its
    gathered-ack bitmap covers every live replica — the write's linearization
    point (SURVEY.md §3.1).  Commits emit lane-aligned VALs.

    Replay lanes commit the same way; a replay slot whose key timestamp moved
    past the slot's (a newer write took over) is simply released — the newer
    writer's VAL will validate the key.
    """
    S, RS = cfg.n_sessions, cfg.replay_slots
    R = in_ack.valid.shape[0]
    full = jnp.int32((1 << R) - 1)
    bit = (jnp.int32(1) << jnp.arange(R, dtype=jnp.int32))[:, None]

    # An ack counts only if it answers THIS pending update: lane alignment
    # plus (key, ts) equality — ts alone is not unique across keys (e.g.
    # every first write by replica c has ts (1, c)), and a delayed/duplicated
    # ack from an earlier same-lane update must not satisfy a later quorum.
    ok = in_ack.valid & (in_ack.epoch == ctl.epoch) & ~ctl.frozen
    sess_ack = (
        ok[:, :S]
        & (in_ack.key[:, :S] == sess.key[None, :])
        & ts_eq(in_ack.ver[:, :S], in_ack.fc[:, :S], sess.ver[None, :], sess.fc[None, :])
    )
    rep_ack = (
        ok[:, S:]
        & (in_ack.key[:, S:] == replay.key[None, :])
        & ts_eq(in_ack.ver[:, S:], in_ack.fc[:, S:], replay.ver[None, :], replay.fc[None, :])
    )

    infl = sess.status == t.S_INFL
    acks = sess.acks | jnp.sum(jnp.where(sess_ack, bit, 0), axis=0).astype(jnp.int32)
    acks = jnp.where(infl, acks, sess.acks)
    covered = ((acks | ~ctl.live_mask) & full) == full
    # Conflict-nack: any matching ack with ok=False means some replica holds
    # a higher ts for this key — a pending RMW aborts (before it could
    # commit; nacks and full coverage in the same step resolve to abort).
    # (A replay-committed update never reaches this test: apply_inv
    # completes it as committed the step after its VAL lands — the
    # pre_committed path — so a late nack cannot turn an observed commit
    # into an abort.)
    nacked = jnp.any(sess_ack & ~in_ack.ok[:, :S], axis=0)
    abort = infl & nacked & (sess.op == t.OP_RMW) & ~ctl.frozen
    commit = infl & covered & ~ctl.frozen & ~abort

    # Key goes Valid only if this update still owns the key's timestamp.
    owns = ts_eq(sess.ver, sess.fc, table.ver[sess.key], table.fc[sess.key])
    table = table._replace(
        state=_set(table.state, sess.key, jnp.full((S,), t.VALID, jnp.int32), commit & owns)
    )

    # --- replay lanes ------------------------------------------------------
    racks = jnp.where(
        replay.active,
        replay.acks | jnp.sum(jnp.where(rep_ack, bit, 0), axis=0).astype(jnp.int32),
        replay.acks,
    )
    rcovered = ((racks | ~ctl.live_mask) & full) == full
    rowns = ts_eq(replay.ver, replay.fc, table.ver[replay.key], table.fc[replay.key])
    # A NACKED replay must never commit (round-9; surfaced by the chaos
    # net-drop schedules): ok=False on a matching replay ack proves a
    # strictly-higher ts exists at a live replica, so the replayed value —
    # possibly an ABORTED RMW's, stranded as this replica's stale table max
    # behind a sustained one-way drop — is obsolete.  Releasing without
    # committing is live: the higher ts cannot have committed without THIS
    # replica's ack, so its coordinator keeps re-broadcasting until it
    # lands here and re-validates the key (and a still-stuck key is
    # re-detected by the next replay scan with the by-then-current row).
    rnacked = jnp.any(rep_ack & ~in_ack.ok[:, S:], axis=0)
    rcommit = replay.active & rcovered & ~ctl.frozen & ~rnacked
    rsuperseded = replay.active & ~rowns & ~ctl.frozen
    rreleased = replay.active & rnacked & ~ctl.frozen
    table = table._replace(
        state=_set(
            table.state, replay.key, jnp.full((RS,), t.VALID, jnp.int32), rcommit & rowns
        )
    )
    replay = replay._replace(
        acks=racks,
        active=replay.active & ~rcommit & ~rsuperseded & ~rreleased,
    )

    # --- outbound VALs -----------------------------------------------------
    out_val = st.Vals(
        valid=jnp.concatenate([commit, rcommit & rowns]) & ~ctl.frozen,
        key=jnp.concatenate([sess.key, replay.key]),
        ver=jnp.concatenate([sess.ver, replay.ver]),
        fc=jnp.concatenate([sess.fc, replay.fc]),
        epoch=jnp.broadcast_to(ctl.epoch, (cfg.n_lanes,)).astype(jnp.int32),
    )

    # --- session completion + stats ---------------------------------------
    is_rmw = sess.op == t.OP_RMW
    code = jnp.where(
        abort,
        t.C_RMW_ABORT,
        jnp.where(commit, jnp.where(is_rmw, t.C_RMW, t.C_WRITE), t.C_NONE),
    )
    comp = st.Completions(
        code=code.astype(jnp.int32),
        key=sess.key,
        wval=sess.val,
        rval=sess.rd_val,
        ver=sess.ver,
        fc=sess.fc,
        invoke_step=sess.invoke_step,
        commit_step=jnp.broadcast_to(ctl.step, (S,)).astype(jnp.int32),
    )
    lat = jnp.where(commit, ctl.step - sess.invoke_step, 0)
    nbin = st.LAT_BINS
    meta = meta._replace(
        n_write=meta.n_write + jnp.sum(commit & ~is_rmw, dtype=jnp.int32),
        n_rmw=meta.n_rmw + jnp.sum(commit & is_rmw, dtype=jnp.int32),
        n_abort=meta.n_abort + jnp.sum(abort, dtype=jnp.int32),
        lat_sum=meta.lat_sum + jnp.sum(lat, dtype=jnp.int32),
        lat_cnt=meta.lat_cnt + jnp.sum(commit, dtype=jnp.int32),
        lat_hist=meta.lat_hist.at[jnp.where(commit, jnp.clip(lat, 0, nbin - 1), nbin)].add(
            1, mode="drop"
        ),
    )

    done = commit | abort
    sess = sess._replace(
        acks=acks,
        status=jnp.where(done, t.S_IDLE, sess.status),
        op_idx=jnp.where(done, sess.op_idx + 1, sess.op_idx),
    )
    return CollectAcksOut(table, sess, replay, meta, out_val, comp)


def apply_val(
    cfg: HermesConfig, ctl: st.Ctl, table: st.KeyTable, in_val: st.Vals
) -> st.KeyTable:
    """Follower-side VAL apply (SURVEY.md §3.1 tail): a VAL whose timestamp
    exactly matches the key's current timestamp validates the key.  Multiple
    same-key VALs in a step necessarily carry the same ts, so duplicate
    scatter rows write identical state."""
    K = cfg.n_keys
    key = in_val.key.reshape(-1)
    ok = (
        in_val.valid.reshape(-1)
        & (in_val.epoch.reshape(-1) == ctl.epoch)
        & ~ctl.frozen
        & ts_eq(
            in_val.ver.reshape(-1),
            in_val.fc.reshape(-1),
            table.ver[jnp.clip(key, 0, K - 1)],
            table.fc[jnp.clip(key, 0, K - 1)],
        )
    )
    return table._replace(
        state=_set(table.state, key, jnp.full(key.shape, t.VALID, jnp.int32), ok)
    )


def merge_completions(*comps: st.Completions) -> st.Completions:
    """At most one completion per session per step (phases complete disjoint
    session sets); later phases win where they completed something."""
    out = comps[0]
    for c in comps[1:]:
        m = c.code != t.C_NONE
        out = st.Completions(
            code=jnp.where(m, c.code, out.code),
            key=jnp.where(m, c.key, out.key),
            wval=jnp.where(m[..., None], c.wval, out.wval),
            rval=jnp.where(m[..., None], c.rval, out.rval),
            ver=jnp.where(m, c.ver, out.ver),
            fc=jnp.where(m, c.fc, out.fc),
            invoke_step=jnp.where(m, c.invoke_step, out.invoke_step),
            commit_step=jnp.where(m, c.commit_step, out.commit_step),
        )
    return out
