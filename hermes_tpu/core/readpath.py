"""Round-16: the local-read fast path (ROADMAP item 4).

Hermes' signature property (PAPER.md) is that reads are LOCAL: any
replica serves a Valid key from its own table with zero wire traffic.
After 15 rounds the rebuild still answered every client get through the
round's session lanes — one key per (replica, session) slot per round,
paying the full intake/arbiter/broadcast machinery for an op that needs
none of it.  This module is the read side built to the same standard as
the write round: ONE jitted dispatch answers a whole batch of keys
against the resident FastState.

Design rules (the op-diet discipline of rounds 2-15 applied to reads):

  * ZERO round impact — the read program is a separate dispatch that
    never touches the round chain, so the round census stays exactly
    12/4 sparse batched and 15/7 sharded (scripts/check_op_census.py
    gates it; the read program's own census is budgeted separately
    under OP_BUDGET.json's ``read_path``/``read_scan`` sections).
  * ONE sparse op for a whole multi-get — the bank row gather.  The
    row layout (core/faststep.py BANK_*) colocates [pts | sst | val],
    so the Valid check, the value words, AND the packed ts the RYW
    fence compares all come from that single gather; the byte->word
    unpack is the strided static form XLA fuses like a slice.
  * ZERO sparse ops for a range scan — contiguous rows move with one
    ``dynamic_slice`` (start traced, size static), which the cost model
    prices as dense work, not a launch-taxed sparse op.
  * Fixed compiled shapes — batches pad to power-of-two buckets
    (min ``MIN_BATCH``) so an arbitrary client batch size cannot
    trigger a recompile per call; padded rows read slot 0 and are
    masked out host-side.

The answer is (valid, val, pts) per key:

  ``valid``  the key's state is types.VALID *at this replica* — the
             ONLY state that may serve a local read (SURVEY.md §3.2).
             Invalid/Write/Trans/Replay keys are NOT answered here; the
             client layer (kvs.KVS.multi_get) falls back to the round
             path for them instead of returning possibly-stale bytes.
  ``val``    the row's value words (words 0-1 = the unique write id,
             the linearizability witness the checker keys on).
  ``pts``    the row's packed (ver<<10|fc) timestamp — what the
             read-your-writes fence compares against the session's own
             committed-write timestamps (kvs.KVS.multi_get).

Consistency argument (why a between-rounds host read of a VALID row is
linearizable): the table's winner-row scatter writes ts, state and
value TOGETHER at commit, and later rounds only ever replace a row with
a strictly higher-ts row (the vpts scatter-max arbitration).  The host
calls this program between round k-1's completion and round k's
harvest, so the observed row is exactly the state device reads of round
k would see — the read linearizes at the round-k read point
(inv = resp = 2k in the recorder's doubled clock), after commits(k-1)
and before commits(k).  A key whose write is still in flight is not
VALID and never answered locally, which is precisely the reference's
read-stall rule.  The stale-read checker (checker/linearizability.
stale_read) verifies the property on recorded histories instead of
assuming it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import types as t

# Smallest compiled batch bucket: batches pad up to powers of two from
# here, so a client mixing batch sizes compiles at most
# log2(n_keys/MIN_BATCH) + 1 programs per (cfg, backend).
MIN_BATCH = 256


def batch_bucket(n: int) -> int:
    """The compiled batch shape serving a client batch of ``n`` keys."""
    b = MIN_BATCH
    while b < n:
        b <<= 1
    return b


class ReadAnswer(NamedTuple):
    """Device answer of one read dispatch (host fetches all three)."""

    valid: jnp.ndarray  # (B,) bool — state == VALID at the serving replica
    val: jnp.ndarray    # (B, V) int32 value words (0-1 = write uid)
    pts: jnp.ndarray    # (B,) int32 packed row timestamp (RYW fence input)


def _answer_rows(rows8):
    """[pts | sst | val] byte rows -> ReadAnswer columns (dense
    slice+elementwise; XLA fuses it into the gather/slice producer)."""
    rows32 = fst._bank_to_i32(rows8)
    state = fst.sst_state(rows32[..., fst.BANK_SST])
    return ReadAnswer(
        valid=state == t.VALID,
        val=rows32[..., fst.BANK_VAL:],
        pts=rows32[..., fst.BANK_PTS],
    )


@functools.lru_cache(maxsize=None)
def build_multi_get(cfg: HermesConfig, backend: str = "batched",
                    batch: int = MIN_BATCH):
    """Compile the batched multi-get: ``fn(table, slots, replica) ->
    ReadAnswer`` for a fixed ``(batch,)`` slot vector.

    ``slots`` are dense key ids clamped to [0, n_keys) on device (an
    untrusted index must never gather out of bounds — the round-3 wire
    clamp rule applied to the read path); padded entries should carry
    slot 0 and be masked by the caller.  ``replica`` selects whose table
    copy serves: ignored in batched mode (the shard's replicas share
    the authoritative table — any live replica's local read observes
    it), row-offset ``replica * K`` in sharded mode (each shard owns
    its own rows; the caller picks a healthy replica).  ONE dynamic
    gather per dispatch — OP_BUDGET.json's ``read_path`` ceiling."""
    k = cfg.n_keys

    def mget(table: fst.FastTable, slots, replica):
        slots = jnp.clip(slots, 0, k - 1)
        if backend == "sharded":
            slots = replica * k + slots
        return _answer_rows(table.bank[slots])

    return jax.jit(mget, static_argnames=())


@functools.lru_cache(maxsize=None)
def build_scan(cfg: HermesConfig, backend: str = "batched",
               size: int = MIN_BATCH):
    """Compile the range scan: ``fn(table, lo, replica) -> ReadAnswer``
    over ``size`` contiguous slots starting at ``lo``.  Contiguous rows
    move with one ``dynamic_slice`` (start traced, extent static) — no
    sparse op at all (``read_scan`` budgets sparse_total = 0); jax
    clamps the start so a tail window reads the last ``size`` rows and
    the caller masks to the requested [lo, hi)."""
    k = cfg.n_keys

    def scan(table: fst.FastTable, lo, replica):
        start = lo if backend != "sharded" else replica * k + lo
        rows8 = jax.lax.dynamic_slice_in_dim(table.bank, start, size)
        return _answer_rows(rows8)

    return jax.jit(scan)


def read_census(cfg: HermesConfig, backend: str = "batched",
                batch: int = 4096) -> dict:
    """StableHLO op census of ONE read dispatch (multi-get) at ``batch``
    keys — the measurement half of the ``read_path`` budget
    (scripts/check_op_census.py), abstract lowering only."""
    from hermes_tpu.obs.profile import census_text

    table = _abstract_table(cfg, backend)
    fn = build_multi_get(cfg, backend, batch)
    txt = fn.lower(table, jax.ShapeDtypeStruct((batch,), jnp.int32),
                   jnp.int32(0)).as_text()
    return census_text(txt)


def scan_census(cfg: HermesConfig, backend: str = "batched",
                size: int = 4096) -> dict:
    """Census of one range-scan dispatch (``read_scan`` budget)."""
    from hermes_tpu.obs.profile import census_text

    table = _abstract_table(cfg, backend)
    fn = build_scan(cfg, backend, size)
    txt = fn.lower(table, jnp.int32(0), jnp.int32(0)).as_text()
    return census_text(txt)


def _abstract_table(cfg: HermesConfig, backend: str):
    n_local = cfg.n_replicas if backend == "sharded" else None
    fs = jax.eval_shape(lambda: fst.init_fast_state(cfg, n_local=n_local))
    return fs.table


class LocalReader:
    """Host-side driver of the read programs over one FastRuntime.

    Owns the per-(shape) compiled-program cache and the serving-replica
    choice: local reads may only be served by a HEALTHY replica (live
    and unfrozen — a fenced replica must not serve reads, the lease
    rule of SURVEY.md §5.3).  Returns numpy-backed ReadAnswers trimmed
    to the client batch; ``None`` when no replica may serve (callers
    fall back to the round path for everything)."""

    def __init__(self, rt):
        self.rt = rt
        self.cfg = rt.cfg
        self.backend = "sharded" if rt.backend == "sharded" else "batched"
        self.dispatches = 0
        self.keys_served = 0

    def _serving_replica(self) -> Optional[int]:
        healthy = self.rt.healthy_replicas()
        return healthy[0] if healthy else None

    def multi_get(self, slots) -> Optional[ReadAnswer]:
        """One read dispatch for an (n,) int array of dense slots."""
        import numpy as np

        rep = self._serving_replica()
        if rep is None:
            return None
        slots = np.asarray(slots, np.int32)
        n = slots.shape[0]
        b = batch_bucket(n)
        fn = build_multi_get(self.cfg, self.backend, b)
        padded = np.zeros(b, np.int32)
        padded[:n] = slots
        ans = fn(self.rt.fs.table, padded, jnp.int32(rep))
        ans = jax.device_get(ans)
        self.dispatches += 1
        self.keys_served += n
        return ReadAnswer(valid=np.asarray(ans.valid)[:n],
                          val=np.asarray(ans.val)[:n],
                          pts=np.asarray(ans.pts)[:n])

    def scan(self, lo: int, hi: int) -> Optional[ReadAnswer]:
        """One scan dispatch over dense slots [lo, hi)."""
        import numpy as np

        if not (0 <= lo < hi <= self.cfg.n_keys):
            raise ValueError(f"scan range [{lo}, {hi}) outside "
                             f"[0, {self.cfg.n_keys})")
        rep = self._serving_replica()
        if rep is None:
            return None
        n = hi - lo
        size = min(batch_bucket(n), self.cfg.n_keys)
        fn = build_scan(self.cfg, self.backend, size)
        # dynamic_slice clamps the start: issue the window so the
        # requested rows are always inside it, then trim host-side
        start = min(lo, self.cfg.n_keys - size)
        ans = jax.device_get(fn(self.rt.fs.table, jnp.int32(start),
                                jnp.int32(rep)))
        off = lo - start
        self.dispatches += 1
        self.keys_served += n
        return ReadAnswer(valid=np.asarray(ans.valid)[off:off + n],
                          val=np.asarray(ans.val)[off:off + n],
                          pts=np.asarray(ans.pts)[off:off + n])
