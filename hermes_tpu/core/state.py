"""State containers: key-state table, sessions, replay slots, message blocks.

Everything is a NamedTuple of fixed-shape int32 arrays (automatic pytrees),
struct-of-arrays so each column maps to a contiguous HBM buffer — the layout
BASELINE.json:5 prescribes ("an HBM-resident key-state table of millions of
in-flight writes").  The reference colocates per-key metadata with the value
in its MICA-style store (SURVEY.md §1 L2); here each metadata field is its own
column, which is what the vmapped kernel wants.

Shapes use the config aliases: K = n_keys, S = n_sessions, RS = replay_slots,
L = n_lanes = S + RS, V = value_words, R = n_replicas, G = ops_per_session.
All state is per-replica; replica-batched runs add a leading R axis via vmap,
sharded runs shard the same pytrees over the 'replica' mesh axis.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from hermes_tpu import config as config_lib
from hermes_tpu.core import types


class KeyTable(NamedTuple):
    """Per-key replicated KVS state (SURVEY.md §1 L2 + L3 metadata).

    ``state``    (K,)   one of types.VALID/INVALID/WRITE/TRANS/REPLAY
    ``ver``      (K,)   timestamp version word
    ``fc``       (K,)   timestamp tie-break word ((flag<<8)|cid)
    ``val``      (K,V)  value words; words 0-1 are the unique write id
    ``inv_step`` (K,)   step of the last timestamp change (drives the replay
                        age test, SURVEY.md §3.4)
    """

    state: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    val: jnp.ndarray
    inv_step: jnp.ndarray


class Sessions(NamedTuple):
    """Per-replica client sessions (reference: session arrays in worker.c,
    SURVEY.md §1 L5).  One in-flight op per session; the session index is also
    the outbound message lane for its pending update.

    ``status``   (S,)  types.S_*
    ``op``       (S,)  current op code (types.OP_*)
    ``op_idx``   (S,)  next index into the pre-generated op stream
    ``key``      (S,)  current op's key
    ``val``      (S,V) value being written (updates)
    ``ver``/``fc`` (S,) pending update timestamp
    ``acks``     (S,)  replica-bitmap of gathered ACKs for the pending update
    ``superseded`` (S,) pending write lost to a higher-ts INV (Trans path)
    ``rd_val``   (S,V) value observed by a read / RMW read-part
    ``invoke_step`` (S,) step the current op was loaded (history invocation time)
    """

    status: jnp.ndarray
    op: jnp.ndarray
    op_idx: jnp.ndarray
    key: jnp.ndarray
    val: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    acks: jnp.ndarray
    superseded: jnp.ndarray
    rd_val: jnp.ndarray
    invoke_step: jnp.ndarray


class ReplaySlots(NamedTuple):
    """In-flight replays (SURVEY.md §3.4): a key stuck Invalid past the age
    threshold is re-driven to Valid by re-broadcasting its last INV with the
    SAME timestamp and value (idempotent).  Value/ts are snapshotted into the
    slot so a concurrent higher-ts INV on the key cannot corrupt the replay.

    ``active`` (RS,)  slot in use
    ``key``    (RS,)
    ``ver``/``fc`` (RS,) the replayed timestamp
    ``val``    (RS,V)
    ``acks``   (RS,)  gathered-ack bitmap
    """

    active: jnp.ndarray
    key: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    val: jnp.ndarray
    acks: jnp.ndarray


class Invs(NamedTuple):
    """INV message block.  Outbound: (L, ...) one lane per session/replay
    slot.  Inbound (after broadcast): (R, L, ...).  INVs carry the value —
    the property that lets any replica finish a dead coordinator's write
    (SURVEY.md §3.4)."""

    valid: jnp.ndarray  # bool
    key: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    epoch: jnp.ndarray
    val: jnp.ndarray  # (..., V)
    alive: jnp.ndarray  # () outbound / (R,) inbound heartbeat bit (SURVEY.md §5.3)


class Acks(NamedTuple):
    """ACK block.  Outbound: (R, L) — ack[p, l] answers the INV received from
    replica p in lane l; routed back by all_to_all.  Inbound: (R, L) where
    [q, l] is q's ack of MY lane l.

    ``ok`` is the conflict flag: True iff the acked INV's ts is (still) the
    key's maximum at the follower after this step's applies.  RMW
    coordinators abort on any ok=False ack — that is how a conflicting
    higher-ts update that has not yet reached the RMW's coordinator is
    detected before commit (YCSB-F conflict rule, BASELINE.json:8); plain
    writes ignore the flag (they commit regardless and order by ts)."""

    valid: jnp.ndarray
    key: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    ok: jnp.ndarray
    epoch: jnp.ndarray


class Vals(NamedTuple):
    """VAL block, lane-aligned with the sender's INV lanes; broadcast."""

    valid: jnp.ndarray
    key: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    epoch: jnp.ndarray


class Completions(NamedTuple):
    """Per-step, per-session completion records — the raw material for the
    linearizability history (SURVEY.md §4) and the stats counters (§5.5).

    ``code`` (S,) types.C_*; C_NONE when the session completed nothing.
    ``key``  (S,)
    ``wval`` (S,V) value written (updates)
    ``rval`` (S,V) value read (reads / RMW read-part)
    ``ver``/``fc`` (S,) the update's protocol timestamp — the checker uses it
    as a linearization witness (checker/linearizability.py)
    ``invoke_step``/``commit_step`` (S,)
    """

    code: jnp.ndarray
    key: jnp.ndarray
    wval: jnp.ndarray
    rval: jnp.ndarray
    ver: jnp.ndarray
    fc: jnp.ndarray
    invoke_step: jnp.ndarray
    commit_step: jnp.ndarray


class Ctl(NamedTuple):
    """Per-replica, per-step control scalars (all int32 unless noted).

    ``step``      global step counter (bulk-synchronous "time"; real-time
                  order for the linearizability history, SURVEY.md §7 hard
                  part 1)
    ``my_cid``    this replica's id (the Lamport tie-break cid)
    ``epoch``     membership epoch; stale-epoch messages are dropped
                  (SURVEY.md §1 L4)
    ``live_mask`` bitmap of live replicas; the ack-quorum test is
                  (acks | ~live_mask) covers all (BASELINE.json:5)
    ``frozen``    bool; failure injection: a frozen replica makes no state
                  transitions and emits nothing (config 4, BASELINE.json:10).
                  Freezing also models lease self-fencing — a fenced replica
                  must not serve reads (SURVEY.md §5.3).
    """

    step: jnp.ndarray
    my_cid: jnp.ndarray
    epoch: jnp.ndarray
    live_mask: jnp.ndarray
    frozen: jnp.ndarray


class Meta(NamedTuple):
    """Per-replica observability state (SURVEY.md §5.5): heartbeat tracking
    for the host-side membership service plus committed-op counters and a
    commit-latency histogram (steps, clipped to the last bin).

    ``last_seen`` (R,) last step a valid heartbeat arrived from each peer
    ``suspect_age`` (R,) per-peer heartbeat staleness in rounds, derived ON
        DEVICE from ``last_seen`` at the end of every round (round-9 async
        failure detection): the host suspicion state machine
        (membership.MembershipService) consumes it off the completion
        harvest instead of issuing a synchronous ``last_seen`` fetch on the
        dispatch path.  The phases engine leaves it 0 (its MembershipService
        polls ``last_seen`` directly — the documented fallback).
    ``n_read`` / ``n_write`` / ``n_rmw`` / ``n_abort`` () completed-op counts
    ``lat_sum`` / ``lat_cnt`` () commit-latency accumulator (update ops)
    ``lat_hist`` (LAT_BINS,) latency histogram
    ``max_pts`` () high-water mark of issued packed timestamps — the
        faststep overflow guard (HermesConfig.max_key_versions): polled
        host-side so a key nearing the int32 packed-ts version limit fails
        loudly instead of silently corrupting the Lamport compare.  The
        phases engine has no packed ts and leaves it 0.

    Phase metrics (hermes_tpu/obs; gated by HermesConfig.phase_metrics,
    summed by the faststep engine — the phases engine leaves them 0):

    ``n_inv``       () INV slots broadcast (fanout = n_inv * live receivers)
    ``n_rebcast``   () re-broadcast slots (non-fresh: ack-waiting sessions on
        their backoff round + replay-slot re-INVs)
    ``n_nack``      () nack (conflict) verdicts observed on in-flight lanes
    ``n_retry``     () RMW retry-in-place transitions (abort-reason
        breakdown: n_abort = nacks that exhausted the retry budget)
    ``replay_peak`` () high-water mark of concurrently active replay slots
    ``qwait_sum`` / ``qwait_hist`` () / (LAT_BINS,) ACK quorum-wait: steps
        from INV issue (first broadcast) to commit — the network-bound slice
        of the commit latency (lat_* measures load->commit; the difference
        is intake/arbitration/backoff wait).  VAL latency is structurally 0
        in faststep — the commit decision and the winner's VALID row land in
        the issue round itself (see faststep._apply_commit).
    """

    last_seen: jnp.ndarray
    suspect_age: jnp.ndarray
    n_read: jnp.ndarray
    n_write: jnp.ndarray
    n_rmw: jnp.ndarray
    n_abort: jnp.ndarray
    lat_sum: jnp.ndarray
    lat_cnt: jnp.ndarray
    lat_hist: jnp.ndarray
    max_pts: jnp.ndarray
    n_inv: jnp.ndarray
    n_rebcast: jnp.ndarray
    n_nack: jnp.ndarray
    n_retry: jnp.ndarray
    replay_peak: jnp.ndarray
    qwait_sum: jnp.ndarray
    qwait_hist: jnp.ndarray


LAT_BINS = 64


class OpStream(NamedTuple):
    """Per-session op stream (SURVEY.md §1 L6): (S, G) arrays.  Synthetic
    workloads store only op codes and keys — write values are derived on
    device from (replica, session, op_idx).  The client KVS API
    (hermes_tpu/kvs.py) additionally supplies user payload words ``uval``
    ((S, G, value_words-2); words 0-1 of every value remain the
    device-derived unique write id the checker keys on)."""

    op: jnp.ndarray
    key: jnp.ndarray
    uval: Optional[jnp.ndarray] = None


def init_table(cfg: config_lib.HermesConfig) -> KeyTable:
    """All keys preloaded Valid at version 0 (reference preloads 1M keys at
    startup, SURVEY.md §3.5 / BASELINE.json:7).  The initial value id is
    (hi=-1, lo=key) so the checker can recognize initial reads."""
    k, v = cfg.n_keys, cfg.value_words
    val = jnp.zeros((k, v), jnp.int32)
    val = val.at[:, 0].set(jnp.arange(k, dtype=jnp.int32))
    val = val.at[:, 1].set(-1)
    return KeyTable(
        state=jnp.full((k,), types.VALID, jnp.int32),
        ver=jnp.zeros((k,), jnp.int32),
        fc=jnp.zeros((k,), jnp.int32),
        val=val,
        inv_step=jnp.zeros((k,), jnp.int32),
    )


def init_sessions(cfg: config_lib.HermesConfig) -> Sessions:
    s, v = cfg.n_sessions, cfg.value_words
    return Sessions(
        status=jnp.full((s,), types.S_IDLE, jnp.int32),
        op=jnp.zeros((s,), jnp.int32),
        op_idx=jnp.zeros((s,), jnp.int32),
        key=jnp.zeros((s,), jnp.int32),
        val=jnp.zeros((s, v), jnp.int32),
        ver=jnp.zeros((s,), jnp.int32),
        fc=jnp.zeros((s,), jnp.int32),
        acks=jnp.zeros((s,), jnp.int32),
        superseded=jnp.zeros((s,), jnp.bool_),
        rd_val=jnp.zeros((s, v), jnp.int32),
        invoke_step=jnp.zeros((s,), jnp.int32),
    )


def init_replay(cfg: config_lib.HermesConfig) -> ReplaySlots:
    rs, v = cfg.replay_slots, cfg.value_words
    return ReplaySlots(
        active=jnp.zeros((rs,), jnp.bool_),
        key=jnp.zeros((rs,), jnp.int32),
        ver=jnp.zeros((rs,), jnp.int32),
        fc=jnp.zeros((rs,), jnp.int32),
        val=jnp.zeros((rs, v), jnp.int32),
        acks=jnp.zeros((rs,), jnp.int32),
    )


def init_meta(cfg: config_lib.HermesConfig) -> Meta:
    z = jnp.zeros((), jnp.int32)
    return Meta(
        last_seen=jnp.zeros((cfg.n_replicas,), jnp.int32),
        suspect_age=jnp.zeros((cfg.n_replicas,), jnp.int32),
        n_read=z,
        n_write=z,
        n_rmw=z,
        n_abort=z,
        lat_sum=z,
        lat_cnt=z,
        lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        max_pts=z,
        n_inv=z,
        n_rebcast=z,
        n_nack=z,
        n_retry=z,
        replay_peak=z,
        qwait_sum=z,
        qwait_hist=jnp.zeros((LAT_BINS,), jnp.int32),
    )


class ReplicaState(NamedTuple):
    """Everything one replica owns: KVS table, client sessions, replay slots,
    observability.  Batched runs give every leaf a leading R axis (vmap);
    sharded runs shard the same pytree over the 'replica' mesh axis."""

    table: KeyTable
    sess: Sessions
    replay: ReplaySlots
    meta: Meta


def init_replica_state(cfg: config_lib.HermesConfig) -> ReplicaState:
    return ReplicaState(
        table=init_table(cfg),
        sess=init_sessions(cfg),
        replay=init_replay(cfg),
        meta=init_meta(cfg),
    )


def empty_invs(cfg: config_lib.HermesConfig, lead=()) -> Invs:
    l, v = cfg.n_lanes, cfg.value_words
    return Invs(
        valid=jnp.zeros(lead + (l,), jnp.bool_),
        key=jnp.zeros(lead + (l,), jnp.int32),
        ver=jnp.zeros(lead + (l,), jnp.int32),
        fc=jnp.zeros(lead + (l,), jnp.int32),
        epoch=jnp.zeros(lead + (l,), jnp.int32),
        val=jnp.zeros(lead + (l, v), jnp.int32),
        alive=jnp.zeros(lead, jnp.bool_),
    )


def empty_acks(cfg: config_lib.HermesConfig, lead=()) -> Acks:
    l = cfg.n_lanes
    z = lambda: jnp.zeros(lead + (l,), jnp.int32)
    return Acks(
        valid=jnp.zeros(lead + (l,), jnp.bool_),
        key=z(),
        ver=z(),
        fc=z(),
        ok=jnp.zeros(lead + (l,), jnp.bool_),
        epoch=z(),
    )


def empty_vals(cfg: config_lib.HermesConfig, lead=()) -> Vals:
    l = cfg.n_lanes
    z = lambda: jnp.zeros(lead + (l,), jnp.int32)
    return Vals(valid=jnp.zeros(lead + (l,), jnp.bool_), key=z(), ver=z(), fc=z(), epoch=z())
