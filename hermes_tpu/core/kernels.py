"""Pallas TPU kernels for faststep's dense hot blocks.

Where Pallas genuinely wins on this workload (measured; see ARCHITECTURE.md
"Why no Pallas kernel" for the random-access cases where it does NOT):
fusing a cluster of dense elementwise+reduction ops into ONE kernel removes
their per-kernel-launch overhead — a dominant cost of the round on the
target runtime (~0.5 ms marginal per launch measured).

``stats_block`` fuses the per-round completion-code computation, the op
counters, and the commit-latency histogram (collect_acks' tail: ~6 separate
XLA fusions) into a single kernel over the (R, S) session arrays, gridded
over session blocks (<= 32Ki lanes per block) so the VMEM working set stays
bounded at any session count; the counter/histogram outputs revisit one
block across grid steps and accumulate.

The kernel runs ``interpret=True`` on non-TPU backends, so the same code
runs under the CPU test suite (tests/test_kernels.py pins equivalence
against the pure-jnp formulation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hermes_tpu.core import layouts
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t

# counter row indices in the packed (R, STATS_CTR.width) counters output —
# derived from the declared table (core/layouts.py) so the kernel, the
# Meta fold in faststep, and the analyzer's kernel seeds cannot drift
CTR_READ = layouts.STATS_CTR.row("read")
CTR_WRITE = layouts.STATS_CTR.row("write")
CTR_RMW = layouts.STATS_CTR.row("rmw")
CTR_ABORT = layouts.STATS_CTR.row("abort")
CTR_LATSUM = layouts.STATS_CTR.row("lat_sum")
CTR_LATCNT = layouts.STATS_CTR.row("lat_cnt")
CTR_WIDTH = layouts.STATS_CTR.width


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _stats_kernel(step_ref, op_ref, invoke_ref, commit_ref, abort_ref,
                  read_ref, code_ref, ctr_ref, hist_ref):
    step = step_ref[0, 0]
    blk = pl.program_id(0)

    @pl.when(blk == 0)
    def _init():
        ctr_ref[:] = jnp.zeros_like(ctr_ref)
        hist_ref[:] = jnp.zeros_like(hist_ref)

    op = op_ref[:]
    commit = commit_ref[:] != 0
    abort = abort_ref[:] != 0
    read_done = read_ref[:] != 0
    is_rmw = op == t.OP_RMW

    code = jnp.where(
        abort, t.C_RMW_ABORT,
        jnp.where(commit, jnp.where(is_rmw, t.C_RMW, t.C_WRITE),
                  jnp.where(read_done, t.C_READ, t.C_NONE)),
    )
    code_ref[:] = code.astype(jnp.int32)

    lat = jnp.where(commit, step - invoke_ref[:], 0)
    ci = commit.astype(jnp.int32)
    # keepdims reductions concatenated on the lane axis — the 2-D form
    # Mosaic lowers reliably (validated on the target TPU via bench.py)
    red = lambda x: jnp.sum(x, axis=1, keepdims=True)
    zero = jnp.zeros((op.shape[0], 1), jnp.int32)
    n_pad = CTR_WIDTH - len(layouts.STATS_CTR.rows)
    ctr_ref[:] += jnp.concatenate([
        red(read_done.astype(jnp.int32)),
        red(ci * (1 - is_rmw.astype(jnp.int32))),
        red(ci * is_rmw.astype(jnp.int32)),
        red(abort.astype(jnp.int32)),
        red(lat),
        red(ci),
    ] + [zero] * n_pad, axis=1)

    # histogram: one reduction per bin (static unroll; all inside this kernel)
    nbin = st.LAT_BINS
    clat = jnp.clip(lat, 0, nbin - 1)
    hist_ref[:] += jnp.concatenate(
        [red(((clat == b) & commit).astype(jnp.int32)) for b in range(nbin)],
        axis=1,
    )


def stats_block(step, sess_op, invoke_step, commit, abort, read_done):
    """Fused completion codes + counters + latency histogram.

    Args: scalar round index + (R, S) session arrays (commit/abort/read_done
    bool).  Returns (code (R,S) int32, ctr (R, STATS_CTR.width) int32 packed
    per the declared CTR_* rows, hist_add (R, LAT_BINS) int32).
    """
    R, S = sess_op.shape
    nbin = st.LAT_BINS
    # Block size bounds the VMEM working set across BOTH dims: ~7 R-wide
    # int32 arrays live per grid step, kept under ~12 MB, additionally
    # capped at 32Ki lanes; block is a multiple of 128 and sized to the
    # smallest cover of S so the common shapes need no padding at all.
    bs_cap = min(1 << 15, max(128, (3 << 20) // (7 * R) // 128 * 128))
    nblk = -(-S // bs_cap)
    bs = min(-(-(-(-S // nblk)) // 128) * 128, bs_cap)
    nblk = -(-S // bs)
    pad = nblk * bs - S
    if pad:
        # neutral padding: commit/abort/read all zero contributes nothing
        # to any counter or histogram bin; the code output is sliced back
        padit = lambda x: jnp.pad(x, ((0, 0), (0, pad)))
        sess_op, invoke_step = padit(sess_op), padit(invoke_step)
        commit, abort, read_done = padit(commit), padit(abort), padit(read_done)
    sblk = pl.BlockSpec((R, bs), lambda j: (0, j))
    fixed = lambda shape: pl.BlockSpec(shape, lambda j: (0, 0))
    args = (
        jnp.asarray(step, jnp.int32).reshape(1, 1),
        sess_op, invoke_step,
        commit.astype(jnp.int32), abort.astype(jnp.int32),
        read_done.astype(jnp.int32),
    )
    # The ctr/hist output blocks have grid-invariant index maps: the same
    # block is revisited and accumulated across grid steps (zeroed on the
    # first visit under pl.when(blk == 0)).  The analyzer's RefHazardPass
    # requires that aliasing be declared — the audit tag on the call site
    # is the declaration, and the pass proves the first-visit init.
    with layouts.audited("stats-ctr-hist-grid-accumulate"):
        code, ctr, hist = pl.pallas_call(
            _stats_kernel,
            grid=(nblk,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda j: (0, 0),
                             memory_space=pltpu.SMEM),
                sblk, sblk, sblk, sblk, sblk,
            ],
            out_specs=[sblk, fixed((R, CTR_WIDTH)), fixed((R, nbin))],
            out_shape=[
                jax.ShapeDtypeStruct((R, nblk * bs), jnp.int32),
                jax.ShapeDtypeStruct((R, CTR_WIDTH), jnp.int32),
                jax.ShapeDtypeStruct((R, nbin), jnp.int32),
            ],
            interpret=_interpret(),
        )(*args)
    return code[:, :S], ctr, hist
