"""Round-15: the Pallas mega-round (ISSUE 11).

Throughput has been flat at ~13.7M committed writes/s since round 6
because the measured cost model (ARCHITECTURE.md "Sparse-op COUNT
dominates") prices a protocol round as (#sparse ops) x ~1.3-2.4 ms of
nearly size-independent launch overhead — the PR-2 diet bottomed out at
12 batched sparse ops and the op COUNT became the floor.  This module
breaks the floor the way the original target spec (SNIPPETS.md header)
asks: the arbiter -> apply -> quorum chain's sparse touches fuse into
Pallas kernels that step the packed per-key state (the core/layouts.py
word tables) with the (K,) vpts arbiter column resident in VMEM, so the
batched round lowers to FOUR sparse XLA ops (2 intake row gathers + the
one fused arbiter sort + the winner-row byte scatter) and the sharded
round to seven (census gate: OP_BUDGET.json ``batched_mega`` /
``sharded_mega``).

What stays XLA, and why (each a measured decision, not an omission):

  * the ONE fused arbiter+compaction ``lax.sort`` — Mosaic has no
    vectorized random access (PALLAS_PROBE.json: ``vgather`` still fails
    to lower), so an in-kernel arbitration would serialize R*S dependent
    per-key scratch accesses (~5-15 ms at bench shape) against the
    sort's ~1.8 ms; the sort is the right tool and its sorted-order
    verdicts are exactly what the route kernel consumes;
  * the intake bank-row gathers — random reads over the 46 MB table are
    XLA's fast path and are not part of the arbiter/apply/quorum chain;
  * the winner-row set-scatter — the int8 byte-move scatter is the
    best-measured op on the chip (~2.3x faster than int32; faststep
    header) and its value payload (R, L, 4V) cannot ride a VMEM-resident
    kernel at bench shape (21 MB > VMEM).

The three kernels (shared verbatim by both engines — the commit decision
stays in the unchanged dense ``_collect_acks``, so there is no duplicated
protocol logic to drift):

  * ``mega_route``   — the fused sort's ONE permutation scatter, serial:
    ``lane_word[si[p]] = word[p]`` plus the slot-ownership region
    (``slot_lane[srank[p]] = si[p]`` for ``srank < C``) — unique targets,
    so serial stores are exactly the max-on-zeros scatter they replace.
  * ``mega_apply``   — the arbiter core: phase-gridded (grid ``(2,)``)
    scatter-MAX of packed timestamps into the VMEM-resident vpts column
    (phase 0), then the settled post-arbiter verdict read-back for every
    row (phase 1) — one launch replacing the ``_ts_scatter_max`` scatter
    AND the post/joint verdict gather.  Wire keys keep faststep's exact
    semantics: a key >= K DROPS from the max (mode="drop" twin) and
    CLAMPS for the verdict read (the promised-in-bounds gather twin).
  * ``mega_replay``  — the cond-gated stuck-key scan: grid over
    VMEM-sized table blocks, dense per-block stuck detection, streaming
    candidate selection in global row order (bit-identical to the
    ``top_k`` of ``-kiota``), per-replica free-slot assignment and the
    REPLAY row marks all block-local — absorbing the scan's 4 gathers +
    1 scatter (and the top_k) into one launch that only runs every
    ``replay_scan_every`` rounds.

Serial-access idiom: every dynamically-indexed array is shaped ``(N, 1)``
and touched through ``pl.ds`` on the sublane dim — the one dynamic access
shape Mosaic reliably lowers (scripts/pallas_probe.py's serial candidate,
measured ~6 ns/iteration VMEM-resident and stamped ``analysis_clean``).
Every dynamic index is clamped to its block (the analyzer proves the
bound; the guard ``pl.when`` keeps the semantics exact), so the PR-8
RefHazard pass walks all three kernels clean.

Resolution (the ``fused_sort`` pattern): ``HermesConfig.use_mega_round``
is the static half; ``resolve(cfg)`` adds the build-time half — a tiny
concrete kernel self-test (catches a backend whose Pallas cannot compile;
interpret mode keeps every CPU/test path working) and the invariant
analyzer's verdict on the kernel bodies (a flagged kernel must not ship).
Refusals warn LOUDLY once and fall back to the fused-sort program, which
remains the A/B baseline (scripts/mega_compare.py measures the pair on
chip).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401 (SMEM scratch)

from hermes_tpu.core import layouts
from hermes_tpu.core import types as t

# bank row word indices (mirrors faststep; importing faststep here would
# cycle — the values are fixed by the declared row layout)
_BANK_PTS, _BANK_SST, _BANK_VAL = 0, 1, 2

#: mega_replay table-block budget: bank block bytes kept under ~4 MB so
#: block + lane arrays + outputs stay inside VMEM at bench shape.
REPLAY_BLOCK_BYTES = 4 << 20


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _col(x):
    """Flatten to the (N, 1) serial-access shape."""
    return x.reshape(-1, 1)


def _u8_to_i32(b4):
    """(..., 4) int8 bytes -> (..., 1) int32 word — the faststep byte
    codec (same-width bitcasts for the sign reinterpretations)."""
    u = jax.lax.bitcast_convert_type(b4, jnp.uint8).astype(jnp.uint32)
    w = (u[..., 0:1] | (u[..., 1:2] << 8) | (u[..., 2:3] << 16)
         | (u[..., 3:4] << 24))
    return jax.lax.bitcast_convert_type(w, jnp.int32)


def _i32_to_u8(w1):
    """(..., 1) int32 word -> (..., 4) int8 bytes (codec inverse)."""
    u = jax.lax.bitcast_convert_type(w1, jnp.uint32)
    b = jnp.concatenate(
        [((u >> (8 * k)) & 0xFF).astype(jnp.uint8) for k in range(4)],
        axis=-1)
    return jax.lax.bitcast_convert_type(b, jnp.int8)


# --------------------------------------------------------------------------
# kernel 1: route — the fused sort's permutation scatter, serialized
# --------------------------------------------------------------------------


def _route_kernel(L: int, C: int):
    def kern(si_ref, word_ref, srank_ref, lw_ref, sl_ref):
        # full-block zero fill first: unwritten slots must read 0 exactly
        # like the max-on-zeros scatter this replaces (si is a permutation
        # so every lane IS written; the zero fill also proves init)
        lw_ref[:] = jnp.zeros_like(lw_ref)
        sl_ref[:] = jnp.zeros_like(sl_ref)

        def body(p, c):
            lane_1 = si_ref[pl.ds(p, 1), 0]  # (1,) sorted lane id
            lane = jnp.clip(lane_1[0], 0, L - 1)
            lw_ref[pl.ds(lane, 1), 0] = word_ref[pl.ds(p, 1), 0]
            s = srank_ref[pl.ds(p, 1), 0][0]
            sc = jnp.clip(s, 0, C - 1)

            @pl.when((s >= 0) & (s < C))
            def _():
                sl_ref[pl.ds(sc, 1), 0] = jnp.clip(lane_1, 0, L - 1)

            return c

        jax.lax.fori_loop(0, L, body, 0)

    return kern


def mega_route(cfg, si, word, srank):
    """Per-lane verdict route-back + slot ownership (the fused path's ONE
    permutation scatter, faststep._coordinate round-6): returns
    ``(lane_word (R, L), slot_lane (R, C))`` — the exact arrays
    ``flat[:, :L]`` / ``flat[:, L:]`` of the scatter formulation (targets
    are unique: si is a permutation, srank a bijection, so serial set ==
    max-on-zeros)."""
    # leading axis from the data, not cfg.n_replicas: per-chip arrays
    # under shard_map carry R_local = 1
    R, L = si.shape
    C = cfg.lane_budget
    blk = lambda n: pl.BlockSpec((n, 1), lambda r: (r, 0))
    with layouts.audited("mega-route-unique-targets"):
        lw, sl = pl.pallas_call(
            _route_kernel(L, C),
            grid=(R,),
            in_specs=[blk(L)] * 3,
            out_specs=[blk(L), blk(C)],
            out_shape=[_sds((R * L, 1), jnp.int32),
                       _sds((R * C, 1), jnp.int32)],
            interpret=_interpret(),
        )(_col(si), _col(word), _col(srank))
    return lw.reshape(R, L), sl.reshape(R, C)


# --------------------------------------------------------------------------
# kernel 2: apply — scatter-max into VMEM-resident vpts + verdict read-back
# --------------------------------------------------------------------------


def _apply_kernel(K: int, N: int):
    def kern(vin_ref, key_ref, pts_ref, mask_ref, vout_ref, post_ref):
        # vout aliases the vpts input (input_output_aliases) — the probe's
        # serial-candidate pattern; vin is the dead pre-alias view
        del vin_ref
        phase = pl.program_id(0)

        @pl.when(phase == 0)
        def _max_pass():
            # scatter-MAX twin: masked rows land max(old, pts); a wire key
            # outside the table DROPS (mode="drop" semantics), hence the
            # in-bounds guard alongside the mask
            def body(m, c):
                k_raw = key_ref[pl.ds(m, 1), 0][0]
                k = jnp.clip(k_raw, 0, K - 1)
                ok = ((mask_ref[pl.ds(m, 1), 0][0] != 0)
                      & (k_raw >= 0) & (k_raw < K))

                @pl.when(ok)
                def _():
                    vout_ref[pl.ds(k, 1), 0] = jnp.maximum(
                        vout_ref[pl.ds(k, 1), 0], pts_ref[pl.ds(m, 1), 0])

                return c

            jax.lax.fori_loop(0, N, body, 0)

        @pl.when(phase == 1)
        def _post_pass():
            # settled verdict read-back for EVERY row (the post/joint
            # gather twin): clamped like the promised-in-bounds gather's
            # explicit min — a bogus wire key yields a garbage-but-defined
            # verdict its validity mask already ignores
            def body(m, c):
                k = jnp.clip(key_ref[pl.ds(m, 1), 0][0], 0, K - 1)
                post_ref[pl.ds(m, 1), 0] = vout_ref[pl.ds(k, 1), 0]
                return c

            jax.lax.fori_loop(0, N, body, 0)

    return kern


def mega_apply(cfg, vpts, keys, pts, mask):
    """The arbiter core in ONE launch: phase 0 scatter-MAXes every masked
    (key, pts) row into the VMEM-resident vpts column; phase 1 reads the
    settled ``vpts[key]`` verdict for every row.  ``keys``/``pts``/``mask``
    are flat (N,) row vectors (batched: R*L lanes; sharded: Rsrc*C wire
    slots + R*RS replay keys).  Returns ``(vpts', post (N,))``."""
    K = int(vpts.shape[0])
    N = int(keys.size)
    full = lambda n: pl.BlockSpec((n, 1), lambda i: (0, 0))
    with layouts.audited("mega-apply-two-phase-revisit"):
        vout, post = pl.pallas_call(
            _apply_kernel(K, N),
            grid=(2,),
            in_specs=[full(K), full(N), full(N), full(N)],
            out_specs=[full(K), full(N)],
            out_shape=[_sds((K, 1), jnp.int32), _sds((N, 1), jnp.int32)],
            input_output_aliases={0: 0},
            interpret=_interpret(),
        )(_col(vpts), _col(keys.reshape(-1)), _col(pts.reshape(-1)),
          _col(mask.reshape(-1).astype(jnp.int32)))
    return vout.reshape(K), post.reshape(-1)


# --------------------------------------------------------------------------
# kernel 3: replay — the cond-gated stuck-key scan, block-gridded
# --------------------------------------------------------------------------


def _replay_kernel(cfg, rows: int, Bk: int, W: int, R: int, RS: int):
    K = cfg.n_keys
    age_thresh = cfg.replay_age
    sst_lo, sst_hi = 4 * _BANK_SST, 4 * _BANK_SST + 4
    val_lo = 4 * _BANK_VAL

    def kern(step_ref, act_ref, frozen_ref, bank_in, vpts_ref,
             key_in, pts_in, acks_in, val_in,
             bank_ref, nact_ref, nkey_ref, npts_ref, nacks_ref, nval_ref,
             cursor):
        # bank_ref aliases bank_in (input_output_aliases); marks are the
        # only writes, so untouched rows keep their bytes
        del bank_in
        blk = pl.program_id(0)
        step = step_ref[0, 0]

        @pl.when(blk == 0)
        def _init():
            # replay outputs start as copies (slots not taken this scan
            # keep their rows); cursor = [n_cand, next-free-slot ptr x R]
            nact_ref[:] = act_ref[:]
            nkey_ref[:] = key_in[:]
            npts_ref[:] = pts_in[:]
            nacks_ref[:] = acks_in[:]
            nval_ref[:] = val_in[:]
            # one FULL-block store: element-wise zeroing would leave the
            # init state at 'maybe' for the RefHazard pass (partial
            # stores cannot prove a block fully initialized)
            cursor[:] = jnp.zeros_like(cursor)

        # dense per-block stuck detection off the PRE-mark block bytes
        # (exactly the do_scan mask: replayable state older than the age
        # threshold; ragged tail rows masked out)
        sst = _u8_to_i32(bank_ref[:, sst_lo:sst_hi])  # (Bk, 1)
        state = sst & 7
        age = step - (sst >> layouts.SST.field("step").shift)
        row0 = blk * Bk
        gidx = row0 + jax.lax.broadcasted_iota(jnp.int32, (Bk, 1), 0)
        stuck0 = (((state == t.INVALID) | (state == t.TRANS)
                   | (state == t.REPLAY))
                  & (age > age_thresh) & (gidx < rows)).astype(jnp.int32)
        iota_b = jax.lax.broadcasted_iota(jnp.int32, (Bk, 1), 0)
        iota_rs = jax.lax.broadcasted_iota(jnp.int32, (RS, 1), 0)

        def cand_body(ci, stuck):
            # next candidate = first remaining stuck row of this block,
            # taken only while the global candidate budget (RS) lasts —
            # the streaming twin of top_k(-kiota)'s ascending-row order
            idx_f = jnp.min(jnp.where(stuck != 0, iota_b, Bk))
            found = (idx_f < Bk) & (cursor[0] < RS)
            idx = jnp.clip(idx_f, 0, Bk - 1)

            @pl.when(found)
            def _take():
                row8 = bank_ref[pl.ds(idx, 1), :]  # (1, 4W) snapshot bytes
                ckey = jnp.remainder(row0 + idx, K)
                cpts = vpts_ref[pl.ds(idx, 1), 0]  # (1,)
                any_take = False
                for r in range(R):
                    # r's next free slot consumes this candidate (the
                    # free-rank mapping) whether or not r takes it
                    ptr = cursor[1 + r]
                    arow = act_ref[pl.ds(r * RS, RS), :]  # (RS, 1)
                    s = jnp.min(jnp.where((iota_rs >= ptr) & (arow == 0),
                                          iota_rs, RS))
                    cursor[1 + r] = jnp.minimum(s + 1, RS)
                    sc = jnp.clip(s, 0, RS - 1)
                    take = (s < RS) & (frozen_ref[r, 0] == 0)
                    any_take = take if r == 0 else (any_take | take)

                    @pl.when(take)
                    def _slot(r=r, s=s, sc=sc):
                        slot = r * RS + sc
                        nact_ref[pl.ds(slot, 1), 0] = jnp.ones(
                            (1,), jnp.int32)
                        nkey_ref[pl.ds(slot, 1), 0] = jnp.full(
                            (1,), ckey, jnp.int32)
                        npts_ref[pl.ds(slot, 1), 0] = cpts
                        nacks_ref[pl.ds(slot, 1), 0] = jnp.zeros(
                            (1,), jnp.int32)
                        nval_ref[pl.ds(slot, 1), :] = row8[:, val_lo:]

                cursor[0] = cursor[0] + 1

                @pl.when(any_take)
                def _mark():
                    # REPLAY mark: same bytes, sst word re-stamped — all
                    # taking replicas write the identical row (the
                    # replay-mark audit of the scatter it replaces)
                    mark_sst = _i32_to_u8(
                        ((step << layouts.SST.field("step").shift)
                         | t.REPLAY).reshape(1, 1))
                    bank_ref[pl.ds(idx, 1), :] = jnp.concatenate(
                        [row8[:, :sst_lo], mark_sst, row8[:, sst_hi:]],
                        axis=1)

            return jnp.where((iota_b == idx) & found, 0, stuck)

        @pl.when((jnp.sum(stuck0) > 0) & (cursor[0] < RS))
        def _scan_block():
            jax.lax.fori_loop(0, RS, cand_body, stuck0)

    return kern


def mega_replay(cfg, step, frozen, table_vpts, table_bank, replay,
                block_bytes: int = None):
    """The replay scan's sparse interior as one block-gridded kernel
    (runs under faststep's existing ``replay_scan_every`` cond): returns
    ``(new_bank, new_replay_fields)`` bit-identical to do_scan's top_k +
    gather/scatter formulation.  ``replay`` is the FastReplay tuple;
    fields come back as ``(active, key, pts, acks, val)`` arrays.
    ``block_bytes`` overrides the table-block budget (the kernel matrix
    forces the multi-block grid at toy shapes with it)."""
    rows = int(table_vpts.shape[0])
    W4 = int(table_bank.shape[1])
    # leading axis from the data (per-chip replay under shard_map is
    # (1, RS)); the key-id modulus stays cfg.n_keys — the per-shard
    # table holds exactly K rows in both engines
    R, RS = replay.active.shape
    V4 = 4 * cfg.value_words
    if block_bytes is None:
        block_bytes = REPLAY_BLOCK_BYTES
    nblk = max(1, -(-(rows * W4) // block_bytes))
    Bk = -(-rows // nblk)
    nblk = -(-rows // Bk)

    bankb = pl.BlockSpec((Bk, W4), lambda b: (b, 0))
    vptsb = pl.BlockSpec((Bk, 1), lambda b: (b, 0))
    fullc = lambda n, w=1: pl.BlockSpec((n, w), lambda b: (0, 0))
    smem = lambda sh: pl.BlockSpec(sh, lambda b: (0, 0),
                                   memory_space=pltpu.SMEM)

    act = _col(replay.active.astype(jnp.int32))
    with layouts.audited("mega-replay-stream-accumulate"):
        outs = pl.pallas_call(
            _replay_kernel(cfg, rows, Bk, W4, R, RS),
            grid=(nblk,),
            in_specs=[
                smem((1, 1)),
                fullc(R * RS), smem((R, 1)),
                bankb, vptsb,
                fullc(R * RS), fullc(R * RS), fullc(R * RS),
                fullc(R * RS, V4),
            ],
            out_specs=[bankb, fullc(R * RS), fullc(R * RS), fullc(R * RS),
                       fullc(R * RS), fullc(R * RS, V4)],
            out_shape=[
                _sds((rows, W4), jnp.int8),
                _sds((R * RS, 1), jnp.int32), _sds((R * RS, 1), jnp.int32),
                _sds((R * RS, 1), jnp.int32), _sds((R * RS, 1), jnp.int32),
                _sds((R * RS, V4), jnp.int8),
            ],
            input_output_aliases={3: 0},
            scratch_shapes=[pltpu.SMEM((1 + R,), jnp.int32)],
            interpret=_interpret(),
        )(jnp.asarray(step, jnp.int32).reshape(1, 1), act,
          frozen.astype(jnp.int32).reshape(R, 1), table_bank,
          _col(table_vpts), _col(replay.key), _col(replay.pts),
          _col(replay.acks), replay.val.reshape(R * RS, V4))
    bank, nact, nkey, npts, nacks, nval = outs
    shp = (R, RS)
    return bank, (nact.reshape(shp) != 0, nkey.reshape(shp),
                  npts.reshape(shp), nacks.reshape(shp),
                  nval.reshape(R, RS, V4))


# --------------------------------------------------------------------------
# resolution: the build-time half of use_mega_round
# --------------------------------------------------------------------------


def _toy_cfg():
    from hermes_tpu.config import HermesConfig

    return HermesConfig(n_replicas=2, n_keys=16, n_sessions=4,
                        replay_slots=2, ops_per_session=4,
                        arb_mode="sort", mega_round=True)


@functools.lru_cache(maxsize=1)
def _self_test() -> tuple:
    """(ok, reason): run every mega kernel CONCRETELY at a toy shape on
    this backend.  Catches a backend whose Pallas cannot lower the
    kernels — the 'platform lacks Pallas' refusal.  On non-TPU backends
    the kernels run interpret-mode (pure jax emulation, no Mosaic), so
    there is nothing platform-specific to probe and the compile probe is
    skipped — the analyzer half of resolve() still runs everywhere."""
    if _interpret():
        return (True, "interpret")
    # The first resolve may happen while an outer round is being traced
    # (profile/census paths jit the round directly).  JAX's trace state
    # is thread-local, so a fresh thread gives the concrete probe a
    # clean trace context regardless of the caller's.
    import threading

    box: dict = {}

    def probe():
        try:
            import numpy as np

            from hermes_tpu.core import faststep as fst

            cfg = _toy_cfg()
            L, R, RS = cfg.n_lanes, cfg.n_replicas, cfg.replay_slots
            si = jnp.tile(jnp.arange(L, dtype=jnp.int32)[None], (R, 1))
            lw, _sl = mega_route(cfg, si, si + 1, si)
            _vpts, post = mega_apply(
                cfg, jnp.zeros((cfg.n_keys,), jnp.int32),
                jnp.arange(R * L, dtype=jnp.int32) % cfg.n_keys,
                jnp.arange(R * L, dtype=jnp.int32),
                jnp.ones((R * L,), jnp.int32))
            # the replay kernel is the structurally riskiest of the
            # three (cross-grid SMEM cursor, aliased int8 block grid):
            # it MUST be part of the platform probe or a toolchain that
            # rejects only it would crash at round compile time instead
            # of falling back loudly here
            state = fst.init_fast_state(cfg)
            bank, (nact, *_rest) = mega_replay(
                cfg, jnp.int32(99), jnp.zeros((R,), jnp.bool_),
                state.table.vpts, state.table.bank, state.replay,
                block_bytes=8 * 4 * (2 + cfg.value_words))
            np.asarray(jax.block_until_ready(post))
            np.asarray(jax.block_until_ready(lw))
            np.asarray(jax.block_until_ready(nact))
            np.asarray(jax.block_until_ready(bank))
            box["v"] = (True, "ok")
        except Exception as e:  # pragma: no cover - backend-specific
            box["v"] = (False, f"kernel self-test failed: {e!r:.200}")

    th = threading.Thread(target=probe, name="mega-self-test")
    th.start()
    th.join()
    return box.get("v", (False, "kernel self-test thread died"))


@functools.lru_cache(maxsize=1)
def _kernels_clean() -> tuple:
    """(ok, reason): the PR-8 invariant analyzer's verdict on the mega
    kernel bodies (shape-independent rules at the toy shape).  A flagged
    kernel must not serve traffic — the 'analysis refuses' refusal."""
    try:
        from hermes_tpu.analysis import diffcheck

        # one representative cell per kernel family: the resolve-time
        # check is a tripwire, not the matrix — scripts/check_analysis.py
        # runs EVERY registered cell (incl. the multi-block replay shape)
        # plus the differential sanitizer
        rep_cells = {"mega_route/r2l6", "mega_apply/k16n16",
                     "mega_replay/k16b1"}
        bad = []
        for cell in diffcheck.kernel_cells():
            if cell.name not in rep_cells:
                continue
            rep = diffcheck.analyze_kernel(cell)
            gating = [f for f in rep["findings"]
                      if f.severity in ("error", "warn")]
            if gating:
                bad.append(f"{cell.name}: "
                           + "; ".join(f"{f.code}@{f.site}" for f in gating))
        if bad:
            return (False, "analyzer flagged mega kernels: "
                    + " | ".join(bad))
        return (True, "ok")
    except Exception as e:  # pragma: no cover
        return (False, f"kernel analysis crashed: {e!r:.200}")


_WARNED = set()


def resolve(cfg) -> bool:
    """The resolved mega switch the round builders consult at trace time:
    config half (``cfg.use_mega_round``) AND the cached build-time half
    (kernel self-test + analyzer verdict).  Refusals warn loudly ONCE per
    reason and fall back to the fused-sort program."""
    if not cfg.use_mega_round:
        return False
    for ok, reason in (_self_test(), _kernels_clean()):
        if not ok:
            if reason not in _WARNED:
                _WARNED.add(reason)
                warnings.warn(
                    f"mega_round requested but refused ({reason}); "
                    f"falling back to the fused-sort program",
                    RuntimeWarning, stacklevel=2)
            return False
    return True


def reset_resolution_cache() -> None:
    """Test hook: clear the cached self-test/analysis verdicts (e.g.
    after monkeypatching a kernel or an analyzer rule)."""
    _self_test.cache_clear()
    _kernels_clean.cache_clear()
    _WARNED.clear()
