"""One bulk-synchronous protocol step, composed from the phases.

The reference's per-op hot loop (SURVEY.md §3.1) becomes:

    coordinate -> [INV broadcast] -> apply_inv -> [ACK route-back]
               -> collect_acks    -> [VAL broadcast] -> apply_val

The three exchanges are the transport seam (SURVEY.md §1 L1, §5.8).  This
module provides the two *collective* realizations:

  * ``build_step_batched`` — all R replicas on one device, leading R axis via
    vmap; exchanges are array ops (broadcast / swapaxes).  This is the
    single-process multi-replica mode the reference uses for cluster-free
    testing (SURVEY.md §4, BASELINE.json:7) and the single-chip bench mode.
  * ``build_step_sharded`` — one replica per device over a
    ``Mesh(('replica',))``; exchanges are ``lax.all_gather`` (INV/VAL are
    broadcasts) and ``lax.all_to_all`` (ACKs route back to their INV's
    sender), riding ICI per BASELINE.json:5 (``transport=tpu_ici``).

The host-mediated transports (deterministic adversarial sim, C++ tcp) reuse
the same vmapped phases but run the exchange outside jit — see
hermes_tpu/transport/.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import compat, phases, state as st
from hermes_tpu.core import types as t


class StepCtl(NamedTuple):
    """Host-supplied per-step control: global step scalar plus per-replica
    epoch / live-mask / frozen arrays (membership + failure injection,
    SURVEY.md §5.3)."""

    step: jnp.ndarray  # () int32
    epoch: jnp.ndarray  # (R,) int32
    live_mask: jnp.ndarray  # (R,) int32
    frozen: jnp.ndarray  # (R,) bool


def make_ctl(cfg: HermesConfig, step: int) -> StepCtl:
    r = cfg.n_replicas
    return StepCtl(
        step=jnp.int32(step),
        epoch=jnp.zeros((r,), jnp.int32),
        live_mask=jnp.full((r,), cfg.full_mask, jnp.int32),
        frozen=jnp.zeros((r,), jnp.bool_),
    )


def _per_replica_ctl(cfg: HermesConfig, ctl: StepCtl) -> st.Ctl:
    r = cfg.n_replicas
    return st.Ctl(
        step=jnp.broadcast_to(ctl.step, (r,)).astype(jnp.int32),
        my_cid=jnp.arange(r, dtype=jnp.int32),
        epoch=ctl.epoch,
        live_mask=ctl.live_mask,
        frozen=ctl.frozen,
    )


# --------------------------------------------------------------------------
# Vmapped phases (shared by the batched step and the host-mediated runtimes)
# --------------------------------------------------------------------------


def phase_fns(cfg: HermesConfig):
    """The four protocol phases bound to a config — the single source for
    every backend (vmapped, sharded, jitted host-mediated)."""
    return dict(
        coordinate=functools.partial(phases.coordinate, cfg),
        apply_inv=functools.partial(phases.apply_inv, cfg),
        collect_acks=functools.partial(phases.collect_acks, cfg),
        apply_val=functools.partial(phases.apply_val, cfg),
    )


def vmapped_phases(cfg: HermesConfig):
    """Phase functions lifted over a leading replica axis."""
    return {k: jax.vmap(v) for k, v in phase_fns(cfg).items()}


def lockstep_bcast(block):
    """Batched-mode broadcast: per-src outbound (R, ...) -> per-dst inbound
    (R_dst, R_src, ...)."""
    r = jax.tree_util.tree_leaves(block)[0].shape[0]
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), block)


def lockstep_route_back(block):
    """Batched-mode ACK routing: out[p][q, l] -> in[q][p, l]."""
    return jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), block)


def _step_core(cfg: HermesConfig, ph, exchange_inv, exchange_ack, exchange_val,
               rs: st.ReplicaState, stream, ctl):
    """The step body, parameterized over the exchange primitives.

    ``ph`` are (possibly vmapped) phase fns; the three exchange callables
    realize the INV/VAL broadcast and ACK route-back on whatever substrate
    (array ops, ICI collectives, host network).  Every backend — fused jit
    (batched/tpu_ici) and host-mediated (sim/tcp) — runs THIS body, so the
    protocol cannot diverge between them."""
    pctl = ctl
    c = ph["coordinate"](pctl, rs.table, rs.sess, rs.replay, stream)
    in_inv = exchange_inv(c.out_inv)
    a = ph["apply_inv"](pctl, c.table, c.sess, rs.meta, in_inv)
    in_ack = exchange_ack(a.out_ack)
    k = ph["collect_acks"](pctl, a.table, a.sess, c.replay, a.meta, in_ack)
    in_val = exchange_val(k.out_val)
    table = ph["apply_val"](pctl, k.table, in_val)

    comp = phases.merge_completions(c.comp, a.comp, k.comp)
    meta = k.meta._replace(
        n_read=k.meta.n_read + jnp.sum(comp.code == t.C_READ, axis=-1, dtype=jnp.int32)
    )
    return st.ReplicaState(table, k.sess, k.replay, meta), comp


def build_step_batched(cfg: HermesConfig, donate: bool = False):
    """Single-device, R-replica lockstep step: jit( (state, stream, ctl) ->
    (state, completions) ).  All leaves carry a leading R axis.  With
    ``donate`` the state buffers are donated (bench mode: avoids a full copy
    of the key-state table per step)."""
    ph = vmapped_phases(cfg)

    def step(rs: st.ReplicaState, stream: st.OpStream, ctl: StepCtl):
        pctl = _per_replica_ctl(cfg, ctl)
        return _step_core(
            cfg, ph, lockstep_bcast, lockstep_route_back, lockstep_bcast, rs, stream, pctl
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def build_step_scan(cfg: HermesConfig, rounds: int, donate: bool = True):
    """``rounds`` protocol rounds in ONE dispatch via ``lax.scan`` (SURVEY.md
    §7 M6).  The per-step builder pays a host->device round trip per protocol
    round — over the tunneled PJRT link that dominates everything — so the
    bench path folds the host loop into the compiled program.  Membership
    (epoch / live_mask / frozen) is constant within a chunk; ``ctl.step`` is
    the chunk's first round index.  Completions are consumed into the meta
    counters only (checked runs use ``build_step_batched``); returns the
    post-chunk state."""
    ph = vmapped_phases(cfg)

    def chunk(rs: st.ReplicaState, stream: st.OpStream, ctl: StepCtl):
        def body(carry, off):
            pctl = _per_replica_ctl(cfg, ctl._replace(step=ctl.step + off))
            nxt, _comp = _step_core(
                cfg, ph, lockstep_bcast, lockstep_route_back, lockstep_bcast,
                carry, stream, pctl,
            )
            return nxt, None
        rs, _ = jax.lax.scan(body, rs, jnp.arange(rounds, dtype=jnp.int32))
        return rs

    return jax.jit(chunk, donate_argnums=(0,) if donate else ())


# --------------------------------------------------------------------------
# Sharded step: one replica per device over Mesh(('replica',))
# --------------------------------------------------------------------------


def _ici_exchanges():
    """The tpu_ici transport collectives (BASELINE.json:5): INV/VAL broadcasts
    are ``all_gather``, the ACK route-back is ``all_to_all``, both over the
    'replica' mesh axis (ICI on a real slice)."""

    def bcast(block):
        return jax.tree.map(
            lambda x: jax.lax.all_gather(x, "replica", axis=0, tiled=False), block
        )

    def route_back(block):
        return jax.tree.map(
            lambda x: jax.lax.all_to_all(x, "replica", split_axis=0, concat_axis=0, tiled=True),
            block,
        )

    return bcast, route_back


def build_step_sharded(cfg: HermesConfig, mesh: Mesh):
    """The ``transport=tpu_ici`` step (BASELINE.json:5): the same phases run
    per-shard under shard_map; INV/VAL broadcasts are ``all_gather`` and the
    ACK route-back is ``all_to_all`` over the 'replica' ICI axis."""
    if mesh.shape["replica"] != cfg.n_replicas:
        raise ValueError("mesh 'replica' axis size must equal cfg.n_replicas")
    bcast, route_back = _ici_exchanges()
    ph = phase_fns(cfg)

    def shard_body(rs, stream, ctl):
        # Leaves arrive with a leading local axis of size 1; strip it.
        rs1 = jax.tree.map(lambda x: x[0], rs)
        stream1 = jax.tree.map(lambda x: x[0], stream)
        my = jax.lax.axis_index("replica").astype(jnp.int32)
        pctl = st.Ctl(
            step=ctl.step,
            my_cid=my,
            epoch=ctl.epoch[0],
            live_mask=ctl.live_mask[0],
            frozen=ctl.frozen[0],
        )
        out_rs, comp = _step_core(cfg, ph, bcast, route_back, bcast, rs1, stream1, pctl)
        return jax.tree.map(lambda x: x[None], out_rs), jax.tree.map(lambda x: x[None], comp)

    rspec = P("replica")
    sharded = compat.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(rspec, rspec, StepCtl(step=P(), epoch=rspec, live_mask=rspec, frozen=rspec)),
        out_specs=(rspec, rspec),
    )
    return jax.jit(sharded)


def build_step_sharded_scan(cfg: HermesConfig, mesh: Mesh, rounds: int, donate: bool = True):
    """``rounds`` tpu_ici protocol rounds in one dispatch: the ``lax.scan``
    lives INSIDE shard_map, so each round's all_gather/all_to_all rides ICI
    back-to-back with no host involvement between rounds (SURVEY.md §7 M6).
    Same chunk semantics as ``build_step_scan``."""
    if mesh.shape["replica"] != cfg.n_replicas:
        raise ValueError("mesh 'replica' axis size must equal cfg.n_replicas")
    bcast, route_back = _ici_exchanges()
    ph = phase_fns(cfg)

    def shard_body(rs, stream, ctl):
        rs1 = jax.tree.map(lambda x: x[0], rs)
        stream1 = jax.tree.map(lambda x: x[0], stream)
        my = jax.lax.axis_index("replica").astype(jnp.int32)

        def body(carry, off):
            pctl = st.Ctl(
                step=ctl.step + off,
                my_cid=my,
                epoch=ctl.epoch[0],
                live_mask=ctl.live_mask[0],
                frozen=ctl.frozen[0],
            )
            nxt, _comp = _step_core(cfg, ph, bcast, route_back, bcast, carry, stream1, pctl)
            return nxt, None

        rs1, _ = jax.lax.scan(body, rs1, jnp.arange(rounds, dtype=jnp.int32))
        return jax.tree.map(lambda x: x[None], rs1)

    rspec = P("replica")
    sharded = compat.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(rspec, rspec, StepCtl(step=P(), epoch=rspec, live_mask=rspec, frozen=rspec)),
        out_specs=rspec,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def place_sharded(cfg: HermesConfig, mesh: Mesh, rs: st.ReplicaState, stream: st.OpStream):
    """Device-place a replica-batched state pytree, sharding the leading R
    axis over the mesh."""
    sh = NamedSharding(mesh, P("replica"))
    return (
        jax.device_put(rs, sh),
        jax.device_put(stream, sh),
    )
