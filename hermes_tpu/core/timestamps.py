"""Lamport timestamps.

The reference compares ``ts = (version, cid)`` lexicographically on every
INV/ACK apply (SURVEY.md §2 "Lamport timestamp comparator"; BASELINE.json:5).
We keep the timestamp as two int32 columns instead of a packed uint64 —
64-bit integer ops are emulated on TPU, two int32 compares fuse fine:

- ``ver``: the version number (monotonically increasing per key).
- ``fc``:  the tie-break word, ``(write_flag << 8) | cid``.  ``write_flag``
  gives plain writes priority over RMWs from the same base version (see
  core/types.py FLAG_*), and ``cid`` (coordinator/replica id) makes
  timestamps from distinct replicas unique.

All helpers are elementwise and jit/vmap/pallas-safe.
"""

from __future__ import annotations


def make_fc(write_flag, cid):
    """Pack the tie-break word: (flag << 8) | cid."""
    return (write_flag << 8) | cid


def fc_cid(fc):
    """Extract the coordinator id from the tie-break word."""
    return fc & 0xFF


def ts_gt(ver_a, fc_a, ver_b, fc_b):
    """Lexicographic (ver, fc) greater-than: a > b."""
    return (ver_a > ver_b) | ((ver_a == ver_b) & (fc_a > fc_b))


def ts_eq(ver_a, fc_a, ver_b, fc_b):
    return (ver_a == ver_b) & (fc_a == fc_b)
