"""TPU-optimized Hermes protocol round ("faststep").

Same protocol as core/phases.py (the readable reference semantics:
coordinate -> INV -> apply_inv -> ACK -> collect_acks -> VAL -> apply_val,
function roles per BASELINE.json:5), re-engineered for the measured cost
model of the target TPU runtime:

  * every XLA fusion/kernel launch costs ~0.3-1.4 ms through the tunneled
    PJRT runtime (measured: sequential unfusable stages at session scale are
    ~1.1 ms EACH, nearly independent of data size), so the round is built
    from the FEWEST possible chained kernels;
  * scatters cost ~4-6 ns/word and gathers ~1-3 ns/word beyond their fixed
    launch cost, so message volume (not key count) is the data cost;
  * dense K-sized passes are cheap in bandwidth but each op pays the launch
    tax, so the common path touches the key-state table ONLY through
    gathers/scatters — no full-table passes outside the (gated) replay scan.

The key engineering moves, mapped to the reference:

  1. **Packed Lamport timestamp** ``pts = (ver << PTS_FC_BITS) | fc`` with
     ``fc = (flag << 8) | cid`` (core/timestamps.py).  Lexicographic
     (ver, fc) compare == integer compare on pts, so the reference's
     per-key conflict resolution (max-timestamp wins, SURVEY.md §7 hard
     part 4) becomes a single ``scatter-max`` into the table — the batch
     winner, the stale-INV drop, and the idempotent same-ts re-apply all
     fall out of one atomic max op.  Packing limit: a key supports
     2^(31-PTS_FC_BITS-1) = ~1M versions before the sign bit corrupts the
     compare (HermesConfig.max_key_versions); runs long enough to rotate a
     single key a million times must use the reference phases path.
  2. **Packed state+age** ``sst = (last_change_step << 3) | state``: the
     per-key state machine word and the replay age (SURVEY.md §3.4) travel
     in one scatter.
  3. **One fused key-state row** ``bank = [pts | sst | val]`` (K, 2+V): the
     per-key columns the session side touches live in ONE array, so the
     session-side read (arbiter ts + Valid check + read value) is ONE
     gather, and the winner apply (ts + state + value) is ONE scatter.  The round writes each
     key's final state ONCE: the commit decision is made before the table
     write, so a winner lands directly as VALID (committed this round) or
     INVALID (awaiting acks) — the reference's separate apply_inv/apply_val
     table writes collapse into a single scatter (the VAL message itself
     still exists: slot bits over the round's own INV block, see
     fast_round_sharded).
  4. **Lane compaction with rebroadcast backoff**: outbound INV lanes
     (sessions + replay slots, SURVEY.md §1 L1 "batching") compact to a
     fixed budget C per round, rotating priority so no lane starves; lanes
     already waiting on acks re-broadcast only every ``rebroadcast_every``
     rounds.  Overflowing lanes simply wait a round — re-broadcast of the
     same-ts INV is idempotent, so backpressure is free (SURVEY.md §7 hard
     part 2).
  5. **Replay scan gating**: the full-table stuck-key scan runs under
     ``lax.cond`` every ``replay_scan_every`` rounds (it only matters after
     failures; BASELINE.json:10).
  6. **No vmap**: the body is written with an explicit leading replica axis
     and flat global scatter/gather indices, so the same code runs batched
     (R replicas on one chip, the reference's single-process test mode,
     BASELINE.json:7) and under shard_map (1 replica per chip over the
     'replica' ICI mesh axis — transport=tpu_ici, BASELINE.json:5).

RMW conflicts (YCSB-F, BASELINE.json:8) are detected purely through the
ACK ``ok`` flag: every replica acks every INV, with ok=False iff the INV's
ts is no longer the key's maximum after this round's applies.  A pending
RMW aborts on any nack; plain writes ignore nacks and commit by ts order.
The coordinator receives its own ACK too (the broadcast includes self), so
local supersession needs no separate detection pass.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import compat, kernels, layouts, megaround
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t

# Packed-word constants all derive from the declared field-layout table
# (core/layouts.py) — the same table hermes_tpu/analysis proves the traced
# program against, so the masks here and the theorems there cannot drift.
PTS_FC_BITS = layouts.PTS_FC_BITS  # fc = (flag << 8) | cid (flag 2b, cid 8b)
FC_MASK = layouts.FC_MASK
SST_STEP_SHIFT = layouts.SST.field("step").shift
SST_STATE_MASK = layouts.SST.field("state").mask
I32_MIN = jnp.iinfo(jnp.int32).min

# bank row layout (FastTable.bank, int8): bytes of [pts | sst | val words].
# The pts word mirrors vpts for every key whose row was written by its
# current winner — in particular for every VALID key (see FastTable): the
# issue path reads its arbiter ts from the same row gather that serves the
# Valid check and the read value, replacing a separate vpts gather (~1.9 ms
# of flat sparse-op cost on this runtime).
BANK_PTS = 0  # int32-word index of the mirrored packed-ts
BANK_SST = 1  # int32-word index of sst within a bank row
BANK_VAL = 2  # first int32-word index of the value

# FastInv.pkf packing: key | fresh-bit | valid-bit (keys fit the declared
# 29-bit field — HBM bounds n_keys far below that; config validates against
# layouts.INV_PKF).  One packed word means the compaction needs ONE
# take_along for (valid, fresh, key) and the sharded all_gather moves one
# tensor instead of three.
INV_KEY_MASK = layouts.INV_PKF.field("key").mask
INV_FRESH = jnp.int32(layouts.INV_PKF.field("fresh").mask)
INV_VALID = jnp.int32(layouts.INV_PKF.field("valid").mask)

# Fused arbiter+compaction sort key (band | sub) and the per-lane verdict
# word its permutation scatter routes back (layouts.FUSED_KEY / LANE_WORD).
FUSED_BAND_SHIFT = layouts.FUSED_KEY.field("band").shift
LANE_CHAIN_MASK = layouts.LANE_WORD.field("chain_rank").mask
LANE_ISSUE_SHIFT = layouts.LANE_WORD.field("issue").shift
LANE_TAKEN_SHIFT = layouts.LANE_WORD.field("taken").shift

# ACK wire header (key | ok | valid) and the INV block scalars
# (epoch | alive) — layouts.ACK_PKF / BLOCK_META.
ACK_KEY_SHIFT = layouts.ACK_PKF.field("key").shift
ACK_OK_MASK = layouts.ACK_PKF.field("ok").mask
ACK_VALID_MASK = layouts.ACK_PKF.field("valid").mask
META_EPOCH_SHIFT = layouts.BLOCK_META.field("epoch").shift
META_ALIVE_MASK = layouts.BLOCK_META.field("alive").mask


def pack_pts(ver, fc):
    return (ver << PTS_FC_BITS) | fc


def pts_ver(pts):
    return pts >> PTS_FC_BITS


def pts_fc(pts):
    return pts & FC_MASK


def pack_sst(step, state):
    return (step << SST_STEP_SHIFT) | state


def sst_state(sst):
    return sst & SST_STATE_MASK


def sst_step(sst):
    return sst >> SST_STEP_SHIFT


def _rotated(idx, step, n: int):
    """Per-round anti-starvation rotation ``(idx + step*stride) % n``,
    computed mod-first: ``step * 127`` wraps int32 once step exceeds ~1.7e7
    rounds, and jax's sign-following ``rem`` turns the wrapped product
    NEGATIVE — which would bleed into the fused sort key's band bits.
    ``(step % n) * stride`` is the same rotation (congruence mod n) and
    provably fits: n <= layouts.ROT_CAP keeps the product under 2^31; the
    (unreachably large) shapes past ROT_CAP fall back to stride 1, still a
    per-round bijection.  The static-analysis bit-pack pass proves the
    bound; tests/test_analysis.py keeps the overflow from regressing."""
    stride = layouts.ROT_STRIDE if n <= layouts.ROT_CAP else 1
    return (idx + (step % n) * stride) % n


# --------------------------------------------------------------------------
# State containers (leading axis = replicas-on-this-shard: R batched, 1 sharded)
# --------------------------------------------------------------------------


class FastTable(NamedTuple):
    """Key-state table (BASELINE.json:5) as HBM-resident columns.

    Lockstep sharing (measured to dominate the bench; soundness arguments in
    _apply_inv/_coordinate): all replicas of a shard receive the identical
    INV/VAL blocks each round, so the authoritative per-key state lives
    ONCE per shard (per-chip in sharded mode, where a chip IS one replica
    and the same body runs with a local view), split into two arrays by
    access pattern:

      ``vpts`` (K,) int32 — max applied packed-ts, the Lamport conflict
        arbiter.  Its only write is the per-round scatter-MAX, which needs
        int32 compare semantics.
      ``bank`` (K, 4*(2+V)) int8 — the BYTES of [pts | sst | val words],
        where sst packs (age_step << 3) | state and pts mirrors the winner's
        packed ts (== vpts whenever the key is VALID: a key turns VALID only
        through a winner-row write, which carries its own ts).  Its only
        write is the winner row SET-scatter, and int8 set-scatters move the
        same bytes ~2.3x faster than int32 on this chip (measured: 16.2 ms
        -> 7.2 ms at bench shape, including the vpts max) — a set is a pure
        byte move, so the element type is free to be whatever scatters
        fastest.

    The round reads the session row in ONE bank gather — Valid check, read
    value, and the issue path's arbiter ts all from the same row (no
    separate vpts gather; vpts is gathered only post-scatter for ack
    derivation and in the gated replay scan) — and writes each winner once:
    ts, state and value land together,
    with the commit decision made first, so there is no separate
    apply_inv/apply_val write pair (and no vpts rewrite — the scatter-max
    already placed it).  Two replicas can only disagree on these cells
    while at least one holds the key un-readable, so reads stay correct
    (see _apply_inv).

    There is NO per-replica issue ledger: an issue either broadcasts in its
    own round (winning a compaction slot — fresh issues that miss the budget
    REVERT and retry next round, see _coordinate) or does not happen, so its
    INV invalidates the key immediately and the plain Valid check blocks any
    same-key re-issue until the write resolves.  No deferred-write window
    exists, hence no dup-ts guard table, no ledger scatter on the hot path.
    """

    vpts: jnp.ndarray  # (K,) int32 batched / (R*K,) sharded-global
    bank: jnp.ndarray  # (K, 4*(2+V)) int8 rows [pts | sst | val] as bytes

    # Read-only int32 views (tests/tools; traced code works on rows).
    @property
    def sst(self):
        return _bank_to_i32(self.bank)[:, BANK_SST]

    @property
    def val(self):
        return _bank_to_i32(self.bank)[:, BANK_VAL:]

    @property
    def row_pts(self):
        return _bank_to_i32(self.bank)[:, BANK_PTS]


def _bank_to_i32(rows8):
    """int8 byte rows (..., 4*W) -> int32 words (..., W), via strided byte
    arithmetic: pure slice+elementwise, which XLA fuses into the consumer.
    (bitcast_convert_type forces a byte-plane relayout COPY of the whole
    array — measured 13 MB/round at bench shape — so it is banned from the
    hot path; this formulation defines the byte order everywhere.  Census
    note: the strided access lowers to a GATHER whose iota indices carry
    ``indices_are_sorted=true`` — XLA fuses it like a slice; a reshape+
    static-index form that lowers to true slices was A/B-measured ~3%
    SLOWER on-chip at bench shape, so the strided form stays and the op
    census classifies gathers by the sorted-indices attribute,
    scripts/sharded_census.py.)

    Promotion discipline (analysis dtype pass): the int8->uint8 and
    uint32->int32 steps REINTERPRET bits (a negative byte is a high byte
    value; a word with byte3 >= 0x80 is a negative int32), so they are
    same-width ``bitcast_convert_type``s — explicit, value-changing by
    declared intent, and free (no relayout: the byte plane is unchanged).
    The only arithmetic promotion left is the value-preserving uint8 ->
    uint32 widen.  An ``astype`` here would be a silent two's-complement
    wrap the analyzer flags as an implicit convert."""
    u = jax.lax.bitcast_convert_type(rows8, jnp.uint8).astype(jnp.uint32)
    w = (u[..., 0::4] | (u[..., 1::4] << 8)
         | (u[..., 2::4] << 16) | (u[..., 3::4] << 24))
    return jax.lax.bitcast_convert_type(w, jnp.int32)


def _i32_to_bank(rows32):
    """int32 words (..., W) -> int8 byte rows (..., 4*W); inverse of
    _bank_to_i32 (same byte order + same promotion discipline: same-width
    bitcasts for the sign reinterpretations, a masked value-preserving
    narrow for the byte extraction), fusable elementwise."""
    u = jax.lax.bitcast_convert_type(rows32, jnp.uint32)
    parts = jnp.stack(
        [((u >> (8 * k)) & 0xFF).astype(jnp.uint8) for k in range(4)],
        axis=-1,
    )
    b = parts.reshape(rows32.shape[:-1] + (4 * rows32.shape[-1],))
    return jax.lax.bitcast_convert_type(b, jnp.int8)


class FastSess(NamedTuple):
    """Client sessions (reference worker.c session arrays, SURVEY.md §1 L5)."""

    status: jnp.ndarray  # (R, S)
    op: jnp.ndarray
    op_idx: jnp.ndarray
    key: jnp.ndarray
    val: jnp.ndarray  # (R, S, 4V) int8 — values are opaque BYTE payloads
    pts: jnp.ndarray  # packed pending-update ts
    acks: jnp.ndarray  # gathered-ack replica bitmap
    rd_val: jnp.ndarray  # (R, S, 4V) int8
    invoke_step: jnp.ndarray
    retries: jnp.ndarray  # RMW retry-in-place count (config.rmw_retries)
    # step of the pending update's FIRST broadcast — the ACK quorum-wait
    # origin (Meta.qwait_*; maintained only under cfg.phase_metrics)
    issue_step: jnp.ndarray


class FastReplay(NamedTuple):
    """Replay slots (SURVEY.md §3.4): snapshot of a stuck key's last INV."""

    active: jnp.ndarray  # (R, RS) bool
    key: jnp.ndarray
    pts: jnp.ndarray
    val: jnp.ndarray  # (R, RS, 4V) int8 byte payload
    acks: jnp.ndarray


class FastInv(NamedTuple):
    """Compacted INV block as ONE byte tensor: ``rows8`` (..., C, 8+4V)
    int8 holds the bytes of [pkf | pts | val] per slot.  Outbound
    (R, C, 8+4V); inbound (R, Rsrc, C, 8+4V).  ``pkf`` packs
    (valid-bit << 30) | (fresh-bit << 29) | key: the fresh bit marks
    first-broadcast slots (a NEW timestamp — unique per (key, ts), since
    only the issuing session ever broadcasts a ts for the first time);
    re-broadcast slots carry a ts whose row the table already holds.
    _apply_commit uses fresh to keep its one set-scatter free of
    conflicting duplicate rows.  ``meta`` packs the per-block scalars
    ``(epoch << 1) | alive`` into ONE word (a replica's whole batch shares
    one epoch — SURVEY.md §1 L4), so the wire moves one collective operand
    for both.

    One tensor instead of three (round-5, SHARDED_CENSUS.json): the
    lane->slot compaction costs ONE take_along (was 3 — each ~1.3-2.4 ms of
    size-independent sparse-op overhead on this chip) and the wire moves
    ONE all_gather operand (was 3); the field views below are dense
    slice+elementwise, which XLA fuses into the consumers.  Round-6 carried
    the packing through the block scalars: the per-round sharded
    collectives are the rows8 + meta all_gathers, the ack all_to_all and
    the VAL-bit all_gather — the ACK/VAL epoch words ride the INV meta word
    gathered the same round (epochs cannot change mid-round), so their
    separate all_gathers are gone."""

    rows8: jnp.ndarray  # (..., C, 8+4V) int8 bytes of [pkf | pts | val]
    meta: jnp.ndarray  # (R,) / (R, Rsrc) int32 (epoch << 1) | alive

    @property
    def epoch(self):
        return self.meta >> META_EPOCH_SHIFT

    @property
    def alive(self):
        return (self.meta & META_ALIVE_MASK) != 0

    @property
    def pkf(self):
        return _bank_to_i32(self.rows8[..., 0:4])[..., 0]

    @property
    def pts(self):
        return _bank_to_i32(self.rows8[..., 4:8])[..., 0]

    @property
    def val(self):
        return self.rows8[..., 8:]

    @property
    def valid(self):
        return (self.pkf & INV_VALID) != 0

    @property
    def fresh(self):
        return (self.pkf & INV_FRESH) != 0

    @property
    def key(self):
        return self.pkf & INV_KEY_MASK


class LaneBlock(NamedTuple):
    """Per-LANE pending-update view (R, L, ...): every session and replay
    slot's (key, ts, value) plus the fresh bit.  The batched engine applies
    the protocol straight from this block (mask = which lanes broadcast);
    the sharded engine compacts it to the C-slot wire block
    (_compact_out_inv) first."""

    key: jnp.ndarray  # (R, L)
    pts: jnp.ndarray  # (R, L)
    val: jnp.ndarray  # (R, L, 4V) int8
    fresh: jnp.ndarray  # (R, L) bool


class FastAck(NamedTuple):
    """ACK block, slot-aligned with the acked INV block, as ONE byte tensor
    ``rows8`` (..., C, 8) int8 = bytes of [pkf | pts].  ``pkf`` packs
    (key << 2) | (ok << 1) | valid into one word — the echoed key plus the
    conflict flag (ok=False: the INV lost to a higher ts — the RMW nack);
    ``pts`` echoes the acked timestamp.  The echo guarantees a delayed or
    stale ack can never mis-credit a different pending update.  One tensor
    means one all_to_all on the wire (round-5; was 2).  The acker's epoch
    no longer rides along (round-6): the receiver checks it against the
    INV meta word all-gathered the same round — same value, one fewer
    collective.  (The VAL phase needs no block type at all: it is a bare
    per-slot commit-bit tensor over the round's own INV slots.)"""

    rows8: jnp.ndarray  # (R, Rdst, C, 8) outbound / (R, Rsrc, C, 8) inbound

    @property
    def pkf(self):
        return _bank_to_i32(self.rows8[..., 0:4])[..., 0]

    @property
    def pts(self):
        return _bank_to_i32(self.rows8[..., 4:8])[..., 0]


class FastState(NamedTuple):
    table: FastTable
    sess: FastSess
    replay: FastReplay
    meta: st.Meta  # reuse the observability container (leading R axis)


def init_fast_state(cfg: HermesConfig, n_local: int | None = None) -> FastState:
    """Fresh replicated state: all keys Valid at version 0 with the
    recognizable initial value (lo=key, hi=-1) (state.init_table)."""
    r = cfg.n_replicas if n_local is None else n_local
    k, s, rs, v = cfg.n_keys, cfg.n_sessions, cfg.replay_slots, cfg.value_words
    # batched mode shares the authoritative table across the shard's
    # replicas; sharded init (n_local=r) allocates one set per future shard
    nv = 1 if n_local is None else r
    rows32 = jnp.zeros((nv * k, 2 + v), jnp.int32)
    rows32 = rows32.at[:, BANK_VAL].set(jnp.tile(jnp.arange(k, dtype=jnp.int32), nv))
    rows32 = rows32.at[:, BANK_VAL + 1].set(-1)
    z = lambda *sh: jnp.zeros(sh, jnp.int32)
    meta = st.Meta(
        last_seen=z(r, cfg.n_replicas),
        suspect_age=z(r, cfg.n_replicas),
        n_read=z(r),
        n_write=z(r),
        n_rmw=z(r),
        n_abort=z(r),
        lat_sum=z(r),
        lat_cnt=z(r),
        lat_hist=z(r, st.LAT_BINS),
        max_pts=z(r),
        n_inv=z(r),
        n_rebcast=z(r),
        n_nack=z(r),
        n_retry=z(r),
        replay_peak=z(r),
        qwait_sum=z(r),
        qwait_hist=z(r, st.LAT_BINS),
    )
    z8 = lambda *sh: jnp.zeros(sh, jnp.int8)
    return FastState(
        table=FastTable(vpts=jnp.zeros((nv * k,), jnp.int32),
                        bank=_i32_to_bank(rows32)),
        sess=FastSess(
            status=z(r, s), op=z(r, s), op_idx=z(r, s), key=z(r, s),
            val=z8(r, s, 4 * v), pts=z(r, s), acks=z(r, s),
            rd_val=z8(r, s, 4 * v), invoke_step=z(r, s), retries=z(r, s),
            issue_step=z(r, s),
        ),
        replay=FastReplay(
            active=jnp.zeros((r, rs), jnp.bool_), key=z(r, rs), pts=z(r, rs),
            val=z8(r, rs, 4 * v), acks=z(r, rs),
        ),
        meta=meta,
    )


# --------------------------------------------------------------------------
# Flat-index gather/scatter helpers (leading replica axis folded in)
# --------------------------------------------------------------------------


def _gkey(col, key, mask=None):
    """Global row index into a flat table column for per-replica keys of any
    rank (R, ...): row = replica*K + key.  Only the small INDEX arrays carry
    the replica axis — the table itself stays flat, which keeps XLA's layout
    row-contiguous (measured ~2.3x faster value scatters than a leading
    replica axis) and avoids all hot-path reshapes.  Masked rows get an
    out-of-bounds index; mode='drop' discards them."""
    r = key.shape[0]
    K = col.shape[0] // r
    ridx = jnp.arange(r, dtype=jnp.int32).reshape((r,) + (1,) * (key.ndim - 1))
    g = ridx * K + key
    if mask is not None:
        g = jnp.where(mask, g, col.shape[0])
    return g


# --------------------------------------------------------------------------
# The round
# --------------------------------------------------------------------------


class FastCtl(NamedTuple):
    """Per-round control: unbatched step scalar (drives the cond-gated
    replay scan) + per-replica membership/failure rows (SURVEY.md §5.3)."""

    step: jnp.ndarray  # () int32 — NOT batched
    my_cid: jnp.ndarray  # (R,)
    epoch: jnp.ndarray  # (R,)
    live_mask: jnp.ndarray  # (R,)
    frozen: jnp.ndarray  # (R,) bool
    # () bool — version-rebase quiesce (build_rebase): blocks NEW intake and
    # NEW issues while in-flight writes/replays drain; reads, ack collection
    # and rebroadcast continue, so a quiesced run converges to zero S_INFL
    # sessions in ~p99-commit rounds.  Traced scalar: flipping it does not
    # recompile.  (Default False keeps every existing construction site.)
    quiesce: jnp.ndarray = False


def _run_issue(cfg: HermesConfig, first, in_run, sop, pos):
    """Equal-key-run issue decision over a SORTED axis, shared by the fused
    and split sort-arbiter paths (the one copy of the chain semantics — the
    A/B baseline must not drift from the production program): the run head
    always issues; with cfg.chain_writes up to chain_writes PLAIN writes
    directly behind it join as a packed-ts chain, and an RMW blocks
    chaining past it (its read-part must observe the immediately-preceding
    value).  ``sop`` is the sorted op operand (only consulted when
    chaining).  Entries outside runs are "bad" too, but cannot perturb the
    test: in both paths they sort strictly before or strictly after every
    run, so only a bad entry INSIDE the run can make last_bad >= start.
    Returns (issue, rank) with rank=None when chaining is off."""
    if not cfg.chain_writes:
        return in_run & first, None
    start = jax.lax.cummax(jnp.where(first, pos, -1), axis=1)
    bad = sop != t.OP_WRITE
    last_bad = jax.lax.cummax(jnp.where(bad, pos, -1), axis=1)
    rank = pos - start
    issue = in_run & (
        first | (~bad & (last_bad < start) & (rank < cfg.chain_writes)))
    # clip is a no-op on issuing entries (0 <= rank < chain_writes holds
    # whenever issue does: the run head's position is the cummax) but
    # makes the bound a THEOREM for the chain-rank field pack downstream
    # (analysis bitpack pass: the unclipped pos - start is abstractly
    # negative outside runs, which would sign-contaminate the win word)
    return issue, jnp.where(
        issue, jnp.clip(rank, 0, cfg.chain_writes - 1), 0)


def _stream_idx(cfg: HermesConfig, op_idx):
    """Stream slot addressed by a session's op counter (wrap vs clip)."""
    G = cfg.ops_per_session
    return op_idx % G if cfg.wrap_stream else jnp.clip(op_idx, 0, G - 1)


def _write_value(cfg: HermesConfig, my_cid, op_idx):
    """Unique write values (checker witness): words 0/1 = (lo, hi) uid,
    identical formula to phases._write_value."""
    r, s = op_idx.shape
    sess_idx = jnp.arange(s, dtype=jnp.int32)[None, :]
    lo = op_idx * cfg.n_sessions + sess_idx
    hi = jnp.broadcast_to(my_cid[:, None], lo.shape)
    words = [lo, hi]
    for j in range(2, cfg.value_words):
        words.append(lo * jnp.int32(-1640531527) + jnp.int32(j))
    return jnp.stack(words, axis=-1).astype(jnp.int32)


def _coordinate(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """Intake + local reads + update issue (reference worker-loop front half,
    SURVEY.md §3.1) + the replay scan (cond-gated) + outbound INV build."""
    R, S = fs.sess.status.shape
    K, G, RS = cfg.n_keys, cfg.ops_per_session, cfg.replay_slots
    V = cfg.value_words
    table, sess, replay = fs.table, fs.sess, fs.replay
    frozen = ctl.frozen[:, None]
    step = ctl.step

    # --- intake + local-read drain (unrolled read_unroll times) -------------
    # A replica drains several LOCAL reads per protocol round — exactly the
    # reference worker loop's behavior: reads never leave the machine
    # (SURVEY.md §3.2), so only updates are bound to the network round,
    # while the per-op loop serves reads back-to-back.  Each sub-step loads
    # the session's next op and completes it if it is a read against a
    # Valid key; a loaded update ends the drain for that session and enters
    # the issue path below.  All sub-steps observe the same table state
    # (this round's writes apply later), so same-round reads of a key
    # return the same value and any linearization order works; sub-step
    # completions are recorded in program order (sub_comps).
    #
    # (A one-gather variant — stack the U candidate keys per session and
    # gather (R,S,U) rows at once, then run the sub-steps as dense selects —
    # was measured SLOWER at bench shape (17.5 vs 16.6 ms/round): the 2x-row
    # gather plus the per-sub-step U-way dense row selects cost more than
    # the second sequential row gather.  Sequential per-sub-step gathers
    # stay.)

    def _intake(sess):
        if cfg.wrap_stream:
            can_load = (sess.status == t.S_IDLE) & ~frozen & ~ctl.quiesce
        else:
            can_load = ((sess.status == t.S_IDLE) & (sess.op_idx < G)
                        & ~frozen & ~ctl.quiesce)
        g = _stream_idx(cfg, sess.op_idx)
        if cfg.device_stream:
            # counter-hash op stream (SURVEY.md §2 "in-kernel PRNG"): ONE
            # shared formula with the host twin (workload.ycsb.stream_hash)
            from hermes_tpu.workload.ycsb import device_stream_params, stream_hash

            read_t, rmw_t = device_stream_params(cfg)
            import numpy as _np

            u_op, u_rmw, hkey = stream_hash(
                cfg,
                ctl.my_cid[:, None].astype(jnp.uint32),
                jnp.arange(S, dtype=jnp.uint32)[None, :],
                sess.op_idx.astype(jnp.uint32),
            )
            new_op = jnp.where(u_op < _np.uint32(read_t), t.OP_READ,
                               jnp.where(u_rmw < _np.uint32(rmw_t), t.OP_RMW,
                                         t.OP_WRITE)).astype(jnp.int32)
            new_key = hkey.astype(jnp.int32)
        else:
            new_op = jnp.take_along_axis(stream.op, g[..., None], axis=2)[..., 0]
            new_key = jnp.take_along_axis(stream.key, g[..., None], axis=2)[..., 0]
        is_nop = can_load & (new_op == t.OP_NOP)
        status = jnp.where(
            can_load,
            jnp.where(new_op == t.OP_READ, t.S_READ,
                      jnp.where(new_op == t.OP_NOP, t.S_IDLE, t.S_ISSUE)),
            sess.status,
        )
        if not cfg.wrap_stream:
            status = jnp.where((status == t.S_IDLE) & (sess.op_idx >= G), t.S_DONE, status)
        return sess._replace(
            status=status,
            op=jnp.where(can_load, new_op, sess.op),
            key=jnp.where(can_load, new_key, sess.key),
            invoke_step=jnp.where(can_load, step, sess.invoke_step),
            op_idx=jnp.where(is_nop, sess.op_idx + 1, sess.op_idx),
        )

    sub_comps = []
    read_extra = jnp.zeros((R, S), jnp.int32)
    for sub in range(cfg.read_unroll):
        sess = _intake(sess)
        # One bank-row gather serves the Valid check, the read value AND the
        # issue-path arbiter ts (the row's pts word mirrors vpts for VALID
        # keys — the only keys the issue path may act on).  Everything stays
        # BYTES: the state is the low 3 bits of the sst word's first byte,
        # and the value is an opaque payload.
        krow8 = table.bank[sess.key]  # (R, S, 4*(2+V)) int8
        k_valid = (krow8[..., 4 * BANK_SST] & 7) == t.VALID
        rd_val = krow8[..., 4 * BANK_VAL:]
        read_done = (sess.status == t.S_READ) & k_valid & ~frozen
        if sub < cfg.read_unroll - 1:
            sess = sess._replace(
                status=jnp.where(read_done, t.S_IDLE, sess.status),
                op_idx=jnp.where(read_done, sess.op_idx + 1, sess.op_idx),
                rd_val=jnp.where(read_done[..., None], rd_val, sess.rd_val),
            )
            # program-order completion record for this sub-step (reads only;
            # discarded by the bench scan, consumed by recorders/clients)
            sub_comps.append(st.Completions(
                code=jnp.where(read_done, t.C_READ, t.C_NONE).astype(jnp.int32),
                key=sess.key,
                wval=_bank_to_i32(sess.val),
                rval=_bank_to_i32(sess.rd_val),
                ver=pts_ver(sess.pts),
                fc=pts_fc(sess.pts),
                invoke_step=sess.invoke_step,
                commit_step=jnp.broadcast_to(step, (R, S)).astype(jnp.int32),
            ))
            read_extra = read_extra + read_done.astype(jnp.int32)

    # final sub-step: status/op_idx advance here; the rd_val write is merged
    # with the RMW read-part snapshot below (disjoint masks)
    sess = sess._replace(
        status=jnp.where(read_done, t.S_IDLE, sess.status),
        op_idx=jnp.where(read_done, sess.op_idx + 1, sess.op_idx),
    )

    # The arbiter ts is only consumed by the issue path — which requires the
    # key VALID, so the final sub-step's row gather already delivered it (the
    # row pts word; no separate vpts gather).  Write values only exist for
    # updates loaded this round — materialized ONCE here rather than per
    # sub-step (the value formula depends only on (cid, session, op_idx),
    # which still addresses the loaded update).
    k_vpts = _bank_to_i32(krow8[..., 4 * BANK_PTS: 4 * BANK_PTS + 4])[..., 0]
    # Pre-committed detection (round-9; the fast-engine twin of
    # phases.apply_inv's pre_committed): a pending update whose key row is
    # VALID at its OWN packed ts was finished by a replayer while this
    # coordinator was frozen/ack-starved — VALID at ts proves a full live
    # quorum acked it, so _collect_acks completes it as COMMITTED and
    # exempts it from the RMW nack (committed-then-superseded is a normal
    # history, not an abort).  Reads the row gather the round already pays.
    pre_comm = ((sess.status == t.S_INFL) & k_valid
                & (k_vpts == sess.pts) & ~frozen)
    w_loaded = (sess.status == t.S_ISSUE) & (sess.invoke_step == step)
    new_wval = _i32_to_bank(_write_value(cfg, ctl.my_cid, sess.op_idx))
    if stream.uval is not None:
        # client-supplied payload (hermes_tpu/kvs.py): words 2.. carry the
        # user value; words 0-1 keep the derived unique write id.  uval is
        # pre-converted to bytes by prep_stream.
        gw = _stream_idx(cfg, sess.op_idx)
        uval = jnp.take_along_axis(stream.uval, gw[..., None, None], axis=2)[:, :, 0]
        new_wval = jnp.concatenate([new_wval[..., :8], uval], axis=-1)
    sess = sess._replace(
        val=jnp.where(w_loaded[..., None], new_wval, sess.val)
    )

    # Same-key same-replica issue arbitration: exactly one of a replica's
    # wanting sessions may issue a key per round (two would mint the SAME
    # packed ts for different values — cfg.arb_mode picks the strategy).
    # An issue requires the key VALID: any in-flight same-key write (its INV
    # applies the round it issues — see the revert rule below) holds the key
    # un-readable, so no duplicate-ts window exists.
    #
    # With cfg.use_fused_sort the arbitration happens INSIDE the single
    # fused lane sort of the compaction block below (round-6 op diet: one
    # lax.sort per round instead of two); the split paths here remain as
    # the race arbiter and the fused-sort fallback/A-B baseline.
    want = (sess.status == t.S_ISSUE) & k_valid & ~frozen & ~ctl.quiesce
    idxs = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (R, S))
    chain_rank = jnp.zeros((R, S), jnp.int32)
    win = None  # fused path: resolved by the lane sort below
    if cfg.use_fused_sort:
        pass
    elif cfg.arb_mode == "sort":
        # lexicographic (key, session) sort per replica: the first entry of
        # each equal-key run (= the lowest wanting session, lax.sort is
        # stable) wins; ineligible sessions sort past K.  One sort + ONE
        # scatter through the permutation (vs the race's scatter-min +
        # gather), and no false collisions — every distinct wanted key
        # issues every round.  With cfg.chain_writes, up to chain_writes
        # entries of a run issue TOGETHER as a packed-ts chain: entry at
        # rank r mints ver+1+r, so a hot key drains a whole queue of
        # same-replica writes in one round (chained writes are superseded
        # in-round by the chain top exactly like cross-replica same-version
        # losers are — they commit, ordered by ts, value never observed;
        # see config.chain_writes).  Only plain writes may follow the run
        # head: an RMW's read-part must observe the immediately-preceding
        # value, so any RMW in the run blocks chaining past it (rank from
        # two dense cummax scans — no extra sparse ops).
        skey = jnp.where(want, sess.key, jnp.int32(cfg.n_keys))
        if cfg.chain_writes:
            sop = jnp.where(want, sess.op, 0)
            sk, si, so = jax.lax.sort((skey, idxs, sop), dimension=1,
                                      num_keys=1)
        else:
            sk, si = jax.lax.sort((skey, idxs), dimension=1, num_keys=1)
            so = None
        first = jnp.concatenate(
            [jnp.ones((R, 1), bool), sk[:, 1:] != sk[:, :-1]], axis=1)
        in_run = sk < cfg.n_keys
        issue, rank = _run_issue(cfg, first, in_run, so, idxs)
        if cfg.chain_writes:
            packed = jnp.where(
                issue, jnp.int32(layouts.ARB_WORD.field("win").mask) | rank,
                0)
        else:
            packed = issue.astype(jnp.int32)
        wz = jnp.zeros((R * S,), jnp.int32)
        p_flat = wz.at[_gkey(wz, si)].max(packed, mode="drop").reshape(R, S)
        win = want & (p_flat != 0)
        if cfg.chain_writes:
            chain_rank = jnp.where(
                win, p_flat & layouts.ARB_WORD.field("chain_rank").mask, 0)
    else:
        # hash-slot race: scatter-min of the session index into a small
        # table; colliding sessions (same slot) defer to the lowest index;
        # a false collision (different keys, same slot) only delays an
        # issue one round.
        HS = cfg.arb_slots
        h = sess.key & (HS - 1)
        arb = jnp.full((R * HS,), jnp.iinfo(jnp.int32).max, jnp.int32)
        arb = arb.at[_gkey(arb, h, want)].min(idxs, mode="drop")
        win = want & (arb[_gkey(arb, h)] == idxs)

    flag = jnp.where(sess.op == t.OP_WRITE, t.FLAG_WRITE, t.FLAG_RMW)
    fc = (flag << 8) | ctl.my_cid[:, None]
    # new_pts is minted after the compaction block: the fused sort resolves
    # win/chain_rank there (dense formula either way, nothing reordered)

    # --- replay scan, cond-gated (SURVEY.md §3.4; only matters after
    # failures, so it runs every replay_scan_every rounds) ------------------
    def do_scan(args):
        # The stuck mask lives in the SHARED state, so every live replica
        # sees the same candidates and replays the same keys — duplicate
        # same-ts re-INVs are idempotent (SURVEY.md §3.4), and any live
        # replica alone suffices to finish a dead coordinator's write.
        table, replay = args
        sstK = _bank_to_i32(
            table.bank[:, 4 * BANK_SST: 4 * BANK_SST + 4]
        ).reshape(1, -1)  # (1, nv*K)
        age = step - sst_step(sstK)
        state = sst_state(sstK)
        # REPLAY is included: the shared mark means SOME replica snapshotted
        # the key, but if every slot-holder dies before committing, the key
        # must be re-detected once it ages again (the mark re-stamps age).
        stuck = (
            (state == t.INVALID) | (state == t.TRANS) | (state == t.REPLAY)
        ) & (age > cfg.replay_age)
        kiota = jnp.arange(sstK.shape[1], dtype=jnp.int32)[None, :]
        score = jnp.where(stuck, -kiota, I32_MIN)
        top, _ = jax.lax.top_k(score, RS)
        cand_ok1 = top[0] != I32_MIN  # (RS,)
        cand1 = jnp.where(cand_ok1, -top[0], 0) % K  # global row -> key id
        cand_ok = jnp.broadcast_to(cand_ok1[None], (R, RS)) & ~frozen[:, :1]
        cand = jnp.broadcast_to(cand1[None], (R, RS))
        # i-th candidate -> i-th free slot (sorted free-slot order)
        free_rank = jnp.cumsum((~replay.active).astype(jnp.int32), axis=1) - 1
        # for each slot: which candidate it takes = rank among free slots
        take = jnp.where(~replay.active, free_rank, RS)
        take_ok = (take < RS) & jnp.take_along_axis(
            jnp.pad(cand_ok, ((0, 0), (0, 1))), jnp.minimum(take, RS), axis=1
        )
        ck = jnp.take_along_axis(jnp.pad(cand, ((0, 0), (0, 1))), jnp.minimum(take, RS), axis=1)
        ckrow8 = table.bank[ck]  # (R, RS, 4*(2+V)) snapshot byte rows
        ckval8 = ckrow8[..., 4 * BANK_VAL:]
        new_replay = FastReplay(
            active=jnp.where(take_ok, True, replay.active),
            key=jnp.where(take_ok, ck, replay.key),
            pts=jnp.where(take_ok, table.vpts[ck], replay.pts),
            val=jnp.where(take_ok[..., None], ckval8, replay.val),
            acks=jnp.where(take_ok, 0, replay.acks),
        )
        mark_sst = _i32_to_bank(
            pack_sst(step, jnp.full(ck.shape, t.REPLAY, jnp.int32))[..., None]
        )
        mark = jnp.concatenate(
            [ckrow8[..., : 4 * BANK_SST], mark_sst, ckval8], axis=-1)
        # set-scatter with duplicate indices only among the OOB-masked rows
        # (mode=drop discards them before the write; live rows are distinct
        # candidates taken by distinct free slots) — audited for the
        # analysis scatter pass, which cannot prove take-injectivity.
        with layouts.audited("replay-mark-dup-oob-dropped"):
            new_bank = table.bank.at[
                jnp.where(take_ok, ck, table.bank.shape[0])
            ].set(mark, mode="drop")
        return table._replace(bank=new_bank), new_replay

    if megaround.resolve(cfg):
        # round-15: the scan's 4 gathers + top_k + mark scatter run
        # block-gridded inside one Pallas launch (streaming candidate
        # selection in global row order == top_k of -kiota; per-replica
        # free-slot assignment and REPLAY marks block-local) — same
        # (table, replay) trees bit-for-bit, and the launch only fires
        # under this cond every replay_scan_every rounds
        def do_scan(args):
            table, replay = args
            bank, (nact, nkey, npts, nacks, nval) = megaround.mega_replay(
                cfg, step, ctl.frozen, table.vpts, table.bank, replay)
            return (table._replace(bank=bank),
                    FastReplay(active=nact, key=nkey, pts=npts, val=nval,
                               acks=nacks))

    table, replay = jax.lax.cond(
        step % cfg.replay_scan_every == 0,
        do_scan,
        lambda args: args,
        (table, replay),
    )

    # --- outbound INV compaction (SURVEY.md §7 hard part 2) ---------------
    # Lanes: sessions 0..S-1, replay slots S..L-1.  Waiting (rebroadcast)
    # and replay lanes take priority band 0 — they are few in steady state
    # and must not starve behind fresh bursts; fresh issues fill band 1.  A
    # fresh issue that misses the budget REVERTS (the session stays S_ISSUE
    # and retries next round): a write that happens always broadcasts — and
    # therefore applies — in its own round, which is what lets the engine
    # run without an issue-ledger table (see FastTable).  Priority rotates
    # with the step so no lane starves within its band.
    L, C = cfg.n_lanes, cfg.lane_budget
    infl = sess.status == t.S_INFL  # in-flight from earlier rounds
    backoff_ok = (step - sess.invoke_step) % cfg.rebroadcast_every == 0
    waiting = infl & backoff_ok
    lane_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (R, L))
    if cfg.use_fused_sort:
        # --- fused arbiter + compaction sort (round-6 op diet) ------------
        # The arbiter's equal-key-run scan and the lane->slot compaction
        # both order the SAME (R, L) lanes each round, so one lax.sort
        # serves both.  Packed key (band << 29) | sub:
        #   band 0 — waiting/replay lanes; sub = rotation index
        #            (lane + 127*step) % L, unique per lane, so the
        #            anti-starvation rotation is exact (the split path had
        #            to coarsen it to spare bits);
        #   band 1 — wanting sessions; sub = ROTATED key
        #            (key + 127*step) % K: a per-round bijection on keys,
        #            so equal-key runs stay contiguous (run detection and
        #            chain ranks work unchanged) while run PRIORITY rotates
        #            — under budget overflow every key still reaches the
        #            front of its band within O(K) rounds;
        #   band 2 — ineligible; never in a run, never takes a slot.
        # lax.sort is stable, so within an equal-key run the original lane
        # order — the session order — is preserved: the run head is the
        # LOWEST wanting session, exactly the split arbiter's
        # lowest-session-wins tie-break.  Slot ownership falls out of the
        # rank among slot-eligible sorted entries (band 0 plus run
        # winners/chain members) against the budget C — a dense cumsum,
        # not a second sort — and everything routes back to lanes through
        # the ONE permutation scatter the arbiter already paid, widened to
        # also land each slot's owning lane id (slot_lane) for the sharded
        # wire path.  Unfilled slots receive non-eligible lanes (never
        # taken, so their wire rows carry valid=0), mirroring the split
        # path's threshold behavior.
        # Trace-time theorem check (layouts.FUSED_KEY; regression-tested in
        # tests/test_analysis.py): a max-valued sub (rotated key or rotation
        # index) must not reach the band shift, or band 1 entries alias
        # band 2 and the arbiter admits ineligible lanes.  config enforces
        # both bounds (n_keys validation + use_fused_sort), so this only
        # fires if a caller bypassed config validation.
        sub_cap = layouts.FUSED_KEY.field("sub").cap
        assert cfg.n_keys <= sub_cap and L <= sub_cap, (
            f"fused sort key overflow: sub holds keys (n_keys={cfg.n_keys})"
            f" and rotation indices (n_lanes={L}); both must fit the "
            f"declared {layouts.FUSED_KEY.field('sub').bits}-bit sub field")
        lane_key = jnp.concatenate([sess.key, replay.key], axis=1)
        lane_want = jnp.concatenate(
            [want, jnp.zeros_like(replay.active)], axis=1)
        lane_wait = jnp.concatenate(
            [waiting, replay.active], axis=1) & ~frozen
        band = jnp.where(lane_wait, 0, jnp.where(lane_want, 1, 2))
        rot = _rotated(lane_idx, step, L)
        rkey = _rotated(lane_key, step, cfg.n_keys)
        sub = jnp.where(band == 0, rot, jnp.where(band == 1, rkey, 0))
        lane_sop = jnp.concatenate(
            [jnp.where(want, sess.op, 0), jnp.zeros_like(replay.key)],
            axis=1)
        sp, si, so = jax.lax.sort(
            (((band << FUSED_BAND_SHIFT) | sub), lane_idx, lane_sop),
            dimension=1, num_keys=1)
        sband = sp >> FUSED_BAND_SHIFT
        first = jnp.concatenate(
            [jnp.ones((R, 1), bool), sp[:, 1:] != sp[:, :-1]], axis=1)
        in_run = sband == 1
        pos = lane_idx  # iota along the sorted axis
        issue, rank_word = _run_issue(cfg, first, in_run, so, pos)
        if rank_word is None:
            rank_word = jnp.zeros((R, L), jnp.int32)
        slot_elig = (sband == 0) | issue
        cum = jnp.cumsum(slot_elig.astype(jnp.int32), axis=1)  # inclusive
        staken = slot_elig & (cum <= C)
        # slot rank: eligible entries take 0..n_elig-1 in priority order,
        # non-eligible entries fill the remainder (their lanes are never
        # taken — placeholder rows, valid=0 on the wire)
        srank = jnp.where(slot_elig, cum - 1, cum[:, -1:] + pos - cum)
        # ONE scatter, two regions of a (R, L+C) target: the per-lane
        # verdict word [taken<<21 | issue<<20 | chain_rank] through the
        # permutation, and each slot's owning lane id at L+srank.  Targets
        # are unique (si is a permutation; srank is a bijection), so
        # max == set.
        word = ((staken.astype(jnp.int32) << LANE_TAKEN_SHIFT)
                | (issue.astype(jnp.int32) << LANE_ISSUE_SHIFT) | rank_word)
        if megaround.resolve(cfg):
            # round-15: the permutation route-back runs serially inside
            # the mega route kernel (unique targets, so serial set ==
            # the max-on-zeros scatter below) — one sparse op off the
            # chain, same (lane_word, slot_lane) arrays bit-for-bit
            lane_word, slot_lane = megaround.mega_route(cfg, si, word,
                                                        srank)
        else:
            gz = jnp.zeros((R * (L + C),), jnp.int32)
            ridx = jnp.arange(R, dtype=jnp.int32)[:, None] * (L + C)
            tgt = jnp.concatenate(
                [ridx + si,
                 jnp.where(srank < C, ridx + L + srank, R * (L + C))],
                axis=1)
            upd = jnp.concatenate([word, si], axis=1)
            flat = gz.at[tgt].max(upd, mode="drop").reshape(R, L + C)
            lane_word = flat[:, :L]
            slot_lane = flat[:, L:]
        taken_lane = (lane_word & (1 << LANE_TAKEN_SHIFT)) != 0
        win = want & ((lane_word[:, :S] & (1 << LANE_ISSUE_SHIFT)) != 0)
        if cfg.chain_writes:
            chain_rank = jnp.where(win, lane_word[:, :S] & LANE_CHAIN_MASK, 0)
        lane_fresh = jnp.concatenate(
            [win, jnp.zeros_like(replay.active)], axis=1)
    else:
        sess_elig = (win | waiting) & ~frozen
        fresh_s = win & ~frozen
        lane_elig = jnp.concatenate(
            [sess_elig, replay.active & ~frozen], axis=1)
        lane_fresh = jnp.concatenate(
            [fresh_s, jnp.zeros_like(replay.active)], axis=1
        )
        if C == L:
            # budget covers every lane: slots ARE lanes, no compaction sort
            slot_lane = lane_idx
            taken_lane = lane_elig
        else:
            # Single-operand sort: one int32 packs (band | rotation | lane)
            # — one sort buffer, and which lanes hold a slot falls out of a
            # THRESHOLD test against the C-th smallest packed value (values
            # are unique — the lane id is the low bits) instead of an
            # inverse scatter.  Band (2b): 0 = waiting/replay, 1 = fresh,
            # 2 = ineligible.  The rotating anti-starvation tie-break is
            # coarsened to the bits left between band and lane: rotation
            # granularity 2^(lb-rb) lanes, with membership shifting by 127
            # lanes per round, so every lane still reaches the front of its
            # band within O(L) rounds.
            lb = max(1, (L - 1).bit_length())  # lane bits
            rb = max(0, 31 - 2 - lb)  # rotation bits
            rot = _rotated(lane_idx, step, L)
            rotp = rot >> max(0, lb - rb)
            band = jnp.where(lane_elig, jnp.where(lane_fresh, 1, 0), 2)
            packed_own = (((band << min(rb, lb)) | rotp) << lb) | lane_idx
            packed = jax.lax.sort(packed_own, dimension=1)
            slot_lane = packed[:, :C] & ((1 << lb) - 1)  # (R, C) slot lanes
            taken_lane = lane_elig & (packed_own <= packed[:, C - 1 : C])
    # The minted ts packs a ver read from the winner-row mirror, whose
    # bound is a PROTOCOL invariant (ver <= max_key_versions, enforced by
    # the Meta.max_pts runtime watermark + auto-rebase), not a config
    # fact — audited so the analysis bit-pack pass reports the assumption
    # instead of an unprovable overflow.
    with layouts.audited("pts-mint-ver-bounded-by-watermark"):
        new_pts = pack_pts(pts_ver(k_vpts) + 1 + chain_rank, fc)

    # fresh issues that won arbitration AND hold a slot actually happen;
    # the rest revert (stay S_ISSUE) and retry next round
    win_eff = win & taken_lane[:, :S]
    # one rd_val write serves both completions: finished reads and the RMW
    # read-part snapshot write the same gathered row (masks are disjoint —
    # S_READ vs S_ISSUE sessions)
    is_rmw_issue = win_eff & (sess.op == t.OP_RMW)
    sess = sess._replace(
        status=jnp.where(win_eff, t.S_INFL, sess.status),
        pts=jnp.where(win_eff, new_pts, sess.pts),
        acks=jnp.where(win_eff, 0, sess.acks),
        rd_val=jnp.where(
            (read_done | is_rmw_issue)[..., None], rd_val, sess.rd_val
        ),
    )
    meta = fs.meta
    if cfg.phase_metrics:
        # phase metrics (hermes_tpu/obs): dense per-round sums over masks the
        # round already computed — XLA fuses them into the existing
        # elementwise work, no extra sparse ops.  issue_step anchors the ACK
        # quorum-wait clock at the pending update's FIRST broadcast.
        sess = sess._replace(
            issue_step=jnp.where(win_eff, step, sess.issue_step))
        meta = meta._replace(
            n_inv=meta.n_inv + jnp.sum(taken_lane, axis=1, dtype=jnp.int32),
            n_rebcast=meta.n_rebcast
            + jnp.sum(taken_lane & ~lane_fresh, axis=1, dtype=jnp.int32),
            replay_peak=jnp.maximum(
                meta.replay_peak,
                jnp.sum(replay.active, axis=1, dtype=jnp.int32)),
        )

    lanes = LaneBlock(
        key=jnp.concatenate([sess.key, replay.key], axis=1),
        pts=jnp.concatenate([sess.pts, replay.pts], axis=1),
        val=jnp.concatenate([sess.val, replay.val], axis=1),
        fresh=lane_fresh,
    )

    fs = fs._replace(table=table, sess=sess, replay=replay, meta=meta)
    return (fs, lanes, slot_lane, taken_lane, read_done, read_extra, sub_comps,
            pre_comm)


def _compact_out_inv(ctl: FastCtl, lanes: "LaneBlock", slot_lane, taken_lane):
    """Lane block -> wire-shaped INV block (the C-slot broadcast batch,
    SURVEY.md §1 L1).  Only the sharded path pays this take_along: the
    batched emulation scatters straight from the lane arrays
    (fast_round_batched) — each take_along costs ~1.3-2.4 ms of nearly
    size-independent sparse-op overhead on the target runtime, so routing
    lanes->slots->table was measured strictly worse than lanes->table when
    no physical wire exists.  The [pkf | pts | val] bytes ride ONE packed
    tensor, so the whole compaction is ONE take_along (round-5; was 3)."""
    lane_pkf = (
        lanes.key
        | jnp.where(lanes.fresh, INV_FRESH, 0)
        | jnp.where(taken_lane, INV_VALID, 0)
    )
    head8 = _i32_to_bank(jnp.stack([lane_pkf, lanes.pts], axis=-1))
    rows8 = jnp.concatenate([head8, lanes.val], axis=-1)  # (R, L, 8+4V)
    return FastInv(
        rows8=jnp.take_along_axis(rows8, slot_lane[..., None], axis=1),
        meta=((ctl.epoch << META_EPOCH_SHIFT)
              | (~ctl.frozen).astype(jnp.int32)),
    )


def _apply_inv(cfg: HermesConfig, ctl: FastCtl, fs: FastState, inv_src: FastInv,
               replay_key):
    """Follower-side ``apply_inv()`` (BASELINE.json:5) over the SOURCE-shaped
    block ``inv_src`` (fields (Rsrc, C); epoch/alive (Rsrc,)): per-key winner
    + stale-drop + idempotent re-apply via one scatter-max on the packed ts.

    Arbitration ONLY — the winner's state+value table write is deferred to
    ``_apply_commit`` at the end of the round, once the commit decision is
    known, so each key row is written once per round (fused [pts|sst|val]
    scatter) instead of the reference's separate apply_inv/apply_val writes.

    Soundness of the shared table under lockstep: a key Valid at ts p on any
    replica means no broadcast INV ever exceeded p (it would have
    invalidated that replica too), so the shared cells — arbitrated by the
    vpts scatter-max — hold exactly ts p's value and state when read through
    a Valid check.  The returned ``ack_flags`` (Rsrc, C) are the shared
    conflict verdicts (the ACK ok bit): conflicts among broadcast writes are
    global facts, and the write-flag tiebreak (types.FLAG_*) guarantees a
    same-version plain write beats any concurrent RMW, which makes the
    shared verdict equivalent to per-replica evaluation.  Epochs are uniform
    across a shard's replicas (FastRuntime bumps them together).  (The
    reference phases engine keeps the fuller per-replica Write/Trans
    bookkeeping.)"""
    key0, pts0 = inv_src.key, inv_src.pts
    v_ok = inv_src.valid & (inv_src.epoch == ctl.epoch[0])[..., None]
    nslot = key0.size
    if megaround.resolve(cfg):
        # round-15: the arbiter scatter-max AND the joint verdict gather
        # below fuse into one mega_apply launch over the same index
        # vector (slots + local replay keys; replay rows carry a zero
        # mask — verdict read only).  The kernel keeps the wire-key
        # semantics exactly: >= K drops from the max, clamps for the read.
        keys_all = jnp.concatenate([key0.reshape(-1),
                                    replay_key.reshape(-1)])
        pts_all = jnp.concatenate(
            [pts0.reshape(-1),
             jnp.zeros((replay_key.size,), jnp.int32)])
        mask_all = jnp.concatenate(
            [v_ok.reshape(-1), jnp.zeros((replay_key.size,), jnp.bool_)])
        vpts, joint = megaround.mega_apply(cfg, fs.table.vpts, keys_all,
                                           pts_all, mask_all)
        fs = fs._replace(table=fs.table._replace(vpts=vpts),
                         meta=_apply_inv_meta(ctl, fs.meta, inv_src))
    else:
        fs = _apply_inv_arb(cfg, ctl, fs, inv_src)
        # ONE post-arbiter gather serves BOTH consumers of the settled
        # vpts (round-6 op diet): the per-slot verdicts below AND the
        # replay supersession test in _collect_acks (the local replay
        # slots' keys ride the same index vector — vpts is written only
        # by the scatter-max above, so the value is final for the round).
        # Gathers are priced by COUNT, not extent, on this runtime.
        #
        # The inbound key is an untrusted 29-bit WIRE field
        # (layouts.INV_PKF) while the local table has only K rows: a
        # corrupt peer's slot would index out of bounds in this
        # promised-in-bounds gather (undefined), so clamp — a correct
        # peer never sends key >= K, the min fuses into the index
        # computation (no new sparse op), and a clamped bogus slot
        # yields a garbage-but-defined verdict its v_ok mask already
        # ignores.  (The scatter path needs no clamp: mode="drop".)
        # Surfaced by the analysis scatter pass (oob-promised-index).
        kcap = fs.table.vpts.shape[0] - 1
        joint = fs.table.vpts[jnp.minimum(jnp.concatenate(
            [key0.reshape(-1), replay_key.reshape(-1)]), kcap)]
    post0 = joint[:nslot].reshape(key0.shape)
    replay_post = joint[nslot:].reshape(replay_key.shape)
    win0 = v_ok & (pts0 == post0)
    ack_flags = pts0 == post0  # (Rsrc, C): ok bit for every slot of every source
    return fs, ack_flags, win0, replay_post


def _apply_inv_arb(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                   inv_src: FastInv):
    """Batched-mode ``apply_inv``: the vpts scatter-max ONLY.  Verdicts
    (win/ack/nack) are derived per LANE afterwards from a single vpts gather
    (_derived_acks) — gathers are near-free on this runtime while the
    per-slot post0 gather + slot->lane scatter of the wire path are not."""
    v_ok = inv_src.valid & (inv_src.epoch == ctl.epoch[0])[..., None]
    table = _ts_scatter_max(fs.table, inv_src.key, inv_src.pts, v_ok)
    return fs._replace(table=table, meta=_apply_inv_meta(ctl, fs.meta,
                                                         inv_src))


def _apply_inv_meta(ctl: FastCtl, meta, inv_src: FastInv):
    """The apply_inv last_seen heartbeat fold (dense; shared by the XLA
    scatter path and the round-15 mega path)."""
    return meta._replace(
        last_seen=jnp.where(
            inv_src.alive[None, :] & ~ctl.frozen[:, None], ctl.step,
            meta.last_seen,
        )
    )


def _ts_scatter_max(table: FastTable, keys, pts, mask):
    """The shared arbitration core: scatter-MAX of packed timestamps into
    the vpts column for every masked (key, ts) row.  Both engines route
    here — slots (_apply_inv_arb) and lanes (_apply_inv_lanes) differ only
    in which rows the mask admits."""
    oob = table.vpts.shape[0]
    vpts = table.vpts.at[jnp.where(mask, keys, oob)].max(pts, mode="drop")
    return table._replace(vpts=vpts)


def _winner_row_scatter(ctl: FastCtl, table: FastTable, keys, pts, vals,
                        win, vbit, fresh):
    """The shared winner-write core (the round's single [pts|sst|val] table
    scatter): every winning row lands with its own ts, its state chosen by
    the commit bit; the write mask admits only rows deterministic under
    duplicate indices — FRESH rows (unique per (key, ts)) or committing rows
    (all duplicates produce the identical VALID row).  Both engines route
    here — per-slot (_apply_commit) and per-lane (_apply_commit_lanes)
    inputs produce the same written-row multiset."""
    state_new = jnp.where(vbit, t.VALID, t.INVALID)
    head8 = _i32_to_bank(
        jnp.stack([pts, pack_sst(ctl.step, state_new)], axis=-1))
    upd8 = jnp.concatenate([head8, vals], axis=-1)
    write0 = win & (fresh | vbit)
    rows = jnp.where(write0, keys, table.bank.shape[0])
    # set-scatter whose duplicate (key, ts) rows are masked to DETERMINISTIC
    # writers (fresh rows unique per (key, ts); committing re-broadcast
    # duplicates all write the identical VALID row — the _apply_commit
    # soundness argument).  Audited: injectivity is a protocol invariant,
    # not provable from config bounds by the analysis scatter pass.
    with layouts.audited("winner-row-dup-writes-identical"):
        bank = table.bank.at[rows].set(upd8, mode="drop")
    return table._replace(bank=bank)


def _apply_inv_lanes(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                     lanes: LaneBlock, taken_lane):
    """Batched-mode ``apply_inv`` scattering straight from the LANE block:
    identical row multiset to _apply_inv_arb over the compacted slots
    (taken_lane marks exactly the lanes holding a slot; OOB-masked rows cost
    the same as live rows on this chip, so the wider lane extent is free),
    minus the lane->slot take_along routing.

    Returns ``(fs, post_lane)``: on the round-15 mega path the apply
    kernel also reads back the settled per-lane verdict (post_lane), so
    ``_derived_acks`` skips its vpts gather; on the XLA path post_lane is
    None and the gather stays."""
    v_ok = taken_lane & (ctl.epoch == ctl.epoch[0])[:, None]
    if megaround.resolve(cfg):
        vpts, post = megaround.mega_apply(cfg, fs.table.vpts, lanes.key,
                                          lanes.pts, v_ok)
        table = fs.table._replace(vpts=vpts)
        post_lane = post.reshape(lanes.key.shape)
    else:
        table = _ts_scatter_max(fs.table, lanes.key, lanes.pts, v_ok)
        post_lane = None
    meta = fs.meta._replace(
        last_seen=jnp.where(
            ~ctl.frozen[None, :] & ~ctl.frozen[:, None], ctl.step,
            fs.meta.last_seen,
        )
    )
    return fs._replace(table=table, meta=meta), post_lane


def _apply_commit_lanes(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                        lanes: LaneBlock, win_lane, commit_lane):
    """Batched-mode winner table write from the LANE block (vbit = the lane
    committed this round).  win_lane already implies taken_lane
    (_derived_acks), so the written row multiset is exactly the slot path's."""
    vbit = commit_lane & (ctl.epoch == ctl.epoch[0])[:, None]
    table = _winner_row_scatter(ctl, fs.table, lanes.key, lanes.pts,
                                lanes.val, win_lane, vbit, lanes.fresh)
    return fs._replace(table=table)


def _apply_commit(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                  inv_src: FastInv, win0, val_bits, val_epochs):
    """The round's single table write (replaces the reference's separate
    apply_inv value write + apply_val state write): every winning INV slot
    lands its [pts | sst | val] row in one scatter, with the state chosen by the
    slot's VAL bit — VALID if its write committed this round (SURVEY.md §3.1
    tail), INVALID if it is still gathering acks.  A superseded slot (not
    win0) writes nothing: its key belongs to the higher-ts winner, whose own
    VAL will validate it.

    Duplicate (key, ts) slots (a still-in-flight session lane plus replay
    snapshots of the same write, possibly on every replica) could disagree
    on the VAL bit within one round, and XLA scatter order for duplicate
    indices is unspecified — so the write mask admits only rows that are
    deterministic under duplication: FRESH slots (first broadcast of a ts —
    unique per (key, ts) by construction, see FastInv.fresh) write their
    row with their own verdict, while re-broadcast winners write ONLY when
    committing (all committing duplicates produce the identical VALID row;
    non-committing re-broadcasts are no-ops — the table already holds this
    ts's value, and a key VALID at this ts stays readable: VALID means the
    ts committed somewhere, so an idempotent re-INV need not re-invalidate).

    The scatter writes the full [pts | sst | val] bank row as int8 BYTES — a set
    is a pure byte move, and int8 set-scatters move the same bytes ~2.3x
    faster than int32 on this chip.  vpts is not rewritten at all: the
    _apply_inv scatter-max already placed the winner's ts.  Full-row
    windows are the fast TPU scatter path; an offset window was measured
    50x slower."""
    vbit = val_bits & (val_epochs == ctl.epoch[0])[..., None]
    table = _winner_row_scatter(ctl, fs.table, inv_src.key, inv_src.pts,
                                inv_src.val, win0, vbit, inv_src.fresh)
    return fs._replace(table=table)


def _derived_acks(ctl: FastCtl, table: FastTable, taken_lane, pend_key,
                  pend_pts, post_lane=None):
    """Lockstep-batched ACK derivation — the quorum bitmap without the wire,
    computed per LANE (no slot->lane scatter).

    In the batched emulation every replica computes the identical shared
    conflict verdict, and an acker's only per-replica contribution is its
    aliveness, so the gathered-ack bitmap for a broadcast lane is exactly
    the alive-replica mask.  The conflict verdict for a lane is read
    straight off the post-scatter arbiter: its pts survived iff it still
    equals vpts[key] — ONE (R, L) gather replaces the wire path's per-slot
    post0 gather AND the slot->lane ack scatter.  Failure injection stays
    faithful: frozen replicas contribute no bits, and membership changes
    act through the live_mask quorum test as always.  (The sharded engine
    keeps the real ACK collective — on a mesh the verdicts genuinely
    travel.)

    Returns (gained, nacked, win_lane, post_lane), all (R, L)."""
    R = taken_lane.shape[0]
    abits = jnp.sum(
        jnp.where(~ctl.frozen, jnp.int32(1) << jnp.arange(R, dtype=jnp.int32), 0)
    ).astype(jnp.int32)
    if post_lane is None:  # mega path delivers it from the apply kernel
        post_lane = table.vpts[pend_key]  # (R, L) post-scatter arbiter
    survived = post_lane == pend_pts
    gained = jnp.where(taken_lane, abits, 0)
    nacked = taken_lane & ~survived & (abits != 0)
    win_lane = taken_lane & survived
    return gained, nacked, win_lane, post_lane


def _wire_acks(cfg: HermesConfig, ctl: FastCtl, inv_src: FastInv, ack_flags,
               out_inv: FastInv, exchange_ack):
    """Sharded ACK exchange: pack my verdicts for every source's slots, move
    them with the collective, and match the returned echoes against the
    block I actually sent — a delayed or stale ack can never mis-credit a
    different pending update."""
    ok = (
        inv_src.valid & (inv_src.epoch == ctl.epoch[0])[..., None]
        & ~ctl.frozen[0]
    )
    pkf = ((inv_src.key << ACK_KEY_SHIFT)
           | (ack_flags.astype(jnp.int32) << 1)
           | ok.astype(jnp.int32))
    ack8 = _i32_to_bank(jnp.stack([pkf, inv_src.pts], axis=-1))
    out_ack = FastAck(rows8=ack8[None])
    in_ack = exchange_ack(out_ack)  # (1, Rsrc, C): each source's ack of MY slots
    Rs = in_ack.pkf.shape[1]
    # acker epochs ride the INV meta word all-gathered THIS round (epochs
    # are fixed per round), so the ack block needs no epoch collective
    epoch_ok = (inv_src.epoch[None, :] == ctl.epoch[:, None])[..., None]
    matched = (
        out_inv.valid[:, None, :]
        & ((in_ack.pkf & ACK_VALID_MASK) == ACK_VALID_MASK) & epoch_ok
        & ~ctl.frozen[:, None, None]
        & ((in_ack.pkf >> ACK_KEY_SHIFT) == out_inv.key[:, None, :])
        & (in_ack.pts == out_inv.pts[:, None, :])
    )
    aok = (in_ack.pkf & ACK_OK_MASK) == ACK_OK_MASK
    bit = jnp.int32(1) << jnp.arange(Rs, dtype=jnp.int32)[None, :, None]
    gained_slot = jnp.sum(jnp.where(matched, bit, 0), axis=1).astype(jnp.int32)
    nacked_slot = jnp.any(matched & ~aok, axis=1)
    return gained_slot, nacked_slot


def _slot_to_lane_acks(cfg: HermesConfig, gained_slot, nacked_slot, slot_lane):
    """Sharded-mode adapter: wire acks arrive per SLOT; route them back to
    lanes through slot_lane — ONE scatter, the gained bitmap and the nack
    bit packed in one word (uint32: gained can use all 31 mask bits;
    slot_lane is injective per replica, so set/max are equivalent)."""
    R, C = gained_slot.shape
    L = cfg.n_lanes
    gshift = layouts.SLOT_ACK.field("gained").shift
    nmask = layouts.SLOT_ACK.field("nacked").mask
    packed_slot = (
        (gained_slot.astype(jnp.uint32) << gshift)
        | nacked_slot.astype(jnp.uint32)
    )
    lz = jnp.zeros((R * L,), jnp.uint32)
    lanes = lz.at[_gkey(lz, slot_lane)].max(packed_slot, mode="drop").reshape(R, L)
    return (lanes >> gshift).astype(jnp.int32), (lanes & nmask) != 0


def _collect_acks(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                  gained, nacked, taken_lane, read_done,
                  read_extra, pre_comm, post_lane=None, replay_post=None):
    """Coordinator-side ``poll_acks()`` + commit + VAL build
    (BASELINE.json:5).  ``gained``/``nacked`` are per-LANE (R, L): derived
    directly there in batched mode (_derived_acks), routed back from the
    wire slots in sharded mode (_slot_to_lane_acks).  commit = ack bitmap
    covers live_mask (the linearization point, SURVEY.md §3.1); RMW aborts
    on any nack."""
    table, sess, replay, meta = fs.table, fs.sess, fs.replay, fs.meta
    R = gained.shape[0]
    Rs = cfg.n_replicas
    S, RS, L = cfg.n_sessions, cfg.replay_slots, cfg.n_lanes
    step = ctl.step
    frozen = ctl.frozen[:, None]

    full = jnp.int32((1 << Rs) - 1)
    live = ctl.live_mask[:, None]

    infl = sess.status == t.S_INFL
    sacks = jnp.where(infl, sess.acks | gained[:, :S], sess.acks)
    covered = ((sacks | ~live) & full) == full
    # RMW nack: the pending RMW's ts lost arbitration to a concurrent
    # higher-ts update.  With cfg.rmw_retries the session retries in place
    # (back to S_ISSUE with op/key/value/uid and invoke_step intact — the
    # nacked ts is globally dead, so nothing leaks between attempts); only
    # the final failure aborts.  Plain writes ignore nacks and commit by ts
    # order, as always.
    # pre_comm (from _coordinate's row gather): this update was already
    # finished by a replayer — complete it as committed below and keep the
    # nack path away from it (a late nack after the key moved on must not
    # turn an observed commit into an abort).
    nack_rmw = (infl & nacked[:, :S] & (sess.op == t.OP_RMW) & ~frozen
                & ~pre_comm)
    if cfg.rmw_retries > 0:
        retry = nack_rmw & (sess.retries < cfg.rmw_retries)
        abort = nack_rmw & ~retry
    else:
        retry = None
        abort = nack_rmw
    # Commit requires having BROADCAST this round: the slot-aligned VAL (see
    # below) can only notify followers through a slot this lane holds.  A
    # lane whose quorum is completed by a membership change (live_mask
    # shrink) while it is in rebroadcast backoff simply commits at its next
    # broadcast round instead — acks persist in the bitmap, so nothing is
    # lost, and the VAL is never silently dropped.  (pre_comm lanes need no
    # broadcast: their VAL already happened — the replayer's.)
    commit = ((infl & covered & taken_lane[:, :S] & ~frozen & ~nack_rmw)
              | pre_comm)

    # Replay-slot release: a slot whose key's shared arbiter moved past the
    # slot's ts was taken over by a newer write — that writer's VAL will
    # validate the key.  (post_lane already holds vpts[key] per lane in
    # batched mode; the sharded path rides its per-slot verdict gather —
    # _apply_inv's joint index vector — so neither engine pays a separate
    # gather here.)
    if post_lane is not None:
        rowns = replay.pts == post_lane[:, S:]
    else:
        rowns = replay.pts == replay_post

    racks = jnp.where(replay.active, replay.acks | gained[:, S:], replay.acks)
    rcovered = ((racks | ~live) & full) == full
    # A NACKED replay must never commit (round-9; surfaced by the chaos
    # net-drop schedules): the nack proves a strictly-higher ts exists at
    # some live replica, so the replayed value — possibly an ABORTED RMW's,
    # stranded as this shard's stale table max after it missed the winner's
    # INV — is obsolete.  Releasing the slot is safe for liveness: the
    # higher ts cannot have committed without THIS replica's ack (the
    # quorum covers every live replica), so its coordinator/replayer keeps
    # re-broadcasting until it lands here and re-validates the key; if the
    # key sticks, the replay scan re-detects it and the next replay carries
    # the by-then-current row.  (Batched lockstep shares one table, so
    # rnack ⊆ rsuper there — this changes only diverged-table cases.)
    rnack = replay.active & nacked[:, S:] & ~frozen
    rcommit = (replay.active & rcovered & taken_lane[:, S:] & ~frozen
               & ~nacked[:, S:])
    rsuper = replay.active & ~rowns & ~frozen
    replay = replay._replace(
        acks=racks, active=replay.active & ~rcommit & ~rsuper & ~rnack)

    # --- outbound VALs ride the round's INV slots -------------------------
    # Lockstep invariant: a lane can only commit in a round it broadcast in
    # (acks answer this round's INVs), so every committing lane holds a slot
    # in THIS round's compaction.  The VAL is then just a per-slot bit —
    # receivers reconstruct (key, pts) from the INV block they already hold;
    # the winner's single [pts|sst|val] write (_apply_commit) covers the
    # committer's own table too, so no separate commit scatter exists.
    # Returned per LANE; the sharded caller routes it to slots
    # (take_along over slot_lane) to put it on the wire.
    commit_lane = jnp.concatenate([commit, rcommit & rowns], axis=1)

    # --- session completion + stats (fused Pallas kernel) -----------------
    code, ctr, hist_add = kernels.stats_block(
        step, sess.op, sess.invoke_step, commit, abort, read_done
    )
    comp = st.Completions(
        code=code,
        key=sess.key,
        wval=_bank_to_i32(sess.val),
        rval=_bank_to_i32(sess.rd_val),
        ver=pts_ver(sess.pts),
        fc=pts_fc(sess.pts),
        invoke_step=sess.invoke_step,
        commit_step=jnp.broadcast_to(step, (R, S)).astype(jnp.int32),
    )
    meta = meta._replace(
        n_read=meta.n_read + ctr[:, kernels.CTR_READ]
        + jnp.sum(read_extra, axis=1),
        n_write=meta.n_write + ctr[:, kernels.CTR_WRITE],
        n_rmw=meta.n_rmw + ctr[:, kernels.CTR_RMW],
        n_abort=meta.n_abort + ctr[:, kernels.CTR_ABORT],
        lat_sum=meta.lat_sum + ctr[:, kernels.CTR_LATSUM],
        lat_cnt=meta.lat_cnt + ctr[:, kernels.CTR_LATCNT],
        lat_hist=meta.lat_hist + hist_add,
        # packed-ts overflow watermark (HermesConfig.max_key_versions): a
        # dense per-round max that the host checks at counter polls —
        # detection instead of silent compare corruption past the limit
        max_pts=jnp.maximum(meta.max_pts, jnp.max(sess.pts, axis=1)),
        # async failure detection (round-9): fold the staleness reduction
        # into the round — per-peer heartbeat age off this round's own
        # last_seen, clipped non-negative (a replica's row may carry
        # last_seen == step for peers heard this round).  Dense
        # elementwise over an (R_local, R) tile: XLA fuses it into the
        # round, no new sparse ops or collectives.  The host detector
        # harvests it WITH completions (FastRuntime.dispatch_round keeps
        # the device handle in the ring), so an attached MembershipService
        # never issues a synchronous device_get on the dispatch path.
        suspect_age=jnp.maximum(step - meta.last_seen, 0),
    )
    if cfg.phase_metrics:
        # ACK quorum-wait (issue -> commit, in rounds) + nack/retry
        # breakdown.  The histogram is one broadcast compare-and-reduce over
        # (R, S, LAT_BINS) — dense, fusable, same formulation as the Pallas
        # stats kernel's per-bin reductions.
        nbin = st.LAT_BINS
        qwait = jnp.where(commit, step - sess.issue_step, 0)
        cq = jnp.clip(qwait, 0, nbin - 1)
        qhist = jnp.sum(
            (cq[..., None] == jnp.arange(nbin, dtype=jnp.int32))
            & commit[..., None],
            axis=1, dtype=jnp.int32)
        meta = meta._replace(
            n_nack=meta.n_nack
            + jnp.sum(infl & nacked[:, :S] & ~frozen, axis=1,
                      dtype=jnp.int32),
            n_retry=(meta.n_retry
                     + jnp.sum(retry, axis=1, dtype=jnp.int32))
            if retry is not None else meta.n_retry,
            qwait_sum=meta.qwait_sum + jnp.sum(qwait, axis=1,
                                               dtype=jnp.int32),
            qwait_hist=meta.qwait_hist + qhist,
        )

    done = commit | abort
    status = jnp.where(done, t.S_IDLE, sess.status)
    new_retries = sess.retries
    if retry is not None:  # static: rmw_retries=0 compiles the old program
        status = jnp.where(retry, t.S_ISSUE, status)  # disjoint from done
        new_retries = jnp.where(done, 0,
                                jnp.where(retry, sess.retries + 1,
                                          sess.retries))
    sess = sess._replace(
        acks=sacks,
        status=status,
        op_idx=jnp.where(done, sess.op_idx + 1, sess.op_idx),
        retries=new_retries,
    )
    fs = fs._replace(table=table, sess=sess, replay=replay, meta=meta)
    return fs, commit_lane, comp


def fast_round_batched(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """One protocol round, batched lockstep emulation: the broadcast IS the
    lane block (every replica sees the same source-shaped tensors), and the
    ACK bitmap derives from the shared verdicts (_derived_acks) — no
    exchange ops at all on a single chip.  The protocol applies STRAIGHT
    from the lane arrays: compaction (slot_lane) only decides WHICH lanes
    broadcast (taken_lane); the per-slot wire tensors are never built —
    every lane->slot take_along costs ~1.5-2 ms of size-independent
    sparse-op overhead on this runtime (measured; see _compact_out_inv),
    and scatters cost the same over the wider OOB-masked lane extent.  The
    commit decision lands in the same round, so the winner table write
    (_apply_commit_lanes) happens once with the final state — the separate
    VAL phase does not exist here."""
    (fs, lanes, slot_lane, taken_lane, read_done,
     read_extra, sub_comps, pre_comm) = _coordinate(cfg, ctl, fs, stream)
    fs, kpost = _apply_inv_lanes(cfg, ctl, fs, lanes, taken_lane)
    gained, nacked, win_lane, post_lane = _derived_acks(
        ctl, fs.table, taken_lane, lanes.key, lanes.pts, post_lane=kpost
    )
    fs, commit_lane, comp = _collect_acks(cfg, ctl, fs, gained, nacked,
                                          taken_lane, read_done,
                                          read_extra, pre_comm,
                                          post_lane=post_lane)
    fs = _apply_commit_lanes(cfg, ctl, fs, lanes, win_lane, commit_lane)
    if sub_comps:
        comp = tuple(sub_comps) + (comp,)
    return fs, comp


def fast_round_sharded(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """One protocol round on the mesh (transport=tpu_ici, BASELINE.json:5):
    INV and VAL blocks ride ``all_gather`` and the ACK verdicts ride
    ``all_to_all`` over the 'replica' ICI axis."""
    (fs, lanes, slot_lane, taken_lane, read_done,
     read_extra, sub_comps, pre_comm) = _coordinate(cfg, ctl, fs, stream)
    out_inv = _compact_out_inv(ctl, lanes, slot_lane, taken_lane)
    inv_src = jax.tree.map(_ici_gather_src, out_inv)
    fs, ack_flags, win0, replay_post = _apply_inv(cfg, ctl, fs, inv_src,
                                                  fs.replay.key)
    gained_slot, nacked_slot = _wire_acks(
        cfg, ctl, inv_src, ack_flags, out_inv, _ici_route_back
    )
    gained, nacked = _slot_to_lane_acks(cfg, gained_slot, nacked_slot, slot_lane)
    fs, commit_lane, comp = _collect_acks(cfg, ctl, fs, gained, nacked,
                                          taken_lane, read_done,
                                          read_extra, pre_comm,
                                          replay_post=replay_post)
    # VAL phase: a bare per-slot commit-bit tensor over THIS round's INV
    # slots — receivers reconstruct (key, ts) from the INV block they hold,
    # and the epoch check rides the INV meta word gathered above (one
    # all_gather for the whole phase; round-6 collective diet)
    commit_at_slot = jnp.take_along_axis(commit_lane, slot_lane, axis=1)
    val_bits = _ici_gather_src(commit_at_slot)
    fs = _apply_commit(cfg, ctl, fs, inv_src, win0, val_bits, inv_src.epoch)
    if sub_comps:
        comp = tuple(sub_comps) + (comp,)
    return fs, comp


# --------------------------------------------------------------------------
# Step builders
# --------------------------------------------------------------------------


def prep_stream(stream):
    """Device-place an (R, S, G[, U]) op stream for the fast engines,
    converting client value payloads to the engine's byte form.  (A G-major
    transpose was tried here and measured slower.)"""
    uval = stream.uval
    if uval is not None:
        uval = _i32_to_bank(jnp.asarray(uval, jnp.int32))
    return st.OpStream(
        op=jnp.asarray(stream.op),
        key=jnp.asarray(stream.key),
        uval=uval,
    )


def make_fast_ctl(cfg: HermesConfig, step: int,
                  quiesce: bool = False) -> FastCtl:
    r = cfg.n_replicas
    return FastCtl(
        step=jnp.int32(step),
        my_cid=jnp.arange(r, dtype=jnp.int32),
        epoch=jnp.zeros((r,), jnp.int32),
        live_mask=jnp.full((r,), cfg.full_mask, jnp.int32),
        frozen=jnp.zeros((r,), jnp.bool_),
        quiesce=jnp.bool_(quiesce),
    )


@jax.jit
def bump_step(step):
    """Device-side round-counter increment (round-8 device-resident
    control): the runtime's FastCtl.step rides this instead of a fresh
    host scalar, so the steady-state round has zero H2D control
    transfers (membership rows are cached separately behind a dirty
    flag — see FastRuntime._ctl)."""
    return step + jnp.int32(1)


@jax.jit
def pending_sessions(status, live_mask, frozen):
    """One device-side reduction for the drain poll (round-8 satellite):
    count sessions not yet S_DONE on live, unfrozen replicas.  Replaces
    the full (R, S) status fetch per polling iteration with a scalar
    readback; works for the batched and sharded layouts alike (the jit
    respreads the cached ctl rows against the sharded status)."""
    r = jnp.arange(status.shape[0], dtype=jnp.int32)
    active = (((live_mask >> r) & 1) == 1) & jnp.logical_not(frozen)
    undone = (status != t.S_DONE).astype(jnp.int32)
    return jnp.sum(jnp.where(active[:, None], undone, 0))


def build_fast_batched(cfg: HermesConfig, donate: bool = False):
    megaround.resolve(cfg)  # warm the cached probe outside any trace

    def step(fs, stream, ctl):
        return fast_round_batched(cfg, ctl, fs, stream)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def build_fast_scan(cfg: HermesConfig, rounds: int, donate: bool = True):
    """``rounds`` rounds per dispatch (amortizes the host round trip,
    SURVEY.md §7 M6).  Completions feed only the meta counters."""
    megaround.resolve(cfg)  # warm the cached probe outside any trace

    def chunk(fs, stream, ctl):
        def body(carry, off):
            nxt, _comp = fast_round_batched(
                cfg, ctl._replace(step=ctl.step + off), carry, stream
            )
            return nxt, None

        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    return jax.jit(chunk, donate_argnums=(0,) if donate else ())


# --------------------------------------------------------------------------
# Sharded (one replica per device) step: transport=tpu_ici (BASELINE.json:5)
# --------------------------------------------------------------------------


def _ici_gather_src(x):
    """Local (1, ...) leaf -> source-shaped (Rsrc, ...) via all_gather."""
    return jax.lax.all_gather(x[0], "replica", axis=0, tiled=False)


def _ici_route_back(block):
    # out[p][0, q, ...] answers q's INVs; all_to_all on axis 1 delivers
    # in[q][0, p, ...] = p's acks of q's slots.  (The ack block is the
    # single rows8 tensor since round-6 — the acker epochs ride the INV
    # meta all_gather instead of a second collective here.)
    def one(x):
        return jax.lax.all_to_all(x, "replica", split_axis=1, concat_axis=1,
                                  tiled=True)

    return jax.tree.map(one, block)


def build_fast_sharded(cfg: HermesConfig, mesh: Mesh, rounds: int = 1,
                       donate: bool = True):
    """The fast round under shard_map over Mesh(('replica',))."""
    if mesh.shape["replica"] != cfg.n_replicas:
        raise ValueError("mesh 'replica' axis must equal cfg.n_replicas")
    megaround.resolve(cfg)  # warm the cached probe outside any trace

    def shard_body(fs, stream, ctl):
        my = jax.lax.axis_index("replica").astype(jnp.int32)
        lctl = FastCtl(
            step=ctl.step,
            my_cid=my[None],
            epoch=ctl.epoch,
            live_mask=ctl.live_mask,
            frozen=ctl.frozen,
            quiesce=ctl.quiesce,
        )
        if rounds == 1:
            # single-round driver shape: completions come back (FastRuntime /
            # kvs.py consume them for history recording + client futures)
            return fast_round_sharded(cfg, lctl, fs, stream)

        def body(carry, off):
            nxt, _comp = fast_round_sharded(
                cfg, lctl._replace(step=lctl.step + off), carry, stream
            )
            return nxt, None

        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    rspec = P("replica")
    ctl_spec = FastCtl(step=P(), my_cid=P(), epoch=rspec, live_mask=rspec,
                       frozen=rspec, quiesce=P())
    sharded = compat.shard_map(
        shard_body, mesh=mesh,
        in_specs=(rspec, rspec, ctl_spec),
        out_specs=(rspec, rspec) if rounds == 1 else rspec,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def place_fast_sharded(cfg: HermesConfig, mesh: Mesh, fs: FastState, stream):
    sh = NamedSharding(mesh, P("replica"))
    return jax.device_put(fs, sh), jax.device_put(stream, sh)


# --------------------------------------------------------------------------
# Version rebase (round-4): restore packed-ts headroom on long runs
# --------------------------------------------------------------------------


def _rebase_core(cfg: HermesConfig, fs: FastState, busy, uniform=None):
    """Shared rebase body over one table copy (K keys) + local sessions.

    A key is ELIGIBLE iff no replica holds a minted, outstanding timestamp
    for it (no S_INFL session, no active replay slot — ``busy``) and it is
    VALID.  For such keys every replica stores the identical (pts, state,
    value) row (lockstep convergence, see FastTable), and no message or
    session anywhere references its ts, so renaming its version is a pure
    per-key relabeling: new writes mint ver+1 from the REBASED base, and
    per-key ts order going forward is preserved.  Non-eligible keys keep
    their versions (best-effort; the runtime quiesces first so that in
    healthy runs everything is eligible).

    Cross-era ordering for the CHECKER is the runtime's job: it accumulates
    the per-key version delta returned here and adds it back to recorded
    completions (FastRuntime._ver_base), so recorded histories stay
    strictly (ver, fc)-ordered across rebases even though on-device
    versions restart."""
    table, sess, replay = fs.table, fs.sess, fs.replay
    ver = pts_ver(table.vpts)
    rows32 = _bank_to_i32(table.bank)
    state = rows32[:, BANK_SST] & 7
    elig = (busy == 0) & (state == t.VALID) & (ver > 1)
    if uniform is not None:
        # sharded: only keys whose (pts, VALID) agree on EVERY chip — a
        # frozen replica's stale table copy must veto the rebase or the
        # per-chip deltas would diverge under the replicated out_spec
        elig = elig & uniform
    new_ver = jnp.where(elig, jnp.int32(1), ver)
    new_vpts = pack_pts(new_ver, pts_fc(table.vpts))
    rows32 = rows32.at[:, BANK_PTS].set(
        jnp.where(elig, new_vpts, rows32[:, BANK_PTS]))
    new_table = FastTable(vpts=new_vpts, bank=_i32_to_bank(rows32))

    # Stale pts of finished sessions would keep the Meta.max_pts watermark
    # (and thus the overflow guard) pinned at pre-rebase heights: clear
    # everything except genuinely in-flight timestamps.
    kept = sess.status == t.S_INFL
    new_sess_pts = jnp.where(kept, sess.pts, 0)
    r_pts = jnp.where(replay.active, replay.pts, 0)
    new_max = jnp.maximum(
        jnp.max(new_vpts),
        jnp.maximum(jnp.max(new_sess_pts, axis=1), jnp.max(r_pts, axis=1)),
    )
    meta = fs.meta._replace(max_pts=jnp.broadcast_to(new_max,
                                                     fs.meta.max_pts.shape))
    delta = ver - new_ver  # (K,) int32, 0 where untouched
    return fs._replace(table=new_table,
                       sess=sess._replace(pts=new_sess_pts),
                       meta=meta), delta


def _busy_mask(cfg: HermesConfig, sess: FastSess, replay: FastReplay):
    """(K,) int32: 1 where any LOCAL session/replay slot holds a minted
    outstanding ts for the key."""
    K = cfg.n_keys
    busy = jnp.zeros((K,), jnp.int32)
    infl = (sess.status == t.S_INFL).astype(jnp.int32).reshape(-1)
    busy = busy.at[sess.key.reshape(-1)].max(infl, mode="drop")
    ract = replay.active.astype(jnp.int32).reshape(-1)
    busy = busy.at[replay.key.reshape(-1)].max(ract, mode="drop")
    return busy


def build_rebase(cfg: HermesConfig, backend: str = "batched", mesh=None):
    """jitted ``fs -> (fs, delta)`` version-rebase pass (round-3 verdict
    item 4: sustained hot-key chaining burns ~chain_writes versions/round
    against the ~1M packed-ts budget; this resets quiesced keys to version
    1, restoring the full budget).  ``delta`` is the (K,) per-key version
    reduction for the runtime's recorder bookkeeping.  Dense K-sized pass —
    fine for an operation that runs once per ~half-budget (~4k rounds at
    chain_writes=128), never on the hot path."""
    if backend == "batched":

        def rebase(fs):
            return _rebase_core(cfg, fs, _busy_mask(cfg, fs.sess, fs.replay))

        return jax.jit(rebase)

    if backend != "sharded":
        raise ValueError(f"unknown backend {backend!r}")
    if mesh is None:
        raise ValueError("sharded rebase needs a mesh")

    def shard_body(fs):
        # each chip owns a full table copy; busy is OR-reduced and the
        # (pts, VALID) view min/max-reduced across the mesh so every chip
        # makes the identical eligibility decision.  The uniformity check
        # exists for failure injection: a frozen replica misses writes, so
        # its stale rows must veto those keys (all chips see the veto —
        # the reductions are the collectives of this rare pass).
        busy = jax.lax.psum(_busy_mask(cfg, fs.sess, fs.replay), "replica")
        vpts = fs.table.vpts
        valid = ((_bank_to_i32(fs.table.bank)[:, BANK_SST] & 7) == t.VALID
                 ).astype(jnp.int32)
        uniform = (
            (jax.lax.pmax(vpts, "replica") == jax.lax.pmin(vpts, "replica"))
            & (jax.lax.pmin(valid, "replica") == 1)
        )
        return _rebase_core(cfg, fs, busy, uniform)

    rspec = P("replica")
    sharded = compat.shard_map(
        shard_body, mesh=mesh,
        in_specs=(rspec,),
        # delta is device-uniform by construction (psum'd busy + identical
        # converged rows on every chip) — replicate it
        out_specs=(rspec, P()),
    )
    return jax.jit(sharded)
