"""TPU-optimized Hermes protocol round ("faststep").

Same protocol as core/phases.py (the readable reference semantics:
coordinate -> INV -> apply_inv -> ACK -> collect_acks -> VAL -> apply_val,
function roles per BASELINE.json:5), re-engineered for the measured cost
model of the target TPU runtime:

  * every XLA fusion/kernel launch costs ~1.4 ms through the tunneled PJRT
    runtime, so the round is built from the FEWEST possible ops;
  * scatters cost ~4 ns/word and gathers ~0.5 ns/word regardless of table
    size, so message volume (not key count) is the data cost;
  * dense K-sized passes are cheap in bandwidth but each op pays the launch
    tax, so the common path touches the key-state table ONLY through
    gathers/scatters — no full-table passes outside the (gated) replay scan.

The key engineering moves, mapped to the reference:

  1. **Packed Lamport timestamp** ``pts = (ver << PTS_FC_BITS) | fc`` with
     ``fc = (flag << 8) | cid`` (core/timestamps.py).  Lexicographic
     (ver, fc) compare == integer compare on pts, so the reference's
     per-key conflict resolution (max-timestamp wins, SURVEY.md §7 hard
     part 4) becomes a single ``scatter-max`` into the table — the batch
     winner, the stale-INV drop, and the idempotent same-ts re-apply all
     fall out of one atomic max op.  Packing limit: a key supports
     2^(31-PTS_FC_BITS-1) = ~1M versions before the sign bit corrupts the
     compare (HermesConfig.max_key_versions); runs long enough to rotate a
     single key a million times must use the reference phases path.
  2. **Packed state+age** ``sst = (last_change_step << 3) | state``: the
     per-key state machine word and the replay age (SURVEY.md §3.4) travel
     in one scatter.
  3. **Lane compaction with rebroadcast backoff**: outbound INV lanes
     (sessions + replay slots, SURVEY.md §1 L1 "batching") compact to a
     fixed budget C per round, rotating priority so no lane starves; lanes
     already waiting on acks re-broadcast only every ``rebroadcast_every``
     rounds.  Overflowing lanes simply wait a round — re-broadcast of the
     same-ts INV is idempotent, so backpressure is free (SURVEY.md §7 hard
     part 2).
  4. **Replay scan gating**: the full-table stuck-key scan runs under
     ``lax.cond`` every ``replay_scan_every`` rounds (it only matters after
     failures; BASELINE.json:10).
  5. **No vmap**: the body is written with an explicit leading replica axis
     and flat global scatter/gather indices, so the same code runs batched
     (R replicas on one chip, the reference's single-process test mode,
     BASELINE.json:7) and under shard_map (1 replica per chip over the
     'replica' ICI mesh axis — transport=tpu_ici, BASELINE.json:5).

RMW conflicts (YCSB-F, BASELINE.json:8) are detected purely through the
ACK ``ok`` flag: every replica acks every INV, with ok=False iff the INV's
ts is no longer the key's maximum after this round's applies.  A pending
RMW aborts on any nack; plain writes ignore nacks and commit by ts order.
The coordinator receives its own ACK too (the broadcast includes self), so
local supersession needs no separate detection pass.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import kernels
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t

PTS_FC_BITS = 10  # fc = (flag << 8) | cid fits 10 bits (flag 2b, cid 8b)
FC_MASK = (1 << PTS_FC_BITS) - 1
I32_MIN = jnp.iinfo(jnp.int32).min


def pack_pts(ver, fc):
    return (ver << PTS_FC_BITS) | fc


def pts_ver(pts):
    return pts >> PTS_FC_BITS


def pts_fc(pts):
    return pts & FC_MASK


def pack_sst(step, state):
    return (step << 3) | state


def sst_state(sst):
    return sst & 7


def sst_step(sst):
    return sst >> 3


# --------------------------------------------------------------------------
# State containers (leading axis = replicas-on-this-shard: R batched, 1 sharded)
# --------------------------------------------------------------------------


class FastTable(NamedTuple):
    """Key-state table (BASELINE.json:5) as HBM-resident columns.

    Lockstep sharing (measured to dominate the bench; soundness arguments in
    _apply_inv/_coordinate): all replicas of a shard receive the identical
    INV/VAL blocks each round, so the authoritative per-key state —
    ``vpts`` (max applied packed-ts, the Lamport conflict arbiter), ``sst``
    (packed (age_step << 3) | state), ``val`` (value words) — is stored ONCE
    per shard (shape (K,)/(K, V) batched; per-chip in sharded mode, where a
    chip IS one replica and the same body runs with a local view).  Two
    replicas can only disagree on these cells while at least one holds the
    key un-readable, so reads stay correct (see _apply_inv).

    ``pts`` is the only per-replica column — the ISSUE LEDGER (R*K, flat
    global indexing): each replica records the packed ts of its own issued
    writes there so a budget-deferred (not-yet-broadcast) write still forces
    the next same-key issue on that replica to a strictly higher version.
    It is written only at issue time and read only by the issue path.
    """

    pts: jnp.ndarray  # (R*K,) per-replica issue ledger
    sst: jnp.ndarray  # (K,) batched / (R*K,) sharded-global
    vpts: jnp.ndarray  # (K,) batched / (R*K,) sharded-global
    val: jnp.ndarray  # (K, V) batched / (R*K, V) sharded-global


class FastSess(NamedTuple):
    """Client sessions (reference worker.c session arrays, SURVEY.md §1 L5)."""

    status: jnp.ndarray  # (R, S)
    op: jnp.ndarray
    op_idx: jnp.ndarray
    key: jnp.ndarray
    val: jnp.ndarray  # (R, S, V)
    pts: jnp.ndarray  # packed pending-update ts
    acks: jnp.ndarray  # gathered-ack replica bitmap
    rd_val: jnp.ndarray  # (R, S, V)
    invoke_step: jnp.ndarray


class FastReplay(NamedTuple):
    """Replay slots (SURVEY.md §3.4): snapshot of a stuck key's last INV."""

    active: jnp.ndarray  # (R, RS) bool
    key: jnp.ndarray
    pts: jnp.ndarray
    val: jnp.ndarray  # (R, RS, V)
    acks: jnp.ndarray


class FastInv(NamedTuple):
    """Compacted INV block.  Outbound (R, C, ...); inbound (R, Rsrc, C, ...).
    ``epoch``/``alive`` are per-block scalars (a replica's whole batch shares
    one epoch — SURVEY.md §1 L4)."""

    valid: jnp.ndarray
    key: jnp.ndarray
    pts: jnp.ndarray
    val: jnp.ndarray  # (..., C, V)
    epoch: jnp.ndarray  # (R,) / (R, Rsrc)
    alive: jnp.ndarray


class FastAck(NamedTuple):
    """ACK block, slot-aligned with the acked INV block.  ``pkf`` packs
    (key << 2) | (ok << 1) | valid into one word — the echoed key plus the
    conflict flag (ok=False: the INV lost to a higher ts — the RMW nack);
    ``pts`` echoes the acked timestamp.  The echo guarantees a delayed or
    stale ack can never mis-credit a different pending update."""

    pkf: jnp.ndarray  # (R, Rdst, C) outbound / (R, Rsrc, C) inbound
    pts: jnp.ndarray
    epoch: jnp.ndarray  # (R,) / (R, Rsrc)


class FastVal(NamedTuple):
    """VAL block: one bit per INV slot of the SAME round ("this slot's write
    committed — validate its key").  key/ts live in the round's INV block;
    fields stay for structural compatibility but are None in faststep."""

    valid: jnp.ndarray  # (R, C) / (R, Rsrc, C)
    key: Optional[jnp.ndarray]
    pts: Optional[jnp.ndarray]
    epoch: jnp.ndarray


class FastState(NamedTuple):
    table: FastTable
    sess: FastSess
    replay: FastReplay
    meta: st.Meta  # reuse the observability container (leading R axis)


def init_fast_state(cfg: HermesConfig, n_local: int | None = None) -> FastState:
    """Fresh replicated state: all keys Valid at version 0 with the
    recognizable initial value (lo=key, hi=-1) (state.init_table)."""
    r = cfg.n_replicas if n_local is None else n_local
    k, s, rs, v = cfg.n_keys, cfg.n_sessions, cfg.replay_slots, cfg.value_words
    # batched mode shares the authoritative tables across the shard's
    # replicas; sharded init (n_local=r) allocates one set per future shard
    nv = 1 if n_local is None else r
    val = jnp.zeros((nv * k, v), jnp.int32)
    val = val.at[:, 0].set(jnp.tile(jnp.arange(k, dtype=jnp.int32), nv))
    val = val.at[:, 1].set(-1)
    z = lambda *sh: jnp.zeros(sh, jnp.int32)
    meta = st.Meta(
        last_seen=z(r, cfg.n_replicas),
        n_read=z(r),
        n_write=z(r),
        n_rmw=z(r),
        n_abort=z(r),
        lat_sum=z(r),
        lat_cnt=z(r),
        lat_hist=z(r, st.LAT_BINS),
    )
    return FastState(
        table=FastTable(pts=z(r * k), sst=z(nv * k), vpts=z(nv * k), val=val),
        sess=FastSess(
            status=z(r, s), op=z(r, s), op_idx=z(r, s), key=z(r, s),
            val=z(r, s, v), pts=z(r, s), acks=z(r, s),
            rd_val=z(r, s, v), invoke_step=z(r, s),
        ),
        replay=FastReplay(
            active=jnp.zeros((r, rs), jnp.bool_), key=z(r, rs), pts=z(r, rs),
            val=z(r, rs, v), acks=z(r, rs),
        ),
        meta=meta,
    )


# --------------------------------------------------------------------------
# Flat-index gather/scatter helpers (leading replica axis folded in)
# --------------------------------------------------------------------------


def _gkey(col, key, mask=None):
    """Global row index into a flat table column for per-replica keys of any
    rank (R, ...): row = replica*K + key.  Only the small INDEX arrays carry
    the replica axis — the table itself stays flat, which keeps XLA's layout
    row-contiguous (measured ~2.3x faster value scatters than a leading
    replica axis) and avoids all hot-path reshapes.  Masked rows get an
    out-of-bounds index; mode='drop' discards them."""
    r = key.shape[0]
    K = col.shape[0] // r
    ridx = jnp.arange(r, dtype=jnp.int32).reshape((r,) + (1,) * (key.ndim - 1))
    g = ridx * K + key
    if mask is not None:
        g = jnp.where(mask, g, col.shape[0])
    return g


def _fgather(col, key):
    """Gather flat col (R*K,) at per-replica keys (R, ...) -> key-shaped."""
    return col[_gkey(col, key)]


def _fscatter(col, key, val, mask):
    """Masked set-scatter into flat col (R*K[, V])."""
    return col.at[_gkey(col, key, mask)].set(val, mode="drop")


def _fscatter_max(col, key, val, mask):
    """Masked max-scatter — the Lamport conflict resolution (max timestamp
    wins) as one atomic op on the packed-ts column."""
    return col.at[_gkey(col, key, mask)].max(val, mode="drop")


# --------------------------------------------------------------------------
# The round
# --------------------------------------------------------------------------


class FastCtl(NamedTuple):
    """Per-round control: unbatched step scalar (drives the cond-gated
    replay scan) + per-replica membership/failure rows (SURVEY.md §5.3)."""

    step: jnp.ndarray  # () int32 — NOT batched
    my_cid: jnp.ndarray  # (R,)
    epoch: jnp.ndarray  # (R,)
    live_mask: jnp.ndarray  # (R,)
    frozen: jnp.ndarray  # (R,) bool


def _write_value(cfg: HermesConfig, my_cid, op_idx):
    """Unique write values (checker witness): words 0/1 = (lo, hi) uid,
    identical formula to phases._write_value."""
    r, s = op_idx.shape
    sess_idx = jnp.arange(s, dtype=jnp.int32)[None, :]
    lo = op_idx * cfg.n_sessions + sess_idx
    hi = jnp.broadcast_to(my_cid[:, None], lo.shape)
    words = [lo, hi]
    for j in range(2, cfg.value_words):
        words.append(lo * jnp.int32(-1640531527) + jnp.int32(j))
    return jnp.stack(words, axis=-1).astype(jnp.int32)


def _coordinate(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """Intake + local reads + update issue (reference worker-loop front half,
    SURVEY.md §3.1) + the replay scan (cond-gated) + outbound INV build."""
    R, S = fs.sess.status.shape
    K, G, RS = cfg.n_keys, cfg.ops_per_session, cfg.replay_slots
    V = cfg.value_words
    table, sess, replay = fs.table, fs.sess, fs.replay
    frozen = ctl.frozen[:, None]
    step = ctl.step

    # --- intake -----------------------------------------------------------
    if cfg.wrap_stream:
        can_load = (sess.status == t.S_IDLE) & ~frozen
        g = sess.op_idx % G
    else:
        can_load = (sess.status == t.S_IDLE) & (sess.op_idx < G) & ~frozen
        g = jnp.clip(sess.op_idx, 0, G - 1)
    if cfg.device_stream:
        # counter-hash op stream (SURVEY.md §2 "in-kernel PRNG"): ONE shared
        # formula with the host twin (workload.ycsb.stream_hash)
        from hermes_tpu.workload.ycsb import device_stream_params, stream_hash

        read_t, rmw_t = device_stream_params(cfg)
        import numpy as _np

        u_op, u_rmw, hkey = stream_hash(
            cfg,
            ctl.my_cid[:, None].astype(jnp.uint32),
            jnp.arange(S, dtype=jnp.uint32)[None, :],
            sess.op_idx.astype(jnp.uint32),
        )
        new_op = jnp.where(u_op < _np.uint32(read_t), t.OP_READ,
                           jnp.where(u_rmw < _np.uint32(rmw_t), t.OP_RMW,
                                     t.OP_WRITE)).astype(jnp.int32)
        new_key = hkey.astype(jnp.int32)
    else:
        new_op = jnp.take_along_axis(stream.op, g[..., None], axis=2)[..., 0]
        new_key = jnp.take_along_axis(stream.key, g[..., None], axis=2)[..., 0]
    new_val = _write_value(cfg, ctl.my_cid, sess.op_idx)
    if stream.uval is not None:
        # client-supplied payload (hermes_tpu/kvs.py): words 2.. carry the
        # user value; words 0-1 keep the derived unique write id.
        uval = jnp.take_along_axis(stream.uval, g[..., None, None], axis=2)[:, :, 0]
        new_val = jnp.concatenate([new_val[..., :2], uval], axis=-1)
    is_nop = can_load & (new_op == t.OP_NOP)
    status = jnp.where(
        can_load,
        jnp.where(new_op == t.OP_READ, t.S_READ,
                  jnp.where(new_op == t.OP_NOP, t.S_IDLE, t.S_ISSUE)),
        sess.status,
    )
    if not cfg.wrap_stream:
        status = jnp.where((status == t.S_IDLE) & (sess.op_idx >= G), t.S_DONE, status)
    sess = sess._replace(
        status=status,
        op=jnp.where(can_load, new_op, sess.op),
        key=jnp.where(can_load, new_key, sess.key),
        val=jnp.where(can_load[..., None], new_val, sess.val),
        invoke_step=jnp.where(can_load, step, sess.invoke_step),
        op_idx=jnp.where(is_nop, sess.op_idx + 1, sess.op_idx),
    )

    # --- reads + issue -----------------------------------------------------
    k_led = _fgather(table.pts, sess.key)  # my issue ledger
    k_vpts = table.vpts[sess.key]  # shared arbiter (plain key indexing)
    k_valid = sst_state(table.sst[sess.key]) == t.VALID
    # a ledger entry above the shared arbiter = my own not-yet-broadcast
    # write: block further same-key issues until it ships (dup-ts guard)
    pending_local = k_led > k_vpts

    read_done = (sess.status == t.S_READ) & k_valid & ~frozen
    rd_val = table.val[sess.key]  # shared value table: plain key indexing
    sess = sess._replace(
        status=jnp.where(read_done, t.S_IDLE, sess.status),
        op_idx=jnp.where(read_done, sess.op_idx + 1, sess.op_idx),
        rd_val=jnp.where(read_done[..., None], rd_val, sess.rd_val),
    )

    # Same-key same-replica issue arbitration via a small hash-slot race:
    # colliding sessions (same slot) defer to the lowest index; a false
    # collision (different keys, same slot) only delays an issue one round.
    want = (sess.status == t.S_ISSUE) & k_valid & ~pending_local & ~frozen
    HS = cfg.arb_slots
    h = sess.key & (HS - 1)
    idxs = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (R, S))
    arb = jnp.full((R * HS,), jnp.iinfo(jnp.int32).max, jnp.int32)
    arb = arb.at[_gkey(arb, h, want)].min(idxs, mode="drop")
    win = want & (arb[_gkey(arb, h)] == idxs)

    flag = jnp.where(sess.op == t.OP_WRITE, t.FLAG_WRITE, t.FLAG_RMW)
    fc = (flag << 8) | ctl.my_cid[:, None]
    new_pts = pack_pts(jnp.maximum(pts_ver(k_led), pts_ver(k_vpts)) + 1, fc)
    old_val = rd_val  # RMW read-part observes the pre-issue value

    # Issue records only the ledger entry; state+value land via the
    # broadcast INV in _apply_inv (the block includes self) — idempotent
    # for re-broadcasts (SURVEY.md §3.4).
    table = table._replace(
        pts=_fscatter_max(table.pts, sess.key, new_pts, win),
    )
    is_rmw_issue = win & (sess.op == t.OP_RMW)
    sess = sess._replace(
        status=jnp.where(win, t.S_INFL, sess.status),
        pts=jnp.where(win, new_pts, sess.pts),
        acks=jnp.where(win, 0, sess.acks),
        rd_val=jnp.where(is_rmw_issue[..., None], old_val, sess.rd_val),
    )

    # --- replay scan, cond-gated (SURVEY.md §3.4; only matters after
    # failures, so it runs every replay_scan_every rounds) ------------------
    def do_scan(args):
        # The stuck mask lives in the SHARED state, so every live replica
        # sees the same candidates and replays the same keys — duplicate
        # same-ts re-INVs are idempotent (SURVEY.md §3.4), and any live
        # replica alone suffices to finish a dead coordinator's write.
        table, replay = args
        sstK = table.sst.reshape(1, -1)  # (1, nv*K): top_k wants a batch dim
        age = step - sst_step(sstK)
        state = sst_state(sstK)
        # REPLAY is included: the shared mark means SOME replica snapshotted
        # the key, but if every slot-holder dies before committing, the key
        # must be re-detected once it ages again (the mark re-stamps age).
        stuck = (
            (state == t.INVALID) | (state == t.TRANS) | (state == t.REPLAY)
        ) & (age > cfg.replay_age)
        kiota = jnp.arange(sstK.shape[1], dtype=jnp.int32)[None, :]
        score = jnp.where(stuck, -kiota, I32_MIN)
        top, _ = jax.lax.top_k(score, RS)
        cand_ok1 = top[0] != I32_MIN  # (RS,)
        cand1 = jnp.where(cand_ok1, -top[0], 0) % K  # global row -> key id
        cand_ok = jnp.broadcast_to(cand_ok1[None], (R, RS)) & ~frozen[:, :1]
        cand = jnp.broadcast_to(cand1[None], (R, RS))
        # i-th candidate -> i-th free slot (sorted free-slot order)
        free_rank = jnp.cumsum((~replay.active).astype(jnp.int32), axis=1) - 1
        # for each slot: which candidate it takes = rank among free slots
        take = jnp.where(~replay.active, free_rank, RS)
        take_ok = (take < RS) & jnp.take_along_axis(
            jnp.pad(cand_ok, ((0, 0), (0, 1))), jnp.minimum(take, RS), axis=1
        )
        ck = jnp.take_along_axis(jnp.pad(cand, ((0, 0), (0, 1))), jnp.minimum(take, RS), axis=1)
        new_replay = FastReplay(
            active=jnp.where(take_ok, True, replay.active),
            key=jnp.where(take_ok, ck, replay.key),
            pts=jnp.where(take_ok, table.vpts[ck], replay.pts),
            val=jnp.where(take_ok[..., None], table.val[ck], replay.val),
            acks=jnp.where(take_ok, 0, replay.acks),
        )
        new_sst = table.sst.at[jnp.where(take_ok, ck, table.sst.shape[0])].set(
            pack_sst(step, jnp.full(ck.shape, t.REPLAY, jnp.int32)), mode="drop"
        )
        return table._replace(sst=new_sst), new_replay

    table, replay = jax.lax.cond(
        step % cfg.replay_scan_every == 0,
        do_scan,
        lambda args: args,
        (table, replay),
    )

    # --- outbound INV compaction (SURVEY.md §7 hard part 2) ---------------
    # Lanes: sessions 0..S-1, replay slots S..L-1.  Eligible lanes: fresh
    # issues always; waiting lanes every rebroadcast_every rounds; replay
    # slots always.  Priority rotates with the step so no lane starves.
    L, C = cfg.n_lanes, cfg.lane_budget
    infl = sess.status == t.S_INFL
    fresh = win
    waiting = infl & ~fresh
    backoff_ok = (step - sess.invoke_step) % cfg.rebroadcast_every == 0
    sess_elig = (fresh | (waiting & backoff_ok)) & ~frozen
    lane_elig = jnp.concatenate([sess_elig, replay.active & ~frozen], axis=1)
    lane_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None], (R, L))
    rot = (lane_idx + step * 127) % L  # rotating tie-break
    prio = jnp.where(lane_elig, rot, L + rot)
    if C == L:
        # budget covers every lane: slots ARE lanes, no compaction sort
        slot_lane = lane_idx
    elif L < (1 << 15):
        # single-operand sort: pack (prio, lane) into one word — one sort
        # buffer instead of two, fewer layout copies
        packed = jax.lax.sort((prio << 15) | lane_idx, dimension=1)
        slot_lane = packed[:, :C] & ((1 << 15) - 1)  # (R, C) lane id per slot
    else:
        _, perm = jax.lax.sort((prio, lane_idx), dimension=1, num_keys=1,
                               is_stable=True)
        slot_lane = perm[:, :C]

    pend_key = jnp.concatenate([sess.key, replay.key], axis=1)
    pend_pts = jnp.concatenate([sess.pts, replay.pts], axis=1)
    pend_val = jnp.concatenate([sess.val, replay.val], axis=1)
    taken = jnp.take_along_axis(lane_elig, slot_lane, axis=1)
    out_inv = FastInv(
        valid=taken,
        key=jnp.take_along_axis(pend_key, slot_lane, axis=1),
        pts=jnp.take_along_axis(pend_pts, slot_lane, axis=1),
        val=jnp.take_along_axis(
            pend_val, slot_lane[..., None], axis=1
        ),
        epoch=ctl.epoch,
        alive=~ctl.frozen,
    )

    fs = fs._replace(table=table, sess=sess, replay=replay)
    return fs, out_inv, slot_lane, lane_elig, read_done


def _apply_inv(cfg: HermesConfig, ctl: FastCtl, fs: FastState, inv_src: FastInv):
    """Follower-side ``apply_inv()`` (BASELINE.json:5) over the SOURCE-shaped
    block ``inv_src`` (fields (Rsrc, C); epoch/alive (Rsrc,)): per-key winner
    + stale-drop + idempotent re-apply via one scatter-max on the packed ts.

    All table writes go to the SHARED columns (see FastTable).  Soundness of
    sharing under lockstep: a key Valid at ts p on any replica means no
    broadcast INV ever exceeded p (it would have invalidated that replica
    too), so the shared cells — arbitrated by the vpts scatter-max — hold
    exactly ts p's value and state when read through a Valid check.  The
    returned ``ack_flags`` (Rsrc, C) are the shared conflict verdicts (the
    ACK ok bit): conflicts among broadcast writes are global facts, and the
    write-flag tiebreak (types.FLAG_*) guarantees a same-version plain write
    beats any concurrent RMW, which makes the shared verdict equivalent to
    per-replica evaluation.  Epochs are uniform across a shard's replicas
    (FastRuntime bumps them together).  (The reference phases engine keeps
    the fuller per-replica Write/Trans bookkeeping.)"""
    table = fs.table
    step = ctl.step

    key0, pts0 = inv_src.key, inv_src.pts
    v_ok = inv_src.valid & (inv_src.epoch == ctl.epoch[0])[..., None]
    oob = table.vpts.shape[0]
    vpts_col = table.vpts.at[jnp.where(v_ok, key0, oob)].max(pts0, mode="drop")
    post0 = vpts_col[key0]
    win0 = v_ok & (pts0 == post0)
    table = table._replace(
        vpts=vpts_col,
        val=table.val.at[jnp.where(win0, key0, oob)].set(inv_src.val, mode="drop"),
        sst=table.sst.at[jnp.where(win0, key0, oob)].set(
            pack_sst(step, jnp.full(key0.shape, t.INVALID, jnp.int32)), mode="drop"),
    )
    ack_flags = pts0 == post0  # (Rsrc, C): ok bit for every slot of every source

    meta = fs.meta._replace(
        last_seen=jnp.where(
            inv_src.alive[None, :] & ~ctl.frozen[:, None], step, fs.meta.last_seen
        )
    )
    return fs._replace(table=table, meta=meta), ack_flags


def _derived_acks(ctl: FastCtl, out_inv: FastInv, ack_flags):
    """Lockstep-batched ACK derivation — the quorum bitmap without the wire.

    In the batched emulation every replica computes the identical shared
    conflict verdict (ack_flags row r = the flags for replica r's slots),
    and an acker's only per-replica contribution is its aliveness, so the
    gathered-ack bitmap for a valid slot is exactly the alive-replica mask.
    Failure injection stays faithful: frozen replicas contribute no bits,
    and membership changes act through the live_mask quorum test as always.
    (The sharded engine keeps the real ACK collective — on a mesh the
    verdicts genuinely travel.)"""
    R, C = out_inv.valid.shape
    abits = jnp.sum(
        jnp.where(~ctl.frozen, jnp.int32(1) << jnp.arange(R, dtype=jnp.int32), 0)
    ).astype(jnp.int32)
    gained_slot = jnp.where(out_inv.valid, abits, 0)
    nacked_slot = out_inv.valid & ~ack_flags & (abits != 0)
    return gained_slot, nacked_slot


def _wire_acks(cfg: HermesConfig, ctl: FastCtl, inv_src: FastInv, ack_flags,
               out_inv: FastInv, exchange_ack):
    """Sharded ACK exchange: pack my verdicts for every source's slots, move
    them with the collective, and match the returned echoes against the
    block I actually sent — a delayed or stale ack can never mis-credit a
    different pending update."""
    ok = (
        inv_src.valid & (inv_src.epoch == ctl.epoch[0])[..., None]
        & ~ctl.frozen[0]
    )
    pkf = ((inv_src.key << 2) | (ack_flags.astype(jnp.int32) << 1)
           | ok.astype(jnp.int32))
    out_ack = FastAck(pkf=pkf[None], pts=inv_src.pts[None], epoch=ctl.epoch)
    in_ack = exchange_ack(out_ack)  # (1, Rsrc, C): each source's ack of MY slots
    Rs = in_ack.pkf.shape[1]
    epoch_ok = (in_ack.epoch == ctl.epoch[:, None])[..., None]
    matched = (
        out_inv.valid[:, None, :] & ((in_ack.pkf & 1) == 1) & epoch_ok
        & ~ctl.frozen[:, None, None]
        & ((in_ack.pkf >> 2) == out_inv.key[:, None, :])
        & (in_ack.pts == out_inv.pts[:, None, :])
    )
    aok = (in_ack.pkf & 2) == 2
    bit = jnp.int32(1) << jnp.arange(Rs, dtype=jnp.int32)[None, :, None]
    gained_slot = jnp.sum(jnp.where(matched, bit, 0), axis=1).astype(jnp.int32)
    nacked_slot = jnp.any(matched & ~aok, axis=1)
    return gained_slot, nacked_slot


def _collect_acks(cfg: HermesConfig, ctl: FastCtl, fs: FastState,
                  gained_slot, nacked_slot, slot_lane, lane_elig, read_done):
    """Coordinator-side ``poll_acks()`` + commit + VAL build
    (BASELINE.json:5).  Per-slot ack bits (derived or wired) scatter back to
    lanes through slot_lane; commit = ack bitmap covers live_mask (the
    linearization point, SURVEY.md §3.1); RMW aborts on any nack."""
    table, sess, replay, meta = fs.table, fs.sess, fs.replay, fs.meta
    R, C = gained_slot.shape
    Rs = cfg.n_replicas
    S, RS, L = cfg.n_sessions, cfg.replay_slots, cfg.n_lanes
    step = ctl.step
    frozen = ctl.frozen[:, None]

    lz = jnp.zeros((R * L,), jnp.int32)
    gained = lz.at[_gkey(lz, slot_lane)].max(gained_slot, mode="drop").reshape(R, L)
    nacked = lz.at[_gkey(lz, slot_lane)].max(
        nacked_slot.astype(jnp.int32), mode="drop").reshape(R, L).astype(jnp.bool_)

    full = jnp.int32((1 << Rs) - 1)
    live = ctl.live_mask[:, None]

    infl = sess.status == t.S_INFL
    sacks = jnp.where(infl, sess.acks | gained[:, :S], sess.acks)
    covered = ((sacks | ~live) & full) == full
    abort = infl & nacked[:, :S] & (sess.op == t.OP_RMW) & ~frozen
    # Commit requires having BROADCAST this round: the slot-aligned VAL (see
    # below) can only notify followers through a slot this lane holds.  A
    # lane whose quorum is completed by a membership change (live_mask
    # shrink) while it is in rebroadcast backoff simply commits at its next
    # broadcast round instead — acks persist in the bitmap, so nothing is
    # lost, and the VAL is never silently dropped.
    commit = infl & covered & lane_elig[:, :S] & ~frozen & ~abort

    # Replay-slot release: a slot whose key's shared arbiter moved past the
    # slot's ts was taken over by a newer write — that writer's VAL will
    # validate the key.
    rowns = replay.pts == table.vpts[replay.key]

    racks = jnp.where(replay.active, replay.acks | gained[:, S:], replay.acks)
    rcovered = ((racks | ~live) & full) == full
    rcommit = replay.active & rcovered & lane_elig[:, S:] & ~frozen
    rsuper = replay.active & ~rowns & ~frozen
    replay = replay._replace(acks=racks, active=replay.active & ~rcommit & ~rsuper)

    # --- outbound VALs ride the round's INV slots -------------------------
    # Lockstep invariant: a lane can only commit in a round it broadcast in
    # (acks answer this round's INVs), so every committing lane holds a slot
    # in THIS round's compaction.  The VAL is then just a per-slot bit —
    # receivers reconstruct (key, pts) from the INV block they already hold;
    # its shared Valid write (with the vpts ownership check) also covers the
    # committer's own table, so no separate commit scatter exists.
    commit_lane = jnp.concatenate([commit, rcommit & rowns], axis=1)
    commit_at_slot = jnp.take_along_axis(commit_lane, slot_lane, axis=1)
    out_val = FastVal(valid=commit_at_slot, key=None, pts=None, epoch=ctl.epoch)

    # --- session completion + stats (fused Pallas kernel) -----------------
    code, ctr, hist_add = kernels.stats_block(
        step, sess.op, sess.invoke_step, commit, abort, read_done
    )
    comp = st.Completions(
        code=code,
        key=sess.key,
        wval=sess.val,
        rval=sess.rd_val,
        ver=pts_ver(sess.pts),
        fc=pts_fc(sess.pts),
        invoke_step=sess.invoke_step,
        commit_step=jnp.broadcast_to(step, (R, S)).astype(jnp.int32),
    )
    meta = meta._replace(
        n_read=meta.n_read + ctr[:, kernels.CTR_READ],
        n_write=meta.n_write + ctr[:, kernels.CTR_WRITE],
        n_rmw=meta.n_rmw + ctr[:, kernels.CTR_RMW],
        n_abort=meta.n_abort + ctr[:, kernels.CTR_ABORT],
        lat_sum=meta.lat_sum + ctr[:, kernels.CTR_LATSUM],
        lat_cnt=meta.lat_cnt + ctr[:, kernels.CTR_LATCNT],
        lat_hist=meta.lat_hist + hist_add,
    )

    done = commit | abort
    sess = sess._replace(
        acks=sacks,
        status=jnp.where(done, t.S_IDLE, sess.status),
        op_idx=jnp.where(done, sess.op_idx + 1, sess.op_idx),
    )
    return fs._replace(table=table, sess=sess, replay=replay, meta=meta), out_val, comp


def _apply_val(cfg: HermesConfig, ctl: FastCtl, fs: FastState, val_bits,
               val_epochs, inv_src: FastInv):
    """VAL apply (SURVEY.md §3.1 tail): ts-matching keys go Valid.  VALs are
    slot-aligned bits ((Rsrc, C)) over the same round's INV block; the write
    lands once in the shared state table, guarded by the shared arbiter so a
    VAL whose write was superseded this round is a no-op."""
    table = fs.table
    key0 = inv_src.key
    ok0 = (
        val_bits
        & inv_src.valid
        & (val_epochs == ctl.epoch[0])[..., None]
        & (inv_src.pts == table.vpts[key0])
    )
    sst = table.sst.at[jnp.where(ok0, key0, table.sst.shape[0])].set(
        pack_sst(ctl.step, jnp.full(key0.shape, t.VALID, jnp.int32)), mode="drop"
    )
    return fs._replace(table=table._replace(sst=sst))


def fast_round_batched(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """One protocol round, batched lockstep emulation: the broadcast IS the
    outbound block (every replica sees the same source-shaped tensors), and
    the ACK bitmap derives from the shared verdicts (_derived_acks) — no
    exchange ops at all on a single chip."""
    fs, out_inv, slot_lane, lane_elig, read_done = _coordinate(cfg, ctl, fs, stream)
    fs, ack_flags = _apply_inv(cfg, ctl, fs, out_inv)
    gained_slot, nacked_slot = _derived_acks(ctl, out_inv, ack_flags)
    fs, out_val, comp = _collect_acks(cfg, ctl, fs, gained_slot, nacked_slot,
                                      slot_lane, lane_elig, read_done)
    fs = _apply_val(cfg, ctl, fs, out_val.valid, out_val.epoch, out_inv)
    return fs, comp


def fast_round_sharded(cfg: HermesConfig, ctl: FastCtl, fs: FastState, stream):
    """One protocol round on the mesh (transport=tpu_ici, BASELINE.json:5):
    INV and VAL blocks ride ``all_gather`` and the ACK verdicts ride
    ``all_to_all`` over the 'replica' ICI axis."""
    fs, out_inv, slot_lane, lane_elig, read_done = _coordinate(cfg, ctl, fs, stream)
    inv_src = jax.tree.map(_ici_gather_src, out_inv)
    fs, ack_flags = _apply_inv(cfg, ctl, fs, inv_src)
    gained_slot, nacked_slot = _wire_acks(
        cfg, ctl, inv_src, ack_flags, out_inv, _ici_route_back
    )
    fs, out_val, comp = _collect_acks(cfg, ctl, fs, gained_slot, nacked_slot,
                                      slot_lane, lane_elig, read_done)
    val_bits = _ici_gather_src(out_val.valid)
    val_epochs = _ici_gather_src(out_val.epoch)
    fs = _apply_val(cfg, ctl, fs, val_bits, val_epochs, inv_src)
    return fs, comp


# --------------------------------------------------------------------------
# Step builders
# --------------------------------------------------------------------------


def prep_stream(stream):
    """Device-place an (R, S, G[, U]) op stream for the fast engines.
    (A G-major transpose was tried here and measured slower.)"""
    return st.OpStream(
        op=jnp.asarray(stream.op),
        key=jnp.asarray(stream.key),
        uval=None if stream.uval is None else jnp.asarray(stream.uval),
    )


def make_fast_ctl(cfg: HermesConfig, step: int) -> FastCtl:
    r = cfg.n_replicas
    return FastCtl(
        step=jnp.int32(step),
        my_cid=jnp.arange(r, dtype=jnp.int32),
        epoch=jnp.zeros((r,), jnp.int32),
        live_mask=jnp.full((r,), cfg.full_mask, jnp.int32),
        frozen=jnp.zeros((r,), jnp.bool_),
    )


def build_fast_batched(cfg: HermesConfig, donate: bool = False):
    def step(fs, stream, ctl):
        return fast_round_batched(cfg, ctl, fs, stream)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def build_fast_scan(cfg: HermesConfig, rounds: int, donate: bool = True):
    """``rounds`` rounds per dispatch (amortizes the host round trip,
    SURVEY.md §7 M6).  Completions feed only the meta counters."""

    def chunk(fs, stream, ctl):
        def body(carry, off):
            nxt, _comp = fast_round_batched(
                cfg, ctl._replace(step=ctl.step + off), carry, stream
            )
            return nxt, None

        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    return jax.jit(chunk, donate_argnums=(0,) if donate else ())


# --------------------------------------------------------------------------
# Sharded (one replica per device) step: transport=tpu_ici (BASELINE.json:5)
# --------------------------------------------------------------------------


def _ici_gather_src(x):
    """Local (1, ...) leaf -> source-shaped (Rsrc, ...) via all_gather."""
    return jax.lax.all_gather(x[0], "replica", axis=0, tiled=False)


def _ici_route_back(block):
    # out[p][0, q, ...] answers q's INVs; all_to_all on axis 1 delivers
    # in[q][0, p, ...] = p's acks of q's slots.  1-D per-block scalars
    # (epoch, local shape (1,)) ride an all_gather instead.
    def one(x):
        if x.ndim == 1:  # per-block epoch, local (1,) -> (1, Rsrc)
            return jax.lax.all_gather(x[0], "replica", axis=0, tiled=False)[None]
        return jax.lax.all_to_all(x, "replica", split_axis=1, concat_axis=1, tiled=True)

    return jax.tree.map(one, block)


def build_fast_sharded(cfg: HermesConfig, mesh: Mesh, rounds: int = 1,
                       donate: bool = True):
    """The fast round under shard_map over Mesh(('replica',))."""
    if mesh.shape["replica"] != cfg.n_replicas:
        raise ValueError("mesh 'replica' axis must equal cfg.n_replicas")

    def shard_body(fs, stream, ctl):
        my = jax.lax.axis_index("replica").astype(jnp.int32)
        lctl = FastCtl(
            step=ctl.step,
            my_cid=my[None],
            epoch=ctl.epoch,
            live_mask=ctl.live_mask,
            frozen=ctl.frozen,
        )
        if rounds == 1:
            # single-round driver shape: completions come back (FastRuntime /
            # kvs.py consume them for history recording + client futures)
            return fast_round_sharded(cfg, lctl, fs, stream)

        def body(carry, off):
            nxt, _comp = fast_round_sharded(
                cfg, lctl._replace(step=lctl.step + off), carry, stream
            )
            return nxt, None

        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    rspec = P("replica")
    ctl_spec = FastCtl(step=P(), my_cid=P(), epoch=rspec, live_mask=rspec, frozen=rspec)
    sharded = jax.shard_map(
        shard_body, mesh=mesh,
        in_specs=(rspec, rspec, ctl_spec),
        out_specs=(rspec, rspec) if rounds == 1 else rspec,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def place_fast_sharded(cfg: HermesConfig, mesh: Mesh, fs: FastState, stream):
    sh = NamedSharding(mesh, P("replica"))
    return jax.device_put(fs, sh), jax.device_put(stream, sh)
