"""Protocol core: the per-key write state machine, timestamps, and phases.

This is SURVEY.md §1 L3 ("spacetime") rebuilt as data-parallel array code:
the reference's ``broadcast_inv()/poll_acks()/broadcast_val()`` coordinator
loop and ``apply_inv()`` follower handler (names per BASELINE.json:5) become
pure functions over a struct-of-arrays key-state table.
"""
