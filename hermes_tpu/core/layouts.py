"""Declared bit layouts of every hand-packed word in the fast engines.

The TPU engines re-encode the Hermes protocol's model-checked invariants
(Katsarakis et al., ASPLOS 2020) as packed int32 bitfields — the Lamport
timestamp ``(ver << 10) | fc``, the INV header ``(valid << 30) |
(fresh << 29) | key``, the fused arbiter+compaction sort key
``(band << 29) | sub`` — and a field that silently aliases a neighbor's
bits corrupts arbitration without any runtime error.  Before this module
the layouts existed only as scattered magic literals (``1 << 29`` in
config validation, ``& 0xFFFF`` masks in faststep) that could drift apart
silently.

This table is the single source of truth, consumed by THREE clients so the
declarations cannot drift from the code:

  * ``core/faststep.py`` derives its runtime shift/mask constants from the
    fields declared here;
  * ``hermes_tpu/config.py`` derives its validation bounds (``n_keys`` must
    fit the INV key field, ``chain_writes`` the chain-rank field, ...);
  * ``hermes_tpu/analysis`` (the static jaxpr analyzer) proves, at trace
    time, that every shift/or pack in the lowered round respects these
    layouts under the config's seeded bounds — the CI gate
    ``scripts/check_analysis.py`` polices it.

Every layout targets a 32-bit word.  ``word_bits=31`` means the sign bit
must stay clear (the word is compared or max-scattered as a SIGNED int32 —
e.g. the packed timestamp, whose integer compare must equal the
lexicographic (ver, fc) compare); ``word_bits=32`` marks unsigned words
that may use all 32 bits.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple, Tuple


class Field(NamedTuple):
    """One bitfield: ``bits`` wide starting at ``shift``."""

    name: str
    shift: int
    bits: int

    @property
    def mask(self) -> int:
        """Word mask selecting this field's bits."""
        return ((1 << self.bits) - 1) << self.shift

    @property
    def cap(self) -> int:
        """Exclusive upper bound on the field's (unshifted) value."""
        return 1 << self.bits


class Layout(NamedTuple):
    """A packed word: named disjoint fields in a 31/32-bit budget."""

    name: str
    doc: str
    fields: Tuple[Field, ...]
    word_bits: int = 31  # 31 = signed int32, sign bit must stay clear

    def field(self, name: str) -> Field:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"layout {self.name!r} has no field {name!r}")

    def validate(self) -> None:
        used = 0
        for f in self.fields:
            if f.shift < 0 or f.bits <= 0:
                raise ValueError(f"{self.name}.{f.name}: bad shift/bits")
            if f.shift + f.bits > self.word_bits:
                raise ValueError(
                    f"{self.name}.{f.name}: bits [{f.shift}, "
                    f"{f.shift + f.bits}) exceed the {self.word_bits}-bit "
                    f"word budget")
            if used & f.mask:
                raise ValueError(f"{self.name}.{f.name}: overlaps a "
                                 f"previously declared field")
            used |= f.mask


# --------------------------------------------------------------------------
# The packed words (see ARCHITECTURE.md "Static invariants" for the prose
# table: word, field, bound, and which analyzer pass proves it).
# --------------------------------------------------------------------------

#: Packed Lamport timestamp (core/timestamps.py, faststep.pack_pts):
#: integer compare == lexicographic (ver, flag, cid) compare, which is what
#: turns per-key conflict resolution into one scatter-max.  The ver field
#: spans 21 bits but the enforced version budget is 2^20
#: (config.max_key_versions): one spare bit of headroom so chain minting
#: (ver + 1 + chain_rank) and overlapping per-replica ranges can never
#: carry into the sign bit between watermark polls.
PTS = Layout("pts", "packed Lamport timestamp (ver | flag | cid)", (
    Field("cid", 0, 8),     # replica id (tie-break; n_replicas <= 31)
    Field("flag", 8, 2),    # write-kind flag (types.FLAG_WRITE beats RMW)
    Field("ver", 10, 21),   # key version; budget 2^20 (one headroom bit)
))

#: Packed per-key state+age word (faststep.pack_sst): the state machine
#: word and the replay-age step stamp travel in one scatter.  The step
#: field bounds how long a run may go before the age compare would wrap:
#: 2^28 rounds (~50 days at 60 rounds/s) — the analyzer seeds ctl.step
#: from this declared budget.
SST = Layout("sst", "packed key state + last-change step", (
    Field("state", 0, 3),   # types.VALID..REPLAY (5 states)
    Field("step", 3, 28),   # last-change step (replay age origin)
))

#: INV wire-header word (FastInv.pkf): key + fresh/valid bits in one word
#: so compaction is one take_along and the sharded wire one all_gather.
INV_PKF = Layout("inv_pkf", "INV header (valid | fresh | key)", (
    Field("key", 0, 29),    # bounds n_keys (config validation)
    Field("fresh", 29, 1),  # first broadcast of this ts (unique (key, ts))
    Field("valid", 30, 1),  # slot holds a live INV
))

#: ACK wire-header word (FastAck.pkf, faststep._wire_acks): the echoed key
#: plus the conflict verdict and validity bits.
ACK_PKF = Layout("ack_pkf", "ACK header (key | ok | valid)", (
    Field("valid", 0, 1),   # acker saw a live INV in this slot
    Field("ok", 1, 1),      # conflict flag (False = the RMW nack)
    Field("key", 2, 29),    # echoed key (same capacity as inv_pkf.key)
))

#: Round-6 fused arbiter+compaction sort key (faststep._coordinate): band
#: 0 = waiting/replay (sub = rotation index over lanes), band 1 = fresh
#: issue runs (sub = per-round ROTATED key, keeping equal-key runs
#: contiguous), band 2 = ineligible.  sub must hold both n_keys and
#: n_lanes; the rotation arithmetic additionally bounds both by ROT_CAP
#: (see below), which config.use_fused_sort enforces.
FUSED_KEY = Layout("fused_key", "fused lane-sort key (band | sub)", (
    Field("sub", 0, 29),    # rotated key (band 1) / rotation index (band 0)
    Field("band", 29, 2),   # 0 waiting/replay, 1 fresh runs, 2 ineligible
))

#: Per-lane verdict word routed back through the fused sort's one
#: permutation scatter: chain rank + issue/taken bits (bits 16-19 spare).
LANE_WORD = Layout("lane_word", "fused-path per-lane verdict", (
    Field("chain_rank", 0, 16),  # rank within an equal-key run (chaining)
    Field("issue", 20, 1),       # won arbitration this round
    Field("taken", 21, 1),       # holds a compaction slot this round
))

#: Split sort-arbiter win word (the fused path's A/B baseline): same
#: chain-rank field, win bit at the same position as lane_word.issue so
#: the two programs stay visually diffable.
ARB_WORD = Layout("arb_word", "split sort-arbiter win verdict", (
    Field("chain_rank", 0, 16),
    Field("win", 20, 1),
))

#: Sharded slot->lane ack routing word (faststep._slot_to_lane_acks):
#: uint32, so the gained bitmap can use 31 bits above the nack bit.
SLOT_ACK = Layout("slot_ack", "sharded per-slot ack word (uint32)", (
    Field("nacked", 0, 1),
    Field("gained", 1, 31),  # replica bitmap of acks gained this round
), word_bits=32)

#: Per-block wire scalars (FastInv.meta): a replica's whole batch shares
#: one epoch, so epoch+alive ride one collective operand.
BLOCK_META = Layout("block_meta", "INV block scalars (epoch | alive)", (
    Field("alive", 0, 1),
    Field("epoch", 1, 30),
))

#: Round-17 value-heap extent reference (hermes_tpu/heap): the MICA-style
#: variable-length value of a key travels the protocol as ONE packed word
#: in the row's first payload slot — ``(granule index << 12) | byte
#: length`` into the replica's HBM-resident append log.  The heap write
#: lands the extent BEFORE the INV issues, so the wire moves only this
#: word and the round census is untouched.  ``len`` bounds
#: ``config.max_value_bytes`` (exclusive cap 4096); ``gran`` bounds the
#: log capacity at 2^19 granules x HEAP_GRANULE bytes.  Granule 0 is
#: reserved: ref word 0 == "no extent" (the zero-initialized bank row),
#: so appends start at granule 1.  Sign bit stays clear — the word rides
#: int32 value columns the analyzer's bitpack pass proves.
HEAP_REF = Layout("heap_ref", "value-heap extent ref (gran | len)", (
    Field("len", 0, 12),    # extent byte length; bounds max_value_bytes
    Field("gran", 12, 19),  # granule index; bounds heap_bytes/HEAP_GRANULE
))

#: Value-heap allocation granule (bytes): extents are granule-aligned so
#: the 19-bit gran field addresses HEAP_GRANULE * 2^19 = 8 MiB of log.
HEAP_GRANULE = 16

#: Split-path single-operand compaction key (faststep._coordinate, C < L):
#: (band | rotation | lane) with lane/rotation widths chosen per shape at
#: trace time — declared here as a NOTE, not a fixed layout: the analyzer
#: proves it per-config from the traced constants.

class RowTable(NamedTuple):
    """A packed row layout: named rows inside a fixed-width minor axis
    (the row analogue of ``Layout`` for arrays like the stats kernel's
    ``(R, width)`` counter block — declared once so the kernel, the
    Meta fold in faststep, and the analyzer's kernel seeds all read the
    same table instead of a bare ``range(6)``)."""

    name: str
    doc: str
    rows: Tuple[str, ...]
    width: int

    def row(self, name: str) -> int:
        try:
            return self.rows.index(name)
        except ValueError:
            raise KeyError(f"row table {self.name!r} has no row {name!r}")

    def validate(self) -> None:
        if len(set(self.rows)) != len(self.rows):
            raise ValueError(f"{self.name}: duplicate row names")
        if len(self.rows) > self.width:
            raise ValueError(
                f"{self.name}: {len(self.rows)} rows exceed the declared "
                f"width {self.width}")


#: Counter rows of the stats_block kernel's packed (R, width) output
#: (core/kernels.py): the per-round op counters + the commit-latency
#: sum/count pair, accumulated across grid revisits; rows beyond the
#: declared ones are zero padding (the width keeps the minor axis a
#: power of two for the TPU lane tiling).
STATS_CTR = RowTable("stats_ctr", "stats_block packed counter rows", (
    "read", "write", "rmw", "abort", "lat_sum", "lat_cnt",
), width=8)

ALL = (PTS, SST, INV_PKF, ACK_PKF, FUSED_KEY, LANE_WORD, ARB_WORD,
       SLOT_ACK, BLOCK_META, HEAP_REF)
for _l in ALL:
    _l.validate()
STATS_CTR.validate()

# cross-layout consistency: the ACK echoes the INV's key verbatim
assert ACK_PKF.field("key").bits == INV_PKF.field("key").bits

# --------------------------------------------------------------------------
# Derived budgets (the constants the runtime + config consume)
# --------------------------------------------------------------------------

#: fc = (flag << 8) | cid — the low-word of the packed ts.
PTS_FC_BITS = PTS.field("ver").shift
FC_MASK = PTS.field("flag").mask | PTS.field("cid").mask
assert FC_MASK == (1 << PTS_FC_BITS) - 1

#: Enforced version budget: one headroom bit under the declared ver field
#: (see PTS doc) — config.max_key_versions and the runtime watermark guard.
MAX_KEY_VERSIONS = 1 << (PTS.field("ver").bits - 1)

SST_STATE_BITS = SST.field("state").shift + 0  # == 3
MAX_STEPS = SST.field("step").cap  # analyzer seed bound for ctl.step

#: Value-heap budgets derived from the declared ref word (round-17):
#: config validation and the heap allocator both read these — a field
#: edit here moves every bound with it.
MAX_VALUE_BYTES = HEAP_REF.field("len").cap - 1
MAX_HEAP_BYTES = HEAP_GRANULE * HEAP_REF.field("gran").cap

#: Anti-starvation rotation stride (fused + split compaction paths): the
#: priority rotation advances by ROT_STRIDE lanes/keys per round.  The
#: rotation product ``(step % n) * ROT_STRIDE + n`` must fit int32, which
#: bounds the rotated domain at ROT_CAP entries (config.use_fused_sort
#: enforces it; far above any reachable shape — 2^24 lanes/keys).
ROT_STRIDE = 127
ROT_CAP = (1 << 31) // (ROT_STRIDE + 1)


# --------------------------------------------------------------------------
# Audit annotations (consumed by hermes_tpu/analysis)
# --------------------------------------------------------------------------

AUDIT_PREFIX = "hermes_audit"


def audited(tag: str):
    """Trace-time audit annotation: marks the ops built inside the scope as
    REVIEWED exceptions to a static-analysis rule, with ``tag`` naming the
    invariant that justifies them (e.g. a set-scatter whose duplicate
    indices provably write identical rows).  Implemented as a
    ``jax.named_scope`` so the marker rides the jaxpr's name stack into
    the analyzer — no runtime cost, no lowering change.  The analyzer
    downgrades findings inside an audited scope to ``info`` and carries
    the tag into the finding record, so every suppression is visible in
    the findings stream instead of silently absent."""
    import jax

    if not tag or any(c in tag for c in "[]"):
        raise ValueError("audit tag must be a non-empty string without "
                         "square brackets")
    return jax.named_scope(f"{AUDIT_PREFIX}[{tag}]")


@contextlib.contextmanager
def unaudited():
    """Test hook: a no-op scope with the same surface as audited() —
    monkeypatching ``audited`` to this must make the analyzer flag the
    previously audited sites (the CI mutation test for the scatter pass)."""
    yield
