"""Environment compatibility shims (JAX API versions, native toolchains).

The sharded engines are written against the stable ``jax.shard_map`` API
(with ``check_vma``); older JAX (< 0.5) ships it as
``jax.experimental.shard_map.shard_map`` with the ``check_rep`` spelling of
the same knob.  One resolver here keeps every build site identical.

``load_native`` is the shared dlopen-or-rebuild policy for the repo's C++
components (checker/fast.py, transport/tcp.py): a checked-in ``.so`` built
by a foreign toolchain can be newer-than-source by mtime yet still fail to
load (e.g. it links a libstdc++ symbol version this machine doesn't have) —
the fallback rebuilds from source with the local compiler.
"""

from __future__ import annotations

import ctypes


def shard_map(f, mesh, in_specs, out_specs):
    """``jax.shard_map(..., check_vma=False)`` on any supported JAX."""
    import jax  # deferred: load_native callers stay importable without jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def load_native(ensure_built) -> ctypes.CDLL:
    """dlopen the path ``ensure_built(force)`` returns; on OSError (foreign
    toolchain binary) force a from-source rebuild and retry once."""
    try:
        return ctypes.CDLL(str(ensure_built(False)))
    except OSError:
        return ctypes.CDLL(str(ensure_built(True)))
