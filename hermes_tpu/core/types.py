"""Shared constants: key states, op codes, session states.

Key states mirror the reference per-key state machine
Valid/Invalid/Write/Replay (BASELINE.json:5) plus Trans, the transient state a
coordinator's pending write enters when a higher-timestamp INV supersedes it
(Hermes paper §3; SURVEY.md §3.1).  Everything is int32 — the TPU-friendly
scalar type — and replica sets are int32 bitmaps (<=32 replicas).
"""

from __future__ import annotations

# --- Per-key states (key-state table `state` column) ---------------------
VALID = 0  # readable; the only state that serves local reads / admits writes
INVALID = 1  # a newer write's INV was applied; awaiting its VAL
WRITE = 2  # this replica coordinates a pending write for the key
TRANS = 3  # pending local write superseded by a higher-ts INV; still completes
REPLAY = 4  # failure recovery: re-broadcasting the last INV with the same ts

# --- Op codes (workload streams / session ops) ---------------------------
OP_NOP = 0  # padding; completes immediately
OP_READ = 1
OP_WRITE = 2
OP_RMW = 3

# --- Session status ------------------------------------------------------
S_IDLE = 0  # ready to load the next op from its stream
S_READ = 1  # read pending (stalls while the key is not Valid)
S_ISSUE = 2  # update loaded but not yet issued (key not Valid, or lost local arbitration)
S_INFL = 3  # update issued: INV broadcast, gathering acks
S_DONE = 4  # op stream exhausted

# --- Write-kind flag (embedded in the timestamp tie-break) ---------------
# Plain writes must beat concurrent RMWs from the same base version so that an
# aborted RMW's timestamp can never dominate a surviving update at any replica
# (otherwise the aborted value could become readable via VAL/replay).  The
# Hermes tie-break is lexicographic; we encode (ver, flag, cid) with flag=1
# for plain writes, 0 for RMWs.  See core/timestamps.py and SURVEY.md §3.3.
FLAG_RMW = 0
FLAG_WRITE = 1

# --- Completion codes (per-step session completion records) --------------
C_NONE = 0
C_READ = 1  # read completed, value in the completion record
C_WRITE = 2  # write committed (linearization point: quorum of live acks)
C_RMW = 3  # RMW committed
C_RMW_ABORT = 4  # RMW aborted (no effect; YCSB-F conflict path, BASELINE.json:8)
C_NOP = 5
