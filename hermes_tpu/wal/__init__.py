"""Round-22 durability tier: the host-side write-ahead extent+commit log.

The completion stream already carries every committed write in round
order (runtime.harvest_comp feeds the recorder from it), so durability is
a TAP on that stream, not a new protocol path: ``GroupCommitWal`` appends
``(uid, key, ts=(ver, fc), value-words-or-heap-ref + extent bytes)``
records in CRC-framed segments (transport/codec.frame_pack — the same
torn-frame triage the serving wire uses), a dedicated flusher thread
group-commits them with ONE fsync per batch, and ``replay`` turns the
segments back into table rows idempotently (by packed timestamp — an
already-snapshotted record is a no-op).

Public surface:
  * ``GroupCommitWal``       — the log + flusher (log.py)
  * ``WalError/WalCorrupt``  — loud refusal types
  * ``read_records/apply_records`` — recovery half (replay.py)
"""

from hermes_tpu.wal.log import (  # noqa: F401
    GroupCommitWal,
    WalError,
    K_SEGHDR,
    K_ROUND,
    K_REMAP,
)
from hermes_tpu.wal.replay import (  # noqa: F401
    WalCorrupt,
    read_records,
    apply_records,
)
