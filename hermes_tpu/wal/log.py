"""The write-ahead extent+commit log (round-22 tentpole).

One ``GroupCommitWal`` owns one segment directory.  Appenders (the KVS
harvest path) never touch a file: ``append_comp``/``append_round`` deep-
copy the committed-write columns out of a harvested ``Completions``,
assign a monotone LSN under one small lock, and enqueue; a dedicated
flusher thread does ALL file work — frame encoding, segment rotation,
directory fsync on a new segment, and ONE ``os.fsync`` per drained batch
(the group commit).  ``sync(lsn)`` blocks until the batch holding ``lsn``
is durable, which is how ``wal_sync='commit'`` gates client completions
without putting an fsync on the per-round hot path.

Segment format: ``wal-%08d.seg`` = a run of transport/codec frames
(CRC-framed, the serving wire's own torn-frame triage).  The first frame
of every segment is a ``K_SEGHDR`` JSON header (seq + the config shape
words replay validates against); every later frame is a ``K_ROUND``
record batch (one harvested round's committed writes: commit step, key,
re-anchored version, fc, the full value words, and — in heap mode — the
extent BYTES behind each heap ref, so replay never needs the old heap)
or a ``K_REMAP`` bookkeeping record (heap GC moved extents; the bytes in
older records stay authoritative, the remap documents the ref rewrite).

Loudness contract: the flusher publishes its first exception to
``_error`` and every subsequent ``sync``/``append`` raises it — a dead
flusher must surface as a refusal at the caller, never as a silent
un-durable log.  Backpressure is the caller's job via ``backpressured()``
(KVS sheds with ``retry_after``); the WAL itself never blocks appends.
"""

from __future__ import annotations

import collections
import json
import os
import struct
import threading
import time

import numpy as np

from hermes_tpu.concurrency import make_lock
from hermes_tpu.core import types as t
from hermes_tpu.transport import codec

# record kinds (first payload byte)
K_SEGHDR = 0  # JSON segment header (seq + config shape words)
K_ROUND = 1  # one harvested round's committed writes (columnar)
K_REMAP = 2  # heap-GC ref rewrite bookkeeping (old[c] -> new[c])

#: K_ROUND / K_REMAP head: kind u8, pad x3, lsn i64, round_idx i64,
#: count u32, value_words u32 — then the columns (see _encode_round).
_HEAD = struct.Struct("<BxxxqqII")

SEG_FMT = "wal-%08d.seg"


class WalError(RuntimeError):
    """A durability promise cannot be kept (dead flusher, sync timeout,
    malformed record): raised loudly, never degraded to a warning."""


def _encode_round(lsn, round_idx, step, key, ver, fc, wv, lens, blob):
    c = int(np.asarray(key).shape[0])
    v = int(np.asarray(wv).shape[1]) if c else 0
    return b"".join((
        _HEAD.pack(K_ROUND, int(lsn), int(round_idx), c, v),
        np.ascontiguousarray(step, np.int64).tobytes(),
        np.ascontiguousarray(key, np.int32).tobytes(),
        np.ascontiguousarray(ver, np.int64).tobytes(),
        np.ascontiguousarray(fc, np.int32).tobytes(),
        np.ascontiguousarray(wv, np.int32).tobytes(),
        np.ascontiguousarray(lens, np.int32).tobytes(),
        bytes(blob),
    ))


def _encode_remap(lsn, old, new):
    c = int(np.asarray(old).shape[0])
    return b"".join((
        _HEAD.pack(K_REMAP, int(lsn), -1, c, 0),
        np.ascontiguousarray(old, np.int32).tobytes(),
        np.ascontiguousarray(new, np.int32).tobytes(),
    ))


def decode_record(payload: bytes) -> dict:
    """Decode one frame payload back into its record dict.  Raises
    ``WalError`` on an internally-inconsistent record (the frame CRC
    passed, so this is a writer bug or a deliberate edit — refuse)."""
    if len(payload) < 1:
        raise WalError("empty wal record payload")
    kind = payload[0]
    if kind == K_SEGHDR:
        try:
            return dict(kind=K_SEGHDR, header=json.loads(payload[1:].decode()))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise WalError(f"malformed segment header record: {e}") from e
    if len(payload) < _HEAD.size:
        raise WalError(
            f"wal record head truncated inside a CRC-valid frame "
            f"({len(payload)} < {_HEAD.size} bytes)")
    kind, lsn, round_idx, c, v = _HEAD.unpack_from(payload, 0)
    off = _HEAD.size

    def take(dtype, n):
        nonlocal off
        a = np.frombuffer(payload, dtype, count=n, offset=off)
        off += a.nbytes
        return a

    try:
        if kind == K_REMAP:
            return dict(kind=K_REMAP, lsn=lsn,
                        old=take(np.int32, c), new=take(np.int32, c))
        if kind != K_ROUND:
            raise WalError(f"unknown wal record kind {kind}")
        step = take(np.int64, c)
        key = take(np.int32, c)
        ver = take(np.int64, c)
        fc = take(np.int32, c)
        wv = take(np.int32, c * v).reshape(c, v)
        lens = take(np.int32, c)
    except ValueError as e:  # np.frombuffer ran off the payload
        raise WalError(f"wal record columns truncated inside a CRC-valid "
                       f"frame: {e}") from e
    blob = payload[off:]
    if len(blob) != int(lens.sum()):
        raise WalError(
            f"wal record extent blob is {len(blob)} bytes but the length "
            f"column sums to {int(lens.sum())}")
    return dict(kind=K_ROUND, lsn=lsn, round_idx=round_idx, step=step,
                key=key, ver=ver, fc=fc, wv=wv, lens=lens, blob=blob)


class GroupCommitWal:
    """Group-commit write-ahead log: lock-light appends, one flusher
    thread owning every file handle, one fsync per drained batch."""

    #: flusher batching window — how long the flusher dozes between batch
    #: drains when nobody kicks it (a kick drains immediately)
    GROUP_WINDOW_S = 0.002

    def __init__(self, cfg, wal_dir: str | None = None, obs=None):
        self.cfg = cfg
        self.dir = wal_dir if wal_dir is not None else cfg.wal_dir
        if self.dir is None:
            raise WalError(
                "GroupCommitWal needs a segment directory (cfg.wal_dir or "
                "an explicit wal_dir)")
        os.makedirs(self.dir, exist_ok=True)
        self.sync_mode = cfg.wal_sync
        self.obs = obs
        # -- appender<->flusher handoff (guarded by _lock) ---------------
        self._lock = make_lock("GroupCommitWal._lock")
        self._buf = collections.deque()  # (op, lsn, arg) tuples
        self._next_lsn = 1  # lsn 0 = "nothing appended yet"
        self._durable_lsn = 0
        self._dirty = 0  # appended-but-not-durable write records
        self._flush_evt = threading.Event()  # swapped per flush generation
        # -- internally-synchronized signals -----------------------------
        self._wake = threading.Event()
        self._stop = threading.Event()
        # -- single-writer publish: flusher writes once, everyone reads --
        self._error = None
        # -- flusher-thread-private file state ---------------------------
        self._f = None
        self._seg_path = None
        self._seg_bytes = 0
        self._seg_max_step = -1
        self._sealed_steps = {}  # sealed path -> max commit step inside
        existing = self.segments()
        self._seg_seq = (self._seq_of(existing[-1]) + 1) if existing else 0
        # -- gil-atomic monotone telemetry counters ----------------------
        self.records = 0
        self.rounds = 0
        self.remaps = 0
        self.fsyncs = 0
        self.wal_bytes = 0
        self.retired_segments = 0
        self._flusher_t = threading.Thread(
            target=self._flusher, name="wal-flusher", daemon=True)
        self._flusher_t.start()

    # ------------------------------------------------------------------
    # appender side (KVS harvest path / recovery re-append)
    # ------------------------------------------------------------------

    @staticmethod
    def _seq_of(path: str) -> int:
        return int(os.path.basename(path)[4:-4])

    def segments(self) -> list:
        """Segment paths on disk, in sequence order."""
        out = [os.path.join(self.dir, n) for n in os.listdir(self.dir)
               if n.startswith("wal-") and n.endswith(".seg")]
        return sorted(out, key=self._seq_of)

    def append_comp(self, comp, heap=None, round_idx=None):
        """Tap a harvested ``Completions``: append its committed writes
        (C_WRITE/C_RMW cells) as one K_ROUND record batch.  Returns the
        batch LSN, or None when the round committed nothing.  In heap
        mode the extent bytes behind each value's heap ref ride in the
        record, so replay is self-contained."""
        self._check_error()
        code = np.asarray(comp.code).ravel()
        m = (code == t.C_WRITE) | (code == t.C_RMW)
        if not bool(m.any()):
            return None
        key = np.asarray(comp.key).ravel()[m].astype(np.int32)
        ver = np.asarray(comp.ver).ravel()[m].astype(np.int64)
        fc = np.asarray(comp.fc).ravel()[m].astype(np.int32)
        step = np.asarray(comp.commit_step).ravel()[m].astype(np.int64)
        wval = np.asarray(comp.wval)
        wv = wval.reshape(-1, wval.shape[-1])[m].astype(np.int32)
        lens = np.zeros(key.shape[0], np.int32)
        blob = b""
        if heap is not None:
            chunks = [heap.read(int(r)) if int(r) else b""
                      for r in wv[:, 2]]
            lens = np.array([len(c) for c in chunks], np.int32)
            blob = b"".join(chunks)
        if round_idx is None:
            round_idx = int(step.max())
        return self.append_round(round_idx, step, key, ver, fc, wv,
                                 lens, blob)

    def append_round(self, round_idx, step, key, ver, fc, wv, lens,
                     blob) -> int:
        """Append one pre-extracted record batch; returns its LSN."""
        self._check_error()
        arg = dict(round_idx=int(round_idx),
                   step=np.ascontiguousarray(step, np.int64),
                   key=np.ascontiguousarray(key, np.int32),
                   ver=np.ascontiguousarray(ver, np.int64),
                   fc=np.ascontiguousarray(fc, np.int32),
                   wv=np.ascontiguousarray(wv, np.int32),
                   lens=np.ascontiguousarray(lens, np.int32),
                   blob=bytes(blob))
        n = int(arg["key"].shape[0])
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            self._dirty += n
            self._buf.append(("round", lsn, arg))
        return lsn

    def note_remap(self, old, new) -> int:
        """Heap GC moved extents: log the ref rewrite (bookkeeping — the
        extent BYTES in earlier records stay authoritative)."""
        self._check_error()
        arg = (np.ascontiguousarray(old, np.int32).copy(),
               np.ascontiguousarray(new, np.int32).copy())
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            self._buf.append(("remap", lsn, arg))
        self.kick()
        return lsn

    def truncate_to(self, step: int, wait: bool = True) -> int:
        """Drop sealed segments whose every record committed at or before
        ``step`` (snapshot-save calls this: the snapshot now covers
        them).  The open segment is never dropped."""
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            self._buf.append(("truncate", lsn, int(step)))
        self.kick()
        if wait:
            self.sync(lsn)
        return lsn

    def retire_segments(self, paths, wait: bool = True) -> int:
        """Delete exactly ``paths`` (recovery calls this after it has
        re-appended their surviving records into this log).  The open
        segment is refused, never deleted."""
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            self._buf.append(("retire", lsn, tuple(paths)))
        self.kick()
        if wait:
            self.sync(lsn)
        return lsn

    def kick(self) -> None:
        """Wake the flusher now instead of at the group window."""
        self._wake.set()

    def last_lsn(self) -> int:
        with self._lock:
            return self._next_lsn - 1

    def durable_lsn(self) -> int:
        with self._lock:
            return self._durable_lsn

    def dirty_records(self) -> int:
        with self._lock:
            return self._dirty

    def backpressured(self) -> bool:
        """True when the appended-but-not-durable window exceeds the
        configured bound — the caller must shed loudly (RETRY_AFTER),
        never queue into a log that cannot drain."""
        with self._lock:
            return self._dirty > self.cfg.wal_dirty_window

    def sync(self, lsn: int | None = None, timeout: float = 60.0) -> None:
        """Block until ``lsn`` (default: everything appended so far) is
        durable under the configured sync mode.  Raises WalError on a
        dead/failed flusher or timeout — never returns un-durable."""
        with self._lock:
            target = (self._next_lsn - 1) if lsn is None else int(lsn)
        deadline = time.monotonic() + timeout
        while True:
            self._check_error()
            with self._lock:
                if self._durable_lsn >= target:
                    return
                evt = self._flush_evt
            if not self._flusher_t.is_alive():
                self._check_error()
                raise WalError(
                    "wal flusher thread is dead (no published error): "
                    f"cannot make lsn {target} durable")
            self.kick()
            evt.wait(0.05)
            if time.monotonic() > deadline:
                raise WalError(
                    f"wal sync timed out after {timeout}s waiting for lsn "
                    f"{target} (durable {self.durable_lsn()}, "
                    f"dirty {self.dirty_records()} records)")

    def close(self) -> None:
        """Drain, seal the open segment, and stop the flusher."""
        self._stop.set()
        self._wake.set()
        self._flusher_t.join(timeout=60.0)
        if self._flusher_t.is_alive():
            raise WalError("wal flusher did not stop within 60s")
        # the thread is dead: sealing from here cannot race it
        self._seal_current()

    def stats(self) -> dict:
        return dict(records=self.records, rounds=self.rounds,
                    remaps=self.remaps, fsyncs=self.fsyncs,
                    bytes=self.wal_bytes, dirty=self.dirty_records(),
                    durable_lsn=self.durable_lsn(),
                    last_lsn=self.last_lsn(),
                    retired_segments=self.retired_segments,
                    segments=len(self.segments()), sync=self.sync_mode)

    def _check_error(self) -> None:
        err = self._error
        if err is not None:
            raise WalError(f"wal flusher failed: {err!r}") from err

    # ------------------------------------------------------------------
    # flusher thread (sole owner of every file handle below here)
    # ------------------------------------------------------------------

    def _flusher(self) -> None:
        try:
            while True:
                self._wake.wait(self.GROUP_WINDOW_S)
                self._wake.clear()
                with self._lock:
                    batch = list(self._buf)
                    self._buf.clear()
                if not batch:
                    if self._stop.is_set():
                        return
                    continue
                max_lsn, n_recs = self._write_batch(batch)
                t0 = time.perf_counter()
                if self._f is not None:
                    self._f.flush()
                    if self.sync_mode != "off":
                        os.fsync(self._f.fileno())
                        self.fsyncs += 1
                dt = time.perf_counter() - t0
                with self._lock:
                    self._durable_lsn = max(self._durable_lsn, max_lsn)
                    self._dirty -= n_recs
                    dirty = self._dirty
                    evt, self._flush_evt = self._flush_evt, threading.Event()
                evt.set()
                self._feed_obs(dt, dirty, n_recs)
        except BaseException as e:  # noqa: BLE001 — published, re-raised at callers
            self._error = e
            with self._lock:
                evt = self._flush_evt
            evt.set()

    def _write_batch(self, batch):
        max_lsn, n = 0, 0
        for op, lsn, arg in batch:
            if op == "round":
                payload = _encode_round(lsn, **arg)
                self._append_frame(
                    payload,
                    int(arg["step"].max()) if arg["step"].size else -1)
                self.rounds += 1
                self.records += int(arg["key"].shape[0])
                n += int(arg["key"].shape[0])
            elif op == "remap":
                old, new = arg
                self._append_frame(_encode_remap(lsn, old, new), -1)
                self.remaps += 1
            elif op == "truncate":
                self._truncate(arg)
            elif op == "retire":
                self._retire(arg)
            max_lsn = max(max_lsn, lsn)
        return max_lsn, n

    def _append_frame(self, payload: bytes, max_step: int) -> None:
        if self._f is None or self._seg_bytes >= self.cfg.wal_segment_bytes:
            self._roll_segment()
        fb = codec.frame_pack(np.frombuffer(payload, np.uint8)).tobytes()
        self._f.write(fb)
        self._seg_bytes += len(fb)
        self.wal_bytes += len(fb)
        self._seg_max_step = max(self._seg_max_step, max_step)

    def _roll_segment(self) -> None:
        self._seal_current()
        path = os.path.join(self.dir, SEG_FMT % self._seg_seq)
        self._seg_seq += 1
        self._f = open(path, "ab")
        self._seg_path = path
        self._seg_bytes = 0
        self._seg_max_step = -1
        hdr = json.dumps(dict(
            seq=self._seq_of(path), n_keys=self.cfg.n_keys,
            value_words=self.cfg.value_words,
            n_replicas=self.cfg.n_replicas,
            max_value_bytes=self.cfg.max_value_bytes,
            sync=self.sync_mode)).encode()
        fb = codec.frame_pack(
            np.frombuffer(bytes([K_SEGHDR]) + hdr, np.uint8)).tobytes()
        self._f.write(fb)
        self._seg_bytes += len(fb)
        self.wal_bytes += len(fb)
        # fsync the directory so the new NAME survives a powercut (the
        # file's own fsync does not cover its directory entry)
        if self.sync_mode != "off":
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)

    def _seal_current(self) -> None:
        if self._f is None:
            return
        self._f.flush()
        if self.sync_mode != "off":
            os.fsync(self._f.fileno())
        self._f.close()
        self._sealed_steps[self._seg_path] = self._seg_max_step
        self._f = None
        self._seg_path = None

    def _truncate(self, step: int) -> None:
        drop = [p for p, ms in self._sealed_steps.items() if ms <= step]
        for p in drop:
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            del self._sealed_steps[p]
            self.retired_segments += 1

    def _retire(self, paths) -> None:
        for p in paths:
            if p == self._seg_path:
                continue  # never delete the open segment
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            self._sealed_steps.pop(p, None)
            self.retired_segments += 1

    def _feed_obs(self, fsync_s: float, dirty: int, n_recs: int) -> None:
        obs = self.obs
        if obs is None:
            return
        reg = obs.registry
        reg.series("wal_fsync_s").append(self.fsyncs, fsync_s)
        reg.series("wal_dirty_records").append(self.fsyncs, dirty)
        reg.counter("wal_records").inc(n_recs)
        reg.gauge("wal_dirty").set(dirty)
