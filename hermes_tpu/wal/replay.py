"""WAL recovery half: segment reading with torn-frame triage, and the
idempotent host-side apply path.

Triage contract (the tentpole's loud/quiet split):

  * **torn tail** — the failure is explainable as ONE interrupted append
    reaching end-of-file in the LAST segment: fewer bytes than a frame
    header remain, or a valid header's declared payload runs past EOF.
    That is the expected kill -9 shape; reading truncates cleanly at the
    last whole record and recovery proceeds with everything before it.
  * **torn interior / checksum mismatch** — anything else: bad magic with
    a full header present, a CRC mismatch over a fully-present payload,
    any failure in a non-last segment, or a record that decodes
    inconsistently inside a CRC-valid frame.  That is bit rot or a
    writer bug, NOT a crash shape — the flight recorder dumps (with the
    offending segment header bytes in the payload) and ``WalCorrupt``
    raises.  Recovery must never guess past it.

Apply contract: a record applies to a table row iff its packed timestamp
``pack_pts(ver - ver_base[key], fc)`` is NEWER than the row's current
``vpts`` — so replaying a record the snapshot already covers is a no-op,
and replaying the whole log twice is identical to once (idempotent by
``(uid, ts)``; the uid rides in value words 0-1 and follows the ts).
"""

from __future__ import annotations

import os

import numpy as np

from hermes_tpu.core import faststep as fst
from hermes_tpu.core import types as t
from hermes_tpu.obs.flightrec import FlightRecorder
from hermes_tpu.transport import codec
from hermes_tpu.wal import log as wlog


class WalCorrupt(RuntimeError):
    """A WAL segment failed integrity checks in a way a crash cannot
    explain (torn interior / checksum mismatch / inconsistent record):
    recovery refuses loudly instead of guessing."""


def _refuse(reason: str, obs, path: str, seq: int, offset: int,
            header: bytes, detail: str) -> None:
    """Arm the flight recorder (same pattern as the checker-red and
    StuckOpError triggers), then raise WalCorrupt."""
    flight = obs.flight if obs is not None else FlightRecorder()
    flight.auto_dump(reason, extra=dict(
        segment=os.path.basename(path), seq=seq, offset=offset,
        header_hex=header.hex(), detail=detail))
    raise WalCorrupt(
        f"{reason}: segment {os.path.basename(path)} (seq {seq}) at "
        f"offset {offset}: {detail} — refusing to replay past it "
        f"(header bytes {header.hex() or '<eof>'})")


def read_records(wal_dir: str, obs=None) -> dict:
    """Parse every segment in ``wal_dir`` in sequence order.

    Returns ``dict(records, remaps, headers, segments, torn_tail)``:
    ``records`` are decoded K_ROUND dicts in append order, ``remaps`` the
    K_REMAP bookkeeping dicts, ``headers`` the per-segment K_SEGHDR
    JSON dicts, ``segments`` the paths read (recovery retires exactly
    these after re-appending), ``torn_tail`` whether the last segment
    ended in a cleanly-truncated partial append."""
    paths = sorted(
        (os.path.join(wal_dir, n) for n in os.listdir(wal_dir)
         if n.startswith("wal-") and n.endswith(".seg")),
        key=wlog.GroupCommitWal._seq_of) if os.path.isdir(wal_dir) else []
    records, remaps, headers = [], [], []
    torn_tail = False
    for pi, path in enumerate(paths):
        seq = wlog.GroupCommitWal._seq_of(path)
        last_seg = pi == len(paths) - 1
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            remaining = len(data) - off
            header = data[off:off + codec.FRAME_OVERHEAD]
            if remaining < codec.FRAME_OVERHEAD:
                if last_seg:
                    torn_tail = True  # interrupted append at EOF
                    break
                _refuse("wal_torn_interior", obs, path, seq, off, header,
                        f"{remaining} trailing bytes (< {codec.FRAME_OVERHEAD}"
                        "-byte frame header) in a NON-last segment")
            magic, algo, _pad, length, crc = codec.FRAME_HEADER.unpack(header)
            if magic != codec.FRAME_MAGIC:
                _refuse("wal_torn_interior", obs, path, seq, off, header,
                        f"bad frame magic 0x{magic:04x} with a full header "
                        "present (appends are sequential, so this is not a "
                        "torn tail)")
            end = off + codec.FRAME_OVERHEAD + length
            if end > len(data):
                if last_seg:
                    torn_tail = True  # header landed, payload did not
                    break
                _refuse("wal_torn_interior", obs, path, seq, off, header,
                        f"frame payload ({length} bytes) runs past EOF in a "
                        "NON-last segment")
            payload = data[off + codec.FRAME_OVERHEAD:end]
            got = codec.wire_crc(payload, algo)
            if got != crc:
                _refuse("wal_checksum_mismatch", obs, path, seq, off, header,
                        f"frame checksum mismatch over a fully-present "
                        f"payload (header 0x{crc:08x} != 0x{got:08x})")
            try:
                rec = wlog.decode_record(payload)
            except wlog.WalError as e:
                _refuse("wal_record_inconsistent", obs, path, seq, off,
                        header, str(e))
            rec["segment"] = path
            if rec["kind"] == wlog.K_SEGHDR:
                headers.append(rec["header"])
            elif rec["kind"] == wlog.K_REMAP:
                remaps.append(rec)
            else:
                records.append(rec)
            off = end
    return dict(records=records, remaps=remaps, headers=headers,
                segments=paths, torn_tail=torn_tail)


def check_headers(headers, cfg, obs=None) -> None:
    """Refuse a log written under a different table shape: replaying it
    would scatter rows into the wrong slots silently."""
    for h in headers:
        bad = [k for k in ("n_keys", "value_words", "n_replicas",
                           "max_value_bytes")
               if h.get(k) != getattr(cfg, k)]
        if bad:
            flight = obs.flight if obs is not None else FlightRecorder()
            flight.auto_dump("wal_recovery_refused", extra=dict(
                header=h, mismatched=bad, expected={
                    k: getattr(cfg, k) for k in bad}))
            raise WalCorrupt(
                f"wal segment seq {h.get('seq')} was written under a "
                f"different config ({', '.join(bad)} mismatch: segment "
                f"{ {k: h.get(k) for k in bad} } vs runtime "
                f"{ {k: getattr(cfg, k) for k in bad} }) — refusing to "
                "replay it into this table")


def apply_records(rt, records, heap=None, replicas=None):
    """Replay decoded K_ROUND records into ``rt``'s table host-side,
    idempotently by packed timestamp.  Returns ``(applied, skipped)``
    record counts.  ``replicas`` restricts the write to those table
    copies on the sharded engine (restart_replica's rejoined-replica
    catch-up); None = every copy.  In heap mode each applied record's
    extent bytes are re-appended into ``heap`` and the row's ref word
    re-minted (the logged ref is from the dead store's heap)."""
    cfg = rt.cfg
    K = cfg.n_keys
    tbl = rt.fs.table
    import jax
    import jax.numpy as jnp

    vpts = np.array(jax.device_get(tbl.vpts))
    bank = np.array(jax.device_get(tbl.bank))
    rows32 = codec.rows_to_words(bank)
    sharded = vpts.shape[0] != K
    R = vpts.shape[0] // K if sharded else 1
    copies = list(range(R)) if replicas is None else list(replicas)
    ver_base = getattr(rt, "_ver_base", None)
    applied = skipped = 0
    for rec in records:
        n = int(rec["key"].shape[0])
        offs = np.zeros(n + 1, np.int64)
        np.cumsum(rec["lens"], out=offs[1:])
        for i in range(n):
            key = int(rec["key"][i])
            if not (0 <= key < K):
                raise WalCorrupt(
                    f"wal record key {key} outside the table "
                    f"[0, {K}) — log/config mismatch")
            gver = int(rec["ver"][i])
            dver = gver - (int(ver_base[key]) if ver_base is not None else 0)
            if not (0 < dver < cfg.max_key_versions):
                raise WalCorrupt(
                    f"wal record for key {key} re-anchors to device "
                    f"version {dver} (global {gver}) outside "
                    f"(0, {cfg.max_key_versions}) — version-era mismatch "
                    "between the log and this runtime's rebase state")
            pts = np.int32(fst.pack_pts(dver, int(rec["fc"][i])))
            rows = ([key] if not sharded
                    else [r * K + key for r in copies])
            hit_rows = [row for row in rows if pts > vpts[row]]
            if not hit_rows:
                skipped += 1  # snapshot (or a later record) already covers it
                continue
            wv = rec["wv"][i].copy()
            if heap is not None and int(rec["lens"][i]):
                # mint a FRESH ref for the logged extent bytes — the
                # logged ref word points into the dead store's heap;
                # minted only for records that actually apply, so a
                # replayed-twice log cannot leak heap space
                ext = rec["blob"][int(offs[i]):int(offs[i + 1])]
                wv[2] = np.int32(heap.append(ext))
            sst = np.int32(fst.pack_sst(int(rec["step"][i]), t.VALID))
            for row in hit_rows:
                vpts[row] = pts
                rows32[row, fst.BANK_PTS] = pts
                rows32[row, fst.BANK_SST] = sst
                rows32[row, fst.BANK_VAL:] = wv
            applied += 1
    tbl = tbl._replace(vpts=jnp.asarray(vpts),
                       bank=jnp.asarray(codec.words_to_rows(rows32)))
    rt.fs = rt.fs._replace(table=tbl)
    return applied, skipped
