"""Fleet bench cells (round-13): per-group + aggregate committed writes/s.

Measurement model — stated up front because the host backend cannot fake
a pod: fleet groups are INDEPENDENT XLA programs with no shared state, so
on the target hardware (one chip-group per Hermes group on the
(groups, replicas) grid) they overlap perfectly and the fleet aggregate
is the sum of per-group rates.  On a shared host the groups timeshare
the cores instead.  The cells therefore report BOTH numbers honestly:

  * ``per_group`` — each group measured ALONE on the machine (the rate a
    group sustains on dedicated hardware; this is what the on-chip rerun
    measures per chip-group) and ``aggregate_writes_per_sec`` = their
    sum — the fleet's scale-out capacity;
  * ``concurrent`` — every group's scan chunks dispatched together, one
    wall for all of them: the host-contention floor (bounded by
    ``host_cores``; on a pod this equals the aggregate because nothing
    is shared).

Groups are placed round-robin over the visible devices
(``jax.default_device``), so under the canonical gate env
(``--xla_force_host_platform_device_count=8``) the concurrent cell
genuinely overlaps group programs on separate host devices.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np


def _fleet_cfg(fcfg, g: int):
    cfg = fcfg.group_cfg(g)
    if not cfg.device_stream:
        raise ValueError(
            "fleet bench cells drive the raw scan round: the group config "
            "needs device_stream=True (counter-hash op streams)")
    return cfg


def _chunks(cfg, rounds: int, dev):
    """(state, stream, chunk_fn, ctl_fn) for one group pinned to one
    device.  The chunk fn is shared across groups of identical shape, so
    XLA compiles once per device, not once per group."""
    import jax

    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    with jax.default_device(dev):
        fs = jax.device_put(fst.init_fast_state(cfg), dev)
        stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)), dev)
        chunk = fst.build_fast_scan(cfg, rounds, donate=True)
    return fs, stream, chunk


def _commits(fs) -> int:
    import jax

    m = jax.device_get(fs.meta)
    return int(m.n_write.sum() + m.n_rmw.sum())


def run_fleet_cells(fcfg, rounds: int = 20, chunks: int = 2,
                    warmup_chunks: int = 1,
                    devices: Optional[list] = None) -> dict:
    """Measure the fleet (module docstring): per-group cells alone, a
    single-group baseline (group 0's config), and the concurrent cell.
    Returns the BENCH_FLEET.json payload."""
    import jax

    devs = list(devices) if devices is not None else list(jax.devices())
    G = fcfg.groups
    states = []
    for g in range(G):
        cfg = _fleet_cfg(fcfg, g)
        dev = devs[g % len(devs)]
        fs, stream, chunk = _chunks(cfg, rounds, dev)
        states.append(dict(g=g, cfg=cfg, dev=dev, fs=fs, stream=stream,
                           chunk=chunk))

    def dispatch(st, c):
        from hermes_tpu.core import faststep as fst

        with jax.default_device(st["dev"]):
            st["fs"] = st["chunk"](st["fs"], st["stream"],
                                   fst.make_fast_ctl(st["cfg"], c * rounds))

    # warm every group (compile + first chunk) and switch the link to
    # synchronous mode via a counter readback
    for st in states:
        for c in range(warmup_chunks):
            dispatch(st, c)
    jax.block_until_ready([st["fs"] for st in states])
    base = [_commits(st["fs"]) for st in states]

    # -- per-group cells: each group measured ALONE -------------------------
    per_group = []
    for st in states:
        t0 = time.perf_counter()
        for c in range(warmup_chunks, warmup_chunks + chunks):
            dispatch(st, c)
        jax.block_until_ready(st["fs"])
        wall = time.perf_counter() - t0
        commits = _commits(st["fs"]) - base[st["g"]]
        per_group.append(dict(
            group=st["g"], writes_per_sec=round(commits / wall, 1),
            commits=commits, rounds=chunks * rounds,
            wall_s=round(wall, 4), device=str(st["dev"])))
    aggregate = round(sum(c["writes_per_sec"] for c in per_group), 1)

    # -- concurrent cell: all groups' chunks in flight together -------------
    base = [_commits(st["fs"]) for st in states]
    t0 = time.perf_counter()
    for c in range(warmup_chunks + chunks, warmup_chunks + 2 * chunks):
        for st in states:
            dispatch(st, c)
    jax.block_until_ready([st["fs"] for st in states])
    conc_wall = time.perf_counter() - t0
    conc_commits = sum(_commits(st["fs"]) - b for st, b in zip(states, base))

    # -- single-group baseline (the scale-out denominator): group 0's own
    # cell IS a single group measured alone at the same shape (vary_seed
    # adds +0 to group 0's seed), so re-measuring it would only pay a
    # duplicate build + warmup + timed window
    cfg0 = _fleet_cfg(fcfg, 0)
    single = {k: per_group[0][k]
              for k in ("writes_per_sec", "commits", "rounds", "wall_s")}

    return dict(
        groups=G,
        per_group=per_group,
        aggregate_writes_per_sec=aggregate,
        single_group=single,
        scaleout_x=round(aggregate / max(1e-9, single["writes_per_sec"]), 2),
        concurrent=dict(
            writes_per_sec=round(conc_commits / conc_wall, 1),
            commits=conc_commits, wall_s=round(conc_wall, 4),
            note="all groups' chunks in flight on this host at once — "
                 "bounded by host_cores; equals the aggregate on "
                 "dedicated per-group hardware"),
        host_cores=os.cpu_count(),
        devices=len(devs),
        shape=dict(
            n_replicas=cfg0.n_replicas, n_keys=cfg0.n_keys,
            n_sessions=cfg0.n_sessions, value_words=cfg0.value_words,
            rounds_per_dispatch=rounds),
        platform=devs[0].platform,
    )
