"""hermes_tpu.fleet — pod-scale key-sharded protocol groups (round-13;
ROADMAP item 2, the "millions of users" axis).

Hermes coordinates writes per key (PAPER.md), so aggregate throughput
scales by running many independent key-sharded replica groups side by
side.  This package composes G complete single-group stacks — each a
``kvs.KVS`` over a ``FastRuntime`` with its own membership service,
chaos scope, and snapshot scope — behind:

  * ``FleetRouter`` (fleet/router.py) — fleet key -> (owning group,
    local dense slot), boundary-exact through ``keyindex.RangeRouter``,
    with the migration drain/flip state machine in fleet coordinates;
  * ``Fleet`` (fleet/core.py) — the routed client facade: sessions and
    batches routed by key, per-group checker + the fleet-level
    ``verify_fleet`` harness (routing injectivity, migration-uid
    namespace disjointness, group-scoped membership), cross-group
    ``migrate`` through the fleet router flip, per-group snapshot scope;
  * ``FleetChaosRunner`` / ``fleet_schedules`` (fleet/chaos.py) —
    group-scoped fault programs driven in lockstep, deterministic
    replay fleet-wide;
  * ``run_fleet_cells`` (fleet/bench.py) — per-group + aggregate +
    concurrent committed-writes/s cells (BENCH_FLEET.json; the eighth CI
    gate scripts/check_fleet.py asserts the 4-group scale-out floor).

Configuration is ``config.FleetConfig`` (groups, ranges, per-group
overrides); device layout for sharded groups is
``launch.fleet_meshes`` — the (groups, replicas) grid, one disjoint
submesh per group.
"""

from hermes_tpu.config import FleetConfig
from hermes_tpu.fleet.chaos import FleetChaosRunner, fleet_schedules, parse_fleet
from hermes_tpu.fleet.core import Fleet, FleetBatch, verify_fleet
from hermes_tpu.fleet.router import FleetRouter

__all__ = [
    "Fleet", "FleetBatch", "FleetChaosRunner", "FleetConfig", "FleetRouter",
    "fleet_schedules", "parse_fleet", "verify_fleet",
]
