"""The fleet runtime (round-13): G independent Hermes groups behind one
key-routed client facade.

Hermes coordinates writes PER KEY (PAPER.md), so the fleet is not a new
protocol — it is G complete single-group stacks (each a ``kvs.KVS`` over a
``FastRuntime`` with its OWN membership service, chaos scope, and snapshot
scope) composed behind a ``FleetRouter`` that maps every fleet key to its
owning group and local dense slot.  Nothing is shared between groups:

  * a group's quorums, failure detector, fault schedules, and version
    rebases see only that group's replicas — a fault in group 0 cannot
    fence a group 1 replica by construction (tests/test_fleet.py proves
    it red-style);
  * linearizability is a PER-KEY property, so the checker runs per group
    over that group's history; the fleet-level addition is
    ``verify_fleet``, which proves the cross-group invariants the
    per-group checkers cannot see — routing injectivity (no two fleet
    keys alias one (group, slot)) and migration-uid namespace
    disjointness (no re-minted hi<=-2 witness appears in two groups'
    histories — ``Fleet.migrate`` reserves a fresh namespace per move).

Device placement: each batched group is pinned round-robin onto the
available devices (one group = one device's program — the host-backend
stand-in for the (groups, replicas) pod grid ``launch.fleet_meshes``
builds from real chips); sharded groups take caller-supplied disjoint
submeshes.  Group dispatches are independent XLA programs, so on real
hardware they overlap perfectly; on a shared host they timeshare the
cores honestly.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from hermes_tpu.config import FleetConfig
from hermes_tpu.fleet.router import FleetRouter
from hermes_tpu.kvs import (C_REJECTED, BatchFutures, Completion, Future,
                            KVS, MultiGetResult)


@dataclasses.dataclass
class _Group:
    """One fleet member: a full single-group serving stack."""

    gid: int
    cfg: object
    kvs: KVS
    dev: object = None  # pinned device (batched placement), else None

    @property
    def rt(self):
        return self.kvs.rt

    def ctx(self):
        """Execution context pinning this group's dispatches to its
        device (no-op for sharded groups — their mesh is the pin)."""
        if self.dev is None:
            return contextlib.nullcontext()
        import jax

        return jax.default_device(self.dev)


class _RoutedFuture(Future):
    """A group future viewed through the router: results echo the FLEET
    key the client submitted (the group KVS only ever saw the local
    dense slot)."""

    def __init__(self, inner: Future, fleet_key: int):
        super().__init__()
        self._inner = inner
        self._fleet_key = fleet_key

    def done(self) -> bool:
        return self._inner.done()

    def result(self) -> Completion:
        return dataclasses.replace(self._inner.result(),
                                   key=self._fleet_key)


class FleetBatch:
    """Merged view over per-group ``BatchFutures`` of one fleet batch:
    the same columns (code/value/uid/found/step) in FLEET submission
    order, filled as the owning groups complete their shares.  Ops on a
    draining fleet range complete immediately as C_REJECTED and never
    reach a group (the fleet-level reject the router's drain promises)."""

    def __init__(self, kinds: np.ndarray, keys: np.ndarray, groups: np.ndarray,
                 u: int):
        n = kinds.shape[0]
        self.kind = kinds
        self.key = keys          # FLEET keys (what the client submitted)
        self.group = groups      # owning group per op (-1 = fleet-rejected)
        self.code = np.zeros(n, np.int32)
        self.value = np.zeros((n, u), np.int32)
        self.uid = np.zeros((n, 2), np.int32)
        self.found = np.ones(n, bool)
        self.step = np.full(n, -1, np.int32)
        # value heap (round-17): per-op byte payloads merged from the
        # owning groups' eager resolutions
        self.data: List[Optional[bytes]] = [None] * n
        # (group, sub BatchFutures, fleet indices of its ops)
        self._subs: List[tuple] = []

    def __len__(self) -> int:
        return self.code.shape[0]

    def _pull(self) -> None:
        """Copy completed sub-batch columns into the fleet columns."""
        for _g, bf, gix in self._subs:
            done = (bf.code != 0) & (self.code[gix] == 0)
            if done.any():
                di = gix[done]
                self.code[di] = bf.code[done]
                self.value[di] = bf.value[done]
                self.uid[di] = bf.uid[done]
                self.found[di] = bf.found[done]
                self.step[di] = bf.step[done]
                if bf._heap is not None:
                    for j, i in zip(np.nonzero(done)[0], di):
                        self.data[int(i)] = bf.data[int(j)]

    def done_count(self) -> int:
        self._pull()
        return int(np.count_nonzero(self.code))

    def all_done(self) -> bool:
        return self.done_count() == len(self)

    def completion(self, i: int) -> Completion:
        self._pull()
        assert self.code[i] != 0, "op not complete; run Fleet.run_batch()"
        # reuse the single-group decode, then restore the FLEET key (the
        # sub-batch echoed the group-local dense slot)
        view = BatchFutures(self.kind, self.key, self.value.shape[1])
        view.code, view.value, view.uid = self.code, self.value, self.uid
        view.found, view.step = self.found, self.step
        view.data = self.data
        return view.completion(i)


class FleetReads(MultiGetResult):
    """Merged view over per-group ``MultiGetResult``s of one fleet
    multi-get/scan (round-16): the inherited columns in FLEET submission
    order, filled as the owning groups answer their shares — locally
    from the device-resident fast path where keys are Valid, via the
    round path otherwise.  Keys on a draining fleet range complete
    immediately as C_REJECTED (the fleet-level reject the router's
    drain promises).  Only ``_pull`` differs from the single-group
    result: it merges MANY sub-results at their fleet index positions."""

    def __init__(self, keys: np.ndarray, groups: np.ndarray, u: int):
        super().__init__(keys, u)
        self.group = groups      # owning group per key (-1 = fleet-rejected)
        self._subs: List[tuple] = []  # (gid, MultiGetResult, fleet indices)

    def _pull(self) -> None:
        for _g, sub, gix in self._subs:
            sub._pull()
            done = (sub.code != 0) & (self.code[gix] == 0)
            if done.any():
                di = gix[done]
                self.code[di] = sub.code[done]
                self.value[di] = sub.value[done]
                self.found[di] = sub.found[done]
                self.local[di] = sub.local[done]
                self.step[di] = sub.step[done]
                if sub._heap is not None:
                    for j, i in zip(np.nonzero(done)[0], di):
                        self.data[int(i)] = sub.data[int(j)]

    @property
    def local_served(self) -> int:
        self._pull()
        return int(np.count_nonzero(self.local))


class Fleet:
    """G key-sharded Hermes groups behind one routed client facade.

    Client surface (mirrors ``kvs.KVS`` with the replica coordinate
    replaced by routing): ``put/get/rmw(session, key, ...)`` route by
    FLEET key through the router — the owning group is chosen by the key,
    the coordinator (replica, session) lane inside it by the fleet
    session id.  ``submit_batch`` fans a whole mix out to the owning
    groups and merges completions (``FleetBatch``).  ``step()`` runs one
    protocol round in EVERY group.
    """

    def __init__(self, fcfg: FleetConfig, backend: str = "batched",
                 meshes: Optional[Sequence] = None, record=False,
                 sparse_keys: bool = False, detect: Optional[int] = None,
                 place: bool = True):
        if sparse_keys:
            raise NotImplementedError(
                "fleet routing is dense-keyed: the fleet key IS the router "
                "slot; put a KeyIndex in front of Fleet to serve sparse "
                "client keys")
        if backend == "sharded" and (meshes is None
                                     or len(meshes) != fcfg.groups):
            raise ValueError(
                "sharded fleet needs one DISJOINT submesh per group "
                "(launch.fleet_meshes builds the (groups, replicas) grid)")
        self.cfg = fcfg
        self.backend = backend
        # value heap (round-17): heap mode must be fleet-uniform — a
        # cross-group migration re-appends extents into the destination's
        # log, which only exists when every group runs one
        for g in range(fcfg.groups):
            if fcfg.group_cfg(g).use_heap != fcfg.base.use_heap:
                raise ValueError(
                    f"group {g} disagrees with the fleet on value-heap "
                    "mode (max_value_bytes): heap mode is fleet-uniform")
        self.router = FleetRouter.from_config(fcfg)
        self.groups: List[_Group] = []
        devs = []
        if backend == "batched" and place:
            import jax

            devs = jax.devices()
        for g in range(fcfg.groups):
            gcfg = fcfg.group_cfg(g)
            dev = devs[g % len(devs)] if devs else None
            ctx = (contextlib.nullcontext() if dev is None
                   else jax.default_device(dev))
            with ctx:
                kvs = KVS(gcfg, backend=backend,
                          mesh=meshes[g] if meshes is not None else None,
                          record=record)
            grp = _Group(gid=g, cfg=gcfg, kvs=kvs, dev=dev)
            grp.rt.group = g  # per-group obs label (rides every trace)
            if detect is not None:
                from hermes_tpu.membership import MembershipService

                grp.rt.attach_membership(
                    MembershipService(gcfg, confirm_steps=detect, group=g))
            self.groups.append(grp)
        self.rejected_ops = 0  # fleet-level (router drain) rejects
        # local slots a group lost to outbound migrations: the rows stay
        # behind (normalized, fenced forever), so the slots can never be
        # re-allocated to an inbound migration
        self._retired_slots: Dict[int, set] = {}
        # migration-uid namespace ledger: hi word -> group that minted it.
        # migrate_range re-mints into hi = -(2 + dst_step); two groups
        # minting the SAME hi could alias witnesses across groups, so the
        # fleet reserves each hi for one group and steps the destination
        # past a collision before fencing anything.
        self._mig_minted: Dict[int, int] = {}

    # -- group access --------------------------------------------------------

    def __len__(self) -> int:
        return len(self.groups)

    def group(self, g: int) -> _Group:
        return self.groups[g]

    def runtimes(self):
        return [grp.rt for grp in self.groups]

    # -- routed sessions -----------------------------------------------------

    def _lane(self, grp: _Group, session: int):
        """Deterministic (replica, session) lane of a fleet session id
        inside one group: coordinators spread round-robin, lanes wrap the
        group's session width.  Two fleet sessions may share a lane —
        the KVS lane queue keeps their FIFO order."""
        r = session % grp.cfg.n_replicas
        s = (session // grp.cfg.n_replicas) % grp.cfg.n_sessions
        return r, s

    def route_op(self, kind: str, session: int, key: int, value=None):
        """Route one op and ALSO report the (group, replica, session)
        lane it landed on — the round-14 frontend needs the lane for its
        stuck-op diag tags, and calling this instead of get/put/rmw
        avoids repeating the locate + lane computation per op.  The lane
        is None for an op refused at the router (draining range)."""
        g, slot = self.router.locate(int(key))
        if self.router.draining(int(key)):
            self.rejected_ops += 1
            fut = Future()
            fut._result = Completion(kind="rejected", key=int(key),
                                     found=False)
            return fut, None
        grp = self.groups[g]
        r, s = self._lane(grp, session)
        with grp.ctx():
            fut = getattr(grp.kvs, kind)(r, s, slot, *(
                (value,) if value is not None else ()))
        return _RoutedFuture(fut, int(key)), (int(g), r, s)

    def _route(self, kind: str, session: int, key: int, value):
        return self.route_op(kind, session, key, value)[0]

    def degraded(self, key: Optional[int] = None) -> bool:
        """Quorum-loss degraded mode, fleet view (round-14 serving
        ladder): with ``key``, whether the OWNING group cannot commit
        writes right now; without, whether any group is degraded."""
        if key is not None:
            g, _slot = self.router.locate(int(key))
            return self.groups[int(g)].kvs.degraded()
        return any(grp.kvs.degraded() for grp in self.groups)

    def get(self, session: int, key: int) -> Future:
        return self._route("get", session, key, None)

    def put(self, session: int, key: int, value) -> Future:
        return self._route("put", session, key, value)

    def rmw(self, session: int, key: int, value) -> Future:
        return self._route("rmw", session, key, value)

    # -- batched fan-out -----------------------------------------------------

    GET, PUT, RMW = KVS.GET, KVS.PUT, KVS.RMW

    def submit_batch(self, kinds, keys, values=None) -> FleetBatch:
        """Fan one op mix out to the owning groups: ops keep FLEET
        submission order within each group's share (sub-batch order is
        the fleet order restricted to that group), and ops landing on a
        draining fleet range complete immediately as C_REJECTED."""
        kinds = np.ascontiguousarray(np.asarray(kinds, np.int32))
        keys = np.asarray(keys, np.int64)
        n = kinds.shape[0]
        if keys.shape != (n,):
            raise ValueError("keys must be shape (n,)")
        gids, slots = self.router.locate(keys)
        gids = np.asarray(gids, np.int32).copy()
        u = self.cfg.base.value_words - 2
        heap_mode = self.cfg.base.use_heap
        uval = np.zeros((n, u), np.int32)
        if values is not None and not heap_mode:
            v = np.asarray(values, np.int32)
            uval[:, : v.shape[1]] = v
        elif values is not None and len(values) != n:
            raise ValueError(f"values must carry {n} byte payloads")
        fb = FleetBatch(kinds, keys.copy(), gids, u)
        draining = np.asarray(self.router.draining(keys), bool)
        if draining.any():
            fb.code[draining] = C_REJECTED
            fb.found[draining] = False
            fb.group[draining] = -1
            self.rejected_ops += int(draining.sum())
        for grp in self.groups:
            mine = (gids == grp.gid) & ~draining
            if not mine.any():
                continue
            gix = np.nonzero(mine)[0]
            with grp.ctx():
                if heap_mode:
                    # byte payloads route verbatim: each owning group's
                    # KVS appends the extent into ITS OWN heap (refs are
                    # group-local — per-group logs, per-group GC)
                    share = (None if values is None
                             else [values[int(i)] for i in gix])
                    bf = grp.kvs.submit_batch(kinds[gix], slots[gix], share)
                else:
                    bf = grp.kvs.submit_batch(kinds[gix], slots[gix],
                                              uval[gix])
            fb._subs.append((grp.gid, bf, gix))
        return fb

    # -- local-read fast path (round-16) -------------------------------------

    def _read_session(self, grp: _Group, session):
        """The fence token a fleet read hands each group's KVS: an int
        fleet session id maps to the group's (replica, session) lane
        exactly like the write path; any other hashable token passes
        through verbatim (the serving front-end's per-tenant fencing —
        fences pinned via ``pin_read_fence`` live under the same token
        in every group, keyed by group-local slots)."""
        if session is None:
            return None
        return self._lane(grp, session) if isinstance(session, int) \
            else session

    def _reject_draining(self, fr: FleetReads, keys: np.ndarray) -> np.ndarray:
        """C_REJECTED every key on a draining fleet range (the facade
        reject the router's drain promises — same as the write paths);
        returns the draining mask."""
        draining = np.asarray(self.router.draining(keys), bool)
        if draining.any():
            fr.code[draining] = C_REJECTED
            fr.found[draining] = False
            fr.group[draining] = -1
            self.rejected_ops += int(draining.sum())
        return draining

    def multi_get(self, keys, session=None, wait: bool = True,
                  max_steps: int = 50_000) -> FleetReads:
        """Batched fleet read: fan the key vector to the owning groups'
        device-resident fast paths (``kvs.KVS.multi_get``) and merge the
        answers in FLEET key order.  ``session`` is a fleet session id
        (int — lane-mapped per group like the write path) or an opaque
        fence token (see ``pin_read_fence``); read-your-writes fencing
        composes with routing either way.  Draining fleet ranges reject
        (C_REJECTED); with ``wait`` the round-path fallbacks are driven
        to completion fleet-wide."""
        keys = np.atleast_1d(np.asarray(keys, np.int64))
        n = keys.shape[0]
        u = self.cfg.base.value_words - 2
        gids, slots = self.router.locate(keys)
        gids = np.asarray(gids, np.int32).copy()
        fr = FleetReads(keys.copy(), gids, u)
        if n == 0:
            return fr
        draining = self._reject_draining(fr, keys)
        for grp in self.groups:
            mine = (gids == grp.gid) & ~draining
            if not mine.any():
                continue
            gix = np.nonzero(mine)[0]
            with grp.ctx():
                sub = grp.kvs.multi_get(
                    np.asarray(slots)[gix],
                    session=self._read_session(grp, session), wait=False)
            # the group echoed local dense slots; the fleet columns echo
            # the fleet keys (fr.key), so the sub result is only read
            # for its answer columns
            fr._subs.append((grp.gid, sub, gix))
        if wait:
            self.run_reads(fr, max_steps=max_steps)
        return fr

    def scan(self, lo: int, hi: int, session=None, wait: bool = True,
             max_steps: int = 50_000) -> FleetReads:
        """Fleet range scan over fleet keys ``[lo, hi)``: contiguous
        group shares ride the zero-sparse-op slice program
        (``kvs.KVS.scan``); shares fragmented by migrations fall back to
        the gather program.  Answers merge in fleet key order."""
        if not (0 <= lo < hi <= self.cfg.total_keys):
            raise ValueError(f"fleet scan range [{lo}, {hi}) outside "
                             f"[0, {self.cfg.total_keys})")
        keys = np.arange(lo, hi, dtype=np.int64)
        u = self.cfg.base.value_words - 2
        gids, slots = self.router.locate(keys)
        gids = np.asarray(gids, np.int32).copy()
        slots = np.asarray(slots)
        fr = FleetReads(keys, gids, u)
        draining = self._reject_draining(fr, keys)
        for grp in self.groups:
            mine = (gids == grp.gid) & ~draining
            if not mine.any():
                continue
            gix = np.nonzero(mine)[0]
            share = slots[gix]
            lane = self._read_session(grp, session)
            contiguous = (share.size == 1
                          or (np.diff(share) == 1).all())
            with grp.ctx():
                if contiguous:
                    sub = grp.kvs.scan(int(share[0]), int(share[-1]) + 1,
                                       session=lane, wait=False)
                else:
                    # migrations fragmented this share's local slots:
                    # the gather program serves it (still one dispatch)
                    sub = grp.kvs.multi_get(share, session=lane,
                                            wait=False)
            fr._subs.append((grp.gid, sub, gix))
        if wait:
            self.run_reads(fr, max_steps=max_steps)
        return fr

    def pin_read_fence(self, session, fleet_key: int, ts) -> None:
        """Pin a per-token read-your-writes fence on the group owning
        ``fleet_key`` (the KVS.pin_read_fence hook, routed): later
        ``multi_get(..., session=token)`` reads of the key must observe
        ``ts`` or fall back to the round path."""
        g, slot = self.router.locate(int(fleet_key))
        self.groups[int(g)].kvs.pin_read_fence(session, int(slot), ts)

    def run_reads(self, fr: FleetReads, max_steps: int = 50_000) -> bool:
        """Drive a FleetReads' round-path fallbacks to completion (a
        no-op when every key answered locally — the common case)."""
        for _ in range(max_steps):
            if fr.all_done():
                return True
            self.step()
        self.flush()
        return fr.all_done()

    def read_stats(self) -> dict:
        """Fleet-wide fast-path accounting (sum of group counters)."""
        agg: Dict[str, int] = {}
        for grp in self.groups:
            for k, v in grp.kvs.read_stats().items():
                agg[k] = agg.get(k, 0) + v
        return agg

    # -- stepping ------------------------------------------------------------

    def step(self) -> int:
        """One protocol round in every group (dispatch order is group
        order; each group's device runs its round independently).
        Returns the fleet-wide count of client ops resolved."""
        n = 0
        for grp in self.groups:
            with grp.ctx():
                n += grp.kvs.step()
        return n

    def flush(self) -> int:
        n = 0
        for grp in self.groups:
            with grp.ctx():
                n += grp.kvs.flush()
                grp.rt.flush_pipeline()
        return n

    def run_batch(self, fb: FleetBatch, max_steps: int = 50_000) -> bool:
        for _ in range(max_steps):
            if fb.all_done():
                return True
            self.step()
        self.flush()
        return fb.all_done()

    def run_until(self, futures, max_steps: int = 10_000) -> bool:
        for _ in range(max_steps):
            if all(f.done() for f in futures):
                return True
            self.step()
        self.flush()
        return all(f.done() for f in futures)

    def drain(self, max_steps: int = 10_000) -> bool:
        ok = True
        for grp in self.groups:
            with grp.ctx():
                for _ in range(max_steps):
                    if not (grp.kvs._inflight or grp.kvs._queued_slots
                            or grp.kvs._bat):
                        break
                    grp.kvs.step()
                else:
                    ok = False
                grp.kvs.flush()
                grp.rt.flush_pipeline()
        return ok

    # -- observability -------------------------------------------------------

    def attach_obs(self, obs) -> None:
        """One obs context for the whole fleet: every group's runtime
        shares the registry/exporter, and every event it emits carries
        the group label (``rt.group``, set at construction)."""
        for grp in self.groups:
            grp.rt.attach_obs(obs)

    def counters(self) -> dict:
        """Per-group counters + the fleet-wide aggregate."""
        per = []
        agg: Dict[str, int] = {}
        for grp in self.groups:
            with grp.ctx():
                c = grp.kvs.counters()
            c = {k: int(v) for k, v in c.items() if np.ndim(v) == 0}
            c["group"] = grp.gid
            per.append(c)
            for k in ("n_read", "n_write", "n_rmw", "n_abort"):
                agg[k] = agg.get(k, 0) + c[k]
        return dict(groups=per, fleet=agg)

    def interval_report(self, obs) -> None:
        """Emit one interval record per group (group-labeled) plus the
        fleet aggregate — the records scripts/obs_report.py aggregates
        fleet-wide."""
        c = self.counters()
        for rec in c["groups"]:
            obs.interval(dict(rec, step=self.groups[rec["group"]].rt.step_idx))
        obs.interval(dict(c["fleet"], group="fleet"))

    # -- correctness ---------------------------------------------------------

    def check(self) -> dict:
        """Per-group linearizability verdicts + the fleet harness
        (verify_fleet).  Returns {ok, groups: [...], fleet_invariants}."""
        out: dict = {"groups": []}
        ok = True
        for grp in self.groups:
            with grp.ctx():
                v = grp.rt.check()
            out["groups"].append(dict(group=grp.gid, ok=bool(v.ok),
                                      keys_checked=v.keys_checked))
            ok &= bool(v.ok)
        verify_fleet(self)
        out["fleet_invariants"] = "ok"
        out["ok"] = ok
        return out

    # -- cross-group migration (through the fleet router flip) ---------------

    def migrate(self, lo: int, hi: int, dst_group: int,
                drain_steps: int = 2000, force: bool = False) -> dict:
        """Move fleet keys ``[lo, hi)`` between two fleet groups: the
        round-10 ``elastic.migrate_range`` drill between the owning
        group's KVS and the destination's, with the FLEET router carrying
        the drain and the atomic flip (the multi-group composition PR 6
        was built for).  The keys' local slots must still be contiguous
        in the source (true until a range is split by migrations).

        Namespace discipline: the transfer re-mints uids into
        ``hi = -(2 + dst_step)``; the fleet ledger reserves that hi for
        one group — on a cross-group collision the destination steps
        forward to a fresh namespace BEFORE anything is fenced, so
        identical witnesses can never appear in two groups' histories.
        """
        from hermes_tpu.elastic import migrate_range

        owners, slots = self.router.locate(np.arange(lo, hi))
        owners = np.asarray(owners)
        src_gid = int(owners[0])
        if not (owners == src_gid).all():
            raise ValueError(
                f"fleet range [{lo}, {hi}) spans groups "
                f"{sorted(set(owners.tolist()))}; migrate one owner's "
                "range at a time")
        if not (0 <= dst_group < len(self.groups)):
            raise ValueError(f"no group {dst_group}")
        if dst_group == src_gid:
            raise ValueError(f"range [{lo}, {hi}) already lives in group "
                             f"{dst_group}")
        llo, lhi = int(slots[0]), int(slots[-1]) + 1
        if not (np.diff(slots) == 1).all():
            raise ValueError(
                f"fleet range [{lo}, {hi}) is no longer slot-contiguous "
                "in its owner (split by earlier migrations); migrate the "
                "contiguous sub-ranges")
        src, dst = self.groups[src_gid], self.groups[dst_group]
        # allocate the DESTINATION's spare slots: its own keys keep their
        # local slots, and slots earlier migrations drained away stay
        # retired (their normalized rows are fenced forever) — so the
        # free set is exactly the never-used remainder of its table
        dst_owned = self.router._local[
            np.asarray(self.router.rr._owner) == dst_group]
        retired_set = self._retired_slots.get(dst_group, ())
        retired = np.fromiter(retired_set, np.int64, len(retired_set))
        used = np.union1d(dst_owned.astype(np.int64), retired)
        free = np.setdiff1d(np.arange(dst.cfg.n_keys, dtype=np.int64), used)
        if free.size < hi - lo:
            raise ValueError(
                f"group {dst_group} has {free.size} spare slot(s) but the "
                f"migration needs {hi - lo}; size the destination's "
                "n_keys past its range (FleetConfig ranges/overrides)")
        dest_alloc = free[: hi - lo]
        # reserve a fresh migration-uid namespace for the destination
        while self._mig_minted.get(-(2 + dst.rt.step_idx),
                                   dst_group) != dst_group:
            with dst.ctx():
                dst.kvs.step()
        self._mig_minted[-(2 + dst.rt.step_idx)] = dst_group

        self.router.begin_drain(lo, hi)
        try:
            with src.ctx():
                summary = migrate_range(src.kvs, dst.kvs, llo, lhi,
                                        router=None, dst_group=dst_group,
                                        drain_steps=drain_steps, force=force,
                                        dest_slots=dest_alloc)
        except BaseException:
            self.router.release(lo, hi)
            raise
        self.router.flip(lo, hi, dst_group,
                         dest_slots=summary["dest_slots"])
        self._retired_slots.setdefault(src_gid, set()).update(
            range(llo, lhi))
        summary["fleet_range"] = (lo, hi)
        summary["src_group"], summary["dst_group"] = src_gid, dst_group
        return summary

    # -- snapshot scope ------------------------------------------------------

    def save(self, dir_path: str) -> dict:
        """Fleet snapshot scope: one checksummed archive PER GROUP
        (group{g}.npz, the round-9 manifest format) plus a fleet manifest
        carrying the router state — a group's archive is restorable alone
        (its group is its recovery domain), the fleet manifest re-anchors
        routing.  Requires quiescent groups (the per-group save refuses
        in-flight client ops loudly)."""
        from hermes_tpu import snapshot as snapshot_lib

        os.makedirs(dir_path, exist_ok=True)
        names = []
        for grp in self.groups:
            with grp.ctx():
                grp.rt.flush_pipeline()
                p = os.path.join(dir_path, f"group{grp.gid}.npz")
                snapshot_lib.save(p, grp.rt)
            names.append(os.path.basename(p))
        manifest = dict(
            version=1, kind="fleet", groups=len(self.groups),
            archives=names,
            owner=self.router.rr._owner.tolist(),
            local=self.router._local.tolist(),
            mig_minted={str(k): v for k, v in self._mig_minted.items()},
            retired_slots={str(g): sorted(s)
                           for g, s in self._retired_slots.items()},
        )
        with open(os.path.join(dir_path, "fleet.json"), "w") as f:
            json.dump(manifest, f)
        return manifest

    def load(self, dir_path: str) -> None:
        from hermes_tpu import snapshot as snapshot_lib

        with open(os.path.join(dir_path, "fleet.json")) as f:
            manifest = json.load(f)
        if manifest.get("kind") != "fleet" or \
                manifest.get("groups") != len(self.groups):
            raise ValueError(
                f"{dir_path} is not a fleet snapshot for {len(self.groups)} "
                "group(s)")
        for grp, name in zip(self.groups, manifest["archives"]):
            with grp.ctx():
                snapshot_lib.load(os.path.join(dir_path, name), grp.rt)
        self.router.rr._owner[:] = np.asarray(manifest["owner"], np.int32)
        self.router._local[:] = np.asarray(manifest["local"], np.int32)
        self._mig_minted = {int(k): v for k, v
                            in manifest["mig_minted"].items()}
        self._retired_slots = {int(g): set(s) for g, s
                               in manifest["retired_slots"].items()}


def verify_fleet(fleet: Fleet) -> dict:
    """The fleet invariants no per-group checker can see (module
    docstring).  Raises AssertionError on the first violation; returns a
    small evidence dict when everything holds.

      1. routing injectivity — no two fleet keys alias one (group, slot);
      2. migration-uid namespaces — every re-minted (hi <= -2) witness
         uid appears in at most ONE group's history (the PR-6 namespace,
         fleet-scoped by Fleet.migrate's ledger);
      3. group-scoped membership — each group's failure-handling state
         (live mask, frozen set, membership service) is its own object
         over its own replicas.
    """
    fleet.router.check_injective()
    seen: Dict[tuple, int] = {}
    mig_uids = 0
    for grp in fleet.groups:
        rt = grp.rt
        if rt.recorder is None:
            continue
        with grp.ctx():
            ops = rt.history_ops()
        for o in ops:
            w = getattr(o, "wuid", None)
            if w is None or w[1] > -2:
                continue
            mig_uids += 1
            other = seen.setdefault(w, grp.gid)
            assert other == grp.gid, (
                f"migration uid {w} appears in group {other} AND group "
                f"{grp.gid}: cross-group witness aliasing (namespace "
                "ledger broken)")
    svcs = [grp.rt.membership for grp in fleet.groups
            if grp.rt.membership is not None]
    assert len(set(map(id, svcs))) == len(svcs), (
        "two groups share one MembershipService instance: detector state "
        "must be group-scoped")
    for grp in fleet.groups:
        assert len(grp.rt.live) == grp.cfg.n_replicas
    return dict(migration_uids=mig_uids, groups=len(fleet.groups))
