"""Fleet-wide chaos (round-13): per-group fault scopes, one lockstep drive.

Faults in a fleet are GROUP-SCOPED by construction: every group gets its
own ``chaos.ChaosRunner`` over its own KVS/runtime/membership service, so
a schedule line for group 0 cannot touch a group 1 replica — there is no
shared live mask, frozen set, detector, or interposer to leak through
(tests/test_fleet.py proves it red-style).  What the fleet adds is the
DRIVE: one lockstep loop ticking every group's runner at the same round
index and stepping all groups each round, so a fleet-wide seeded program
replays byte-identically (same seed + FleetConfig => identical per-group
executed logs AND final state trees — the round-9 determinism contract,
fleet-scoped).

Text form: one schedule per group, each line prefixed with its group
(``g1@12 freeze 2``); unprefixed lines go to group 0 so single-group
schedules stay valid fleet schedules.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Sequence

from hermes_tpu.chaos.schedule import ChaosRunner, ChaosSpec, Schedule


def fleet_schedules(fcfg, seed: int, steps: int,
                    spec: Optional[ChaosSpec] = None) -> List[Schedule]:
    """One seeded program per group: group g draws from a seed derived
    as ``seed * 1_000_003 + g`` (deterministic, group-disjoint streams),
    over that group's OWN config — so per-group shapes draw per-group
    legal targets."""
    return [Schedule.random(fcfg.group_cfg(g), seed * 1_000_003 + g, steps,
                            spec)
            for g in range(fcfg.groups)]


def parse_fleet(text: str, groups: int) -> List[Schedule]:
    """Parse a fleet schedule: ``gN@STEP KIND ...`` lines route to group
    N; unprefixed ``@STEP ...`` lines route to group 0."""
    per: List[list] = [[] for _ in range(groups)]
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        g = 0
        if line.startswith("g"):
            head, _, rest = line.partition("@")
            try:
                g = int(head[1:])
            except ValueError:
                raise ValueError(f"line {ln}: bad group prefix {head!r}")
            if not (0 <= g < groups):
                raise ValueError(f"line {ln}: group {g} outside "
                                 f"[0, {groups})")
            line = "@" + rest
        per[g].append(line)
    return [Schedule.parse("\n".join(lines) + "\n") if lines
            else Schedule([]) for lines in per]


class FleetChaosRunner:
    """Drive a Fleet through per-group schedules in lockstep: round k
    ticks every group's runner (expiries, lease rule, due events — all
    group-scoped), then steps every group once.  Heal, drain, and the
    per-group + fleet-level correctness gate ride the fleet facade."""

    def __init__(self, fleet, schedules: Sequence[Schedule],
                 spec: Optional[ChaosSpec] = None,
                 on_step: Optional[Callable[[int], None]] = None):
        if len(schedules) != len(fleet.groups):
            raise ValueError(
                f"need one schedule per group "
                f"({len(schedules)} != {len(fleet.groups)}); use "
                "Schedule([]) for groups the adversary leaves alone")
        self.fleet = fleet
        self.on_step = on_step
        self.runners = [
            ChaosRunner(grp.kvs, sched, spec=spec)
            for grp, sched in zip(fleet.groups, schedules)
        ]

    def run(self, steps: int, heal: bool = True, drain_steps: int = 4000,
            check: bool = False) -> dict:
        for step in range(steps):
            for grp, runner in zip(self.fleet.groups, self.runners):
                with grp.ctx():
                    runner.tick(step)
            self.fleet.step()
            if self.on_step is not None:
                self.on_step(step)
        result: dict = dict(
            steps=steps,
            lost_ops=sum(r.lost_ops for r in self.runners),
            lost_client_futures=sum(r.lost_client for r in self.runners),
        )
        if heal:
            for grp, runner in zip(self.fleet.groups, self.runners):
                with grp.ctx():
                    runner._heal_adversary(steps)
                    runner._heal_cluster(steps)
                    runner._update_net_phase(steps)
            result["drained"] = bool(self.fleet.drain(drain_steps))
        if check:
            verdicts = self.fleet.check()
            result["checked_ok"] = bool(verdicts["ok"])
            result["group_verdicts"] = verdicts["groups"]
        result["events"] = {g: runner.log
                            for g, runner in enumerate(self.runners)}
        return result

    def log_json(self) -> str:
        """Canonical fleet executed-event log (the determinism witness:
        same seed + FleetConfig => byte-identical)."""
        return json.dumps([r.log for r in self.runners], sort_keys=True,
                          separators=(",", ":"))
