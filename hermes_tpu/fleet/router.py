"""Fleet-level key routing (round-13, hermes_tpu/fleet).

The fleet keyspace ``[0, total_keys)`` is partitioned across G groups;
``FleetRouter`` answers, per fleet key, *which group serves it* and *which
dense slot it occupies there* — the two lookups every routed session and
every batched fan-out needs.  It composes two dense per-slot arrays:

  * ownership + drain state ride ``keyindex.RangeRouter`` unchanged — the
    round-10 migration state machine (begin_drain → flip | release) with
    its boundary-exact semantics (``lo`` in, ``hi`` out, no interval
    arithmetic to get off by one) and its one-host-update atomic flip;
  * ``_local`` maps each fleet key to its dense slot in the owning group.
    At construction that is the affine ``k - lo_g``; a cross-group
    migration replaces the migrated keys' entries with the destination
    slots the transfer actually allocated (``Fleet.migrate`` threads the
    ``migrate_range`` summary through ``flip(..., dest_slots=...)``), so
    the map stays exact across arbitrary move histories.

The (owner, local) pair must stay INJECTIVE — two fleet keys aliasing one
(group, slot) would merge their histories and corrupt both keys' witness
order.  ``check_injective`` proves it from the live arrays; the fleet
verification harness (fleet.core.verify_fleet) runs it after every drill.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from hermes_tpu.keyindex import RangeRouter


class FleetRouter:
    """Fleet key -> (owning group, local dense slot), with the migration
    drain/flip state machine of ``keyindex.RangeRouter`` underneath."""

    def __init__(self, total_keys: int,
                 ranges: Sequence[Tuple[int, int]] = ()):
        self.total_keys = total_keys
        self.rr = RangeRouter(total_keys, default_group=0)
        self._local = np.zeros(total_keys, np.int32)
        for g, (lo, hi) in enumerate(ranges):
            self.rr.assign(lo, hi, g)
            self._local[lo:hi] = np.arange(hi - lo, dtype=np.int32)

    @classmethod
    def from_config(cls, fcfg) -> "FleetRouter":
        return cls(fcfg.total_keys,
                   [fcfg.group_range(g) for g in range(fcfg.groups)])

    # -- lookups (vectorized; scalars accepted) -----------------------------

    def _check(self, keys: np.ndarray) -> None:
        if keys.size and not ((keys >= 0) & (keys < self.total_keys)).all():
            bad = keys[(keys < 0) | (keys >= self.total_keys)]
            raise ValueError(
                f"fleet key(s) {bad[:4].tolist()} outside "
                f"[0, {self.total_keys})")

    def locate(self, keys):
        """(group ids, local dense slots) for fleet keys (shape of
        ``keys``; scalars in, scalars out)."""
        shape = np.shape(keys)
        k = np.atleast_1d(np.asarray(keys, np.int64))
        self._check(k)
        g, s = self.rr.owner(k), self._local[k]
        if shape:
            return g, s
        return int(g[0]), int(s[0])

    def owner(self, keys):
        shape = np.shape(keys)
        k = np.atleast_1d(np.asarray(keys, np.int64))
        self._check(k)
        g = self.rr.owner(k)
        return g if shape else int(g[0])

    def draining(self, keys):
        shape = np.shape(keys)
        k = np.atleast_1d(np.asarray(keys, np.int64))
        self._check(k)
        d = self.rr.draining(k)
        return d if shape else bool(d[0])

    def owned_ranges(self):
        return self.rr.owned_ranges()

    def check_injective(self) -> None:
        """Prove no two fleet keys alias one (group, slot) — the routing
        half of the fleet witness-aliasing invariant (module docstring).
        Raises with the first aliased pair."""
        pair = (self.rr._owner.astype(np.int64) * (2 ** 32)
                + self._local.astype(np.int64))
        uniq, first, counts = np.unique(pair, return_index=True,
                                        return_counts=True)
        dup = counts > 1
        if dup.any():
            w = int(uniq[dup][0])
            ks = np.flatnonzero(pair == w)[:2]
            raise AssertionError(
                f"fleet keys {ks.tolist()} alias (group {w >> 32}, "
                f"slot {w & 0xFFFFFFFF}): their histories would merge")

    # -- migration state machine (fleet coordinates) ------------------------

    def begin_drain(self, lo: int, hi: int) -> None:
        self.rr.begin_drain(lo, hi)

    def release(self, lo: int, hi: int) -> None:
        self.rr.release(lo, hi)

    def flip(self, lo: int, hi: int, new_group: int,
             dest_slots: Optional[np.ndarray] = None) -> None:
        """Atomic cutover: ownership, drain state, AND the local-slot map
        change in one host-side update (``dest_slots[i]`` is the
        destination slot of fleet key ``lo + i`` — the transfer's actual
        allocation; required, because the affine guess would alias the
        destination's own range)."""
        if dest_slots is None:
            raise ValueError(
                "flip needs the transfer's dest_slots: the destination "
                "chose the slots, the router only records them")
        dest_slots = np.asarray(dest_slots, np.int32)
        if dest_slots.shape != (hi - lo,):
            raise ValueError(
                f"dest_slots must map every key of [{lo}, {hi}) "
                f"(got shape {dest_slots.shape})")
        self.rr.flip(lo, hi, new_group)
        self._local[lo:hi] = dest_slots
