"""Correctness gate: history recording + linearizability checking.

BASELINE.json:2 makes "linearizability pass" part of the acceptance metric;
SURVEY.md §4 sets the strategy: unique-valued writes, per-key histories with
real-time intervals derived from step indices, Wing&Gong-style search.
"""
