"""Array-based history recording + native witness checking.

The reference validates at full speed with cheap in-band asserts; our gate
is a real linearizability check (BASELINE.json:2), so bench-scale histories
(millions of ops) need a path without per-op Python objects:

  * ``ArrayRecorder`` — drop-in for checker.history.HistoryRecorder that
    stores completions as packed numpy columns (vectorized per step).
  * ``check_arrays`` — runs the O(n log n) timestamp-witness check in the
    C++ core (native/checker_core.cpp) over all keys at once; only keys the
    witness cannot certify fall back to the exact Python search
    (checker/linearizability.py), so verdicts are identical to the pure
    Python path — FAILs are always confirmed by the exact checker.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
from typing import List, Optional

import numpy as np

from hermes_tpu.checker import linearizability as lin
from hermes_tpu.checker.history import INF, Op
from hermes_tpu.config import HermesConfig
from hermes_tpu.core import types as t

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO = _NATIVE_DIR / "libhermes_checker.so"
_SRC = _NATIVE_DIR / "checker_core.cpp"

_I64_MIN = np.iinfo(np.int64).min
_I64_MAX = np.iinfo(np.int64).max

# kind codes shared with the C++ core
K_READ, K_WRITE, K_RMW, K_MAYBE_W = 0, 1, 2, 3


_CXX = "g++"  # the witness core's compiler (single source of truth)


def default_record(check: bool = True):
    """The recorder kind a checked run should use: ``"array"`` (columnar
    recorder + this native witness) when the compiler is available, the
    pure-Python recorder (``True``) otherwise, ``False`` when not checking.
    Shared by acceptance / kvs_scale so the compiler choice lives here."""
    import shutil

    return ("array" if shutil.which(_CXX) else True) if check else False


def _ensure_built(force: bool = False) -> pathlib.Path:
    if not force and _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    tmp = _SO.with_suffix(f".so.tmp.{os.getpid()}")
    subprocess.run(
        [_CXX, "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)],
        check=True, cwd=str(_NATIVE_DIR),
    )
    os.replace(tmp, _SO)
    return _SO


_lib = None


def _core():
    global _lib
    if _lib is None:
        from hermes_tpu.core.compat import load_native

        _lib = load_native(_ensure_built)
        _lib.hc_check_witness.restype = ctypes.c_int64
        _lib.hc_check_witness.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
        ]
    return _lib


def _pack_uid(lo, hi):
    return (hi.astype(np.int64) & 0xFFFFFFFF) << 32 | (lo.astype(np.int64) & 0xFFFFFFFF)


class ArrayRecorder:
    """Columnar history recorder (same surface as HistoryRecorder)."""

    def __init__(self, cfg: HermesConfig):
        self.cfg = cfg
        self._chunks: List[dict] = []
        self.aborted_uids: set = set()
        self._finalized = False

    def record_step(self, comp) -> None:
        code = np.asarray(comp.code)
        sel = code != t.C_NONE
        if not sel.any():
            return
        wval = np.asarray(comp.wval)[sel]
        rval = np.asarray(comp.rval)[sel]
        c = code[sel]
        chunk = dict(
            code=c.astype(np.int32),
            key=np.asarray(comp.key)[sel].astype(np.int32),
            wlo=wval[:, 0].astype(np.int32), whi=wval[:, 1].astype(np.int32),
            rlo=rval[:, 0].astype(np.int32), rhi=rval[:, 1].astype(np.int32),
            ver=np.asarray(comp.ver)[sel].astype(np.int64),
            fc=np.asarray(comp.fc)[sel].astype(np.int64),
            inv=np.asarray(comp.invoke_step)[sel].astype(np.int64),
            cmt=np.asarray(comp.commit_step)[sel].astype(np.int64),
        )
        ab = chunk["code"] == t.C_RMW_ABORT
        if ab.any():
            self.aborted_uids.update(
                zip(chunk["wlo"][ab].tolist(), chunk["whi"][ab].tolist())
            )
        self._chunks.append(chunk)

    def fold_pending(self, sess, replica: int = None, mask=None) -> int:
        """Fold in-flight updates (optionally one replica's row, or an
        arbitrary ``(R, S)`` slot ``mask``) in as maybe_w rows (they may or
        may not have taken effect; the checker lets them linearize
        optionally).  Called by ``finalize`` at end of run, by
        ``chaos.recovery.restart_replica`` at crash time, and by a range
        migration's forced cutover (hermes_tpu.elastic) for salvaged
        slots."""
        status = np.asarray(sess.status)
        op = np.asarray(sess.op)
        sel = (status == t.S_INFL) & ((op == t.OP_WRITE) | (op == t.OP_RMW))
        if replica is not None:
            keep = np.zeros_like(sel)
            keep[replica] = True
            sel = sel & keep
        if mask is not None:
            sel = sel & np.asarray(mask, bool)
        if sel.any():
            val = np.asarray(sess.val)[sel]
            self._chunks.append(dict(
                code=np.full(sel.sum(), -1, np.int32),  # -1 = maybe_w
                key=np.asarray(sess.key)[sel].astype(np.int32),
                wlo=val[:, 0].astype(np.int32), whi=val[:, 1].astype(np.int32),
                rlo=np.zeros(sel.sum(), np.int32), rhi=np.zeros(sel.sum(), np.int32),
                ver=np.asarray(sess.ver)[sel].astype(np.int64),
                fc=np.asarray(sess.fc)[sel].astype(np.int64),
                inv=np.asarray(sess.invoke_step)[sel].astype(np.int64),
                cmt=np.full(sel.sum(), -1, np.int64),
            ))
        return int(sel.sum())

    def record_migration(self, keys, uids, vers, fcs, step: int) -> int:
        """Seed migrated-in keys as committed writes (round-10 elastic
        migration; same semantics as HistoryRecorder.record_migration):
        one columnar chunk, responding at ``2*(step-1)+1`` — strictly
        before any post-flip completion."""
        keys = np.asarray(keys, np.int32)
        uids = np.asarray(uids, np.int32).reshape(-1, 2)
        n = keys.shape[0]
        if n == 0:
            return 0
        self._chunks.append(dict(
            code=np.full(n, t.C_WRITE, np.int32),
            key=keys,
            wlo=uids[:, 0], whi=uids[:, 1],
            rlo=np.zeros(n, np.int32), rhi=np.zeros(n, np.int32),
            ver=np.asarray(vers, np.int64),
            fc=np.asarray(fcs, np.int64),
            inv=np.full(n, step - 1, np.int64),
            cmt=np.full(n, step - 1, np.int64),
        ))
        return n

    def finalize(self, sess=None) -> "ArrayRecorder":
        """Fold still-in-flight updates in as maybe_w rows (fold_pending);
        idempotent — the end-of-run fold happens once."""
        if sess is not None and not self._finalized:
            self._finalized = True
            self.fold_pending(sess)
        return self

    # -- packed views --------------------------------------------------------

    @property
    def n_recorded(self) -> int:
        """Raw completion rows recorded so far (pre-finalize; includes NOP
        and aborted-RMW rows that columns() drops)."""
        return sum(c["code"].shape[0] for c in self._chunks)

    def columns(self) -> dict:
        if not self._chunks:
            return {k: np.zeros(0, np.int64) for k in
                    ("kind", "key", "inv", "resp", "wuid", "ruid", "ts")}
        cat = {f: np.concatenate([c[f] for c in self._chunks])
               for f in self._chunks[0]}
        code = cat["code"]
        keep = code != t.C_NOP
        code, cat = code[keep], {f: v[keep] for f, v in cat.items()}
        # drop aborted-RMW completion rows (no-ops; the global aborted-value
        # rule is enforced in check_arrays)
        keep = code != t.C_RMW_ABORT
        code, cat = code[keep], {f: v[keep] for f, v in cat.items()}

        kind = np.full(code.shape, K_MAYBE_W, np.int8)
        kind[code == t.C_READ] = K_READ
        kind[code == t.C_WRITE] = K_WRITE
        kind[code == t.C_RMW] = K_RMW

        inv = 2 * cat["inv"]
        resp = np.where(code == t.C_READ, 2 * cat["cmt"], 2 * cat["cmt"] + 1)
        resp = np.where(code == -1, _I64_MAX, resp)

        wuid = _pack_uid(cat["wlo"], cat["whi"])
        ruid = np.where(
            (kind == K_READ) | (kind == K_RMW),
            _pack_uid(cat["rlo"], cat["rhi"]), _I64_MIN,
        )
        ts = np.where(kind != K_READ, (cat["ver"] << 32) | cat["fc"], _I64_MIN)
        return dict(kind=kind, key=cat["key"], inv=inv, resp=resp,
                    wuid=wuid, ruid=ruid, ts=ts)

    def to_ops(self, cols: Optional[dict] = None,
               only_keys: Optional[set] = None) -> List[Op]:
        """Materialize (a subset of) the history as checker Op objects."""
        c = cols or self.columns()
        ops = []
        for i in range(len(c["kind"])):
            k = int(c["key"][i])
            if only_keys is not None and k not in only_keys:
                continue
            kind = {K_READ: "r", K_WRITE: "w", K_RMW: "rmw", K_MAYBE_W: "maybe_w"}[
                int(c["kind"][i])]
            wuid = ruid = None
            if kind != "r":
                w = int(c["wuid"][i])
                wuid = (_s32(w & 0xFFFFFFFF), _s32((w >> 32) & 0xFFFFFFFF))
            if int(c["ruid"][i]) != _I64_MIN:
                r = int(c["ruid"][i])
                ruid = (_s32(r & 0xFFFFFFFF), _s32((r >> 32) & 0xFFFFFFFF))
            ts = None
            if int(c["ts"][i]) != _I64_MIN:
                ts = (int(c["ts"][i]) >> 32, int(c["ts"][i]) & 0xFFFFFFFF)
            resp = float("inf") if c["resp"][i] == _I64_MAX else float(c["resp"][i])
            ops.append(Op(kind, k, float(c["inv"][i]), resp, wuid=wuid,
                          ruid=ruid, ts=ts))
        return ops


def _s32(x: int) -> int:
    return x - (1 << 32) if x >= (1 << 31) else x


def check_arrays(rec: ArrayRecorder, max_keys: Optional[int] = None,
                 seed: int = 0) -> lin.Verdict:
    """Native witness over every key; exact Python search on suspects."""
    cols = rec.columns()
    n = len(cols["kind"])

    # global rule: an aborted RMW's value must never be observed
    if rec.aborted_uids:
        ab = np.array([_pack_uid(np.int32(lo), np.int32(hi))
                       for lo, hi in rec.aborted_uids], np.int64)
        bad = np.isin(cols["ruid"], ab) & (cols["ruid"] != _I64_MIN)
        if bad.any():
            i = int(np.nonzero(bad)[0][0])
            return lin.Verdict(ok=False, keys_checked=0, failures=[
                lin.KeyVerdict(int(cols["key"][i]), False,
                               "aborted RMW value observed")], undecided=[])

    if max_keys is not None:
        keys = np.unique(cols["key"])
        if len(keys) > max_keys:
            import random

            keep = np.array(sorted(random.Random(seed).sample(
                keys.tolist(), max_keys)), np.int32)
            sel = np.isin(cols["key"], keep)
            cols = {f: v[sel] for f, v in cols.items()}
            n = len(cols["kind"])

    n_keys = len(np.unique(cols["key"])) if n else 0
    if n == 0:
        return lin.Verdict(ok=True, keys_checked=0, failures=[], undecided=[])

    lib = _core()
    max_out = n_keys + 1
    out = np.zeros(max_out, np.int32)
    ns = lib.hc_check_witness(
        n,
        np.ascontiguousarray(cols["key"], np.int32),
        np.ascontiguousarray(cols["kind"], np.int8),
        np.ascontiguousarray(cols["inv"], np.int64),
        np.ascontiguousarray(cols["resp"], np.int64),
        np.ascontiguousarray(cols["wuid"], np.int64),
        np.ascontiguousarray(cols["ruid"], np.int64),
        np.ascontiguousarray(cols["ts"], np.int64),
        out, max_out,
    )
    if ns < 0:
        raise RuntimeError("hc_check_witness: invalid arguments")
    suspects = set(out[: min(ns, max_out)].tolist())

    failures, undecided = [], []
    if suspects:
        ops = rec.to_ops(cols, only_keys=suspects)
        by_key = {}
        for o in ops:
            by_key.setdefault(o.key, []).append(o)
        for k, kops in by_key.items():
            v = lin.check_key(k, kops, (k, -1))
            if v.undecided:
                undecided.append(v)
            elif not v.ok:
                failures.append(v)
    return lin.Verdict(ok=not failures and not undecided, keys_checked=n_keys,
                       failures=failures, undecided=undecided)
