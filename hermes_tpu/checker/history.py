"""Operation-history recording (SURVEY.md §4, §7 hard part 1).

The bulk-synchronous step gives a natural real-time order: within step s the
phase pipeline fixes  commits(s-1)  <  reads(s)  <  commits(s).  We encode it
by doubling: a read completing at step s responds at time 2s; an update
committing at step s responds (and linearizes) at 2s+1; every op's invocation
is 2*load_step.  These are exactly the client-observable invocation/response
times, so checking against them is neither optimistic nor pessimistic.

Write values are unique (uid = (lo, hi) int32 pair derived from
replica/session/op — see phases._write_value); the initial value of key k is
(lo=k, hi=-1) (state.init_table).  Uniqueness is what makes per-key
linearizability checking tractable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from hermes_tpu.config import HermesConfig
from hermes_tpu.core import types as t

Uid = Tuple[int, int]  # (lo, hi)

INF = float("inf")


@dataclasses.dataclass(frozen=True)
class Op:
    """One client operation in the history."""

    kind: str  # 'r' | 'w' | 'rmw' | 'maybe_w' (incomplete update, may have applied)
    key: int
    inv: float  # invocation time (2 * load_step)
    resp: float  # response time (2s for reads, 2s+1 for commits, inf if incomplete)
    wuid: Optional[Uid] = None  # value written (updates)
    ruid: Optional[Uid] = None  # value observed (reads; RMW read-part)
    ts: Optional[Tuple[int, int]] = None  # protocol (ver, fc) — linearization witness
    replica: int = -1
    session: int = -1


class HistoryRecorder:
    """Accumulates per-step completion records into a flat op history.

    Completions arrive as (R, S) arrays per step (state.Completions).  At end
    of run, ``finalize`` folds in still-pending updates (which may or may not
    have taken effect — the checker treats them as optional writes) from the
    final session state."""

    def __init__(self, cfg: HermesConfig):
        self.cfg = cfg
        self.ops: List[Op] = []
        self.aborted_uids: set = set()
        self._finalized = False

    def record_step(self, comp) -> None:
        code = np.asarray(comp.code)
        if not (code != t.C_NONE).any():
            return
        key = np.asarray(comp.key)
        wval = np.asarray(comp.wval)
        rval = np.asarray(comp.rval)
        ver = np.asarray(comp.ver)
        fc = np.asarray(comp.fc)
        inv = np.asarray(comp.invoke_step)
        cmt = np.asarray(comp.commit_step)
        rr, ss = np.nonzero(code != t.C_NONE)
        for r, s in zip(rr.tolist(), ss.tolist()):
            c = int(code[r, s])
            k = int(key[r, s])
            i2 = 2.0 * inv[r, s]
            ts = (int(ver[r, s]), int(fc[r, s]))
            if c == t.C_READ:
                self.ops.append(
                    Op("r", k, i2, 2.0 * cmt[r, s],
                       ruid=(int(rval[r, s, 0]), int(rval[r, s, 1])), replica=r, session=s)
                )
            elif c == t.C_WRITE:
                self.ops.append(
                    Op("w", k, i2, 2.0 * cmt[r, s] + 1,
                       wuid=(int(wval[r, s, 0]), int(wval[r, s, 1])), ts=ts,
                       replica=r, session=s)
                )
            elif c == t.C_RMW:
                self.ops.append(
                    Op("rmw", k, i2, 2.0 * cmt[r, s] + 1,
                       wuid=(int(wval[r, s, 0]), int(wval[r, s, 1])),
                       ruid=(int(rval[r, s, 0]), int(rval[r, s, 1])), ts=ts,
                       replica=r, session=s)
                )
            elif c == t.C_RMW_ABORT:
                self.aborted_uids.add((int(wval[r, s, 0]), int(wval[r, s, 1])))
            # C_NOP: no effect on the register history

    def fold_pending(self, sess, replica: int = None, mask=None) -> int:
        """Fold in-flight updates of ``sess`` (optionally one replica's
        row, or an arbitrary ``(R, S)`` slot ``mask``) as ``maybe_w`` ops:
        an update still gathering acks may have been applied at some
        replica and must be allowed — but not required — to linearize.
        ``finalize`` calls this once at end of run for the whole cluster;
        ``chaos.recovery.restart_replica`` calls it at CRASH time for the
        dying replica, whose in-flight broadcasts may still commit via
        replay even though the client never hears back; a key-range
        migration's forced cutover (hermes_tpu.elastic) calls it with the
        mask of salvaged slots.  Returns the number of ops folded."""
        status = np.asarray(sess.status)
        op = np.asarray(sess.op)
        key = np.asarray(sess.key)
        val = np.asarray(sess.val)
        ver = np.asarray(sess.ver)
        fc = np.asarray(sess.fc)
        inv = np.asarray(sess.invoke_step)
        infl = status == t.S_INFL
        if mask is not None:
            infl = infl & np.asarray(mask, bool)
        rr, ss = np.nonzero(infl)
        n = 0
        for r, s in zip(rr.tolist(), ss.tolist()):
            if replica is not None and r != replica:
                continue
            if op[r, s] in (t.OP_WRITE, t.OP_RMW):
                self.ops.append(
                    Op("maybe_w", int(key[r, s]), 2.0 * inv[r, s], INF,
                       wuid=(int(val[r, s, 0]), int(val[r, s, 1])),
                       ts=(int(ver[r, s]), int(fc[r, s])),
                       replica=r, session=s)
                )
                n += 1
        return n

    def record_migration(self, keys, uids, vers, fcs, step: int) -> int:
        """Seed migrated-in keys (round-10, hermes_tpu.elastic): each key's
        current value enters this history as a committed write — the
        migration IS a write of the transferred value, linearized strictly
        before any post-flip op (``step`` is the destination round of the
        flip; the synthetic op responds at ``2*(step-1)+1``, ahead of any
        completion of round ``step``).  ``uids`` are the re-minted
        (lo=slot, hi<-2) migration uids the restored rows now carry, so
        later reads observe exactly this write.  Preconditions owned by
        the migration driver: the keys are FRESH here (no prior committed
        ops in this history)."""
        n = 0
        for k, (wlo, whi), ver, fc in zip(keys, uids, vers, fcs):
            self.ops.append(
                Op("w", int(k), 2.0 * (step - 1), 2.0 * (step - 1) + 1,
                   wuid=(int(wlo), int(whi)), ts=(int(ver), int(fc))))
            n += 1
        return n

    def finalize(self, sess=None) -> List[Op]:
        """Fold in incomplete updates from the final session state
        (``fold_pending``).  Idempotent: the fold-in happens once."""
        if sess is not None and not self._finalized:
            self._finalized = True
            self.fold_pending(sess)
        return self.ops

    def by_key(self) -> Dict[int, List[Op]]:
        out: Dict[int, List[Op]] = {}
        for o in self.ops:
            out.setdefault(o.key, []).append(o)
        return out
