"""Per-key linearizability checker (SURVEY.md §4; gate per BASELINE.json:2).

A Wing&Gong-style search specialised to registers with unique write values
(the workload guarantees uniqueness; history.py documents the encoding):

  * The history is partitioned by key — a register history is linearizable
    iff each key's sub-history is (locality of linearizability).
  * Per key, DFS over linearization prefixes with memoization on
    (done-set, current-value).  An op may be linearized next iff no undone
    op's response precedes its invocation (real-time), and its value
    constraint holds: reads/RMW-read-parts must observe the current value.
  * Incomplete updates ('maybe_w') may linearize at any point after their
    invocation or be dropped entirely (the coordinator may or may not have
    propagated them before the history ended).
  * Aborted RMWs are no-ops; their uids must never be observed anywhere
    (checked globally first — the write-flag tie-break in the protocol
    guarantees it, see core/types.py).

Complexity is exponential in the worst case (the problem is NP-hard in
general) but with unique values and real-time pruning it is fast on the
histories our runs produce; `max_states` bounds pathological blowup and
turns it into an explicit "undecided" outcome rather than a hang.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from hermes_tpu.checker.history import INF, Op, Uid


class _Budget(Exception):
    pass


@dataclasses.dataclass
class KeyVerdict:
    key: int
    ok: bool
    reason: str = ""
    states_explored: int = 0
    undecided: bool = False


@dataclasses.dataclass
class Verdict:
    ok: bool
    keys_checked: int
    failures: List[KeyVerdict]
    undecided: List[KeyVerdict]

    def __bool__(self) -> bool:
        return self.ok

    def to_dict(self, max_examples: int = 3) -> dict:
        """JSON-friendly summary (artifact scripts); a non-ok verdict always
        carries diagnosable examples — failures or undecided keys."""
        return {
            "verdict_ok": self.ok,
            "keys_checked": self.keys_checked,
            "failures": [repr(f) for f in self.failures[:max_examples]],
            "undecided": [repr(u) for u in self.undecided[:max_examples]],
        }


def check_history(
    ops: Sequence[Op],
    initial_uid_for_key=lambda k: (k, -1),
    aborted_uids: Optional[set] = None,
    max_states: int = 2_000_000,
) -> Verdict:
    """Check a full multi-key history.  Returns an aggregate Verdict."""
    aborted = aborted_uids or set()
    # Global rule: an aborted RMW's value must never be observed.
    for o in ops:
        if o.ruid is not None and o.ruid in aborted:
            return Verdict(
                ok=False,
                keys_checked=0,
                failures=[KeyVerdict(o.key, False, f"aborted RMW value {o.ruid} observed by {o}")],
                undecided=[],
            )

    by_key: Dict[int, List[Op]] = {}
    for o in ops:
        by_key.setdefault(o.key, []).append(o)

    failures, undecided = [], []
    for k, kops in by_key.items():
        v = check_key(k, kops, initial_uid_for_key(k), max_states=max_states)
        if v.undecided:
            undecided.append(v)
        elif not v.ok:
            failures.append(v)
    return Verdict(
        ok=not failures and not undecided,
        keys_checked=len(by_key),
        failures=failures,
        undecided=undecided,
    )


def check_key(key: int, ops: Sequence[Op], initial_uid: Uid, max_states: int = 2_000_000) -> KeyVerdict:
    """Check one key's sub-history.

    Fast path: the protocol's own timestamps are a linearization *witness* —
    updates in (ver, fc) order with each read placed after the update that
    wrote its value.  Verifying a given sequence is O(n log n); if it is
    real-time-feasible the history is linearizable, full stop.  Only when the
    witness fails (which in a correct run should never happen) do we fall
    back to the exact Wing&Gong search, so a checker FAIL is never a false
    alarm from the shortcut."""
    n = len(ops)
    if n == 0:
        return KeyVerdict(key, True)

    wv = _check_witness(key, ops, initial_uid)
    if wv is not None and wv.ok:
        return wv
    exact = _check_key_exact(key, ops, initial_uid, max_states)
    if exact.undecided and wv is not None and not wv.ok:
        # exact search can't decide (too large) but the witness concretely
        # failed — report that failure rather than an empty "undecided"
        return dataclasses.replace(
            wv, reason="witness failed (exact search infeasible): " + wv.reason
        )
    return exact


def _check_witness(key: int, ops: Sequence[Op], initial_uid: Uid) -> Optional[KeyVerdict]:
    """O(n log n) witness check using protocol timestamps.  Returns None when
    inapplicable (some update lacks a ts)."""
    updates = [o for o in ops if o.kind in ("w", "rmw")]
    observed = {o.ruid for o in ops if o.ruid is not None}
    updates += [o for o in ops if o.kind == "maybe_w" and o.wuid in observed]
    if any(o.ts is None for o in updates):
        return None
    ts_list = [o.ts for o in updates]
    if len(set(ts_list)) != len(ts_list):
        return KeyVerdict(key, False, reason="duplicate update timestamps (protocol bug)")
    updates.sort(key=lambda o: o.ts)

    reads_by_uid: dict = {}
    for o in ops:
        if o.kind == "r":
            reads_by_uid.setdefault(o.ruid, []).append(o)
    for rl in reads_by_uid.values():
        rl.sort(key=lambda o: o.inv)

    seq: List[Op] = list(reads_by_uid.get(initial_uid, []))
    cur = initial_uid
    for u in updates:
        if u.kind == "rmw" and u.ruid != cur:
            return KeyVerdict(
                key, False,
                reason=f"witness: RMW {u.wuid} observed {u.ruid} but ts-predecessor value is {cur}",
            )
        seq.append(u)
        cur = u.wuid
        seq.extend(reads_by_uid.get(cur, []))
    known = {initial_uid} | {u.wuid for u in updates}
    for uid, rl in reads_by_uid.items():
        if uid not in known:
            return KeyVerdict(key, False, reason=f"read of unknown value {uid} (op {rl[0]})")

    # greedy feasibility: strictly non-decreasing points p_i in [inv_i, resp_i]
    p = -INF
    for o in seq:
        p = max(p, o.inv)
        if p > o.resp:
            return KeyVerdict(
                key, False,
                reason=f"witness: real-time infeasible at {o} (needed point {p} > resp {o.resp})",
            )
    return KeyVerdict(key, True, states_explored=0)


def _check_key_exact(key: int, ops: Sequence[Op], initial_uid: Uid, max_states: int) -> KeyVerdict:
    """DFS (Wing&Gong) linearizability check of one key's sub-history."""
    n = len(ops)
    if n > 62:
        # bitmask-int done-sets need n <= 62; larger keys rely on the witness
        # path (which has no size limit).  Flag honestly rather than guess.
        return KeyVerdict(key, True, reason=f"exact search skipped: {n} ops > 62", undecided=True)

    inv = [o.inv for o in ops]
    resp = [o.resp for o in ops]
    kind = [o.kind for o in ops]
    wuid = [o.wuid for o in ops]
    ruid = [o.ruid for o in ops]
    required_mask = 0
    for i, o in enumerate(ops):
        if o.kind != "maybe_w":
            required_mask |= 1 << i

    # quick necessary condition: a completed read observing X requires X to be
    # initial or written by some op in the history
    writes_by_uid = {w: i for i, w in enumerate(wuid) if w is not None}
    for i, o in enumerate(ops):
        if o.ruid is not None and o.ruid != initial_uid and o.ruid not in writes_by_uid:
            return KeyVerdict(key, False, f"read of unknown value {o.ruid} (op {o})")

    seen = set()
    states = 0

    def dfs(done: int, cur: Uid) -> bool:
        nonlocal states
        if (done & required_mask) == required_mask:
            return True
        if (done, cur) in seen:
            return False
        states += 1
        if states > max_states:
            raise _Budget()
        seen.add((done, cur))
        # frontier: min response among undone ops — an op can linearize next
        # only if its invocation precedes every undone op's response
        min_resp = INF
        for i in range(n):
            if not done & (1 << i) and resp[i] < min_resp:
                min_resp = resp[i]
        for i in range(n):
            bit = 1 << i
            if done & bit or inv[i] > min_resp:
                continue
            ki = kind[i]
            if ki == "r":
                if ruid[i] == cur and dfs(done | bit, cur):
                    return True
            elif ki == "rmw":
                if ruid[i] == cur and dfs(done | bit, wuid[i]):
                    return True
            else:  # 'w' or 'maybe_w'
                if dfs(done | bit, wuid[i]):
                    return True
        return False

    try:
        ok = dfs(0, initial_uid)
    except _Budget:
        return KeyVerdict(key, True, reason=f"state budget exceeded ({max_states})",
                          states_explored=states, undecided=True)
    if ok:
        return KeyVerdict(key, True, states_explored=states)
    return KeyVerdict(
        key, False,
        reason=f"no linearization exists for {n} ops: {sorted(ops, key=lambda o: o.inv)[:6]}...",
        states_explored=states,
    )


def committed_write_lost(committed_uids, ops: Sequence[Op],
                         aborted_uids: Optional[set] = None) -> List[Uid]:
    """Round-11 safety cross-check, structural form of the PR-5 bug class
    (committed-and-observed write reported aborted): given the write uids
    the CLIENT saw commit (resolved put/rmw futures), return every uid the
    recorded history contradicts — reported aborted, or recorded only as a
    non-committed row (maybe_w/absent counts as lost: the history must
    carry a definite committed write for every client-visible commit).
    Empty list = no committed-and-observed write was ever reported
    lost/aborted — the partition+heal acceptance criterion."""
    aborted = aborted_uids or set()
    definite = {o.wuid for o in ops if o.kind in ("w", "rmw")
                and o.wuid is not None}
    lost = []
    for uid in committed_uids:
        if uid in aborted or uid not in definite:
            lost.append(uid)
    return lost


def stale_read(ops: Sequence[Op], initial_uid_for_key=lambda k: (k, -1)
               ) -> List[dict]:
    """Round-16 read-side safety cross-check, structural form of the
    local-read hazard class: a read that returned a value the history
    PROVES was overwritten before the read was even issued.

    The full Wing&Gong search would also reject such a history, but (like
    ``committed_write_lost`` for the PR-5 bug class) this names the exact
    failure shape the read fast path could introduce — serving stale
    bytes from a row the protocol already superseded — so a violation is
    diagnosed as "stale read", not as an opaque no-linearization-exists.

    Rule: updates linearize in protocol-timestamp order (the witness).
    For a read r observing value v written by committed update u1, if ANY
    committed update u2 on the key has ts(u2) > ts(u1) and responded
    before r was invoked (u2.resp < r.inv), then v was provably no longer
    current at every point in [r.inv, r.resp] — u2 had already linearized
    and only higher-ts updates can follow — so r cannot linearize.
    Reads of the initial value are stale once any committed update
    responded before their invocation.  Incomplete updates (maybe_w)
    never prove staleness (they may linearize arbitrarily late).

    Returns evidence dicts (empty list = clean): one per stale read with
    the read, the value it observed, and the superseding update."""
    by_key: Dict[int, List[Op]] = {}
    for o in ops:
        by_key.setdefault(o.key, []).append(o)
    evidence: List[dict] = []
    for k, kops in by_key.items():
        updates = [o for o in kops if o.kind in ("w", "rmw")
                   and o.ts is not None]
        if not updates:
            continue
        updates.sort(key=lambda o: o.ts)
        ts_of = {u.wuid: i for i, u in enumerate(updates)}
        # sufmin[i] = earliest response among updates ranked > i (the
        # first PROVEN overwrite time of update i's value)
        sufmin = [INF] * (len(updates) + 1)
        for i in range(len(updates) - 1, -1, -1):
            sufmin[i] = min(sufmin[i + 1], updates[i].resp)
        initial = initial_uid_for_key(k)
        for o in kops:
            if o.kind not in ("r", "rmw") or o.ruid is None:
                continue
            if o.ruid == initial:
                overwritten = sufmin[0]
            else:
                rank = ts_of.get(o.ruid)
                if rank is None:
                    continue  # unknown/maybe value: not this check's job
                overwritten = sufmin[rank + 1]
            if overwritten < o.inv:
                cands = (updates if o.ruid == initial
                         else updates[ts_of[o.ruid] + 1:])
                sup = min(cands, key=lambda u: u.resp)
                evidence.append(dict(
                    key=k, read=o, observed=o.ruid,
                    superseded_by=sup.wuid, superseded_resp=sup.resp))
        if len(evidence) >= 64:
            break  # plenty of evidence; keep failure reports bounded
    return evidence


def sample_keys(ops: Sequence[Op], max_keys: int = 512, seed: int = 0) -> List[Op]:
    """Down-sample a huge history to ``max_keys`` keys (bench-scale runs
    check a sample; tests check everything).  Keeps whole per-key
    sub-histories so locality still applies."""
    import random

    keys = sorted({o.key for o in ops})
    if len(keys) <= max_keys:
        return list(ops)
    rnd = random.Random(seed)
    keep = set(rnd.sample(keys, max_keys))
    return [o for o in ops if o.key in keep]
