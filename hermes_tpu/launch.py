"""Multi-host bootstrap (SURVEY.md §1 L0, §5.8 "DCN via jax.distributed").

The reference boots one process per machine and exchanges connection info
through a registry (HERD-style memcached bootstrap).  The JAX-native
equivalent is ``jax.distributed.initialize`` — the coordinator address
plays the registry role, and the global device mesh that results carries
replica traffic over ICI within a slice and DCN across hosts.

Single-process usage (tests, single chip/slice) skips initialization and
just builds the mesh over local devices.

    # one process per host, same command everywhere:
    python -m hermes_tpu.launch --coordinator host0:9999 --num-hosts 4 \
        --host-id $ID --replicas 16 --steps 200

Each global device becomes one Hermes replica (BASELINE.json:5: one chip =
one replica); the sharded faststep round runs under shard_map over the
'replica' axis of the global mesh, so INV/ACK/VAL collectives ride ICI
within a host's slice and DCN between hosts — no NCCL/MPI analog needed,
XLA owns the wire.
"""

from __future__ import annotations

import argparse
from typing import Optional

import numpy as np


def init_distributed(coordinator: Optional[str] = None, num_hosts: int = 1,
                     host_id: int = 0) -> None:
    """Initialize cross-host JAX (no-op for single-process runs)."""
    if num_hosts <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_hosts,
        process_id=host_id,
    )


def replica_mesh(n_replicas: Optional[int] = None):
    """Mesh(('replica',)) over the global device list (all hosts)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_replicas or len(devs)
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices for {n} replicas, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("replica",))


def fleet_meshes(n_groups: int, n_replicas: Optional[int] = None):
    """The (groups, replicas) fleet grid (round-13, hermes_tpu/fleet):
    the global device list reshaped into ``n_groups`` rows of
    ``n_replicas`` devices, ONE disjoint ``Mesh(('replica',))`` per row.
    Groups are independent protocol instances, so each gets its own mesh
    over its own chips — the mesh-at-call-site pattern, with group
    isolation enforced by device DISJOINTNESS rather than by a shared
    2-D mesh's axis discipline.

    Process-to-group placement falls out of the row-major reshape: with
    one host per slice and devices enumerated host-major
    (jax.distributed), a host's addressable devices land in contiguous
    rows — ``group_of_process`` names the group(s) a process serves."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_replicas is None:
        if len(devs) % n_groups:
            raise RuntimeError(
                f"{len(devs)} devices do not split into {n_groups} equal "
                "groups; pass n_replicas explicitly")
        n_replicas = len(devs) // n_groups
    need = n_groups * n_replicas
    if len(devs) < need:
        raise RuntimeError(
            f"need {need} devices for a {n_groups}x{n_replicas} fleet "
            f"grid, have {len(devs)}")
    grid = np.array(devs[:need]).reshape(n_groups, n_replicas)
    return [Mesh(grid[g], ("replica",)) for g in range(n_groups)]


def group_of_process(n_groups: int, n_replicas: Optional[int] = None) -> list:
    """The fleet groups THIS process holds devices of (multi-host
    process-to-group placement): the rows of the fleet grid containing
    at least one locally-addressable device."""
    import jax

    devs = jax.devices()
    if n_replicas is None:
        n_replicas = len(devs) // n_groups
    local = {d.id for d in jax.local_devices()}
    return sorted({g for g in range(n_groups)
                   for d in devs[g * n_replicas:(g + 1) * n_replicas]
                   if d.id in local})


def run(cfg, steps: int, coordinator=None, num_hosts=1, host_id=0):
    """Boot (multi-host if asked), build the mesh, run the sharded fast
    round for ``steps`` rounds; returns the runtime for inspection."""
    init_distributed(coordinator, num_hosts, host_id)
    from hermes_tpu.runtime import FastRuntime

    mesh = replica_mesh(cfg.n_replicas)
    rt = FastRuntime(cfg, backend="sharded", mesh=mesh)
    rt.run(steps)
    return rt


def run_fleet(fcfg, steps: int, coordinator=None, num_hosts=1, host_id=0):
    """Boot (multi-host if asked) and run a sharded FLEET: G independent
    group runtimes on the (groups, replicas) grid, one disjoint submesh
    each (fleet_meshes), stepped in lockstep — dispatches are
    independent XLA programs, so group rounds overlap on the grid.
    Returns the per-group runtimes (group g = rts[g])."""
    init_distributed(coordinator, num_hosts, host_id)
    from hermes_tpu.runtime import FastRuntime

    meshes = fleet_meshes(fcfg.groups, fcfg.base.n_replicas)
    rts = []
    for g in range(fcfg.groups):
        rt = FastRuntime(fcfg.group_cfg(g), backend="sharded",
                         mesh=meshes[g])
        rt.group = g
        rts.append(rt)
    for _ in range(steps):
        for rt in rts:
            rt.step_once()
    return rts


class ServeWorkers:
    """Handle over a sharded-accept serving fleet (round-19): N worker
    processes, one SO_REUSEPORT listener each on ``addr``, started by
    ``start_serve_workers`` and joined by ``stop()``."""

    def __init__(self, procs, stop_ev, addr):
        self.procs = procs
        self.addr = addr
        self._stop_ev = stop_ev

    def alive(self) -> int:
        return sum(p.is_alive() for p in self.procs)

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop_ev.set()
        for p in self.procs:
            p.join(timeout=timeout_s)
        for p in self.procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def start_serve_workers(n_workers: int, cfg=None, scfg=None,
                        host: str = "127.0.0.1", port: int = 0,
                        ready_timeout_s: float = 120.0) -> ServeWorkers:
    """Start ``n_workers`` columnar serving worker PROCESSES sharing one
    port via SO_REUSEPORT accept sharding (serving/rpc.py round-19):
    each worker owns its own KVS, ColumnarFrontend, and GIL; the kernel
    load-balances client connections across them.  Blocks until every
    worker is accepting (or raises loudly if one dies during boot)."""
    import multiprocessing as mp
    import socket as _socket

    from hermes_tpu.config import HermesConfig
    from hermes_tpu.serving.rpc import serve_worker_main
    from hermes_tpu.serving.server import ServingConfig

    if n_workers < 1:
        raise ValueError("need at least one serve worker")
    cfg = cfg or HermesConfig(n_replicas=4, n_keys=1 << 10, n_sessions=64,
                              value_words=6)
    scfg = scfg or ServingConfig()
    if port == 0:
        # claim a concrete port up front: every worker must bind the
        # SAME number for the kernel to shard accepts across them
        probe = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        probe.bind((host, 0))
        port = probe.getsockname()[1]
        probe.close()
    ctx = mp.get_context("spawn")
    ready_q = ctx.Queue()
    stop_ev = ctx.Event()
    procs = []
    for w in range(n_workers):
        p = ctx.Process(target=serve_worker_main,
                        args=(w, host, port, cfg, scfg, ready_q, stop_ev),
                        daemon=True)
        p.start()
        procs.append(p)
    fleet = ServeWorkers(procs, stop_ev, (host, port))
    ready = set()
    import queue as _queue
    while len(ready) < n_workers:
        try:
            wid, _port = ready_q.get(timeout=ready_timeout_s)
        except _queue.Empty:
            fleet.stop()
            raise RuntimeError(
                f"serve workers failed to come up: {sorted(ready)} of "
                f"{n_workers} ready within {ready_timeout_s}s")
        ready.add(wid)
        if fleet.alive() < n_workers:
            fleet.stop()
            raise RuntimeError(
                "a serve worker died during boot — check its stderr")
    return fleet


def start_one_store(n_workers: int, cfg=None, scfg=None,
                    host: str = "127.0.0.1", port: int = 0,
                    nslots: int = 8, slot_rows: int = 512,
                    ready_timeout_s: float = 120.0):
    """Start the round-21 ONE-STORE topology (serving/ipc.py): THIS
    process owns the single KVS + ColumnarFrontend and the owner pump
    thread; ``n_workers`` shm front-end processes shard TCP accepts on
    one SO_REUSEPORT port and feed it over zero-copy columnar rings.
    Counterpart of ``start_serve_workers`` (per-worker PRIVATE stores):
    here the device round stays one program at full lane occupancy and
    only the socket work scales out.  Returns the ``OneStoreServer``
    handle (``.addr``, ``.alive()``, ``.close()``, context manager)."""
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving.ipc import OneStoreServer
    from hermes_tpu.serving.server import ServingConfig

    if n_workers < 1:
        raise ValueError("need at least one shm worker")
    cfg = cfg or HermesConfig(n_replicas=4, n_keys=1 << 10,
                              n_sessions=64, value_words=6)
    scfg = scfg or ServingConfig()
    store = KVS(cfg)
    return OneStoreServer(store, scfg, host=host, port=port,
                          n_workers=n_workers, nslots=nslots,
                          slot_rows=slot_rows,
                          ready_timeout_s=ready_timeout_s)


def _main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--coordinator", type=str, default=None,
                    help="host:port of process 0 (multi-host only)")
    ap.add_argument("--num-hosts", type=int, default=1)
    ap.add_argument("--host-id", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=None,
                    help="default: one per global device")
    ap.add_argument("--fleet-groups", type=int, default=1,
                    help="run a key-sharded FLEET (round-13, hermes_tpu/"
                    "fleet): G groups of --replicas each on the "
                    "(groups, replicas) device grid, one disjoint submesh "
                    "per group; prints one counters dict per group")
    ap.add_argument("--keys", type=int, default=1 << 16)
    ap.add_argument("--sessions", type=int, default=256)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--serve-workers", type=int, default=0,
                    help="instead of a protocol run: start N columnar "
                    "serving worker processes sharding accepts on one "
                    "port (SO_REUSEPORT) and serve until interrupted")
    ap.add_argument("--serve-port", type=int, default=0,
                    help="shared serving port (0 = pick a free one)")
    ap.add_argument("--serve-seconds", type=float, default=0.0,
                    help="serve for this long then exit (0 = until ^C)")
    ap.add_argument("--one-store", action="store_true",
                    help="with --serve-workers: round-21 topology — the "
                    "workers are thin shm front-ends (serving/ipc.py) "
                    "feeding ONE store owned by this process over "
                    "zero-copy columnar rings, instead of each worker "
                    "owning a private store")
    args = ap.parse_args()

    if args.serve_workers > 0 and args.one_store:
        import json
        import time as _time

        from hermes_tpu.config import HermesConfig

        cfg = HermesConfig(n_replicas=args.replicas or 4, n_keys=args.keys,
                           n_sessions=args.sessions, value_words=6)
        srv = start_one_store(args.serve_workers, cfg=cfg,
                              port=args.serve_port)
        print(json.dumps({"serving": list(srv.addr),
                          "workers": args.serve_workers,
                          "one_store": True}), flush=True)
        try:
            if args.serve_seconds > 0:
                _time.sleep(args.serve_seconds)
            else:
                while srv.alive() and srv.pump_error is None:
                    _time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            srv.close()
        return

    if args.serve_workers > 0:
        import json
        import time as _time

        from hermes_tpu.config import HermesConfig

        cfg = HermesConfig(n_replicas=args.replicas or 4, n_keys=args.keys,
                           n_sessions=args.sessions, value_words=6)
        fleet = start_serve_workers(args.serve_workers, cfg=cfg,
                                    port=args.serve_port)
        print(json.dumps({"serving": list(fleet.addr),
                          "workers": args.serve_workers}), flush=True)
        try:
            if args.serve_seconds > 0:
                _time.sleep(args.serve_seconds)
            else:
                while fleet.alive():
                    _time.sleep(0.5)
        except KeyboardInterrupt:
            pass
        finally:
            fleet.stop()
        return

    init_distributed(args.coordinator, args.num_hosts, args.host_id)
    import jax

    from hermes_tpu.config import FleetConfig, HermesConfig

    if args.fleet_groups > 1:
        n = args.replicas or len(jax.devices()) // args.fleet_groups
        fcfg = FleetConfig(
            groups=args.fleet_groups,
            base=HermesConfig(n_replicas=n, n_keys=args.keys,
                              n_sessions=args.sessions,
                              ops_per_session=256, wrap_stream=True))
        rts = run_fleet(fcfg, args.steps)
        for g, rt in enumerate(rts):
            counters = rt.counters()  # collective — every process joins
            if jax.process_index() == 0:
                print({"group": g, **{k: int(v) for k, v in counters.items()
                                      if np.ndim(v) == 0}})
        return

    n = args.replicas or len(jax.devices())
    cfg = HermesConfig(n_replicas=n, n_keys=args.keys, n_sessions=args.sessions,
                       ops_per_session=256, wrap_stream=True)
    rt = run(cfg, args.steps)
    counters = rt.counters()  # collective (allgather) — every process joins
    if jax.process_index() == 0:
        print({k: int(v) for k, v in counters.items()
               if np.ndim(v) == 0})  # scalar counters as a parseable dict


if __name__ == "__main__":
    _main()
