"""Multi-process TCP transport test (SURVEY.md §2 M5): three OS processes,
one replica each, exchanging INV/ACK/VAL over real sockets through the C++
mesh; combined history must linearize and tables must converge."""

import os
import pickle
import subprocess
import sys

import numpy as np
import pytest


@pytest.mark.parametrize("n", [3])
def test_three_process_tcp_run(tmp_path, n):
    steps = 60
    port = 29630
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.pop("XLA_FLAGS", None)

    procs = []
    outs = []
    for r in range(n):
        out = tmp_path / f"rank{r}.pkl"
        outs.append(out)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "hermes_tpu.distributed",
                    "--rank", str(r), "--n-ranks", str(n),
                    "--steps", str(steps), "--base-port", str(port),
                    "--out", str(out),
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    for p in procs:
        stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr.decode()[-2000:]

    from hermes_tpu.distributed import combine_and_check

    verdict, results = combine_and_check(outs)
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])

    # convergence across processes
    for r in results[1:]:
        np.testing.assert_array_equal(results[0]["table_ver"], r["table_ver"])
        np.testing.assert_array_equal(results[0]["table_val"], r["table_val"])
    # every session drained (S_DONE == 4)
    for r in results:
        assert (r["sess_status"] == 4).all()
    total = sum(sum(r["counters"].values()) for r in results)
    assert total == n * 8 * 24  # R * S * G
