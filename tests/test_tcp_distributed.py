"""Multi-process TCP transport tests (SURVEY.md §2 M5): OS processes, one
replica each, exchanging INV/ACK/VAL over real sockets through the C++
mesh; combined history must linearize and tables must converge.  Round-11
extends the surface: CRC-framed wire blocks (corruption detected ->
dropped, never applied), the FaultingTransport interposer composing over
the REAL socket transport, staggered-start dial retry, and loud (not hung)
failure when a peer dies mid-run."""

import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env.pop("XLA_FLAGS", None)
    return env


def _launch(rank, n, steps, port, out, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "hermes_tpu.distributed",
         "--rank", str(rank), "--n-ranks", str(n),
         "--steps", str(steps), "--base-port", str(port),
         "--out", str(out), *extra],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE)


class _StubMesh:
    """Loopback exchanger standing in for the socket mesh: echoes each
    outbound slice back (every peer 'sent' what we sent), with an optional
    byte-flip on selected peer slices — the frame path without sockets."""

    registry = None

    def __init__(self, flip_peers=()):
        self.flip_peers = set(flip_peers)

    def exchange(self, out_slices):
        inb = np.array(out_slices)
        for p in self.flip_peers:
            inb[p, inb.shape[1] // 2] ^= 0xFF
        return inb


def test_tcp_frame_corrupt_drops_without_sockets():
    """Fast sibling of the subprocess runs: a corrupted inbound frame is
    detected by the CRC and downgraded to a ZERO block (never applied),
    counted in corrupt_dropped; clean frames round-trip bit-exact."""
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.core import state as st
    from hermes_tpu.transport.tcp import TcpHostTransport

    cfg = HermesConfig(n_replicas=3, n_keys=32, n_sessions=4, replay_slots=4,
                       ops_per_session=4)
    t = TcpHostTransport(cfg, my_rank=1, n_ranks=3, mesh=_StubMesh())
    out = st.empty_invs(cfg)
    out = out._replace(valid=np.ones_like(np.asarray(out.valid)),
                       key=np.full_like(np.asarray(out.key), 5),
                       alive=np.ones_like(np.asarray(out.alive)))
    inb = t.exchange_inv(out, step=0)
    assert np.asarray(inb.valid).all() and (np.asarray(inb.key) == 5).all()
    assert t.corrupt_dropped == 0

    torn = TcpHostTransport(cfg, my_rank=1, n_ranks=3,
                            mesh=_StubMesh(flip_peers=(0,)))
    inb = torn.exchange_inv(out, step=0)
    assert torn.corrupt_dropped == 1
    assert not np.asarray(inb.valid)[0].any(), "corrupt frame was applied"
    assert not np.asarray(inb.alive)[0]
    assert np.asarray(inb.valid)[2].all()  # the clean peer still lands


@pytest.mark.parametrize("n", [3])
def test_three_process_tcp_run(tmp_path, n):
    steps = 60
    port = 29630

    procs = []
    outs = []
    for r in range(n):
        out = tmp_path / f"rank{r}.pkl"
        outs.append(out)
        procs.append(_launch(r, n, steps, port, out))
    for p in procs:
        stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr.decode()[-2000:]

    from hermes_tpu.distributed import combine_and_check

    verdict, results = combine_and_check(outs)
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])

    # convergence across processes
    for r in results[1:]:
        np.testing.assert_array_equal(results[0]["table_ver"], r["table_ver"])
        np.testing.assert_array_equal(results[0]["table_val"], r["table_val"])
    # every session drained (S_DONE == 4)
    for r in results:
        assert (r["sess_status"] == 4).all()
    total = sum(sum(r["counters"].values()) for r in results)
    assert total == n * 8 * 24  # R * S * G
    # framed wire: no clean-run frame ever failed its CRC
    assert all(r["corrupt_dropped"] == 0 for r in results)


def test_tcp_wire_corruption_end_to_end(tmp_path):
    """The FaultingTransport interposer over the REAL socket transport:
    every rank runs the same seeded corrupt window on edge 0 -> 1; the CRC
    detects each corrupted frame (downgraded to a drop), the protocol
    absorbs the drops, and the combined history still linearizes."""
    n, steps, port = 3, 80, 29660
    faults = "corrupt:0:1:4:16;drop:2:0:6:12"
    procs, outs = [], []
    for r in range(n):
        out = tmp_path / f"rank{r}.pkl"
        outs.append(out)
        procs.append(_launch(r, n, steps, port, out,
                             extra=("--wire-seed", "5",
                                    "--wire-faults", faults)))
    for p in procs:
        _stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr.decode()[-2000:]

    from hermes_tpu.distributed import combine_and_check

    verdict, results = combine_and_check(outs)
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])
    by_rank = {r["rank"]: r for r in results}
    w1 = by_rank[1]["wire"]["counters"]
    assert w1.get("wire_corrupt", 0) > 0, w1
    assert w1.get("wire_corrupt_dropped", 0) == w1["wire_corrupt"], w1
    assert w1.get("wire_corrupt_applied", 0) == 0, w1
    assert by_rank[0]["wire"]["counters"].get("wire_drop", 0) > 0
    # convergence survives the adversary
    for r in results[1:]:
        np.testing.assert_array_equal(results[0]["table_ver"],
                                      r["table_ver"])
        np.testing.assert_array_equal(results[0]["table_val"],
                                      r["table_val"])


def test_tcp_staggered_start_retries_dial(tmp_path):
    """Reconnect-ish behavior of the mesh bring-up: a rank that starts
    EARLY retry-dials its missing peers (~60s budget) instead of failing,
    so a staggered launch still forms the full mesh and completes."""
    n, steps, port = 3, 20, 29690
    outs = [tmp_path / f"rank{r}.pkl" for r in range(n)]
    procs = [_launch(0, n, steps, port, outs[0])]
    time.sleep(2.0)  # rank 0 is already dialing into nothing
    for r in (1, 2):
        procs.append(_launch(r, n, steps, port, outs[r]))
    for p in procs:
        _stdout, stderr = p.communicate(timeout=300)
        assert p.returncode == 0, stderr.decode()[-2000:]
    from hermes_tpu.distributed import combine_and_check

    verdict, _results = combine_and_check(outs)
    assert verdict.ok


def test_tcp_peer_death_fails_loudly_not_hang(tmp_path):
    """Half-open / dead-peer handling: when a peer exits mid-run, the
    survivors' exchange must fail LOUDLY (bounded wait, clear error) —
    never hang the mesh forever on a closed or silent socket."""
    n, port = 3, 29720
    outs = [tmp_path / f"rank{r}.pkl" for r in range(n)]
    # rank 2 runs far fewer steps: it finishes, closes its sockets, and
    # leaves ranks 0/1 mid-exchange against a dead peer
    procs = [_launch(0, n, 400, port, outs[0]),
             _launch(1, n, 400, port, outs[1]),
             _launch(2, n, 5, port, outs[2])]
    t0 = time.monotonic()
    rcs, errs = [], []
    for p in procs[:2]:
        _stdout, stderr = p.communicate(timeout=240)
        rcs.append(p.returncode)
        errs.append(stderr.decode()[-2000:])
    procs[2].communicate(timeout=60)
    elapsed = time.monotonic() - t0
    assert all(rc != 0 for rc in rcs), (rcs, errs)
    assert any("tcp exchange failed" in e for e in errs), errs
    # bounded: the recv deadline is 60s; a FIN-closed peer fails fast
    assert elapsed < 200, f"survivors took {elapsed:.0f}s to notice"
