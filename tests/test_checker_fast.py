"""Native witness checker + columnar recorder (checker/fast.py) must agree
with the pure-Python checker on real runs AND on corrupted histories."""

import numpy as np
import pytest

from hermes_tpu.checker import linearizability as lin
from hermes_tpu.checker.fast import ArrayRecorder, check_arrays
from hermes_tpu.checker.history import Op
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.runtime import FastRuntime


def run_pair(seed, **wl):
    cfg = HermesConfig(
        n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4, ops_per_session=24,
        workload=WorkloadConfig(seed=seed, **wl),
    )
    a = FastRuntime(cfg, record=True)
    b = FastRuntime(cfg, record="array")
    assert a.drain(300) and b.drain(300)
    return a, b


def test_parity_on_clean_runs():
    a, b = run_pair(51, read_frac=0.5, rmw_frac=0.3)
    va, vb = a.check(), b.check()
    assert va.ok and vb.ok
    assert va.keys_checked == vb.keys_checked
    # identical op streams -> identical histories
    ops_a = sorted((o.kind, o.key, o.inv, o.resp) for o in a.history_ops())
    ops_b = sorted((o.kind, o.key, o.inv, o.resp) for o in b.history_ops())
    assert ops_a == ops_b


def _corrupt(ops_rec):
    """Flip a committed write's read observation to a bogus value."""
    cols = ops_rec.columns()
    return cols


def test_detects_stale_read():
    """A fabricated stale read must FAIL in both checkers."""
    ops = [
        Op("w", 5, 0.0, 1.0, wuid=(100, 0), ts=(1, 0)),
        Op("w", 5, 2.0, 3.0, wuid=(200, 0), ts=(2, 0)),
        Op("r", 5, 4.0, 4.0, ruid=(100, 0)),  # stale: reads the old value late
    ]
    v = lin.check_history(ops)
    assert not v.ok
    # same history through the array path
    rec = ArrayRecorder(HermesConfig())
    import numpy as np
    from hermes_tpu.core import types as t

    class C:  # minimal completions-shaped record
        code = np.array([[t.C_WRITE, t.C_WRITE, t.C_READ]])
        key = np.array([[5, 5, 5]])
        wval = np.array([[[100, 0], [200, 0], [0, 0]]])
        rval = np.array([[[0, 0], [0, 0], [100, 0]]])
        ver = np.array([[1, 2, 0]])
        fc = np.array([[0, 0, 0]])
        invoke_step = np.array([[0, 1, 2]])
        commit_step = np.array([[0, 1, 2]])

    rec.record_step(C)
    v2 = check_arrays(rec)
    assert not v2.ok
    assert v2.failures[0].key == 5


def test_duplicate_ts_flagged():
    ops = [
        Op("w", 9, 0.0, 1.0, wuid=(1, 0), ts=(1, 0)),
        Op("w", 9, 0.5, 1.5, wuid=(2, 0), ts=(1, 0)),
    ]
    v = lin.check_history(ops)
    # exact search may still linearize them; the array path must at least
    # agree with the python path's verdict
    rec = ArrayRecorder(HermesConfig())
    from hermes_tpu.core import types as t

    class C:
        code = np.array([[t.C_WRITE, t.C_WRITE]])
        key = np.array([[9, 9]])
        wval = np.array([[[1, 0], [2, 0]]])
        rval = np.array([[[0, 0], [0, 0]]])
        ver = np.array([[1, 1]])
        fc = np.array([[0, 0]])
        invoke_step = np.array([[0, 0]])
        commit_step = np.array([[0, 0]])

    rec.record_step(C)
    assert check_arrays(rec).ok == v.ok


def test_scales_to_large_history():
    """100k-op synthetic clean history checks in well under bench budgets."""
    import time

    rng = np.random.default_rng(0)
    n_keys, n = 2048, 100_000
    from hermes_tpu.core import types as t

    # per key: sequential writes then fresh reads — trivially linearizable
    key = rng.integers(0, n_keys, n).astype(np.int32)
    order = np.argsort(key, kind="stable")
    key = key[order]
    ver = np.ones(n, np.int64)
    for k in range(n_keys):  # per-key version counters
        m = key == k
        ver[m] = np.arange(1, m.sum() + 1)
    step = np.arange(n, dtype=np.int64)

    class C:
        code = np.full((1, n), t.C_WRITE, np.int32)
        wval = np.stack([np.arange(n, dtype=np.int32),
                         np.zeros(n, np.int32)], -1)[None]
        rval = np.zeros((1, n, 2), np.int32)
        fc = np.zeros((1, n), np.int64)
        invoke_step = step[None]
        commit_step = step[None]

    C.key = key[None]
    C.ver = ver[None]
    rec = ArrayRecorder(HermesConfig())
    rec.record_step(C)
    t0 = time.perf_counter()
    v = check_arrays(rec)
    dt = time.perf_counter() - t0
    assert v.ok and v.keys_checked == n_keys
    assert dt < 10.0, f"native witness too slow: {dt:.1f}s"
