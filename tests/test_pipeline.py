"""Round-8 serving pipeline: donated state, device-resident control, async
completion harvest (runtime.FastRuntime), and the overlapped KVS client
layer (kvs.KVS at cfg.pipeline_depth >= 2).

The invariants under test:
  * pipelined <-> synchronous STATE IDENTITY: the harvest ring only
    re-schedules the completion readback, so the same stream produces
    byte-identical state trees and Meta counters, and the recorder sees
    the same history (checker-gated) — both engines;
  * donation is LOUD: a superseded reference to the state tree raises on
    use (and donate_state=False restores the copying program);
  * control rows are cached on device: the ctl_upload trace event fires
    once per membership/fault transition, never per round;
  * a membership change between pipelined dispatches lands in the very
    next round's ctl (freeze-at-k identity with the sync engine);
  * rebase-mid-pipeline re-anchors in-flight completions with the
    pre-rebase version era (checker stays green).
"""

import dataclasses

import jax
import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import types as t
from hermes_tpu.kvs import KVS
from hermes_tpu.obs import Observability
from hermes_tpu.runtime import FastRuntime, Runtime

from helpers import get, tiny_cfg


def _mix_cfg(**kw):
    base = dict(
        n_replicas=3, n_keys=64, n_sessions=6, replay_slots=2,
        ops_per_session=12,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.3, seed=11),
    )
    base.update(kw)
    return HermesConfig(**base)


def _assert_state_equal(a: FastRuntime, b: FastRuntime) -> None:
    """Byte-identical state trees + Meta counters."""
    la = jax.tree.leaves(jax.device_get(a.fs))
    lb = jax.tree.leaves(jax.device_get(b.fs))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# pipelined <-> sync state identity
# --------------------------------------------------------------------------


@pytest.mark.parametrize("depth", [2, 4])
def test_pipelined_matches_sync_batched(depth):
    cfg = _mix_cfg()
    a = FastRuntime(cfg, record=True)
    b = FastRuntime(dataclasses.replace(cfg, pipeline_depth=depth),
                    record=True)
    assert a.drain(400)
    assert b.drain(400)
    _assert_state_equal(a, b)
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort", "lat_sum", "lat_cnt"):
        assert ca[k] == cb[k], k
    np.testing.assert_array_equal(ca["lat_hist"], cb["lat_hist"])
    # the ring preserved round order, so the recorded histories check clean
    assert a.check().ok
    assert b.check().ok


def test_pipelined_matches_sync_sharded():
    cfg = HermesConfig(
        n_replicas=8, n_keys=64, n_sessions=4, replay_slots=2,
        ops_per_session=8,
        workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.3, seed=37),
    )
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = FastRuntime(cfg, backend="sharded", mesh=mesh)
    b = FastRuntime(dataclasses.replace(cfg, pipeline_depth=3),
                    backend="sharded", mesh=mesh)
    assert a.drain(300)
    assert b.drain(300)
    _assert_state_equal(a, b)
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k


def test_step_once_returns_lagged_rounds_in_order():
    """Depth d: step_once returns None while the ring fills, then round
    k - (d-1)'s completions — strictly in round order."""
    cfg = tiny_cfg(ops_per_session=16)
    rt = FastRuntime(dataclasses.replace(cfg, pipeline_depth=3))
    assert rt.step_once() is None
    assert rt.step_once() is None
    seen = []
    for _ in range(6):
        comp = rt.step_once()
        assert comp is not None
        seen.append(int(np.asarray(comp.commit_step).max()))
    # commit_step of round k's completions never exceeds k; the harvested
    # sequence must be non-decreasing (round order)
    assert seen == sorted(seen)
    assert rt.flush_pipeline() == 2  # the two ring rounds drain at the end


# --------------------------------------------------------------------------
# donated state
# --------------------------------------------------------------------------


def test_donation_stale_reference_raises():
    rt = FastRuntime(tiny_cfg())
    old = rt.fs
    rt.step_once()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.device_get(old.table.vpts))


def test_donation_off_keeps_old_reference_readable():
    rt = FastRuntime(tiny_cfg(donate_state=False))
    old = rt.fs
    rt.step_once()
    v = np.asarray(jax.device_get(old.table.vpts))
    assert v.shape[0] == rt.cfg.n_keys


def test_donated_sharded_runs_and_checks():
    from jax.sharding import Mesh

    cfg = HermesConfig(
        n_replicas=8, n_keys=64, n_sessions=4, replay_slots=2,
        ops_per_session=6,
        workload=WorkloadConfig(read_frac=0.5, seed=5),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    rt = FastRuntime(cfg, backend="sharded", mesh=mesh)
    old = rt.fs
    assert rt.drain(300)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.device_get(old.sess.status))


# --------------------------------------------------------------------------
# device-resident control
# --------------------------------------------------------------------------


def _ctl_uploads(obs) -> int:
    return sum(1 for r in obs.records
               if r.get("kind") == "event" and r.get("name") == "ctl_upload")


@pytest.mark.parametrize("runtime_cls", [FastRuntime, Runtime])
def test_ctl_uploaded_once_until_dirtied(runtime_cls):
    """Satellite regression: _ctl() must NOT re-upload epoch/live/frozen
    every round — one upload at first use, one per membership/fault
    transition (counted via the obs trace hook)."""
    rt = runtime_cls(tiny_cfg(ops_per_session=16))
    obs = rt.attach_obs(Observability())
    rt.run(6)
    assert _ctl_uploads(obs) == 1
    rt.freeze(1)
    rt.run(4)
    assert _ctl_uploads(obs) == 2
    rt.thaw(1)
    rt.run(4)
    assert _ctl_uploads(obs) == 3
    rt.run(10)
    assert _ctl_uploads(obs) == 3  # steady state: zero per-round uploads


def test_device_step_counter_tracks_host():
    rt = FastRuntime(tiny_cfg())
    rt.run(5)
    assert int(jax.device_get(rt._step_dev)) == rt.step_idx == 5
    rt.step_idx = 17  # snapshot-restore path re-seeds the device scalar
    assert int(jax.device_get(rt._step_dev)) == 17


def test_membership_change_mid_pipeline_lands_next_round():
    """A freeze between pipelined dispatches must be visible to the very
    next dispatched round (the dirty ctl re-uploads before round k+1), so
    the pipelined run is byte-identical to a sync run with the same fault
    schedule."""
    cfg = _mix_cfg(n_replicas=3)

    def drive(depth):
        rt = FastRuntime(dataclasses.replace(cfg, pipeline_depth=depth))
        rt.run(4)
        rt.freeze(2)
        rt.run(8)
        rt.thaw(2)
        assert rt.drain(400)
        return rt

    a, b = drive(1), drive(3)
    _assert_state_equal(a, b)
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k


def test_pending_sessions_probe_semantics():
    """The drain poll's one-scalar reduction: frozen / non-live replicas
    are excluded exactly like the old host-side predicate."""
    status = np.full((3, 4), t.S_DONE, np.int32)
    status[1, 2] = t.S_INFL
    live = np.full((3,), 0b111, np.int32)
    frozen = np.zeros((3,), bool)
    n = int(jax.device_get(fst.pending_sessions(status, live, frozen)))
    assert n == 1
    frozen[1] = True
    assert int(jax.device_get(fst.pending_sessions(status, live, frozen))) == 0
    frozen[1] = False
    live[:] = 0b101  # replica 1 not live
    assert int(jax.device_get(fst.pending_sessions(status, live, frozen))) == 0


# --------------------------------------------------------------------------
# obs: overlap counters + pipeline gauge
# --------------------------------------------------------------------------


def test_overlap_counters_and_depth_gauge():
    rt = FastRuntime(tiny_cfg(pipeline_depth=3, ops_per_session=16))
    obs = rt.attach_obs(Observability())
    rt.run(8)
    reg = obs.registry
    assert "host_work_s" in reg and "device_wait_s" in reg
    assert reg.counter("host_work_s").value > 0
    assert reg.counter("device_wait_s").value > 0
    # steady state: the ring holds depth-1 in-flight rounds after harvest
    assert reg.gauge("pipeline_depth").value == 2


# --------------------------------------------------------------------------
# pipelined KVS (checker-gated) + rebase interplay
# --------------------------------------------------------------------------


def test_kvs_pipelined_depth2_checked():
    cfg = HermesConfig(n_replicas=3, n_keys=128, value_words=6, n_sessions=8,
                       replay_slots=2, ops_per_session=1, pipeline_depth=2)
    kvs = KVS(cfg, record=True)
    puts = [kvs.put(i % 3, (i // 3) % 8, i % 13, [i, i + 1, 7, 9])
            for i in range(30)]
    assert kvs.run_until(puts, 300)
    gets = [kvs.get((i + 1) % 3, i % 8, i % 13) for i in range(15)]
    rmws = [kvs.rmw(i % 3, (i + 3) % 8, i % 13, [50 + i, 0, 0, 0])
            for i in range(8)]
    assert kvs.run_until(gets + rmws, 300)
    for f in gets:
        assert f.result().value is not None
    for f in rmws:
        assert f.result().kind in ("rmw", "rmw_abort")
    assert kvs.rt.check().ok
    c = kvs.counters()
    assert (int(c["n_read"]), int(c["n_write"]), int(c["n_rmw"])) \
        == (15, 30, 8 - int(c["n_abort"]))


def test_kvs_pipelined_batch_path_matches_sync_totals():
    def drive(depth):
        cfg = HermesConfig(n_replicas=3, n_keys=256, value_words=6,
                           n_sessions=16, replay_slots=2, ops_per_session=1,
                           pipeline_depth=depth)
        kvs = KVS(cfg, record=True)
        rng = np.random.default_rng(7)
        n = 200
        kinds = rng.choice([KVS.GET, KVS.PUT, KVS.RMW], size=n,
                           p=[0.4, 0.4, 0.2]).astype(np.int32)
        keys = rng.integers(0, 40, size=n)
        values = np.stack([np.arange(4, dtype=np.int32) + i
                           for i in range(n)])
        bf = kvs.submit_batch(kinds, keys, values)
        assert kvs.run_batch(bf, 600)
        assert kvs.rt.check().ok
        c = kvs.counters()
        return {k: int(c[k]) for k in ("n_read", "n_write", "n_rmw",
                                       "n_abort")}

    c1, c2 = drive(1), drive(2)
    # the pipelined client staggers injection by one round, so CONTENTION
    # outcomes may differ (an RMW that lost a race in one schedule commits
    # in the other) — but every op resolves exactly once: reads/writes
    # match, and rmw commits + aborts conserve the submitted RMW count
    assert (c1["n_read"], c1["n_write"]) == (c2["n_read"], c2["n_write"])
    assert c1["n_rmw"] + c1["n_abort"] == c2["n_rmw"] + c2["n_abort"]


def test_rebase_mid_pipeline_reanchors_ring_completions():
    """Force a version rebase while the harvest ring holds in-flight
    rounds: the ring must flush BEFORE the delta accumulates, or those
    completions would be re-anchored with the post-rebase base and the
    checker's witness order would corrupt."""
    cfg = _mix_cfg(n_keys=16, n_sessions=4, ops_per_session=20,
                   pipeline_depth=3)
    rt = FastRuntime(cfg, record=True)
    rt.run(6)  # ring is full (2 in-flight rounds)
    assert len(rt._ring) == 2
    rebased = rt.rebase_versions()
    assert rebased >= 0  # pass is best-effort; flush must have happened
    assert len(rt._ring) == 0
    assert rt.drain(400)
    assert rt.check().ok


def test_snapshot_load_drains_inflight_ring(tmp_path):
    """A restore over a pipelined runtime must drain the harvest ring
    first — otherwise pre-restore completions would be harvested after
    the restore and re-anchored into the restored history."""
    from hermes_tpu import snapshot

    path = str(tmp_path / "snap.npz")
    rt = FastRuntime(_mix_cfg(ops_per_session=24, pipeline_depth=3))
    rt.run(4)
    snapshot.save(path, rt)  # save itself flushes
    assert len(rt._ring) == 0
    rt.run(6)
    assert len(rt._ring) == 2
    snapshot.load(path, rt)
    assert len(rt._ring) == 0
    assert rt.drain(400)


def test_acceptance_configs_pass_pipelined():
    """Acceptance scenarios through the pipelined serving loop (depth 2):
    fault injection (4) and membership reconfiguration (5) land their
    transitions in the dirty ctl between dispatches."""
    from hermes_tpu import acceptance

    for n in (1, 4, 5):
        counters, verdict = acceptance.run_config(
            n, scale=0.004, pipeline_depth=2)
        assert counters["drained"], (n, counters)
        assert verdict is not None and verdict.ok, (n, verdict)


# --------------------------------------------------------------------------
# config validation
# --------------------------------------------------------------------------


def test_pipeline_depth_validation():
    with pytest.raises(ValueError, match="pipeline_depth"):
        HermesConfig(pipeline_depth=0)
    with pytest.raises(ValueError, match="pipeline_depth"):
        HermesConfig(pipeline_depth=65)
