"""Round-11 adversarial wire chaos: the transport-generic fault interposer
(chaos/net.py), CRC-checksummed frames (transport/codec.py), partition
tolerance through the detector, and the KVS's bounded-retry / degraded-mode
client answers — each contract unit-tested here, soak-gated by
scripts/check_netchaos.py."""

import numpy as np
import pytest

from hermes_tpu import chaos
from hermes_tpu.chaos.net import FaultingTransport
from hermes_tpu.checker import linearizability as lin
from hermes_tpu.checker.history import Op
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st
from hermes_tpu.kvs import KVS, StuckOpError
from hermes_tpu.membership import MembershipService
from hermes_tpu.runtime import FastRuntime, Runtime
from hermes_tpu.transport import codec
from hermes_tpu.transport.base import LockstepHostTransport
from hermes_tpu.transport.sim import SimTransport


# -- frame codec (CRC layer) -------------------------------------------------


def test_frame_roundtrip_and_red():
    payload = np.arange(300, dtype=np.uint8)
    frame = codec.frame_pack(payload)
    assert frame.nbytes == payload.nbytes + codec.FRAME_OVERHEAD
    np.testing.assert_array_equal(codec.frame_unpack(frame), payload)
    # single flipped payload bit -> rejected
    torn = frame.copy()
    torn[codec.FRAME_OVERHEAD + 123] ^= 0x01
    with pytest.raises(codec.FrameCorrupt, match="checksum"):
        codec.frame_unpack(torn)
    # header damage -> rejected
    bad_magic = frame.copy()
    bad_magic[0] ^= 0xFF
    with pytest.raises(codec.FrameCorrupt, match="magic"):
        codec.frame_unpack(bad_magic)
    # truncation -> rejected (both below-header and mid-payload)
    with pytest.raises(codec.FrameCorrupt, match="truncated"):
        codec.frame_unpack(frame[:4])
    with pytest.raises(codec.FrameCorrupt, match="length"):
        codec.frame_unpack(frame[:-10])


# -- the interposer, pair by pair -------------------------------------------


def _cfg_sim(**kw):
    base = dict(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=8,
        ops_per_session=10, replay_age=5,
        workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.3, seed=5),
    )
    base.update(kw)
    return HermesConfig(**base)


def _inv_block(cfg, key=7):
    out = st.empty_invs(cfg, lead=(cfg.n_replicas,))
    return out._replace(
        valid=np.ones_like(np.asarray(out.valid)),
        key=np.full_like(np.asarray(out.key), key),
        alive=np.ones_like(np.asarray(out.alive)))


def test_wire_drop_and_partition_are_directed():
    cfg = _cfg_sim()
    wire = FaultingTransport(LockstepHostTransport(), 3, seed=1)
    wire.add("drop", 0, 2, 0, 10)          # 0 -> 2 dark
    wire.add("partition", 1, -1, 0, 10)    # 1's whole outbound dark
    inb = wire.exchange_inv(_inv_block(cfg), step=0)
    valid = np.asarray(inb.valid)
    alive = np.asarray(inb.alive)
    assert not valid[2, 0].any() and not alive[2, 0]      # dropped edge
    assert valid[1, 0].any()                              # 0 -> 1 fine
    for dst in (0, 2):
        assert not valid[dst, 1].any(), "partitioned src leaked outbound"
    assert valid[0, 1].sum() == 0 and valid[1, 2].any()   # asymmetric: 1
    assert alive[1, 2] and alive[0, 2]                    # still HEARS peers


def test_wire_delay_holds_and_redelivers():
    cfg = _cfg_sim()
    wire = FaultingTransport(LockstepHostTransport(), 3, seed=1)
    wire.add("delay", 0, 1, 0, 1, param=3)  # only step 0's frame delayed
    blk = _inv_block(cfg, key=9)
    inb0 = wire.exchange_inv(blk, step=0)
    assert not np.asarray(inb0.valid)[1, 0].any(), "delayed frame arrived"
    assert wire.pending() == 1
    # nothing new sent on the edge: deliver an EMPTY outbound at step 3
    empty = st.empty_invs(cfg, lead=(3,))
    inb3 = wire.exchange_val(empty, step=3)  # different kind: still held
    assert not np.asarray(inb3.valid)[1, 0].any()
    inb_due = wire.exchange_inv(empty, step=3)
    assert np.asarray(inb_due.valid)[1, 0].any(), "held frame not delivered"
    assert (np.asarray(inb_due.key)[1, 0][np.asarray(inb_due.valid)[1, 0]]
            == 9).all()
    assert wire.pending() == 0


def test_wire_dup_composes_and_corrupt_crc_modes():
    cfg = _cfg_sim()
    wire = FaultingTransport(LockstepHostTransport(), 3, seed=2)
    wire.add("dup", 0, 1, 0, 4)
    wire.exchange_inv(_inv_block(cfg), step=0)
    assert wire.counters["wire_dup"] == 1 and wire.pending() >= 1
    # crc=True: corrupt detected -> drop; the pair block arrives ZEROED
    for crc, applied in ((True, 0), (False, 1)):
        w = FaultingTransport(LockstepHostTransport(), 3, seed=3, crc=crc)
        w.add("corrupt", 0, 1, 0, 4)
        inb = w.exchange_inv(_inv_block(cfg), step=0)
        if crc:
            assert not np.asarray(inb.valid)[1, 0].any()
        assert w.counters.get("wire_corrupt_applied", 0) == (applied and 1)
        assert w.counters.get("wire_corrupt_dropped", 0) == (not applied and 1)


def test_sim_engine_wire_matrix_checked():
    """Composed drop/delay/dup/reorder/corrupt on the sim engine: the run
    drains and the history linearizes — corruption is detected (CRC) and
    downgraded to drops the protocol already tolerates."""
    cfg = _cfg_sim()
    wire = FaultingTransport(SimTransport(3), 3, seed=7)
    wire.add("drop", 0, 2, 2, 12)
    wire.add("delay", 1, -1, 4, 16, param=2)
    wire.add("dup", 2, -1, 6, 14)
    wire.add("reorder", 0, 1, 3, 18, param=3)
    wire.add("corrupt", 2, 0, 5, 15)
    rt = Runtime(cfg, backend="sim", record=True, transport=wire)
    assert rt.drain(400), "did not drain"
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    c = wire.counters
    for op in ("drop", "delay", "dup", "reorder", "corrupt"):
        assert c.get(f"wire_{op}", 0) > 0, dict(c)
    assert c["wire_corrupt_dropped"] == c["wire_corrupt"]
    assert c.get("wire_corrupt_applied", 0) == 0


def test_partition_heal_cycle_sim_engine():
    """A partitioned-but-alive replica is ejected by the detector (epoch
    bump, fenced), keeps its state, and rejoins on heal through the
    epoch-fenced state-transfer join — no committed write is lost."""
    cfg = _cfg_sim(n_replicas=4, n_sessions=4, ops_per_session=12,
                   lease_steps=5)
    wire = FaultingTransport(SimTransport(4), 4, seed=5)
    rt = Runtime(cfg, backend="sim", record=True, transport=wire)
    rt.attach_membership(MembershipService(cfg, confirm_steps=2))
    sched = chaos.Schedule.parse("@5 partition 2 until=30\n@34 heal\n")
    runner = chaos.ChaosRunner(rt, sched, wire=wire)
    res = runner.run(60, check=True)
    assert res["drained"] and res["checked_ok"], res
    kinds = [(e.kind, e.replica) for e in rt.membership.events]
    assert ("remove", 2) in kinds and ("join", 2) in kinds, kinds


# -- schedule verbs + runner refusal ----------------------------------------


def test_schedule_new_verbs_roundtrip():
    text = ("@4 netdrop 0 dst=2 until=24\n"
            "@6 netreorder 1 skew=3 until=30\n"
            "@8 netcorrupt 1 dst=3 until=28\n"
            "@10 partition 2 until=40\n"
            "@44 heal\n")
    sched = chaos.Schedule.parse(text)
    assert len(sched) == 5
    again = chaos.Schedule.parse(sched.format())
    assert again.events == sched.events


def test_random_schedule_draws_wire_and_partition_verbs():
    cfg = _cfg_sim()
    spec = chaos.ChaosSpec(p_freeze=0, p_thaw=0, p_join=0, p_crash=0,
                           p_skew=0, p_wire=0.5, p_partition=0.2)
    sched = chaos.Schedule.random(cfg, seed=3, steps=200, spec=spec)
    kinds = {e.kind for e in sched}
    assert "partition" in kinds
    assert kinds & set(chaos.schedule.WIRE_EVENTS), kinds
    # deterministic draw
    again = chaos.Schedule.random(cfg, seed=3, steps=200, spec=spec)
    assert again.events == sched.events


def test_runner_refuses_net_faults_without_interposer():
    """Satellite red test: net-fault schedule lines on a transport with no
    interposer attached fail AT CONSTRUCTION with an error naming the
    transport class (previously: silently skipped, or failed late)."""
    cfg = _cfg_sim()
    sched = chaos.Schedule.parse("@4 netdrop 0 dst=2 until=24\n")
    rt = Runtime(cfg, backend="sim", transport=SimTransport(3))
    with pytest.raises(ValueError, match="SimTransport.*FaultingTransport"):
        chaos.ChaosRunner(rt, sched)
    # legacy net_* verbs: same early refusal when neither carrier exists
    legacy = chaos.Schedule.parse("@4 net_drop 0 dst=2 until=24\n")
    with pytest.raises(ValueError, match="SimTransport"):
        chaos.ChaosRunner(Runtime(cfg, backend="sim",
                                  transport=SimTransport(3)), legacy)
    # fast engine: no host transport at all — the error still names it
    fcfg = _cfg_sim(n_replicas=3)
    frt = FastRuntime(fcfg)
    with pytest.raises(ValueError, match="FastRuntime.*FaultingTransport"):
        chaos.ChaosRunner(frt, sched)
    # partition on a fast engine needs the detector oracle
    psched = chaos.Schedule.parse("@4 partition 1 until=20\n")
    with pytest.raises(ValueError, match="MembershipService"):
        chaos.ChaosRunner(frt, psched)
    # ... and is accepted once one is attached
    frt.attach_membership(MembershipService(fcfg))
    chaos.ChaosRunner(frt, psched)


def test_legacy_net_verbs_route_to_interposer():
    """net_drop/net_delay/net_dup fall back to the FaultingTransport when
    only it is attached — the same fault, one layer up."""
    cfg = _cfg_sim()
    wire = FaultingTransport(SimTransport(3), 3, seed=4)
    rt = Runtime(cfg, backend="sim", record=True, transport=wire)
    sched = chaos.Schedule.parse("@2 net_drop 0 dst=2 until=12\n")
    runner = chaos.ChaosRunner(rt, sched, wire=wire)
    res = runner.run(30, check=True)
    assert res["drained"] and res["checked_ok"]
    assert wire.counters["wire_drop"] > 0


# -- membership partition oracle (fast engines) ------------------------------


def test_sever_min_over_observers_protects_healthy_replica():
    """One severed observer edge must NOT eject a replica the rest of the
    cluster hears fine (the min-over-observers rule) — only severing the
    replica's whole outbound side starves every observer."""
    cfg = _cfg_sim(n_replicas=4, lease_steps=4)
    rt = FastRuntime(cfg, record=True)
    svc = MembershipService(cfg, confirm_steps=1)
    rt.attach_membership(svc)
    svc.sever(2, 0, at_step=0)  # only observer 0 stops hearing replica 2
    rt.run(20)
    assert not any(e.kind == "remove" for e in svc.events), svc.events
    svc.sever(2, -1, at_step=rt.step_idx)  # now EVERY observer starves
    rt.run(20)
    removed = [e.replica for e in svc.events if e.kind == "remove"]
    assert removed == [2], svc.events
    # heal + rejoin: partitioned replica kept its state, joins epoch-fenced
    svc.heal_partitions()
    rt.join(2, from_replica=0)
    rt.run(4)
    assert rt.drain(400)
    assert rt.check().ok


# -- KVS: bounded retry, degraded mode, diagnostics --------------------------


def _kvs_cfg(**kw):
    base = dict(
        n_replicas=5, n_keys=64, n_sessions=4, replay_slots=6,
        value_words=4, ops_per_session=1, lease_steps=5,
        pipeline_depth=2, op_timeout_rounds=6, op_retry_limit=2,
        rebroadcast_every=2, replay_scan_every=4,
        workload=WorkloadConfig(seed=9))
    base.update(kw)
    return HermesConfig(**base)


def test_kvs_retry_reroutes_ops_wedged_by_partition():
    """An op wedged on a partition-ejected (fenced) coordinator is salvaged
    (maybe_w fold + volatile wipe — the crash model, per slot) and
    transparently re-submitted on a healthy replica: the ORIGINAL future
    resolves, the history still linearizes, and no committed write is
    reported lost."""
    cfg = _kvs_cfg()
    kvs = KVS(cfg, record=True)
    svc = MembershipService(cfg, confirm_steps=2)
    kvs.rt.attach_membership(svc)
    sched = chaos.Schedule.parse("@4 partition 1 until=60\n@62 heal\n")
    runner = chaos.ChaosRunner(kvs, sched)
    futs = []

    def on_step(step):
        if step % 3 == 0 and step < 55:
            futs.append(kvs.put((step // 3) % 5, (step // 15) % 4,
                                (7 * step) % 64, [step + 1]))

    runner.on_step = on_step
    res = runner.run(110, check=True)
    assert res["drained"] and res["checked_ok"], res
    assert all(f.done() for f in futs), "futures stranded by the adversary"
    assert kvs.retried_ops > 0
    assert ("remove", 1) in [(e.kind, e.replica) for e in svc.events]
    committed = [f.result().uid for f in futs if f.result().kind == "put"]
    assert committed, "no writes committed under the adversary"
    lost = lin.committed_write_lost(committed, kvs.rt.history_ops(),
                                    kvs.rt.recorder.aborted_uids)
    assert not lost, lost
    # the stuck-op diagnostics carried the adversary window (satellite 3)
    assert kvs.stuck_ops and "net" in kvs.stuck_ops[0], kvs.stuck_ops[:1]
    assert "partition:1->-1" in kvs.stuck_ops[0]["net"]["windows"][0]


def test_kvs_retry_exhaustion_resolves_lost():
    """With no healthy replica to re-route to, retries exhaust and the
    future resolves loudly as kind='lost' — never a silent hang."""
    cfg = _kvs_cfg(n_replicas=3, op_retry_limit=1, op_timeout_rounds=4)
    kvs = KVS(cfg, record=True)
    # fence the WHOLE cluster first: remove the coordinator, freeze the
    # rest — the op wedges at injection and has nowhere to be re-routed
    kvs.rt.remove(2)
    kvs.rt.freeze(0)
    kvs.rt.freeze(1)
    fut = kvs.put(2, 0, 5, [1])
    for _ in range(30):
        if fut.done():
            break
        kvs.step()
    assert fut.done(), "wedged future never resolved"
    assert fut.result().kind == "lost"


def test_kvs_backoff_never_retries_healthy_coordinator():
    """A stuck op whose coordinator is HEALTHY (its quorum is what's
    frozen) is re-examined with backoff but never salvaged — blind retry
    would double-write; once the quorum thaws the op completes normally."""
    cfg = _kvs_cfg(n_replicas=3, op_timeout_rounds=4, op_retry_limit=3,
                   lease_steps=100)  # detector-less: freezes stay
    kvs = KVS(cfg, record=True)
    kvs.rt.freeze(1)
    kvs.rt.freeze(2)
    fut = kvs.put(0, 0, 9, [3])
    for _ in range(20):
        kvs.step()
    assert not fut.done() and kvs.retried_ops == 0
    assert kvs.stuck_ops, "watchdog silent on a wedged op"
    kvs.rt.thaw(1)
    kvs.rt.thaw(2)
    assert kvs.run_until([fut], 200)
    assert fut.result().kind == "put"
    assert kvs.retried_ops == 0
    v = kvs.rt.check()
    assert v.ok


def test_kvs_degraded_mode_sheds_writes_loudly():
    cfg = _kvs_cfg(n_replicas=3, pipeline_depth=1, op_timeout_rounds=0,
                   op_retry_limit=0, min_healthy_for_writes=2)
    kvs = KVS(cfg)
    kvs.rt.freeze(1)
    kvs.rt.freeze(2)
    f_put = kvs.put(0, 0, 1, [5])
    f_get = kvs.get(0, 0, 1)
    assert f_put.done() and f_put.result().kind == "rejected"
    assert kvs.shed_writes == 1
    assert not f_get.done()  # reads are not shed
    # batch path sheds too
    bf = kvs.submit_batch(np.array([KVS.PUT, KVS.GET]), np.array([2, 2]),
                          np.array([[7, 7]]).repeat(2, axis=0))
    assert bf.code[0] == -3 and bf.code[1] == 0  # C_REJECTED / pending get
    # healing clears degraded mode; writes flow again
    kvs.rt.thaw(1)
    kvs.rt.thaw(2)
    f2 = kvs.put(0, 1, 1, [6])
    assert kvs.run_until([f_get, f2], 300)
    assert f2.result().kind == "put"


def test_stuck_op_error_carries_net_window():
    cfg = _kvs_cfg(n_replicas=3, pipeline_depth=1, op_timeout_rounds=3,
                   op_retry_limit=0, lease_steps=100)
    kvs = KVS(cfg, strict_timeouts=True)
    kvs.net_phase = dict(windows=["partition:1->-1@40"])
    kvs.rt.freeze(1)
    kvs.rt.freeze(2)
    kvs.put(0, 0, 2, [1])
    with pytest.raises(StuckOpError, match="partition:1->-1"):
        for _ in range(10):
            kvs.step()


def test_frame_unsupported_algo_fails_loudly():
    """A receiver must never verify with the WRONG polynomial: an algo
    this end cannot compute is a named FrameCorrupt, not a silent zlib
    fallback that drops 100% of a better-equipped sender's frames."""
    if codec._crc32c is None:
        with pytest.raises(codec.FrameCorrupt, match="crc32c"):
            codec.wire_crc(b"abc", algo=1)
    with pytest.raises(codec.FrameCorrupt, match="unknown"):
        codec.wire_crc(b"abc", algo=9)


def test_degraded_shed_does_not_burn_sparse_slots():
    """A shed write never enters the store — including the sparse-key
    index: an outage of novel-key puts must not consume dense slots
    (KeyIndex never deletes)."""
    cfg = _kvs_cfg(n_replicas=3, pipeline_depth=1, op_timeout_rounds=0,
                   op_retry_limit=0, min_healthy_for_writes=2)
    kvs = KVS(cfg, sparse_keys=True)
    kvs.rt.freeze(1)
    kvs.rt.freeze(2)
    f = kvs.put(0, 0, 0xDEAD_BEEF_0001, [1])
    bf = kvs.submit_batch(np.array([KVS.PUT, KVS.GET]),
                          np.array([0xDEAD_BEEF_0002, 0xDEAD_BEEF_0002],
                                   dtype=np.uint64),
                          np.array([[2, 2], [0, 0]]))
    assert f.result().kind == "rejected"
    assert bf.code[0] == -3  # C_REJECTED
    # the shed write of a novel key must not have inserted it: the batch
    # get of the same key reads not-found WITHOUT claiming a slot either
    from hermes_tpu.core import types as t

    assert bf.code[1] == t.C_READ and not bf.found[1]  # absent-key read
    assert kvs.index.n_used == 0, "degraded shed consumed dense slots"
    assert kvs.shed_writes == 2 and kvs.rejected_ops == 0


def test_held_frames_die_in_partition_blackout():
    """A partition is a SUSTAINED blackout: a frame delayed into the
    window does not tunnel through it (a held heartbeat released
    mid-blackout would refresh the observer and stall detector ejection)."""
    cfg = _cfg_sim()
    wire = FaultingTransport(LockstepHostTransport(), 3, seed=1)
    wire.add("delay", 0, 1, 0, 1, param=3)      # step-0 frame due at 3
    wire.add("partition", 0, 1, 2, 10)          # blackout opens at 2
    empty = st.empty_invs(cfg, lead=(3,))
    wire.exchange_inv(_inv_block(cfg), step=0)  # held
    inb = wire.exchange_inv(empty, step=3)      # due mid-blackout: dies
    assert not np.asarray(inb.valid)[1, 0].any()
    assert wire.pending() == 0, "held frame survived the blackout"
    assert any(f.get("held") == "dropped_in_blackout"
               for f in wire.fault_log)


def test_overlapping_partitions_do_not_heal_early():
    """Two overlapping partition windows on the same src: expiring the
    SHORT one must not restore edges the LONG one still claims (the
    expiry path re-derives the severed set from live windows)."""
    cfg = _cfg_sim(n_replicas=4, lease_steps=4)
    rt = FastRuntime(cfg, record=True)
    svc = MembershipService(cfg, confirm_steps=1)
    rt.attach_membership(svc)
    sched = chaos.Schedule.parse(
        "@2 partition 2 until=8\n@4 partition 2 until=60\n")
    runner = chaos.ChaosRunner(rt, sched)
    runner.run(20, heal=False)
    # the short window lapsed at 8; the long one still holds the edges
    assert svc.severed_edges(), "long partition window ended early"
    # and the oracle age still grounds the suspicion (replica removed)
    assert any(e.kind == "remove" and e.replica == 2 for e in svc.events)


def test_committed_write_lost_helper():
    ops = [Op("w", 1, 0.0, 1.0, wuid=(1, 1)),
           Op("maybe_w", 1, 0.0, 2.0, wuid=(2, 2)),
           Op("rmw", 1, 2.0, 3.0, wuid=(3, 3))]
    aborted = {(4, 4)}
    assert lin.committed_write_lost([(1, 1), (3, 3)], ops, aborted) == []
    # a client-visible commit recorded only as maybe_w counts as lost
    assert lin.committed_write_lost([(2, 2)], ops, aborted) == [(2, 2)]
    # ... as does one the recorder reported aborted
    assert lin.committed_write_lost([(4, 4)], ops, aborted) == [(4, 4)]
