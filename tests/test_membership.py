"""Membership service tests (SURVEY.md §5.3; configs 4-5, BASELINE.json:10-11):
automatic lease-based failure detection from in-band heartbeats, quorum
unblocking, scripted rejoin — all under the linearizability gate."""

import numpy as np

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.membership import MembershipService
from hermes_tpu.runtime import Runtime
from hermes_tpu.transport.sim import SimTransport

from helpers import get


def make_rt(seed=50, n_replicas=4, **kw):
    base = dict(
        n_replicas=n_replicas, n_keys=64, n_sessions=4, replay_slots=8,
        ops_per_session=20, replay_age=5, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=seed),
    )
    base.update(kw)
    cfg = HermesConfig(**base)
    rt = Runtime(cfg, backend="sim", record=True, transport=SimTransport(n_replicas))
    rt.attach_membership(MembershipService(cfg))
    return cfg, rt


def test_auto_detect_removes_stalled_replica():
    """Config 4 (BASELINE.json:10): stall a replica mid-workload; the service
    must suspect it after the lease and remove it, unblocking writes."""
    cfg, rt = make_rt()
    rt.run(5)
    rt.freeze(3)
    rt.run(cfg.lease_steps + 3)
    assert rt.membership.events, "no membership event fired"
    evt = rt.membership.events[0]
    assert evt.kind == "remove" and evt.replica == 3
    assert not (int(rt.live[0]) >> 3) & 1
    # the surviving trio drains and the history linearizes
    assert rt.drain(500)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])


def test_auto_detect_then_rejoin_converges():
    """Config 5 (BASELINE.json:11): remove via lease expiry, then scripted
    join with state transfer; full convergence + checker."""
    cfg, rt = make_rt(seed=51)
    rt.run(4)
    rt.freeze(2)
    rt.run(cfg.lease_steps + 3)
    assert any(e.kind == "remove" and e.replica == 2 for e in rt.membership.events)
    rt.run(10)
    rt.join(2, from_replica=0)
    assert any(e.kind == "join" for e in rt.membership.events)
    assert rt.drain(500)
    assert rt.check().ok
    state = get(rt.rs.table.state)
    assert (state == t.VALID).all()
    ver = get(rt.rs.table.ver)
    for r in range(1, cfg.n_replicas):
        np.testing.assert_array_equal(ver[0], ver[r])


def test_false_suspicion_fences_partitioned_replica():
    """Regression: a replica that is merely PARTITIONED (messages dropped,
    process alive) must be fenced when the service removes it — otherwise it
    would keep serving stale reads after the quorum shrinks past it."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=8, ops_per_session=30,
        replay_age=5, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.6, rmw_frac=0.0, seed=53),
    )

    def partition_2(kind, src, dst, step):
        if (src == 2 or dst == 2) and src != dst and 5 <= step:
            return []  # drop everything to/from replica 2 (it stays unfrozen!)
        return [step]

    rt = Runtime(cfg, backend="sim", record=True, transport=SimTransport(3, partition_2))
    rt.attach_membership(MembershipService(cfg))
    rt.run(5 + cfg.lease_steps + 3)
    assert any(e.kind == "remove" and e.replica == 2 for e in rt.membership.events)
    assert rt.frozen[2], "removed replica must be fenced (no stale reads)"
    assert rt.drain(500)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])


def test_healthy_cluster_never_ejects():
    cfg, rt = make_rt(seed=52)
    rt.run(3 * cfg.lease_steps)
    assert not rt.membership.events
    assert int(rt.live[0]) == cfg.full_mask
