"""Round-19 columnar serving data plane: the batch codec against its
per-struct oracle (both directions, both payload modes, adversarial
edges), batch admission vs the scalar ladder (state-exact), the
completion-ring frontend's envelope (validity refusals, deadlines, ring
exhaustion), loopback byte-log walkability, the columnar TCP server,
and SO_REUSEPORT accept sharding."""

import dataclasses
import socket
import threading

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.kvs import KVS
from hermes_tpu.serving import (ColumnarClient, ColumnarFrontend,
                                ColumnarLoopback, ColumnarTcpServer,
                                ServingConfig, VirtualClock,
                                verify_columnar, wire)
from hermes_tpu.serving.admission import AdmissionControl
from hermes_tpu.serving.server import CompletionRing
from hermes_tpu.serving.soak import committed_uids, run_columnar_soak
from hermes_tpu.workload.openloop import MixSpec


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=64, n_sessions=4, replay_slots=6,
              ops_per_session=96, value_words=6, pipeline_depth=2,
              workload=WorkloadConfig(read_frac=0.5, seed=7))
    kw.update(over)
    return HermesConfig(**kw)


def _scfg(**over):
    kw = dict(tenant_rate_per_s=1e6, tenant_burst=1e4, tenant_quota=16,
              queue_cap=64, round_us=1000)
    kw.update(over)
    return ServingConfig(**kw)


# -- batch codec vs the per-struct oracle ------------------------------------


def _random_requests(rng, k, u, vbytes=0, traced=False):
    out = []
    for i in range(k):
        kind = ("get", "put", "rmw")[int(rng.integers(3))]
        r = wire.Request(
            kind=kind, req_id=int(rng.integers(1 << 32)),
            tenant=int(rng.integers(1 << 16)),
            key=int(rng.integers(-(1 << 40), 1 << 40)),
            deadline_us=int(rng.integers(1 << 32)),
            trace=int(rng.integers(1, 1 << 16)) if traced
            and rng.random() < 0.5 else 0)
        if vbytes:
            # adversarial payloads: absent, zero-length, max-length, and
            # high-bit bytes that would tear a sign-careless decoder
            roll = rng.random()
            if kind != "get" and roll < 0.75:
                n = (0 if roll < 0.15 else
                     vbytes if roll < 0.3 else int(rng.integers(vbytes + 1)))
                r.data = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        elif kind != "get":
            r.value = rng.integers(-(1 << 31), 1 << 31,
                                   int(rng.integers(u + 1))).tolist()
        out.append(r)
    return out


def _random_responses(rng, k, u, vbytes=0):
    out = []
    statuses = (wire.S_OK, wire.S_RMW_ABORT, wire.S_REJECTED,
                wire.S_RETRY_AFTER, wire.S_DEADLINE, wire.S_LOST)
    for i in range(k):
        st = int(statuses[int(rng.integers(len(statuses)))])
        r = wire.Response(
            status=st, req_id=int(rng.integers(1 << 32)),
            reason=int(rng.integers(6)), found=bool(rng.integers(2)),
            step=int(rng.integers(-1, 1 << 31)),
            retry_after_us=int(rng.integers(1 << 32)),
            uid=((int(rng.integers(-(1 << 31), 1 << 31)),
                  int(rng.integers(-(1 << 31), 1 << 31)))
                 if rng.random() < 0.5 else None))
        if vbytes:
            if st == wire.S_OK and rng.random() < 0.75:
                n = int(rng.integers(vbytes + 1))
                r.data = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        elif rng.random() < 0.75:
            r.value = rng.integers(-(1 << 31), 1 << 31, u).tolist()
        out.append(r)
    return out


@pytest.mark.parametrize("vbytes", [0, 24])
def test_req_batch_codec_byte_identical_to_struct_oracle(vbytes):
    u, rng = 3, np.random.default_rng(19)
    for k in (0, 1, 7, 257):
        reqs = _random_requests(rng, k, u, vbytes, traced=True)
        oracle = b"".join(wire.encode_request(r, u, vbytes) for r in reqs)
        b = wire.ReqBatch.from_requests(reqs, u, vbytes)
        assert wire.encode_request_batch(b, u, vbytes) == oracle
        # decode inverts: row structs match what the struct decoder sees
        back = wire.decode_request_batch(oracle, u, vbytes).to_requests()
        off = 0
        for r in back:
            step = len(wire.encode_request(r, u, vbytes))
            assert r == wire.decode_request(oracle[off: off + step],
                                            u, vbytes)
            off += step
        assert off == len(oracle)


@pytest.mark.parametrize("vbytes", [0, 24])
def test_rsp_batch_codec_byte_identical_to_struct_oracle(vbytes):
    u, rng = 3, np.random.default_rng(23)
    for k in (0, 1, 7, 257):
        rsps = _random_responses(rng, k, u, vbytes)
        oracle = b"".join(wire.encode_response(r, u, vbytes) for r in rsps)
        b = wire.RspBatch.from_responses(rsps, u, vbytes)
        assert wire.encode_response_batch(b, u, vbytes) == oracle
        back = wire.decode_response_batch(oracle, u, vbytes).to_responses()
        off = 0
        for r in back:
            step = wire.response_extent(oracle, off, u, vbytes)
            assert r == wire.decode_response(oracle[off: off + step],
                                             u, vbytes)
            off += step
        assert off == len(oracle)


def test_batch_codec_torn_and_garbage_are_loud():
    u = 2
    reqs = _random_requests(np.random.default_rng(5), 4, u)
    raw = wire.encode_request_batch(wire.ReqBatch.from_requests(reqs, u), u)
    with pytest.raises(ValueError, match="torn batch"):
        wire.decode_request_batch(raw[:-3], u)
    bad = bytearray(raw)
    bad[wire.req_nbytes(u)] ^= 0xFF  # second record's magic
    with pytest.raises(ValueError, match="magic"):
        wire.decode_request_batch(bytes(bad), u)
    bad = bytearray(raw)
    bad[wire.req_nbytes(u) + 2] = 77  # second record's kind
    with pytest.raises(ValueError, match="kind"):
        wire.decode_request_batch(bytes(bad), u)
    # heap mode: truncated header, torn tail, oversize length prefix
    vb = 16
    hreqs = _random_requests(np.random.default_rng(6), 4, u, vb)
    hraw = wire.encode_request_batch(
        wire.ReqBatch.from_requests(hreqs, u, vb), u, vb)
    with pytest.raises(ValueError, match="torn batch"):
        wire.decode_request_batch(hraw[:-1], u, vb)
    one = wire.encode_request(wire.Request(
        kind="put", req_id=1, tenant=0, key=0, data=b"abcd"), u, vb)
    huge = bytearray(one)
    huge[-8:-4] = (vb + 1).to_bytes(4, "little")  # dlen > vbytes
    with pytest.raises(ValueError, match="payload tail"):
        wire.decode_request_batch(bytes(huge), u, vb)
    # responses share the triage rules
    rsps = _random_responses(np.random.default_rng(7), 3, u)
    rraw = wire.encode_response_batch(
        wire.RspBatch.from_responses(rsps, u), u)
    with pytest.raises(ValueError, match="torn batch"):
        wire.decode_response_batch(rraw[:-2], u)
    rbad = bytearray(rraw)
    rbad[0] ^= 0xFF
    with pytest.raises(ValueError, match="magic"):
        wire.decode_response_batch(bytes(rbad), u)


def test_batch_codec_refuses_oversize_payloads_on_encode():
    u, vb = 2, 8
    b = wire.ReqBatch.from_requests([wire.Request(
        kind="put", req_id=1, tenant=0, key=0, data=b"x" * vb)], u, vb)
    b.vlen = np.array([vb + 1], np.int64)  # lie about the extent
    with pytest.raises(ValueError, match="max_value_bytes"):
        wire.encode_request_batch(b, u, vb)
    with pytest.raises(ValueError, match="int32 words"):
        wire.ReqBatch.from_requests([wire.Request(
            kind="put", req_id=1, tenant=0, key=0,
            value=list(range(u + 1)))], u)


# -- batch admission vs the scalar ladder ------------------------------------


def test_admit_batch_state_exact_vs_scalar_ladder():
    """The fuzz contract as a regression test: over random batches the
    batch ladder must return the same reasons and hints AND leave the
    same tenant counters and bucket floats as the scalar loop."""
    rng = np.random.default_rng(41)
    scfg = _scfg(tenant_quota=5, queue_cap=12, tenant_rate_per_s=50.0,
                 tenant_burst=6, shed_write_frac=0.5, shed_read_frac=0.8,
                 hot_keys=(3, 9))
    a, b = AdmissionControl(scfg), AdmissionControl(scfg)
    now, q_a, q_b = 0.0, 0, 0
    for trial in range(40):
        k = int(rng.integers(0, 9))
        writes = rng.integers(2, size=k).astype(bool)
        keys = rng.integers(0, 16, k).astype(np.int64)
        tenants = rng.integers(0, 3, k)
        degraded = bool(rng.random() < 0.15)
        now += float(rng.random() * 0.1)
        exp_r, exp_w = [], []
        for i in range(k):
            rsn, wt = a.admit("put" if writes[i] else "get", int(keys[i]),
                              int(tenants[i]), now, q_a, degraded)
            if rsn == wire.R_NONE:
                a.note_admitted(int(tenants[i]))
                q_a += 1
            exp_r.append(rsn), exp_w.append(wt)
        got_r, got_w = b.admit_batch(writes, keys, tenants, now, q_b,
                                     degraded)
        q_b += int((got_r == wire.R_NONE).sum())
        assert got_r.tolist() == exp_r, f"trial {trial}"
        assert np.allclose(got_w, exp_w), f"trial {trial}"
        assert q_a == q_b
        assert a.counters() == b.counters()
        for t in a.tenants:
            ba, bb = a.tenants[t].bucket, b.tenants[t].bucket
            assert (ba.tokens, ba._t_last) == (bb.tokens, bb._t_last)
        # drain some inflight so later trials see fresh quota room
        for t, ts in a.tenants.items():
            drop = int(rng.integers(0, ts.inflight + 1))
            for _ in range(drop):
                a.note_resolved(t, wire.S_OK)
            if drop:
                b.note_resolved_batch(np.full(drop, t),
                                      np.full(drop, wire.S_OK))
            q_a, q_b = q_a - drop, q_b - drop


# -- completion ring + columnar frontend envelope ----------------------------


def _batch(kind, keys, req_id0=1, tenant=0, u=4, deadline_us=0, value=None):
    k = len(keys)
    return wire.ReqBatch(
        kind=np.asarray(kind, np.uint8),
        req_id=np.arange(req_id0, req_id0 + k, dtype=np.uint32),
        tenant=np.full(k, tenant, np.uint16),
        trace=np.zeros(k, np.uint16),
        deadline_us=np.full(k, deadline_us, np.uint32),
        key=np.asarray(keys, np.int64),
        value=(np.asarray(value, np.int32) if value is not None
               else np.zeros((k, u), np.int32)))


def test_completion_ring_exhaustion_is_loud_and_release_reuses():
    ring = CompletionRing(cap=4, u=2, vbytes=0)
    first = ring.alloc(ring.cap)
    assert ring.in_use() == ring.cap
    with pytest.raises(RuntimeError, match="accounting bug"):
        ring.alloc(1)
    ring.release(first[:3])
    again = ring.alloc(3)
    assert set(again.tolist()) == set(first[:3].tolist())
    assert (ring.status[again] == 0xFF).all()  # slots come back open


def test_columnar_validity_refusals_are_rejected_rows():
    fe = ColumnarFrontend(KVS(_cfg()), _scfg(), clock=VirtualClock())
    b = _batch([wire.K_PUT, 9, wire.K_GET], [1, 2, 10_000])
    out = fe.submit_batch(b)
    # rows 1 (unknown kind) and 2 (key out of range) refuse immediately,
    # definitively (S_REJECTED, not retry_after) and in batch row order
    assert out.req_id.tolist() == [2, 3]
    assert out.status.tolist() == [wire.S_REJECTED] * 2
    assert fe.drain()[0]
    tot = verify_columnar(fe)
    assert tot["completed"] == 1 and tot["rejected"] == 0  # store-level ctr


def test_columnar_deadline_enforced_at_intake_backlog():
    clock = VirtualClock()
    fe = ColumnarFrontend(KVS(_cfg()), _scfg(store_inflight_cap=1,
                                             queue_cap=32),
                          clock=clock)
    out = fe.submit_batch(_batch([wire.K_PUT] * 8, list(range(8)),
                                 deadline_us=1500))
    assert len(out) == 0  # all admitted
    emitted = []
    for _ in range(200):
        if fe.idle():
            break
        emitted.append(fe.pump())
        clock.advance(0.001)  # one serving round per pump
    st = np.concatenate([rb.status for d in emitted for rb in d.values()])
    names = [wire.STATUS_NAMES[int(s)] for s in st]
    # the cap-1 store serves a trickle; the backlog expires loudly
    assert names.count("deadline") >= 4
    assert set(names) <= {"ok", "deadline"}
    verify_columnar(fe)
    assert fe.ring.in_use() == 0


def test_columnar_quota_refusal_carries_retry_hint():
    fe = ColumnarFrontend(KVS(_cfg()), _scfg(tenant_quota=3),
                          clock=VirtualClock())
    out = fe.submit_batch(_batch([wire.K_PUT] * 6, list(range(6))))
    assert out.status.tolist() == [wire.S_RETRY_AFTER] * 3
    assert out.reason.tolist() == [wire.R_QUOTA] * 3
    assert (out.retry_after_us > 0).all()
    assert fe.drain()[0]
    verify_columnar(fe)


def test_columnar_heap_payload_roundtrip():
    fe = ColumnarFrontend(KVS(_cfg(max_value_bytes=32)), _scfg(),
                          clock=VirtualClock())
    payload = bytes(range(7))
    put = wire.ReqBatch(
        kind=np.array([wire.K_PUT], np.uint8),
        req_id=np.array([1], np.uint32), tenant=np.zeros(1, np.uint16),
        trace=np.zeros(1, np.uint16), deadline_us=np.zeros(1, np.uint32),
        key=np.array([5], np.int64), vlen=np.array([len(payload)], np.int64),
        voff=np.zeros(1, np.int64), blob=payload)
    assert len(fe.submit_batch(put)) == 0
    assert fe.drain()[0]
    get = dataclasses.replace(put, kind=np.array([wire.K_GET], np.uint8),
                              req_id=np.array([2], np.uint32),
                              vlen=np.array([-1], np.int64), blob=b"")
    assert len(fe.submit_batch(get)) == 0
    _, emitted = fe.drain()
    got = [rb for d in emitted for rb in d.values()
           if 2 in rb.req_id.tolist()]
    assert got and got[-1].row_data(got[-1].req_id.tolist().index(2)) \
        == payload
    verify_columnar(fe)


def test_columnar_frontend_refuses_fleet_stores():
    from hermes_tpu.config import FleetConfig
    from hermes_tpu.fleet import Fleet

    fleet = Fleet(FleetConfig(groups=2, base=_cfg()))
    with pytest.raises(ValueError, match="single KVS"):
        ColumnarFrontend(fleet, _scfg())


# -- loopback byte log + soak ------------------------------------------------


def test_columnar_loopback_log_walkable_and_soak_replays():
    shas, logs = [], []
    for _ in range(2):
        res = run_columnar_soak(KVS(_cfg()), _scfg(tenant_quota=8),
                                MixSpec(tenants=2, read_frac=0.4),
                                rate_per_s=4000.0, n=120, seed=11,
                                deadline_us=50_000)
        shas.append(res["response_log_sha"])
        logs.append((res["_frontend"], res["_server"]))
    assert shas[0] == shas[1]  # byte-identical replay
    fe, lb = logs[0]
    uids = committed_uids(fe, lb)  # the struct walker, record by record
    # second decoder over the SAME bytes: the whole log is one fixed-
    # width columnar batch — both decoders must agree on the uids
    rb = wire.decode_response_batch(lb.response_log(), lb.u)
    ok_uid = (rb.status == wire.S_OK) & rb.has_uid
    assert uids == [tuple(row) for row in rb.uid[ok_uid].tolist()]
    assert uids  # the soak committed writes
    assert sum(res["statuses"].values()) == 120


def test_columnar_soak_refuses_heap_stores():
    with pytest.raises(ValueError, match="fixed-width"):
        run_columnar_soak(KVS(_cfg(max_value_bytes=16)), _scfg(),
                          MixSpec(), rate_per_s=100.0, n=4, seed=1,
                          deadline_us=0)


# -- columnar TCP + accept sharding ------------------------------------------


def test_columnar_tcp_server_end_to_end():
    fe = ColumnarFrontend(KVS(_cfg()), _scfg())
    server = ColumnarTcpServer(fe)
    try:
        cl = ColumnarClient(server.addr, fe.u)
        val = np.arange(4 * fe.u, dtype=np.int32).reshape(4, fe.u)
        puts = _batch([wire.K_PUT] * 4, [1, 2, 3, 4], u=fe.u,
                      req_id0=int(cl.next_ids(4)[0]), value=val)
        for rsp in cl.call_batch(puts).values():
            assert rsp.status_name == "ok"
        gets = _batch([wire.K_GET] * 4, [1, 2, 3, 4], u=fe.u,
                      req_id0=int(cl.next_ids(4)[0]))
        got = cl.call_batch(gets)
        for i, rid in enumerate(gets.req_id.tolist()):
            assert got[rid].status_name == "ok" and got[rid].found
            assert got[rid].value == val[i].tolist()
        cl.close()
    finally:
        server.close()
    assert server.pump_error is None and server.undecodable == 0


def test_columnar_tcp_undecodable_batch_tears_down_loudly():
    fe = ColumnarFrontend(KVS(_cfg()), _scfg())
    server = ColumnarTcpServer(fe)
    try:
        cl = ColumnarClient(server.addr, fe.u)
        cl.fsock.send(b"\x00" * 10)  # frame-valid garbage
        assert cl.recv_batch() is None  # loud EOF, not silence
        cl.close()
    finally:
        server.close()
    assert server.undecodable == 1


def test_serving_listener_reuseport_gate():
    from hermes_tpu.transport.tcp import serving_listener

    a = serving_listener("127.0.0.1", 0, reuseport=True)
    port = a.getsockname()[1]
    b = serving_listener("127.0.0.1", port, reuseport=True)
    a.close(), b.close()
    plain = serving_listener("127.0.0.1", 0)
    with pytest.raises(OSError):
        serving_listener("127.0.0.1", plain.getsockname()[1])
    plain.close()
    if not hasattr(socket, "SO_REUSEPORT"):
        with pytest.raises(RuntimeError, match="SO_REUSEPORT"):
            serving_listener("127.0.0.1", 0, reuseport=True)


def test_accept_sharding_in_process_under_concurrent_clients():
    """Two reuseport servers (independent stores) on ONE port; eight
    threaded clients land on whichever the kernel picks — every batch
    must answer, and the pump/reader lock split must hold up under the
    concurrency (the round-19 fairness satellite's regression)."""
    servers = []
    port = 0
    try:
        for _ in range(2):
            fe = ColumnarFrontend(KVS(_cfg()), _scfg(tenant_quota=64))
            s = ColumnarTcpServer(fe, port=port, reuseport=True)
            port = s.addr[1]
            servers.append(s)
        errs, done = [], []

        def client(i):
            try:
                cl = ColumnarClient(("127.0.0.1", port), servers[0].u)
                b = _batch([wire.K_PUT] * 8, list(range(8)),
                           u=servers[0].u, tenant=i,
                           req_id0=int(cl.next_ids(8)[0]))
                rsps = cl.call_batch(b)
                assert len(rsps) == 8
                assert all(r.status_name in ("ok", "retry_after")
                           for r in rsps.values())
                done.append(i)
                cl.close()
            except Exception as e:  # noqa: BLE001
                errs.append((i, repr(e)))

        ts = [threading.Thread(target=client, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60.0)
        assert not errs and len(done) == 8
    finally:
        for s in servers:
            s.close()
    assert all(s.pump_error is None for s in servers)


@pytest.mark.slow
def test_sharded_worker_processes_serve_and_stop():
    """Full accept-sharding topology: N spawned worker processes behind
    one SO_REUSEPORT port (the launch.py --serve-workers path)."""
    from hermes_tpu.launch import start_serve_workers

    with start_serve_workers(2, cfg=_cfg(n_sessions=8)) as fleet:
        assert fleet.alive() == 2
        oks = 0
        for w in range(3):
            cl = ColumnarClient(fleet.addr, _cfg().value_words - 2)
            b = _batch([wire.K_PUT] * 4, [w, w + 1, w + 2, w + 3],
                       u=_cfg().value_words - 2, tenant=w,
                       req_id0=int(cl.next_ids(4)[0]))
            oks += sum(r.status_name == "ok"
                       for r in cl.call_batch(b).values())
            cl.close()
        assert oks == 12
    assert fleet.alive() == 0
