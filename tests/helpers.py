"""Shared test helpers: tiny configs, control scalars, message-block builders."""

import jax
import jax.numpy as jnp
import numpy as np

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st
from hermes_tpu.core import types as t


def tiny_cfg(**kw) -> HermesConfig:
    base = dict(
        n_replicas=3,
        n_keys=64,
        n_sessions=4,
        replay_slots=2,
        ops_per_session=8,
        replay_age=4,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    base.update(kw)
    return HermesConfig(**base)


def ctl_scalars(step=0, cid=0, epoch=0, live_mask=None, frozen=False, cfg=None) -> st.Ctl:
    if live_mask is None:
        live_mask = cfg.full_mask if cfg else 0b111
    return st.Ctl(
        step=jnp.int32(step),
        my_cid=jnp.int32(cid),
        epoch=jnp.int32(epoch),
        live_mask=jnp.int32(live_mask),
        frozen=jnp.bool_(frozen),
    )


def empty_stream(cfg: HermesConfig) -> st.OpStream:
    """All-NOP stream (sessions idle through the run)."""
    shape = (cfg.n_sessions, cfg.ops_per_session)
    return st.OpStream(
        op=jnp.zeros(shape, jnp.int32), key=jnp.zeros(shape, jnp.int32)
    )


def inv_block(cfg: HermesConfig, records, n_senders=None, epoch=0):
    """Build an inbound (R, L) INV block from [(sender, lane, key, ver, fc,
    val_words), ...]."""
    r = n_senders or cfg.n_replicas
    blk = st.empty_invs(cfg, lead=(r,))
    valid = np.zeros((r, cfg.n_lanes), bool)
    key = np.zeros((r, cfg.n_lanes), np.int32)
    ver = np.zeros((r, cfg.n_lanes), np.int32)
    fc = np.zeros((r, cfg.n_lanes), np.int32)
    val = np.zeros((r, cfg.n_lanes, cfg.value_words), np.int32)
    for s, lane, k, v, f, words in records:
        valid[s, lane] = True
        key[s, lane] = k
        ver[s, lane] = v
        fc[s, lane] = f
        val[s, lane, : len(words)] = words
    return blk._replace(
        valid=jnp.asarray(valid),
        key=jnp.asarray(key),
        ver=jnp.asarray(ver),
        fc=jnp.asarray(fc),
        epoch=jnp.full((r, cfg.n_lanes), epoch, jnp.int32),
        val=jnp.asarray(val),
        alive=jnp.ones((r,), jnp.bool_),
    )


def ack_block(cfg: HermesConfig, records, n_senders=None, epoch=0):
    """Inbound (R, L) ACK block from [(sender, lane, key, ver, fc[, ok]), ...]."""
    r = n_senders or cfg.n_replicas
    valid = np.zeros((r, cfg.n_lanes), bool)
    key = np.zeros((r, cfg.n_lanes), np.int32)
    ver = np.zeros((r, cfg.n_lanes), np.int32)
    fc = np.zeros((r, cfg.n_lanes), np.int32)
    ok = np.zeros((r, cfg.n_lanes), bool)
    for rec in records:
        s, lane, k, v, f = rec[:5]
        valid[s, lane] = True
        key[s, lane] = k
        ver[s, lane] = v
        fc[s, lane] = f
        ok[s, lane] = rec[5] if len(rec) > 5 else True
    return st.Acks(
        valid=jnp.asarray(valid),
        key=jnp.asarray(key),
        ver=jnp.asarray(ver),
        fc=jnp.asarray(fc),
        ok=jnp.asarray(ok),
        epoch=jnp.full((r, cfg.n_lanes), epoch, jnp.int32),
    )


def get(x):
    return np.asarray(jax.device_get(x))
