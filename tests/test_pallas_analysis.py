"""Tests for the pallas kernel sub-interpreter (ISSUE 8).

The analyzer no longer skips ``pallas_call`` bodies: stats_block is
fully interpreted (tight output intervals, kernel-internal finding
sites), the RefHazard discipline flips red on seeded kernel mutations
(overlapping pack inside a kernel, out-of-bounds block store, dropped
``pl.when(blk == 0)`` init, unaudited grid-revisit accumulator,
out-of-range BlockSpec index map), an unmodeled primitive degrades to a
``pallas-skipped`` info finding instead of a silent pass, and the
differential sanitizer both passes on the in-tree kernel matrix and
catches a deliberately unsound transfer rule.  Mirrors the PR-3
mutation-test pattern in tests/test_analysis.py.
"""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hermes_tpu import analysis as ana
from hermes_tpu.analysis import diffcheck
from hermes_tpu.analysis import domain as D
from hermes_tpu.analysis import interp as I
from hermes_tpu.analysis import seeds
from hermes_tpu.analysis.domain import iv
from hermes_tpu.analysis.passes import RefHazardPass, default_passes
from hermes_tpu.config import HermesConfig
from hermes_tpu.core import kernels, layouts
from hermes_tpu.core import state as st


def _run(fn, in_avs, shapes, passes=None):
    jx = jax.make_jaxpr(fn)(*shapes)
    ps = passes if passes is not None else default_passes()
    ctx = I.Ctx(passes=ps, mesh_axes=None)
    outs = I.eval_jaxpr(jx.jaxpr, in_avs, ctx, consts=list(jx.consts))
    findings = [f for p in ps for f in p.results()]
    return outs, findings, ctx, ps


def _gating(findings):
    return [f for f in findings if f.severity in ana.GATING]


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _stats_shapes(R, S):
    return (_sds((), jnp.int32), _sds((R, S), jnp.int32),
            _sds((R, S), jnp.int32), _sds((R, S), jnp.bool_),
            _sds((R, S), jnp.bool_), _sds((R, S), jnp.bool_))


# --------------------------------------------------------------------------
# the kernel black box is open
# --------------------------------------------------------------------------


class TestKernelInterp:
    def test_stats_block_fully_interpreted(self):
        # pre-ISSUE-8 every pallas output was dtype-TOP; now the code
        # output carries the exact completion-code interval and the
        # single-block histogram is bounded by the block width
        outs, findings, ctx, ps = _run(
            kernels.stats_block, seeds.seed_stats_block(),
            _stats_shapes(4, 512))
        assert _gating(findings) == []
        code, ctr, hist = outs
        assert (code.lo, code.hi) == (0, 4)  # C_NONE..C_RMW_ABORT
        assert not D.is_top(code, np.int32)
        assert (hist.lo, hist.hi) == (0, 512)
        hp = next(p for p in ps if p.name == "refhazard")
        assert hp.n_proved > 0

    def test_multiblock_revisit_audited_visible(self):
        # the multi-block grid revisits ctr/hist; the declared audit on
        # the call site surfaces as an info finding carrying the tag
        outs, findings, _, _ = _run(
            kernels.stats_block, seeds.seed_stats_block(),
            _stats_shapes(1024, 600))
        assert _gating(findings) == []
        revisit = [f for f in findings
                   if f.code == "grid-revisit-accumulator"]
        assert revisit and all(f.severity == "info" for f in revisit)
        assert all(f.audit == "stats-ctr-hist-grid-accumulate"
                   for f in revisit)
        assert (outs[0].lo, outs[0].hi) == (0, 4)

    def test_mutation_drop_revisit_audit_flips_red(self, monkeypatch):
        # the kernel analogue of PR-3's dropped-scatter-audit mutation.
        # pallas_call's jit cache would replay the audited trace from
        # the earlier tests — drop it so the mutation really re-traces
        jax.clear_caches()
        monkeypatch.setattr(layouts, "audited",
                            lambda tag: contextlib.nullcontext())
        _, findings, _, _ = _run(
            kernels.stats_block, seeds.seed_stats_block(),
            _stats_shapes(1024, 600))
        gating = _gating(findings)
        assert any(f.code == "grid-revisit-accumulator" for f in gating)

    def test_round_program_polices_kernel(self):
        # the engine round CONTAINS stats_block: the sub-interpreter now
        # walks it inside the round analysis (in-bounds + init proofs
        # counted) and the round stays clean
        cfg = HermesConfig(n_replicas=3, n_keys=1 << 12, n_sessions=16,
                           replay_slots=8, ops_per_session=8)
        reports = ana.analyze_config(cfg, engines=("batched",))
        assert _gating(ana.findings_of(reports)) == []
        assert all(r["proved"]["refhazard"] > 0 for r in reports)
        skipped = [f for f in ana.findings_of(reports)
                   if f.code == "pallas-skipped"]
        assert skipped == []  # the kernel is modeled, not skipped

    def test_kernel_internal_finding_site(self):
        # findings inside a kernel name the kernel function and file,
        # not the pallas_call call site
        def _pack_kernel(a_ref, b_ref, o_ref):
            o_ref[:] = (a_ref[:] << 29) | b_ref[:]

        def f(a, b):
            return pl.pallas_call(
                _pack_kernel,
                out_shape=_sds((8, 128), jnp.int32),
                interpret=True)(a, b)

        s = _sds((8, 128), jnp.int32)
        _, findings, _, _ = _run(f, [iv(0, 2), iv(0, 1 << 29)], (s, s))
        errs = [f_ for f_ in findings if f_.code == "pack-overlap"]
        assert errs, "overlapping pack inside a kernel body must flag"
        assert errs[0].severity == "error"
        assert errs[0].file.endswith("test_pallas_analysis.py")
        assert errs[0].fn == "_pack_kernel"

    def test_disjoint_kernel_pack_proved(self):
        def _pack_kernel(a_ref, b_ref, o_ref):
            o_ref[:] = (a_ref[:] << 29) | b_ref[:]

        def f(a, b):
            return pl.pallas_call(
                _pack_kernel,
                out_shape=_sds((8, 128), jnp.int32),
                interpret=True)(a, b)

        s = _sds((8, 128), jnp.int32)
        outs, findings, _, ps = _run(
            f, [iv(0, 2), iv(0, (1 << 29) - 1)], (s, s))
        assert _gating(findings) == []
        assert next(p for p in ps if p.name == "bitpack").n_proved >= 2
        # and the output keeps the pack's sign-safe hull, not dtype-TOP
        assert outs[0].lo == 0 and not D.is_top(outs[0], np.int32)


# --------------------------------------------------------------------------
# ref hazards: stores in bounds, init discipline, block specs
# --------------------------------------------------------------------------


def _store_at_idx(idx_av, blk=8):
    """A kernel storing one row at a dynamic SMEM-scalar index."""

    def _kern(i_ref, v_ref, o_ref):
        o_ref[:] = jnp.zeros_like(o_ref)
        i = i_ref[0, 0]
        o_ref[pl.dslice(i, 1), :] = v_ref[pl.dslice(0, 1), :]

    def f(i, v):
        return pl.pallas_call(
            _kern,
            in_specs=[
                pl.BlockSpec((1, 1), lambda: (0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((blk, 128), lambda: (0, 0)),
            ],
            out_specs=pl.BlockSpec((blk, 128), lambda: (0, 0)),
            out_shape=_sds((blk, 128), jnp.int32),
            interpret=True)(i, v)

    shapes = (_sds((1, 1), jnp.int32), _sds((blk, 128), jnp.int32))
    return _run(f, [idx_av, iv(0, 100)], shapes)


class TestRefHazards:
    def test_oob_block_store_flips_red(self):
        _, findings, _, _ = _store_at_idx(iv(0, 100), blk=8)
        errs = [f for f in findings if f.code == "oob-block-store"]
        assert errs and errs[0].severity == "error"

    def test_in_bounds_store_proved(self):
        _, findings, _, ps = _store_at_idx(iv(0, 7), blk=8)
        assert _gating(findings) == []
        assert next(p for p in ps if p.name == "refhazard").n_proved > 0

    def _acc(self, with_init, audited):
        """A 2-block grid accumulating into a revisited (8, 1) output."""

        def _kern(x_ref, o_ref):
            if with_init:
                @pl.when(pl.program_id(0) == 0)
                def _init():
                    o_ref[:] = jnp.zeros_like(o_ref)

            o_ref[:] += jnp.sum(x_ref[:], axis=1, keepdims=True)

        def f(x):
            scope = (layouts.audited("test-acc-revisit") if audited
                     else contextlib.nullcontext())
            with scope:
                return pl.pallas_call(
                    _kern,
                    grid=(2,),
                    in_specs=[pl.BlockSpec((8, 128), lambda j: (0, j))],
                    out_specs=pl.BlockSpec((8, 1), lambda j: (0, 0)),
                    out_shape=_sds((8, 1), jnp.int32),
                    interpret=True)(x)

        return _run(f, [iv(0, 3)], (_sds((8, 256), jnp.int32),))

    def test_dropped_when_init_flips_red(self):
        # stats_block's pl.when(blk == 0) zero-fill, removed: the first
        # visit reads garbage
        _, findings, _, _ = self._acc(with_init=False, audited=True)
        errs = [f for f in findings if f.code == "ref-read-before-init"]
        assert errs and errs[0].severity == "error"

    def test_first_visit_init_proved(self):
        _, findings, _, _ = self._acc(with_init=True, audited=True)
        assert not [f for f in findings
                    if f.code == "ref-read-before-init"]
        assert _gating(findings) == []

    def test_unaudited_revisit_warns(self):
        _, findings, _, _ = self._acc(with_init=True, audited=False)
        ws = [f for f in findings if f.code == "grid-revisit-accumulator"]
        assert ws and ws[0].severity == "warn"

    def test_blockspec_oob_flips_red(self):
        # an index map pointing one block past the operand
        def _kern(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        def f(x):
            return pl.pallas_call(
                _kern,
                grid=(2,),
                in_specs=[pl.BlockSpec((8, 128), lambda j: (0, j))],
                out_specs=pl.BlockSpec((8, 128), lambda j: (0, j + 1)),
                out_shape=_sds((8, 256), jnp.int32),
                interpret=True)(x)

        _, findings, _, _ = _run(f, [iv(0, 3)],
                                 (_sds((8, 256), jnp.int32),))
        errs = [f_ for f_ in findings if f_.code == "blockspec-oob"]
        assert errs and errs[0].severity == "error"

    def test_serial_scan_store_in_bounds(self):
        # the pallas_probe serial formulation: a fori_loop (scan) whose
        # induction index must stay inside the SMEM block and whose
        # table store is bounded by the seeded key range
        K, M, W = 64, 32, 10

        def _kern(keys_ref, rows_ref, tin_ref, tout_ref):
            del tin_ref

            def body(i, _):
                k = keys_ref[i]
                tout_ref[pl.dslice(k, 1), :] = rows_ref[pl.dslice(i, 1), :]
                return 0

            jax.lax.fori_loop(0, keys_ref.shape[0], body, 0)

        def f(table, keys, rows):
            return pl.pallas_call(
                _kern,
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec((M, W), lambda: (0, 0)),
                    pl.BlockSpec((K, W), lambda: (0, 0)),
                ],
                out_specs=pl.BlockSpec((K, W), lambda: (0, 0)),
                out_shape=_sds((K, W), jnp.int32),
                input_output_aliases={2: 0},
                interpret=True)(keys, rows, table)

        shapes = (_sds((K, W), jnp.int32), _sds((M,), jnp.int32),
                  _sds((M, W), jnp.int32))
        outs, findings, _, _ = _run(
            f, [iv(0, 100), iv(0, K - 1), iv(0, 1 << 20)], shapes)
        assert _gating(findings) == []
        # out aliases the table input: its cell is seeded, so the join
        # of table and stored rows — not TOP
        assert outs[0].lo == 0 and outs[0].hi == 1 << 20

    def test_serial_scan_oob_key_flips_red(self):
        # same kernel, keys seeded past the table: the store can escape
        K, M, W = 64, 32, 10

        def _kern(keys_ref, rows_ref, tin_ref, tout_ref):
            del tin_ref

            def body(i, _):
                k = keys_ref[i]
                tout_ref[pl.dslice(k, 1), :] = rows_ref[pl.dslice(i, 1), :]
                return 0

            jax.lax.fori_loop(0, keys_ref.shape[0], body, 0)

        def f(table, keys, rows):
            return pl.pallas_call(
                _kern,
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec((M, W), lambda: (0, 0)),
                    pl.BlockSpec((K, W), lambda: (0, 0)),
                ],
                out_specs=pl.BlockSpec((K, W), lambda: (0, 0)),
                out_shape=_sds((K, W), jnp.int32),
                input_output_aliases={2: 0},
                interpret=True)(keys, rows, table)

        shapes = (_sds((K, W), jnp.int32), _sds((M,), jnp.int32),
                  _sds((M, W), jnp.int32))
        _, findings, _, _ = _run(
            f, [iv(0, 100), iv(0, K), iv(0, 1 << 20)], shapes)
        errs = [f_ for f_ in findings if f_.code == "oob-block-store"]
        assert errs and errs[0].severity == "error"


# --------------------------------------------------------------------------
# the escape hatch: unmodeled kernels degrade loudly, never silently
# --------------------------------------------------------------------------


class TestEscapeHatch:
    def test_unmodeled_primitive_emits_pallas_skipped(self):
        # a DMA kernel: dma_start touches Refs and is outside the cell
        # model — the finding names it, and outputs fall back to TOP
        def _kern(x_ref, o_ref, sem):
            cp = pltpu.make_async_copy(x_ref, o_ref, sem)
            cp.start()
            cp.wait()

        def f(x):
            return pl.pallas_call(
                _kern,
                out_shape=_sds((8, 128), jnp.int32),
                scratch_shapes=[pltpu.SemaphoreType.DMA],
                interpret=True)(x)

        outs, findings, _, _ = _run(f, [iv(0, 7)],
                                    (_sds((8, 128), jnp.int32),))
        skipped = [f_ for f_ in findings if f_.code == "pallas-skipped"]
        assert skipped, "an unmodeled kernel must NOT pass silently"
        assert all(f_.severity == "info" for f_ in skipped)
        assert "dma_start" in skipped[0].message
        assert D.is_top(outs[0], np.int32)  # sound fallback

    def test_modeled_kernel_not_skipped(self):
        _, findings, _, _ = _run(
            kernels.stats_block, seeds.seed_stats_block(),
            _stats_shapes(4, 512))
        assert not [f for f in findings if f.code == "pallas-skipped"]


# --------------------------------------------------------------------------
# differential sanitizer
# --------------------------------------------------------------------------


class TestDiffCheck:
    def test_sanitizer_passes_small_cell(self):
        # the quick-tier sibling of the full-matrix soak below
        r = diffcheck.diff_check(
            diffcheck.cell_by_name("stats_block/r4s512"), n_draws=2)
        assert r["ok"], r["violations"]

    def test_sanitizer_passes_kernel_matrix(self):
        # >= 3 seeded shapes per kernel, concrete always inside abstract
        cells = diffcheck.kernel_cells()
        assert len(cells) >= 3
        for cell in cells:
            r = diffcheck.diff_check(cell, n_draws=3)
            assert r["ok"], (cell.name, r["violations"])

    def test_loop_accumulation_not_underapproximated(self):
        # review-caught soundness regression: a fori_loop accumulating
        # into a ref must NOT 'converge' after one body evaluation —
        # the scan fixpoint widens loop-carried cell state
        def _kern(x_ref, o_ref):
            o_ref[:] = jnp.zeros_like(o_ref)

            def body(i, _):
                o_ref[:] = o_ref[:] + 1
                return 0

            jax.lax.fori_loop(0, 10, body, 0)

        def f(x):
            return pl.pallas_call(
                _kern, out_shape=_sds((8, 128), jnp.int32),
                interpret=True)(x)

        outs, findings, _, _ = _run(f, [iv(0, 7)],
                                    (_sds((8, 128), jnp.int32),))
        assert _gating(findings) == []
        conc = int(np.asarray(f(jnp.zeros((8, 128), jnp.int32))).max())
        assert conc == 10
        assert outs[0].lo <= 0 and outs[0].hi >= conc
        # and the registry keeps a sanitizer sentinel for the pattern
        r = diffcheck.diff_check(
            diffcheck.cell_by_name("synthetic/scan-accumulate"),
            n_draws=2)
        assert r["ok"], r["violations"]

    def test_unsound_rule_mutation_caught(self, monkeypatch):
        # break a transfer rule on purpose: concrete histogram counts
        # escape the (now wrongly tight) abstract cell
        cell = diffcheck.cell_by_name("stats_block/r4s512")
        monkeypatch.setitem(I.RULES, "reduce_sum",
                            lambda eqn, ins, ctx: [D.iv(0)])
        r = diffcheck.diff_check(cell, n_draws=2)
        assert not r["ok"]
        assert any(v["kind"] == "interval" for v in r["violations"])

    def test_draws_respect_declared_bounds(self):
        cell = diffcheck.cell_by_name("stats_block/r4s512")
        rng = np.random.default_rng(0)
        for sds, av in zip(cell.shapes, cell.in_avs):
            a = diffcheck._draw(rng, sds, av)
            assert a.shape == sds.shape
            assert int(np.min(a)) >= av.lo and int(np.max(a)) <= av.hi

    def test_ctr_rows_from_declared_table(self):
        # satellite: no more bare range(6) — the kernel's counter rows
        # and width derive from the layouts.STATS_CTR table
        t = layouts.STATS_CTR
        assert (kernels.CTR_READ, kernels.CTR_WRITE, kernels.CTR_RMW,
                kernels.CTR_ABORT, kernels.CTR_LATSUM,
                kernels.CTR_LATCNT) == tuple(
                    t.row(n) for n in ("read", "write", "rmw", "abort",
                                       "lat_sum", "lat_cnt"))
        assert kernels.CTR_WIDTH == t.width
        t.validate()
        with pytest.raises(ValueError, match="exceed"):
            layouts.RowTable("bad", "", ("a", "b", "c"), 2).validate()
        # and the kernel's packed output really is table-shaped
        code, ctr, hist = kernels.stats_block(
            3, jnp.zeros((2, 256), jnp.int32),
            jnp.zeros((2, 256), jnp.int32), jnp.zeros((2, 256), bool),
            jnp.zeros((2, 256), bool), jnp.zeros((2, 256), bool))
        assert ctr.shape == (2, t.width)
        assert hist.shape == (2, st.LAT_BINS)


# --------------------------------------------------------------------------
# CLI + gate plumbing
# --------------------------------------------------------------------------


class TestKernelsCLI:
    def test_kernels_flag_runs_matrix(self, monkeypatch, capsys):
        import json as json_mod

        from hermes_tpu.analysis import __main__ as cli

        small = diffcheck.cell_by_name("stats_block/r4s512")
        monkeypatch.setattr(diffcheck, "kernel_cells", lambda: [small])
        rc = cli.main(["--kernels", "--json", "--draws", "2"])
        assert rc == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json_mod.loads(line)
        assert doc["ok"] and doc["config"] == "kernels"
        (cell_info,) = doc["cells"].values()
        assert cell_info["sanitizer_ok"] and cell_info["draws"] == 2
        assert cell_info["seconds"] > 0

    def test_gate_kernel_section_red_on_unsound_rule(self, tmp_path,
                                                     monkeypatch):
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "check_analysis_k",
            os.path.join(repo, "scripts", "check_analysis.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "gate_configs", lambda: {})
        small = diffcheck.cell_by_name("stats_block/r4s512")
        monkeypatch.setattr(diffcheck, "kernel_cells", lambda: [small])
        baseline = tmp_path / "B.json"

        def run(*argv):
            monkeypatch.setattr(
                "sys.argv",
                ["check_analysis.py", "--baseline", str(baseline), *argv])
            return mod.main()

        assert run() == 0  # clean kernel matrix passes
        monkeypatch.setitem(I.RULES, "reduce_sum",
                            lambda eqn, ins, ctx: [D.iv(0)])
        assert run() == 1  # sanitizer violation fails the gate
