"""Round-6 op-diet tooling: the StableHLO op census, the per-fusion cost
ledger, the obs-schema JSONL export, and the census budget gate
(hermes_tpu/obs/profile.py; the CI entry is scripts/check_op_census.py).

These pin (a) the census SCHEMA the gate consumes, (b) the gate's
pass/fail semantics, and (c) the tentpole itself: the fused
arbiter+compaction sort lowers to exactly ONE lax.sort per round, one
fewer sparse op than the split program.
"""

import json
import pathlib

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.obs import profile as prof


def _cfg(**over):
    kw = dict(
        n_replicas=4, n_keys=1 << 9, value_words=2, n_sessions=16,
        replay_slots=4, ops_per_session=16, wrap_stream=True,
        arb_mode="sort", chain_writes=4, lane_budget_cfg=12,
        rebroadcast_every=4, replay_scan_every=32,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    kw.update(over)
    return HermesConfig(**kw)


def test_census_schema_and_fused_sort_diet():
    cen = prof.op_census(_cfg())
    for k in prof.SPARSE + prof.COLLECTIVE:
        assert isinstance(cen[k], int) and cen[k] >= 0
    assert cen["sparse_total"] == sum(cen[k] for k in prof.SPARSE)
    assert cen["collective_total"] == sum(cen[k] for k in prof.COLLECTIVE)
    assert cen["collective_total"] == 0  # batched: no wire
    # THE tentpole: one fused arbiter+compaction sort per round; the split
    # fallback pays two — census totals differ by exactly that sort
    assert cen["stablehlo.sort"] == 1
    split = prof.op_census(_cfg(fused_sort=False))
    assert split["stablehlo.sort"] == 2
    assert cen["sparse_total"] == split["sparse_total"] - 1


def test_sharded_census_counts_wire_collectives(cpu_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(cpu_devices[:8]), ("replica",))
    cen = prof.op_census(_cfg(n_replicas=8), "sharded", mesh)
    # round-6 wire diet: INV rows8+meta all_gathers, ONE ack all_to_all,
    # ONE VAL-bit all_gather — epoch words ride the INV meta gather
    assert cen["stablehlo.all_to_all"] == 1
    assert cen["stablehlo.all_gather"] == 3
    assert cen["collective_total"] == 4
    assert cen["stablehlo.sort"] == 1


def test_budget_gate_pass_and_fail_paths():
    cen = {"batched": {"sparse_total": 12, "collective_total": 0,
                       "stablehlo.sort": 1}}
    assert prof.check_budget(cen, {"batched": {"sparse_total": 12}}) == []
    fails = prof.check_budget(cen, {"batched": {"sparse_total": 11}})
    assert len(fails) == 1 and "sparse_total" in fails[0]
    assert "12" in fails[0] and "11" in fails[0]
    # a budgeted engine with no census must FAIL, not silently pass
    assert prof.check_budget({}, {"batched": {"sparse_total": 99}})
    # a budgeted metric the census lacks must fail too
    assert prof.check_budget(cen, {"batched": {"no_such_metric": 1}})


def test_ledger_schema_and_jsonl_export(tmp_path):
    led = prof.round_ledger(_cfg(), time_stages=False)
    assert [r["fusion"] for r in led["stages"]] == [
        "coordinate", "apply_inv", "acks_commit_val"]
    # stage deltas telescope to the full round: the ledger accounts for
    # every sparse op exactly once
    assert (sum(r["sparse_delta"] for r in led["stages"])
            == led["census"]["sparse_total"])
    for r in led["stages"]:
        assert r["ms"] is None  # census-only mode
        lo, hi = r["modeled_ms"]
        assert lo == round(r["sparse_delta"] * prof.COST_LO, 2)
        assert hi == round(r["sparse_delta"] * prof.COST_HI, 2)
    assert led["round_ms"] is None
    assert led["shape"]["fused_sort"] is True

    p = tmp_path / "prof.jsonl"
    prof.export_profile(str(p), prof.ledger_records(led))
    recs = [json.loads(line) for line in p.read_text().splitlines()]
    assert len(recs) == 1 + len(led["stages"])
    # PR-1 obs run-log schema: every record stamped with t + kind
    assert all(r["kind"] == "profile" and "t" in r for r in recs)
    assert [r["t"] for r in recs] == sorted(r["t"] for r in recs)
    assert recs[0]["record"] == "round"
    assert recs[0]["census"]["sparse_total"] == led["census"]["sparse_total"]
    assert {r["record"] for r in recs[1:]} == {"fusion"}


def test_ledger_timed_smoke():
    """time_stages=True runs the honest-timing protocol (functional smoke
    on CPU — the numbers are only meaningful on the chip)."""
    led = prof.round_ledger(_cfg(), rounds=3, reps=1, time_stages=True)
    assert led["round_ms"] is not None and led["round_ms"] > 0
    assert all(r["ms"] is not None for r in led["stages"])


def test_repo_budget_file_matches_diet():
    """The checked-in OP_BUDGET.json must gate both engines at the round-6
    diet ceilings ISSUE 2 committed to (batched <= 12, sharded <= 15
    sparse / <= 5 collectives) — loosening it is a conscious, reviewed
    act."""
    root = pathlib.Path(__file__).resolve().parent.parent
    with open(root / "OP_BUDGET.json") as f:
        budget = {k: v for k, v in json.load(f).items()
                  if not k.startswith("_")}
    assert budget["batched"]["sparse_total"] <= 12
    assert budget["sharded"]["sparse_total"] <= 15
    assert budget["sharded"]["collective_total"] <= 5
    assert budget["batched"]["stablehlo.sort"] == 1
    # and the gate predicate accepts a census exactly at the ceilings
    at_ceiling = {eng: dict(lim) for eng, lim in budget.items()}
    assert prof.check_budget(at_ceiling, budget) == []
