"""Adversarial-schedule simulation (SURVEY.md §4.2): message delay, loss,
duplication, replica stall + membership change + rejoin — every run gated by
the linearizability checker.  This is the deterministic race exploration the
reference never had (SURVEY.md §5.2)."""

import hashlib

import numpy as np

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.runtime import Runtime
from hermes_tpu.transport.sim import SimTransport

from helpers import get


def h(*args) -> int:
    return int.from_bytes(hashlib.blake2b(repr(args).encode(), digest_size=4).digest(), "little")


def chaotic_schedule(seed, p_drop=0.15, p_dup=0.1, max_delay=3, until=10_000):
    """Deterministic pseudo-random drop/dup/delay per (kind, src, dst, step);
    clean after ``until`` so runs can drain."""

    def sched(kind, src, dst, step):
        if step >= until or src == dst:  # keep self-delivery clean
            return [step]
        x = h(seed, kind, src, dst, step)
        if x % 1000 < p_drop * 1000:
            return []
        d = (x // 7) % (max_delay + 1)
        out = [step + d]
        if (x // 1000) % 1000 < p_dup * 1000:
            out.append(step + (x // 31) % (max_delay + 1))
        return out

    return sched


def cfg_small(seed, rmw_frac=0.5, **kw):
    # rmw_frac > 0 by default: the RMW conflict path MUST be exercised under
    # adversarial schedules (a delayed conflicting INV once hid a lost-update
    # bug that lockstep runs could never trigger — see
    # test_rmw_delayed_conflict_aborts).
    base = dict(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=8, ops_per_session=12,
        replay_age=6,
        workload=WorkloadConfig(read_frac=0.5, rmw_frac=rmw_frac, seed=seed),
    )
    base.update(kw)
    return HermesConfig(**base)


def run_checked(cfg, schedule, max_steps=600):
    rt = Runtime(
        cfg, backend="sim", record=True,
        transport=SimTransport(cfg.n_replicas, schedule),
    )
    assert rt.drain(max_steps), "did not drain"
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    return rt


def test_chaos_drop_dup_delay():
    # 12 seeds (round 4 doubled to 6, round 5 doubled again): each is a
    # distinct adversarial interleaving of drops/dups/delays over the full
    # op mix
    for seed in range(12):
        rt = run_checked(cfg_small(30 + seed), chaotic_schedule(seed, until=300))
        c = rt.counters()
        assert c["n_write"] > 0


def test_val_blackout_replay_recovers():
    """Drop ALL VALs for a window: keys stick Invalid at followers until the
    replay scan re-drives them (SURVEY.md §3.4).  The checker must still
    pass and the run must drain."""

    def sched(kind, src, dst, step):
        if kind == "val" and step < 30 and src != dst:
            return []
        return [step]

    cfg = cfg_small(40, replay_age=5)
    rt = run_checked(cfg, sched)
    # replay must actually have fired (some key went Invalid past the age)
    # - witnessed indirectly: run drained with VALs destroyed for 30 steps


def test_inv_starvation_one_direction():
    """INVs from replica 0 to replica 2 delayed heavily: commits by 0 stall
    (need 2's ack) but eventually land; linearizability holds."""

    def sched(kind, src, dst, step):
        if kind == "inv" and src == 0 and dst == 2 and step < 40:
            return [step + 5]
        return [step]

    run_checked(cfg_small(41), sched)


def test_rmw_delayed_conflict_aborts():
    """Regression (conflict-nack acks): two RMWs on the same key from the
    same base version, with the higher-ts INV delayed past the lower RMW's
    would-be commit.  Without the ok-flag on ACKs both committed reading the
    same old value (lost update); with it the lower-ts RMW aborts on the
    nack from the conflicting coordinator."""
    import numpy as np
    from hermes_tpu.core import state as st_mod, types as tt

    cfg = HermesConfig(
        n_replicas=3, n_keys=8, n_sessions=1, replay_slots=2, ops_per_session=1,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    # replicas 0 and 1 both RMW key 0; replica 2 idle
    op = np.zeros((3, 1, 1), np.int32)
    op[0, 0, 0] = tt.OP_RMW
    op[1, 0, 0] = tt.OP_RMW
    key = np.zeros((3, 1, 1), np.int32)
    stream = st_mod.OpStream(op=op, key=key)

    def sched(kind, src, dst, step):
        if kind == "inv" and src == 1 and dst == 0 and step < 3:
            return [step + 2]  # hide the higher-ts INV from replica 0
        return [step]

    rt = Runtime(cfg, backend="sim", record=True,
                 transport=SimTransport(3, sched), stream=stream)
    assert rt.drain(100)
    c = rt.counters()
    assert int(c["n_rmw"]) == 1 and int(c["n_abort"]) == 1, c
    v = rt.check()
    assert v.ok, (v.failures, v.undecided)


def test_stall_remove_rejoin_checked():
    """Config 4+5 shaped (BASELINE.json:10-11): replica stalls mid-workload,
    lease expiry removes it (quorum shrinks, writes unblock), then it rejoins
    with state transfer; the whole history must linearize."""
    cfg = cfg_small(42, n_replicas=4, ops_per_session=20, replay_age=5)
    rt = Runtime(cfg, backend="sim", record=True, transport=SimTransport(4))
    rt.run(5)
    rt.freeze(2)
    rt.run(cfg.lease_steps)  # stalled but still in membership: writes block
    rt.remove(2)  # lease expired -> removed; quorum = {0,1,3}
    rt.run(30)
    rt.join(2, from_replica=0)  # state transfer + readmit
    assert rt.drain(600)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    # converged: all replicas identical and Valid
    state = get(rt.rs.table.state)
    assert (state == t.VALID).all()
    ver = get(rt.rs.table.ver)
    val = get(rt.rs.table.val)
    for r in range(1, 4):
        np.testing.assert_array_equal(ver[0], ver[r])
        np.testing.assert_array_equal(val[0], val[r])
