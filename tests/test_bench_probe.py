"""bench.py outage behavior (round-2 verdict item 2): when the default
backend cannot initialize, the bench must fail fast with ONE diagnosable
JSON line and a non-zero rc — never hang into the driver's timeout."""

import json
import os
import subprocess
import sys

import numpy as np

import bench


def test_commit_latency_fields_are_honest_bounds():
    """Round-15 satellite: BENCH_r05 reported p50/p99_commit_rounds = 0
    (legitimate: commits land in-round) but derived 'p50_commit_us_est'
    fields that just echoed the amortized dispatch time as if it were a
    measured percentile.  The fields are now explicit upper bounds: the
    *_us_ub value is (rounds+1) * round_us (1-round histogram
    resolution), the note names the bound semantics, and the old _est
    keys are gone."""
    # degenerate-at-zero histogram: every commit in its issue round
    hist = np.zeros(32, np.int64)
    hist[0] = 1000
    f = bench.commit_latency_fields(hist, step_us=28609.0)
    assert f["p50_commit_rounds"] == 0 and f["p99_commit_rounds"] == 0
    assert f["p50_commit_us_ub"] == round(1 * 28609.0, 1)
    assert "UPPER BOUNDS" in f["commit_us_note"]
    assert not any(k.endswith("_us_est") for k in f)

    # a spread histogram keeps the bound one round above the percentile
    hist = np.zeros(32, np.int64)
    hist[0], hist[3], hist[9] = 50, 49, 1
    f = bench.commit_latency_fields(hist, step_us=100.0)
    assert f["p50_commit_rounds"] == 0
    assert f["p99_commit_rounds"] == 3
    assert f["p99_commit_us_ub"] == round(4 * 100.0, 1)

    # empty histogram (zero commits): bounds are None, never a crash
    f = bench.commit_latency_fields(np.zeros(32, np.int64), step_us=5.0)
    assert f["p50_commit_rounds"] is None
    assert f["p50_commit_us_ub"] is None and f["p99_commit_us_ub"] is None


def test_probe_skips_on_cpu(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    ok, info = bench.probe_backend(0.001)  # would time out if it ran
    assert ok and info == "cpu"


def test_probe_times_out_on_hang(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    ok, info = bench.probe_backend(
        1.0, cmd=[sys.executable, "-c", "import time; time.sleep(60)"])
    assert not ok
    assert "did not complete within 1s" in info


def test_probe_reports_child_failure(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    ok, info = bench.probe_backend(
        30.0,
        cmd=[sys.executable, "-c",
             "import sys; print('boom: no backend', file=sys.stderr); "
             "sys.exit(3)"])
    assert not ok
    assert "rc=3" in info and "boom: no backend" in info


def test_bench_main_outage_contract():
    """End to end: bench.py under an uninitializable platform prints one
    JSON error line on stdout and exits non-zero, quickly."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"  # not installed here -> init fails fast
    env["PALLAS_AXON_POOL_IPS"] = ""  # never touch the real chip from tests
    # bench's default probe budget (180 s) exceeds this test's own kill
    # timer: if the probe child BLOCKS instead of failing fast (seen when
    # /tmp/libtpu_lockfile is contended by a sibling test's subprocess),
    # the contract line must still beat our timeout — cap the probe budget
    env["HERMES_BENCH_PROBE_TIMEOUT"] = "45"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=120, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert p.returncode == 1, p.stderr
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["metric"] == "committed_writes_per_sec"
    assert rec["value"] == 0.0 and rec["vs_baseline"] == 0.0
    # "backend init failed rc=..." when the probe child fails fast;
    # "backend init did not complete within ..." when it wedges on a
    # contended libtpu lockfile — both are the diagnosable contract
    assert "backend init" in rec["error"]


def test_entry_probe_fails_fast_on_dead_backend():
    """entry() under an uninitializable default backend raises a diagnosable
    RuntimeError in seconds (round-3 verdict weak #1: the rc=124 signature
    was a harness hanging in backend init via entry() before any repo
    logic)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"  # not installed here -> init fails fast
    env["PALLAS_AXON_POOL_IPS"] = ""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo
    code = ("import __graft_entry__ as g\n"
            "try:\n"
            "    g.entry()\n"
            "except RuntimeError as e:\n"
            "    print('ENTRY_GUARDED:', e)\n")
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, env=env, cwd=repo)
    assert p.returncode == 0, p.stderr
    assert "ENTRY_GUARDED:" in p.stdout
    assert "backend unavailable" in p.stdout
    # heartbeats localize the hang point for a future red tail
    assert "entry(): entered" in p.stdout
    assert "probing default backend" in p.stdout


def test_main_records_dryrun_before_entry_outage():
    """python __graft_entry__.py under a dead default backend must still
    complete the multi-chip dryrun (it never touches the default backend in
    the parent) BEFORE the entry() compile check fails fast — so the driver
    artifact of record carries the multi-chip green even under chip outage."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo
    p = subprocess.run([sys.executable, "__graft_entry__.py"],
                       capture_output=True, text=True, timeout=300, env=env,
                       cwd=repo)
    assert "dryrun_multichip subprocess ok" in p.stdout, p.stdout + p.stderr
    assert "dryrun_multichip ok" in p.stdout
    # the dryrun green precedes the entry failure in the recorded tail
    assert p.returncode != 0
    assert "backend unavailable" in (p.stdout + p.stderr)
