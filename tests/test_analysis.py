"""Tests for the static jaxpr invariant analyzer (hermes_tpu/analysis).

Covers the ISSUE-3 acceptance points: interval-domain unit tests, a
deliberately overflowing packed key is caught, an injective permutation
scatter is NOT flagged (false-positive guard), the gate's
pass/fail/--update paths, and the seeded mutations (widen n_keys past
the band shift; drop the scatter audits) flip the analysis red.  Plus
the satellite regressions: the byte<->word codec round-trip and the
rotation-overflow fix in faststep.
"""

import contextlib
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hermes_tpu import analysis as ana
from hermes_tpu.analysis import domain as D
from hermes_tpu.analysis import interp as I
from hermes_tpu.analysis.domain import iv
from hermes_tpu.analysis.passes import (
    BitPackPass, DtypePromotionPass, ScatterHazardPass,
    ShardingConsistencyPass, default_passes)
from hermes_tpu.config import HermesConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import layouts
from hermes_tpu.core import types as t

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------
# domain unit tests
# --------------------------------------------------------------------------


class TestDomain:
    def test_const_mask_is_exact(self):
        # 1 << 20 has exactly one possible bit — what proves WIN | rank
        assert iv(1 << 20).ones == 1 << 20
        assert iv(0).ones == 0
        assert iv(5).ones == 5

    def test_interval_mask(self):
        assert iv(0, 127).ones == 0x7F
        assert iv(0, 128).ones == 0xFF
        assert iv(-1, 5).ones == -1  # negative-capable: unconstrained

    def test_add_disjoint_is_or(self):
        # replica * K + key with disjoint bits keeps the exact mask
        a = iv(0, 3 << 16, ones=3 << 16)
        b = iv(0, 0xFFFF)
        r = D.add(a, b)
        assert r.ones == (3 << 16) | 0xFFFF
        assert (r.lo, r.hi) == (0, (3 << 16) + 0xFFFF)

    def test_shl_keeps_low_bits_clear(self):
        r = D.shl(iv(0, 2047), iv(10))
        assert r.ones & 0x3FF == 0

    def test_or_and_masks(self):
        assert D.or_(iv(0, 7), iv(8, 8)).ones == 0xF
        assert D.and_(D.top(np.int32), iv(0xFF, 0xFF)).hi == 0xFF

    def test_rem_positive_divisor(self):
        r = D.rem(iv(0, 10**6), iv(64))
        assert (r.lo, r.hi) == (0, 63)
        r = D.rem(iv(-5, 10), iv(64))  # sign follows dividend
        assert r.lo < 0 <= r.hi

    def test_clamp_wrap_flag(self):
        _, wrapped = D.clamp(iv(0, 1 << 40), np.int32)
        assert wrapped
        av, wrapped = D.clamp(iv(0, 100), np.int32)
        assert not wrapped and av.hi == 100

    def test_join(self):
        j = D.join(iv(0, 10), iv(100, 200))
        assert (j.lo, j.hi) == (0, 200)

    def test_sum_n(self):
        assert D.sum_n(iv(0, 10), 5).hi == 50
        assert D.sum_n(iv(0, 10), 0).hi == 0

    def test_and_or_sound_for_negatives(self):
        # -5 & -3 == -7 (below both); 10 | 5 == 15 (above both)
        r = D.clamp(D.and_(iv(-5, -3), iv(-5, -3)), np.int32)[0]
        assert r.lo <= -7
        r = D.clamp(D.or_(iv(-1, 10), iv(0, 5)), np.int32)[0]
        assert r.hi >= 15
        # the mask restore stays precise: TOP & const mask
        assert D.and_(D.top(np.int32), iv(0xFF)).hi == 0xFF

    def test_bool_clamp_widens_never_narrows(self):
        # `not` on a bool must not collapse to a false constant
        av, _ = D.clamp(D.not_(iv(0, 1)), np.bool_)
        assert (av.lo, av.hi) == (0, 1)


# --------------------------------------------------------------------------
# interpreter: bounds propagate through real traced programs
# --------------------------------------------------------------------------


def _run(fn, in_avs, shapes, passes=None, mesh_axes=None, donated=None):
    jx = jax.make_jaxpr(fn)(*shapes)
    ctx = I.Ctx(passes=passes or [], mesh_axes=mesh_axes, donated=donated)
    outs = I.eval_jaxpr(jx.jaxpr, in_avs, ctx, consts=list(jx.consts))
    return outs, ctx, jx


class TestInterp:
    def test_basic_bounds(self):
        s = jax.ShapeDtypeStruct((8,), jnp.int32)

        def f(x, y):
            return (x + y) * 2

        outs, _, _ = _run(f, [iv(0, 10), iv(0, 5)], (s, s))
        assert (outs[0].lo, outs[0].hi) == (0, 30)

    def test_remainder_contract(self):
        s = jax.ShapeDtypeStruct((8,), jnp.int32)
        outs, _, _ = _run(lambda x: x % 64, [D.top(np.int32)], (s,))
        assert (outs[0].lo, outs[0].hi) == (0, 63)

    def test_negative_index_normalization_refined(self):
        tbl = jax.ShapeDtypeStruct((4096,), jnp.int32)
        idx = jax.ShapeDtypeStruct((16,), jnp.int32)
        p = ScatterHazardPass()
        _run(lambda t_, i: t_[i], [D.top(np.int32), iv(0, 4095)],
             (tbl, idx), passes=[p])
        assert not [f for f in p.results() if f.severity != "info"]

    def test_rotation_provably_bounded(self):
        # the faststep._rotated mod-first formula stays in [0, n)
        s = jax.ShapeDtypeStruct((64,), jnp.int32)
        st_ = jax.ShapeDtypeStruct((), jnp.int32)
        outs, _, _ = _run(lambda i, stp: fst._rotated(i, stp, 64),
                          [iv(0, 63), iv(0, layouts.MAX_STEPS - 1)],
                          (s, st_))
        assert (outs[0].lo, outs[0].hi) == (0, 63)


# --------------------------------------------------------------------------
# bit-pack pass
# --------------------------------------------------------------------------


class TestBitPack:
    def test_overflowing_pack_is_caught(self):
        # a 29-bit shift with a sub field that can reach the band bits
        s = jax.ShapeDtypeStruct((16,), jnp.int32)

        def f(band, sub):
            return (band << 29) | sub

        p = BitPackPass()
        _run(f, [iv(0, 2), iv(0, 1 << 29)], (s, s), passes=[p])
        errs = [f_ for f_ in p.results() if f_.severity == "error"]
        assert any(f_.code == "pack-overlap" for f_ in errs)

    def test_disjoint_pack_proved(self):
        s = jax.ShapeDtypeStruct((16,), jnp.int32)

        def f(band, sub):
            return (band << 29) | sub

        p = BitPackPass()
        _run(f, [iv(0, 2), iv(0, (1 << 29) - 1)], (s, s), passes=[p])
        assert not p.results()
        assert p.n_proved >= 2  # the shift and the or

    def test_negative_operand_caught(self):
        s = jax.ShapeDtypeStruct((16,), jnp.int32)
        p = BitPackPass()
        _run(lambda x: (jnp.int32(1) << 20) | x, [iv(-5, 10)], (s,),
             passes=[p])
        assert any(f_.code == "pack-negative-operand"
                   for f_ in p.results() if f_.severity == "error")

    def test_bitmap_union_not_flagged(self):
        # overlapping ack-bitmap union: NOT a pack site, never flagged
        s = jax.ShapeDtypeStruct((16,), jnp.int32)
        p = BitPackPass()
        _run(lambda a, b: a | b, [iv(0, 7), iv(0, 7)], (s, s), passes=[p])
        assert not p.results()

    def test_not_mask_pack_overlap_caught(self):
        # soundness regression: `~frozen` used to abstract to constant
        # False, silently proving a deliberately overlapping epoch|alive
        # pack clean
        se = jax.ShapeDtypeStruct((8,), jnp.int32)
        sb = jax.ShapeDtypeStruct((8,), jnp.bool_)

        def f(epoch, frozen):
            return (epoch << 0) | (~frozen).astype(jnp.int32)

        p = BitPackPass()
        _run(f, [iv(0, 3), iv(0, 1)], (se, sb), passes=[p])
        assert any(f_.code == "pack-overlap" and f_.severity == "error"
                   for f_ in p.results())

    def test_audited_pack_downgrades_to_info(self):
        s = jax.ShapeDtypeStruct((16,), jnp.int32)

        def f(x):
            with layouts.audited("test-known-bound"):
                return (x << 29) | jnp.int32(7)

        p = BitPackPass()
        _run(f, [D.top(np.int32)], (s,), passes=[p])
        res = p.results()
        assert res and all(f_.severity == "info" for f_ in res)
        assert all(f_.audit == "test-known-bound" for f_ in res)


# --------------------------------------------------------------------------
# dtype pass
# --------------------------------------------------------------------------


class TestDtype:
    def test_wrapping_convert_flagged(self):
        s = jax.ShapeDtypeStruct((8,), jnp.int8)
        p = DtypePromotionPass()
        # int8 -> uint32 astype sign-extends/wraps negatives silently
        _run(lambda x: x.astype(jnp.uint32), [D.top(np.int8)], (s,),
             passes=[p])
        assert any(f_.code == "implicit-wrap-convert" for f_ in p.results())

    def test_bitcast_is_explicit(self):
        s = jax.ShapeDtypeStruct((8,), jnp.int8)
        p = DtypePromotionPass()
        _run(lambda x: jax.lax.bitcast_convert_type(x, jnp.uint8),
             [D.top(np.int8)], (s,), passes=[p])
        assert not p.results()

    def test_value_preserving_convert_proved(self):
        s = jax.ShapeDtypeStruct((8,), jnp.uint8)
        p = DtypePromotionPass()
        _run(lambda x: x.astype(jnp.uint32), [iv(0, 255)], (s,), passes=[p])
        assert not p.results() and p.n_proved >= 1

    def test_float_in_integer_round_warns(self):
        s = jax.ShapeDtypeStruct((8,), jnp.int32)
        p = DtypePromotionPass(allow_float=False)
        _run(lambda x: (x.astype(jnp.float32) * 0.5).astype(jnp.int32),
             [iv(0, 10)], (s,), passes=[p])
        assert any(f_.code in ("float-in-round", "float-to-int")
                   for f_ in p.results())


# --------------------------------------------------------------------------
# scatter pass
# --------------------------------------------------------------------------


class TestScatter:
    def test_injective_permutation_scatter_not_flagged(self):
        # false-positive guard: a permutation scatter annotated
        # unique_indices=True must not gate
        s = jax.ShapeDtypeStruct((64,), jnp.int32)
        p = ScatterHazardPass()

        def f(perm, vals):
            return jnp.zeros((64,), jnp.int32).at[perm].set(
                vals, unique_indices=True, mode="drop")

        _run(f, [iv(0, 63), iv(0, 100)], (s, s), passes=[p])
        assert not [f_ for f_ in p.results() if f_.severity != "info"]

    def test_max_scatter_not_flagged(self):
        s = jax.ShapeDtypeStruct((64,), jnp.int32)
        p = ScatterHazardPass()
        _run(lambda i, v: jnp.zeros((64,), jnp.int32).at[i].max(
            v, mode="drop"), [iv(0, 63), iv(0, 100)], (s, s), passes=[p])
        assert not p.results()

    def test_unannotated_set_scatter_warns(self):
        s = jax.ShapeDtypeStruct((64,), jnp.int32)
        p = ScatterHazardPass()
        _run(lambda i, v: jnp.zeros((64,), jnp.int32).at[i].set(
            v, mode="drop"), [iv(0, 63), iv(0, 100)], (s, s), passes=[p])
        assert any(f_.code == "scatter-set-not-injective"
                   and f_.severity == "warn" for f_ in p.results())

    def test_promised_oob_index_error(self):
        tbl = jax.ShapeDtypeStruct((128,), jnp.int32)
        idx = jax.ShapeDtypeStruct((8,), jnp.int32)
        p = ScatterHazardPass()
        _run(lambda t_, i: t_.at[i].get(mode="promise_in_bounds"),
             [D.top(np.int32), iv(0, 1 << 20)], (tbl, idx), passes=[p])
        assert any(f_.code == "oob-promised-index" and
                   f_.severity == "error" for f_ in p.results())

    def test_donation_wasted_warns(self):
        s = jax.ShapeDtypeStruct((64,), jnp.int32)
        p = ScatterHazardPass()
        # donated arg 0 has no same-shaped output to alias
        _, ctx, jx = _run(lambda x: jnp.sum(x), [iv(0, 10)], (s,),
                          passes=[p], donated={0})
        p.check_donation(ctx, jx.jaxpr)
        assert any(f_.code == "donation-wasted" for f_ in p.results())


# --------------------------------------------------------------------------
# sharding pass
# --------------------------------------------------------------------------


def _tiny_sharded_fn():
    from jax.sharding import Mesh, PartitionSpec as P

    from hermes_tpu.core import compat

    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))

    def body(x):
        return jax.lax.all_gather(x[0], "replica", axis=0, tiled=False)

    return compat.shard_map(body, mesh=mesh, in_specs=(P("replica"),),
                            out_specs=P("replica"))


class TestSharding:
    def test_declared_axes_clean(self):
        s = jax.ShapeDtypeStruct((8, 4), jnp.int32)
        p = ShardingConsistencyPass()
        _run(_tiny_sharded_fn(), [iv(0, 10)], (s,), passes=[p],
             mesh_axes={"replica": 8})
        assert not p.results() and p.n_proved >= 1

    def test_wrong_declared_axis_flagged(self):
        s = jax.ShapeDtypeStruct((8, 4), jnp.int32)
        p = ShardingConsistencyPass()
        _run(_tiny_sharded_fn(), [iv(0, 10)], (s,), passes=[p],
             mesh_axes={"shard": 8})
        assert any(f_.code == "unknown-mesh-axis" for f_ in p.results())

    def test_collective_in_batched_engine_flagged(self):
        s = jax.ShapeDtypeStruct((8, 4), jnp.int32)
        p = ShardingConsistencyPass()
        _run(_tiny_sharded_fn(), [iv(0, 10)], (s,), passes=[p],
             mesh_axes={})  # batched declaration: no collectives allowed
        assert any(f_.code == "collective-in-batched-engine"
                   for f_ in p.results())


# --------------------------------------------------------------------------
# whole-engine analysis: clean engines, red mutations
# --------------------------------------------------------------------------


def _small_cfg(**kw):
    base = dict(n_replicas=3, n_keys=1 << 12, n_sessions=16,
                replay_slots=8, ops_per_session=8)
    base.update(kw)
    return HermesConfig(**base)


def _gating(reports):
    return [f for r in reports for f in r["findings"]
            if f.severity in ana.GATING]


class TestEngineAnalysis:
    def test_batched_race_clean(self):
        reports = ana.analyze_config(_small_cfg(), engines=("batched",))
        assert _gating(reports) == []

    def test_fused_and_split_clean_batched_and_sharded(self):
        cfg = _small_cfg(arb_mode="sort", chain_writes=4, lane_budget_cfg=8)
        reports = ana.analyze_config(cfg)  # both engines, fused + split
        assert {r["engine"] for r in reports} == {
            "batched/fused", "batched/split", "sharded/fused",
            "sharded/split"}
        assert _gating(reports) == []

    def test_audited_assumptions_visible(self):
        reports = ana.analyze_config(_small_cfg(), engines=("batched",))
        audits = {f.audit for r in reports for f in r["findings"]
                  if f.severity == "info" and f.audit}
        assert "pts-mint-ver-bounded-by-watermark" in audits
        assert "winner-row-dup-writes-identical" in audits

    def test_mutation_wide_keys_flips_red(self):
        # widen n_keys past the INV pkf key field (bypassing config
        # validation): the wire-header pack must flag the alias
        cfg = _small_cfg()
        object.__setattr__(cfg, "n_keys", 1 << 30)
        rep = ana.analyze_program(ana.trace_program(cfg, "sharded"))
        errs = [f for f in rep["findings"] if f.severity == "error"]
        assert any(f.code == "pack-overlap" for f in errs)

    def test_mutation_wide_keys_trips_fused_assert(self):
        # the fused sort key's trace-time capacity assert (satellite):
        # band cannot collide with a max-sub value
        cfg = _small_cfg(arb_mode="sort")
        object.__setattr__(cfg, "n_keys", 1 << 30)
        assert cfg.use_fused_sort
        with pytest.raises(AssertionError, match="fused sort key overflow"):
            ana.trace_program(cfg, "batched")

    def test_mutation_drop_audit_flips_red(self, monkeypatch):
        monkeypatch.setattr(layouts, "audited",
                            lambda tag: contextlib.nullcontext())
        reports = ana.analyze_config(_small_cfg(), engines=("batched",))
        gating = _gating(reports)
        assert any(f.code == "scatter-set-not-injective" for f in gating)


# --------------------------------------------------------------------------
# findings export + gate pass/fail/--update
# --------------------------------------------------------------------------


def _load_gate_module():
    spec = importlib.util.spec_from_file_location(
        "check_analysis", os.path.join(REPO, "scripts", "check_analysis.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestGate:
    def test_export_findings_obs_schema(self, tmp_path):
        reports = ana.analyze_config(_small_cfg(), engines=("batched",))
        out = tmp_path / "findings.jsonl"
        ana.export_findings(str(out), reports)
        recs = [json.loads(line) for line in out.read_text().splitlines()]
        assert recs
        assert all("t" in r and r["kind"] == "analysis" for r in recs)
        assert recs[0]["record"] == "program"
        kinds = {r["record"] for r in recs}
        assert kinds <= {"program", "finding"}

    def test_key_counts_and_diff(self):
        f1 = ana.Finding(pass_name="bitpack", code="pack-overlap",
                         severity="error", message="m", file="f.py",
                         fn="g", op="or", engine="batched/fused")
        f2 = ana.Finding(pass_name="scatter", code="x", severity="info",
                         message="m", engine="batched/fused")
        f1.engine = f"bench:{f1.engine}"  # the gate's config stamp
        counts = ana.key_counts([f1, f2])
        assert len(counts) == 1  # info never gates
        (k, c), = counts.items()
        assert k.startswith("bench:batched/fused|bitpack|pack-overlap")
        new, stale = ana.diff_baseline(counts, {})
        assert new == counts and not stale
        new, stale = ana.diff_baseline(counts, dict(counts))
        assert not new and not stale
        new, stale = ana.diff_baseline({}, dict(counts))
        assert not new and stale == counts

    def test_gate_script_pass_fail_update(self, tmp_path, monkeypatch):
        mod = _load_gate_module()
        monkeypatch.setattr(
            mod, "gate_configs",
            lambda: {"tiny": _small_cfg(n_replicas=3)})
        baseline = tmp_path / "BASELINE.json"

        def run(*argv):
            # --no-kernels: the kernel matrix + sanitizer path has its
            # own gate test (tests/test_pallas_analysis.py); this one
            # stays focused on the engine baseline machinery
            monkeypatch.setattr(
                "sys.argv",
                ["check_analysis.py", "--baseline", str(baseline),
                 "--no-kernels", *argv])
            return mod.main()

        # pass: clean engines, empty baseline
        assert run() == 0
        # fail: drop the audits -> new warn findings, not baselined
        monkeypatch.setattr(layouts, "audited",
                            lambda tag: contextlib.nullcontext())
        assert run() == 1
        # --update grandfathers them, then the gate passes again
        assert run("--update") == 0
        doc = json.loads(baseline.read_text())
        assert doc["grandfathered"]
        assert all(k.startswith("tiny:") for k in doc["grandfathered"])
        assert run() == 0


# --------------------------------------------------------------------------
# satellite regressions in faststep
# --------------------------------------------------------------------------


class TestFaststepRegressions:
    def test_codec_round_trip_negatives(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randint(-2**31, 2**31, size=(5, 7),
                                    dtype=np.int64).astype(np.int32))
        b = fst._i32_to_bank(x)
        assert b.dtype == jnp.int8
        y = fst._bank_to_i32(b)
        assert (x == y).all()

    def test_rotation_congruent_and_overflow_safe(self):
        for n in (5, 64, 16640):
            idx = jnp.arange(n, dtype=jnp.int32)
            for s in (0, 1, 7, 1000, 123457):
                old = (idx + s * layouts.ROT_STRIDE) % n  # pre-fix formula
                assert (fst._rotated(idx, jnp.int32(s), n) == old).all()
        # past the old formula's int32 overflow point the fix stays a
        # bijection in [0, n) while step*127 would have wrapped negative
        big = jnp.int32(17_000_000)
        assert int(big) * layouts.ROT_STRIDE > 2**31  # the old hazard
        r = fst._rotated(jnp.arange(64, dtype=jnp.int32), big, 64)
        assert (r >= 0).all() and (r < 64).all()
        assert len(set(np.asarray(r).tolist())) == 64

    def test_run_issue_rank_clip_is_noop_on_issuers(self):
        # the analysis-driven clip must not change which lanes issue or
        # their chain ranks (bench-shape semantics regression)
        cfg = _small_cfg(arb_mode="sort", chain_writes=4)
        first = jnp.asarray([[True, False, False, True, False, False]])
        in_run = jnp.asarray([[True, True, True, True, True, False]])
        sop = jnp.full((1, 6), t.OP_WRITE)
        pos = jnp.arange(6, dtype=jnp.int32)[None]
        issue, rank = fst._run_issue(cfg, first, in_run, sop, pos)
        assert issue.tolist() == [[True, True, True, True, True, False]]
        assert rank.tolist() == [[0, 1, 2, 0, 1, 0]]

    def test_layouts_consistency(self):
        # the declared table and the runtime constants cannot drift
        assert fst.INV_KEY_MASK == (1 << 29) - 1
        assert int(fst.INV_FRESH) == 1 << 29
        assert int(fst.INV_VALID) == 1 << 30
        assert fst.PTS_FC_BITS == 10
        assert HermesConfig().max_key_versions == layouts.MAX_KEY_VERSIONS
        for lay in layouts.ALL:
            lay.validate()

    def test_fused_drive_still_drains(self):
        cfg = _small_cfg(arb_mode="sort", chain_writes=4,
                         ops_per_session=16, n_sessions=8)
        from hermes_tpu.workload import ycsb

        fs = fst.init_fast_state(cfg)
        stream = fst.prep_stream(jax.tree.map(jnp.asarray,
                                              ycsb.make_streams(cfg)))
        step = fst.build_fast_batched(cfg)
        for s in range(60):
            fs, _ = step(fs, stream, fst.make_fast_ctl(cfg, s))
        assert (fs.sess.status == t.S_DONE).all()
        assert ((fs.table.sst & 7) == t.VALID).all()
