"""Linearizability checker unit tests (SURVEY.md §4.1): known-good and
known-bad synthetic histories, both through the ts-witness fast path and the
exact Wing&Gong search."""

from hermes_tpu.checker.history import INF, Op
from hermes_tpu.checker.linearizability import check_history, check_key

INIT = (0, -1)  # initial uid for key 0 under the default convention (k, -1)


def K(ops, **kw):
    return check_key(0, ops, (0, -1), **kw)


def test_empty_and_reads_of_initial():
    assert K([]).ok
    assert K([Op("r", 0, 0, 2, ruid=INIT), Op("r", 0, 4, 6, ruid=INIT)]).ok


def test_simple_write_then_read():
    h = [
        Op("w", 0, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 2, 4, ruid=(1, 0)),
    ]
    assert K(h).ok


def test_stale_read_after_write_committed_fails():
    """Read starts after W's response yet observes the initial value —
    the classic stale-read violation."""
    h = [
        Op("w", 0, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 2, 4, ruid=INIT),
    ]
    assert not K(h).ok


def test_new_old_inversion_fails():
    """Two sequential reads observing new-then-old is not atomic."""
    h = [
        Op("w", 0, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("w", 0, 2, 3, wuid=(2, 0), ts=(2, 256)),
        Op("r", 0, 4, 5, ruid=(2, 0)),
        Op("r", 0, 6, 7, ruid=(1, 0)),
    ]
    assert not K(h).ok


def test_concurrent_reads_either_order_ok():
    """Overlapping reads may observe either side of a concurrent write."""
    h = [
        Op("w", 0, 0, 9, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 1, 3, ruid=INIT),
        Op("r", 0, 1, 3, ruid=(1, 0)),
    ]
    assert K(h).ok


def test_read_from_the_future_fails():
    """A read that responded before the write was invoked cannot observe it."""
    h = [
        Op("r", 0, 0, 1, ruid=(1, 0)),
        Op("w", 0, 4, 5, wuid=(1, 0), ts=(1, 256)),
    ]
    assert not K(h).ok


def test_rmw_chain_ok_and_broken():
    ok = [
        Op("w", 0, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("rmw", 0, 2, 3, wuid=(2, 0), ruid=(1, 0), ts=(2, 1)),
        Op("r", 0, 4, 5, ruid=(2, 0)),
    ]
    assert K(ok).ok
    # RMW observing the initial value although W committed before it started
    bad = [
        Op("w", 0, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("rmw", 0, 2, 3, wuid=(2, 0), ruid=INIT, ts=(2, 1)),
    ]
    assert not K(bad).ok


def test_incomplete_write_may_or_may_not_apply():
    # observed incomplete write -> must linearize; fine
    h1 = [
        Op("maybe_w", 0, 0, INF, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 2, 3, ruid=(1, 0)),
    ]
    assert K(h1).ok
    # unobserved incomplete write -> dropped; reads of initial still fine
    h2 = [
        Op("maybe_w", 0, 0, INF, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 2, 3, ruid=INIT),
        Op("r", 0, 4, 5, ruid=INIT),
    ]
    assert K(h2).ok
    # but new-old inversion across it still fails
    h3 = [
        Op("maybe_w", 0, 0, INF, wuid=(1, 0), ts=(1, 256)),
        Op("r", 0, 2, 3, ruid=(1, 0)),
        Op("r", 0, 4, 5, ruid=INIT),
    ]
    assert not K(h3).ok


def test_aborted_rmw_value_never_observable():
    h = [
        Op("r", 0, 0, 1, ruid=(9, 9)),
    ]
    v = check_history(h, aborted_uids={(9, 9)})
    assert not v.ok


def test_witness_scales_past_exact_limit():
    """>62 ops on one key: the exact search would punt, but the ts witness
    decides (this is the Zipfian hot-key case, BASELINE.json:9)."""
    h = []
    t_ = 0
    for i in range(1, 200):
        h.append(Op("w", 0, t_, t_ + 1, wuid=(i, 0), ts=(i, 256)))
        h.append(Op("r", 0, t_ + 2, t_ + 3, ruid=(i, 0)))
        t_ += 4
    v = K(h)
    assert v.ok and not v.undecided
    # ...and a violation in a big history is still caught
    h.append(Op("r", 0, t_, t_ + 1, ruid=(1, 0)))  # ancient value read at the end
    v2 = K(h)
    assert not v2.ok


def test_multi_key_partitioning():
    h = [
        Op("w", 3, 0, 1, wuid=(1, 0), ts=(1, 256)),
        Op("r", 3, 2, 3, ruid=(1, 0)),
        Op("w", 4, 0, 1, wuid=(2, 0), ts=(1, 257)),
        Op("r", 4, 2, 3, ruid=(4, -1)),  # initial of key 4: (k, -1)
    ]
    v = check_history(h)
    assert not v.ok  # key 4 read initial after a committed write
    assert [f.key for f in v.failures] == [4]
