"""Unit tests: Lamport timestamp total order (SURVEY.md §4.1).

The reference compares ts = (version, cid) lexicographically on every apply
(SURVEY.md §2 "Lamport timestamp comparator"); our encoding adds the
write-over-RMW tie-break flag in the fc word (core/types.py FLAG_*).
"""

import itertools

import numpy as np

from hermes_tpu.core import types as t
from hermes_tpu.core.timestamps import fc_cid, make_fc, ts_eq, ts_gt


def all_ts(n_ver=3, cids=(0, 1, 2)):
    out = []
    for ver in range(n_ver):
        for flag in (t.FLAG_RMW, t.FLAG_WRITE):
            for cid in cids:
                out.append((ver, int(make_fc(flag, cid))))
    return out


def test_total_order():
    ts = all_ts()
    for a, b in itertools.product(ts, ts):
        gt = bool(ts_gt(np.int32(a[0]), np.int32(a[1]), np.int32(b[0]), np.int32(b[1])))
        lt = bool(ts_gt(np.int32(b[0]), np.int32(b[1]), np.int32(a[0]), np.int32(a[1])))
        eq = bool(ts_eq(np.int32(a[0]), np.int32(a[1]), np.int32(b[0]), np.int32(b[1])))
        assert gt + lt + eq == 1, (a, b)  # trichotomy
    # transitivity on the sorted order
    key = lambda x: (x[0], x[1])
    s = sorted(ts, key=key)
    for i in range(len(s) - 1):
        assert ts_gt(
            np.int32(s[i + 1][0]), np.int32(s[i + 1][1]), np.int32(s[i][0]), np.int32(s[i][1])
        )


def test_version_dominates_tiebreak():
    # A higher version always wins regardless of flag/cid.
    hi = (2, int(make_fc(t.FLAG_RMW, 0)))
    lo = (1, int(make_fc(t.FLAG_WRITE, 7)))
    assert ts_gt(np.int32(hi[0]), np.int32(hi[1]), np.int32(lo[0]), np.int32(lo[1]))


def test_write_beats_rmw_same_version():
    """The safety-critical tie-break (core/types.py): a plain write from any
    replica beats a concurrent RMW from any replica at the same base version,
    so an aborted RMW's timestamp can never dominate a surviving update."""
    for wcid in range(8):
        for rcid in range(8):
            w = int(make_fc(t.FLAG_WRITE, wcid))
            r = int(make_fc(t.FLAG_RMW, rcid))
            assert ts_gt(np.int32(1), np.int32(w), np.int32(1), np.int32(r))


def test_cid_roundtrip():
    for flag in (t.FLAG_RMW, t.FLAG_WRITE):
        for cid in range(32):
            assert int(fc_cid(make_fc(flag, cid))) == cid
