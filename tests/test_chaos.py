"""Chaos & recovery subsystem (hermes_tpu/chaos, round-9): async pipelined
failure detection, crash-consistent snapshots, crash-restart recovery,
declarative fault schedules — each leg gated by the linearizability
checker and the obs timeline."""

import json
import os
import zipfile

import numpy as np
import pytest

from hermes_tpu import chaos, snapshot
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.kvs import KVS, C_LOST, StuckOpError
from hermes_tpu.membership import MembershipService
from hermes_tpu.obs import Observability
from hermes_tpu.runtime import FastRuntime

from helpers import get


def _cfg(**kw):
    base = dict(
        n_replicas=5, n_keys=96, n_sessions=6, replay_slots=6,
        ops_per_session=24, replay_age=6, replay_scan_every=4,
        rebroadcast_every=2, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.25, seed=23),
    )
    base.update(kw)
    return HermesConfig(**base)


def _events(obs):
    return [r["name"] for r in obs.records if r.get("kind") == "event"]


# -- leg 1: async pipelined failure detection --------------------------------


def test_async_detector_zero_dispatch_fetch_pipelined():
    """The acceptance regression (ctl_upload pattern applied to detection):
    with the detector attached to a pipelined FastRuntime, the dispatch
    path issues ZERO synchronous last_seen fetches — suspicion/removal are
    driven entirely off the harvested Meta.suspect_age columns — and the
    frozen replica is suspected, confirmed, removed, and the healed run
    passes the checker."""
    cfg = _cfg(n_replicas=4, pipeline_depth=2)
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability())
    rt.attach_membership(MembershipService(cfg, confirm_steps=3))
    rt.run(4)
    rt.freeze(3)
    rt.run(25)
    ev = _events(obs)
    assert "membership_fetch" not in ev, "dispatch-path device_get leaked"
    assert ev.index("suspect") < ev.index("remove")
    assert rt.membership.events and rt.membership.events[0].kind == "remove"
    assert rt.membership.events[0].replica == 3
    assert rt.drain(1500)
    assert rt.check().ok


def test_confirm_window_spontaneous_recovery_cancels():
    """A replica that recovers inside the confirm window is NEVER removed:
    the suspicion cancels (suspect_clear on the timeline) instead of
    ejecting a healthy replica — the detector hysteresis."""
    cfg = _cfg(n_replicas=4, pipeline_depth=2)
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability())
    rt.attach_membership(MembershipService(cfg, confirm_steps=30))
    rt.run(3)
    rt.freeze(2)
    rt.run(cfg.lease_steps + 4)  # past the lease: suspected, not confirmed
    assert "suspect" in _events(obs)
    rt.thaw(2)  # spontaneous recovery before the confirm window elapses
    rt.run(10)
    ev = _events(obs)
    assert "suspect_clear" in ev
    assert not rt.membership.events, "healthy replica was removed"
    assert int(rt.live[0]) == cfg.full_mask
    assert rt.drain(1500) and rt.check().ok


def test_hb_skew_exercises_hysteresis_without_faults():
    """Heartbeat clock-skew (the fast engines' network-fault class): a
    skewed detector view pushes a HEALTHY replica into suspicion; when the
    skew window expires before the confirm window, the suspicion clears
    and nobody is ejected."""
    cfg = _cfg(n_replicas=4, pipeline_depth=2)
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability())
    rt.attach_membership(MembershipService(cfg, confirm_steps=20))
    sched = chaos.Schedule.parse("@5 hb_skew 1 skew=9 until=15\n")
    runner = chaos.ChaosRunner(rt, sched)
    res = runner.run(40, check=True)
    ev = _events(obs)
    assert "hb_skew" in ev and "suspect" in ev and "suspect_clear" in ev
    assert "remove" not in ev
    assert res["drained"] and res["checked_ok"]


def test_harvested_ages_ride_the_ring_per_round():
    """The detector input must never block on an EXECUTING round: each
    harvest consumes the suspect-age copy of a round the completion fetch
    already proved complete, so at depth d the observed age round lags the
    dispatch by d-1 — it must never equal the freshest in-flight round."""
    cfg = _cfg(n_replicas=4, pipeline_depth=3)
    rt = FastRuntime(cfg, record=True)
    rt.attach_membership(MembershipService(cfg))
    for _ in range(10):
        rt.step_once()
        if rt.harvested_ages is not None and len(rt._ring) >= 2:
            age_round = rt.harvested_ages[0]
            newest_inflight = rt.step_idx - 1
            assert age_round < newest_inflight, (
                "age fetch touched the executing round — pipeline "
                "re-serialized")
    assert rt.harvested_ages is not None
    assert rt.drain(1500) and rt.check().ok


def test_runner_second_run_replays_schedule():
    """run() replays the schedule from its first event every call (the
    round-13 tick() refactor moved the cursor onto the instance — a
    second run() must not silently apply nothing)."""
    cfg = _cfg(n_replicas=4, pipeline_depth=1)
    rt = FastRuntime(cfg)
    sched = chaos.Schedule.parse("@2 freeze 1\n@6 thaw 1\n")
    runner = chaos.ChaosRunner(rt, sched)
    runner.run(10, heal=True)
    runner.run(10, heal=True)
    assert [e["kind"] for e in runner.log].count("freeze") >= 2


def test_runner_remove_floor_and_heal_without_donor():
    """An all-remove declarative schedule must degrade at the healthy
    floor (skipped events in the log), never crash the runner or empty
    the cluster."""
    cfg = _cfg(n_replicas=5, pipeline_depth=1)
    rt = FastRuntime(cfg, record=True)
    sched = chaos.Schedule.parse(
        "\n".join(f"@0 remove {r}" for r in range(5)) + "\n")
    runner = chaos.ChaosRunner(rt, sched,
                               spec=chaos.ChaosSpec(min_healthy=3))
    res = runner.run(20, check=True)
    removed = [e for e in res["events"] if e["kind"] == "remove"]
    skipped = [e for e in res["events"] if e["kind"] == "skipped"]
    assert len(removed) == 2 and len(skipped) == 3  # floor held at 3
    assert res["drained"] and res["checked_ok"]
    assert int(rt.live[0]) == cfg.full_mask  # heal rejoined everyone


def test_detector_fallback_fetch_without_harvest():
    """fetch_completions=False runs never harvest, so the detector falls
    back to the synchronous poll — counted loudly as membership_fetch."""
    cfg = _cfg(n_replicas=4)
    rt = FastRuntime(cfg)  # no recorder
    rt.fetch_completions = False
    obs = rt.attach_obs(Observability())
    rt.attach_membership(MembershipService(cfg))
    rt.run(3)
    rt.freeze(3)
    rt.run(cfg.lease_steps + 3)
    ev = _events(obs)
    assert "membership_fetch" in ev
    assert any(e.kind == "remove" and e.replica == 3
               for e in rt.membership.events)


# -- leg 2: crash-consistent snapshots + crash-restart recovery --------------


def test_crash_restart_loses_inflight_ops_checked():
    """Full host-crash of a coordinator holding quorum-blocked in-flight
    writes: the clients' futures resolve as kind='lost', the history
    carries the lost updates as maybe_w (the cluster may still finish them
    via replay), and after heal the run drains and linearizes."""
    cfg = _cfg(n_keys=64, n_sessions=4, value_words=6, replay_slots=4,
               pipeline_depth=2)
    kvs = KVS(cfg, record=True)
    obs = kvs.rt.attach_obs(Observability())
    # block the quorum so replica 0's writes pin in flight
    kvs.freeze(3)
    kvs.freeze(4)
    futs = [kvs.put(0, s, 7 + s, [s, 1]) for s in range(4)]
    for _ in range(6):
        kvs.step()
    assert not any(f.done() for f in futs), "quorum was not blocked"
    n_ops = len(kvs.rt.recorder.ops)
    s = chaos.restart_replica(kvs, 0)
    assert s["lost_ops"] == 4 and s["lost_client_futures"] == 4
    assert all(f.done() and f.result().kind == "lost" for f in futs)
    # the lost in-flight updates were salvaged as maybe_w rows
    folded = [o for o in kvs.rt.recorder.ops if o.kind == "maybe_w"]
    assert len(folded) == 4 and len(kvs.rt.recorder.ops) == n_ops + 4
    assert "crash_restart" in _events(obs)
    kvs.rt.thaw(3)
    kvs.rt.thaw(4)
    g = kvs.get(1, 0, 7)
    assert kvs.run_until([g], 400)
    assert kvs.rt.check().ok


@pytest.mark.parametrize("backend", ["batched", "sharded"])
def test_crash_restart_soak_checked(backend):
    """Crash-restart composed with a running workload on both engines:
    totals conserve against the lost ops, every key is readable again,
    and the history linearizes."""
    mesh = None
    if backend == "sharded":
        import jax
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:5]), ("replica",))
    cfg = _cfg(pipeline_depth=2)
    rt = FastRuntime(cfg, backend=backend, mesh=mesh, record=True)
    rt.run(25)
    s1 = chaos.restart_replica(rt, 2)
    rt.run(20)
    s2 = chaos.restart_replica(rt, 4, donor=0)
    assert rt.drain(3000)
    assert rt.check().ok
    c = rt.counters()
    total = c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"]
    lost = s1["lost_ops"] + s2["lost_ops"]
    assert total == 5 * 6 * 24 - lost
    assert ((get(rt.fs.table.sst) & 7) == t.VALID).all()


def test_restart_from_snapshot_and_torn_fallback(tmp_path):
    """Snapshot-seeded restore on the sharded layout: a valid snapshot
    reports its still-current rows (the transfer volume it saves); a torn
    snapshot is REJECTED on the timeline and recovery falls back to pure
    peer transfer — never silently restoring garbage."""
    import jax
    from jax.sharding import Mesh

    cfg = _cfg(n_replicas=5, pipeline_depth=2)
    mesh = Mesh(np.array(jax.devices()[:5]), ("replica",))
    rt = FastRuntime(cfg, backend="sharded", mesh=mesh, record=True)
    obs = rt.attach_obs(Observability())
    rt.run(10)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, rt)
    rt.run(10)
    s = chaos.restart_replica(rt, 1, snapshot_path=p)
    assert s["source"] == "snapshot"
    assert 0 <= s["rows_current"] <= cfg.n_keys

    torn = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("state.table.vpts"):
                data[len(data) // 2] ^= 0xFF
            zout.writestr(name, bytes(data))
    s = chaos.restart_replica(rt, 2, snapshot_path=torn)
    assert s["source"] == "transfer"
    assert "snapshot_rejected" in _events(obs)
    assert rt.drain(3000) and rt.check().ok


def test_restart_torn_snapshot_rejected_batched_any_member(tmp_path):
    """Torn-archive rejection holds on the BATCHED engine too, and for a
    corrupt member the batched restore path never even reads (the full
    verify_archive pass guards both engines)."""
    cfg = _cfg(n_replicas=4, pipeline_depth=1)
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability())
    rt.run(8)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, rt)
    torn = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("state.table.bank"):
                data[len(data) // 2] ^= 0xFF
            zout.writestr(name, bytes(data))
    s = chaos.restart_replica(rt, 1, snapshot_path=torn)
    assert s["source"] == "transfer" and s["rows_current"] is None
    assert "snapshot_rejected" in _events(obs)
    # and the intact archive is accepted with every row current (shared
    # batched table survives the crash)
    s = chaos.restart_replica(rt, 2, snapshot_path=p)
    assert s["source"] == "snapshot" and s["rows_current"] == cfg.n_keys
    assert rt.drain(2000) and rt.check().ok


def test_rejoin_grace_confirm_zero_not_instantly_reejected():
    """Detector regression: with confirm_steps=0 at depth 2, a crashed and
    rejoined replica must NOT be re-removed off pre-join harvested ages —
    the join grace window (one lease) absorbs them."""
    cfg = _cfg(n_replicas=4, pipeline_depth=2)
    rt = FastRuntime(cfg, record=True)
    rt.attach_membership(MembershipService(cfg, confirm_steps=0))
    rt.run(6)
    rt.freeze(2)
    rt.run(cfg.lease_steps + 6)  # detector removes replica 2
    assert not (int(rt.live[0]) >> 2) & 1
    rt.thaw(2)
    chaos.restart_replica(rt, 2, donor=0)  # rejoin via crash-restart
    rt.run(cfg.lease_steps + 8)  # past the grace: healthy heartbeats rule
    removes = [e for e in rt.membership.events
               if e.kind == "remove" and e.replica == 2]
    assert len(removes) == 1, "rejoined replica was re-ejected on stale ages"
    assert (int(rt.live[0]) >> 2) & 1
    assert rt.drain(2000) and rt.check().ok


def test_snapshot_manifest_torn_and_fingerprint(tmp_path):
    """Crash-consistent save/load: tmp+rename leaves no temp files, a
    bit-flipped archive rejects on the manifest checksum, and a config
    fingerprint mismatch is loud."""
    cfg = _cfg(n_replicas=3, pipeline_depth=1)
    rt = FastRuntime(cfg)
    rt.run(5)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, rt)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]
    man = snapshot.read_manifest(p)
    assert man["step"] == 5
    assert man["config_sha256"] == snapshot.config_fingerprint(cfg)
    assert man["pipeline_depth"] == 1

    torn = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("state.sess.status"):
                data[-1] ^= 0x01
            zout.writestr(name, bytes(data))
    with pytest.raises(ValueError, match="checksum"):
        snapshot.load(torn, FastRuntime(cfg))

    other = FastRuntime(_cfg(n_replicas=3, n_keys=128, pipeline_depth=1))
    with pytest.raises(ValueError, match="fingerprint"):
        snapshot.load(p, other)


def test_kvs_snapshot_quiescence_trap_counts():
    """save() on a non-quiescent KVS raises with the in-flight evidence."""
    cfg = _cfg(n_replicas=3, n_keys=64, n_sessions=4, value_words=6)
    kvs = KVS(cfg)
    kvs.freeze(1)
    kvs.freeze(2)
    futs = [kvs.put(0, s, s, [1]) for s in range(3)]
    for _ in range(3):
        kvs.step()
    with pytest.raises(ValueError) as ei:
        snapshot.save("/tmp/never_written.npz", kvs)
    msg = str(ei.value)
    assert "quiescent" in msg and "3 op(s) in flight" in msg
    kvs.rt.thaw(1)
    kvs.rt.thaw(2)
    assert kvs.run_until(futs, 300)


# -- leg 3: declarative schedules -------------------------------------------


def test_schedule_parse_format_roundtrip():
    text = (
        "@12 freeze 2\n"
        "@18 thaw 2\n"
        "@30 crash_restart 2 donor=0\n"
        "@40 hb_skew 1 skew=9 until=55\n"
        "@15 net_drop 0 dst=3 until=40\n"
    )
    sched = chaos.Schedule.parse(text)
    assert len(sched) == 5
    assert sched.events[0].step == 12  # sorted by step
    again = chaos.Schedule.parse(sched.format())
    assert again.events == sched.events
    # a typo'd kind names its line, like every other parse diagnostic
    with pytest.raises(ValueError, match="line 2.*unknown chaos event kind"):
        chaos.Schedule.parse("@1 freeze 0\n@3 meteor 1\n")
    with pytest.raises(ValueError, match="line 1"):
        chaos.Schedule.parse("12 freeze 2\n")


@pytest.mark.parametrize("backend", ["batched", "sharded"])
def test_schedule_determinism(backend):
    """Satellite contract: same seed + config => byte-identical executed
    event log AND final state across two runs, on both engines — with the
    detector attached and crash-restart in the mix."""
    import jax

    mesh = None
    if backend == "sharded":
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:5]), ("replica",))
    cfg = _cfg(pipeline_depth=2)

    def run():
        rt = FastRuntime(cfg, backend=backend, mesh=mesh, record=True)
        rt.attach_membership(MembershipService(cfg, confirm_steps=3))
        sched = chaos.Schedule.random(cfg, seed=23, steps=120,
                                      spec=chaos.ChaosSpec(p_crash=0.03))
        runner = chaos.ChaosRunner(rt, sched)
        res = runner.run(120, check=True)
        assert res["drained"] and res["checked_ok"]
        return (runner.log_json(),
                jax.tree.leaves(jax.device_get(rt.fs)),
                json.dumps([dataclasses_row(e) for e in
                            rt.membership.events]))

    def dataclasses_row(e):
        return [e.step, e.kind, e.replica, e.live_mask]

    log_a, state_a, mem_a = run()
    log_b, state_b, mem_b = run()
    assert log_a == log_b, "executed-event logs differ"
    assert mem_a == mem_b, "membership event logs differ"
    for x, y in zip(state_a, state_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_net_chaos_sim_engine_checked():
    """net drop/delay/dup windows compose with freezes on the sim engine
    (the host-mediated wire) and the history still linearizes."""
    from hermes_tpu.runtime import Runtime
    from hermes_tpu.transport.sim import SimTransport

    cfg = HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=4, replay_slots=8,
        ops_per_session=16, replay_age=5, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=29),
    )
    net = chaos.NetChaos()
    rt = Runtime(cfg, backend="sim", record=True,
                 transport=SimTransport(cfg.n_replicas, net))
    sched = chaos.Schedule.parse(
        "@4 net_drop 0 dst=2 until=20\n"
        "@8 net_delay 1 skew=3 until=30\n"
        "@12 net_dup 2 until=28\n"
        "@16 freeze 3\n"
        "@24 thaw 3\n")
    runner = chaos.ChaosRunner(rt, sched, net=net)
    res = runner.run(50, check=True)
    assert res["drained"] and res["checked_ok"], res
    assert {"net_drop", "net_delay", "net_dup"} <= {e["kind"]
                                                    for e in runner.log}


def test_runner_quorum_floor_skips_illegal_events():
    """Legality resolution: the runner never freezes/crashes below the
    healthy floor — an over-aggressive schedule degrades to what the
    cluster can absorb, deterministically."""
    cfg = _cfg(n_replicas=4, pipeline_depth=1)
    rt = FastRuntime(cfg, record=True)
    sched = chaos.Schedule.parse("\n".join(
        f"@{s} freeze" for s in range(1, 20)) + "\n")
    runner = chaos.ChaosRunner(rt, sched,
                               spec=chaos.ChaosSpec(min_healthy=3))
    res = runner.run(30, check=True)
    frozen_events = [e for e in res["events"] if e["kind"] == "freeze"]
    assert len(frozen_events) == 1  # 4 healthy -> exactly one freeze legal
    assert res["drained"] and res["checked_ok"]


# -- satellite: KVS stuck-op watchdog ---------------------------------------


def test_kvs_stuck_op_watchdog_diagnostic():
    """A quorum-blocked op past cfg.op_timeout_rounds surfaces ONE
    stuck_op event + per-session diagnostic (coordinator, phase, age)
    instead of hanging silently, and completes once the quorum heals."""
    cfg = _cfg(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
               op_timeout_rounds=5)
    kvs = KVS(cfg)
    obs = kvs.rt.attach_obs(Observability())
    kvs.freeze(1)
    kvs.freeze(2)
    fut = kvs.put(0, 0, 9, [42])
    for _ in range(10):
        kvs.step()
    stuck = [r for r in obs.records if r.get("name") == "stuck_op"]
    assert len(stuck) == 1, "stuck_op must fire exactly once per op"
    d = kvs.stuck_ops[0]
    assert d["replica"] == 0 and d["session"] == 0 and d["kind"] == "put"
    assert d["phase"] == "ack-wait" and d["age_rounds"] > 5
    kvs.rt.thaw(1)
    kvs.rt.thaw(2)
    assert kvs.run_until([fut], 200)
    assert fut.result().kind == "put"


def test_kvs_stuck_op_sparse_reports_client_key():
    """Sparse-key mode: the diagnostic names the CLIENT's 64-bit key, not
    the dense device slot it hashed to."""
    cfg = _cfg(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
               op_timeout_rounds=4)
    kvs = KVS(cfg, sparse_keys=True)
    kvs.freeze(1)
    kvs.freeze(2)
    big_key = 0xDEAD_BEEF_0000_0042
    kvs.put(0, 0, big_key, [7])
    for _ in range(8):
        kvs.step()
    assert kvs.stuck_ops and kvs.stuck_ops[0]["key"] == big_key


def test_kvs_stuck_op_strict_raises():
    cfg = _cfg(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
               op_timeout_rounds=4)
    kvs = KVS(cfg, strict_timeouts=True)
    kvs.freeze(1)
    kvs.freeze(2)
    kvs.put(0, 0, 3, [1])
    with pytest.raises(StuckOpError, match="stuck past op_timeout_rounds"):
        for _ in range(12):
            kvs.step()


def test_watchdog_off_by_default_zero_cost_path():
    cfg = _cfg(n_replicas=3, n_keys=64, n_sessions=4, value_words=6)
    assert cfg.op_timeout_rounds == 0
    kvs = KVS(cfg)
    f = kvs.put(0, 0, 1, [7])
    assert kvs.run_until([f], 100)
    assert not kvs.stuck_ops
