"""Arbitrary-key hash index (hermes_tpu/keyindex.py) + KVS sparse-key mode
(SURVEY.md §1 L2 "MICA-derived index" parity; VERDICT round-1 item 6)."""

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.keyindex import KeyIndex, KeyspaceFull, _splitmix64
from hermes_tpu.kvs import KVS


def test_insert_lookup_roundtrip_random_64bit():
    rng = np.random.default_rng(0)
    idx = KeyIndex(n_keys=512)
    keys = rng.integers(0, 2**63, size=300, dtype=np.uint64)
    keys = np.unique(keys)
    slots = idx.get_slots(keys)
    # dense, in insertion order, no holes
    assert sorted(slots.tolist()) == list(range(len(keys)))
    # idempotent re-lookup, with and without insert
    np.testing.assert_array_equal(idx.get_slots(keys), slots)
    np.testing.assert_array_equal(idx.get_slots(keys, insert=False), slots)
    # inverse mapping
    for k, s in zip(keys.tolist(), slots.tolist()):
        assert idx.key_of(s) == k
    assert len(idx) == len(keys)


def test_collisions_probe_correctly():
    idx = KeyIndex(n_keys=64)  # capacity 128 buckets
    mask = np.uint64(idx._cap - 1)
    # find 5 distinct keys whose hash lands in the SAME bucket
    target = _splitmix64(np.uint64(1)) & mask
    colliders = [1]
    k = 2
    while len(colliders) < 5:
        if (_splitmix64(np.uint64(k)) & mask) == target:
            colliders.append(k)
        k += 1
    slots = [idx.slot(c) for c in colliders]
    assert sorted(slots) == list(range(5))  # all found homes via probing
    # every collider still resolves to its own slot
    for c, s in zip(colliders, slots):
        assert idx.slot(c, insert=False) == s
        assert c in idx
    assert idx.slot(999_999_999_999, insert=False) == -1


def test_keyspace_full_raises():
    idx = KeyIndex(n_keys=8)
    for k in range(8):
        idx.slot(k + 1000)
    with pytest.raises(KeyspaceFull):
        idx.slot(5000)
    # existing keys still resolve after the failed insert
    assert idx.slot(1000, insert=False) == 0


def test_kvs_sparse_keys_end_to_end_checked():
    """Sparse 64-bit client keys through the full protocol: puts/gets on
    huge keys, cross-replica visibility, completions echo the CLIENT key,
    and the run is checker-clean."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
        workload=WorkloadConfig(seed=21),
    )
    kvs = KVS(cfg, record=True, sparse_keys=True)
    k1 = 0xDEADBEEF_CAFEBABE
    k2 = (1 << 62) + 12345
    f1 = kvs.put(0, 0, k1, [7, 8, 9])
    f2 = kvs.put(1, 0, k2, [11])
    assert kvs.run_until([f1, f2])
    assert f1.result().kind == "put" and f1.result().key == k1
    g1 = kvs.get(2, 1, k1)  # remote replica sees the committed value
    g2 = kvs.get(0, 2, k2)
    assert kvs.run_until([g1, g2])
    assert g1.result().value[:3] == [7, 8, 9]
    assert g1.result().key == k1
    assert g2.result().value[:1] == [11]
    # RMW on a sparse key
    r1 = kvs.rmw(1, 3, k1, [42])
    assert kvs.run_until([r1])
    assert r1.result().kind in ("rmw", "rmw_abort")
    assert kvs.rt.check().ok


def test_kvs_sparse_keyspace_full_propagates():
    cfg = HermesConfig(n_replicas=3, n_keys=4, n_sessions=2, value_words=6)
    kvs = KVS(cfg, sparse_keys=True)
    for i in range(4):
        kvs.put(0, 0, (i + 1) * 10**15, [i])
    with pytest.raises(KeyspaceFull):
        kvs.put(0, 1, 999 * 10**15, [9])


def test_keyindex_fuzz_against_dict_model():
    """Randomized ops vs a dict reference model: interleaved inserts,
    repeat lookups, and absent probes over a small (high-collision) table
    must agree with the model exactly."""
    rng = np.random.default_rng(7)
    idx = KeyIndex(n_keys=128)
    model = {}
    universe = rng.integers(0, 2**63, size=400, dtype=np.uint64)
    for step in range(2000):
        k = int(universe[rng.integers(0, len(universe))])
        if rng.random() < 0.5 and len(model) < 128:
            s = idx.slot(k, insert=True)
            if k in model:
                assert s == model[k]
            else:
                assert s == len(model)  # dense, insertion-ordered
                model[k] = s
        else:
            assert idx.slot(k, insert=False) == model.get(k, -1)
            assert (k in idx) == (k in model)
    for k, s in model.items():
        assert idx.key_of(s) == k
