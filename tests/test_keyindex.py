"""Arbitrary-key hash index (hermes_tpu/keyindex.py) + KVS sparse-key mode
(SURVEY.md §1 L2 "MICA-derived index" parity; VERDICT round-1 item 6)."""

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.keyindex import KeyIndex, KeyspaceFull, _splitmix64
from hermes_tpu.kvs import KVS


def test_insert_lookup_roundtrip_random_64bit():
    rng = np.random.default_rng(0)
    idx = KeyIndex(n_keys=512)
    keys = rng.integers(0, 2**63, size=300, dtype=np.uint64)
    keys = np.unique(keys)
    slots = idx.get_slots(keys)
    # dense, in insertion order, no holes
    assert sorted(slots.tolist()) == list(range(len(keys)))
    # idempotent re-lookup, with and without insert
    np.testing.assert_array_equal(idx.get_slots(keys), slots)
    np.testing.assert_array_equal(idx.get_slots(keys, insert=False), slots)
    # inverse mapping
    for k, s in zip(keys.tolist(), slots.tolist()):
        assert idx.key_of(s) == k
    assert len(idx) == len(keys)


def test_collisions_probe_correctly():
    idx = KeyIndex(n_keys=64)  # capacity 128 buckets
    mask = np.uint64(idx._cap - 1)
    # find 5 distinct keys whose hash lands in the SAME bucket
    target = _splitmix64(np.uint64(1)) & mask
    colliders = [1]
    k = 2
    while len(colliders) < 5:
        if (_splitmix64(np.uint64(k)) & mask) == target:
            colliders.append(k)
        k += 1
    slots = [idx.slot(c) for c in colliders]
    assert sorted(slots) == list(range(5))  # all found homes via probing
    # every collider still resolves to its own slot
    for c, s in zip(colliders, slots):
        assert idx.slot(c, insert=False) == s
        assert c in idx
    assert idx.slot(999_999_999_999, insert=False) == -1


def test_keyspace_full_raises():
    idx = KeyIndex(n_keys=8)
    for k in range(8):
        idx.slot(k + 1000)
    with pytest.raises(KeyspaceFull):
        idx.slot(5000)
    # existing keys still resolve after the failed insert
    assert idx.slot(1000, insert=False) == 0


def test_kvs_sparse_keys_end_to_end_checked():
    """Sparse 64-bit client keys through the full protocol: puts/gets on
    huge keys, cross-replica visibility, completions echo the CLIENT key,
    and the run is checker-clean."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
        workload=WorkloadConfig(seed=21),
    )
    kvs = KVS(cfg, record=True, sparse_keys=True)
    k1 = 0xDEADBEEF_CAFEBABE
    k2 = (1 << 62) + 12345
    f1 = kvs.put(0, 0, k1, [7, 8, 9])
    f2 = kvs.put(1, 0, k2, [11])
    assert kvs.run_until([f1, f2])
    assert f1.result().kind == "put" and f1.result().key == k1
    g1 = kvs.get(2, 1, k1)  # remote replica sees the committed value
    g2 = kvs.get(0, 2, k2)
    assert kvs.run_until([g1, g2])
    assert g1.result().value[:3] == [7, 8, 9]
    assert g1.result().key == k1
    assert g2.result().value[:1] == [11]
    # RMW on a sparse key
    r1 = kvs.rmw(1, 3, k1, [42])
    assert kvs.run_until([r1])
    assert r1.result().kind in ("rmw", "rmw_abort")
    assert kvs.rt.check().ok


def test_kvs_sparse_keyspace_full_propagates():
    cfg = HermesConfig(n_replicas=3, n_keys=4, n_sessions=2, value_words=6)
    kvs = KVS(cfg, sparse_keys=True)
    for i in range(4):
        kvs.put(0, 0, (i + 1) * 10**15, [i])
    with pytest.raises(KeyspaceFull):
        kvs.put(0, 1, 999 * 10**15, [9])


def test_keyindex_fuzz_against_dict_model():
    """Randomized ops vs a dict reference model: interleaved inserts,
    repeat lookups, and absent probes over a small (high-collision) table
    must agree with the model exactly."""
    rng = np.random.default_rng(7)
    idx = KeyIndex(n_keys=128)
    model = {}
    universe = rng.integers(0, 2**63, size=400, dtype=np.uint64)
    for step in range(2000):
        k = int(universe[rng.integers(0, len(universe))])
        if rng.random() < 0.5 and len(model) < 128:
            s = idx.slot(k, insert=True)
            if k in model:
                assert s == model[k]
            else:
                assert s == len(model)  # dense, insertion-ordered
                model[k] = s
        else:
            assert idx.slot(k, insert=False) == model.get(k, -1)
            assert (k in idx) == (k in model)
    for k, s in model.items():
        assert idx.key_of(s) == k


def test_bulk_load_1m_keys_vectorized():
    """Stream-scale bulk path (round-2 verdict item 5): 1M distinct keys
    load in seconds via the vectorized probe rounds, slots stay dense in
    first-occurrence order, and bulk lookup agrees."""
    import time

    n = 1 << 20
    rng = np.random.default_rng(3)
    keys = rng.permutation(np.arange(1, n + 1, dtype=np.uint64) * np.uint64(
        0x10001))
    idx = KeyIndex(n_keys=n)
    t0 = time.perf_counter()
    slots = idx.get_slots(keys)
    load_s = time.perf_counter() - t0
    assert load_s < 30, f"bulk insert took {load_s:.1f}s"
    assert len(idx) == n
    # dense, no holes
    assert slots.min() == 0 and slots.max() == n - 1
    assert np.unique(slots).shape[0] == n
    # first-occurrence order: key at batch position i got slot i
    np.testing.assert_array_equal(slots, np.arange(n, dtype=np.int32))
    # vectorized re-lookup is idempotent and insert-free
    t0 = time.perf_counter()
    again = idx.get_slots(keys, insert=False)
    assert time.perf_counter() - t0 < 30
    np.testing.assert_array_equal(again, slots)
    # absent probes stay absent
    missing = np.array([7, 13, 999], np.uint64)
    np.testing.assert_array_equal(
        idx.get_slots(missing, insert=False), [-1, -1, -1])


def test_bulk_insert_duplicates_and_mixed_batch():
    """One batch containing repeats of the same new key, already-present
    keys, and fresh keys: repeats share one slot, present keys keep theirs,
    slot order follows first occurrence."""
    idx = KeyIndex(n_keys=16)
    assert idx.slot(100) == 0
    batch = np.array([200, 100, 300, 200, 300, 400], np.uint64)
    slots = idx.get_slots(batch)
    assert slots.tolist() == [1, 0, 2, 1, 2, 3]
    assert len(idx) == 4


def test_bulk_keyspace_full_is_atomic():
    """A too-large batch raises BEFORE mutating (documented bulk contract)."""
    idx = KeyIndex(n_keys=8)
    idx.get_slots(np.arange(1, 7, dtype=np.uint64))  # 6 used
    with pytest.raises(KeyspaceFull):
        idx.get_slots(np.array([100, 200, 300], np.uint64))  # 6+3 > 8
    assert len(idx) == 6
    assert idx.slot(100, insert=False) == -1  # nothing partially inserted
    assert idx.get_slots(np.array([100, 200], np.uint64)).tolist() == [6, 7]


def test_kvs_sparse_get_absent_key_is_not_found():
    """ADVICE round-2: a get of a never-written sparse key completes
    immediately as not-found and does NOT claim a dense slot, so read-only
    probes cannot exhaust the keyspace."""
    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=2, value_words=6,
                       replay_slots=8)
    kvs = KVS(cfg, sparse_keys=True)
    # read probes over many more keys than the table holds
    for i in range(128):
        f = kvs.get(0, 0, (i + 1) * 10**12)
        assert f.done()
        c = f.result()
        assert c.kind == "get" and not c.found and c.value is None
        assert c.key == (i + 1) * 10**12
    assert len(kvs.index) == 0  # no slots burned
    # writes still allocate and a subsequent get finds the value
    fw = kvs.put(0, 0, 777, [5])
    assert kvs.run_until([fw])
    fg = kvs.get(1, 1, 777)
    assert kvs.run_until([fg])
    assert fg.result().found and fg.result().value[:1] == [5]
