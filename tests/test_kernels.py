"""Pallas kernels (core/kernels.py) vs pure-jnp reference formulation."""

import jax.numpy as jnp
import numpy as np

from hermes_tpu.core import kernels, state as st, types as t


def test_stats_block_matches_reference():
    rng = np.random.default_rng(0)
    R, S = 4, 512
    op = rng.choice([t.OP_READ, t.OP_WRITE, t.OP_RMW], (R, S)).astype(np.int32)
    invoke = rng.integers(0, 40, (R, S)).astype(np.int32)
    commit = rng.random((R, S)) < 0.3
    abort = (rng.random((R, S)) < 0.05) & ~commit
    read = (rng.random((R, S)) < 0.3) & ~commit & ~abort
    step = 41

    code, ctr, hist = kernels.stats_block(
        step, jnp.asarray(op), jnp.asarray(invoke),
        jnp.asarray(commit), jnp.asarray(abort), jnp.asarray(read))

    is_rmw = op == t.OP_RMW
    ref_code = np.where(
        abort, t.C_RMW_ABORT,
        np.where(commit, np.where(is_rmw, t.C_RMW, t.C_WRITE),
                 np.where(read, t.C_READ, t.C_NONE)))
    np.testing.assert_array_equal(np.asarray(code), ref_code)

    lat = np.where(commit, step - invoke, 0)
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_READ]), read.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_WRITE]),
                                  (commit & ~is_rmw).sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_RMW]),
                                  (commit & is_rmw).sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_ABORT]), abort.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_LATSUM]), lat.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_LATCNT]), commit.sum(1))

    ref_hist = np.zeros((R, st.LAT_BINS), np.int32)
    for r in range(R):
        for s in range(S):
            if commit[r, s]:
                ref_hist[r, min(lat[r, s], st.LAT_BINS - 1)] += 1
    np.testing.assert_array_equal(np.asarray(hist), ref_hist)


def test_stats_block_multi_block_grid():
    """S > 32Ki exercises the gridded accumulation path (nblk > 1), and a
    non-multiple S exercises the neutral padding; both must match the
    single-block reference formulation exactly."""
    import numpy as np
    import jax.numpy as jnp
    from hermes_tpu.core import kernels, state as st, types as t

    rng = np.random.default_rng(5)
    for S in (1 << 16, 40000):  # multiple of 32Ki and a ragged size
        R = 2
        op = jnp.asarray(rng.integers(0, 3, (R, S), dtype=np.int32))
        invoke = jnp.asarray(rng.integers(0, 50, (R, S), dtype=np.int32))
        commit = jnp.asarray(rng.random((R, S)) < 0.3)
        abort = jnp.asarray((rng.random((R, S)) < 0.05)) & ~commit
        read_done = jnp.asarray(rng.random((R, S)) < 0.2) & ~commit & ~abort
        step = 57
        code, ctr, hist = kernels.stats_block(step, op, invoke, commit, abort, read_done)

        is_rmw = np.asarray(op) == t.OP_RMW
        cm, ab, rd = map(np.asarray, (commit, abort, read_done))
        lat = np.where(cm, step - np.asarray(invoke), 0)
        assert int(ctr[:, kernels.CTR_READ].sum()) == int(rd.sum())
        assert int(ctr[:, kernels.CTR_WRITE].sum()) == int((cm & ~is_rmw).sum())
        assert int(ctr[:, kernels.CTR_RMW].sum()) == int((cm & is_rmw).sum())
        assert int(ctr[:, kernels.CTR_ABORT].sum()) == int(ab.sum())
        assert int(ctr[:, kernels.CTR_LATSUM].sum()) == int(lat.sum())
        assert int(ctr[:, kernels.CTR_LATCNT].sum()) == int(cm.sum())
        clat = np.clip(lat, 0, st.LAT_BINS - 1)
        for b in range(st.LAT_BINS):
            assert int(hist[:, b].sum()) == int(((clat == b) & cm).sum())
        assert code.shape == (R, S)
