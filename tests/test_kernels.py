"""Pallas kernels (core/kernels.py) vs pure-jnp reference formulation."""

import jax.numpy as jnp
import numpy as np

from hermes_tpu.core import kernels, state as st, types as t


def test_stats_block_matches_reference():
    rng = np.random.default_rng(0)
    R, S = 4, 512
    op = rng.choice([t.OP_READ, t.OP_WRITE, t.OP_RMW], (R, S)).astype(np.int32)
    invoke = rng.integers(0, 40, (R, S)).astype(np.int32)
    commit = rng.random((R, S)) < 0.3
    abort = (rng.random((R, S)) < 0.05) & ~commit
    read = (rng.random((R, S)) < 0.3) & ~commit & ~abort
    step = 41

    code, ctr, hist = kernels.stats_block(
        step, jnp.asarray(op), jnp.asarray(invoke),
        jnp.asarray(commit), jnp.asarray(abort), jnp.asarray(read))

    is_rmw = op == t.OP_RMW
    ref_code = np.where(
        abort, t.C_RMW_ABORT,
        np.where(commit, np.where(is_rmw, t.C_RMW, t.C_WRITE),
                 np.where(read, t.C_READ, t.C_NONE)))
    np.testing.assert_array_equal(np.asarray(code), ref_code)

    lat = np.where(commit, step - invoke, 0)
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_READ]), read.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_WRITE]),
                                  (commit & ~is_rmw).sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_RMW]),
                                  (commit & is_rmw).sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_ABORT]), abort.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_LATSUM]), lat.sum(1))
    np.testing.assert_array_equal(np.asarray(ctr[:, kernels.CTR_LATCNT]), commit.sum(1))

    ref_hist = np.zeros((R, st.LAT_BINS), np.int32)
    for r in range(R):
        for s in range(S):
            if commit[r, s]:
                ref_hist[r, min(lat[r, s], st.LAT_BINS - 1)] += 1
    np.testing.assert_array_equal(np.asarray(hist), ref_hist)
