"""End-to-end lockstep runs of the batched R-replica step (SURVEY.md §4.2-ish
without adversarial scheduling — that arrives with the sim transport):
completion accounting and cross-replica convergence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st, step as step_lib
from hermes_tpu.core import types as t
from hermes_tpu.workload import ycsb

from helpers import get


def run(cfg, n_steps):
    rs0 = st.init_replica_state(cfg)
    r = cfg.n_replicas
    rs = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), rs0)
    stream = jax.tree.map(jnp.asarray, ycsb.make_streams(cfg))
    step = step_lib.build_step_batched(cfg)
    for s in range(n_steps):
        rs, comp = step(rs, stream, step_lib.make_ctl(cfg, s))
    return rs


def assert_converged(cfg, rs):
    """After the workload drains, every replica must hold an identical,
    fully-Valid table (broadcast invalidation converges; SURVEY.md §3.1)."""
    state = get(rs.table.state)
    assert (state == t.VALID).all(), np.bincount(state.ravel(), minlength=5)
    for col in ("ver", "fc", "val"):
        arr = get(getattr(rs.table, col))
        for r in range(1, cfg.n_replicas):
            np.testing.assert_array_equal(arr[0], arr[r], err_msg=col)


@pytest.mark.parametrize("mix", ["a", "f", "zipf"])
def test_workload_drains_and_converges(mix):
    wl = {
        "a": WorkloadConfig(read_frac=0.5, seed=2),
        "f": WorkloadConfig(read_frac=0.5, rmw_frac=1.0, seed=3),
        "zipf": WorkloadConfig(read_frac=0.5, distribution="zipfian", zipf_theta=0.99, seed=4),
    }[mix]
    cfg = HermesConfig(
        n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4, ops_per_session=16,
        workload=wl,
    )
    rs = run(cfg, 80)
    sess_status = get(rs.sess.status)
    assert (sess_status == t.S_DONE).all(), np.bincount(sess_status.ravel())
    assert_converged(cfg, rs)
    meta = rs.meta
    total_ops = cfg.n_replicas * cfg.n_sessions * cfg.ops_per_session
    done = int(
        get(meta.n_read).sum()
        + get(meta.n_write).sum()
        + get(meta.n_rmw).sum()
        + get(meta.n_abort).sum()
    )
    assert done == total_ops
    if mix == "f":
        assert int(get(meta.n_rmw).sum()) > 0


def test_five_replicas_converge():
    cfg = HermesConfig(
        n_replicas=5, n_keys=64, n_sessions=4, replay_slots=2, ops_per_session=8,
        workload=WorkloadConfig(read_frac=0.2, seed=5),
    )
    rs = run(cfg, 60)
    assert_converged(cfg, rs)


def test_uncontended_write_commits_same_step():
    """Hermes's headline: commit latency = one INV/ACK round trip — in the
    lockstep schedule that is the same step it was issued (SURVEY.md §3.1)."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=1024, n_sessions=2, replay_slots=2, ops_per_session=4,
        workload=WorkloadConfig(read_frac=0.0, seed=7),
    )
    rs = run(cfg, 30)
    meta = rs.meta
    # every committed update took <= 1 step issue->commit (step of load ==
    # step of commit under no contention; contended ones may take longer)
    hist = get(meta.lat_hist).sum(axis=0)
    assert hist[2:].sum() <= hist.sum() * 0.2
    assert hist[0] > 0
