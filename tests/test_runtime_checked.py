"""End-to-end runs through the Runtime with full history recording and the
linearizability gate — the rebuild of BASELINE config 1 (3-replica
single-process KVS, YCSB-A, uniform; BASELINE.json:7) and config 2 (YCSB-F
RMW mix; BASELINE.json:8), scaled down for CI."""

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.runtime import Runtime

from helpers import get


def drained_checked(cfg, backend="batched", max_steps=400):
    rt = Runtime(cfg, backend=backend, record=True)
    assert rt.drain(max_steps)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    return rt


def test_config1_ycsb_a_uniform():
    cfg = HermesConfig(
        n_replicas=3, n_keys=512, n_sessions=16, replay_slots=8, ops_per_session=32,
        workload=WorkloadConfig(read_frac=0.5, seed=21),
    )
    rt = drained_checked(cfg)
    c = rt.counters()
    total = 3 * 16 * 32
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == total


def test_config2_ycsb_f_rmw():
    cfg = HermesConfig(
        n_replicas=5, n_keys=64, n_sessions=8, replay_slots=8, ops_per_session=24,
        workload=WorkloadConfig(read_frac=0.3, rmw_frac=1.0, seed=22),
    )
    rt = drained_checked(cfg)
    c = rt.counters()
    assert c["n_rmw"] > 0


def test_zipfian_contention_checked():
    """Config-3-shaped (BASELINE.json:9): few keys + Zipfian 0.99 makes every
    step a contended-INV conflict."""
    cfg = HermesConfig(
        n_replicas=7, n_keys=32, n_sessions=8, replay_slots=8, ops_per_session=16,
        workload=WorkloadConfig(read_frac=0.5, distribution="zipfian", zipf_theta=0.99, seed=23),
    )
    drained_checked(cfg)


def test_sharded_backend_equivalence():
    """The tpu_ici-shaped sharded backend (8-way shard_map over the virtual
    CPU mesh) must produce the same tables as the batched backend and pass
    the checker — guards the all_gather/all_to_all exchange wiring."""
    import jax
    from jax.sharding import Mesh

    cfg = HermesConfig(
        n_replicas=8, n_keys=128, n_sessions=4, replay_slots=4, ops_per_session=8,
        workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.3, seed=25),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = Runtime(cfg, backend="batched", record=True)
    b = Runtime(cfg, backend="sharded", mesh=mesh, record=True)
    assert a.drain(300) and b.drain(300)
    np.testing.assert_array_equal(get(a.rs.table.ver), get(b.rs.table.ver))
    np.testing.assert_array_equal(get(a.rs.table.val), get(b.rs.table.val))
    assert a.check().ok and b.check().ok


def test_sim_backend_lockstep_equivalence():
    """The host-mediated sim transport at zero delay must behave exactly like
    the fused batched step (same protocol, different exchange substrate)."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=128, n_sessions=4, replay_slots=4, ops_per_session=12,
        workload=WorkloadConfig(read_frac=0.5, seed=24),
    )
    a = Runtime(cfg, backend="batched", record=True)
    b = Runtime(cfg, backend="sim", record=True)
    assert a.drain(200) and b.drain(200)
    ka = get(a.rs.table.ver)
    kb = get(b.rs.table.ver)
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_array_equal(get(a.rs.table.val), get(b.rs.table.val))
    assert a.check().ok and b.check().ok
