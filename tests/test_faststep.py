"""Correctness of the TPU-optimized round (core/faststep.py).

faststep re-engineers the phases for the measured TPU cost model (packed
timestamps + scatter-max conflict resolution, lane compaction with
rebroadcast backoff, cond-gated replay scan) — these tests pin that it still
IS the Hermes protocol: every run drains and passes the linearizability gate
(BASELINE.json:2), failure/recovery works, and the batched and sharded
(tpu_ici-shaped) executions agree.
"""

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import types as t
from hermes_tpu.runtime import FastRuntime, Runtime

from helpers import get


def drained_checked(cfg, max_steps=400, **kw):
    rt = FastRuntime(cfg, record=True, **kw)
    assert rt.drain(max_steps)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    return rt


def test_pts_packing_orders_like_lex():
    ver = np.array([0, 1, 1, 2, 1])
    fc = np.array([5, 1, 2, 0, 1023])
    pts = [(int(v) << fst.PTS_FC_BITS) | int(f) for v, f in zip(ver, fc)]
    lex = sorted(range(5), key=lambda i: (ver[i], fc[i]))
    assert sorted(range(5), key=lambda i: pts[i]) == lex


def test_ycsb_a_uniform_checked():
    """Config-1-shaped (BASELINE.json:7): YCSB-A, uniform keys."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=512, n_sessions=16, replay_slots=8, ops_per_session=32,
        workload=WorkloadConfig(read_frac=0.5, seed=31),
    )
    rt = drained_checked(cfg)
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == 3 * 16 * 32


def test_ycsb_f_rmw_checked():
    """Config-2-shaped (BASELINE.json:8): write-heavy RMW mix; the ok-flag
    nack path must abort conflicting RMWs without breaking linearizability."""
    cfg = HermesConfig(
        n_replicas=5, n_keys=64, n_sessions=8, replay_slots=8, ops_per_session=24,
        workload=WorkloadConfig(read_frac=0.3, rmw_frac=1.0, seed=32),
    )
    rt = drained_checked(cfg)
    assert rt.counters()["n_rmw"] > 0


def test_wire_block_pack_roundtrip():
    """FastInv/FastAck ride the wire as single int8 byte tensors
    (round-5): the field views must recover exactly the packed words,
    including sign bits (INV_VALID occupies bit 30; negative-looking
    bytes must not corrupt the unpack)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    key = jnp.asarray(rng.integers(0, 1 << 29, (2, 5), dtype=np.int32))
    pts = jnp.asarray(rng.integers(0, 1 << 30, (2, 5), dtype=np.int32))
    val = jnp.asarray(rng.integers(-128, 128, (2, 5, 8), dtype=np.int8))
    fresh = jnp.asarray(rng.random((2, 5)) < 0.5)
    taken = jnp.asarray(rng.random((2, 5)) < 0.7)
    pkf = (key | jnp.where(fresh, fst.INV_FRESH, 0)
           | jnp.where(taken, fst.INV_VALID, 0))
    head8 = fst._i32_to_bank(jnp.stack([pkf, pts], axis=-1))
    epoch = jnp.asarray([3, 1 << 20], jnp.int32)
    alive = jnp.asarray([True, False])
    inv = fst.FastInv(rows8=jnp.concatenate([head8, val], axis=-1),
                      meta=(epoch << 1) | alive.astype(jnp.int32))
    np.testing.assert_array_equal(get(inv.key), get(key))
    np.testing.assert_array_equal(get(inv.pts), get(pts))
    np.testing.assert_array_equal(get(inv.val), get(val))
    np.testing.assert_array_equal(get(inv.fresh), get(fresh))
    np.testing.assert_array_equal(get(inv.valid), get(taken))
    # the per-block scalars ride one packed word (round-6 collective diet)
    np.testing.assert_array_equal(get(inv.epoch), get(epoch))
    np.testing.assert_array_equal(get(inv.alive), get(alive))

    apkf = (key << 2) | 2 | 1
    ack = fst.FastAck(
        rows8=fst._i32_to_bank(jnp.stack([apkf, pts], axis=-1))[None])
    np.testing.assert_array_equal(get(ack.pkf)[0], get(apkf))
    np.testing.assert_array_equal(get(ack.pts)[0], get(pts))


def test_fused_sort_matches_split_arbiter():
    """Round-6: the fused arbiter+compaction sort must be OUTCOME-IDENTICAL
    to the split two-sort program when the lane budget covers every lane
    (no compaction overflow, where the two programs' slot priority orders
    legitimately differ): same winners (lowest-session-wins tie-break),
    same chain ranks, same timestamps, same table."""
    base = dict(
        n_replicas=3, n_keys=64, n_sessions=8, replay_slots=4,
        ops_per_session=16, arb_mode="sort", chain_writes=3,
        workload=WorkloadConfig(read_frac=0.3, rmw_frac=0.2, seed=42),
    )
    a = FastRuntime(HermesConfig(fused_sort=True, **base), record=True)
    b = FastRuntime(HermesConfig(fused_sort=False, **base), record=True)
    assert a.drain(500) and b.drain(500)
    np.testing.assert_array_equal(get(a.fs.sess.pts), get(b.fs.sess.pts))
    np.testing.assert_array_equal(get(a.fs.table.val), get(b.fs.table.val))
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k
    assert a.check().ok


def test_fused_sort_overflow_drains_and_checks():
    """Fused sort under budget OVERFLOW (slot-rank threshold + rotated-key
    band priority live): reverted issues retry, nothing is lost, history
    linearizes."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=8, n_sessions=8, replay_slots=4,
        ops_per_session=12, arb_mode="sort", chain_writes=2,
        lane_budget_cfg=5, rebroadcast_every=2,
        workload=WorkloadConfig(read_frac=0.2, rmw_frac=0.2, seed=47),
    )
    rt = drained_checked(cfg, max_steps=3000)
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == 3 * 8 * 12


def test_rmw_retry_converts_aborts_to_commits():
    """config.rmw_retries (round-5): a nacked RMW retries in place instead
    of aborting; under heavy same-key RMW contention the retry run must
    commit strictly more RMWs (fewer aborts) than the reference-behavior
    run, both checker-clean, with every RMW still resolving exactly once."""
    base = dict(n_replicas=5, n_keys=8, n_sessions=8, replay_slots=4,
                ops_per_session=24,
                workload=WorkloadConfig(read_frac=0.0, rmw_frac=1.0, seed=71))
    a = drained_checked(HermesConfig(**base))
    b = drained_checked(HermesConfig(rmw_retries=64, **base), max_steps=800)
    ca, cb = a.counters(), b.counters()
    assert ca["n_abort"] > 0, "contention sanity: the reference run aborts"
    assert cb["n_abort"] < ca["n_abort"]
    assert cb["n_rmw"] > ca["n_rmw"]
    # every RMW resolves exactly once either way
    assert ca["n_rmw"] + ca["n_abort"] == cb["n_rmw"] + cb["n_abort"]


def test_rmw_retry_bounded_then_aborts():
    """The retry budget is a bound, not a promise: rmw_retries=1 under the
    same contention still aborts some RMWs (the client-visible abort
    semantics survive as the fallback), checker-clean."""
    cfg = HermesConfig(
        n_replicas=5, n_keys=4, n_sessions=8, replay_slots=4,
        ops_per_session=16, rmw_retries=1,
        workload=WorkloadConfig(read_frac=0.0, rmw_frac=1.0, seed=72),
    )
    rt = drained_checked(cfg, max_steps=800)
    c = rt.counters()
    assert c["n_abort"] > 0 and c["n_rmw"] > 0


def test_rmw_retry_sharded_matches_batched():
    import jax
    from jax.sharding import Mesh

    cfg = HermesConfig(
        n_replicas=8, n_keys=16, n_sessions=4, replay_slots=4,
        ops_per_session=12, rmw_retries=32,
        workload=WorkloadConfig(read_frac=0.2, rmw_frac=1.0, seed=73),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = FastRuntime(cfg, backend="batched", record=True)
    b = FastRuntime(cfg, backend="sharded", mesh=mesh)
    assert a.drain(500) and b.drain(500)
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k
    assert a.check().ok


def test_zipfian_contention_checked():
    """Config-3-shaped (BASELINE.json:9): hot keys force the scatter-max
    winner path (many same-key INVs per round)."""
    cfg = HermesConfig(
        n_replicas=7, n_keys=32, n_sessions=8, replay_slots=8, ops_per_session=16,
        workload=WorkloadConfig(read_frac=0.5, distribution="zipfian",
                                zipf_theta=0.99, seed=33),
    )
    drained_checked(cfg)


def test_lane_budget_backpressure():
    """A lane budget far below the in-flight count must only slow the run
    (overflowing lanes wait; idempotent re-broadcast), never lose ops."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=256, n_sessions=16, replay_slots=4, ops_per_session=16,
        lane_budget_cfg=4, rebroadcast_every=2,
        workload=WorkloadConfig(read_frac=0.2, seed=34),
    )
    rt = drained_checked(cfg, max_steps=2000)
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == 3 * 16 * 16


@pytest.mark.parametrize("arb_mode", ["race", "sort"])
def test_frozen_replica_stall_and_recovery(arb_mode):
    """Config-4-shaped (BASELINE.json:10): a replica stalls mid-run; after
    the membership removes it, waiting writes commit against the shrunken
    quorum and stuck Invalid keys recover via the (gated) replay scan —
    under both issue-arbitration strategies."""
    cfg = HermesConfig(
        n_replicas=4, n_keys=128, n_sessions=8, replay_slots=16, ops_per_session=16,
        replay_age=4, replay_scan_every=4, arb_mode=arb_mode,
        workload=WorkloadConfig(read_frac=0.4, seed=35),
    )
    rt = FastRuntime(cfg, record=True)
    rt.run(6)
    rt.freeze(3)
    rt.run(4)  # writes stall against the dead replica's missing acks
    rt.remove(3)  # membership: epoch++, live mask shrinks
    assert rt.drain(1500)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    # survivors finished their streams
    status = get(rt.fs.sess.status)
    for r in range(3):
        assert (status[r] == t.S_DONE).all()


def test_membership_join_mid_workload():
    """Config-5-shaped (BASELINE.json:11): remove a replica, then re-join it
    via state transfer mid-workload; run drains and checks."""
    cfg = HermesConfig(
        n_replicas=4, n_keys=128, n_sessions=6, replay_slots=8, ops_per_session=12,
        replay_age=4, replay_scan_every=4,
        workload=WorkloadConfig(read_frac=0.5, seed=36),
    )
    rt = FastRuntime(cfg, record=True)
    rt.run(4)
    rt.remove(2)
    rt.run(6)
    rt.join(2, from_replica=0)
    assert rt.drain(1500)
    assert rt.check().ok


@pytest.mark.parametrize("variant", ["plain", "chained", "tiebreak"])
def test_sharded_matches_batched(variant):
    """The shard_map execution (all_gather/all_to_all over the 'replica'
    axis — the tpu_ici transport shape, BASELINE.json:5) must produce the
    same table state as the batched execution on the same stream — with
    and without write chaining (the chain ranks come from the per-replica
    sort, identical in both executions).  The tiebreak variant pins the
    round-6 FUSED arbiter+compaction sort at its hard shape: a tiny
    keyspace makes every replica's wanting sessions pile into duplicate
    hot-key runs (the stable-sort lowest-session-wins tie-break), while an
    overflowing lane budget exercises the slot-rank threshold and the
    rotating band priority."""
    import jax
    from jax.sharding import Mesh

    if variant == "tiebreak":
        cfg = HermesConfig(
            n_replicas=8, n_keys=8, n_sessions=8, replay_slots=4,
            ops_per_session=10, arb_mode="sort", chain_writes=2,
            lane_budget_cfg=6, rebroadcast_every=2,
            workload=WorkloadConfig(read_frac=0.2, rmw_frac=0.2, seed=47),
        )
    elif variant == "chained":
        # high-contention shape: small keyspace, write-leaning mix — chains
        # actually FORM here (verified: final state differs from the
        # unchained run), so sharded chain-rank propagation is exercised
        cfg = HermesConfig(
            n_replicas=8, n_keys=32, n_sessions=6, replay_slots=4,
            ops_per_session=8, arb_mode="sort", chain_writes=4,
            workload=WorkloadConfig(read_frac=0.3, rmw_frac=0.2, seed=41),
        )
    else:
        cfg = HermesConfig(
            n_replicas=8, n_keys=128, n_sessions=4, replay_slots=4,
            ops_per_session=8,
            workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.3, seed=37),
        )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = FastRuntime(cfg, backend="batched", record=True)
    b = FastRuntime(cfg, backend="sharded", mesh=mesh)
    # the contended tiebreak shape backpressures (budget < demand), so
    # lanes wait rounds out; give it headroom
    steps = 2000 if variant == "tiebreak" else 300
    assert a.drain(steps)
    assert b.drain(steps)
    # sessions end with identical issued timestamps under both executions
    np.testing.assert_array_equal(get(a.fs.sess.pts), get(b.fs.sess.pts))
    # batched shares one value table; each drained shard must equal it
    bval = get(b.fs.table.val).reshape(cfg.n_replicas, cfg.n_keys, -1)
    for r in range(cfg.n_replicas):
        np.testing.assert_array_equal(get(a.fs.table.val), bval[r])
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k
    assert a.check().ok


def test_matches_reference_phases_commit_totals():
    """faststep and the reference phases implementation must agree on the
    workload outcome (op totals; both checker-clean) for the same stream."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=256, n_sessions=8, replay_slots=4, ops_per_session=16,
        workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.2, seed=38),
    )
    a = Runtime(cfg, backend="batched", record=True)
    b = FastRuntime(cfg, backend="batched", record=True)
    assert a.drain(300) and b.drain(300)
    ca, cb = a.counters(), b.counters()
    total_a = ca["n_read"] + ca["n_write"] + ca["n_rmw"] + ca["n_abort"]
    total_b = cb["n_read"] + cb["n_write"] + cb["n_rmw"] + cb["n_abort"]
    assert total_a == total_b == 3 * 8 * 16
    assert ca["n_read"] == cb["n_read"]
    assert a.check().ok and b.check().ok


def test_commit_during_backoff_after_membership_change():
    """A lane whose quorum completes via a live-mask shrink while it is in
    rebroadcast backoff must still deliver its VAL: commit waits for the
    lane's next broadcast round (slot-aligned VALs need a slot), so no
    follower is left Invalid until the replay scan."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=2, ops_per_session=6,
        rebroadcast_every=4, replay_age=1000, replay_scan_every=1000,  # replay OFF
        workload=WorkloadConfig(read_frac=0.0, seed=39),
    )
    rt = FastRuntime(cfg, record=True)
    rt.run(2)
    rt.freeze(2)  # quorum stalls: writes gather acks from {0,1} only
    rt.run(3)
    rt.remove(2)  # live mask shrink completes the quorums mid-backoff
    assert rt.drain(600)
    v = rt.check()
    assert v.ok, (v.failures[:2], v.undecided[:2])
    # every surviving replica's touched keys reached VALID without replay
    status = get(rt.fs.sess.status)
    for r in range(2):
        assert (status[r] == t.S_DONE).all()
    sst = get(rt.fs.table.sst)  # shared (K,) in batched mode
    assert ((sst & 7) == t.VALID).all()


def test_device_stream_matches_host_twin_and_checks():
    """The on-device counter-hash workload (cfg.device_stream) must be
    bit-identical to its host twin and pass the checker end to end."""
    from hermes_tpu.workload import ycsb

    R, S, G = 3, 8, 16
    cfg = HermesConfig(
        n_replicas=R, n_keys=256, n_sessions=S, replay_slots=4, ops_per_session=G,
        device_stream=True, workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.0, seed=5),
    )
    rt = FastRuntime(cfg, record=True)
    assert rt.drain(400)
    assert rt.check().ok
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == R * S * G

    # bit-identity: with rmw_frac=0 (no aborts) every op completes and is
    # recorded, so each session's recorded (kind, key) sequence must equal
    # the host twin's for g = 0..G-1
    r, s, g = np.meshgrid(np.arange(R), np.arange(S), np.arange(G), indexing="ij")
    top, tkey = ycsb.device_stream_host(
        cfg, r.astype(np.uint32), s.astype(np.uint32), g.astype(np.uint32))
    kind_of = {t.OP_READ: "r", t.OP_WRITE: "w"}
    by_sess = {}
    for o in rt.history_ops():
        by_sess.setdefault((o.replica, o.session), []).append(o)
    checked = 0
    for (rr, ss), ops in by_sess.items():
        ops.sort(key=lambda o: o.inv)
        assert len(ops) == G
        for gg, o in enumerate(ops):
            assert o.key == int(tkey[rr, ss, gg]), (rr, ss, gg)
            assert o.kind == kind_of[int(top[rr, ss, gg])], (rr, ss, gg)
            checked += 1
    assert checked == R * S * G


def test_read_unroll_drains_reads_and_checks():
    """read_unroll > 1 (the reference worker loop's local-read batching,
    SURVEY.md §3.2): a round completes several consecutive reads per
    session.  Totals and the checker verdict must match the unroll=1 run;
    the unrolled run must take strictly fewer rounds to drain."""
    base = dict(n_replicas=3, n_keys=256, n_sessions=8, replay_slots=4,
                ops_per_session=32,
                workload=WorkloadConfig(read_frac=0.7, rmw_frac=0.2, seed=44))
    a = FastRuntime(HermesConfig(**base), record=True)
    b = FastRuntime(HermesConfig(read_unroll=3, **base), record=True)
    assert a.drain(500) and b.drain(500)
    assert b.step_idx < a.step_idx, "unroll should finish the stream sooner"
    ca, cb = a.counters(), b.counters()
    # reads/writes are timing-independent; RMW conflict outcomes may shift
    # with the interleaving, but every RMW still resolves exactly once
    assert ca["n_read"] == cb["n_read"]
    assert ca["n_write"] == cb["n_write"]
    assert ca["n_rmw"] + ca["n_abort"] == cb["n_rmw"] + cb["n_abort"]
    assert a.check().ok and b.check().ok


def test_read_unroll_sharded_matches_batched():
    import jax
    from jax.sharding import Mesh

    cfg = HermesConfig(
        n_replicas=8, n_keys=128, n_sessions=4, replay_slots=4,
        ops_per_session=12, read_unroll=2,
        workload=WorkloadConfig(read_frac=0.6, rmw_frac=0.2, seed=45),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = FastRuntime(cfg, backend="batched", record=True)
    b = FastRuntime(cfg, backend="sharded", mesh=mesh)
    assert a.drain(300) and b.drain(300)
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write", "n_rmw", "n_abort"):
        assert ca[k] == cb[k], k
    assert a.check().ok


def test_pending_write_uids_recorded_after_failure():
    """A session left in-flight at check time must have its maybe_w uid
    recorded from the value WORDS, not the raw bytes (the byte-bank layout
    regression class): freeze a replica so a write never resolves, then
    check — the verdict must be clean, which requires the pending uid to
    match what any reader could have observed."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=32, n_sessions=4, replay_slots=2,
        ops_per_session=6, replay_age=1000, replay_scan_every=1000,
        workload=WorkloadConfig(read_frac=0.3, seed=46),
    )
    rt = FastRuntime(cfg, record=True)
    rt.run(2)
    rt.freeze(2)  # quorum stalls: in-flight writes stay S_INFL
    rt.run(8)
    status = get(rt.fs.sess.status)
    assert (status == t.S_INFL).any(), "expected stuck in-flight writes"
    ops = rt.history_ops()
    pend = [o for o in ops if o.kind == "maybe_w"]
    assert pend, "expected maybe_w records for in-flight writes"
    for o in pend:
        # uid hi-word is the replica id (phases._write_value formula); a
        # byte-level misread would leave hi as a mangled byte pattern
        assert 0 <= o.wuid[1] < cfg.n_replicas, o
    assert rt.check().ok


def test_device_stream_zipfian_skew_and_checks():
    """Config-3-shaped (BASELINE.json:9) on the DEVICE stream: the analytic
    Zipfian inverse (ycsb._zipf_rank, no CDF table) must produce the
    YCSB-grade skew — a small set of hot keys absorbing a large op share —
    and the contended run must stay checker-clean.  Device/host agreement
    for zipfian is statistical (f32 pow ULPs can flip rank boundaries), so
    this asserts distribution properties, not per-element equality."""
    from hermes_tpu.workload import ycsb

    cfg = HermesConfig(
        n_replicas=7, n_keys=1 << 14, n_sessions=8, replay_slots=4,
        ops_per_session=16, device_stream=True,
        workload=WorkloadConfig(
            read_frac=0.5, seed=7, distribution="zipfian", zipf_theta=0.99),
    )
    # distribution shape: top-64 of 16384 scrambled-zipfian keys should
    # carry >25% of samples (uniform would give ~0.4%)
    n = 1 << 16
    _, _, keys = ycsb.stream_hash(
        cfg, np.uint32(0), np.arange(n, dtype=np.uint32), np.uint32(0))
    counts = np.bincount(keys.astype(np.int64), minlength=cfg.n_keys)
    top = np.sort(counts)[::-1]
    assert top[:64].sum() > 0.25 * n, top[:8]
    assert counts.max() < 0.5 * n  # scrambling spread the head

    # the device engine agrees with the host twin on the op MIX and runs
    # checker-clean under contention
    rt = FastRuntime(cfg, record=True)
    assert rt.drain(600)
    assert rt.check().ok
    c = rt.counters()
    total = c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"]
    assert total == cfg.n_replicas * cfg.n_sessions * cfg.ops_per_session
    assert 0.35 < c["n_read"] / total < 0.65


def test_packed_ts_overflow_guard_detects():
    """Packed-ts overflow guard (HermesConfig.max_key_versions): rotating a
    key to the version limit must be DETECTED at a counter poll (loud
    RuntimeError pointing at the phases engine), not silently corrupt the
    int32 Lamport compare.  The limit is ~1M versions — unreachable in test
    time by actually writing — so the soak seeds the key near the limit
    (vpts + the mirrored bank pts word) and rotates it across the boundary."""
    import jax.numpy as jnp
    import pytest
    from hermes_tpu.core import faststep as fst

    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=2,
        ops_per_session=64, wrap_stream=True, auto_rebase=False,
        workload=WorkloadConfig(read_frac=0.0, seed=13),
    )
    rt = FastRuntime(cfg)
    # seed key 0 at (limit - 4) versions, VALID, consistent row mirror
    near = cfg.max_key_versions - 4
    seeded_pts = fst.pack_pts(jnp.int32(near), jnp.int32(0))
    tbl = rt.fs.table
    rows32 = fst._bank_to_i32(tbl.bank)
    rows32 = rows32.at[0, fst.BANK_PTS].set(seeded_pts)
    tbl = tbl._replace(
        vpts=tbl.vpts.at[0].set(seeded_pts),
        bank=fst._i32_to_bank(rows32),
    )
    # every session hammers key 0 with writes
    stream = rt.stream._replace(
        op=jnp.full_like(rt.stream.op, t.OP_WRITE),
        key=jnp.zeros_like(rt.stream.key),
    )
    rt.fs = rt.fs._replace(table=tbl)
    rt.stream = stream
    rt.run(2)
    assert rt.counters()["max_ver"] >= near  # watermark tracks the rotation
    rt.run(16)  # crosses the limit (~1 version/round, 4 of headroom)
    with pytest.raises(RuntimeError, match="packed-timestamp overflow"):
        rt.counters()


def test_bench_mix_configs_construct():
    """bench.py's mix configs must stay constructible (config validation
    drift guard — the bench runs on the chip where a late ValueError wastes
    a driver round); latency-mode config included."""
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("bench", root / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    for mix in bench.MIXES:
        cfg = bench._cfg(mix)
        assert cfg.n_keys == 1 << 20
        assert cfg.device_stream
    assert bench._latency_cfg().n_sessions == 1024


def test_arb_mode_sort_checked_and_matches_totals():
    """cfg.arb_mode='sort' (collision-free issue arbitration) must drain the
    same workload checker-clean, with identical per-kind op totals to the
    race mode (both arbitrations are protocol-equivalent; they may differ
    in which ROUND an issue happens, never in what completes), batched and
    sharded alike."""
    import jax
    from jax.sharding import Mesh

    base = dict(
        n_replicas=8, n_keys=128, n_sessions=6, replay_slots=4,
        ops_per_session=10,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.3, seed=41),
    )
    a = FastRuntime(HermesConfig(**base, arb_mode="race"), record=True)
    b = FastRuntime(HermesConfig(**base, arb_mode="sort"), record=True)
    assert a.drain(400) and b.drain(400)
    assert a.check().ok and b.check().ok
    ca, cb = a.counters(), b.counters()
    for k in ("n_read", "n_write"):
        assert ca[k] == cb[k], k
    # rmw+abort split may differ (conflict timing differs); the sum cannot
    assert ca["n_rmw"] + ca["n_abort"] == cb["n_rmw"] + cb["n_abort"]

    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    c = FastRuntime(HermesConfig(**base, arb_mode="sort"),
                    backend="sharded", mesh=mesh)
    assert c.drain(400)
    # sharded sort-mode equals batched sort-mode (lockstep equality)
    np.testing.assert_array_equal(get(b.fs.sess.pts), get(c.fs.sess.pts))




# --------------------------------------------------------------------------
# Intra-round same-key write chaining (cfg.chain_writes; BASELINE.json:9's
# hot-key lever): a replica's wanting plain-write sessions for one key issue
# as a packed-ts chain and commit together in one round.
# --------------------------------------------------------------------------


def _hot_write_stream(cfg, key=0):
    """Every session writes the same key, ops_per_session times."""
    from hermes_tpu.core import state as st

    r, s, g = cfg.n_replicas, cfg.n_sessions, cfg.ops_per_session
    return st.OpStream(
        op=np.full((r, s, g), t.OP_WRITE, np.int32),
        key=np.full((r, s, g), key, np.int32),
        uval=None,
    )


def test_chain_writes_hot_key_service_rate_and_check():
    """With chaining, one round commits ~n_sessions writes of a single hot
    key per replica instead of 1; the drained run stays checker-clean."""
    base = dict(n_replicas=3, n_keys=64, n_sessions=16, replay_slots=4,
                ops_per_session=8, arb_mode="sort")
    commits = {}
    for cw in (0, 16):
        cfg = HermesConfig(**base, chain_writes=cw)
        rt = FastRuntime(cfg, record=False, stream=_hot_write_stream(cfg))
        rt.run(6)
        commits[cw] = rt.counters()["n_write"]
    # unchained: one commit per replica per round; chained: one per wanting
    # session per replica per round
    assert commits[16] >= 8 * commits[0], commits
    rt = drained_checked(
        HermesConfig(**base, chain_writes=16),
        stream=_hot_write_stream(HermesConfig(**base, chain_writes=16)),
    )
    c = rt.counters()
    assert c["n_write"] == 3 * 16 * 8  # every write committed


def test_chain_writes_with_rmws_checked():
    """RMWs never chain behind other writes (their read-part must observe
    the immediately-preceding value) — the checker's RMW witness pins it
    under heavy same-key contention."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=8, n_sessions=12, replay_slots=4,
        ops_per_session=8, arb_mode="sort", chain_writes=8,
        workload=WorkloadConfig(read_frac=0.3, rmw_frac=0.5, seed=11),
    )
    drained_checked(cfg, max_steps=1000)


def test_chain_writes_blocked_quorum_then_flows():
    """Chained in-flight writes survive a blocked quorum: with a frozen
    live replica nothing commits (each chain member holds its distinct ts
    across rebroadcasts); after membership removes it, all flow and check."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=16, n_sessions=8, replay_slots=4,
        ops_per_session=4, arb_mode="sort", chain_writes=8,
        rebroadcast_every=2,
        workload=WorkloadConfig(read_frac=0.0, seed=13),
    )
    rt = FastRuntime(cfg, record=True, stream=_hot_write_stream(cfg))
    rt.freeze(2)
    rt.run(8)
    assert rt.counters()["n_write"] == 0  # quorum blocked: no commits
    rt.remove(2)
    assert rt.drain(400)
    assert rt.check().ok
    # the two surviving replicas' writes all committed (the removed
    # replica is fenced: its own sessions never run)
    assert rt.counters()["n_write"] == 2 * 8 * 4


def test_version_rebase_restores_headroom():
    """rebase_versions (round-4): after a quiesce+rebase, settled keys sit
    at version 1, the watermark drops, and the run continues checked-clean
    with recorded history spanning the rebase (per-key deltas re-anchor
    completions into the global version order)."""
    import jax.numpy as jnp
    from hermes_tpu.core import faststep as fst

    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=16, replay_slots=4,
        ops_per_session=24, workload=WorkloadConfig(read_frac=0.3, seed=21),
    )
    rt = FastRuntime(cfg, record=True)
    rt.run(10)
    pre = rt.counters()["max_ver"]
    assert pre > 1
    n = rt.rebase_versions()
    assert n > 0
    assert rt.counters()["max_ver"] <= pre
    ver = fst.pts_ver(rt.fs.table.vpts)
    import numpy as np
    assert int(jnp.max(ver)) <= max(1, rt._inflight_count() and pre)
    # history across the rebase stays monotone: keep running, then check
    assert rt.drain(2000)
    assert rt.check().ok


def test_auto_rebase_soak_crosses_old_budget(monkeypatch):
    """Round-3 verdict item 4's done-criterion: a sustained hot-key
    chaining soak CROSSES the old version budget while checked-clean — no
    RuntimeError cliff.  The ~1M real budget is unreachable in test time,
    so the budget property is shrunk to 512; auto-rebase (counter polls)
    must then keep the on-device watermark under it indefinitely while the
    cumulative global version climbs far past it."""
    import numpy as np

    monkeypatch.setattr(HermesConfig, "max_key_versions",
                        property(lambda self: 512))
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=64, replay_slots=4,
        ops_per_session=64, wrap_stream=True,
        arb_mode="sort", chain_writes=8,
        workload=WorkloadConfig(read_frac=0.2, seed=22),
    )
    # hammer a tiny key set so chains burn versions fast
    rt = FastRuntime(cfg, record="array")
    import jax.numpy as jnp
    rt.stream = rt.stream._replace(key=rt.stream.key % 4)
    crossed = 0
    for _ in range(40):
        rt.run(4)
        c = rt.counters()  # poll: triggers auto-rebase past the soft mark
        assert c["max_ver"] < 512  # never reaches the (shrunk) cliff
    assert rt.rebases >= 1
    # cumulative global version crossed the old budget
    assert int(rt._ver_base.max()) + int(c["max_ver"]) > 512
    rt.quiesce = True
    for _ in range(200):
        if rt._inflight_count() == 0:
            break
        rt.step_once()
    assert rt.check().ok


def test_rebase_preserves_host_quiesce_flag():
    cfg = HermesConfig(
        n_replicas=3, n_keys=32, n_sessions=8, replay_slots=4,
        ops_per_session=8, workload=WorkloadConfig(read_frac=0.5, seed=23),
    )
    rt = FastRuntime(cfg)
    rt.run(3)
    rt.quiesce = True
    rt.rebase_versions()
    assert rt.quiesce is True  # host-initiated quiesce survives the rebase


def test_rebase_during_kvs_inflight_resolves_futures():
    """The rebase quiesce drain steps through the KVS layer (comp_sink), so
    client ops completing inside the drain still resolve their futures."""
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=8, replay_slots=4,
        ops_per_session=8, value_words=4,
        workload=WorkloadConfig(read_frac=0.5, seed=24),
    )
    kvs = KVS(cfg, record=True)
    futs = [kvs.put(0, s, s, [s + 100]) for s in range(4)]
    kvs.step()  # inject + issue: some ops now genuinely in flight
    n = kvs.rt.rebase_versions()  # drain must route through kvs.step
    assert all(f.done() for f in futs) or kvs.run_until(futs, 50)
    assert kvs.rt.check().ok


def test_sharded_rebase_nonuniform_keys_vetoed():
    """The sharded rebase's cross-chip uniformity reduction: a key whose
    table rows DISAGREE between chips must be vetoed everywhere (the
    replicated delta out_spec demands identical per-chip decisions), while
    agreed keys still rebase.  Divergence cannot arise from faststep's own
    stall model (a frozen chip still applies inbound INVs — outbound-only
    suppression), so the stale copy is manufactured directly."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg = HermesConfig(
        n_replicas=8, n_keys=64, n_sessions=4, replay_slots=4,
        ops_per_session=8,
        workload=WorkloadConfig(read_frac=0.2, seed=25),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    rt = FastRuntime(cfg, backend="sharded", mesh=mesh)
    assert rt.drain(300)
    pre = get(fst.pts_ver(rt.fs.table.vpts)).reshape(8, 64)
    hot = int(np.argmax(pre[0]))
    assert pre[0, hot] > 1
    # manufacture a stale copy of `hot` on chip 7 (e.g. a torn join)
    vpts = get(rt.fs.table.vpts).copy().reshape(8, 64)
    stale_pts = int(fst.pack_pts(jnp.int32(1), jnp.int32(3)))
    vpts[7, hot] = stale_pts
    sh = NamedSharding(mesh, P("replica"))
    rt.fs = rt.fs._replace(table=rt.fs.table._replace(
        vpts=jax.device_put(jnp.asarray(vpts.reshape(-1)), sh)))
    n = rt.rebase_versions(max_quiesce_rounds=8)
    ver = get(fst.pts_ver(rt.fs.table.vpts)).reshape(8, 64)
    # the non-uniform key kept its (divergent) versions on every chip
    assert ver[0, hot] == pre[0, hot]
    assert ver[7, hot] == 1  # the stale copy as manufactured
    # agreed hot keys were rebased
    agreed_hot = pre[0] > 1
    agreed_hot[hot] = False
    if agreed_hot.any():
        assert (ver[0][agreed_hot] == 1).all()
        assert n > 0


def test_auto_rebase_backoff_latch(monkeypatch):
    """When a rebase can't reclaim the watermark (busy key pinned by a
    frozen coordinator), subsequent counter polls must NOT re-pay the
    quiesce drain until the watermark grows again."""
    monkeypatch.setattr(HermesConfig, "max_key_versions",
                        property(lambda self: 1 << 16))
    cfg = HermesConfig(
        n_replicas=3, n_keys=32, n_sessions=4, replay_slots=2,
        ops_per_session=8, wrap_stream=True,
        workload=WorkloadConfig(read_frac=0.0, seed=26),
    )
    rt = FastRuntime(cfg)
    import jax.numpy as jnp
    near = (1 << 15) + 10  # past the soft mark (fraction 0.5)
    seeded = fst.pack_pts(jnp.int32(near), jnp.int32(0))
    tbl = rt.fs.table
    rows32 = fst._bank_to_i32(tbl.bank)
    rows32 = rows32.at[0, fst.BANK_PTS].set(seeded)
    rt.fs = rt.fs._replace(table=tbl._replace(
        vpts=tbl.vpts.at[0].set(seeded), bank=fst._i32_to_bank(rows32)))
    # pin key 0 BUSY: an active replay slot that can never resolve (all
    # replicas frozen) — the rebase must veto it and reclaim nothing
    rt.fs = rt.fs._replace(
        replay=rt.fs.replay._replace(
            active=rt.fs.replay.active.at[0, 0].set(True),
            key=rt.fs.replay.key.at[0, 0].set(0),
            pts=rt.fs.replay.pts.at[0, 0].set(seeded)),
        meta=rt.fs.meta._replace(
            max_pts=jnp.full_like(rt.fs.meta.max_pts, seeded)))
    for r in range(3):
        rt.freeze(r)
    rt.counters()  # first poll: pays one (futile) rebase attempt
    first = rt.rebases
    next_at = rt._next_rebase_at
    assert next_at > near
    steps_before = rt.step_idx
    rt.counters()  # second poll: latched — no new drain rounds
    assert rt.step_idx == steps_before
    assert rt.rebases == first


def test_deep_chain_single_key_checked():
    """Full-depth chaining (chain_writes >= every wanting session): all of
    a replica's writers to ONE key commit each round as a single packed-ts
    chain, and the recorded history still checks clean — pins the
    linearizability of the deep-chain operating point the bench sweep
    selects (chain up to 1024 on chip)."""
    import jax.numpy as jnp

    cfg = HermesConfig(
        n_replicas=3, n_keys=32, n_sessions=64, replay_slots=4,
        ops_per_session=8, arb_mode="sort", chain_writes=64,
        workload=WorkloadConfig(read_frac=0.1, seed=27),
    )
    rt = FastRuntime(cfg, record="array")
    # every write targets key 0
    rt.stream = rt.stream._replace(key=jnp.zeros_like(rt.stream.key))
    assert rt.drain(400)
    c = rt.counters()
    assert c["n_write"] + c["n_rmw"] + c["n_read"] + c["n_abort"] \
        == 3 * 64 * 8
    # the chain actually formed: total versions burned on key 0 ~= commits
    assert c["max_ver"] > 64  # far beyond one-per-round serialization
    assert rt.check().ok


def test_bench_cfg_override_contract():
    """bench._cfg is the single cell-runner config source (sweeps, checked
    windows, soak all build through it): any field may be overridden, and
    the lane budget tracks an overridden session count at the 3/4 ratio
    unless explicitly pinned."""
    import importlib.util
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location("bench", root / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    base = bench._cfg("a")
    assert base.arb_mode == "sort" and base.n_sessions == 65536
    assert base.lane_budget == 49152
    z = bench._cfg("zipfian")
    assert z.chain_writes == 2048 and z.n_sessions == 32768

    o = bench._cfg("zipfian", over=dict(n_sessions=65536))
    assert o.lane_budget == 49152  # ratio tracked the override
    p = bench._cfg("zipfian", over=dict(n_sessions=65536,
                                        lane_budget_cfg=1024))
    assert p.lane_budget == 1024  # explicit pin wins
    q = bench._cfg("a", over=dict(arb_mode="race", chain_writes=0))
    assert q.arb_mode == "race" and q.chain_writes == 0


def test_recorder_monotone_across_multiple_rebases():
    """The recorder's re-anchored (ver, fc) witness order must be STRICTLY
    monotone per key across several rebase eras — the property the checker's
    timestamp witness depends on (cross-era version reuse would alias two
    different writes to one timestamp)."""
    cfg = HermesConfig(
        n_replicas=3, n_keys=16, n_sessions=16, replay_slots=4,
        ops_per_session=64, wrap_stream=True, arb_mode="sort",
        chain_writes=8,
        workload=WorkloadConfig(read_frac=0.2, seed=28),
    )
    rt = FastRuntime(cfg, record="array")
    import jax.numpy as jnp
    rt.stream = rt.stream._replace(key=rt.stream.key % 2)  # two hot keys
    for _ in range(3):
        rt.run(15)
        assert rt.rebase_versions() > 0
    rt.run(10)
    assert rt.rebases >= 3
    cols = rt.recorder.columns()
    writes = cols["kind"] != 0  # K_READ == 0
    for k in np.unique(cols["key"][writes]):
        ts = cols["ts"][writes & (cols["key"] == k)]
        ts = np.sort(ts)
        assert (np.diff(ts) > 0).all(), f"duplicate/regressed ts on key {k}"
    # and the full gate agrees
    rt.quiesce = True
    for _ in range(100):
        if rt._inflight_count() == 0:
            break
        rt.step_once()
    assert rt.check().ok
