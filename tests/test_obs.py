"""Unified observability layer (hermes_tpu/obs + the Meta phase columns).

Pins the three pillars: (1) the registry/exporter machinery (metric types,
get-or-create semantics, Prometheus snapshot, the byte-compatible unstamped
JSONL mode), (2) the obs run-log schema — every record carries ``t`` and
``kind`` with non-decreasing ``t`` — and (3) the fault-event timeline: a
freeze/thaw cycle appears as ordered events bracketing the throughput dip.
Also the percentile sentinel regression (empty histogram -> None, field
omitted from summarize output — never ``-1`` poisoning downstream JSON).
"""

import io
import json

import numpy as np
import pytest

from hermes_tpu import stats
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import state as st
from hermes_tpu.obs import (
    BufferExporter,
    Histogram,
    JsonlExporter,
    MetricsRegistry,
    Observability,
    percentile_from_counts,
    prometheus_text,
)
from hermes_tpu.obs import report as report_lib
from hermes_tpu.runtime import FastRuntime
from hermes_tpu.transport.sim import SimTransport


def small_cfg(**kw):
    base = dict(
        n_replicas=3, n_keys=256, n_sessions=16, replay_slots=8,
        ops_per_session=32,
        workload=WorkloadConfig(read_frac=0.5, seed=7),
    )
    base.update(kw)
    return HermesConfig(**base)


# --- pillar 2: registry + exporters ----------------------------------------


def test_registry_get_or_create_and_type_guard():
    reg = MetricsRegistry()
    c = reg.counter("commits")
    c.inc()
    c.inc(4)
    assert reg.counter("commits").value == 5  # same object back
    reg.gauge("depth").set(17)
    h = reg.histogram("lat", bins=8)
    h.observe(3)
    h.observe(100)  # clips into the last bin
    assert h.total == 2 and h.counts[7] == 1
    with pytest.raises(TypeError):
        reg.gauge("commits")
    with pytest.raises(TypeError):
        reg.histogram("depth")


def test_registry_snapshot_derives_percentiles_and_omits_empty():
    reg = MetricsRegistry()
    reg.counter("n").set_total(42)
    reg.histogram("lat", bins=4).observe(1, n=10)
    reg.histogram("empty", bins=4)
    snap = reg.snapshot()
    assert snap["n"] == 42
    assert snap["lat_p50"] == 1 and snap["lat_p99"] == 1
    assert "empty_p50" not in snap and "empty_p99" not in snap
    json.dumps(snap)  # JSON-clean


def test_histogram_set_counts_rejects_wrong_bins():
    h = Histogram("x", bins=4)
    with pytest.raises(ValueError):
        h.set_counts(np.zeros(8, np.int64))


def test_prometheus_text_snapshot():
    reg = MetricsRegistry()
    reg.counter("ops", help="total ops").inc(3)
    reg.gauge("depth").set(2)
    reg.histogram("lat", bins=3).observe(1, n=2)
    text = prometheus_text(reg)
    assert "# TYPE ops counter\nops 3" in text
    assert "# TYPE depth gauge\ndepth 2" in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 2' in text and "lat_count 2" in text


def test_unstamped_exporter_is_byte_compatible_with_json_dumps():
    buf = io.StringIO()
    rec = {"metric": "committed_writes_per_sec", "value": 1.5, "none": None}
    JsonlExporter(buf, stamp=False).write(rec)
    assert buf.getvalue() == json.dumps(rec) + "\n"


# --- percentile sentinel regression (satellite 1) --------------------------


def test_percentile_empty_hist_returns_none_not_sentinel():
    assert percentile_from_counts(np.zeros(16, np.int64), 0.5) is None
    assert stats.percentile_from_hist(np.zeros(st.LAT_BINS), 0.99) is None
    h = np.zeros(16, np.int64)
    h[3] = 1
    assert percentile_from_counts(h, 0.5) == 3


def test_summarize_omits_percentiles_on_empty_histogram():
    cfg = small_cfg()
    meta = fst.init_fast_state(cfg).meta  # all-zero: nothing committed yet
    rec = stats.summarize(meta)
    assert "p50_commit_steps" not in rec and "p99_commit_steps" not in rec
    assert rec["commits"] == 0
    json.dumps(rec)


# --- pillar 1: device-side phase metrics -----------------------------------


def test_phase_metrics_populate_and_do_not_change_behavior():
    import jax

    base_cols = ("n_read", "n_write", "n_rmw", "n_abort",
                 "lat_sum", "lat_cnt", "lat_hist", "max_pts")
    metas = {}
    for on in (True, False):
        rt = FastRuntime(small_cfg(phase_metrics=on))
        assert rt.drain(400)
        metas[on] = jax.device_get(rt.fs.meta)
    m_on, m_off = metas[True], metas[False]
    for f in base_cols:
        assert np.array_equal(np.asarray(getattr(m_on, f)),
                              np.asarray(getattr(m_off, f))), f
    assert int(np.asarray(m_on.n_inv).sum()) > 0
    assert int(np.asarray(m_on.qwait_hist).sum()) == int(
        np.asarray(m_on.n_write).sum() + np.asarray(m_on.n_rmw).sum())
    for f in ("n_inv", "n_rebcast", "n_nack", "n_retry", "replay_peak",
              "qwait_sum", "qwait_hist"):
        assert not np.asarray(getattr(m_off, f)).any(), f
    rec = stats.summarize(m_on)
    assert rec["n_inv"] > 0 and "p50_qwait_steps" in rec


# --- pillar 3: run-log schema + fault timeline -----------------------------


def test_obs_jsonl_schema_t_and_kind_monotonic(tmp_path):
    path = tmp_path / "run.jsonl"
    cfg = small_cfg()
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability(path=str(path), trace_steps=True))
    rt.run(3)
    rt.freeze(1)
    rt.thaw(1)
    obs.interval(stats.summarize(rt.fs.meta, wall_s=0.1, steps=3))
    assert rt.drain(400)
    v = rt.check()
    assert v.ok
    obs.summary(stats.summarize(rt.fs.meta, hists=True))
    obs.close()

    records = report_lib.load_records([str(path)])
    assert len(records) > 6
    last_t = 0.0
    for r in records:
        assert "t" in r and "kind" in r, r
        assert r["t"] >= last_t, "t must be non-decreasing"
        last_t = r["t"]
    kinds = {r["kind"] for r in records}
    assert {"event", "metrics", "summary", "span_begin",
            "span_end"} <= kinds
    names = [r.get("name") for r in records if r["kind"] == "event"]
    assert "freeze" in names and "thaw" in names
    assert "checker_verdict" in names
    # drain ran under a span; per-step spans carry matched begin/end
    spans = [r["name"] for r in records if r["kind"] == "span_end"]
    assert "drain" in spans and "step_dispatch" in spans


def test_tracer_timeline_contract_schema_pairing_and_labels():
    """The obs/trace.py record contract: every record carries t/kind/name
    with non-decreasing t, spans close as begin/end PAIRS (two records,
    not one stamped at begin time), dur_s rides only the end, and caller
    labels (step, fleet group, replica) pass through both halves
    verbatim — the invariants naive line-order timeline merging rests
    on."""
    from hermes_tpu.obs.trace import Tracer

    exp = BufferExporter()
    tr = Tracer(exp)
    tr.event("freeze", replica=2, group=1)
    with tr.span("step_dispatch", step=7, group=1):
        tr.event("suspect", replica=0)
    t0 = tr.span_begin("readback", step=8)
    tr.span_end("readback", t0, step=8)

    recs = exp.records
    last = 0.0
    for r in recs:
        assert {"t", "kind", "name"} <= set(r)
        assert r["t"] >= last, "t must be non-decreasing across ALL kinds"
        last = r["t"]
    assert [(r["kind"], r["name"]) for r in recs] == [
        ("event", "freeze"),
        ("span_begin", "step_dispatch"),
        ("event", "suspect"),          # nested event inside the open span
        ("span_end", "step_dispatch"),
        ("span_begin", "readback"),
        ("span_end", "readback"),
    ]
    begins = [r for r in recs if r["kind"] == "span_begin"]
    ends = [r for r in recs if r["kind"] == "span_end"]
    assert [b["name"] for b in begins] == [e["name"] for e in ends]
    for b, e in zip(begins, ends):
        assert "dur_s" not in b and e["dur_s"] >= 0
    # labels ride the begin record (the span() context manager stamps
    # fields at open; the end half carries the measured dur_s)
    b_sd = [b for b in begins if b["name"] == "step_dispatch"][0]
    assert b_sd["group"] == 1 and b_sd["step"] == 7
    events = [r for r in recs if r["kind"] == "event"]
    assert events[0]["replica"] == 2 and events[0]["group"] == 1


def test_fault_timeline_orders_freeze_thaw_around_dip():
    """A frozen replica blocks the ack quorum: commits stall between the
    freeze and thaw events, and recover after — in ONE ordered record
    stream (the 'what did the cluster look like' story)."""
    cfg = small_cfg(n_sessions=8, ops_per_session=64, wrap_stream=True)
    rt = FastRuntime(cfg)
    obs = rt.attach_obs(Observability())  # in-memory sink

    def commits_now():
        import jax

        m = jax.device_get(rt.fs.meta)
        return int(np.asarray(m.n_write).sum() + np.asarray(m.n_rmw).sum())

    def tick(n):
        rt.run(n)
        obs.interval({"commits": commits_now(), "step": rt.step_idx})

    tick(10)
    before = commits_now()
    assert before > 0
    rt.freeze(2)
    tick(10)
    during = commits_now()
    rt.thaw(2)
    tick(15)
    after = commits_now()

    assert during == before, "commits must stall while the quorum is broken"
    assert after > during, "commits must recover after thaw"

    recs = obs.records
    order = [(r["kind"], r.get("name")) for r in recs]
    i_freeze = order.index(("event", "freeze"))
    i_thaw = order.index(("event", "thaw"))
    assert i_freeze < i_thaw
    # one metrics record strictly between freeze and thaw, one after thaw
    between = [r for r in recs[i_freeze + 1:i_thaw] if r["kind"] == "metrics"]
    post = [r for r in recs[i_thaw + 1:] if r["kind"] == "metrics"]
    assert between and between[-1]["commits"] == before
    assert post and post[-1]["commits"] == after
    ts = [r["t"] for r in recs]
    assert ts == sorted(ts)


def test_report_renders_faults_throughput_and_histograms():
    exp = BufferExporter()
    exp.write({"commits": 100, "steps": 10}, kind="metrics")
    exp.write({"name": "freeze", "step": 12, "replica": 1}, kind="event")
    exp.write({"commits": 100, "steps": 20}, kind="metrics")
    exp.write({"name": "thaw", "step": 25, "replica": 1}, kind="event")
    hist = [0] * st.LAT_BINS
    hist[0], hist[2] = 90, 10
    exp.write({"commits": 250, "steps": 40, "lat_hist": hist,
               "qwait_hist": hist}, kind="summary")
    out = report_lib.render_report(exp.records)
    assert "freeze" in out and "thaw" in out
    assert "membership / fault events (2)" in out
    assert "commit latency" in out and "ACK quorum-wait" in out
    assert "p50=0" in out
    ivals = report_lib.interval_throughput(exp.records)
    assert [iv["commits"] for iv in ivals] == [0, 150]


# --- transport registry feed -----------------------------------------------


def test_sim_transport_feeds_registry_drop_dup_counts():
    reg = MetricsRegistry()

    def chaos(kind, src, dst, step):
        if kind == "inv" and dst == 1:
            return []  # drop every INV into replica 1
        if kind == "ack" and src == 0:
            return [step, step + 1]  # duplicate ACKs out of replica 0
        return [step]

    from hermes_tpu.runtime import Runtime

    cfg = small_cfg(n_keys=64, n_sessions=4, ops_per_session=8)
    tr = SimTransport(cfg.n_replicas, schedule=chaos, registry=reg)
    rt = Runtime(cfg, backend="sim", transport=tr, record=True)
    rt.run(12)
    assert reg.counter("net_inv_sends").value > 0
    assert reg.counter("net_inv_dropped").value > 0
    assert reg.counter("net_ack_duplicated").value > 0
    assert reg.counter("net_inv_delivered").value > 0
    tr.pending()
    assert "net_pending_blocks" in reg
    snap = reg.snapshot()
    assert snap["net_inv_sends"] >= snap["net_inv_dropped"]
