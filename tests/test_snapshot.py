"""Snapshot/restore (hermes_tpu/snapshot.py, SURVEY.md §5.4): a mid-run
snapshot resumes deterministically."""

import numpy as np

from hermes_tpu import snapshot
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.runtime import FastRuntime

from helpers import get


def test_snapshot_resume_deterministic(tmp_path):
    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=61))
    a = FastRuntime(cfg)
    a.run(7)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)

    b = FastRuntime(cfg)
    snapshot.load(p, b)
    assert b.step_idx == 7
    np.testing.assert_array_equal(get(a.fs.table.vpts), get(b.fs.table.vpts))
    np.testing.assert_array_equal(get(a.fs.table.bank), get(b.fs.table.bank))

    a.run(10)
    b.run(10)
    np.testing.assert_array_equal(get(a.fs.table.vpts), get(b.fs.table.vpts))
    np.testing.assert_array_equal(get(a.fs.table.bank), get(b.fs.table.bank))
    np.testing.assert_array_equal(get(a.fs.table.val), get(b.fs.table.val))
    np.testing.assert_array_equal(get(a.fs.sess.status), get(b.fs.sess.status))


def test_snapshot_config_mismatch_rejected(tmp_path):
    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=62))
    a = FastRuntime(cfg)
    a.run(2)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)
    other = FastRuntime(HermesConfig(n_replicas=3, n_keys=256, n_sessions=8,
                                     replay_slots=4, ops_per_session=16))
    import pytest

    with pytest.raises(ValueError):
        snapshot.load(p, other)


def test_kvs_sparse_snapshot_roundtrip(tmp_path):
    """A sparse-key KVS snapshot captures the KeyIndex: the restored KVS
    resolves the same 64-bit client keys to the same dense slots, reads
    back pre-snapshot values, and keeps serving new ops."""
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu import snapshot

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
                       replay_slots=8)
    a = KVS(cfg, sparse_keys=True)
    k1, k2 = 0xDEAD_BEEF_0000_0001, (1 << 61) + 7
    assert a.run_until([a.put(0, 0, k1, [11]), a.put(1, 1, k2, [22])])
    p = str(tmp_path / "kvs.npz")
    snapshot.save(p, a)

    b = KVS(cfg, sparse_keys=True)
    snapshot.load(p, b)
    assert b.index.slot(k1, insert=False) == a.index.slot(k1, insert=False)
    assert len(b.index) == len(a.index)
    g1, g2 = b.get(2, 0, k1), b.get(0, 2, k2)
    assert b.run_until([g1, g2])
    assert g1.result().value[:1] == [11] and g2.result().value[:1] == [22]
    # restored KVS keeps serving: new key allocates the next dense slot
    f = b.put(0, 3, 999, [33])
    assert b.run_until([f])
    assert b.index.slot(999, insert=False) == len(a.index)


def test_kvs_snapshot_refuses_inflight():
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu import snapshot
    import pytest

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
                       replay_slots=8)
    kvs = KVS(cfg, sparse_keys=True)
    kvs.put(0, 0, 42, [1])  # queued, unresolved
    with pytest.raises(ValueError, match="quiescent"):
        snapshot.save("/tmp/should_not_exist.npz", kvs)


def test_kvs_load_validates_before_mutating():
    """A rejected load leaves the target untouched: wrong-mode and
    non-quiescent targets raise with no partial restore."""
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu import snapshot
    import pytest

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=4, value_words=6,
                       replay_slots=8)
    src = KVS(cfg, sparse_keys=True)
    assert src.run_until([src.put(0, 0, 0xABC, [9])])
    import tempfile, os
    p = os.path.join(tempfile.mkdtemp(), "s.npz")
    snapshot.save(p, src)

    # dense target must refuse a sparse snapshot (mapping would be lost)
    dense = KVS(cfg)
    with pytest.raises(ValueError, match="sparse_keys=True"):
        snapshot.load(p, dense)

    # non-quiescent target must refuse, and stay intact
    busy = KVS(cfg, sparse_keys=True)
    fut = busy.put(0, 0, 5, [1])
    with pytest.raises(ValueError, match="quiescent"):
        snapshot.load(p, busy)
    assert busy.run_until([fut])  # its pending op still completes


def test_truncated_archive_rejected_before_mutation(tmp_path):
    """Round-3 advisor: a corrupt/truncated npz (missing state.* keys) must
    reject BEFORE anything — KVS arrays included — is overwritten."""
    import zipfile

    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=8, replay_slots=4,
                       ops_per_session=16, value_words=4,
                       workload=WorkloadConfig(seed=63))
    kvs = KVS(cfg)
    kvs.run_until([kvs.put(0, 0, 3, [7])])
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, kvs)

    # truncate: drop one state.* member from the zip archive
    trunc = str(tmp_path / "trunc.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(trunc, "w") as zout:
        victims = [n for n in zin.namelist() if n.startswith("state.")]
        for name in zin.namelist():
            if name != victims[0]:
                zout.writestr(name, zin.read(name))

    target = KVS(cfg)
    before_op = target._op.copy()
    before_key = target._key.copy()
    try:
        snapshot.load(trunc, target)
        raise AssertionError("truncated archive must be rejected")
    except ValueError as e:
        assert "incomplete" in str(e)
    np.testing.assert_array_equal(target._op, before_op)
    np.testing.assert_array_equal(target._key, before_key)


def test_snapshot_carries_rebase_bookkeeping(tmp_path):
    """Round-4 advisor: a post-rebase snapshot must persist the version-
    rebase bookkeeping (_ver_base etc.) so completions recorded after a
    restore re-anchor from the right era; a pre-round-5 archive without it
    must refuse to land on an already-rebased target."""
    import zipfile

    import pytest

    cfg = HermesConfig(n_replicas=3, n_keys=32, n_sessions=8, replay_slots=4,
                       ops_per_session=16, wrap_stream=True,
                       workload=WorkloadConfig(seed=66, read_frac=0.0))
    a = FastRuntime(cfg)
    a.run(30)
    assert a.rebase_versions() > 0 and a._ver_base is not None
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)

    b = FastRuntime(cfg)
    snapshot.load(p, b)
    assert b.rebases == a.rebases
    assert b._next_rebase_at == a._next_rebase_at
    np.testing.assert_array_equal(b._ver_base, a._ver_base)

    # strip the bookkeeping entries to fake a pre-round-5 archive: loading
    # it into the (already-rebased) target must raise before mutation
    old = str(tmp_path / "old.npz")
    drop = ("ctl.ver_base", "ctl.rebases", "ctl.next_rebase_at",
            "ctl.quiesce")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(old, "w") as zout:
        for name in zin.namelist():
            if not name.startswith(drop):
                zout.writestr(name, zin.read(name))
    with pytest.raises(ValueError, match="rebase"):
        snapshot.load(old, b)


def test_never_rebased_snapshot_writes_sentinel_not_zeros(tmp_path):
    """Round-5 advice #2: a runtime that never rebased must not ship n_keys
    of int64 zeros as ctl.ver_base (~8 MB dead payload at the 1M-key
    shape) — it writes a zero-length sentinel, load() keys on the shape,
    and the truncation checks still see the entry."""
    cfg = HermesConfig(n_replicas=3, n_keys=256, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=71))
    a = FastRuntime(cfg)
    a.run(5)
    assert a._ver_base is None
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)
    z = np.load(p)
    assert "ctl.ver_base" in z  # still present: truncation checks intact
    assert z["ctl.ver_base"].size == 0

    b = FastRuntime(cfg)
    snapshot.load(p, b)
    assert b._ver_base is None
    assert b.step_idx == 5
    # and a REBASED runtime still round-trips its real deltas (non-empty)
    a.run(25)
    if a.rebase_versions() > 0:
        snapshot.save(p, a)
        assert np.load(p)["ctl.ver_base"].size == cfg.n_keys


def test_sharded_snapshot_roundtrip(tmp_path):
    """Snapshot/restore over the sharded (tpu_ici-shaped) backend: the
    global device arrays flatten and rebuild with the same values, and the
    restored runtime continues deterministically."""
    import jax
    from jax.sharding import Mesh

    cfg = HermesConfig(n_replicas=8, n_keys=64, n_sessions=4, replay_slots=4,
                       ops_per_session=8, workload=WorkloadConfig(seed=65))
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    a = FastRuntime(cfg, backend="sharded", mesh=mesh)
    a.run(5)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)
    b = FastRuntime(cfg, backend="sharded", mesh=mesh)
    snapshot.load(p, b)
    assert b.step_idx == 5
    a.run(8)
    b.run(8)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a.fs.table.vpts)),
        np.asarray(jax.device_get(b.fs.table.vpts)))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(a.fs.sess.status)),
        np.asarray(jax.device_get(b.fs.sess.status)))


def test_range_archive_refused_as_full_restore(tmp_path):
    """Scope red test (round-10): a range-scoped migration archive can
    NEVER be mistaken for a crash-recovery archive — ``load`` refuses it
    on the manifest scope before touching any state, and the inverse path
    (``read_range`` of a full archive) refuses too."""
    import pytest

    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=66))
    a = FastRuntime(cfg)
    a.run(5)
    rp = str(tmp_path / "range.npz")
    fp = str(tmp_path / "full.npz")
    m = snapshot.save_range(rp, a, 16, 48)
    assert m["scope"] == "range:[16,48)"
    snapshot.save(fp, a)
    assert snapshot.read_manifest(fp)["scope"] == "full"

    tgt = FastRuntime(cfg)
    before = get(tgt.fs.table.vpts).copy()
    with pytest.raises(ValueError, match="scope="):
        snapshot.load(rp, tgt)
    np.testing.assert_array_equal(before, get(tgt.fs.table.vpts))
    with pytest.raises(ValueError, match="not a range transfer"):
        snapshot.read_range(fp)


def test_range_archive_roundtrip_and_checksum(tmp_path):
    """save_range -> load_range restores the exact rows (identity
    placement), leaves everything outside the range untouched, and a
    bit-flipped range archive rejects on its checksum."""
    import zipfile

    import pytest

    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=67))
    a = FastRuntime(cfg)
    a.run(6)
    a.drain(200)
    p = str(tmp_path / "range.npz")
    snapshot.save_range(p, a, 32, 64)

    tgt = FastRuntime(cfg)
    outside = get(tgt.fs.table.vpts).copy()
    snapshot.load_range(p, tgt)
    np.testing.assert_array_equal(
        get(a.fs.table.vpts)[32:64], get(tgt.fs.table.vpts)[32:64])
    np.testing.assert_array_equal(
        get(a.fs.table.bank)[32:64], get(tgt.fs.table.bank)[32:64])
    np.testing.assert_array_equal(
        outside[:32], get(tgt.fs.table.vpts)[:32])
    np.testing.assert_array_equal(
        outside[64:], get(tgt.fs.table.vpts)[64:])

    torn = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("range.bank"):
                data[len(data) // 2] ^= 0xFF
            zout.writestr(name, bytes(data))
    with pytest.raises(ValueError, match="checksum|torn"):
        snapshot.read_range(torn)
