"""Snapshot/restore (hermes_tpu/snapshot.py, SURVEY.md §5.4): a mid-run
snapshot resumes deterministically."""

import numpy as np

from hermes_tpu import snapshot
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.runtime import FastRuntime

from helpers import get


def test_snapshot_resume_deterministic(tmp_path):
    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=61))
    a = FastRuntime(cfg)
    a.run(7)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)

    b = FastRuntime(cfg)
    snapshot.load(p, b)
    assert b.step_idx == 7
    np.testing.assert_array_equal(get(a.fs.table.vpts), get(b.fs.table.vpts))
    np.testing.assert_array_equal(get(a.fs.table.bank), get(b.fs.table.bank))

    a.run(10)
    b.run(10)
    np.testing.assert_array_equal(get(a.fs.table.vpts), get(b.fs.table.vpts))
    np.testing.assert_array_equal(get(a.fs.table.bank), get(b.fs.table.bank))
    np.testing.assert_array_equal(get(a.fs.table.val), get(b.fs.table.val))
    np.testing.assert_array_equal(get(a.fs.sess.status), get(b.fs.sess.status))


def test_snapshot_config_mismatch_rejected(tmp_path):
    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4,
                       ops_per_session=16, workload=WorkloadConfig(seed=62))
    a = FastRuntime(cfg)
    a.run(2)
    p = str(tmp_path / "snap.npz")
    snapshot.save(p, a)
    other = FastRuntime(HermesConfig(n_replicas=3, n_keys=256, n_sessions=8,
                                     replay_slots=4, ops_per_session=16))
    import pytest

    with pytest.raises(ValueError):
        snapshot.load(p, other)
