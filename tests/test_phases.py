"""State-machine unit tests (SURVEY.md §4.1): transition rules, ack-bitmap
commit predicate, RMW abort rule, same-ts idempotence — the invariants the
replay path (SURVEY.md §3.4) and the YCSB-F conflict path (BASELINE.json:8)
rely on."""

import jax.numpy as jnp
import numpy as np

from hermes_tpu.core import phases, state as st
from hermes_tpu.core import types as t
from hermes_tpu.core.timestamps import make_fc

from helpers import ack_block, ctl_scalars, empty_stream, get, inv_block, tiny_cfg


def fresh(cfg):
    rs = st.init_replica_state(cfg)
    return rs.table, rs.sess, rs.replay, rs.meta


def test_apply_inv_applies_higher_ts_and_acks():
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    fc = int(make_fc(t.FLAG_WRITE, 1))
    inv = inv_block(cfg, [(1, 0, 5, 1, fc, [42, 7])])
    out = phases.apply_inv(cfg, ctl_scalars(cfg=cfg), table, sess, meta, inv)
    assert get(out.table.state)[5] == t.INVALID
    assert get(out.table.ver)[5] == 1 and get(out.table.fc)[5] == fc
    assert get(out.table.val)[5, 0] == 42
    # always-ack: the ack echoes the INV's ts back on the same (sender, lane)
    assert bool(get(out.out_ack.valid)[1, 0])
    assert get(out.out_ack.ver)[1, 0] == 1 and get(out.out_ack.fc)[1, 0] == fc
    # untouched keys stay Valid
    assert get(out.table.state)[6] == t.VALID


def test_apply_inv_same_ts_idempotent_but_acked():
    """Replay safety (SURVEY.md §3.4): re-INV with the same ts changes
    nothing but is still acked."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    fc = int(make_fc(t.FLAG_WRITE, 1))
    inv = inv_block(cfg, [(1, 0, 5, 1, fc, [42, 7])])
    ctl = ctl_scalars(cfg=cfg)
    out1 = phases.apply_inv(cfg, ctl, table, sess, meta, inv)
    out2 = phases.apply_inv(cfg, ctl, out1.table, sess, out1.meta, inv)
    for a, b in zip(out1.table, out2.table):
        np.testing.assert_array_equal(get(a), get(b))
    assert bool(get(out2.out_ack.valid)[1, 0])


def test_apply_inv_stale_ts_ignored_but_acked():
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    hi = int(make_fc(t.FLAG_WRITE, 2))
    lo = int(make_fc(t.FLAG_WRITE, 0))
    ctl = ctl_scalars(cfg=cfg)
    out = phases.apply_inv(
        cfg, ctl, table, sess, meta, inv_block(cfg, [(2, 0, 5, 3, hi, [99, 1])])
    )
    out2 = phases.apply_inv(
        cfg, ctl, out.table, sess, out.meta, inv_block(cfg, [(0, 1, 5, 1, lo, [11, 2])])
    )
    assert get(out2.table.ver)[5] == 3 and get(out2.table.val)[5, 0] == 99
    assert bool(get(out2.out_ack.valid)[0, 1])  # stale INV still acked


def test_apply_inv_batch_contention_max_ts_wins():
    """Contended key, one step (SURVEY.md §7 hard part 4): segmented max by
    (ver, fc), not last-write-wins."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    recs = [
        (0, 0, 9, 1, int(make_fc(t.FLAG_WRITE, 0)), [100, 0]),
        (2, 0, 9, 2, int(make_fc(t.FLAG_RMW, 2)), [300, 0]),
        (1, 0, 9, 2, int(make_fc(t.FLAG_WRITE, 1)), [200, 0]),
    ]
    out = phases.apply_inv(cfg, ctl_scalars(cfg=cfg), table, sess, meta, inv_block(cfg, recs))
    # ver 2 beats ver 1; at ver 2 the plain write's flag beats the RMW's
    assert get(out.table.ver)[9] == 2
    assert get(out.table.fc)[9] == int(make_fc(t.FLAG_WRITE, 1))
    assert get(out.table.val)[9, 0] == 200
    # every INV still acked
    assert get(out.out_ack.valid)[[0, 1, 2], [0, 0, 0]].all()


def test_ack_bitmap_quorum_commit():
    """poll_acks (BASELINE.json:5): commit iff acks cover every live replica;
    partial acks accumulate across steps."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    fc = int(make_fc(t.FLAG_WRITE, 0))
    key, ver = 7, 1
    sess = sess._replace(
        status=sess.status.at[0].set(t.S_INFL),
        op=sess.op.at[0].set(t.OP_WRITE),
        key=sess.key.at[0].set(key),
        ver=sess.ver.at[0].set(ver),
        fc=sess.fc.at[0].set(fc),
    )
    table = table._replace(
        state=table.state.at[key].set(t.WRITE),
        ver=table.ver.at[key].set(ver),
        fc=table.fc.at[key].set(fc),
    )
    ctl = ctl_scalars(cfg=cfg)
    # acks from replicas 0 and 1 only -> no commit (live = 0b111)
    out = phases.collect_acks(
        cfg, ctl, table, sess, replay, meta,
        ack_block(cfg, [(0, 0, key, ver, fc), (1, 0, key, ver, fc)]),
    )
    assert get(out.sess.status)[0] == t.S_INFL
    assert get(out.sess.acks)[0] == 0b011
    assert not bool(get(out.out_val.valid)[0])
    # replica 2's ack arrives later -> commit, VAL out, key Valid
    out2 = phases.collect_acks(
        cfg, ctl, out.table, out.sess, out.replay, out.meta,
        ack_block(cfg, [(2, 0, key, ver, fc)]),
    )
    assert get(out2.sess.status)[0] == t.S_IDLE
    assert get(out2.comp.code)[0] == t.C_WRITE
    assert bool(get(out2.out_val.valid)[0])
    assert get(out2.table.state)[key] == t.VALID


def test_commit_quorum_shrinks_with_live_mask():
    """Membership removal unblocks pending writes (SURVEY.md §3.4): with
    replica 2 removed from the live mask, acks {0,1} suffice."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    fc = int(make_fc(t.FLAG_WRITE, 0))
    key, ver = 7, 1
    sess = sess._replace(
        status=sess.status.at[0].set(t.S_INFL),
        op=sess.op.at[0].set(t.OP_WRITE),
        key=sess.key.at[0].set(key),
        ver=sess.ver.at[0].set(ver),
        fc=sess.fc.at[0].set(fc),
    )
    table = table._replace(
        state=table.state.at[key].set(t.WRITE),
        ver=table.ver.at[key].set(ver),
        fc=table.fc.at[key].set(fc),
    )
    ctl = ctl_scalars(cfg=cfg, live_mask=0b011)
    out = phases.collect_acks(
        cfg, ctl, table, sess, replay, meta,
        ack_block(cfg, [(0, 0, key, ver, fc), (1, 0, key, ver, fc)]),
    )
    assert get(out.sess.status)[0] == t.S_IDLE
    assert get(out.comp.code)[0] == t.C_WRITE


def test_rmw_abort_on_conflicting_write():
    """YCSB-F conflict rule (BASELINE.json:8, SURVEY.md §3.3): a pending RMW
    aborts when a conflicting higher-ts update supersedes it; the write-flag
    tie-break makes any concurrent plain write higher-ts."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    key = 3
    rfc = int(make_fc(t.FLAG_RMW, 0))
    sess = sess._replace(
        status=sess.status.at[0].set(t.S_INFL),
        op=sess.op.at[0].set(t.OP_RMW),
        key=sess.key.at[0].set(key),
        ver=sess.ver.at[0].set(1),
        fc=sess.fc.at[0].set(rfc),
    )
    table = table._replace(
        state=table.state.at[key].set(t.WRITE),
        ver=table.ver.at[key].set(1),
        fc=table.fc.at[key].set(rfc),
    )
    wfc = int(make_fc(t.FLAG_WRITE, 1))  # same base version, write flag -> higher ts
    out = phases.apply_inv(
        cfg, ctl_scalars(cfg=cfg), table, sess, meta,
        inv_block(cfg, [(1, 0, key, 1, wfc, [55, 0])]),
    )
    assert get(out.comp.code)[0] == t.C_RMW_ABORT
    assert get(out.sess.status)[0] == t.S_IDLE
    assert get(out.meta.n_abort) == 1
    # the conflicting write owns the key now
    assert get(out.table.fc)[key] == wfc and get(out.table.val)[key, 0] == 55


def test_plain_write_superseded_not_aborted():
    """Concurrent plain writes both commit, ordered by ts (SURVEY.md §3.3):
    the loser keeps gathering acks with ``superseded`` set, and on commit the
    key is NOT forced Valid."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    key = 3
    myfc = int(make_fc(t.FLAG_WRITE, 0))
    sess = sess._replace(
        status=sess.status.at[0].set(t.S_INFL),
        op=sess.op.at[0].set(t.OP_WRITE),
        key=sess.key.at[0].set(key),
        ver=sess.ver.at[0].set(1),
        fc=sess.fc.at[0].set(myfc),
    )
    table = table._replace(
        state=table.state.at[key].set(t.WRITE),
        ver=table.ver.at[key].set(1),
        fc=table.fc.at[key].set(myfc),
    )
    hifc = int(make_fc(t.FLAG_WRITE, 2))
    ctl = ctl_scalars(cfg=cfg)
    out = phases.apply_inv(
        cfg, ctl, table, sess, meta, inv_block(cfg, [(2, 0, key, 1, hifc, [77, 0])])
    )
    assert get(out.sess.status)[0] == t.S_INFL  # not aborted
    assert bool(get(out.sess.superseded)[0])
    assert get(out.table.state)[key] == t.TRANS
    # full acks arrive -> commit completes the session but leaves the key
    # awaiting the winner's VAL
    out2 = phases.collect_acks(
        cfg, ctl, out.table, out.sess, replay, out.meta,
        ack_block(cfg, [(r, 0, key, 1, myfc) for r in range(3)]),
    )
    assert get(out2.comp.code)[0] == t.C_WRITE
    assert get(out2.table.state)[key] == t.TRANS  # still invalid-like
    # winner's VAL validates
    val = st.Vals(
        valid=jnp.zeros((3, cfg.n_lanes), bool).at[2, 0].set(True),
        key=jnp.zeros((3, cfg.n_lanes), jnp.int32).at[2, 0].set(key),
        ver=jnp.zeros((3, cfg.n_lanes), jnp.int32).at[2, 0].set(1),
        fc=jnp.zeros((3, cfg.n_lanes), jnp.int32).at[2, 0].set(hifc),
        epoch=jnp.zeros((3, cfg.n_lanes), jnp.int32),
    )
    table3 = phases.apply_val(cfg, ctl, out2.table, val)
    assert get(table3.state)[key] == t.VALID
    assert get(table3.val)[key, 0] == 77


def test_apply_val_requires_exact_ts():
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    ctl = ctl_scalars(cfg=cfg)
    fc = int(make_fc(t.FLAG_WRITE, 1))
    inv = inv_block(cfg, [(1, 0, 5, 2, fc, [42, 7])])
    table = phases.apply_inv(cfg, ctl, table, sess, meta, inv).table
    stale = st.Vals(
        valid=jnp.zeros((3, cfg.n_lanes), bool).at[1, 0].set(True),
        key=jnp.zeros((3, cfg.n_lanes), jnp.int32).at[1, 0].set(5),
        ver=jnp.ones((3, cfg.n_lanes), jnp.int32),  # ver 1 != table's 2
        fc=jnp.full((3, cfg.n_lanes), fc, jnp.int32),
        epoch=jnp.zeros((3, cfg.n_lanes), jnp.int32),
    )
    t2 = phases.apply_val(cfg, ctl, table, stale)
    assert get(t2.state)[5] == t.INVALID  # stale VAL ignored
    good = stale._replace(ver=jnp.full((3, cfg.n_lanes), 2, jnp.int32))
    t3 = phases.apply_val(cfg, ctl, t2, good)
    assert get(t3.state)[5] == t.VALID


def test_replay_scan_picks_stuck_keys():
    """SURVEY.md §3.4: a key Invalid past replay_age is snapshotted into a
    replay slot and re-broadcast with the SAME ts."""
    cfg = tiny_cfg(replay_age=4)
    table, sess, replay, meta = fresh(cfg)
    fc = int(make_fc(t.FLAG_WRITE, 1))
    inv = inv_block(cfg, [(1, 0, 5, 1, fc, [42, 7])])
    ctl0 = ctl_scalars(step=0, cfg=cfg)
    table = phases.apply_inv(cfg, ctl0, table, sess, meta, inv).table
    # young: no replay yet
    out = phases.coordinate(cfg, ctl_scalars(step=3, cfg=cfg), table, sess, replay, empty_stream(cfg))
    assert not get(out.replay.active).any()
    # old: replayed with the same ts+value
    out = phases.coordinate(cfg, ctl_scalars(step=10, cfg=cfg), table, sess, replay, empty_stream(cfg))
    assert bool(get(out.replay.active)[0])
    assert get(out.replay.key)[0] == 5
    assert get(out.replay.ver)[0] == 1 and get(out.replay.fc)[0] == fc
    assert get(out.replay.val)[0, 0] == 42
    assert get(out.table.state)[5] == t.REPLAY
    lane = cfg.n_sessions  # first replay lane
    assert bool(get(out.out_inv.valid)[lane])
    assert get(out.out_inv.ver)[lane] == 1 and get(out.out_inv.key)[lane] == 5


def test_frozen_replica_does_nothing():
    """Failure injection (config 4, BASELINE.json:10): a frozen replica makes
    no transitions and emits nothing."""
    cfg = tiny_cfg()
    table, sess, replay, meta = fresh(cfg)
    stream = empty_stream(cfg)._replace(
        op=jnp.full((cfg.n_sessions, cfg.ops_per_session), t.OP_WRITE, jnp.int32)
    )
    ctl = ctl_scalars(cfg=cfg, frozen=True)
    out = phases.coordinate(cfg, ctl, table, sess, replay, stream)
    assert not get(out.out_inv.valid).any()
    assert not bool(get(out.out_inv.alive))
    assert (get(out.sess.status) == t.S_IDLE).all()
    fc = int(make_fc(t.FLAG_WRITE, 1))
    out2 = phases.apply_inv(
        cfg, ctl, table, sess, meta, inv_block(cfg, [(1, 0, 5, 1, fc, [42, 7])])
    )
    assert get(out2.table.state)[5] == t.VALID  # not applied
    assert not get(out2.out_ack.valid).any()
