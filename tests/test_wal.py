"""Round-22 durability tier (hermes_tpu/wal): crash-point matrix over the
segment format, replay idempotency across a snapshot boundary, group-commit
client semantics (labels, backpressure), scoping, and the powercut verb.

The torn-frame triage contract under test (wal/replay.py docstring): a
failure explainable as ONE interrupted append at EOF in the LAST segment
truncates cleanly (the kill -9 shape); anything else — interior damage, a
checksum mismatch over a fully-present payload, any failure in a non-last
segment — refuses loudly with a flight-recorder dump."""

import glob
import json
import os

import numpy as np
import pytest

from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
from hermes_tpu.kvs import KVS
from hermes_tpu.transport import codec
from hermes_tpu.wal import GroupCommitWal, WalCorrupt, WalError, replay


def _cfg(wal_dir, **kw):
    base = dict(n_replicas=3, n_keys=256, n_sessions=8, replay_slots=4,
                value_words=6, replay_age=4, replay_scan_every=4,
                wal_dir=str(wal_dir) if wal_dir is not None else None,
                wal_sync="commit")
    base.update(kw)
    return HermesConfig(**base)


def _write_log(wal_dir, batches=3, per=4, **kw):
    """A sealed synthetic log: ``batches`` K_ROUND records of ``per``
    writes each, no KVS/JAX in the loop."""
    wal = GroupCommitWal(_cfg(wal_dir, **kw))
    for b in range(batches):
        keys = np.arange(per, dtype=np.int32) + b * per
        wv = np.zeros((per, 6), np.int32)
        wv[:, 0] = 1000 + b  # uid lo
        wv[:, 1] = np.arange(per)  # uid hi
        wv[:, 3] = 7 * b + np.arange(per)  # payload
        wal.append_round(b, np.full(per, b, np.int64), keys,
                         np.ones(per, np.int64), np.zeros(per, np.int32),
                         wv, np.zeros(per, np.int32), b"")
    wal.sync()
    wal.close()
    segs = wal.segments()
    assert len(segs) == 1
    return segs[0]


def _frame_offsets(path):
    data = open(path, "rb").read()
    offs, off = [], 0
    while off < len(data):
        _m, _a, _p, length, _c = codec.FRAME_HEADER.unpack(
            data[off:off + codec.FRAME_OVERHEAD])
        offs.append(off)
        off += codec.FRAME_OVERHEAD + length
    return offs, len(data)


# ---------------------------------------------------------------------------
# crash-point matrix: torn tails truncate cleanly, interior damage refuses
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("crash_point", ["mid_record", "mid_frame_header",
                                         "mid_fsync_window"])
def test_torn_tail_truncates_cleanly(tmp_path, crash_point):
    seg = _write_log(tmp_path, batches=3, per=4)
    offs, size = _frame_offsets(seg)
    # frame 0 is the K_SEGHDR; frames 1..3 the three record batches
    assert len(offs) == 4
    if crash_point == "mid_record":
        cut = size - 5  # inside the last record's payload
    elif crash_point == "mid_frame_header":
        cut = offs[-1] + 3  # only 3 bytes of the last frame header landed
    else:  # mid_fsync_window: a multi-record batch partially persisted
        cut = offs[2] + codec.FRAME_OVERHEAD + 2
    with open(seg, "r+b") as f:
        f.truncate(cut)
    scan = replay.read_records(str(tmp_path))
    assert scan["torn_tail"] is True
    want = 1 if crash_point == "mid_fsync_window" else 2
    assert len(scan["records"]) == want
    # what survived is intact and in append order
    for b, rec in enumerate(scan["records"]):
        assert rec["round_idx"] == b
        assert rec["key"].tolist() == list(range(b * 4, b * 4 + 4))


def test_flipped_byte_in_sealed_interior_refuses(tmp_path, monkeypatch):
    flight_dir = tmp_path / "flight"
    monkeypatch.setenv("HERMES_FLIGHT_DIR", str(flight_dir))
    seg = _write_log(tmp_path / "wal", batches=3, per=4)
    offs, _size = _frame_offsets(seg)
    with open(seg, "r+b") as f:  # one bit of rot inside frame 1's payload
        f.seek(offs[1] + codec.FRAME_OVERHEAD + 4)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(WalCorrupt, match="checksum"):
        replay.read_records(str(tmp_path / "wal"))
    # the refusal armed the flight recorder with the offending header
    dumps = glob.glob(str(flight_dir / "flight_*.json"))
    assert dumps, "refusal did not dump the flight recorder"
    blob = json.dumps(json.load(open(dumps[-1])))
    assert "wal_checksum_mismatch" in blob
    assert os.path.basename(seg) in blob
    assert "header_hex" in blob


def test_torn_interior_nonlast_segment_refuses(tmp_path, monkeypatch):
    monkeypatch.setenv("HERMES_FLIGHT_DIR", str(tmp_path / "flight"))
    cfg = _cfg(tmp_path)
    wal = GroupCommitWal(cfg)
    wal.append_round(0, np.zeros(2, np.int64), np.arange(2, dtype=np.int32),
                     np.ones(2, np.int64), np.zeros(2, np.int32),
                     np.zeros((2, 6), np.int32), np.zeros(2, np.int32), b"")
    wal.sync()
    wal.close()
    # a second store generation continues the sequence in a NEW segment
    wal2 = GroupCommitWal(cfg)
    wal2.append_round(1, np.ones(2, np.int64), np.arange(2, dtype=np.int32),
                      np.full(2, 2, np.int64), np.zeros(2, np.int32),
                      np.zeros((2, 6), np.int32), np.zeros(2, np.int32), b"")
    wal2.sync()
    wal2.close()
    seg0, seg1 = wal2.segments()
    with open(seg0, "r+b") as f:
        f.seek(0, os.SEEK_END)
        f.truncate(f.tell() - 3)  # a tail cut — but NOT in the last segment
    with pytest.raises(WalCorrupt, match="torn_interior|NON-last"):
        replay.read_records(str(tmp_path))
    assert glob.glob(str(tmp_path / "flight" / "flight_*.json"))


def test_header_mismatch_recovery_refused(tmp_path, monkeypatch):
    monkeypatch.setenv("HERMES_FLIGHT_DIR", str(tmp_path / "flight"))
    _write_log(tmp_path, batches=1, per=2)
    scan = replay.read_records(str(tmp_path))
    other = _cfg(tmp_path, n_keys=512)  # not the table this log was cut for
    with pytest.raises(WalCorrupt, match="different config"):
        replay.check_headers(scan["headers"], other)
    dumps = glob.glob(str(tmp_path / "flight" / "flight_*.json"))
    assert dumps and "wal_recovery_refused" in json.dumps(
        json.load(open(dumps[-1])))


def test_unknown_record_kind_refuses(tmp_path):
    seg = _write_log(tmp_path, batches=1, per=2)
    with open(seg, "ab") as f:  # CRC-valid frame around garbage
        f.write(codec.frame_pack(
            np.frombuffer(bytes([99]) * 40, np.uint8)).tobytes())
    with pytest.raises(WalCorrupt, match="inconsistent|unknown"):
        replay.read_records(str(tmp_path))


# ---------------------------------------------------------------------------
# replay: idempotent, snapshot-boundary-safe, on both recorder kinds
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("record", [True, "array"],
                         ids=["history-recorder", "columnar-recorder"])
def test_replay_idempotent_across_snapshot_boundary(tmp_path, record):
    import jax

    from hermes_tpu import snapshot
    from hermes_tpu.chaos.recovery import recover_store

    wal_dir = tmp_path / "wal"
    kvs = KVS(_cfg(wal_dir), record=record)
    f1 = [kvs.put(0, s, key=10 + s, value=[100 + s, 0, 0, s])
          for s in range(4)]
    assert kvs.run_until(f1)
    snap = str(tmp_path / "snap.npz")
    snapshot.save(snap, kvs)  # truncates the log behind it (sealed segs)
    f2 = [kvs.put(1, s, key=20 + s, value=[200 + s, 0, 0, s])
          for s in range(4)]
    assert kvs.run_until(f2)
    kvs.wal.sync()
    kvs.wal.close()  # stop the flusher; segments stay (kill -9 keeps them)

    kvs2, summary = recover_store(_cfg(wal_dir), snapshot_path=snap,
                                  record=record)
    # every logged record either applied or was already covered by the
    # snapshot (the boundary): nothing refused, nothing double-applied
    assert summary["applied"] + summary["skipped"] == summary["records"]
    assert summary["applied"] >= 4  # the post-snapshot tail
    for s in range(4):
        g1, g2 = kvs2.get(2, 0, 10 + s), kvs2.get(2, 1, 20 + s)
        assert kvs2.run_until([g1, g2])
        assert g1.result().value == [100 + s, 0, 0, s]
        assert g2.result().value == [200 + s, 0, 0, s]

    # idempotency proper: replaying the recovered store's own log AGAIN
    # is a pure no-op — same vpts, zero applied
    before = np.array(jax.device_get(kvs2.rt.fs.table.vpts))
    kvs2.flush()
    kvs2.wal.sync()
    scan = replay.read_records(str(wal_dir))
    applied, skipped = replay.apply_records(kvs2.rt, scan["records"])
    assert applied == 0 and skipped == len(
        [i for r in scan["records"] for i in range(r["key"].shape[0])])
    after = np.array(jax.device_get(kvs2.rt.fs.table.vpts))
    np.testing.assert_array_equal(before, after)
    kvs2.wal.close()


def test_recovered_log_stands_alone(tmp_path):
    """After recovery the OLD segments are retired and the fresh log alone
    must cover the state: recover from the re-appended log a second time
    and serve the same values."""
    from hermes_tpu.chaos.recovery import recover_store

    wal_dir = tmp_path / "wal"
    kvs = KVS(_cfg(wal_dir))
    futs = [kvs.put(0, s, key=s, value=[s, s, s, s]) for s in range(6)]
    assert kvs.run_until(futs)
    kvs.wal.sync()
    old_segs = set(kvs.wal.segments())
    kvs.wal.close()

    kvs2, _ = recover_store(_cfg(wal_dir))
    assert not (old_segs & set(kvs2.wal.segments())), "old segments survive"
    kvs2.wal.close()
    kvs3, summary = recover_store(_cfg(wal_dir))
    assert summary["applied"] == 6
    g = kvs3.get(1, 0, 3)
    assert kvs3.run_until([g])
    assert g.result().value == [3, 3, 3, 3]
    kvs3.wal.close()


# ---------------------------------------------------------------------------
# group-commit client semantics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode,label", [
    ("commit", "commit"),
    ("round", "round:not-fsynced-at-resolve"),
    ("off", "off:not-fsynced-at-resolve"),
])
def test_durability_labels(tmp_path, mode, label):
    kvs = KVS(_cfg(tmp_path / mode, wal_sync=mode))
    fut = kvs.put(0, 0, key=1, value=[1, 2, 3, 4])
    assert kvs.run_until([fut])
    c = fut.result()
    assert c.kind == "put" and c.durability == label
    bf = kvs.submit_batch(np.array([KVS.PUT], np.int32),
                          np.array([2]), np.array([[9, 9, 9, 9]], np.int32))
    assert kvs.run_batch(bf)
    assert bf.completion(0).durability == label
    kvs.wal.close()


def test_no_wal_no_label(tmp_path):
    kvs = KVS(_cfg(None))
    fut = kvs.put(0, 0, key=1, value=[1, 2, 3, 4])
    assert kvs.run_until([fut])
    assert fut.result().durability is None


def test_backpressure_sheds_retry_after(tmp_path):
    # 'round' mode so resolution doesn't park, then kill the flusher: the
    # dirty window can only grow, and the client surface must shed LOUDLY
    kvs = KVS(_cfg(tmp_path, wal_sync="round", wal_dirty_window=4))
    wal = kvs.wal
    wal._stop.set()
    wal.kick()
    wal._flusher_t.join(timeout=10)
    assert not wal._flusher_t.is_alive()
    futs = [kvs.put(0, s, key=s, value=[s, 0, 0, 0]) for s in range(8)]
    assert kvs.run_until(futs)  # round mode: resolves without fsync
    assert wal.dirty_records() > 4 and wal.backpressured()
    shed = kvs.put(0, 0, key=99, value=[9, 9, 9, 9])
    assert shed.result().kind == "retry_after"
    bf = kvs.submit_batch(np.array([KVS.PUT] * 3, np.int32),
                          np.arange(3), np.zeros((3, 4), np.int32))
    kvs.step()
    assert all(bf.completion(i).kind == "retry_after" for i in range(3))
    assert kvs.wal_shed >= 4
    # reads still flow under write backpressure
    g = kvs.get(1, 1, 0)
    assert kvs.run_until([g])
    assert g.result().kind == "get"
    # and a dead flusher can never fake durability
    with pytest.raises(WalError, match="dead|failed"):
        wal.sync(timeout=1.0)


def test_fleet_groups_get_scoped_wal_dirs(tmp_path):
    fcfg = FleetConfig(groups=3, base=_cfg(tmp_path / "fleet"))
    dirs = [fcfg.group_cfg(g).wal_dir for g in range(3)]
    assert dirs == [str(tmp_path / "fleet" / f"group{g:03d}")
                    for g in range(3)]
    assert len(set(dirs)) == 3
    assert FleetConfig(groups=2, base=_cfg(None)).group_cfg(0).wal_dir is None


# ---------------------------------------------------------------------------
# the powercut chaos verb
# ---------------------------------------------------------------------------

def test_powercut_requires_carrier(tmp_path):
    from hermes_tpu import chaos
    from hermes_tpu.runtime import FastRuntime

    rt = FastRuntime(_cfg(None))
    sched = chaos.Schedule([chaos.ChaosEvent(step=2, kind="powercut")])
    with pytest.raises(ValueError, match="powercut"):
        chaos.ChaosRunner(rt, sched)

    fired = []
    runner = chaos.ChaosRunner(rt, sched, powercut=fired.append)
    for s in range(4):
        runner.tick(s)
    assert fired == [2]
    assert [e["kind"] for e in runner.log] == ["powercut"]


def test_powercut_parses_in_schedule_text():
    from hermes_tpu import chaos

    sched = chaos.Schedule.parse("@7 powercut\n")
    assert len(sched) == 1 and sched.events[0].kind == "powercut"


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_config_validation():
    with pytest.raises(ValueError, match="wal_sync"):
        HermesConfig(wal_dir="/tmp/x", wal_sync="sometimes")
    with pytest.raises(ValueError, match="wal_segment_bytes"):
        HermesConfig(wal_dir="/tmp/x", wal_segment_bytes=16)
    with pytest.raises(ValueError, match="wal_dirty_window"):
        HermesConfig(wal_dir="/tmp/x", wal_dirty_window=0)
    assert HermesConfig(wal_dir="/tmp/x").use_wal
    assert not HermesConfig().use_wal


def test_segment_rotation_and_truncate(tmp_path):
    cfg = _cfg(tmp_path, wal_segment_bytes=4096)
    wal = GroupCommitWal(cfg)
    per = 16
    for b in range(40):
        wv = np.zeros((per, 6), np.int32)
        wv[:, 3] = b
        wal.append_round(b, np.full(per, b, np.int64),
                         np.arange(per, dtype=np.int32),
                         np.full(per, 1 + b, np.int64),
                         np.zeros(per, np.int32), wv,
                         np.zeros(per, np.int32), b"")
    wal.sync()
    assert len(wal.segments()) > 1, "rotation never fired"
    # truncating behind the last batch drops every SEALED segment whose
    # records all committed at or before it; the open segment stays
    wal.truncate_to(39)
    segs = wal.segments()
    assert len(segs) >= 1
    scan = replay.read_records(str(tmp_path))
    assert scan["records"], "truncate must never empty the live log"
    wal.close()
