"""Scan-chunked step (SURVEY.md §7 M6): build_step_scan must be bit-identical
to looping build_step_batched, the sharded scan must match the batched scan,
and wrap_stream must keep sessions running past ops_per_session with unique
write uids."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st, step as step_lib
from hermes_tpu.core import types as t
from hermes_tpu.workload import ycsb

from helpers import get


def setup(cfg):
    r = cfg.n_replicas
    rs = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), st.init_replica_state(cfg)
    )
    stream = jax.tree.map(jnp.asarray, ycsb.make_streams(cfg))
    return rs, stream


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(get(x), get(y))


def test_scan_matches_step_loop():
    cfg = HermesConfig(
        n_replicas=3, n_keys=128, n_sessions=8, replay_slots=4, ops_per_session=64,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.5, seed=11),
    )
    rs, stream = setup(cfg)

    step = step_lib.build_step_batched(cfg)
    rs_loop = rs
    for s in range(12):
        rs_loop, _ = step(rs_loop, stream, step_lib.make_ctl(cfg, s))

    chunk = step_lib.build_step_scan(cfg, rounds=4, donate=False)
    rs_scan = rs
    for c in range(3):
        rs_scan = chunk(rs_scan, stream, step_lib.make_ctl(cfg, c * 4))

    assert_trees_equal(rs_loop, rs_scan)


def test_sharded_scan_matches_batched_scan():
    cfg = HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=4, replay_slots=2, ops_per_session=32,
        workload=WorkloadConfig(read_frac=0.5, seed=13),
    )
    rs, stream = setup(cfg)

    chunk = step_lib.build_step_scan(cfg, rounds=6, donate=False)
    want = chunk(rs, stream, step_lib.make_ctl(cfg, 0))

    mesh = Mesh(np.array(jax.devices()[: cfg.n_replicas]), ("replica",))
    rs_sh, stream_sh = step_lib.place_sharded(cfg, mesh, rs, stream)
    shchunk = step_lib.build_step_sharded_scan(cfg, mesh, rounds=6, donate=False)
    got = shchunk(rs_sh, stream_sh, step_lib.make_ctl(cfg, 0))

    assert_trees_equal(want, got)


def test_wrap_stream_runs_past_G_with_unique_uids():
    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=2, ops_per_session=8,
        wrap_stream=True,
        workload=WorkloadConfig(read_frac=0.0, seed=17),
    )
    rs, stream = setup(cfg)
    chunk = step_lib.build_step_scan(cfg, rounds=40, donate=False)
    rs = chunk(rs, stream, step_lib.make_ctl(cfg, 0))

    # Sessions never go DONE and keep consuming ops well past G.
    assert (get(rs.sess.status) != t.S_DONE).all()
    assert get(rs.sess.op_idx).min() > cfg.ops_per_session

    # All replicas converge to identical Valid tables whose surviving values
    # carry distinct uids per (key); committed count ~= writes issued.
    meta = rs.meta
    assert int(get(meta.n_write).sum()) > cfg.n_replicas * cfg.n_sessions * 20

    # uid lo-word = op_idx * S + sess is unique across the run: spot-check
    # that the table's current values have lo-words consistent with op_idx
    # having exceeded G (i.e. wrap reuses stream slots, not uids).
    lo = get(rs.table.val)[..., 0]
    hi = get(rs.table.val)[..., 1]
    written = hi >= 0  # initial values have hi=-1
    assert written.any()
    assert lo[written].max() > cfg.ops_per_session * cfg.n_sessions
