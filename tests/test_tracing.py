"""Round-18 observability: per-op sampled tracing (obs/tracing.py), the
windowed time-series store (obs/series.py), and the crash flight recorder
(obs/flightrec.py).

The three contracts gated here:

  * determinism — a seeded traced run samples the SAME ops with the SAME
    ids on every replay and on every engine, so ``canonical_span_bytes``
    (the span stream minus wall-clock fields) is byte-identical across
    replays and across batched/sharded;
  * behavior identity — tracing off means no sampler, no spans, and the
    wire carries 0 in the (formerly pad) trace slot, so old peers
    interoperate bit-for-bit (the round census not moving is
    scripts/check_op_census.py's job);
  * trustworthy post-mortems — a flight archive round-trips its
    checksum, a tampered one is refused loudly, and a deliberately
    wedged op dumps BEFORE StuckOpError propagates.
"""

import dataclasses
import json
import signal

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.kvs import KVS, StuckOpError
from hermes_tpu.obs import (
    OP_SPANS,
    FlightRecorder,
    MetricsRegistry,
    Observability,
    Series,
    TraceSampler,
    canonical_span_bytes,
)
from hermes_tpu.obs import flightrec
from hermes_tpu.obs import report as report_lib
from hermes_tpu.runtime import FastRuntime
from hermes_tpu.serving import wire
from hermes_tpu.serving.server import ServingConfig
from hermes_tpu.serving.soak import run_open_loop
from hermes_tpu.workload.openloop import MixSpec


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=64, n_sessions=8, replay_slots=8,
              ops_per_session=4, value_words=4, trace_sample=4,
              workload=WorkloadConfig(seed=7))
    kw.update(over)
    return HermesConfig(**kw)


# -- sampler -----------------------------------------------------------------


def test_sampler_is_seeded_deterministic_and_in_range():
    a = [TraceSampler(4, seed=7).sample(i) for i in range(256)]
    assert a == [TraceSampler(4, seed=7).sample(i) for i in range(256)]
    hits = [t for t in a if t]
    assert hits and len(hits) < len(a)  # ~1 in 4, never all or none
    assert all(1 <= t <= 0xFFFF for t in hits)
    assert [TraceSampler(4, seed=8).sample(i) for i in range(256)] != a
    # rate=1 traces every op; rate<=0 belongs to config, not a sampler
    assert all(TraceSampler(1).sample(i) for i in range(32))
    with pytest.raises(ValueError, match="sample rate"):
        TraceSampler(0)


# -- wire field --------------------------------------------------------------


def test_wire_trace_field_roundtrip_range_and_size():
    req = wire.Request(kind="put", req_id=9, tenant=2, key=5,
                       value=[1, 2], trace=777)
    out = wire.decode_request(wire.encode_request(req, 2), 2)
    assert out.trace == 777 and out.key == 5 and out.value[:2] == [1, 2]
    # unsampled encodes as 0 — the old pad value, so the wire size and
    # the bytes old peers see are unchanged
    plain = wire.Request(kind="get", req_id=1, tenant=0, key=0)
    assert wire.decode_request(wire.encode_request(plain, 2), 2).trace == 0
    assert len(wire.encode_request(req, 2)) == \
        len(wire.encode_request(dataclasses.replace(req, trace=0), 2))
    with pytest.raises(ValueError, match="trace id"):
        wire.encode_request(dataclasses.replace(req, trace=0x10000), 2)


# -- series ------------------------------------------------------------------


def test_series_window_rate_percentile_and_bounds():
    s = Series("depth", capacity=4)
    for x, v in [(0, 0), (2, 4), (4, 4), (6, 10), (8, 12)]:
        s.append(x, v)
    assert len(s) == 4  # capacity-bounded: (0, 0) evicted
    assert s.window(2) == [(6, 10), (8, 12)]
    assert s.values() == [4, 4, 10, 12]
    assert s.rate() == (12 - 4) / (8 - 2)  # dv/dx over the retained ring
    assert s.rate(2) == 1.0
    assert s.percentile(0.5) in (4, 10)
    assert s.last == (8, 12)
    assert s.snapshot() == dict(x=[2, 4, 6, 8], v=[4, 4, 10, 12])
    # same-x appends are fine (same round, two polls); regressions raise
    s.append(8, 13)
    with pytest.raises(ValueError, match="went backwards"):
        s.append(7, 0)
    with pytest.raises(ValueError, match="capacity"):
        Series("tiny", capacity=1)
    empty = Series("empty")
    assert empty.rate() is None and empty.percentile(0.5) is None
    assert empty.last is None


def test_registry_series_accessor_and_snapshot_separation():
    reg = MetricsRegistry()
    s = reg.series("intake_depth_series", capacity=8)
    s.append(0, 3)
    assert reg.series("intake_depth_series") is s  # get-or-create
    with pytest.raises(TypeError):
        reg.counter("intake_depth_series")
    reg.counter("commits").inc(2)
    snap = reg.snapshot()
    assert snap["commits"] == 2
    assert "intake_depth_series" not in snap  # point snapshot stays scalar
    ss = reg.series_snapshot()
    assert ss == {"intake_depth_series": dict(x=[0], v=[3])}
    from hermes_tpu.obs import prometheus_text

    assert "intake_depth_series" not in prometheus_text(reg)


def test_runtime_feeds_series_and_flight_meta():
    cfg = _cfg(trace_sample=0, n_sessions=16, ops_per_session=32)
    rt = FastRuntime(cfg)
    obs = rt.attach_obs(Observability())
    assert rt.drain(400)
    rt.counters()
    reg = obs.registry
    assert len(reg.series("pipeline_depth_series")) > 0
    assert len(reg.series("max_ver_series")) == 1
    assert reg.series("commits_series").last[1] > 0
    assert obs.flight.metas and obs.flight.metas[-1]["step"] == rt.step_idx
    obs.series_snapshot()
    series_recs = [r for r in obs.records if r["kind"] == "series"]
    assert len(series_recs) == 1
    assert set(series_recs[0]) >= {"t", "kind", "pipeline_depth_series",
                                   "max_ver_series", "commits_series"}


# -- KVS op tracing ----------------------------------------------------------


def _traced_kvs_run(backend="batched", mesh=None):
    kv = KVS(_cfg(), backend=backend, mesh=mesh)
    obs = kv.rt.attach_obs(Observability())
    futs = [kv.put(i % 3, i % 8, i % 64, value=[i, i + 1])
            for i in range(32)]
    assert kv.run_until(futs)
    return canonical_span_bytes(obs.records), obs.records


def test_kvs_spans_replay_byte_identical_and_off_means_off():
    b1, recs = _traced_kvs_run()
    b2, _ = _traced_kvs_run()
    assert b1 and b1 == b2
    spans = [r for r in recs if r.get("kind") == "span_end"
             and r.get("name") in OP_SPANS]
    assert spans
    for s in spans:
        assert s["name"] in ("op_queue", "op_rounds")  # KVS-level phases
        assert 1 <= s["trace"] <= 0xFFFF
        assert s["r1"] >= s["r0"] >= 0
        assert {"replica", "session", "op", "key"} <= set(s)
    # every sampled op closes both phases: submit->inject, inject->resolve
    by_trace = {}
    for s in spans:
        by_trace.setdefault((s["trace"], s["key"]), set()).add(s["name"])
    assert by_trace
    assert all(v == {"op_queue", "op_rounds"} for v in by_trace.values())
    # tracing off: no sampler, no op spans
    kv0 = KVS(_cfg(trace_sample=0), backend="batched")
    obs0 = kv0.rt.attach_obs(Observability())
    assert kv0._sampler is None
    assert kv0.run_until([kv0.put(0, 0, 1, value=[1, 2])])
    assert canonical_span_bytes(obs0.records) == b""


# -- serving path ------------------------------------------------------------


def _traced_soak(backend="batched", mesh=None):
    cfg = _cfg(trace_sample=8)
    scfg = ServingConfig(trace_sample=8, trace_seed=7, round_us=1000)
    kv = KVS(cfg, backend=backend, mesh=mesh)
    obs = kv.rt.attach_obs(Observability())
    res = run_open_loop(kv, scfg, MixSpec(), rate_per_s=20000, n=80,
                        seed=3, deadline_us=200_000)
    return canonical_span_bytes(obs.records), res, obs


def test_traced_soak_covers_four_phases_and_replays_identically():
    b1, res1, obs = _traced_soak()
    b2, res2, _ = _traced_soak()
    assert b1 and b1 == b2
    assert res1["response_log_sha"] == res2["response_log_sha"]
    lines = [json.loads(ln) for ln in b1.decode().strip().splitlines()]
    names = {n: sum(1 for r in lines if r["name"] == n)
             for n in {r["name"] for r in lines}}
    assert set(names) == set(OP_SPANS)  # the full critical path closed
    # one sampled request's chain walks every phase end-to-end
    chains = {}
    for r in lines:
        chains.setdefault(r["trace"], set()).add(r["name"])
    assert any(c == set(OP_SPANS) for c in chains.values())
    # fe spans carry the admission/tenant identity, with terminal status
    fe = [r for r in lines if r["name"] == "fe_resolve"]
    assert fe and all({"tenant", "op", "key", "status"} <= set(r)
                      for r in fe)
    # the serving ladder fed its windowed series at the store's round clock
    reg = obs.registry
    assert len(reg.series("intake_depth_series")) > 0
    assert len(reg.series("shed_level_series")) > 0
    heat = reg.series("key_heat_max_series")
    assert len(heat) > 0 and max(heat.values()) >= 1
    assert max(reg.series("key_distinct_series").values()) >= 1
    # and the report's critical-path section renders from these spans
    cp = report_lib.critical_path(obs.records)
    assert cp is not None and cp["traces"] == len(chains)
    assert set(cp["phases"]) <= set(OP_SPANS)
    assert "per-op critical path" in report_lib.render_report(obs.records)


def test_traced_soak_spans_identical_across_engines(cpu_devices):
    from jax.sharding import Mesh

    b_batched, res_b, _ = _traced_soak()
    mesh = Mesh(np.array(cpu_devices[:3]), ("replica",))
    b_sharded, res_s, _ = _traced_soak(backend="sharded", mesh=mesh)
    assert b_batched and b_batched == b_sharded
    assert res_b["response_log_sha"] == res_s["response_log_sha"]


def test_columnar_batch_codec_carries_trace_ids_byte_identically():
    """Round-19: the trace id is a first-class COLUMN — nonzero u16 ids
    survive the batch codec both directions, and a traced batch's bytes
    are identical to the per-struct encode of the same rows (old peers
    read traced columnar streams unchanged)."""
    u = 3
    reqs = [wire.Request(kind="put", req_id=1, tenant=0, key=2,
                         value=[7], trace=777),
            wire.Request(kind="get", req_id=2, tenant=1, key=3),
            wire.Request(kind="rmw", req_id=3, tenant=2, key=4,
                         value=[9], trace=0xFFFF)]
    oracle = b"".join(wire.encode_request(r, u) for r in reqs)
    b = wire.ReqBatch.from_requests(reqs, u)
    assert b.trace.dtype == np.uint16
    assert wire.encode_request_batch(b, u) == oracle
    back = wire.decode_request_batch(oracle, u)
    assert back.trace.tolist() == [777, 0, 0xFFFF]
    assert [r.trace for r in back.to_requests()] == [777, 0, 0xFFFF]


def test_traced_columnar_soak_replays_identically():
    """The traced COLUMNAR soak: same determinism bar as the scalar
    traced soak — byte-identical response log AND span stream across
    two same-seed runs, with fe_resolve spans minted by the serving
    sampler for rows whose wire trace arrived 0."""
    from hermes_tpu.serving.soak import run_columnar_soak

    def one():
        kv = KVS(_cfg(trace_sample=8), backend="batched")
        obs = kv.rt.attach_obs(Observability())
        res = run_columnar_soak(
            kv, ServingConfig(trace_sample=8, trace_seed=7,
                              round_us=1000),
            MixSpec(), rate_per_s=20000, n=80, seed=3,
            deadline_us=200_000)
        return canonical_span_bytes(obs.records), res

    b1, res1 = one()
    b2, res2 = one()
    assert b1 and b1 == b2
    assert res1["response_log_sha"] == res2["response_log_sha"]
    lines = [json.loads(ln) for ln in b1.decode().strip().splitlines()]
    fe = [r for r in lines if r["name"] == "fe_resolve"]
    assert fe and all(r["trace"] for r in fe)
    assert all({"tenant", "op", "key", "status"} <= set(r) for r in fe)


# -- critical path (synthetic) -----------------------------------------------


def test_critical_path_breakdown_on_synthetic_spans():
    recs = [
        dict(t=0.0, kind="span_end", name="op_queue", trace=5, r0=1, r1=3),
        dict(t=0.1, kind="span_end", name="op_rounds", trace=5, r0=3, r1=9),
        dict(t=0.2, kind="span_end", name="fe_resolve", trace=5, r0=0,
             r1=9, dur_s=0.01),
        dict(t=0.3, kind="span_end", name="op_queue", trace=9, r0=2, r1=2),
        dict(t=0.4, kind="event", name="freeze", trace=0),  # not a span
    ]
    cp = report_lib.critical_path(recs)
    assert cp["traces"] == 2
    assert cp["phases"]["op_queue"]["n"] == 2
    assert cp["phases"]["op_queue"]["p50_rounds"] == 0
    assert cp["phases"]["op_queue"]["p99_rounds"] == 2
    assert cp["phases"]["op_rounds"]["p50_rounds"] == 6
    assert cp["phases"]["fe_resolve"]["p99_dur_s"] == 0.01
    assert "fe_queue" not in cp["phases"]  # no span, no row
    assert report_lib.critical_path([]) is None


# -- flight recorder ---------------------------------------------------------


def test_flight_archive_roundtrips_checksum_and_refuses_tamper(tmp_path):
    fr = FlightRecorder(capacity=4, meta_keep=2)
    for i in range(6):
        fr.record({"t": float(i), "kind": "metrics", "i": i})
    for i in range(3):
        fr.note_meta({"step": i})
    fr.set_config(_cfg())
    path = str(tmp_path / "dump.json")
    assert fr.dump(path, "unit", extra=dict(k="v")) == path
    payload = flightrec.load(path)
    assert payload["reason"] == "unit" and payload["extra"] == {"k": "v"}
    assert payload["n_events"] == 4  # ring bounded at capacity
    assert [e["i"] for e in payload["events"]] == [2, 3, 4, 5]
    assert [m["step"] for m in payload["meta_summaries"]] == [1, 2]
    assert payload["config_sha256"]
    assert fr.dumps == [path]
    # tampering flips the checksum — refused, never returned as data
    archive = json.loads(open(path).read())
    archive["payload"]["events"][0]["i"] = 99
    with open(path, "w") as f:
        json.dump(archive, f)
    with pytest.raises(flightrec.FlightArchiveError, match="checksum"):
        flightrec.load(path)
    with open(path, "w") as f:
        json.dump({"not": "an archive"}, f)
    with pytest.raises(flightrec.FlightArchiveError, match="not a flight"):
        flightrec.load(path)


def test_flight_auto_dump_gated_on_dir(tmp_path, monkeypatch):
    monkeypatch.delenv(flightrec.FLIGHT_DIR_ENV, raising=False)
    fr = FlightRecorder()
    fr.record({"t": 0.0, "kind": "event", "name": "x"})
    assert fr.auto_dump("nowhere") is None  # no dir, no litter
    monkeypatch.setenv(flightrec.FLIGHT_DIR_ENV, str(tmp_path / "env"))
    p = fr.auto_dump("enved")
    assert p and flightrec.load(p)["reason"] == "enved"
    # explicit ctor dir wins over the environment
    fr2 = FlightRecorder(dump_dir=str(tmp_path / "ctor"))
    p2 = fr2.auto_dump("ctored", extra=dict(a=1))
    assert p2 and str(tmp_path / "ctor") in p2


def test_observability_tees_records_into_flight_ring():
    obs = Observability()
    obs.tracer.event("freeze", replica=2)
    obs.interval({"commits": 5})
    kinds = [e["kind"] for e in obs.flight.events]
    assert kinds == ["event", "metrics"]
    assert obs.flight.events[0]["name"] == "freeze"
    # the tee preserves the exporter's records too (not a redirect)
    assert [r["kind"] for r in obs.records] == kinds


def test_wedged_op_dumps_flight_archive_before_stuckop_raises(tmp_path):
    cfg = _cfg(value_words=6, op_timeout_rounds=4, trace_sample=0)
    kv = KVS(cfg, strict_timeouts=True)
    obs = kv.rt.attach_obs(Observability(flight_dir=str(tmp_path)))
    kv.freeze(1)
    kv.freeze(2)  # no ack quorum: the put below can never commit
    kv.put(0, 0, 3, [1])
    with pytest.raises(StuckOpError, match="stuck past op_timeout_rounds"):
        for _ in range(12):
            kv.step()
    assert obs.flight.dumps, "the watchdog must dump before raising"
    payload = flightrec.load(obs.flight.dumps[-1])  # checksum round-trip
    assert payload["reason"] == "stuck_op"
    diags = payload["extra"]["diags"]
    assert diags and diags[0]["key"] == 3
    assert payload["events"], "the ring must carry the run's recent records"


def test_checker_red_triggers_flight_dump(tmp_path, monkeypatch):
    from hermes_tpu.checker import linearizability as lin

    cfg = _cfg(trace_sample=0, n_sessions=16, ops_per_session=32)
    rt = FastRuntime(cfg, record=True)
    obs = rt.attach_obs(Observability(flight_dir=str(tmp_path)))
    assert rt.drain(400)
    assert rt.check().ok
    assert not obs.flight.dumps  # green checks never dump

    class _Red:  # stubbed red verdict: tests the trigger, not the checker
        ok = False
        keys_checked = 7

    monkeypatch.setattr(lin, "check_history", lambda *a, **k: _Red)
    monkeypatch.setattr("hermes_tpu.runtime.check_arrays",
                        lambda *a, **k: _Red, raising=False)
    assert not rt.check().ok
    assert obs.flight.dumps
    payload = flightrec.load(obs.flight.dumps[-1])
    assert payload["reason"] == "checker_red"
    assert payload["extra"]["keys_checked"] == 7


def test_install_sigterm_dumps_then_defers(tmp_path):
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda *a: seen.append("prev"))
    try:
        fr = FlightRecorder(dump_dir=str(tmp_path))
        fr.record({"t": 0.0, "kind": "event", "name": "tick"})
        restore = flightrec.install_sigterm(fr, extra=dict(where="test"))
        signal.raise_signal(signal.SIGTERM)
        assert fr.dumps and flightrec.load(fr.dumps[-1])["reason"] == \
            "sigterm"
        assert seen == ["prev"]  # previous disposition honored after dump
        restore()
        assert signal.getsignal(signal.SIGTERM) is not None
    finally:
        signal.signal(signal.SIGTERM, prev)
