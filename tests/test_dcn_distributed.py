"""Multi-host DCN smoke test (SURVEY.md §5.8; VERDICT round-1 item 8): two
OS processes, each exposing 4 virtual CPU devices, joined by
``jax.distributed.initialize`` into one 8-device global mesh driving
``hermes_tpu.launch`` — the sharded faststep round's INV/ACK/VAL
collectives then genuinely cross the process boundary (the DCN path of the
tpu_ici transport).  This is the jax.distributed analog of
test_tcp_distributed.py's C++ socket run."""

import ast
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("n_hosts,devs_per_host", [(2, 4), (4, 2)])
def test_two_process_dcn_launch(n_hosts, devs_per_host):
    steps = 25
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for h in range(n_hosts):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devs_per_host}"
        )
        env["PALLAS_AXON_POOL_IPS"] = ""  # never claim the tunneled TPU
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "hermes_tpu.launch",
                    "--coordinator", f"localhost:{port}",
                    "--num-hosts", str(n_hosts),
                    "--host-id", str(h),
                    "--replicas", str(n_hosts * devs_per_host),
                    "--keys", "4096",
                    "--sessions", "8",
                    "--steps", str(steps),
                ],
                env=env,
                cwd=repo,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=420)
        assert p.returncode == 0, stderr.decode()[-3000:]
        outs.append(stdout.decode())

    # rank 0 prints the allgathered counters dict; the run must have
    # completed ops on every replica through cross-process collectives
    printed = [o for o in outs if o.strip()]
    assert printed, outs
    counters = ast.literal_eval(printed[0].strip().splitlines()[-1])
    total = (int(counters["n_read"]) + int(counters["n_write"])
             + int(counters["n_rmw"]) + int(counters["n_abort"]))
    assert total > 0, counters
    assert int(counters["n_write"]) > 0, counters
