"""Multi-host DCN smoke test (SURVEY.md §5.8; VERDICT round-1 item 8): two
OS processes, each exposing 4 virtual CPU devices, joined by
``jax.distributed.initialize`` into one 8-device global mesh driving
``hermes_tpu.launch`` — the sharded faststep round's INV/ACK/VAL
collectives then genuinely cross the process boundary (the DCN path of the
tpu_ici transport).  This is the jax.distributed analog of
test_tcp_distributed.py's C++ socket run."""

import ast
import os
import socket
import subprocess
import sys
import textwrap

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _probe_dcn_cpu():
    """Collection-time probe: does THIS jaxlib's CPU backend run
    multiprocess collectives at all?  Some builds raise ``Multiprocess
    computations aren't implemented on the CPU backend`` from the first
    cross-process psum — an environment property, not a regression, so
    the launch tests skip LOUDLY with the probe's own error in the skip
    reason instead of failing 7 minutes into a full launch.  Memoized
    via the returned tuple so both parametrizations pay one probe."""
    port = _free_port()
    src = textwrap.dedent(
        """
        import sys
        import jax
        jax.distributed.initialize(
            coordinator_address="localhost:%d",
            num_processes=2, process_id=int(sys.argv[1]))
        import jax.numpy as jnp
        out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),)))
        assert float(out[0]) == jax.device_count(), out
        print("DCN_OK")
        """ % port)
    procs = []
    for h in range(2):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PALLAS_AXON_POOL_IPS"] = ""
        procs.append(subprocess.Popen(
            [sys.executable, "-c", src, str(h)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    outs = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=120)
            outs.append((p.returncode, stdout.decode(), stderr.decode()))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False, "probe timed out after 120s (coordinator never met)"
    if all(rc == 0 and "DCN_OK" in out for rc, out, _err in outs):
        return True, "probe ok"
    err = next((e for rc, _o, e in outs if rc != 0), outs[0][2])
    tail = [ln for ln in err.strip().splitlines() if ln.strip()]
    return False, (tail[-1][-300:] if tail
                   else f"probe exited {[o[0] for o in outs]}")


_DCN_OK, _DCN_DETAIL = _probe_dcn_cpu()


@pytest.mark.skipif(
    not _DCN_OK,
    reason="this jaxlib's CPU backend cannot run multiprocess "
           f"collectives (2-process psum probe: {_DCN_DETAIL})")
@pytest.mark.parametrize("n_hosts,devs_per_host", [(2, 4), (4, 2)])
def test_two_process_dcn_launch(n_hosts, devs_per_host):
    steps = 25
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for h in range(n_hosts):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devs_per_host}"
        )
        env["PALLAS_AXON_POOL_IPS"] = ""  # never claim the tunneled TPU
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "hermes_tpu.launch",
                    "--coordinator", f"localhost:{port}",
                    "--num-hosts", str(n_hosts),
                    "--host-id", str(h),
                    "--replicas", str(n_hosts * devs_per_host),
                    "--keys", "4096",
                    "--sessions", "8",
                    "--steps", str(steps),
                ],
                env=env,
                cwd=repo,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        )
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=420)
        assert p.returncode == 0, stderr.decode()[-3000:]
        outs.append(stdout.decode())

    # rank 0 prints the allgathered counters dict; the run must have
    # completed ops on every replica through cross-process collectives
    printed = [o for o in outs if o.strip()]
    assert printed, outs
    counters = ast.literal_eval(printed[0].strip().splitlines()[-1])
    total = (int(counters["n_read"]) + int(counters["n_write"])
             + int(counters["n_rmw"]) + int(counters["n_abort"]))
    assert total > 0, counters
    assert int(counters["n_write"]) > 0, counters
