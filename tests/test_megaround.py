"""Round-15 Pallas mega-round (core/megaround.py, ISSUE 11).

The mega path's contract is BIT-IDENTITY: with ``mega_round=True`` the
round must produce byte-for-byte the same FastState/Meta trees as the
fused-sort program it fuses — on both engines, through freeze/thaw (the
replay-scan kernel's take path), through the multi-block ragged table
grid, at pipeline depth 2 and under a seeded chaos schedule.  Plus the
resolution contract (loud fallback when analysis refuses), the census
floor, and the analyzer red tests (a deliberately broken kernel must
flip the findings red and the resolution must then refuse it).
"""

import dataclasses
import warnings

import numpy as np
import pytest

import jax

from hermes_tpu import chaos
from hermes_tpu.config import (HermesConfig, MEGA_VPTS_VMEM_BYTES,
                               WorkloadConfig)
from hermes_tpu.core import megaround
from hermes_tpu.runtime import FastRuntime


def _cfg(**kw):
    base = dict(
        n_replicas=3, n_keys=32, n_sessions=8, replay_slots=4,
        ops_per_session=24, arb_mode="sort", chain_writes=2,
        replay_scan_every=4, replay_age=4, rebroadcast_every=2,
        workload=WorkloadConfig(read_frac=0.3, rmw_frac=0.2, seed=7),
    )
    base.update(kw)
    return HermesConfig(**base)


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"state leaf {i} diverged")


def _drive_freeze_thaw(cfg, backend="batched", mesh=None):
    rt = FastRuntime(cfg, backend=backend, mesh=mesh, record=True)
    for i in range(100):
        if i == 10:
            rt.freeze(1)
        if i == 40:
            rt.thaw(1)
        if i == 60:
            rt.freeze(0)
        if i == 80:
            rt.thaw(0)
        rt.step_once()
    rt.drain(3000)
    return rt


def test_mega_quick_drain_check_with_replay():
    """Quick-tier sibling (single compile — the two-program bit-identity
    runs live in the slow tier and every gate run): one mega round
    program through a freeze window at a tiny shape must exercise route
    + apply + the replay-scan kernel (replay_age=4, scan every 4), drain
    every op, conserve totals, and pass the linearizability checker."""
    cfg = _cfg(n_keys=16, n_sessions=4, ops_per_session=8,
               mega_round=True)
    rt = FastRuntime(cfg, record=True)
    for i in range(30):
        if i == 5:
            rt.freeze(1)
        if i == 18:
            rt.thaw(1)
        rt.step_once()
    assert rt.drain(1000)
    assert int(np.asarray(rt.fs.meta.replay_peak).max()) > 0, \
        "replay kernel path was not exercised"
    c = rt.counters()
    total = c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"]
    assert total == cfg.n_replicas * cfg.n_sessions * cfg.ops_per_session
    assert rt.check().ok


def test_mega_matches_fused_batched_through_freeze_thaw():
    """State identity under failure injection: freezes age keys past
    replay_age, so the mega replay kernel's candidate/mark/slot path runs
    for real (replay_peak reaches the slot count) — every leaf of the
    final FastState/Meta tree must match the fused-sort program's."""
    a = _drive_freeze_thaw(_cfg())
    b = _drive_freeze_thaw(_cfg(mega_round=True))
    _tree_equal(a.fs, b.fs)
    assert int(np.asarray(b.fs.meta.replay_peak).max()) > 0, \
        "replay path was not exercised — the identity claim is vacuous"
    assert b.check().ok


def test_mega_matches_fused_sharded():
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("replica",))
    base = dict(n_replicas=4, workload=WorkloadConfig(
        read_frac=0.3, rmw_frac=0.2, seed=9))
    a = _drive_freeze_thaw(_cfg(**base), backend="sharded", mesh=mesh)
    b = _drive_freeze_thaw(_cfg(mega_round=True, **base),
                           backend="sharded", mesh=mesh)
    _tree_equal(a.fs, b.fs)
    assert b.check().ok


def test_mega_replay_multiblock_ragged_identity(monkeypatch):
    """The replay kernel's block grid at a RAGGED shape (37 rows over
    13-row blocks): the streaming candidate cursor crosses block visits
    and the tail block masks its padding rows — still bit-identical."""
    monkeypatch.setattr(megaround, "REPLAY_BLOCK_BYTES", 13 * 40)
    a = _drive_freeze_thaw(_cfg(n_keys=37))
    b = _drive_freeze_thaw(_cfg(n_keys=37, mega_round=True))
    _tree_equal(a.fs, b.fs)
    assert int(np.asarray(b.fs.meta.replay_peak).max()) > 0
    assert b.check().ok


def test_mega_pipeline_depth2_chaos_schedule_identity():
    """The serving shape: pipeline depth 2 + a seeded chaos schedule
    (freeze/thaw/heartbeat skew) driven identically against the fused and
    mega programs — byte-identical executed event log AND final state,
    checker green."""
    def run(mega):
        cfg = _cfg(n_replicas=4, pipeline_depth=2, mega_round=mega,
                   ops_per_session=16)
        rt = FastRuntime(cfg, record=True)
        sched = chaos.Schedule.random(cfg, seed=23, steps=80)
        runner = chaos.ChaosRunner(rt, sched)
        res = runner.run(80, check=True)
        assert res["drained"] and res["checked_ok"]
        return runner.log_json(), rt.fs

    log_a, fs_a = run(False)
    log_b, fs_b = run(True)
    assert log_a == log_b, "executed chaos logs differ"
    _tree_equal(fs_a, fs_b)


def test_mega_census_floor_and_interior_policed():
    """The round-15 acceptance floor at a device-stream shape: the mega
    batched round lowers to <= 4 sparse ops (vs the fused baseline's
    strictly more), the kernel interiors carry ZERO cost-model ops, and
    the Pallas ledger sees all four kernels (stats + route + apply +
    replay) with a nonzero serial bound — the census can no longer go
    blind inside a pallas_call."""
    from hermes_tpu.obs import profile as prof

    base = dict(n_keys=64, n_sessions=8, device_stream=True,
                wrap_stream=True, ops_per_session=8)
    fused = prof.op_census(_cfg(**base), "batched")
    mega = prof.op_census(_cfg(mega_round=True, **base), "batched")
    assert mega["sparse_total"] <= 4
    assert mega["sparse_total"] < fused["sparse_total"]
    assert mega["pallas_interior_sparse"] == 0
    assert mega["pallas_calls"] == 4
    assert mega["pallas_serial_iter_bound"] > 0
    assert mega["collective_total"] == 0
    # the non-mega census rides the ledger too (stats_block policed)
    assert fused["pallas_calls"] == 1
    assert fused["pallas_interior_sparse"] == 0


def test_mega_config_validation_and_resolution():
    with pytest.raises(ValueError, match="mega_round"):
        HermesConfig(mega_round=True, arb_mode="race")
    with pytest.raises(ValueError, match="mega_round"):
        HermesConfig(mega_round=True, arb_mode="sort", fused_sort=False)
    with pytest.raises(ValueError, match="VMEM"):
        HermesConfig(mega_round=True, arb_mode="sort",
                     n_keys=(MEGA_VPTS_VMEM_BYTES // 4) * 2)
    assert not HermesConfig().use_mega_round
    assert _cfg(mega_round=True).use_mega_round
    assert not megaround.resolve(_cfg())  # knob off -> never resolves


def test_mega_resolution_refusal_falls_back_loudly(monkeypatch):
    """The 'analysis refuses' contract: when the kernel verdict is
    dirty, the builders must warn LOUDLY (once) and the built program
    must be the fused-sort fallback — bit-identical to fused_sort=True,
    with zero pallas mega kernels in the lowering."""
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.obs import profile as prof
    from hermes_tpu.workload import ycsb

    monkeypatch.setattr(megaround, "_kernels_clean",
                        lambda: (False, "forced-dirty (test)"))
    megaround._WARNED.clear()
    try:
        cfg = _cfg(mega_round=True)
        with pytest.warns(RuntimeWarning, match="forced-dirty"):
            assert not megaround.resolve(cfg)
        # the built program is the fused baseline: same lowering census
        cen = prof.op_census(cfg, "batched")
        ref = prof.op_census(_cfg(), "batched")
        assert cen == ref
        # and it still runs correctly end to end
        stream = fst.prep_stream(ycsb.make_streams(cfg))
        fs = fst.init_fast_state(cfg)
        step = fst.build_fast_batched(cfg)
        for i in range(5):
            fs, _ = step(fs, stream, fst.make_fast_ctl(cfg, i))
        ref_fs = fst.init_fast_state(_cfg())
        ref_step = fst.build_fast_batched(_cfg())
        for i in range(5):
            ref_fs, _ = ref_step(ref_fs, stream,
                                 fst.make_fast_ctl(_cfg(), i))
        _tree_equal(fs, ref_fs)
    finally:
        megaround._WARNED.clear()


def test_broken_kernel_oob_store_flips_analyzer_red(monkeypatch):
    """Analyzer red test: drop the apply kernel's index clamp/guard and
    the RefHazard pass must flag the scatter site (the untrusted 29-bit
    wire key escapes the vpts block) — and the resolution must then
    REFUSE the mega path."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from hermes_tpu.analysis import diffcheck

    def bad_apply_kernel(K, N):
        def kern(vin_ref, key_ref, pts_ref, mask_ref, vout_ref, post_ref):
            del vin_ref
            phase = pl.program_id(0)

            @pl.when(phase == 0)
            def _():
                def body(m, c):
                    k = key_ref[pl.ds(m, 1), 0][0]  # UNCLAMPED wire key
                    vout_ref[pl.ds(k, 1), 0] = jnp.maximum(
                        vout_ref[pl.ds(k, 1), 0], pts_ref[pl.ds(m, 1), 0])
                    return c

                jax.lax.fori_loop(0, N, body, 0)

            @pl.when(phase == 1)
            def _():
                def body(m, c):
                    k = jnp.clip(key_ref[pl.ds(m, 1), 0][0], 0, K - 1)
                    post_ref[pl.ds(m, 1), 0] = vout_ref[pl.ds(k, 1), 0]
                    return c

                jax.lax.fori_loop(0, N, body, 0)

        return kern

    monkeypatch.setattr(megaround, "_apply_kernel", bad_apply_kernel)
    megaround.reset_resolution_cache()
    try:
        rep = diffcheck.analyze_kernel(
            diffcheck.cell_by_name("mega_apply/k16n16"))
        codes = [f.code for f in rep["findings"]
                 if f.severity in ("error", "warn")]
        assert "oob-block-store" in codes
        ok, reason = megaround._kernels_clean()
        assert not ok and "oob-block-store" in reason
        with pytest.warns(RuntimeWarning):
            assert not megaround.resolve(_cfg(mega_round=True))
    finally:
        megaround.reset_resolution_cache()


def test_broken_kernel_pack_overflow_flips_analyzer_red(monkeypatch):
    """Analyzer red test #2 (the pack half): a route kernel that shifts
    the verdict word into the sign bit must trip the bitpack pass inside
    the kernel body."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from hermes_tpu.analysis import diffcheck

    def bad_route_kernel(L, C):
        def kern(si_ref, word_ref, srank_ref, lw_ref, sl_ref):
            lw_ref[:] = jnp.zeros_like(lw_ref)
            sl_ref[:] = jnp.zeros_like(sl_ref)

            def body(p, c):
                lane = jnp.clip(si_ref[pl.ds(p, 1), 0][0], 0, L - 1)
                w = word_ref[pl.ds(p, 1), 0]
                lw_ref[pl.ds(lane, 1), 0] = (w << 12) | w  # sign-bit pack
                return c

            jax.lax.fori_loop(0, L, body, 0)

        return kern

    monkeypatch.setattr(megaround, "_route_kernel", bad_route_kernel)
    megaround.reset_resolution_cache()
    try:
        rep = diffcheck.analyze_kernel(
            diffcheck.cell_by_name("mega_route/r2l6"))
        codes = [f.code for f in rep["findings"]
                 if f.severity in ("error", "warn")]
        assert "pack-shift-overflow" in codes
        ok, _reason = megaround._kernels_clean()
        assert not ok
    finally:
        megaround.reset_resolution_cache()


def test_mega_kernel_cells_registered_and_sanitized():
    """The differential sanitizer must draw against the mega kernels
    (ISSUE 11 satellite): all three kernels registered, including the
    multi-block ragged replay cell; one representative cell sanitized
    here (the full matrix runs in the analysis gate)."""
    from hermes_tpu.analysis import diffcheck

    names = {c.name for c in diffcheck.kernel_cells()}
    assert {"mega_route/r2l6", "mega_apply/k16n16", "mega_replay/k16b1",
            "mega_replay/k22b3"} <= names
    res = diffcheck.diff_check(
        diffcheck.cell_by_name("mega_apply/k16n16"), n_draws=2)
    assert res["ok"], res["violations"]


def test_resolution_probe_usable_under_trace():
    """The first resolve may happen while an outer round is being traced
    (census/profile paths jit the round directly): the probe must not
    leak tracers or refuse.  Force the cold path inside a jit trace."""
    import jax.numpy as jnp

    megaround.reset_resolution_cache()
    try:
        cfg = _cfg(mega_round=True)
        seen = {}

        @jax.jit
        def traced(x):
            seen["resolved"] = megaround.resolve(cfg)
            return x + 1

        with warnings.catch_warnings():
            warnings.simplefilter("error", RuntimeWarning)
            traced(jnp.zeros((4,), jnp.int32))
        assert seen["resolved"] is True
    finally:
        megaround.reset_resolution_cache()
