"""Test env: force pure-CPU JAX with a virtual 8-device mesh.

Two things matter here (see SURVEY.md §7 "Local environment"):
  * The container's sitecustomize registers the `axon` PJRT plugin (the
    tunneled single TPU chip) in every python process; initializing it can
    block on the TPU claim.  Tests must never touch it: we force the cpu
    platform and clear any pre-registered backend set BEFORE first device
    use (registration already happened at interpreter start; backend *init*
    is lazy, so this is early enough).
  * Sharded-step tests need multiple devices: 8 virtual CPU devices via
    --xla_force_host_platform_device_count (the standard way to exercise
    Mesh/shard_map code without 8 real chips).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # drop any backend set the axon sitecustomize may have pinned
    from jax._src import xla_bridge

    _clear = getattr(xla_bridge, "clear_backends", None) or getattr(
        xla_bridge, "_clear_backends"
    )
    _clear()
except Exception:
    pass

import pytest  # noqa: E402

# --- suite tiers (round-4 verdict weak #7) ---------------------------------
# The full suite is the gate (`python -m pytest tests/ -x -q`, ~14 min on
# this 1-CPU container); `-m "not slow"` is the quick tier (< 5 min) for a
# cold session / judge pass.  A test goes in SLOW_TESTS when it measured
# >= ~7.5 s on the reference container (pytest --durations); every slow
# test keeps a faster sibling in the default tier covering the same
# mechanism at smaller shape, so the quick tier stays a real signal.
SLOW_TESTS = {
    "test_two_process_dcn_launch",          # multi-process jax.distributed
    "test_three_process_tcp_run",           # multi-process C++ tcp
    "test_tcp_wire_corruption_end_to_end",  # multi-process C++ tcp + wire chaos
    "test_tcp_staggered_start_retries_dial",  # multi-process, sleeps in dial
    "test_tcp_peer_death_fails_loudly_not_hang",  # multi-process death drill
    "test_chaos_drop_dup_delay",            # 12-seed adversarial soak
    "test_main_records_dryrun_before_entry_outage",  # subprocess re-exec
    "test_parity_on_clean_runs",
    "test_kvs_sparse_snapshot_roundtrip",
    "test_sharded_snapshot_roundtrip",
    "test_snapshot_resume_deterministic",
    "test_snapshot_carries_rebase_bookkeeping",
    "test_kvs_load_validates_before_mutating",
    "test_kvs_sharded_backend_roundtrip",
    "test_arb_mode_sort_checked_and_matches_totals",
    "test_chain_writes_hot_key_service_rate_and_check",
    "test_sharded_matches_batched",
    "test_read_unroll_sharded_matches_batched",
    "test_stats_block_multi_block_grid",
    "test_sanitizer_passes_kernel_matrix",  # 3-shape diffcheck soak
    "test_gate_kernel_section_red_on_unsound_rule",  # gate subprocess-ish
    "test_frozen_replica_stall_and_recovery",
    "test_kvs_client_path_at_scale_checked",
    "test_kvs_sparse_keys_end_to_end_checked",
    "test_kvs_sparse_get_absent_key_is_not_found",
    "test_put_get_roundtrip_remote_replica",
    "test_zipfian_contention_checked",
    "test_ycsb_f_rmw_checked",
    "test_ycsb_a_uniform_checked",
    "test_auto_detect_removes_stalled_replica",
    "test_auto_detect_then_rejoin_converges",
    "test_false_suspicion_fences_partitioned_replica",
    "test_membership_join_mid_workload",
    "test_survives_replica_failure",
    "test_session_queueing_fifo",
    "test_lane_budget_backpressure",
    "test_read_unroll_drains_reads_and_checks",
    "test_submit_batch_sharded_backend",
    "test_checked_client_run",
    "test_concurrent_puts_same_key_converge",
    "test_rmw_reads_displaced_value",
    "test_get_untouched_key_returns_initial",
    "test_stall_remove_rejoin_checked",
    "test_random_fault_soak_checked_sharded",
    "test_rmw_retry_sharded_matches_batched",
    "test_rmw_retry_converts_aborts_to_commits",
    # quick-tier trim (round-5): each of these has a same-mechanism sibling
    # that stays in the quick tier — rebase keeps headroom/kvs-inflight/
    # quiesce-flag; scan equivalence keeps the sharded variant; backend
    # equivalence keeps the sharded cell; retry keeps acceptance[2r]
    "test_sharded_rebase_nonuniform_keys_vetoed",
    "test_auto_rebase_soak_crosses_old_budget",
    "test_auto_rebase_backoff_latch",
    "test_scan_matches_step_loop",
    "test_sim_backend_lockstep_equivalence",
    "test_rmw_retry_bounded_then_aborts",
    # round-13 fleet: each keeps a quick sibling — routing/batch edges and
    # the group-0-isolation red test stay quick on the shared fixture;
    # migration keeps its refusal + dest_slots-validation branches quick,
    # membership scoping keeps the chaos-isolation sibling
    "test_fleet_chaos_deterministic_replay",
    "test_fleet_snapshot_scope_roundtrip",
    "test_fleet_routed_sessions_roundtrip",
    "test_fleet_sharded_groups_on_submeshes",
    "test_fleet_migration_smoke",
    "test_membership_and_healthy_set_group_scoped",
    # round-15 mega-round: the quick tier keeps a single-compile
    # checker-gated mega drive (test_mega_quick_drain_check_with_replay),
    # the census floor, the kernel-cell registration + sanitizer draw,
    # and both analyzer red tests (which also cover the refusal->
    # fallback warning path); the two-program bit-identity matrix is
    # slow-tier (it compiles both programs — and every serial gate run
    # exercises the identity machinery anyway)
    "test_mega_matches_fused_batched_through_freeze_thaw",
    "test_mega_matches_fused_sharded",
    "test_mega_replay_multiblock_ragged_identity",
    "test_mega_pipeline_depth2_chaos_schedule_identity",
    "test_mega_resolution_refusal_falls_back_loudly",
    # round-16 read path: the quick tier keeps the core local-serve +
    # checker test, the invalid-fallback branch, the sharded stale-read
    # red test, the batch-token fence sibling (same fence mechanism as
    # the lane/tenant variants), the loopback serving e2e, the sparse
    # scan sibling, and the fleet draining-reject sibling; everything
    # below pays a fresh multi-second compile for a mechanism its quick
    # sibling already exercises
    "test_stale_read_red_batched",
    "test_fleet_multi_get_merges_in_fleet_key_order",
    "test_ryw_holds_under_seeded_chaos_depth2",
    "test_serving_mget_over_real_sockets",
    "test_multi_get_sparse_absent_not_found_no_slot",
    "test_serving_ryw_fence_is_tenant_scoped",
    "test_ryw_fence_redirects_to_round_path",
    "test_scan_sparse_echoes_client_keys_in_write_order",
    "test_sharded_multi_get_serves_and_checks",
    "test_scan_probe_cannot_hide_cold_interior_behind_hot_endpoints",
    # round-17 value heap: the quick tier keeps the batched round trip,
    # the pressure/rebase GC churn, and the unit/codec/wire coverage;
    # these heavier soaks (fleet composition, sharded engine, chaos at
    # depth 2, the migration/snapshot/serving drills) ride the full
    # suite + scripts/check_heap.py, which re-proves each end to end
    "test_fleet_heap_roundtrip_and_cross_group_migration",
    "test_gc_under_chaos_traffic_depth2",
    "test_kvs_sharded_put_get_scan_byte_exact",
    "test_migrate_range_moves_extents_byte_exact",
    "test_snapshot_roundtrip_and_torn_heap_red",
    "test_serving_loopback_heap_end_to_end",
    # round-20 hostlint: the native-sanitizer build+run suite (ASan/UBSan
    # + TSan compiles of the C++ transport) is minutes of g++; the quick
    # tier keeps the toolchain-presence test so absence is LOUD
    "test_native_sanitizer_suite",
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        base = item.name.split("[")[0]
        if base in SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
            matched.add(base)
    # Tier-drift guard (round-5 advice #1): a renamed or mistyped test
    # silently drops out of the slow tier — the entry lingers here matching
    # nothing, and the test runs in the wrong tier forever.  When the FULL
    # suite was collected, every entry must have matched something.  Partial
    # collections (single file, or a module that failed to import under
    # --continue-on-collection-errors) legitimately miss entries, so the
    # guard only fires when every test module on disk made it into the
    # collected set.  (This hook runs before pytest's own -m/-k deselection
    # — it must, for the slow markers it adds to be filterable — so marker
    # expressions like 'not slow' never hide items from this check.)
    unmatched = SLOW_TESTS - matched
    if unmatched:
        import pathlib

        here = pathlib.Path(__file__).parent
        on_disk = {p.name for p in here.glob("test_*.py")}
        collected = {pathlib.Path(str(item.fspath)).name for item in items}
        if on_disk <= collected:
            raise pytest.UsageError(
                "SLOW_TESTS entries matched no collected test (renamed or "
                f"mistyped? fix tests/conftest.py): {sorted(unmatched)}")


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert devs[0].platform == "cpu"
    return devs
