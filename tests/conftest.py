"""Test env: force pure-CPU JAX with a virtual 8-device mesh.

Two things matter here (see SURVEY.md §7 "Local environment"):
  * The container's sitecustomize registers the `axon` PJRT plugin (the
    tunneled single TPU chip) in every python process; initializing it can
    block on the TPU claim.  Tests must never touch it: we force the cpu
    platform and clear any pre-registered backend set BEFORE first device
    use (registration already happened at interpreter start; backend *init*
    is lazy, so this is early enough).
  * Sharded-step tests need multiple devices: 8 virtual CPU devices via
    --xla_force_host_platform_device_count (the standard way to exercise
    Mesh/shard_map code without 8 real chips).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # drop any backend set the axon sitecustomize may have pinned
    from jax._src import xla_bridge

    _clear = getattr(xla_bridge, "clear_backends", None) or getattr(
        xla_bridge, "_clear_backends"
    )
    _clear()
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert devs[0].platform == "cpu"
    return devs
