"""Randomized fault-injection soak over the fast engine (SURVEY.md §4.4).

A seeded scheduler drives freeze / lease-style remove / rejoin-with-state-
transfer / spontaneous-thaw events against a running workload, then heals
the cluster, drains, and gates the whole history on the linearizability
checker.  This stresses exactly the paths the optimized engine treats
specially: replay of dead coordinators' writes, commit-during-backoff after
live-mask shrinks, duplicate (key, ts) slots from replay rebroadcasts, and
join state transfer — under arbitrary interleavings rather than the
hand-written drills.
"""

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.runtime import FastRuntime

from helpers import get


@pytest.mark.parametrize("seed,arb_mode,chain,retries", [
    (11, "race", 0, 0),
    (23, "race", 0, 0),
    (23, "sort", 0, 0),
    (23, "sort", 6, 0),
    (31, "sort", 6, 0),
    # round-5: RMW retry-in-place under the same chaos — a retrying
    # session must survive freezes/removes/joins of its own replica's
    # peers (its dead nacked ts must not resurface through replay)
    (23, "sort", 6, 8),
    (31, "race", 0, 8),
])
def test_random_fault_soak_checked(seed, arb_mode, chain, retries):
    cfg = _soak_cfg(seed, arb_mode, chain, retries)
    _run_soak(FastRuntime(cfg, record=True))


def test_random_fault_soak_checked_sharded():
    """The same randomized chaos schedule against the SHARDED engine (the
    transport=tpu_ici program shape: real collectives over a 5-device
    mesh) — freeze/remove/rejoin-with-state-transfer interleavings travel
    the wire path, not the lockstep emulation."""
    import jax
    from jax.sharding import Mesh

    seed = 23
    cfg = _soak_cfg(seed, "sort", 6, 8)
    mesh = Mesh(np.array(jax.devices()[: cfg.n_replicas]), ("replica",))
    _run_soak(FastRuntime(cfg, backend="sharded", mesh=mesh, record=True))


def _soak_cfg(seed, arb_mode, chain, retries):
    return HermesConfig(
        n_replicas=5, n_keys=96, n_sessions=6, replay_slots=6,
        ops_per_session=30, replay_age=6, replay_scan_every=4,
        rebroadcast_every=2, arb_mode=arb_mode, chain_writes=chain,
        rmw_retries=retries,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.25, seed=seed),
    )


def _run_soak(rt):
    cfg = rt.cfg
    R = cfg.n_replicas
    rng = np.random.default_rng(cfg.workload.seed)

    frozen_since = {}  # replica -> step frozen (still in live mask)
    removed = set()

    for step in range(260):
        rt.step_once()
        live = int(rt.live[0])
        alive_ok = [r for r in range(R) if (live >> r) & 1 and not rt.frozen[r]]

        # lease-style detection: a replica frozen too long gets removed
        for r, since in list(frozen_since.items()):
            if step - since > 5:
                rt.remove(r)
                removed.add(r)
                del frozen_since[r]

        u = rng.random()
        if u < 0.06 and len(alive_ok) > 3:
            r = int(rng.choice(alive_ok))
            rt.freeze(r)
            frozen_since[r] = step
        elif u < 0.10 and frozen_since:
            # spontaneous recovery before the lease fires
            r = int(rng.choice(list(frozen_since)))
            rt.thaw(r)
            del frozen_since[r]
        elif u < 0.16 and removed:
            r = removed.pop()
            donor = int(rng.choice([d for d in range(R) if (int(rt.live[0]) >> d) & 1
                                    and not rt.frozen[d]]))
            rt.join(r, from_replica=donor)

    # heal: thaw stragglers, rejoin everyone, let the workload finish
    for r in list(frozen_since):
        rt.thaw(r)
    for r in list(removed):
        donor = next(d for d in range(R) if (int(rt.live[0]) >> d) & 1 and not rt.frozen[d])
        rt.join(r, from_replica=donor)
    assert rt.drain(4000), "cluster did not drain after healing"

    v = rt.check()
    assert v.ok, (v.failures[:3], v.undecided[:3])
    # every key readable again and totals conserved
    sst = get(rt.fs.table.sst)
    assert ((sst & 7) == t.VALID).all()
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == R * 6 * 30
