"""Round-14 serving front-end: wire codec, admission exactness,
deadlines, backpressure, shed ladder, fleet routing, determinism, and
the run_gates timeout satellite."""

import os
import socket
import sys

import numpy as np
import pytest

from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
from hermes_tpu.kvs import KVS, StuckOpError
from hermes_tpu.serving import (Frontend, LoopbackServer, RpcClient,
                                ServingConfig, TcpRpcServer, TokenBucket,
                                VirtualClock, measure_capacity,
                                run_open_loop, verify_serving, wire)
from hermes_tpu.workload.openloop import (MixSpec, ShapedArrivals, make_mix,
                                          poisson_arrivals, scenario_matrix,
                                          scenario_seed)


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=64, n_sessions=4, replay_slots=6,
              ops_per_session=96, value_words=6, replay_age=6,
              replay_scan_every=4, rebroadcast_every=2, lease_steps=6,
              workload=WorkloadConfig(read_frac=0.5, seed=7))
    kw.update(over)
    return HermesConfig(**kw)


def _scfg(**over):
    kw = dict(tenant_rate_per_s=1e6, tenant_burst=1e4, tenant_quota=16,
              queue_cap=64, round_us=1000)
    kw.update(over)
    return ServingConfig(**kw)


# -- wire codec --------------------------------------------------------------

def test_wire_request_response_roundtrip():
    req = wire.Request(kind="rmw", req_id=99, tenant=3, key=41,
                       deadline_us=12345, value=[5, 6])
    out = wire.decode_request(wire.encode_request(req, 4), 4)
    assert (out.kind, out.req_id, out.tenant, out.key, out.deadline_us) == \
        ("rmw", 99, 3, 41, 12345)
    assert out.value == [5, 6, 0, 0]
    rsp = wire.Response(status=wire.S_RETRY_AFTER, req_id=99,
                        reason=wire.R_QUEUE_FULL, retry_after_us=777)
    back = wire.decode_response(wire.encode_response(rsp, 4), 4)
    assert back.status == wire.S_RETRY_AFTER
    assert back.reason_name == "queue_full"
    assert back.retry_after_us == 777


def test_wire_rejects_bad_magic_and_size():
    raw = bytearray(wire.encode_request(
        wire.Request(kind="get", req_id=1, tenant=0, key=0), 2))
    raw[0] ^= 0xFF
    with pytest.raises(ValueError, match="magic"):
        wire.decode_request(bytes(raw), 2)
    with pytest.raises(ValueError, match="size"):
        wire.decode_request(b"\x00" * 7, 2)


def test_framed_socket_drops_corrupt_frame():
    from hermes_tpu.transport import codec
    from hermes_tpu.transport.tcp import FramedSocket

    a, b = socket.socketpair()
    tx, rx = FramedSocket(a), FramedSocket(b)
    bad = codec.frame_pack(np.frombuffer(b"hello", np.uint8)).copy()
    bad[-1] ^= 0xFF  # corrupt the payload AFTER the crc was computed
    a.sendall(bad.tobytes())
    tx.send(b"world")
    assert rx.recv() == b"world"  # corrupt frame skipped, not applied
    assert rx.corrupt_dropped == 1
    tx.close(), rx.close()


def test_framed_socket_corrupt_length_tears_down_not_desyncs():
    # a bit flip in the header's LENGTH field (CRC covers only the
    # payload) would silently shift the stream cursor; with expect_lens
    # the receiver detects the implausible length on CRC failure and
    # tears down LOUDLY instead of delivering misaligned frames
    from hermes_tpu.transport import codec
    from hermes_tpu.transport.tcp import FramedSocket

    a, b = socket.socketpair()
    tx, rx = FramedSocket(a), FramedSocket(b, expect_lens={5})
    bad = bytearray(codec.frame_pack(
        np.frombuffer(b"hello", np.uint8)).tobytes())
    # header <HBBII: magic(2) algo(1) pad(1) length(4) crc(4)
    assert bad[4] == 5
    bad[4] = 6  # corrupted length: still plausible-looking, wrong
    a.sendall(bytes(bad))
    tx.send(b"world")  # rx would consume 1 byte of THIS frame's header
    with pytest.raises(codec.FrameCorrupt, match="length"):
        rx.recv()
    # payload corruption with an EXPECTED length still skips, as before
    a2, b2 = socket.socketpair()
    tx2, rx2 = FramedSocket(a2), FramedSocket(b2, expect_lens={5})
    bad2 = codec.frame_pack(np.frombuffer(b"howdy", np.uint8)).copy()
    bad2[-1] ^= 0xFF
    a2.sendall(bad2.tobytes())
    tx2.send(b"again")
    assert rx2.recv() == b"again"
    assert rx2.corrupt_dropped == 1
    tx.close(), rx.close(), tx2.close(), rx2.close()


# -- generators --------------------------------------------------------------

def test_poisson_arrivals_byte_identical():
    a = poisson_arrivals(500.0, 300, seed=21)
    assert a.tobytes() == poisson_arrivals(500.0, 300, seed=21).tobytes()
    assert a.tobytes() != poisson_arrivals(500.0, 300, seed=22).tobytes()
    assert (np.diff(a) > 0).all()


def test_make_mix_deterministic_and_shaped():
    m1 = make_mix(MixSpec(tenants=3), 64, 200, seed=5, value_words=4)
    m2 = make_mix(MixSpec(tenants=3), 64, 200, seed=5, value_words=4)
    for k in ("kind", "key", "tenant", "value"):
        assert m1[k].tobytes() == m2[k].tobytes()
    hot = make_mix(MixSpec(distribution="hotkey", hot_frac=1.0, hot_keys=2),
                   64, 100, seed=5)
    assert set(hot["key"].tolist()) <= {0, 1}


def test_shaped_arrivals_overload_compresses_deterministically():
    runs = []
    for _ in range(2):
        sa = ShapedArrivals(100.0, 50, seed=3)
        out = []
        for i in range(50):
            if i == 20:
                sa.set_rate_x(4.0)
            out.append(sa.peek())
            sa._next = None  # consume
        runs.append(out)
    assert runs[0] == runs[1]
    # after the multiplier, arrivals land earlier than the unshaped
    # schedule (gaps past the window compress by 4x)
    assert runs[0][30] < poisson_arrivals(100.0, 50, 3)[30]


def test_overload_verb_parse_format_and_refusal():
    from hermes_tpu import chaos

    sched = chaos.Schedule.parse("@5 overload x=3.5 until=20\n@30 overload_clear\n")
    assert sched.events[0].x == 3.5 and sched.events[0].until == 20
    assert chaos.Schedule.parse(sched.format()).format() == sched.format()
    storm = chaos.Schedule.overload_storm(9, steps=100, n_windows=2)
    assert storm.format() == chaos.Schedule.overload_storm(
        9, steps=100, n_windows=2).format()
    kvs = KVS(_cfg())
    with pytest.raises(ValueError, match="load shaper"):
        chaos.ChaosRunner(kvs, sched)  # no load= attached
    sa = ShapedArrivals(100.0, 10, seed=1)
    runner = chaos.ChaosRunner(kvs, sched, load=sa)
    runner.tick(5)
    assert sa.rate_x == 3.5
    runner.tick(20)  # window expires
    assert sa.rate_x == 1.0


def test_heal_closes_open_overload_window():
    # an `overload x=N` with no until= (awaiting an overload_clear) must
    # not outlive a heal — same rule as skews/partitions
    from hermes_tpu import chaos

    sched = chaos.Schedule.parse("@2 overload x=4\n@6 heal\n")
    kvs = KVS(_cfg())
    sa = ShapedArrivals(100.0, 10, seed=1)
    runner = chaos.ChaosRunner(kvs, sched, load=sa)
    runner.tick(2)
    assert sa.rate_x == 4.0
    runner.tick(6)
    assert sa.rate_x == 1.0


# -- admission ---------------------------------------------------------------

def test_token_bucket_exact():
    tb = TokenBucket(rate_per_s=10.0, burst=2.0)
    assert tb.take(0.0) and tb.take(0.0) and not tb.take(0.0)
    assert not tb.take(0.05)   # half a token accrued
    assert tb.take(0.1)        # exactly one
    assert tb.wait_s(0.1) == pytest.approx(0.1)


def test_quota_accounting_exact_under_concurrent_tenants():
    kvs = KVS(_cfg())
    clock = VirtualClock()
    quota = 3
    fe = Frontend(kvs, _scfg(tenant_quota=quota, queue_cap=64), clock=clock)
    refused = {0: 0, 1: 0}
    rid = 0
    for wave in range(6):
        for t in (0, 1):
            for _ in range(5):  # 5 > quota: some must be refused
                rid += 1
                rsp = fe.submit(wire.Request(kind="put", req_id=rid,
                                             tenant=t, key=rid % 64,
                                             value=[rid]))
                if rsp is not None:
                    assert rsp.status == wire.S_RETRY_AFTER
                    assert rsp.reason == wire.R_QUOTA
                    refused[t] += 1
        # in-flight per tenant can NEVER exceed the quota
        for t, row in fe.adm.counters().items():
            assert row["inflight"] <= quota
        fe.pump()
        clock.advance(0.001)
    assert fe.drain()
    ev = verify_serving(fe)  # admitted == resolved, inflight == 0, exact
    assert refused[0] > 0 and refused[1] > 0
    assert ev["requests"] == ev["responses"] == rid


def test_backpressure_queue_full_is_loud():
    kvs = KVS(_cfg())
    clock = VirtualClock()
    # store takes 1 op at a time; queue holds 4: the 6th+ must be refused
    fe = Frontend(kvs, _scfg(tenant_quota=1000, queue_cap=4,
                             store_inflight_cap=1), clock=clock)
    refusals = 0
    for i in range(20):
        rsp = fe.submit(wire.Request(kind="put", req_id=i + 1, tenant=0,
                                     key=i % 64, value=[i]))
        if rsp is not None:
            assert rsp.status == wire.S_RETRY_AFTER
            assert rsp.reason in (wire.R_QUEUE_FULL, wire.R_SHED_WRITE)
            assert rsp.retry_after_us > 0
            refusals += 1
    assert refusals >= 14  # nothing was silently buffered
    while not fe.drain(200):
        clock.advance(0.001)
    verify_serving(fe)
    assert fe.requests == fe.responses == 20


def test_deadline_enforced_at_completion_and_is_a_maybe():
    cfg = _cfg(op_timeout_rounds=0)
    kvs = KVS(cfg)
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(), clock=clock)
    kvs.rt.freeze(1)  # a frozen ack peer stalls every write
    assert fe.submit(wire.Request(kind="put", req_id=1, tenant=0, key=5,
                                  deadline_us=3000, value=[42])) is None
    rsps = []
    for _ in range(8):
        rsps += fe.pump()
        clock.advance(0.001)
    dl = [r for r in rsps if r.status == wire.S_DEADLINE]
    assert dl and dl[0].req_id == 1, rsps
    assert fe.adm.counters()[0]["deadline"] == 1
    assert fe._abandoned  # the store op is still open — a MAYBE
    kvs.rt.thaw(1)
    assert fe.drain()
    verify_serving(fe)


def test_deadline_enforced_at_intake_queue():
    kvs = KVS(_cfg())
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(store_inflight_cap=1, queue_cap=32),
                  clock=clock)
    kvs.rt.freeze(1)  # head op wedges the single store slot
    for i in range(5):
        assert fe.submit(wire.Request(kind="put", req_id=i + 1, tenant=0,
                                      key=i, deadline_us=2000,
                                      value=[i])) is None
    rsps = []
    for _ in range(6):
        rsps += fe.pump()
        clock.advance(0.001)
    intake_expired = [r for r in rsps if r.status == wire.S_DEADLINE
                      and r.req_id > 1]
    assert len(intake_expired) == 4  # expired IN the queue, never injected
    assert fe._lane_seq[0] == 1      # only the head was ever issued
    kvs.rt.thaw(1)
    assert fe.drain()
    verify_serving(fe)


# -- shed ladder -------------------------------------------------------------

def test_degraded_mode_sheds_writes_first_reads_serve():
    from hermes_tpu.obs import Observability

    cfg = _cfg(min_healthy_for_writes=3)
    kvs = KVS(cfg)
    obs = kvs.rt.attach_obs(Observability())
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(), clock=clock)
    kvs.rt.freeze(2)  # healthy 2 < floor 3 => degraded
    w = fe.submit(wire.Request(kind="put", req_id=1, tenant=0, key=3,
                               value=[1]))
    assert w is not None and w.reason == wire.R_SHED_WRITE
    r = fe.submit(wire.Request(kind="get", req_id=2, tenant=0, key=3))
    assert r is None  # reads still admitted at rung 1
    fe.pump()
    clock.advance(0.001)
    kvs.rt.thaw(2)
    assert fe.drain()
    names = [rec.get("name") for rec in obs.records
             if rec.get("kind") == "event"]
    assert "shed" in names and "shed_clear" in names
    verify_serving(fe)


def test_rung2_sheds_cold_reads_hot_keys_survive():
    kvs = KVS(_cfg())
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(queue_cap=10, shed_write_frac=0.3,
                             shed_read_frac=0.5, hot_keys=(1,),
                             store_inflight_cap=1), clock=clock)
    kvs.rt.freeze(1)  # wedge the store so the intake queue fills
    rid = 0
    for i in range(6):  # fill past shed_read_frac * 10 = 5
        rid += 1
        fe.submit(wire.Request(kind="get", req_id=rid, tenant=0,
                               key=10 + i))
    assert fe.shed_level == 2
    rid += 1
    cold = fe.submit(wire.Request(kind="get", req_id=rid, tenant=0, key=20))
    assert cold is not None and cold.reason == wire.R_SHED_READ
    rid += 1
    hot = fe.submit(wire.Request(kind="get", req_id=rid, tenant=0, key=1))
    assert hot is None  # the hot key keeps serving
    rid += 1
    wr = fe.submit(wire.Request(kind="put", req_id=rid, tenant=0, key=2,
                                value=[9]))
    assert wr is not None and wr.reason == wire.R_SHED_WRITE
    kvs.rt.thaw(1)
    assert fe.drain()
    verify_serving(fe)


# -- watchdog tags (satellite) ----------------------------------------------

def test_stuck_op_diag_carries_tenant_and_deadline_budget():
    cfg = _cfg(op_timeout_rounds=4)
    kvs = KVS(cfg, strict_timeouts=True)
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(), clock=clock)
    kvs.rt.freeze(1)
    assert fe.submit(wire.Request(kind="put", req_id=1, tenant=5, key=9,
                                  deadline_us=1_000_000,
                                  value=[1])) is None
    with pytest.raises(StuckOpError) as ei:
        for _ in range(12):
            fe.pump()
            clock.advance(0.001)
    diag = ei.value.diagnostics[0]
    assert diag["tenant"] == 5
    assert 0 < diag["deadline_left_us"] <= 1_000_000
    assert "tenant=5" in str(ei.value)
    assert "deadline_left_us=" in str(ei.value)


# -- fleet + misc ------------------------------------------------------------

def test_fleet_frontend_routes_and_checks():
    fcfg = FleetConfig(groups=2, base=_cfg(pipeline_depth=2))
    from hermes_tpu.fleet import Fleet, verify_fleet

    fleet = Fleet(fcfg, record="array")
    res = run_open_loop(fleet, _scfg(), MixSpec(tenants=3),
                        rate_per_s=4000.0, n=120, seed=11,
                        deadline_us=50_000)
    assert res["statuses"].get("ok", 0) > 0
    mix = make_mix(MixSpec(tenants=3), fcfg.total_keys, 120, 11,
                   value_words=4)
    gids, _ = fleet.router.locate(np.asarray(mix["key"], np.int64))
    assert set(np.asarray(gids).tolist()) == {0, 1}
    assert fleet.check()["ok"]
    verify_fleet(fleet)


def test_frontend_rejects_out_of_range_key_loudly():
    kvs = KVS(_cfg())
    fe = Frontend(kvs, _scfg(), clock=VirtualClock())
    rsp = fe.submit(wire.Request(kind="put", req_id=1, tenant=0,
                                 key=10_000, value=[1]))
    assert rsp is not None and rsp.status == wire.S_REJECTED
    verify_serving(fe)


def test_loopback_put_get_roundtrip_through_frames():
    kvs = KVS(_cfg(pipeline_depth=2))
    clock = VirtualClock()
    fe = Frontend(kvs, _scfg(), clock=clock)
    lb = LoopbackServer(fe)
    assert lb.submit(wire.Request(kind="put", req_id=1, tenant=0, key=7,
                                  value=[3, 1, 4])) is None
    got = {}
    for _ in range(40):
        for rsp in lb.pump():
            got[rsp.req_id] = rsp
        clock.advance(0.001)
        if 1 in got:
            break
    # the get is sequenced AFTER the put's response: it must see the value
    assert lb.submit(wire.Request(kind="get", req_id=2, tenant=0,
                                  key=7)) is None
    for _ in range(40):
        for rsp in lb.pump():
            got[rsp.req_id] = rsp
        clock.advance(0.001)
        if 2 in got:
            break
    assert got[1].status == wire.S_OK and got[1].uid is not None
    assert got[2].status == wire.S_OK and got[2].value[:3] == [3, 1, 4]
    assert lb.wire_rx > 0 and lb.wire_tx > 0


def test_open_loop_soak_replays_byte_identically():
    shas = []
    for _ in range(2):
        kvs = KVS(_cfg(pipeline_depth=2))
        res = run_open_loop(kvs, _scfg(tenant_quota=6, queue_cap=24),
                            MixSpec(tenants=3), rate_per_s=6000.0, n=150,
                            seed=17, deadline_us=9000)
        shas.append(res["response_log_sha"])
    assert shas[0] == shas[1]


def test_measure_capacity_resolves_everything():
    kvs = KVS(_cfg(pipeline_depth=2))
    cap = measure_capacity(kvs, _scfg(), MixSpec(tenants=2), n=80, seed=3)
    assert cap["ops_per_round"] > 0
    assert cap["ops"] >= 80


def test_verify_serving_red_on_lost_response():
    kvs = KVS(_cfg())
    fe = Frontend(kvs, _scfg(), clock=VirtualClock())
    fe.requests += 1  # a request that never got a response
    with pytest.raises(AssertionError, match="conservation"):
        verify_serving(fe)


def test_scenario_matrix_and_seed_anchor():
    seed = scenario_seed()
    assert isinstance(seed, int) and seed == scenario_seed()
    names = [s.name for s in scenario_matrix()]
    # round-16: the read-heavy YCSB cells joined the original three
    assert names == ["uniform", "zipfian", "hotkey",
                     "ycsb_b", "ycsb_c", "ycsb_d"]


def test_tcp_rpc_server_end_to_end():
    cfg = _cfg(pipeline_depth=2)
    kvs = KVS(cfg)
    fe = Frontend(kvs, _scfg())
    srv = TcpRpcServer(fe)
    try:
        cl = RpcClient(srv.addr, fe.u)
        put = cl.call("put", 9, value=[7, 7])
        assert put.status == wire.S_OK and put.uid is not None
        get = cl.call("get", 9)
        assert get.status == wire.S_OK and get.value[:2] == [7, 7]
        cl.close()
    finally:
        srv.close()


def test_tcp_rpc_req_id_collision_across_connections():
    # client req_ids are only unique PER CONNECTION: two clients both
    # numbering from 1 must not collide in the frontend's pending map or
    # steal each other's responses (the server re-mints internal ids)
    cfg = _cfg(pipeline_depth=2)
    kvs = KVS(cfg)
    fe = Frontend(kvs, _scfg())
    srv = TcpRpcServer(fe)
    try:
        a = RpcClient(srv.addr, fe.u)
        b = RpcClient(srv.addr, fe.u)
        assert a._next_id == b._next_id == 1
        pa = a.call("put", 3, value=[11, 0], tenant=1)
        pb = b.call("put", 4, value=[22, 0], tenant=2)
        assert pa.status == wire.S_OK and pb.status == wire.S_OK
        ga = a.call("get", 3, tenant=1)
        gb = b.call("get", 4, tenant=2)
        assert ga.value[:2] == [11, 0], "client A got someone else's answer"
        assert gb.value[:2] == [22, 0], "client B got someone else's answer"
        # the responses echo EACH CLIENT's own req_id numbering
        assert ga.req_id == gb.req_id == 2
        a.close()
        b.close()
    finally:
        srv.close()


def test_admission_refusal_does_not_charge_token_bucket():
    # a quota/queue refusal must not burn the tenant's rate budget: the
    # bucket is charged LAST, only on actual admission
    from hermes_tpu.serving.admission import AdmissionControl

    scfg = _scfg(tenant_quota=1, tenant_rate_per_s=10.0, tenant_burst=2.0,
                 hot_keys=(0,))
    adm = AdmissionControl(scfg)
    assert adm.admit("put", 0, 7, 0.0, 0, False)[0] == wire.R_NONE
    adm.note_admitted(7)
    for _ in range(5):  # quota-refused retries, bucket untouched
        assert adm.admit("put", 0, 7, 0.0, 0, False)[0] == wire.R_QUOTA
    assert adm.tenant(7).bucket.tokens == 1.0
    # queue-full refusals don't charge either (hot-key get: passes the
    # shed ladder at full queue, refused by the queue bound itself)
    for _ in range(3):
        reason, _w = adm.admit("get", 0, 8, 0.0, scfg.queue_cap, False)
        assert reason == wire.R_QUEUE_FULL
    assert adm.tenant(8).bucket.tokens == 2.0


def test_tcp_rpc_undecodable_request_refused_loudly():
    # a frame-valid request the server cannot decode (payload-width
    # mismatch) must come back S_REJECTED, never silence + client timeout
    import socket as socket_mod

    from hermes_tpu.transport.tcp import FramedSocket

    cfg = _cfg(pipeline_depth=2)
    kvs = KVS(cfg)
    fe = Frontend(kvs, _scfg())
    srv = TcpRpcServer(fe)
    try:
        fsock = FramedSocket(socket_mod.create_connection(srv.addr,
                                                          timeout=10.0))
        req = wire.Request(kind="put", req_id=77, tenant=0, key=1,
                           value=[5])
        fsock.send(wire.encode_request(req, fe.u + 3))  # wrong width
        raw = fsock.recv()
        rsp = wire.decode_response(raw, fe.u)
        assert rsp.status == wire.S_REJECTED and rsp.req_id == 77
        assert srv.undecodable == 1
        fsock.close()
    finally:
        srv.close()


def test_run_gates_records_timed_out(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import run_gates
    finally:
        sys.path.pop(0)
    scripts = tmp_path / "scripts"
    scripts.mkdir()
    (scripts / "wedge.py").write_text(
        "import subprocess, sys, time\n"
        # a grandchild too: the process-group kill must take it down
        "subprocess.Popen([sys.executable, '-c', 'import time; "
        "time.sleep(60)'])\n"
        "time.sleep(60)\n")
    old_repo = run_gates.REPO
    run_gates.REPO = str(tmp_path)
    try:
        r = run_gates.run_gate("wedge", "wedge.py", timeout=2,
                               flight_dir=str(tmp_path / "flight"))
    finally:
        run_gates.REPO = old_repo
    assert r["timed_out"] is True and r["ok"] is False
    assert r["seconds"] < 30
    assert "serving" in [g[0] for g in run_gates.GATES]
