"""Round-20 host concurrency analyzer: the guard registry schema, the
static AST lint (hostlint.py), the dynamic lock-order sanitizer
(lockgraph.ObsLock), and the eleventh gate's pass/fail/--update flow.

The red tests here are the analyzer's own teeth-check: a lint that
stops firing on a known-bad snippet is a broken gate, not a clean
codebase (the same contract the jaxpr analyzer's red tests enforce).
"""

import importlib.util
import json
import os
import pathlib
import threading
import time

import pytest

from hermes_tpu import analysis as ana
from hermes_tpu import concurrency as conc
from hermes_tpu.analysis import hostlint, lockgraph
from hermes_tpu.analysis.passes import ERROR, INFO, WARN, Finding

REPO = pathlib.Path(__file__).resolve().parent.parent


def _by_code(findings, code):
    return [f for f in findings if f.code == code]


def _gating(findings):
    return [f for f in findings if f.severity in (ERROR, WARN)]


# --- registry schema ---------------------------------------------------------


class TestRegistry:
    def test_shipped_registry_validates(self):
        conc.validate()  # also runs at import; explicit here

    def test_by_class_covers_every_entry(self):
        table = conc.by_class()
        assert len(table) == len(conc.REGISTRY)
        assert table[("hermes_tpu.serving.rpc", "TcpRpcServer")].locks == (
            "_lock", "_map_lock")

    def test_guard_must_name_declared_lock(self):
        bad = (conc.ClassGuards(
            cls="C", module="m", locks=("_a",),
            guards=(conc.Guard("_b", ("x",)),)),)
        with pytest.raises(ValueError, match="not in the entry's declared"):
            conc.validate(bad)

    def test_attr_guarded_xor_audited(self):
        bad = (conc.ClassGuards(
            cls="C", module="m", locks=("_a",),
            guards=(conc.Guard("_a", ("x",)),),
            audited=(conc.audited("why", "x"),)),)
        with pytest.raises(ValueError, match="declared twice"):
            conc.validate(bad)

    def test_duplicate_entry_rejected(self):
        e = conc.ClassGuards(cls="C", module="m")
        with pytest.raises(ValueError, match="duplicate"):
            conc.validate((e, e))

    def test_audit_tag_contract(self):
        with pytest.raises(ValueError):
            conc.audited("", "x")
        with pytest.raises(ValueError):
            conc.audited("bad [tag]", "x")
        with pytest.raises(ValueError):
            conc.audited("tag-only")
        au = conc.audited("ok", "x", "y")
        assert au.attrs == ("x", "y") and au.tag == "ok"

    def test_make_lock_obeys_env_switch(self, monkeypatch):
        monkeypatch.delenv(conc.LOCKLINT_ENV, raising=False)
        lk = conc.make_lock("T.plain")
        assert not isinstance(lk, lockgraph.ObsLock)
        monkeypatch.setenv(conc.LOCKLINT_ENV, "1")
        lk = conc.make_lock("T.obs")
        assert isinstance(lk, lockgraph.ObsLock) and lk.name == "T.obs"
        monkeypatch.setenv(conc.LOCKLINT_ENV, "0")
        assert not isinstance(conc.make_lock("T.off"), lockgraph.ObsLock)


# --- the static pass ---------------------------------------------------------

# a minimal registry for synthetic snippets: one guarded attr, one
# audited attr, one sanctioned blocking site
BOX = conc.ClassGuards(
    cls="Box", module="m", locks=("_lk", "_lk2"),
    guards=(conc.Guard("_lk", ("items",)),),
    audited=(conc.audited("test-lockfree", "hits"),),
    blocking=(conc.BlockingAudit("_lk", "sendall", "test-sanctioned"),))
WILD = conc.ClassGuards(
    cls="Wild", module="m",
    audited=(conc.audited("single-threaded-by-contract", "*"),))
OWNED = conc.ClassGuards(
    cls="Owned", module="m", thread_owner="_threads",
    audited=(conc.audited("test", "*"),))
REG = (BOX, WILD, OWNED)


def lint(src, module="m"):
    return hostlint.lint_source(src, module=module, registry=REG)


class TestStaticLint:
    def test_guarded_write_outside_lock_is_error(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        self.items.append(1)\n")
        hit = _by_code(fs, "guarded-attr-unlocked")
        assert len(hit) == 1
        assert hit[0].severity == ERROR and hit[0].op == "items"
        assert "Box._lk" in hit[0].message

    def test_guarded_read_outside_lock_is_error(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        return len(self.items)\n")
        assert _by_code(fs, "guarded-attr-unlocked")

    def test_access_under_the_right_lock_is_clean(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        with self._lk:\n"
                  "            self.items.append(1)\n")
        assert not _gating(fs)

    def test_wrong_lock_does_not_satisfy_the_guard(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        with self._lk2:\n"
                  "            self.items.append(1)\n")
        assert _by_code(fs, "guarded-attr-unlocked")

    def test_init_is_exempt(self):
        fs = lint("class Box:\n"
                  "    def __init__(self):\n"
                  "        self.items = []\n")
        assert not _gating(fs)

    def test_except_handler_keeps_lock_context(self):
        # regression: ast.ExceptHandler is not an ast.stmt; a walker that
        # flattens handler bodies into expression scanning loses the
        # surrounding with-block and false-positives the error path
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        try:\n"
                  "            pass\n"
                  "        except Exception:\n"
                  "            with self._lk:\n"
                  "                self.items.append(1)\n"
                  "        with self._lk:\n"
                  "            try:\n"
                  "                pass\n"
                  "            except Exception:\n"
                  "                self.items.clear()\n")
        assert not _gating(fs)

    def test_nested_def_loses_the_lexical_lock(self):
        # a nested def runs later, possibly unlocked: accesses inside it
        # must NOT inherit the enclosing with
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        with self._lk:\n"
                  "            def cb():\n"
                  "                self.items.append(1)\n"
                  "            return cb\n")
        assert _by_code(fs, "guarded-attr-unlocked")

    def test_audited_attr_is_info_with_tag(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        self.hits += 1\n")
        assert not _gating(fs)
        hit = _by_code(fs, "host-audited")
        assert hit and hit[0].severity == INFO
        assert hit[0].audit == "test-lockfree"

    def test_blocking_under_lock_is_error(self):
        fs = lint("class Box:\n"
                  "    def f(self, sock):\n"
                  "        with self._lk:\n"
                  "            sock.recv(4)\n")
        hit = _by_code(fs, "blocking-under-lock")
        assert hit and hit[0].severity == ERROR and hit[0].op == "recv"

    def test_blocking_audit_downgrades_to_info(self):
        fs = lint("class Box:\n"
                  "    def f(self, sock):\n"
                  "        with self._lk:\n"
                  "            sock.sendall(b'x')\n")
        assert not _by_code(fs, "blocking-under-lock")
        hit = _by_code(fs, "blocking-under-lock-audited")
        assert hit and hit[0].severity == INFO
        assert hit[0].audit == "test-sanctioned"

    def test_blocking_audit_is_lock_specific(self):
        # the sanction names _lk; the same call under _lk2 stays an error
        fs = lint("class Box:\n"
                  "    def f(self, sock):\n"
                  "        with self._lk2:\n"
                  "            sock.sendall(b'x')\n")
        assert _by_code(fs, "blocking-under-lock")

    def test_static_order_cycle_in_methods(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        with self._lk:\n"
                  "            with self._lk2:\n"
                  "                pass\n"
                  "    def g(self):\n"
                  "        with self._lk2:\n"
                  "            with self._lk:\n"
                  "                pass\n")
        hit = _by_code(fs, "lock-order-cycle")
        assert len(hit) == 1 and hit[0].severity == ERROR
        assert "Box._lk" in hit[0].op and "Box._lk2" in hit[0].op

    def test_static_order_cycle_in_module_functions(self):
        fs = lint("def f():\n"
                  "    with a_lock:\n"
                  "        with b_lock:\n"
                  "            pass\n"
                  "def g():\n"
                  "    with b_lock:\n"
                  "        with a_lock:\n"
                  "            pass\n")
        hit = _by_code(fs, "lock-order-cycle")
        assert len(hit) == 1
        assert "acquisition sites" in hit[0].message

    def test_consistent_order_is_clean(self):
        fs = lint("def f():\n"
                  "    with a_lock:\n"
                  "        with b_lock:\n"
                  "            pass\n"
                  "def g():\n"
                  "    with a_lock:\n"
                  "        with b_lock:\n"
                  "            pass\n")
        assert not _by_code(fs, "lock-order-cycle")

    def test_unregistered_lock_class_warns(self):
        fs = lint("import threading\n"
                  "class Rogue:\n"
                  "    def setup(self):\n"
                  "        self._lock = threading.Lock()\n")
        hit = _by_code(fs, "unregistered-lock-class")
        assert hit and hit[0].severity == WARN and hit[0].op == "_lock"

    def test_undeclared_lock_on_registered_class_warns(self):
        fs = lint("import threading\n"
                  "class Box:\n"
                  "    def setup(self):\n"
                  "        self._extra_lock = threading.Lock()\n")
        hit = _by_code(fs, "undeclared-lock")
        assert hit and hit[0].op == "_extra_lock"

    def test_thread_without_owner_warns(self):
        fs = lint("import threading\n"
                  "class Box:\n"
                  "    def go(self):\n"
                  "        threading.Thread(target=self.go).start()\n")
        hit = _by_code(fs, "daemon-thread-unowned")
        assert hit and hit[0].severity == WARN

    def test_thread_with_owner_and_closer_is_clean(self):
        fs = lint("import threading\n"
                  "class Owned:\n"
                  "    def go(self):\n"
                  "        t = threading.Thread(target=self.go)\n"
                  "        self._threads.append(t)\n"
                  "        t.start()\n"
                  "    def close(self):\n"
                  "        pass\n")
        assert not _by_code(fs, "daemon-thread-unowned")

    def test_module_function_thread_must_join(self):
        warn = lint("import threading\n"
                    "def fire():\n"
                    "    threading.Thread(target=print).start()\n")
        assert _by_code(warn, "daemon-thread-unowned")
        clean = lint("import threading\n"
                     "def fire():\n"
                     "    t = threading.Thread(target=print)\n"
                     "    t.start()\n"
                     "    t.join()\n")
        assert not _by_code(clean, "daemon-thread-unowned")

    def test_wildcard_audit_aggregates_one_info(self):
        fs = lint("class Wild:\n"
                  "    def f(self):\n"
                  "        self.a = 1\n"
                  "        self.b.append(2)\n")
        assert not _gating(fs)
        hit = [f for f in _by_code(fs, "host-audited") if f.op == "*"]
        assert len(hit) == 1 and hit[0].count == 2
        assert "a" in hit[0].message and "b" in hit[0].message

    def test_undeclared_mutable_attr_warns(self):
        fs = lint("class Box:\n"
                  "    def f(self):\n"
                  "        self.stray = 1\n")
        hit = _by_code(fs, "undeclared-mutable-attr")
        assert hit and hit[0].severity == WARN and hit[0].op == "stray"


# --- the whole package proves clean ------------------------------------------


class TestPackage:
    def test_package_has_zero_gating_findings(self):
        # the empty-baseline invariant the eleventh gate enforces: every
        # real violation gets a fix or a declared audit, never a
        # grandfather entry (HOSTLINT_BASELINE.json ships empty)
        report = hostlint.lint_package()
        gating = ana.key_counts(report["findings"])
        assert gating == {}, f"host tier regressed: {sorted(gating)}"
        assert report["proved"]["registered"] == len(conc.REGISTRY)
        assert report["proved"]["files"] > 50
        assert report["proved"]["with_sites"] > 10

    def test_shipped_baseline_is_empty(self):
        doc = json.loads((REPO / "HOSTLINT_BASELINE.json").read_text())
        assert doc["grandfathered"] == {}

    def test_stale_registry_entry_warns(self):
        ghost = conc.ClassGuards(cls="Ghost", module="hermes_tpu.nowhere")
        report = hostlint.lint_package(registry=conc.REGISTRY + (ghost,))
        hit = _by_code(report["findings"], "registry-stale-entry")
        assert [f.fn for f in hit] == ["Ghost"]


# --- the dynamic sanitizer ---------------------------------------------------


class TestObsLock:
    def test_reentrant_acquire_no_self_edge(self):
        g = lockgraph.LockGraph()
        lk = lockgraph.ObsLock("t.re", g)
        with lk:
            with lk:   # RLock semantics: a drop-in must allow this
                pass
        rep = g.report()
        assert rep["locks"]["t.re"]["acquires"] == 1
        assert rep["n_edges"] == 0 and not rep["cycles"]
        # one hold sample, spanning outermost acquire -> last release
        assert g.hold_p99_us("t.re") is not None

    def test_context_manager_exactness(self):
        g = lockgraph.LockGraph()
        lk = lockgraph.ObsLock("t.cm", g)
        grabbed = []

        def try_grab():
            got = lk.acquire(blocking=False)
            grabbed.append(got)
            if got:
                lk.release()

        with lk:
            t = threading.Thread(target=try_grab)
            t.start()
            t.join()
        t = threading.Thread(target=try_grab)
        t.start()
        t.join()
        assert grabbed == [False, True]  # held inside the with, free after

    def test_release_unheld_raises(self):
        lk = lockgraph.ObsLock("t.bad", lockgraph.LockGraph())
        with pytest.raises(RuntimeError):
            lk.release()

    def test_contention_is_counted(self):
        g = lockgraph.LockGraph()
        lk = lockgraph.ObsLock("t.cont", g)
        inside = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                inside.set()
                release.wait(timeout=5)

        t = threading.Thread(target=holder)
        t.start()
        assert inside.wait(timeout=5)
        t2 = threading.Thread(target=lambda: (lk.acquire(), lk.release()))
        t2.start()
        time.sleep(0.02)
        release.set()
        t.join()
        t2.join()
        rep = g.report()
        st = rep["locks"]["t.cont"]
        assert st["acquires"] == 2 and st["contended"] >= 1

    def test_cycle_finding_carries_both_stacks(self):
        g = lockgraph.LockGraph()
        a = lockgraph.ObsLock("t.A", g)
        b = lockgraph.ObsLock("t.B", g)

        def fwd():
            with a:
                with b:
                    pass

        def rev():
            with b:
                with a:
                    pass

        for fn in (fwd, rev):   # sequential: no real deadlock risk
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        cycles = g.cycles()
        assert len(cycles) == 1 and sorted(cycles[0]) == ["t.A", "t.B"]
        (f,) = g.findings()
        assert f.code == "lock-order-cycle" and f.severity == ERROR
        assert "held at" in f.message and "acquired at" in f.message
        # the evidence names the functions that took the locks
        assert "fwd" in f.message and "rev" in f.message

    def test_registry_feed_uses_lock_prefix(self):
        from hermes_tpu.obs.metrics import MetricsRegistry

        g = lockgraph.LockGraph()
        reg = MetricsRegistry()
        g.attach_registry(reg)
        lk = lockgraph.ObsLock("t.feed", g)
        for _ in range(3):
            with lk:
                pass
        names = reg.names()
        assert "lock_hold_us:t.feed" in names
        assert "lock_acquires:t.feed" in names
        assert all(n.startswith(lockgraph.LOCK_METRIC_PREFIX)
                   for n in names)
        snap = reg.series("lock_hold_us:t.feed").snapshot()
        assert snap["x"] == sorted(snap["x"]) and len(snap["v"]) == 3

    def test_reset_global_retargets_default_locks(self):
        lk = lockgraph.ObsLock("t.global")  # no explicit graph
        try:
            with lk:
                pass
            old = lockgraph.global_graph()
            assert "t.global" in old.report()["locks"]
            fresh = lockgraph.reset_global()
            with lk:   # follows the swap: lands in the NEW graph
                pass
            assert "t.global" in fresh.report()["locks"]
            assert fresh.report()["locks"]["t.global"]["acquires"] == 1
        finally:
            lockgraph.reset_global()


# --- the eleventh gate -------------------------------------------------------


@pytest.fixture()
def gate():
    """scripts/check_hostlint.py loaded as a module (its import sets
    HERMES_LOCKLINT=1 for the soak leg; restore the env afterwards)."""
    saved = os.environ.get("HERMES_LOCKLINT")
    spec = importlib.util.spec_from_file_location(
        "check_hostlint_under_test",
        REPO / "scripts" / "check_hostlint.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    yield mod
    if saved is None:
        os.environ.pop("HERMES_LOCKLINT", None)
    else:
        os.environ["HERMES_LOCKLINT"] = saved


def _run_gate(gate, capsys, *argv):
    rc = gate.main(list(argv))
    out = capsys.readouterr().out
    return rc, json.loads(out.strip().splitlines()[-1])


def _empty_baseline(tmp_path):
    p = tmp_path / "BASE.json"
    p.write_text(json.dumps({"_doc": "test", "grandfathered": {}}))
    return str(p)


INJECTED = Finding(
    pass_name="hostlint", code="guarded-attr-unlocked", severity=ERROR,
    message="injected for the gate red test", file="hermes_tpu/x.py",
    fn="X.f", op="boom", engine="host")


def _fake_report():
    return dict(engine="host", n_eqns=1,
                proved=dict(files=1, classes=1, registered=0,
                            with_sites=0, lock_edges=0, threads=0),
                findings=[INJECTED])


class TestGate:
    def test_gate_passes_on_clean_tree(self, gate, capsys, tmp_path):
        rc, rep = _run_gate(gate, capsys, "--static-only",
                            "--baseline", _empty_baseline(tmp_path))
        assert rc == 0 and rep["ok"]
        assert rep["errors"] == 0 and rep["warnings"] == 0
        assert rep["new_findings"] == [] and rep["stale_baseline"] == []
        assert rep["legs"]["red_static"]["guarded_flip"]
        assert rep["legs"]["red_static"]["order_flip"]

    def test_gate_fails_on_new_finding_and_update_clears(
            self, gate, capsys, tmp_path, monkeypatch):
        base = _empty_baseline(tmp_path)
        monkeypatch.setattr(hostlint, "lint_package",
                            lambda *a, **kw: _fake_report())
        rc, rep = _run_gate(gate, capsys, "--static-only",
                            "--baseline", base)
        assert rc == 1 and not rep["ok"]
        assert rep["new_findings"] == [INJECTED.key]
        # --update grandfathers it (a consciously-staged transition) and
        # the written table carries the key + message note
        rc, rep = _run_gate(gate, capsys, "--static-only",
                            "--baseline", base, "--update")
        assert rc == 0 and rep["new_findings"] == []
        doc = json.loads(pathlib.Path(base).read_text())
        assert doc["grandfathered"][INJECTED.key]["count"] == 1
        assert "injected" in doc["grandfathered"][INJECTED.key]["note"]

    def test_gate_reports_stale_baseline_without_failing(
            self, gate, capsys, tmp_path):
        p = tmp_path / "BASE.json"
        p.write_text(json.dumps({"grandfathered": {
            "host|hostlint|gone|x.py|X.f|attr": {"count": 2,
                                                 "note": "fixed"}}}))
        rc, rep = _run_gate(gate, capsys, "--static-only",
                            "--baseline", str(p))
        assert rc == 0, "stale entries report, they don't fail"
        assert rep["stale_baseline"] == ["host|hostlint|gone|x.py|X.f|attr"]

    def test_gate_exports_findings_jsonl(self, gate, capsys, tmp_path):
        out = tmp_path / "host.jsonl"
        rc, _rep = _run_gate(gate, capsys, "--static-only",
                             "--baseline", _empty_baseline(tmp_path),
                             "--out", str(out))
        assert rc == 0
        recs = [json.loads(ln) for ln in out.read_text().splitlines()]
        assert any(r.get("record") == "program" and r.get("engine") == "host"
                   for r in recs)
        assert all(r.get("config") == "host" for r in recs)

    def test_red_dynamic_leg(self, gate):
        leg = gate.leg_red_dynamic(lockgraph)
        assert leg["ok"] and leg["n_findings"] == 1


# --- regressions from the round-20 audit -------------------------------------


class TestAuditRegressions:
    def test_metrics_registry_survives_concurrent_insert(self):
        # pre-fix, _metrics was an unlocked dict: a snapshot() racing
        # an inserter thread raised "dictionary changed size during
        # iteration"
        from hermes_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        stop = threading.Event()
        errs = []

        def insert():
            i = 0
            while not stop.is_set():
                reg.counter(f"c{i}").inc()
                i += 1

        def snap():
            try:
                while not stop.is_set():
                    reg.snapshot()
                    reg.names()
            except Exception as e:  # noqa: BLE001 — the regression
                errs.append(e)

        threads = [threading.Thread(target=insert),
                   threading.Thread(target=snap)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join()
        assert not errs, f"registry raced: {errs!r}"

    def test_tcp_server_registers_threads_before_start(self):
        # pre-fix, __init__ did start-then-append: the accept loop's
        # prune (under _map_lock) could run before the pump thread's
        # registration landed, leaving close() unable to join it
        from hermes_tpu.serving.rpc import TcpRpcServer

        class FakeFrontend:
            u, vbytes = 4, 0
            _intake, _pending, _abandoned = (), {}, {}

        srv = TcpRpcServer(FakeFrontend())
        try:
            assert len(srv._threads) == 2
            assert all(t.is_alive() for t in srv._threads)
        finally:
            srv.close()
        assert all(not t.is_alive() for t in srv._threads)

    def test_obs_overhead_gate_forces_locklint_off(self, monkeypatch):
        # satellite (f): the overhead gate must never measure the lock
        # sanitizer's own series in its traced leg — loading the script
        # forces the env switch OFF no matter what the caller exported
        monkeypatch.setenv("HERMES_LOCKLINT", "1")
        spec = importlib.util.spec_from_file_location(
            "check_obs_overhead_under_test",
            REPO / "scripts" / "check_obs_overhead.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert os.environ["HERMES_LOCKLINT"] == "0"
        assert "lock_" == lockgraph.LOCK_METRIC_PREFIX

    def test_cli_locklint_summary_gates_on_cycles(self):
        # satellite (e): the --locklint flag's helper appends the graph
        # report to the run summary and fails the run on any cycle
        from hermes_tpu import cli

        try:
            g = lockgraph.reset_global()
            a = lockgraph.ObsLock("cli.A")
            b = lockgraph.ObsLock("cli.B")
            with a:
                with b:
                    pass
            clean = {}
            assert cli._append_locklint(clean) is True
            assert clean["locklint"]["n_edges"] == 1
            with b:
                with a:
                    pass
            dirty = {}
            assert cli._append_locklint(dirty) is False
            assert dirty["locklint"]["cycles"]
            assert g is lockgraph.global_graph()
        finally:
            lockgraph.reset_global()
