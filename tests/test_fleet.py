"""Round-13 fleet: key-sharded protocol groups, routed sessions, fleet
gating (hermes_tpu/fleet).

Covers the fleet routing edges (boundary-exact ownership at range lo and
hi-1, batches spanning >= 3 groups with completion-order and totals
conservation, rejected ops on a draining fleet range, deterministic
replay of a fleet-wide seeded chaos schedule), the cross-group migration
smoke (through the fleet router flip, dest-group ``_ver_base`` re-anchor
asserted), and the per-group isolation contracts (a fault schedule
targeting group 0 never fences a group 1 replica; ``healthy_replicas()``
and the membership service are group-scoped).
"""

import numpy as np
import pytest

from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig


def _base(**over):
    kw = dict(n_replicas=3, n_keys=32, n_sessions=4, replay_slots=4,
              ops_per_session=64, value_words=6, replay_scan_every=4,
              rebroadcast_every=2, lease_steps=4,
              workload=WorkloadConfig(read_frac=0.4, seed=3))
    kw.update(over)
    return HermesConfig(**kw)


def _fleet(groups=3, record=True, detect=None, **over):
    from hermes_tpu.fleet import Fleet

    return Fleet(FleetConfig(groups=groups, base=_base(**over)),
                 record=record, detect=detect)


# -- config + router ---------------------------------------------------------


def test_fleet_config_validation():
    FleetConfig(groups=2, base=_base())  # default even split
    with pytest.raises(ValueError, match="tile the fleet keyspace"):
        FleetConfig(groups=2, base=_base(), ranges=((0, 16), (17, 32)))
    with pytest.raises(ValueError, match="dense table holds"):
        FleetConfig(groups=2, base=_base(), ranges=((0, 40), (40, 80)))
    with pytest.raises(ValueError, match="one entry per group"):
        FleetConfig(groups=2, base=_base(), overrides=({},))
    f = FleetConfig(groups=2, base=_base(),
                    overrides=({"n_sessions": 8}, None))
    assert f.group_cfg(0).n_sessions == 8
    assert f.group_cfg(1).n_sessions == 4
    # vary_seed: per-group streams are distinct but deterministic
    assert f.group_cfg(1).workload.seed == f.base.workload.seed + 1


def test_router_boundary_exact_ownership():
    from hermes_tpu.fleet import FleetRouter

    r = FleetRouter.from_config(FleetConfig(groups=3, base=_base()))
    assert r.owned_ranges() == [(0, 32, 0), (32, 64, 1), (64, 96, 2)]
    for g, (lo, hi) in enumerate(((0, 32), (32, 64), (64, 96))):
        assert r.owner(lo) == g          # lo is IN the range
        assert r.owner(hi - 1) == g      # hi-1 is the last key in
        if lo > 0:
            assert r.owner(lo - 1) == g - 1
        if hi < 96:
            assert r.owner(hi) == g + 1
        assert r.locate(lo) == (g, 0)
        assert r.locate(hi - 1) == (g, hi - 1 - lo)
    with pytest.raises(ValueError, match="outside"):
        r.owner(96)
    with pytest.raises(ValueError, match="outside"):
        r.owner(-1)


def test_router_flip_needs_dest_slots_and_updates_local():
    from hermes_tpu.fleet import FleetRouter

    # group 0 owns 24 fleet keys on a 32-slot table: slots 24+ are spare,
    # so a flip into them keeps the (group, slot) map injective
    r = FleetRouter(64, [(0, 24), (24, 64)])
    r.begin_drain(40, 44)
    assert r.draining(40) and r.draining(43) and not r.draining(44)
    with pytest.raises(ValueError, match="dest_slots"):
        r.flip(40, 44, 0)
    with pytest.raises(ValueError, match="every key"):
        r.flip(40, 44, 0, dest_slots=[1, 2])
    r.flip(40, 44, 0, dest_slots=[28, 29, 30, 31])
    assert r.locate(41) == (0, 29)
    assert not r.draining(41)
    r.check_injective()


def test_router_injectivity_detects_aliasing():
    from hermes_tpu.fleet import FleetRouter

    r = FleetRouter.from_config(FleetConfig(groups=2, base=_base()))
    r.begin_drain(40, 41)
    r.flip(40, 41, 0, dest_slots=[7])  # fleet key 40 -> group 0 slot 7
    with pytest.raises(AssertionError, match="alias"):
        r.check_injective()  # fleet key 7 also maps to group 0 slot 7


# -- routed sessions + batch fan-out ----------------------------------------


def test_fleet_routed_sessions_roundtrip(fleet3):
    f = fleet3
    keys = [1, 31, 32, 63, 64, 95]  # both boundary keys of every group
    futs = [f.put(i, k, [k, 9]) for i, k in enumerate(keys)]
    assert f.run_until(futs)
    assert all(x.result().kind == "put" for x in futs)
    gets = [f.get(i, k) for i, k in enumerate(keys)]
    assert f.run_until(gets)
    for k, g in zip(keys, gets):
        assert g.result().value[:2] == [k, 9]
        assert g.result().key == k  # completions echo the FLEET key


def test_fleet_batch_spans_groups_totals_conserved(fleet3):
    f = fleet3
    n = 24
    rng = np.random.default_rng(7)
    keys = rng.permutation(np.arange(96))[:n].astype(np.int64)
    kinds = np.where(np.arange(n) % 3 == 0, f.GET, f.PUT).astype(np.int32)
    vals = np.arange(n, dtype=np.int32)[:, None] * np.ones((1, 4), np.int32)
    fb = f.submit_batch(kinds, keys, vals)
    spanned = {int(g) for g in fb.group}
    assert len(spanned) >= 3, "mix must span >= 3 groups"
    assert f.run_batch(fb)
    # totals conservation: every op resolved exactly once, across exactly
    # the owning groups' sub-batches
    assert fb.done_count() == n
    assert sum(len(bf) for _g, bf, _gix in fb._subs) == n
    for g, bf, gix in fb._subs:
        assert bf.all_done()
        # completion order: a group's share preserves FLEET submission
        # order (sub index i is fleet op gix[i], gix strictly increasing)
        assert (np.diff(gix) > 0).all()
        # ... and routing was by key: every op in this share is owned here
        assert (np.asarray(f.router.owner(keys[gix])) == g).all()
    # per-kind conservation
    for kind, code in ((f.GET, 1), (f.PUT, 2)):
        want = int((kinds == kind).sum())
        from hermes_tpu.core import types as t

        c = t.C_READ if kind == f.GET else t.C_WRITE
        assert int((fb.code[kinds == kind] == c).sum()) == want


def test_fleet_draining_range_rejects(fleet3):
    f = fleet3
    before = f.rejected_ops
    f.router.begin_drain(32, 48)  # half of group 1's range
    fut = f.put(0, 40, [1])
    assert fut.done() and fut.result().kind == "rejected"
    ok = f.put(0, 50, [1])  # the other half still serves
    kinds = np.full(6, f.PUT, np.int32)
    keys = np.array([33, 40, 47, 48, 2, 70], np.int64)
    fb = f.submit_batch(kinds, keys, np.ones((6, 1), np.int32))
    from hermes_tpu.kvs import C_REJECTED

    assert (fb.code[:3] == C_REJECTED).all()   # draining: 33, 40, 47
    assert (fb.group[:3] == -1).all()
    assert f.run_batch(fb) and f.run_until([ok])
    assert ok.result().kind == "put"
    assert fb.completion(3).kind == "put"      # 48 is OUTSIDE the drain
    assert f.rejected_ops == before + 4
    f.router.release(32, 48)
    again = f.put(0, 40, [2])
    assert f.run_until([again]) and again.result().kind == "put"


# -- cross-group migration (through the fleet router flip) -------------------


def test_fleet_migration_smoke():
    from hermes_tpu.fleet import Fleet, verify_fleet

    # groups sized past their ranges (n_keys 48, ranges 32): the spare 16
    # slots are the destination capacity cross-group migration lands in
    f = Fleet(FleetConfig(groups=2, base=_base(n_keys=48),
                          ranges=((0, 32), (32, 64))), record=True)
    # two writes per key so versions reach 2, then a source rebase so the
    # source carries nonzero _ver_base deltas the migration must re-anchor
    futs = [f.put(i % 4, k, [k, r]) for r in range(2) for i, k in
            enumerate(range(34, 40))]
    assert f.run_until(futs)
    src_rt = f.groups[1].rt
    assert src_rt.rebase_versions() > 0
    deltas = src_rt._ver_base.copy()
    s = f.migrate(34, 40, dst_group=0)
    assert s["src_group"] == 1 and s["dst_group"] == 0
    # ownership flipped atomically, boundary-exact
    assert f.router.owner(34) == 0 and f.router.owner(39) == 0
    assert f.router.owner(33) == 1 and f.router.owner(40) == 1
    assert not f.router.draining(np.arange(34, 40)).any()
    # dest slots came from group 0's SPARE capacity (its own keys 0..31
    # keep their slots; nothing aliases)
    assert (np.asarray(s["dest_slots"]) >= 32).all()
    # dest-group _ver_base re-anchor: the destination adopted the
    # source's cumulative per-key deltas for the migrated slots
    dst_rt = f.groups[0].rt
    src_local = np.arange(34 - 32, 40 - 32)
    assert dst_rt._ver_base is not None
    np.testing.assert_array_equal(dst_rt._ver_base[s["dest_slots"]],
                                  deltas[src_local])
    # post-flip service: reads route to the destination and see the values
    gets = [f.get(0, k) for k in range(34, 40)]
    assert f.run_until(gets)
    assert [g.result().value[:2] for g in gets] == [[k, 1] for k in
                                                    range(34, 40)]
    v = f.check()
    assert v["ok"], v
    ev = verify_fleet(f)
    assert ev["migration_uids"] == 6


def test_fleet_migration_refused_without_capacity():
    f = _fleet(groups=2, record=False)  # ranges == n_keys: zero spare
    with pytest.raises(ValueError, match="spare slot"):
        f.migrate(32, 40, dst_group=0)
    # refusal happened BEFORE the fence: the range still serves
    fut = f.put(0, 33, [1])
    assert f.run_until([fut]) and fut.result().kind == "put"


def test_migrate_range_dest_slots_validation():
    from hermes_tpu.elastic import migrate_range
    from hermes_tpu.kvs import KVS

    cfg = _base()
    src, dst = KVS(cfg, record=False), KVS(cfg, record=False)
    with pytest.raises(ValueError, match="every slot"):
        migrate_range(src, dst, 0, 4, dest_slots=[1, 2])
    with pytest.raises(ValueError, match="distinct"):
        migrate_range(src, dst, 0, 4, dest_slots=[1, 1, 2, 3])
    with pytest.raises(ValueError, match="slot space"):
        migrate_range(src, dst, 0, 4, dest_slots=[1, 2, 3, 99])
    sp = KVS(cfg, record=False, sparse_keys=True)
    sp2 = KVS(cfg, record=False, sparse_keys=True)
    with pytest.raises(ValueError, match="dense-mode"):
        migrate_range(sp, sp2, 0, 1, dest_slots=[0])


# -- per-group isolation (the round-13 fix + red tests) ----------------------


def test_chaos_on_group0_never_fences_group1():
    """The red isolation test: a fault schedule targeting group 0 (freeze,
    crash-restart, detector ejection) must never fence a group 1 replica
    — there is no shared live mask, frozen set, or detector to leak
    through."""
    from hermes_tpu import chaos
    from hermes_tpu.fleet import FleetChaosRunner

    f = _fleet(groups=2, record=False, detect=1)
    sched0 = chaos.Schedule.parse(
        "@2 freeze 1\n@6 crash_restart 2\n@14 thaw 1\n")
    runner = FleetChaosRunner(
        f, [sched0, chaos.Schedule([])],
        spec=chaos.ChaosSpec(min_healthy=1))
    g1 = f.groups[1].rt
    touched = []
    runner.on_step = lambda s: touched.append(
        g1.frozen.any() or int(g1.live[0]) != g1.cfg.full_mask)
    res = runner.run(20, heal=True)
    applied = [e["kind"] for e in runner.runners[0].log]
    assert "freeze" in applied and "crash_restart" in applied
    assert not any(touched), "a group-0 fault fenced a group-1 replica"
    assert not runner.runners[1].log  # the empty schedule applied nothing
    assert g1.healthy_replicas() == list(range(g1.cfg.n_replicas))


def test_membership_and_healthy_set_group_scoped():
    f = _fleet(groups=2, record=False, detect=0)
    g0, g1 = f.groups[0].rt, f.groups[1].rt
    # distinct service instances, group-labeled
    assert g0.membership is not g1.membership
    assert (g0.membership.group, g1.membership.group) == (0, 1)
    g0.freeze(1)
    assert g0.healthy_replicas() == [0, 2]
    assert g1.healthy_replicas() == [0, 1, 2]  # group-scoped healthy set
    # drive group 0 until its detector ejects the frozen replica; group
    # 1's membership log must stay empty
    for _ in range(3 * f.cfg.base.lease_steps):
        f.step()
    assert any(e.kind == "remove" and e.group == 0
               for e in g0.membership.events)
    assert g1.membership.events == []
    assert int(g1.live[0]) == g1.cfg.full_mask


def test_verify_fleet_catches_cross_group_uid_aliasing():
    from hermes_tpu.fleet import verify_fleet

    f = _fleet(groups=2, record=True)
    verify_fleet(f)  # clean fleet passes
    # forge the SAME migration-namespace uid into both groups' histories
    for grp in f.groups:
        grp.rt.recorder.record_migration(
            np.array([1]), np.array([[5, -7]]), np.array([1]),
            np.array([0]), step=grp.rt.step_idx + 1)
    with pytest.raises(AssertionError, match="aliasing"):
        verify_fleet(f)


# -- fleet-wide chaos: deterministic replay ---------------------------------


def test_fleet_chaos_deterministic_replay():
    import jax

    from hermes_tpu import chaos
    from hermes_tpu.fleet import Fleet, FleetChaosRunner, fleet_schedules

    fcfg = FleetConfig(groups=2, base=_base(n_replicas=4))

    def one():
        f = Fleet(fcfg, record=True, detect=2)
        kinds = np.full(30, Fleet.PUT, np.int32)
        keys = (np.arange(30) * 5) % fcfg.total_keys
        fb = f.submit_batch(kinds, keys, np.ones((30, 1), np.int32))
        runner = FleetChaosRunner(
            f, fleet_schedules(fcfg, seed=11, steps=18),
            spec=chaos.ChaosSpec(min_healthy=2))
        res = runner.run(18, check=True)
        assert res["checked_ok"] and res["drained"], res
        f.run_batch(fb)
        states = [jax.tree.leaves(jax.device_get(g.rt.fs))
                  for g in f.groups]
        return runner.log_json(), states

    log1, st1 = one()
    log2, st2 = one()
    assert log1 == log2, "fleet executed logs differ across replays"
    for ga, gb in zip(st1, st2):
        for a, b in zip(ga, gb):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_parse_fleet_schedule_routing():
    from hermes_tpu.fleet import parse_fleet

    scheds = parse_fleet(
        "@2 freeze 1\n"         # unprefixed -> group 0
        "g1@4 freeze 0\n"
        "g2@6 thaw 0  # comment\n", groups=3)
    assert [len(s) for s in scheds] == [1, 1, 1]
    assert scheds[1].events[0].step == 4
    with pytest.raises(ValueError, match="group 7"):
        parse_fleet("g7@1 freeze 0\n", groups=3)


# -- obs: per-group labels + fleet aggregation -------------------------------


def test_fleet_obs_group_labels_and_aggregation(fleet3):
    from hermes_tpu.obs import Observability
    from hermes_tpu.obs.report import fleet_totals, render_report

    f = fleet3
    obs = Observability()
    f.attach_obs(obs)
    f.groups[1].rt.freeze(0)
    f.groups[1].rt.thaw(0)
    futs = [f.put(i, k, [k]) for i, k in enumerate((2, 40, 70))]
    assert f.run_until(futs)
    f.interval_report(obs)
    evs = [r for r in obs.records if r.get("kind") == "event"
           and r.get("name") == "freeze"]
    assert evs and evs[0]["group"] == 1  # trace events carry the group
    ft = fleet_totals(obs.records)
    assert set(ft["groups"]) == {0, 1, 2}
    assert ft["fleet"]["n_write"] == sum(
        r["n_write"] for r in ft["groups"].values())
    report = render_report(obs.records)
    assert "-- fleet (per-group / aggregate, 3 group(s)) --" in report


# -- device layout: the (groups, replicas) grid ------------------------------


def test_fleet_meshes_disjoint_grid(cpu_devices):
    from hermes_tpu import launch

    meshes = launch.fleet_meshes(4, 2)
    assert len(meshes) == 4
    seen = set()
    for m in meshes:
        ids = {d.id for d in m.devices.ravel()}
        assert len(ids) == 2 and not (ids & seen)
        seen |= ids
    assert launch.group_of_process(4, 2) == [0, 1, 2, 3]  # single process
    with pytest.raises(RuntimeError, match="do not split"):
        launch.fleet_meshes(3)


def test_fleet_base_port_windows_disjoint():
    from hermes_tpu.distributed import fleet_base_port

    ports = [fleet_base_port(29500, g, n_ranks=4) for g in range(3)]
    assert ports == sorted(set(ports))
    # a group's window (4 ports per rank of headroom) never overlaps the
    # next group's base
    for a, b in zip(ports, ports[1:]):
        assert a + 4 * 4 <= b


# -- sharded fleet: disjoint submeshes ---------------------------------------


def test_fleet_sharded_groups_on_submeshes(cpu_devices):
    from hermes_tpu import launch
    from hermes_tpu.fleet import Fleet

    fcfg = FleetConfig(groups=2, base=_base(n_replicas=2, n_sessions=2))
    f = Fleet(fcfg, backend="sharded", meshes=launch.fleet_meshes(2, 2),
              record=True)
    futs = [f.put(i, k, [k, 3]) for i, k in enumerate((1, 31, 32, 63))]
    assert f.run_until(futs)
    gets = [f.get(i, k) for i, k in enumerate((1, 31, 32, 63))]
    assert f.run_until(gets)
    assert [g.result().value[:2] for g in gets] == [
        [1, 3], [31, 3], [32, 3], [63, 3]]
    assert f.check()["ok"]


def test_fleet_snapshot_scope_roundtrip(tmp_path):
    import jax

    f = _fleet(groups=2, record=False)
    futs = [f.put(i, k, [k, 4]) for i, k in enumerate((3, 40))]
    assert f.run_until(futs)
    f.drain()
    manifest = f.save(str(tmp_path / "fleet"))
    assert manifest["groups"] == 2 and len(manifest["archives"]) == 2
    before = [jax.tree.leaves(jax.device_get(g.rt.fs)) for g in f.groups]
    # a fresh fleet restores per-group state AND router scope
    f2 = _fleet(groups=2, record=False)
    f2.load(str(tmp_path / "fleet"))
    after = [jax.tree.leaves(jax.device_get(g.rt.fs)) for g in f2.groups]
    for ga, gb in zip(before, after):
        for a, b in zip(ga, gb):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    g = f2.get(0, 40)
    assert f2.run_until([g]) and g.result().value[:2] == [40, 4]
    # a wrong-shape fleet refuses the archive
    f3 = _fleet(groups=3, record=False)
    with pytest.raises(ValueError, match="not a fleet snapshot"):
        f3.load(str(tmp_path / "fleet"))


@pytest.fixture(scope="module")
def fleet3():
    """One recorded 3-group fleet shared by the read-only routing tests
    (each KVS construction compiles its group's round — sharing keeps the
    quick tier quick).  Tests that mutate fleet topology build their own."""
    return _fleet(groups=3, record=True)
